"""Iterative solver framework (≙ reference ``algorithms/``).

- ``krylov``: LSQR / CG / FlexibleCG / Chebyshev as ``lax.while_loop``
  iterations (≙ ``algorithms/Krylov/``)
- ``precond``: preconditioner interface (≙ ``algorithms/Krylov/precond.hpp``)
- ``accelerated``: Blendenpik / LSRN sketch-to-precondition least squares
  (≙ ``algorithms/regression/accelerated_linearl2_regression_solver*``)
- ``refine``: certified mixed-precision iterative refinement — the
  sketch-preconditioned factorization runs at low working precision,
  residuals at f64, and the guard certifies the final gate
- ``cond_est``: condition-number estimation (≙ ``nla/CondEst.hpp``)
- ``gauss_seidel``: synchronous randomized block Gauss-Seidel (≙ the
  asynchronous AsyRGS, ``algorithms/asynch/``, re-expressed for TPU)
- ``prox``: loss/regularizer prox library (≙ ``algorithms/regression/
  loss.hpp``, ``regularizers.hpp``)
"""

from .accelerated import FasterLeastSquaresParams, faster_least_squares, lsrn_least_squares
from .asynch import asy_fcg
from .cond_est import CondEstParams, CondEstResult, cond_est
from .gauss_seidel import randomized_block_gauss_seidel
from .krylov import (
    KrylovParams,
    cg,
    cg_chunked,
    chebyshev,
    chebyshev_chunked,
    flexible_cg,
    flexible_cg_chunked,
    lsqr,
    lsqr_chunked,
)
from .precond import IdPrecond, MatPrecond, TriInversePrecond
from .prox import LOSSES, REGULARIZERS, get_loss, get_regularizer
from .refine import RefineParams, refine_least_squares
from .regression import RegressionProblem, solve_regression

__all__ = [
    "KrylovParams",
    "lsqr",
    "cg",
    "flexible_cg",
    "chebyshev",
    "lsqr_chunked",
    "cg_chunked",
    "flexible_cg_chunked",
    "chebyshev_chunked",
    "IdPrecond",
    "MatPrecond",
    "TriInversePrecond",
    "FasterLeastSquaresParams",
    "faster_least_squares",
    "lsrn_least_squares",
    "RefineParams",
    "refine_least_squares",
    "cond_est",
    "CondEstParams",
    "CondEstResult",
    "randomized_block_gauss_seidel",
    "LOSSES",
    "REGULARIZERS",
    "get_loss",
    "get_regularizer",
    "asy_fcg",
    "RegressionProblem",
    "solve_regression",
]
