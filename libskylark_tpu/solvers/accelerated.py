"""Sketch-to-precondition least squares: Blendenpik and LSRN.

≙ ``algorithms/regression/accelerated_linearl2_regression_solver_Elemental
.hpp:68-290`` and ``nla/least_squares.hpp:237-314`` (``FasterLeastSquares``):

- Blendenpik: S·A (columnwise sketch to a replicated s×n) → QR → R⁻¹ as
  right preconditioner → LSQR; if the preconditioner's condition estimate
  is bad, re-sketch with a larger sketch (the retry loop at ``:241-252``).
- LSRN: SVD of S·A → N = V·Σ⁻¹ as right preconditioner → LSQR.

TPU notes: the sketch is the sharded MXU-heavy op; QR/SVD of the s×n
sketch is replicated-small (the reference holds SA in ``[*,*]``).  The
retry loop runs eagerly (host) since it changes shapes; each LSQR solve is
a single jitted while_loop.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np

from .. import guard, telemetry
from ..core.context import SketchContext
from ..core.params import Params
from ..sketch.base import Dimension, create_sketch
from .krylov import KrylovParams, lsqr
from .precond import MatPrecond, TriInversePrecond

__all__ = [
    "FasterLeastSquaresParams",
    "faster_least_squares",
    "lsrn_least_squares",
]


@dataclass
class FasterLeastSquaresParams(Params):
    """Knobs ≙ the reference's blendenpik/lsrn params structs."""

    # None → auto: FJLT for dense A, CWT for sparse (the reference's
    # dense/sparse split, accelerated_...Elemental.hpp:200-250).
    sketch_type: str | None = None
    gamma: float = 4.0  # sketch rows = gamma * n
    max_attempts: int = 3  # re-sketch retries (≙ :241-252)
    cond_threshold: float | None = None  # default 1/(10·eps^(1/2))
    krylov: KrylovParams | None = None


def _sketch_once(A, s, sketch_type, context):
    m = A.shape[0]
    S = create_sketch(sketch_type, m, s, context)
    return S.apply(A, Dimension.COLUMNWISE)


def _tri_condest(R) -> float:
    """1-norm condition estimate of upper-triangular R — ≙ the reference's
    ``utcondest`` (LAPACK ``trcon``-style, ``accelerated_...Elemental.hpp:
    25-66``): ‖R‖₁·‖R⁻¹‖₁ via a triangular solve against the identity."""
    import jax.scipy.linalg as jsl

    n = R.shape[0]
    Rinv = jsl.solve_triangular(R, jnp.eye(n, dtype=R.dtype), lower=False)
    one_norm = lambda M: jnp.max(jnp.sum(jnp.abs(M), axis=0))
    return float(one_norm(R) * one_norm(Rinv))


def faster_least_squares(
    A,
    B,
    context: SketchContext,
    params: FasterLeastSquaresParams | None = None,
):
    """Blendenpik: near machine-precision LS at sketch-and-solve speed.

    Returns ``(X, info)``; ``info["attempts"]`` counts re-sketches and
    ``info["recovery"]`` is the guard-layer ledger of the retry loop
    (every re-sketch / SVD fallback as a :class:`~libskylark_tpu.guard.
    RecoveryAttempt`; ``guarded=False`` under ``SKYLARK_GUARD=0``, in
    which case the Blendenpik-native retry loop still runs — it predates
    the guard and is the paper's own robustness mechanism).
    """
    params = params or FasterLeastSquaresParams()
    m, n = A.shape
    if m < n:
        raise ValueError(f"faster_least_squares needs tall A, got {A.shape}")
    eps = float(jnp.finfo(jnp.asarray(A).dtype if not hasattr(A, "todense") else A.data.dtype).eps)
    threshold = params.cond_threshold or 0.1 / np.sqrt(eps)

    guarded = guard.enabled()
    report = (
        guard.RecoveryReport(stage="blendenpik")
        if guarded
        else guard.RecoveryReport.disabled("blendenpik")
    )
    stype = params.sketch_type or (
        "CWT" if hasattr(A, "todense") else "FJLT"
    )
    gamma = params.gamma
    R = None
    for attempt in range(1, params.max_attempts + 1):
        s = min(int(gamma * n), m)
        SA = _sketch_once(A, s, stype, context)
        R_try = jnp.linalg.qr(SA, mode="r")
        # 1-norm triangular condition estimate of the preconditioner, the
        # quantity the reference's retry loop consumes (``utcondest`` in
        # ``build_precond``, accelerated_...Elemental.hpp:68-77, 225-246).
        cond = _tri_condest(R_try)
        R = R_try
        good = np.isfinite(cond) and cond < threshold
        report.record(
            "initial" if attempt == 1 else "grow",
            verdict=guard.OK if good else guard.RESKETCH,
            cond=cond,
            sketch_size=s,
            detail="" if good else f"utcondest {cond:.3e} >= {threshold:.3e}",
        )
        if good:
            report.recovered = attempt > 1
            break
        gamma *= 2  # re-sketch larger (accelerated_...hpp:241-252)
    if not (np.isfinite(cond) and cond < threshold):
        # All attempts produced a bad preconditioner: fall back to the
        # exact SVD solver, as the reference does after its retry budget
        # (``_alt_solver``, accelerated_...Elemental.hpp:247-257, 275-280).
        from ..linalg.least_squares import exact_least_squares

        A_d = A.todense() if hasattr(A, "todense") else A
        X = exact_least_squares(A_d, B, alg="svd")
        report.record(
            "fallback", verdict=guard.FALLBACK, detail="exact svd solve"
        )
        report.recovered = True
        info = {
            "attempts": attempt,
            "condest": cond,
            "fallback": "svd",
            "iterations": 0,
            "recovery": report.to_dict(),
        }
        telemetry.run_summary("blendenpik", info)
        return X, info
    precond = TriInversePrecond(R, lower=False)
    X, info = lsqr(A, B, precond=precond, params=params.krylov)
    if guarded:
        guard.check_finite(X, "blendenpik_lsqr", report=report)
    info["attempts"] = attempt
    info["condest"] = cond
    info["recovery"] = report.to_dict()
    telemetry.run_summary("blendenpik", info)
    return X, info


def lsrn_least_squares(
    A,
    B,
    context: SketchContext,
    params: FasterLeastSquaresParams | None = None,
):
    """LSRN: SVD-based preconditioning — robust for rank-deficient A
    (≙ ``lsrn_tag`` branch, ``accelerated_...Elemental.hpp:96-160``).

    Returns ``(X, info)``; under guarding (``SKYLARK_GUARD``, default on)
    a non-finite sketch climbs one fresh-seed resketch rung before the
    solve, the solution passes a finiteness sentinel, and
    ``info["recovery"]`` records the attempts.
    """
    params = params or FasterLeastSquaresParams()
    m, n = A.shape
    s = min(int(params.gamma * n), m)
    # LSRN wants a Gaussian-like sketch for its SVD preconditioner.
    stype = params.sketch_type or (
        "CWT" if hasattr(A, "todense") else "JLT"
    )
    guarded = guard.enabled()
    report = (
        guard.RecoveryReport(stage="lsrn")
        if guarded
        else guard.RecoveryReport.disabled("lsrn")
    )
    SA = _sketch_once(A, s, stype, context)
    if guarded and not guard.tree_all_finite(SA):
        # LSRN's SVD preconditioner absorbs ill conditioning by design, so
        # the only sketch pathology worth guarding here is non-finiteness.
        report.record(
            "initial", verdict=guard.RESKETCH, sketch_size=s,
            detail="non-finite sketch output",
        )
        SA = _sketch_once(A, s, stype, guard.derived_context(context, 1))
        report.record("resketch", verdict=guard.OK, sketch_size=s)
        guard.check_finite(SA, "lsrn_sketch", report=report)
        report.recovered = True
    elif guarded:
        report.record("initial", verdict=guard.OK, sketch_size=s)
    _, sv, Vt = jnp.linalg.svd(SA, full_matrices=False)
    eps = jnp.finfo(sv.dtype).eps
    cutoff = sv[0] * eps * max(SA.shape)
    sinv = jnp.where(sv > cutoff, 1.0 / sv, 0.0)
    N = Vt.T * sinv[None, :]  # V·Σ⁻¹
    X, info = lsqr(A, B, precond=MatPrecond(N), params=params.krylov)
    if guarded:
        guard.check_finite(X, "lsrn_lsqr", report=report)
    info["recovery"] = report.to_dict()
    telemetry.run_summary("lsrn", info)
    return X, info
