"""Condition-number estimation (≙ ``nla/CondEst.hpp:67-305``).

The reference estimates σ_max by power iteration and σ_min by an LSQR-like
Golub-Kahan bidiagonalization sweep, tracking the bidiagonal's smallest
singular value as a certificate.  Here: power iteration on AᵀA for σ_max;
k steps of Golub-Kahan with full reorthogonalization, σ_min from the small
bidiagonal SVD.  All matmul-bound; jit-compatible (static step counts).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.context import SketchContext
from ..core.matrices import gaussian_matrix

__all__ = ["cond_est"]


def cond_est(
    A,
    context: SketchContext,
    power_its: int = 30,
    lanczos_steps: int = 40,
):
    """Returns ``(cond, sigma_max, sigma_min)`` estimates for tall A."""
    A = A if hasattr(A, "todense") else jnp.asarray(A)
    m, n = A.shape
    steps = min(lanczos_steps, n)
    dtype = A.data.dtype if hasattr(A, "todense") else A.dtype

    # sigma_max: power iteration on AᵀA (CondEst.hpp power loop).
    v = gaussian_matrix(context, (n, 1), dtype=dtype)[:, 0]
    v = v / jnp.linalg.norm(v)

    def pbody(_, v):
        w = A.T @ (A @ v)
        return w / jnp.linalg.norm(w)

    v = lax.fori_loop(0, power_its, pbody, v)
    sigma_max = jnp.sqrt(jnp.linalg.norm(A.T @ (A @ v)))

    # sigma_min: Golub-Kahan bidiagonalization with reorthogonalization,
    # smallest singular value of the (steps+1, steps) bidiagonal matrix
    # (≙ the R-diagonal tracking sweep, CondEst.hpp:150-260).
    u0 = gaussian_matrix(context, (m, 1), dtype=dtype)[:, 0]
    beta0 = jnp.linalg.norm(u0)
    u0 = u0 / beta0
    Us = jnp.zeros((steps + 1, m), dtype).at[0].set(u0)
    Vs = jnp.zeros((steps, n), dtype)
    alphas = jnp.zeros((steps,), dtype)
    betas = jnp.zeros((steps,), dtype)

    def gkbody(i, carry):
        Us, Vs, alphas, betas = carry
        u = Us[i]
        v = A.T @ u
        # Full reorthogonalization against previous V's (covers the
        # classical -beta*v_prev term and keeps the basis numerically
        # orthogonal; rows > i are zero so they contribute nothing).
        v = v - Vs.T @ (Vs @ v)
        alpha = jnp.linalg.norm(v)
        v = v / jnp.where(alpha > 0, alpha, 1)
        Vs = Vs.at[i].set(v)
        alphas = alphas.at[i].set(alpha)
        unew = A @ v - alpha * u
        unew = unew - Us.T @ (Us @ unew)
        beta = jnp.linalg.norm(unew)
        unew = unew / jnp.where(beta > 0, beta, 1)
        Us = Us.at[i + 1].set(unew)
        betas = betas.at[i].set(beta)
        return (Us, Vs, alphas, betas)

    Us, Vs, alphas, betas = lax.fori_loop(
        0, steps, gkbody, (Us, Vs, alphas, betas)
    )
    # Bidiagonal B: diag(alphas), subdiag(betas[:-1]) — (steps+1, steps).
    Bmat = (
        jnp.zeros((steps + 1, steps), dtype)
        .at[jnp.arange(steps), jnp.arange(steps)]
        .set(alphas)
        .at[jnp.arange(1, steps + 1), jnp.arange(steps)]
        .set(betas)
    )
    sv = jnp.linalg.svd(Bmat, compute_uv=False)
    sigma_min = sv[-1]
    return sigma_max / sigma_min, sigma_max, sigma_min
