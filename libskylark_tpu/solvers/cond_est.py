"""Condition-number estimation with certificates (≙ ``nla/CondEst.hpp:22-301``).

Implements the Avron-Druinsky-Toledo estimator the reference ships:

- σ_max by power iteration, with a certificate pair ``(u_max, v_max)``:
  ``A @ v_max ≈ sigma_max * u_max`` with unit-norm vectors
  (``CondEst.hpp:92-97``).
- σ_min by an LSQR sweep on ``A x = b`` where ``b = A @ xhat`` for a known
  random ``xhat``: the forward error ``d = xhat - x`` yields a *certified*
  estimate ``sigma_min_c = ‖A d‖/‖d‖`` with certificate pair
  ``(u_min, v_min)`` whenever it improves (``CondEst.hpp:200-224``), plus
  an uncertified estimate from the smallest singular value of the LSQR
  R-factor bidiagonal (``CondEst.hpp:176-187, 282-296``).
- The τ machinery: ``tau = sqrt(2)·erfinv(c2)/‖xhat‖`` bounds how small the
  forward error of a *random* xhat can get before further shrinkage is
  statistically uninformative; reaching it stops the sweep
  (``CondEst.hpp:108-117, 248-255``).

Stopping flags mirror the reference's return codes: ``-1`` cond ≈ 1
detected, ``-2`` C1 backward-style convergence, ``-3`` C2 forward error
below τ, ``-4`` C3 numerically singular, ``-6`` no convergence within the
iteration limit.  As in the reference, after a criterion first fires the
sweep continues to ``1.25·itn + 1`` iterations before exiting
(``CondEst.hpp:238-264``).

TPU notes: the whole sweep is ONE jitted ``lax.while_loop`` over fixed-size
buffers (no per-iteration host sync); the final bidiagonal SVD pads unused
slots with σ_max on the diagonal, which adds singular values ≥ the true
minimum and so cannot perturb it.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..core.context import SketchContext
from ..core.matrices import gaussian_matrix
from ..core.params import Params

__all__ = ["CondEstParams", "CondEstResult", "cond_est"]


@dataclass
class CondEstParams(Params):
    """≙ ``condest_params_t`` (``CondEst.hpp:22-45``).

    ``c1..c4, c1t`` default from machine epsilon exactly as the reference
    does (there in f64; here from the input dtype's eps so f32 runs get
    consistent thresholds).  ``None`` → derive at call time.
    """

    iter_lim: int = 300
    powerits: int = 100
    c1: float | None = None  # 8·eps      (C1 convergence scale)
    c2: float = 1e-3  #                    (τ quantile)
    c3: float | None = None  # 64/eps     (declare singular)
    c4: float | None = None  # sqrt(eps)  (ill-conditioning gate)
    c1t: float | None = None  # 4·eps     (tightened C1)


class CondEstResult(NamedTuple):
    """First three fields are the round-1 ``(cond, sigma_max, sigma_min)``
    triple — access them by name or index (``r.cond`` / ``r[0]``; note a
    bare 3-way tuple unpack no longer works since the certificate fields
    follow); the rest are the reference's certificate outputs."""

    cond: jax.Array
    sigma_max: jax.Array
    sigma_min: jax.Array
    sigma_min_c: jax.Array  # certified estimate (≥ sigma_min)
    u_max: jax.Array  # (m,) left certificate: A v_max ≈ σ_max u_max
    v_max: jax.Array  # (n,) right certificate
    u_min: jax.Array  # (m,) left certificate: A v_min ≈ σ_min_c u_min
    v_min: jax.Array  # (n,) right certificate
    flag: jax.Array  # int32 reference return code (-1..-4, -6)


def _power_sigma_max(matvec, rmatvec, v0, powerits):
    """Dominant singular triplet by power iteration on AᵀA
    (≙ ``PowerIteration`` call, ``CondEst.hpp:92-97``).

    Every normalization is zero-guarded (``x/max(‖x‖,·)`` with a
    ``where``): a zero start vector falls back to a uniform one, and a
    zero A (or an iterate that lands in the null space) yields σ=0 with a
    finite certificate instead of NaN-ing the whole estimate.  The guards
    are bitwise no-ops on the generic (positive-norm) path.
    """

    def _unit(x):
        nrm = jnp.linalg.norm(x)
        return jnp.where(nrm > 0, x / jnp.where(nrm > 0, nrm, 1), x)

    n = v0.shape[0]
    nrm0 = jnp.linalg.norm(v0)
    v0 = jnp.where(
        nrm0 > 0,
        v0 / jnp.where(nrm0 > 0, nrm0, 1),
        jnp.full_like(v0, 1.0 / jnp.sqrt(jnp.asarray(n, v0.dtype))),
    )

    def body(_, v):
        w = rmatvec(matvec(v))
        nrm = jnp.linalg.norm(w)
        # A null-space iterate (w = 0) stays put instead of dividing by 0.
        return jnp.where(nrm > 0, w / jnp.where(nrm > 0, nrm, 1), v)

    v = lax.fori_loop(0, powerits, body, v0)
    u = matvec(v)
    sigma = jnp.linalg.norm(u)
    return sigma, _unit(u), v


def cond_est(
    A,
    context: SketchContext,
    params: CondEstParams | None = None,
    *,  # keyword-only: the round-1 shim must not bind positionally
    power_its: int | None = None,
    lanczos_steps: int | None = None,
):
    """Estimate cond(A) with certificates for tall (or square) A.

    A may be dense or BCOO (only matvecs are taken, as in the reference).
    Returns a :class:`CondEstResult`; ``r.cond, r.sigma_max, r.sigma_min``
    are the round-1 triple (by name/index; positional 3-unpack no longer
    applies).
    """
    params = params or CondEstParams()
    if power_its is not None or lanczos_steps is not None:
        params = replace(
            params,
            powerits=params.powerits if power_its is None else power_its,
            iter_lim=(
                params.iter_lim if lanczos_steps is None else lanczos_steps
            ),
        )
    if not hasattr(A, "todense"):
        A = jnp.asarray(A)
    n = A.shape[1]
    dtype = A.data.dtype if hasattr(A, "todense") else A.dtype
    eps = float(jnp.finfo(dtype).eps)
    c1 = params.c1 if params.c1 is not None else 8 * eps
    c2 = params.c2
    c3 = params.c3 if params.c3 is not None else 64.0 / eps
    c4 = params.c4 if params.c4 is not None else float(jnp.sqrt(eps))
    c1t = params.c1t if params.c1t is not None else 4 * eps
    T_max = int(params.iter_lim)

    v0 = gaussian_matrix(context, (n, 1), dtype=dtype)[:, 0]
    xhat0 = gaussian_matrix(context, (n, 1), dtype=dtype)[:, 0]
    return _cond_est_impl(
        A, v0, xhat0, int(params.powerits), T_max, c1, c2, c3, c4, c1t
    )


@partial(
    jax.jit,
    static_argnames=(
        "powerits", "T_max", "c1", "c2", "c3", "c4", "c1t",
    ),
)
def _cond_est_impl(A, v0, xhat0, powerits, T_max, c1, c2, c3, c4, c1t):
    dtype = v0.dtype
    matvec = lambda x: A @ x
    rmatvec = lambda y: A.T @ y

    def _run(v0, xhat0):
        sigma_max, u_max, v_max = _power_sigma_max(
            matvec, rmatvec, v0, powerits
        )

        # xhat / tau (CondEst.hpp:108-117).
        nrm_xhat = jnp.linalg.norm(xhat0)
        tau = (
            jnp.sqrt(jnp.asarray(2.0, dtype))
            * jax.scipy.special.erfinv(jnp.asarray(c2, dtype))
            / nrm_xhat
        )
        xhat = xhat0 / nrm_xhat

        # b and LSQR initialization (CondEst.hpp:119-152).  The beta0 /
        # alpha0 divisions are zero-guarded (bitwise identical whenever
        # the norms are positive): rank-deficient or zero A can put xhat
        # in the null space, and an unguarded 0/0 here NaNs every
        # downstream certificate.
        b = matvec(xhat)
        nrm_b = jnp.linalg.norm(b)
        beta0 = nrm_b
        u = jnp.where(beta0 > 0, b / jnp.where(beta0 > 0, beta0, 1), b)
        v_init = rmatvec(u)
        alpha0 = jnp.linalg.norm(v_init)
        v = jnp.where(
            alpha0 > 0, v_init / jnp.where(alpha0 > 0, alpha0, 1), v_init
        )

        Rdiag = jnp.zeros((T_max,), dtype)
        Rsub = jnp.zeros((T_max,), dtype)

        state = dict(
            itn=jnp.asarray(0, jnp.int32),
            T=jnp.asarray(T_max, jnp.int32),
            flag=jnp.asarray(-6, jnp.int32),
            c1=jnp.asarray(c1, dtype),
            u=u,
            v=v,
            x=jnp.zeros_like(xhat0),
            w=v,
            alpha=alpha0,
            phibar=beta0,
            rhobar=alpha0,
            theta=jnp.asarray(0.0, dtype),
            Rdiag=Rdiag,
            Rsub=Rsub,
            sigma_min=sigma_max,
            u_min=u_max,
            v_min=v_max,
            done_one=jnp.asarray(False),
        )

        def cond_fn(s):
            return jnp.logical_and(s["itn"] < s["T"], ~s["done_one"])

        def body_fn(s):
            itn = s["itn"]
            # 1-2. Golub-Kahan updates (CondEst.hpp:161-174), with exact-
            # breakdown guards (beta or alpha == 0 on low-rank/structured
            # A must not NaN-poison the remaining extension iterations).
            u_new = matvec(s["v"]) - s["alpha"] * s["u"]
            beta = jnp.linalg.norm(u_new)
            u_new = u_new / jnp.where(beta > 0, beta, 1)
            v_new = rmatvec(u_new) - beta * s["v"]
            alpha = jnp.linalg.norm(v_new)
            v_new = v_new / jnp.where(alpha > 0, alpha, 1)

            # 3. Givens rotation; store R entries (CondEst.hpp:176-188).
            rho = jnp.sqrt(s["rhobar"] ** 2 + beta**2)
            Rdiag = s["Rdiag"].at[itn].set(rho)
            Rsub = jnp.where(
                itn > 0, s["Rsub"].at[itn - 1].set(s["theta"]), s["Rsub"]
            )
            cs = s["rhobar"] / rho
            sn = beta / rho
            theta = sn * alpha
            rhobar = -cs * alpha
            phi = cs * s["phibar"]
            phibar = sn * s["phibar"]

            # 4. x / w updates (CondEst.hpp:190-198).
            x = s["x"] + (phi / rho) * s["w"]
            w = v_new - (theta / rho) * s["w"]

            # 5. Forward error; cond≈1 early exit (CondEst.hpp:200-214).
            d = xhat - x
            nrm_d = jnp.linalg.norm(d)
            done_one = nrm_d == 0.0

            # 6. Certified sigma_min update (CondEst.hpp:216-224).
            Ad = matvec(d)
            nrm_ad = jnp.linalg.norm(Ad)
            improves = (nrm_ad <= s["sigma_min"] * nrm_d) & (nrm_d > 0)
            sigma_min = jnp.where(
                improves, nrm_ad / jnp.where(nrm_d > 0, nrm_d, 1),
                s["sigma_min"],
            )
            safe_ad = jnp.where(nrm_ad > 0, nrm_ad, 1)
            u_min = jnp.where(improves, Ad / safe_ad, s["u_min"])
            v_min = jnp.where(
                improves, d / jnp.where(nrm_d > 0, nrm_d, 1), s["v_min"]
            )

            # 7. Tighten C1 when highly ill-conditioned (CondEst.hpp:227-234).
            c1_cur = jnp.where(
                sigma_min / sigma_max <= c4, jnp.asarray(c1t, dtype), s["c1"]
            )

            # 8. Stopping criteria; first trigger sets T = 1.25·itn + 1
            # (CondEst.hpp:236-264).
            nrm_x = jnp.linalg.norm(x)
            open_ = s["T"] == T_max
            itf = itn.astype(dtype)
            T_ext = jnp.minimum(
                (1.25 * itf + 1).astype(jnp.int32), jnp.asarray(T_max)
            )
            hit_c1 = jnp.logical_and(
                open_, nrm_ad <= c1_cur * (sigma_max * nrm_x + nrm_b)
            )
            hit_c2 = jnp.logical_and(open_, nrm_d <= tau)
            hit_c3 = jnp.logical_and(open_, sigma_max / sigma_min >= c3)
            hit = hit_c1 | hit_c2 | hit_c3
            flag = jnp.where(
                hit_c1,
                -2,
                jnp.where(hit_c2, -3, jnp.where(hit_c3, -4, s["flag"])),
            ).astype(jnp.int32)
            T = jnp.where(hit, T_ext, s["T"])

            return dict(
                itn=itn + 1,
                T=T,
                flag=flag,
                c1=c1_cur,
                u=u_new,
                v=v_new,
                x=x,
                w=w,
                alpha=alpha,
                phibar=phibar,
                rhobar=rhobar,
                theta=theta,
                Rdiag=Rdiag,
                Rsub=Rsub,
                sigma_min=sigma_min,
                u_min=u_min,
                v_min=v_min,
                done_one=done_one,
            )

        s = lax.while_loop(cond_fn, body_fn, state)

        # R-based (uncertified) sigma_min: smallest singular value of the
        # bidiagonal R over iterations actually run (CondEst.hpp:282-296).
        # Unused slots pad the diagonal with sigma_max (decoupled singular
        # values equal to sigma_max — can't go below the true minimum).
        count = s["itn"]
        idx = jnp.arange(T_max)
        diag = jnp.where(idx < count, s["Rdiag"], sigma_max)
        sub = jnp.where(idx + 1 < count, s["Rsub"], 0.0)
        Bmat = (
            jnp.zeros((T_max, T_max), dtype)
            .at[idx, idx]
            .set(diag)
            .at[idx[:-1], idx[:-1] + 1]
            .set(sub[:-1])
        )
        sigma_min_R = jnp.linalg.svd(Bmat, compute_uv=False)[-1]
        sigma_min_R = jnp.where(count > 0, sigma_min_R, sigma_max)

        sigma_min_c = s["sigma_min"]
        sigma_min = jnp.minimum(sigma_min_c, sigma_min_R)

        # cond ≈ 1 early exit overrides (CondEst.hpp:204-214).
        one = s["done_one"]
        sigma_min = jnp.where(one, sigma_max, sigma_min)
        sigma_min_c = jnp.where(one, sigma_max, sigma_min_c)
        u_min = jnp.where(one, u_max, s["u_min"])
        v_min = jnp.where(one, v_max, s["v_min"])
        flag = jnp.where(one, -1, s["flag"]).astype(jnp.int32)
        cond = jnp.where(one, 1.0, sigma_max / sigma_min)

        return CondEstResult(
            cond=cond,
            sigma_max=sigma_max,
            sigma_min=sigma_min,
            sigma_min_c=sigma_min_c,
            u_max=u_max,
            v_max=v_max,
            u_min=u_min,
            v_min=v_min,
            flag=flag,
        )

    return _run(v0, xhat0)
