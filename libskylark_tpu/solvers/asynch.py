"""Randomized-preconditioned flexible CG (≙ ``algorithms/asynch/``).

``AsyFCG`` in the reference pairs FlexibleCG with an *asynchronous*
randomized Gauss-Seidel inner solve as a (varying) preconditioner
(``AsyFCG.hpp:8``, ``asynch/precond.hpp:7-22``).  On TPU the asynchrony
has no analogue (SURVEY §2.7 P9); the math — FCG with an inexact,
iteration-varying randomized GS preconditioner — is preserved with the
synchronous randomized sweeps of ``gauss_seidel``.  Determinism: the
sweep schedule is counter-derived per outer iteration.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.context import SketchContext
from .gauss_seidel import gs_num_blocks, randomized_block_gauss_seidel
from .krylov import KrylovParams, flexible_cg

__all__ = ["asy_fcg"]


def asy_fcg(
    A,
    B,
    context: SketchContext,
    params: KrylovParams | None = None,
    inner_sweeps: int = 2,
    block_size: int = 64,
):
    """Solve SPD ``A X = B`` by FCG with a randomized block-GS inner
    preconditioner.  Returns ``(X, info)``."""
    A = jnp.asarray(A)
    params = params or KrylovParams()
    # One counter block PER OUTER ITERATION drives the inner sweeps'
    # schedule, so each FCG iteration sees a fresh randomized GS sweep —
    # matching AsyFCG's per-call randomization (``AsyFCG.hpp:8``,
    # ``asynch/precond.hpp:7-22``).  The schedule LENGTH is trace-static;
    # the traced outer-iteration index only shifts the counter window.
    seed = context.seed
    per_iter = inner_sweeps * gs_num_blocks(A.shape[0], block_size)
    base = context.reserve(params.iter_lim * per_iter)

    def precond(R, it):
        inner_ctx = SketchContext(seed=seed, counter=base)
        Z, _ = randomized_block_gauss_seidel(
            A,
            R,
            inner_ctx,
            block_size=block_size,
            sweeps=inner_sweeps,
            counter_offset=it.astype(jnp.uint32) * jnp.uint32(per_iter),
        )
        return Z

    return flexible_cg(A, B, precond=precond, params=params)
