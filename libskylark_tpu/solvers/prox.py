"""Loss / regularizer prox library (≙ ``algorithms/regression/loss.hpp``,
``regularizers.hpp``) — the ADMM building blocks.

Each loss provides ``evaluate(O, Y)`` (total loss over the batch) and
``prox(V, lam, Y)`` = argmin_X  lam·loss(X, Y) + ½‖X − V‖²  — the same
contract as the reference's ``loss_t::evaluate`` / ``proxoperator``
(``loss.hpp:7-25``, note the reference parameterizes with 1/ρ).  Shapes
follow BlockADMM: O and Y are (k, n) — k outputs (1 for regression /
binary, #classes for multiclass) by n examples.

Multiclass hinge/logistic follow the reference's formulations
(``loss.hpp:203-306`` crammed hinge, ``:309+`` multinomial logistic with an
inner prox solved iteratively; here a fixed-step bisection/Newton inside
``vmap`` keeps it jit-compatible).

All functions are elementwise/vectorized — XLA fuses them; the OpenMP
loops of the reference are irrelevant on TPU (P8).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

__all__ = [
    "SquaredLoss",
    "LadLoss",
    "HingeLoss",
    "LogisticLoss",
    "EmptyRegularizer",
    "L2Regularizer",
    "L1Regularizer",
    "LOSSES",
    "REGULARIZERS",
    "get_loss",
    "get_regularizer",
]


class SquaredLoss:
    """½‖O − Y‖² (≙ ``squaredloss_t``, loss.hpp:26-105)."""

    name = "squared"
    label_based = False  # takes numeric targets (coded ±1 for classes)

    def evaluate(self, O, Y):
        return 0.5 * jnp.sum((O - Y) ** 2)

    def prox(self, V, lam, Y):
        # argmin lam/2 (x-y)² + ½(x-v)² = (v + lam·y)/(1 + lam)
        return (V + lam * Y) / (1.0 + lam)


class LadLoss:
    """‖O − Y‖₁ — least absolute deviations (≙ ``ladloss_t``,
    loss.hpp:107-201)."""

    name = "lad"
    label_based = False

    def evaluate(self, O, Y):
        return jnp.sum(jnp.abs(O - Y))

    def prox(self, V, lam, Y):
        D = V - Y
        return Y + jnp.sign(D) * jnp.maximum(jnp.abs(D) - lam, 0.0)


class HingeLoss:
    """Σ max(0, 1 − y·o) with the reference's multiclass extension
    (≙ ``hingeloss_t``, loss.hpp:203-306).

    Binary: Y ∈ {−1, +1}, O (1, n).  Multiclass: Y holds class indices
    (0..k−1), O (k, n); the reference encodes class c as +1 row c, −1
    elsewhere and applies the binary hinge per row — reproduced here.
    """

    name = "hinge"
    label_based = True  # takes class indices (multiclass) or ±1 (binary)

    def _code(self, O, Y):
        if O.ndim >= 2 and O.shape[0] > 1:
            k = O.shape[0]
            cls = Y.astype(jnp.int32).reshape(-1)
            return 2.0 * jax.nn.one_hot(cls, k, dtype=O.dtype).T - 1.0
        return Y.reshape(O.shape).astype(O.dtype)

    def evaluate(self, O, Y):
        C = self._code(O, Y)
        return jnp.sum(jnp.maximum(0.0, 1.0 - C * O))

    def prox(self, V, lam, Y):
        C = self._code(V, Y)
        yv = C * V
        # piecewise prox of x ↦ lam·max(0, 1 − yx)
        shifted = jnp.where(yv < 1.0 - lam, V + lam * C, C)
        return jnp.where(yv > 1.0, V, shifted)


class LogisticLoss:
    """Multinomial logistic −log softmax (≙ ``logisticloss_t``,
    loss.hpp:309+; the reference solves the prox with an iterative inner
    method — here a fixed number of Newton steps on the softmax fixed
    point, jit-compatible)."""

    name = "logistic"
    label_based = True

    def __init__(self, newton_steps: int = 20):
        self.newton_steps = newton_steps

    def _is_binary(self, O):
        return O.ndim < 2 or O.shape[0] == 1

    def evaluate(self, O, Y):
        if self._is_binary(O):
            # log(1 + exp(−y·o)), Y ∈ {−1, +1}
            yo = Y.reshape(O.shape).astype(O.dtype) * O
            return jnp.sum(jnp.logaddexp(0.0, -yo))
        cls = Y.astype(jnp.int32).reshape(-1)
        logZ = jax.scipy.special.logsumexp(O, axis=0)
        picked = jnp.take_along_axis(O, cls[None, :], axis=0)[0]
        return jnp.sum(logZ - picked)

    def prox(self, V, lam, Y):
        if self._is_binary(V):
            # Newton on  lam·log(1+exp(−y·x)) + ½(x−v)²  per element.
            yv = Y.reshape(V.shape).astype(V.dtype)

            def nbody(_, X):
                sig = jax.nn.sigmoid(-yv * X)
                g = -lam * yv * sig + (X - V)
                h = lam * sig * (1.0 - sig) + 1.0
                return X - g / h

            return lax.fori_loop(0, self.newton_steps, nbody, V)

        cls = Y.astype(jnp.int32).reshape(-1)
        k, n = V.shape
        E = jax.nn.one_hot(cls, k, dtype=V.dtype).T  # (k, n)

        # Solve X = V − lam·(softmax(X) − e_y) by diagonal-Hessian Newton;
        # a few iterations suffice (prox is well-conditioned).
        def body(_, X):
            Pr = jax.nn.softmax(X, axis=0)
            G = Pr - E
            H = lam * Pr * (1 - Pr) + 1.0
            return X - (X - V + lam * G) / H

        return lax.fori_loop(0, self.newton_steps, body, V)


class EmptyRegularizer:
    """No regularization (≙ ``empty_regularizer_t``)."""

    name = "none"

    def evaluate(self, W):
        return jnp.asarray(0.0, jnp.result_type(W))

    def prox(self, V, lam):
        return V


class L2Regularizer:
    """½‖W‖² (≙ ``l2_regularizer_t``): prox = V/(1+lam)."""

    name = "l2"

    def evaluate(self, W):
        return 0.5 * jnp.sum(W * W)

    def prox(self, V, lam):
        return V / (1.0 + lam)


class L1Regularizer:
    """‖W‖₁ (≙ ``l1_regularizer_t``): soft threshold."""

    name = "l1"

    def evaluate(self, W):
        return jnp.sum(jnp.abs(W))

    def prox(self, V, lam):
        return jnp.sign(V) * jnp.maximum(jnp.abs(V) - lam, 0.0)


LOSSES = {
    "squared": SquaredLoss,
    "lad": LadLoss,
    "hinge": HingeLoss,
    "logistic": LogisticLoss,
}

REGULARIZERS = {
    "none": EmptyRegularizer,
    "l2": L2Regularizer,
    "l1": L1Regularizer,
}


def get_loss(name: str):
    return LOSSES[name]()


def get_regularizer(name: str):
    return REGULARIZERS[name]()
