"""Loss / regularizer prox library (≙ ``algorithms/regression/loss.hpp``,
``regularizers.hpp``) — the ADMM building blocks.

Each loss provides ``evaluate(O, Y)`` (total loss over the batch) and
``prox(V, lam, Y)`` = argmin_X  lam·loss(X, Y) + ½‖X − V‖²  — the same
contract as the reference's ``loss_t::evaluate`` / ``proxoperator``
(``loss.hpp:7-25``, note the reference parameterizes with 1/ρ).  Shapes
follow BlockADMM: O and Y are (k, n) — k outputs (1 for regression /
binary, #classes for multiclass) by n examples.

Multiclass hinge/logistic follow the reference's formulations
(``loss.hpp:203-306`` crammed hinge, ``:309+`` multinomial logistic with an
inner prox solved iteratively; here a fixed-step bisection/Newton inside
``vmap`` keeps it jit-compatible).

All functions are elementwise/vectorized — XLA fuses them; the OpenMP
loops of the reference are irrelevant on TPU (P8).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

__all__ = [
    "SquaredLoss",
    "LadLoss",
    "HingeLoss",
    "LogisticLoss",
    "EmptyRegularizer",
    "L2Regularizer",
    "L1Regularizer",
    "LOSSES",
    "REGULARIZERS",
    "get_loss",
    "get_regularizer",
]


class SquaredLoss:
    """½‖O − Y‖² (≙ ``squaredloss_t``, loss.hpp:26-105)."""

    name = "squared"
    label_based = False  # takes numeric targets (coded ±1 for classes)

    def evaluate(self, O, Y):
        return 0.5 * jnp.sum((O - Y) ** 2)

    def prox(self, V, lam, Y):
        # argmin lam/2 (x-y)² + ½(x-v)² = (v + lam·y)/(1 + lam)
        return (V + lam * Y) / (1.0 + lam)


class LadLoss:
    """‖O − Y‖₁ — least absolute deviations (≙ ``ladloss_t``,
    loss.hpp:107-201)."""

    name = "lad"
    label_based = False

    def evaluate(self, O, Y):
        return jnp.sum(jnp.abs(O - Y))

    def prox(self, V, lam, Y):
        D = V - Y
        return Y + jnp.sign(D) * jnp.maximum(jnp.abs(D) - lam, 0.0)


class HingeLoss:
    """Σ max(0, 1 − y·o) with the reference's multiclass extension
    (≙ ``hingeloss_t``, loss.hpp:203-306).

    Binary: Y ∈ {−1, +1}, O (1, n).  Multiclass: Y holds class indices
    (0..k−1), O (k, n); the reference encodes class c as +1 row c, −1
    elsewhere and applies the binary hinge per row — reproduced here.
    """

    name = "hinge"
    label_based = True  # takes class indices (multiclass) or ±1 (binary)

    def _code(self, O, Y):
        if O.ndim >= 2 and O.shape[0] > 1:
            k = O.shape[0]
            cls = Y.astype(jnp.int32).reshape(-1)
            return 2.0 * jax.nn.one_hot(cls, k, dtype=O.dtype).T - 1.0
        return Y.reshape(O.shape).astype(O.dtype)

    def evaluate(self, O, Y):
        C = self._code(O, Y)
        return jnp.sum(jnp.maximum(0.0, 1.0 - C * O))

    def prox(self, V, lam, Y):
        C = self._code(V, Y)
        yv = C * V
        # piecewise prox of x ↦ lam·max(0, 1 − yx)
        shifted = jnp.where(yv < 1.0 - lam, V + lam * C, C)
        return jnp.where(yv > 1.0, V, shifted)


class LogisticLoss:
    """Multinomial logistic −log softmax (≙ ``logisticloss_t``,
    loss.hpp:309-440).

    The prox is solved the way the reference's ``logexp`` does: damped
    Newton with Armijo backtracking (α=0.1, β=0.5), stopping on the Newton
    decrement ``gᵀu < 2ε`` with ε=1e-4 or after MAXITER=100 iterations
    (``loss.hpp:365-420``).  Multiclass uses the exact softmax Hessian via
    a Sherman-Morrison solve (diag + rank-1, as the reference's
    ``u/z/pu/pptil`` recurrence); everything is vectorized over examples
    with per-example convergence masks inside one ``lax.while_loop``."""

    name = "logistic"
    label_based = True

    def __init__(self, max_newton_steps: int = 100, epsilon: float = 1e-4):
        self.max_newton_steps = max_newton_steps
        self.epsilon = epsilon

    _ALPHA = 0.1  # Armijo slope fraction (loss.hpp:370)
    _BETA = 0.5  # step halving factor (loss.hpp:371)
    _MAX_HALVINGS = 30

    def _is_binary(self, O):
        return O.ndim < 2 or O.shape[0] == 1

    def evaluate(self, O, Y):
        if self._is_binary(O):
            # log(1 + exp(−y·o)), Y ∈ {−1, +1}
            yo = Y.reshape(O.shape).astype(O.dtype) * O
            return jnp.sum(jnp.logaddexp(0.0, -yo))
        cls = Y.astype(jnp.int32).reshape(-1)
        logZ = jax.scipy.special.logsumexp(O, axis=0)
        picked = jnp.take_along_axis(O, cls[None, :], axis=0)[0]
        return jnp.sum(logZ - picked)

    def _damped_newton(self, V, x0, obj, grad_dir):
        """Shared guarded-Newton loop: ``grad_dir(X) -> (G, U)`` gives the
        gradient and Newton direction; Armijo backtracking per example;
        stop when every example's Newton decrement ``ΣG·U`` is below 2ε
        (≙ the decrement test + line search of ``loss.hpp:389-416``)."""
        eps2 = 2.0 * self.epsilon

        def cond(s):
            return (s["it"] < self.max_newton_steps) & ~jnp.all(s["done"])

        def body(s):
            X = s["X"]
            G, U = grad_dir(X)
            dec = jnp.sum(G * U, axis=0)  # per-example Newton decrement
            done = s["done"] | (dec < eps2)
            f0 = obj(X)

            # Backtracking with one objective evaluation per step size:
            # carry (t, need-mask); halve only still-failing examples.
            def ls_cond(ts):
                _, need, k = ts
                return jnp.any(need & ~done) & (k < self._MAX_HALVINGS)

            def ls_body(ts):
                t, need, k = ts
                t = jnp.where(need, self._BETA * t, t)
                trial = obj(X - t[None, :] * U)
                return t, trial > f0 - self._ALPHA * t * dec, k + 1

            t1 = jnp.ones_like(dec)
            need0 = obj(X - t1[None, :] * U) > f0 - self._ALPHA * t1 * dec
            t, _, _ = lax.while_loop(
                ls_cond, ls_body, (t1, need0, jnp.asarray(0))
            )
            X_new = jnp.where(done[None, :], X, X - t[None, :] * U)
            return dict(it=s["it"] + 1, X=X_new, done=done)

        n = V.shape[1]
        state = dict(
            it=jnp.asarray(0), X=x0, done=jnp.zeros((n,), bool)
        )
        return lax.while_loop(cond, body, state)["X"]

    def prox(self, V, lam, Y):
        if self._is_binary(V):
            # Guarded Newton on  lam·log(1+exp(−y·x)) + ½(x−v)²  per
            # element (shape (1, n) or (n,)).
            shape = V.shape
            V2 = V.reshape(1, -1)
            yv = Y.reshape(V2.shape).astype(V.dtype)

            def obj(X):
                return jnp.sum(
                    lam * jnp.logaddexp(0.0, -yv * X)
                    + 0.5 * (X - V2) ** 2,
                    axis=0,
                )

            def grad_dir(X):
                sig = jax.nn.sigmoid(-yv * X)
                g = -lam * yv * sig + (X - V2)
                h = lam * sig * (1.0 - sig) + 1.0
                return g, g / h

            return self._damped_newton(V2, V2, obj, grad_dir).reshape(shape)

        cls = Y.astype(jnp.int32).reshape(-1)
        k, n = V.shape
        E = jax.nn.one_hot(cls, k, dtype=V.dtype).T  # (k, n)

        def obj(X):
            logZ = jax.scipy.special.logsumexp(X, axis=0)
            return lam * (logZ - jnp.sum(E * X, axis=0)) + 0.5 * jnp.sum(
                (X - V) ** 2, axis=0
            )

        def grad_dir(X):
            # Hessian = diag(lam·p + 1) − lam·p pᵀ per example; exact
            # Newton direction by Sherman-Morrison (≙ the u/z/pu/pptil
            # recurrence of loss.hpp:381-397).
            Pr = jax.nn.softmax(X, axis=0)
            G = lam * (Pr - E) + (X - V)
            D = lam * Pr + 1.0
            U0 = G / D
            Z = Pr / D
            pu = jnp.sum(Pr * U0, axis=0)
            pptil = 1.0 - lam * jnp.sum(Pr * Z, axis=0)
            U = U0 + (lam * pu / pptil)[None, :] * Z
            return G, U

        return self._damped_newton(V, V, obj, grad_dir)


class EmptyRegularizer:
    """No regularization (≙ ``empty_regularizer_t``)."""

    name = "none"

    def evaluate(self, W):
        return jnp.asarray(0.0, jnp.result_type(W))

    def prox(self, V, lam):
        return V


class L2Regularizer:
    """½‖W‖² (≙ ``l2_regularizer_t``): prox = V/(1+lam)."""

    name = "l2"

    def evaluate(self, W):
        return 0.5 * jnp.sum(W * W)

    def prox(self, V, lam):
        return V / (1.0 + lam)


class L1Regularizer:
    """‖W‖₁ (≙ ``l1_regularizer_t``): soft threshold."""

    name = "l1"

    def evaluate(self, W):
        return jnp.sum(jnp.abs(W))

    def prox(self, V, lam):
        return jnp.sign(V) * jnp.maximum(jnp.abs(V) - lam, 0.0)


LOSSES = {
    "squared": SquaredLoss,
    "lad": LadLoss,
    "hinge": HingeLoss,
    "logistic": LogisticLoss,
}

REGULARIZERS = {
    "none": EmptyRegularizer,
    "l2": L2Regularizer,
    "l1": L1Regularizer,
}


def get_loss(name: str):
    return LOSSES[name]()


def get_regularizer(name: str):
    return REGULARIZERS[name]()
