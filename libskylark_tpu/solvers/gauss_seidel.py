"""Synchronous randomized block Gauss-Seidel.

≙ ``algorithms/asynch/AsyRGS.hpp`` (Avron-Druinsky-Gupta): the reference
runs lock-free asynchronous randomized coordinate sweeps with OpenMP
atomics.  TPU has no cross-core atomics in the JAX model (SURVEY §2.7 P9),
so the *mathematics* is kept — randomized block coordinate descent on SPD
``A X = B`` — and the *schedule* becomes synchronous: per sweep, a
counter-derived random permutation of blocks, each block update solving the
``block × block`` diagonal system exactly.  Deterministic given the
context (unlike the reference's schedule-dependent output, tagged
"NOT deterministic" in ``AsyRGS.hpp:25-27``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.context import SketchContext
from ..core.random import sample

__all__ = ["randomized_block_gauss_seidel", "gs_num_blocks"]


def gs_num_blocks(n: int, block_size: int) -> int:
    """Number of (clamped, possibly overlapping) blocks a GS sweep visits —
    the schedule consumes ``sweeps * gs_num_blocks(n, bs)`` counters.
    Exposed so callers reserving per-outer-iteration counter windows
    (``asy_fcg``) share this arithmetic instead of re-deriving it."""
    bs = min(block_size, n)
    return (n + bs - 1) // bs


def randomized_block_gauss_seidel(
    A,
    B,
    context: SketchContext,
    block_size: int = 64,
    sweeps: int = 10,
    x0=None,
    counter_offset=0,
):
    """Solve SPD ``A X = B`` by randomized block Gauss-Seidel sweeps.

    Returns ``(X, info)``.  n must be ≥ block_size; a trailing ragged block
    is padded into the last full block (updates overlap harmlessly — GS
    tolerates overlapping blocks).

    ``counter_offset`` may be a traced scalar shifting the schedule's
    counter window (callers embedding GS in a jitted outer loop — e.g.
    ``asy_fcg`` — reserve one block per outer iteration and pass
    ``it * sweeps * nblocks``).
    """
    A = jnp.asarray(A)
    B = jnp.asarray(B)
    squeeze = B.ndim == 1
    if squeeze:
        B = B[:, None]
    n = A.shape[0]
    bs = min(block_size, n)
    nblocks = gs_num_blocks(n, block_size)
    # Block start offsets; last block clamped (overlap instead of ragged).
    starts = jnp.minimum(jnp.arange(nblocks) * bs, n - bs)
    seed = context.seed
    base = context.reserve(sweeps * nblocks)

    X = jnp.zeros_like(B) if x0 is None else jnp.asarray(x0).reshape(B.shape)

    # All sweep orders generated up-front from the counter stream (static
    # shapes for the jitted loop; ≙ the per-sweep RNG draws of AsyRGS).
    u = sample(
        "uniform",
        seed,
        base,
        sweeps * nblocks,
        dtype=jnp.float32,
        offset=counter_offset,
    )
    orders = jnp.argsort(u.reshape(sweeps, nblocks), axis=1)

    def sweep(s, X):
        order = orders[s]

        def block_update(j, X):
            start = starts[order[j]]
            Ablk = lax.dynamic_slice(A, (start, 0), (bs, n))  # (bs, n)
            Rblk = lax.dynamic_slice(B, (start, 0), (bs, B.shape[1])) - Ablk @ X
            Dblk = lax.dynamic_slice(Ablk, (0, start), (bs, bs))
            delta = jnp.linalg.solve(Dblk, Rblk)
            Xblk = lax.dynamic_slice(X, (start, 0), (bs, X.shape[1]))
            return lax.dynamic_update_slice(X, Xblk + delta, (start, 0))

        return lax.fori_loop(0, nblocks, block_update, X)

    X = lax.fori_loop(0, sweeps, sweep, X)
    R = B - A @ X
    info = {"sweeps": jnp.asarray(sweeps), "resid": jnp.linalg.norm(R, axis=0)}
    return (X[:, 0] if squeeze else X), info
