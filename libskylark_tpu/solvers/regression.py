"""Regression problem/solver framework (≙ ``algorithms/regression/``).

The reference's template-tag system — ``regression_problem_t<Input,
RegressionType, PenaltyType, RegularizationType>`` with solver tags
(``regression_problem.hpp:10-89``, ``regression_solver.hpp``) — collapses
to a dataclass + string enums + a dispatching ``solve``:

- penalty "l2" exact     → QR/SNE/NE/SVD (``linearl2_regression_solver``)
- penalty "l2" sketched  → sketch-and-solve (``sketched_regression_solver``)
- penalty "l2" accelerated → Blendenpik / LSRN
  (``accelerated_regression_solver``)
- penalty "l2" refine    → certified mixed-precision refinement (sketch-
  preconditioned low-precision factorization + f64 residual refinement;
  no reference counterpart — documented deviation)
- penalty "l1" sketched  → l1 sketch-and-solve via a Cauchy/MMT sketch +
  IRLS on the reduced problem (the reference frames l1 tags in the same
  system; its concrete l1 solvers run sketched problems through an LP —
  here IRLS, documented deviation)

``Ridge`` regularization adds λ via the augmented system (the standard
[A; √λI] stacking), matching ``El::Ridge`` semantics used by the
reference's KRR path.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax.numpy as jnp

from ..core.context import SketchContext
from ..linalg.least_squares import LeastSquaresParams, approximate_least_squares, exact_least_squares
from .accelerated import FasterLeastSquaresParams, faster_least_squares, lsrn_least_squares

__all__ = ["RegressionProblem", "solve_regression"]


@dataclass
class RegressionProblem:
    """≙ ``regression_problem_t``: (m, n, A) + penalty/regularization."""

    A: Any
    penalty: str = "l2"  # "l2" | "l1"
    regularization: str = "none"  # "none" | "ridge"
    lam: float = 0.0

    @property
    def shape(self):
        return self.A.shape


def _augment_ridge(A, B, lam):
    m, n = A.shape
    sq = jnp.sqrt(jnp.asarray(lam, A.dtype))
    A_aug = jnp.concatenate([A, sq * jnp.eye(n, dtype=A.dtype)], axis=0)
    B = jnp.asarray(B)
    pad_shape = (n,) + B.shape[1:]
    B_aug = jnp.concatenate([B, jnp.zeros(pad_shape, B.dtype)], axis=0)
    return A_aug, B_aug


def _irls_l1(A, B, iters=30, eps=1e-6):
    """IRLS for min ‖Ax − b‖₁ on a small (sketched) problem, per column."""
    B = jnp.asarray(B)
    squeeze = B.ndim == 1
    if squeeze:
        B = B[:, None]

    def one(b):
        x = exact_least_squares(A, b)
        for _ in range(iters):
            r = A @ x - b
            w = 1.0 / jnp.sqrt(jnp.abs(r) + eps)
            x = exact_least_squares(w[:, None] * A, w * b)
        return x

    X = jnp.stack([one(B[:, j]) for j in range(B.shape[1])], axis=1)
    return X[:, 0] if squeeze else X


def solve_regression(
    problem: RegressionProblem,
    B,
    solver: str = "exact",
    context: SketchContext | None = None,
    alg: str = "qr",
    params: Any = None,
):
    """Dispatch ≙ the regression_solver_t specializations.

    solver ∈ {"exact", "sketched", "accelerated", "lsrn", "refine",
    "auto"}.  Returns X (and (X, info) for iterative solvers, refine
    included).

    ``"auto"`` hands the l2 route to the policy layer: the sketched
    entrypoint consults :func:`~libskylark_tpu.policy.choose_route`
    against the profile store (``SKYLARK_POLICY_DIR``) and a matured
    entry may reroute to Blendenpik/LSRN/refine/exact — with an empty
    store it IS ``"sketched"`` (the historical default, bit-identical).
    """
    A = problem.A
    if problem.regularization == "ridge" and problem.lam > 0:
        A, B = _augment_ridge(jnp.asarray(A), B, problem.lam)

    if problem.penalty == "l1":
        if context is None:
            raise ValueError("l1 regression needs a SketchContext")
        from ..sketch.base import Dimension
        from ..sketch.hash import MMT

        m, n = A.shape
        s = min(max(4 * n, 64), m)
        # Cauchy-value sketch preserves l1 geometry (MMT, Meng-Mahoney).
        S = MMT(m, s, context)
        SA = S.apply(jnp.asarray(A), Dimension.COLUMNWISE)
        SB = S.apply(jnp.asarray(B), Dimension.COLUMNWISE)
        return _irls_l1(SA, SB)

    if solver == "exact":
        return exact_least_squares(A, B, alg=alg)
    if solver == "auto":
        if context is None:
            raise ValueError("auto solver needs a SketchContext")
        # Route is left open: approximate_least_squares consults the
        # policy layer and may land on sketch / blendenpik / lsrn / exact.
        return approximate_least_squares(
            A, B, context, params or LeastSquaresParams(), alg=alg
        )
    if solver == "sketched":
        if context is None:
            raise ValueError("sketched solver needs a SketchContext")
        # "sketched" means sketch-and-solve by name: pin the route so a
        # matured profile cannot reroute it (that is "auto"'s privilege).
        return approximate_least_squares(
            A, B, context, params or LeastSquaresParams(), alg=alg,
            route="sketch",
        )
    if solver == "refine":
        if context is None:
            raise ValueError("refine solver needs a SketchContext")
        # Mixed-precision refinement by name: pin the route (same
        # privilege split as "sketched" vs "auto") and surface the
        # iteration/certification info like the iterative solvers do.
        return approximate_least_squares(
            A, B, context, params or LeastSquaresParams(), alg=alg,
            route="refine", return_info=True,
        )
    if solver == "accelerated":
        if context is None:
            raise ValueError("accelerated solver needs a SketchContext")
        return faster_least_squares(A, B, context, params)
    if solver == "lsrn":
        if context is None:
            raise ValueError("lsrn solver needs a SketchContext")
        return lsrn_least_squares(A, B, context, params)
    raise ValueError(f"unknown solver {solver!r}")
