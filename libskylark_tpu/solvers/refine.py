"""Sketch-preconditioned mixed-precision iterative refinement for LS.

The Carson-Higham mixed-precision recipe grafted onto the
sketch-to-precondition lineage (Blendenpik/LSRN, ``algorithms/``): do the
expensive factorization work at a LOW working precision — QR of the
sketched matrix ``S·A`` at bf16-entries/f32-accumulate where
:func:`~libskylark_tpu.core.precision.f32_accumulable` allows, f32
otherwise — then recover full f64 accuracy with cheap refinement sweeps:

    r_k = b - A x_k                      (f64 — the only f64 matvecs)
    z_k = R⁻¹ R⁻ᵀ (Aᵀ r_k)              (working precision, two
                                          triangular solves through
                                          ``TriInversePrecond``)
    x_{k+1} = x_k + θ_k p_k              (conjugate-direction step built
                                          from the z's)

i.e. preconditioned CG on the normal equations with the sketched factor
as preconditioner: for a subspace embedding of distortion ε the
preconditioned condition number is ≤ ((1+ε)/(1−ε))², so tens of sweeps
of O(mn) matvecs replace the O(mn²) f64 factorization — and the
conjugate steps are parameter-free, adapting to the embedding quality
actually drawn instead of assuming a distortion bound.

Certification rides the existing guard ladder: attempt 0 certifies the
computed factor ``R`` of ``QR(S·A)`` with ``guard.certify_sketch`` —
``R`` carries exactly ``S·A``'s singular values at an n×n probe cost,
and certifying the factor actually used as preconditioner also catches
a QR breakdown the sketch itself would hide (so attempt-0-OFF behavior
of the other routes is untouched), the refinement gate is the
guard-certified optimality residual ``‖Aᵀr‖ ≤ rtol·σ_max·‖r‖`` (σ_max
from the certificate), and a stagnation/divergence detector demotes the attempt
to a RESKETCH verdict — the ladder falls down its existing rungs (fresh
seed → grow → exact dense solve).  With guarding disabled the detector
raises :class:`~libskylark_tpu.utils.exceptions.RefinementError`
(code 115) instead.
"""

from __future__ import annotations

from dataclasses import dataclass, replace

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .. import guard, plans
from ..core.context import SketchContext
from ..core.params import Params
from ..core.precision import f32_accumulable
from ..sketch.base import Dimension, create_sketch
from ..utils.exceptions import RefinementError
from .precond import TriInversePrecond

__all__ = ["RefineParams", "refine_least_squares"]

_STAGE = "refine_ls"

# Stagnation detector: this many consecutive sweeps without a
# stagnation_factor improvement over the best certified gate value
# trips the detector (momentum makes single-sweep progress lumpy, so
# one flat sweep must not fire it).
_STALL_LIMIT = 5
_DIVERGE_FACTOR = 100.0


@dataclass
class RefineParams(Params):
    """Knobs for the refine route (defaults match the sketch route's
    sizing so the policy layer can compare like for like)."""

    sketch_type: str | None = None  # None → FJLT dense / CWT sparse
    sketch_size: int | None = None  # default 4 * n, floored at 2 * n
    max_iters: int = 100
    rtol: float | None = None  # gate: ||A'r|| <= rtol * sigma_max * ||r||
    stagnation_factor: float = 0.9


def _working_cast(A, dtype):
    """(A_for_sketch, qr_dtype, rung): bf16 sketch operand with an f32
    factorization where ``f32_accumulable`` allows the input dtype to
    ride f32 accumulation, plain f32 otherwise (f64 inputs refuse the
    silent demotion — ``f32_accumulable(f64)`` is False — so only the
    explicit refine contract lowers them, and only to f32)."""
    if f32_accumulable(dtype):
        return A.astype(jnp.bfloat16), jnp.float32, "bf16+f32"
    return A.astype(jnp.float32), jnp.float32, "f32"


def _solve_pair(precond, G, wdtype, rdtype):
    """One correction through the low-precision factor: two triangular
    solves of ``(RᵀR) Z = G`` at working precision, lifted back."""
    return precond.apply(precond.apply_adjoint(G.astype(wdtype))).astype(
        rdtype
    )


def _colsum(U, V):
    return jnp.sum(U * V, axis=0)


def _rmatvec(A, V):
    """``Aᵀ·V`` without a transposed contraction: XLA:CPU lowers
    ``A.T @ V`` to a strided gather that runs ~40× slower than the
    bitwise-different-but-mathematically-identical ``(Vᵀ A)ᵀ`` row-major
    form, and the refinement sweeps live on this matvec.  Sparse
    operands keep the native transpose (their kernels are fine and the
    dense rewrite cannot dispatch through them)."""
    if hasattr(A, "todense"):
        return A.T @ V
    return (V.T @ A).T


@jax.jit
def _sweep(A, R, X, Rres, P, gz):
    """One fused conjugate-direction sweep (dense operands): the two
    O(mn) matvecs, the incremental X/residual updates, and the
    SPECULATIVE next direction, compiled once per shape so the
    host-driven loop pays two GEMV passes per sweep instead of a dozen
    eager dispatches.  Returns the new state plus the stacked
    ``[‖G‖, ‖r‖, ‖X‖]`` diagnostics the host gates on (the caller
    discards the speculative direction when it restarts or halts)."""
    precond = TriInversePrecond(R)
    wdtype = R.dtype
    rdtype = X.dtype
    W = A @ P
    w2 = _colsum(W, W)
    theta = jnp.where(w2 > 0, gz / jnp.where(w2 > 0, w2, 1.0), 0.0)
    X = X + theta[None, :] * P
    Rres = Rres - theta[None, :] * W
    G = _rmatvec(A, Rres)
    Z = _solve_pair(precond, G, wdtype, rdtype)
    gz_new = _colsum(G, Z)
    beta = jnp.where(gz > 0, gz_new / jnp.where(gz > 0, gz, 1.0), 0.0)
    norms = jnp.stack(
        [jnp.linalg.norm(G), jnp.linalg.norm(Rres), jnp.linalg.norm(X)]
    )
    return X, Rres, G, Z + beta[None, :] * P, gz_new, norms


def _sweep_sparse(A, R, X, Rres, P, gz):
    """Eager twin of :func:`_sweep` for sparse ``A`` (scipy-style
    operands cannot trace through jit)."""
    precond = TriInversePrecond(R)
    wdtype = R.dtype
    rdtype = X.dtype
    W = A @ P
    w2 = _colsum(W, W)
    theta = jnp.where(w2 > 0, gz / jnp.where(w2 > 0, w2, 1.0), 0.0)
    X = X + theta[None, :] * P
    Rres = Rres - theta[None, :] * W
    G = _rmatvec(A, Rres)
    Z = _solve_pair(precond, G, wdtype, rdtype)
    gz_new = _colsum(G, Z)
    beta = jnp.where(gz > 0, gz_new / jnp.where(gz > 0, gz, 1.0), 0.0)
    norms = jnp.stack(
        [jnp.linalg.norm(G), jnp.linalg.norm(Rres), jnp.linalg.norm(X)]
    )
    return X, Rres, G, Z + beta[None, :] * P, gz_new, norms


def _refine_loop(A, B, R, *, sigma_max, rtol, max_iters,
                 stagnation_factor, rdtype):
    """Host-driven refinement sweeps; returns ``(X, stats)`` where
    ``stats["halt"]`` is one of ``converged | stagnated | diverged``.

    The sweep is conjugate-direction refinement (preconditioned CG on
    the normal equations with per-column directions): parameter-free, it
    adapts to the ACTUAL preconditioned spectrum instead of assuming a
    distortion bound, so a weaker-than-Gaussian embedding (FJLT at small
    s) just takes a few more sweeps rather than stalling.  Residuals are
    tracked incrementally at f64 and the convergence gate only passes on
    a FRESHLY recomputed ``b - A x`` (the certified gate); a recompute
    that disagrees restarts the directions from the true residual."""
    n = R.shape[1]
    precond = TriInversePrecond(R)
    wdtype = R.dtype
    sweep = _sweep_sparse if hasattr(A, "todense") else _sweep
    X = jnp.zeros((n, B.shape[1]), rdtype)
    bnorm = float(jnp.linalg.norm(B))
    eps = float(jnp.finfo(rdtype).eps)
    Rres = B
    G = _rmatvec(A, Rres)
    Z = _solve_pair(precond, G, wdtype, rdtype)
    P = Z
    gz = _colsum(G, Z)
    best = float("inf")
    stall = 0
    gnorm = float(jnp.linalg.norm(G))
    gate = float("nan")
    halt = "stagnated"
    iters = 0
    for it in range(1, max_iters + 1):
        X, Rres, G, P_next, gz_next, norms = sweep(A, R, X, Rres, P, gz)
        gnorm, rnorm, xnorm = (float(v) for v in np.asarray(norms))
        gate = rtol * sigma_max * rnorm + eps * sigma_max * bnorm
        iters = it
        if not np.isfinite(gnorm) or not np.isfinite(rnorm):
            halt = "diverged"
            break
        passed = gnorm <= gate or rnorm <= rtol * (sigma_max * xnorm + bnorm)
        if passed or it == max_iters or (
            stall + 1 >= _STALL_LIMIT and gnorm > stagnation_factor * best
        ):
            # Certify on a freshly recomputed f64 residual — incremental
            # updates drift, and only the true residual gates.
            Rres = B - A @ X
            G = _rmatvec(A, Rres)
            gnorm = float(jnp.linalg.norm(G))
            rnorm = float(jnp.linalg.norm(Rres))
            gate = rtol * sigma_max * rnorm + eps * sigma_max * bnorm
            relax = 1.0 if passed else 32.0
            if (
                gnorm <= relax * gate
                or rnorm <= rtol * (sigma_max * xnorm + bnorm)
            ):
                halt = "converged"
                break
            if it == max_iters:
                halt = "stagnated"
                break
            if not passed:  # genuine stall on the true residual too
                halt = "stagnated"
                break
            # Drift only: restart the directions from the true residual
            # (discard the speculative direction the sweep built).
            Z = _solve_pair(precond, G, wdtype, rdtype)
            P = Z
            gz = _colsum(G, Z)
            stall = 0
            best = min(best, gnorm)
            continue
        if gnorm > _DIVERGE_FACTOR * max(best, eps * sigma_max * bnorm):
            halt = "diverged"
            break
        stall = 0 if gnorm <= stagnation_factor * best else stall + 1
        best = min(best, gnorm)
        P, gz = P_next, gz_next
    stats = {
        "iters": iters,
        "halt": halt,
        "converged": halt == "converged",
        "gate": gate,
        "gradient_norm": gnorm,
    }
    return X, stats


def _refine_loop_traced(A, B, R, *, max_iters, rdtype):
    """Fixed-trip jit-compatible sweeps (no host gates, no detector) for
    callers tracing the unguarded path — same conjugate-direction
    update, fori_loop body."""
    n = R.shape[1]
    precond = TriInversePrecond(R)
    wdtype = R.dtype
    X0 = jnp.zeros((n, B.shape[1]), rdtype)
    G0 = _rmatvec(A, B)
    Z0 = _solve_pair(precond, G0, wdtype, rdtype)

    def body(_, carry):
        X, Rres, P, gz = carry
        W = A @ P
        w2 = _colsum(W, W)
        theta = jnp.where(w2 > 0, gz / jnp.where(w2 > 0, w2, 1.0), 0.0)
        X = X + theta[None, :] * P
        Rres = Rres - theta[None, :] * W
        G = _rmatvec(A, Rres)
        Z = _solve_pair(precond, G, wdtype, rdtype)
        gz_new = _colsum(G, Z)
        beta = jnp.where(gz > 0, gz_new / jnp.where(gz > 0, gz, 1.0), 0.0)
        return X, Rres, Z + beta[None, :] * P, gz_new

    X, _, _, _ = lax.fori_loop(
        0, max_iters, body, (X0, B, Z0, _colsum(G0, Z0))
    )
    stats = {"iters": max_iters, "halt": "traced", "converged": None}
    return X, stats


def refine_least_squares(
    A,
    B,
    context: SketchContext,
    params: RefineParams | None = None,
    *,
    fault_plan=None,
):
    """Solve ``min_X ||A X - B||_F`` by sketch-preconditioned
    mixed-precision iterative refinement; returns ``(X, info)``.

    ``info`` carries ``recovery`` (the guard ladder's report) and
    ``refine`` (``iters``, ``rung``, ``converged``, ``gate``,
    ``sketch_size`` — what the policy store folds as refine outcomes).
    Guard-on stagnation falls down the ladder (resketch → grow → exact
    dense solve); guard-off stagnation raises
    :class:`~libskylark_tpu.utils.exceptions.RefinementError`.
    """
    params = params or RefineParams()
    is_sparse = hasattr(A, "todense")
    if not is_sparse:
        A = jnp.asarray(A)
    B = jnp.asarray(B)
    squeeze = B.ndim == 1
    if squeeze:
        B = B[:, None]
    m, n = A.shape
    in_dtype = A.data.dtype if is_sparse else A.dtype
    rdtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    rtol = (
        params.rtol
        if params.rtol is not None
        else float(jnp.finfo(rdtype).eps) ** 0.75
    )
    stype = params.sketch_type or ("CWT" if is_sparse else "FJLT")
    s0 = params.sketch_size or min(4 * n, m)
    s0 = min(max(s0, min(2 * n, m)), m)
    A64 = A if is_sparse else A.astype(rdtype)
    B64 = B.astype(rdtype)

    def dense64():
        return (A64.todense() if is_sparse else A64)

    if s0 >= m:
        # Sketching cannot shrink the problem — the "refined" answer IS
        # the exact full-precision solve; report it honestly.
        from ..linalg.least_squares import exact_least_squares

        X = exact_least_squares(dense64(), B64, alg="qr")
        report = guard.RecoveryReport.disabled(_STAGE)
        info = {
            "recovery": report.to_dict(),
            "refine": {
                "iters": 0, "rung": "exact-f64", "converged": True,
                "sketch_size": int(s0),
            },
        }
        return (X[:, 0] if squeeze else X), info

    guard_on = guard.enabled() and not guard.is_traced(A, B)

    if not guard_on and guard.is_traced(A, B):
        # Under an enclosing jit: fixed-trip traced sweeps, no host-side
        # certification or detector.
        A_w, qr_dtype, rung = _working_cast(A, in_dtype)
        S = create_sketch(stype, m, s0, context)
        SA = plans.apply(S, A_w, Dimension.COLUMNWISE).astype(qr_dtype)
        R = jnp.linalg.qr(SA, mode="r")
        X, stats = _refine_loop_traced(
            A64, B64, R, max_iters=params.max_iters, rdtype=rdtype
        )
        report = guard.RecoveryReport.disabled(_STAGE)
        stats.update(rung=rung, sketch_size=int(s0))
        info = {"recovery": report.to_dict(), "refine": stats}
        return (X[:, 0] if squeeze else X), info

    def attempt(ctx, s_i, i):
        S = create_sketch(stype, m, s_i, ctx)
        A_w, qr_dtype, rung = _working_cast(A, in_dtype)
        SA = plans.apply(S, A_w, Dimension.COLUMNWISE).astype(qr_dtype)
        if fault_plan is not None:
            SA = fault_plan.corrupt_sketch(i, SA)
        R = jnp.linalg.qr(SA, mode="r")
        # Certify the factor, not the sketch: R carries exactly S·A's
        # singular values at an n×n probe cost (vs s×n), and a QR
        # breakdown (non-finite R from a finite-but-degenerate sketch)
        # is caught where the sketch itself would certify clean.
        cert = guard.certify_sketch(R, stage=_STAGE)
        if not cert.ok:
            return None, cert
        X, stats = _refine_loop(
            A64, B64, R,
            sigma_max=float(cert.sigma_max),
            rtol=rtol,
            max_iters=params.max_iters,
            stagnation_factor=params.stagnation_factor,
            rdtype=rdtype,
        )
        stats.update(rung=rung, sketch_size=int(s_i))
        if stats["halt"] != "converged":
            cert = replace(
                cert,
                verdict=guard.RESKETCH,
                detail=(
                    f"refinement {stats['halt']} after {stats['iters']} "
                    f"sweeps (gate {stats['gate']:.3e}, "
                    f"||A'r|| {stats['gradient_norm']:.3e})"
                ),
            )
            return None, cert
        return (X, stats), cert

    if not guard_on:
        ctx = SketchContext(seed=context.seed, counter=context.counter)
        result, cert = attempt(ctx, s0, 0)
        if result is None:
            raise RefinementError(
                f"mixed-precision refinement failed with guarding "
                f"disabled: {cert.detail}",
                iters=params.max_iters,
                residual=cert.cond,
                stage=_STAGE,
            )
        X, stats = result
        report = guard.RecoveryReport.disabled(_STAGE)
        info = {"recovery": report.to_dict(), "refine": stats}
        return (X[:, 0] if squeeze else X), info

    def fallback():
        from ..linalg.least_squares import exact_least_squares

        X = exact_least_squares(dense64(), B64, alg="svd")
        return X, {
            "iters": 0, "rung": "exact-f64", "converged": False,
            "halt": "fallback", "sketch_size": int(s0),
        }

    (X, stats), report = guard.run_ladder(
        _STAGE, context, s0, m, attempt, fallback
    )
    info = {"recovery": report.to_dict(), "refine": stats}
    return (X[:, 0] if squeeze else X), info
