"""Krylov solvers as jitted ``lax.while_loop`` iterations.

≙ ``algorithms/Krylov/``: LSQR (``LSQR.hpp:21-259``), preconditioned CG
(``CG.hpp:24-150``), FlexibleCG (``FlexibleCG.hpp:23``), Chebyshev
(``Chebyshev.hpp``), with ``krylov_iter_params_t``
(``krylov_iter_params.hpp:8``) as a dataclass.

TPU design:

- Everything runs inside one ``lax.while_loop`` — convergence tests are
  computed on-device (no per-iteration host sync, unlike the reference's
  rank-0 logging round-trips).  The hot ops are the two matvecs per
  iteration, which for sharded A are GSPMD matmuls with psum reductions
  over ICI (≙ the MPI allreduces inside Elemental's Gemv).
- All solvers are **multi-RHS**: B may be (m,) or (m, k); the Golub-Kahan /
  CG scalars become per-column vectors (the reference iterates columns
  together the same way, via Elemental matrices of width k).
- Stopping: per-column Paige-Saunders S1/S2 tests plus the reference's
  stagnation detector (``LSQR.hpp:193-230``); the loop exits when every
  column has converged or stagnated.

Preemption safety: every solver is structured as a ``*_chunked`` factory
returning a :class:`~libskylark_tpu.resilient.chunked.ChunkedSolver` —
``init_state()`` builds the loop carry, ``step_chunk(state, k)`` runs one
jitted while-loop segment of ≤ k iterations, ``extract_result(state)``
finishes.  The classic one-shot entry points (``lsqr`` etc.) run a single
chunk of the full ``iter_lim`` budget, so they keep their exact semantics;
``resilient.ResilientRunner`` drives the same factories in checkpointed
host rounds.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax

from ..core.params import Params
from ..resilient.chunked import ChunkedSolver
from .precond import IdPrecond

__all__ = [
    "KrylovParams",
    "lsqr",
    "cg",
    "flexible_cg",
    "chebyshev",
    "lsqr_chunked",
    "cg_chunked",
    "flexible_cg_chunked",
    "chebyshev_chunked",
]


@dataclass
class KrylovParams(Params):
    """≙ ``krylov_iter_params_t`` (tolerance, iter_lim)."""

    tolerance: float = 1e-14
    iter_lim: int = 100


def _ops(A):
    """(matvec, rmatvec) for dense / BCOO / (matvec, rmatvec) pair."""
    if isinstance(A, tuple):
        return A
    return (lambda x: A @ x), (lambda y: A.T @ y)


def _colnorm(X):
    return jnp.sqrt(jnp.sum(X * X, axis=0))


def _as2d(b):
    b = jnp.asarray(b)
    return (b[:, None], True) if b.ndim == 1 else (b, False)


def _chunk_stepper(body, iter_lim: int, done_of=None):
    """Jitted ≤ num_iters while-loop segment over carry dicts holding a
    global ``it`` counter.  ``done_of(state)`` adds the solver's on-device
    convergence predicate to the loop condition."""

    @partial(jax.jit, static_argnames=("num_iters",))
    def step_chunk(s, num_iters: int):
        stop = jnp.minimum(s["it"] + num_iters, iter_lim)

        def cond(st):
            go = st["it"] < stop
            if done_of is not None:
                go = go & ~done_of(st)
            return go

        return lax.while_loop(cond, body, s)

    return step_chunk


def _one_shot(factory_state_solver, iter_lim: int):
    sol = factory_state_solver
    return sol.extract_result(sol.step_chunk(sol.init_state(), max(iter_lim, 0)))


def lsqr_chunked(
    A, B, precond=None, params: KrylovParams | None = None, x0=None
) -> ChunkedSolver:
    """Chunkable LSQR: state in/out per ≤ k-iteration jitted segment (see
    :func:`lsqr` for the math and return convention of the result)."""
    params = params or KrylovParams()
    N = precond or IdPrecond()
    matvec0, rmatvec0 = _ops(A)
    matvec = lambda v: matvec0(N.apply(v))
    rmatvec = lambda u: N.apply_adjoint(rmatvec0(u))

    B, squeeze = _as2d(B)
    dtype = B.dtype
    eps = jnp.finfo(dtype).eps
    atol = btol = jnp.asarray(max(params.tolerance, float(eps)), dtype)

    if x0 is not None:
        x0 = jnp.asarray(x0)
        if x0.ndim == 1:
            x0 = x0[:, None]

    def init_state():
        U = B if x0 is None else B - matvec0(x0)
        beta = _colnorm(U)
        U = U / jnp.where(beta > 0, beta, 1)
        V = rmatvec(U)
        alpha = _colnorm(V)
        V = V / jnp.where(alpha > 0, alpha, 1)
        n = V.shape[0]
        k = B.shape[1]
        return dict(
            it=jnp.zeros((), jnp.int32),
            Y=jnp.zeros((n, k), dtype),
            U=U,
            V=V,
            W=V,
            alpha=alpha,
            beta=beta,
            rhobar=alpha,
            phibar=beta,
            anorm=jnp.zeros((), dtype),
            done=beta <= btol * _colnorm(B),
            stag=jnp.zeros((k,), jnp.int32),
            arnorm_best=jnp.full((k,), jnp.inf, dtype),
            bnorm=_colnorm(B),
        )

    def body(s):
        U, V, W, Y = s["U"], s["V"], s["W"], s["Y"]
        alpha, beta = s["alpha"], s["beta"]
        # Golub-Kahan bidiagonalization step (LSQR.hpp:100-130).
        U = matvec(V) - alpha[None, :] * U
        beta = _colnorm(U)
        U = U / jnp.where(beta > 0, beta, 1)
        V = rmatvec(U) - beta[None, :] * V
        alpha_new = _colnorm(V)
        V = V / jnp.where(alpha_new > 0, alpha_new, 1)
        # Givens rotation update (LSQR.hpp:135-160).  rho can be 0 for an
        # all-zero RHS column (alpha=beta=0); guard every division so the
        # column stays exactly 0 instead of NaN-poisoning Y.
        rho = jnp.hypot(s["rhobar"], beta)
        rho_s = jnp.where(rho > 0, rho, 1)
        c = s["rhobar"] / rho_s
        sn = beta / rho_s
        theta = sn * alpha_new
        rhobar = -c * alpha_new
        phi = c * s["phibar"]
        phibar_new = sn * s["phibar"]
        step = jnp.where(s["done"], 0.0, phi / rho_s)
        Y = Y + step[None, :] * W
        W = V - (theta / rho_s)[None, :] * W
        anorm = jnp.hypot(s["anorm"], jnp.max(jnp.hypot(alpha, beta)))
        # Paige-Saunders S1/S2 per column (LSQR.hpp:193-230).
        rnorm = phibar_new
        arnorm = alpha_new * jnp.abs(c * phibar_new)
        ynorm = _colnorm(Y)
        s1 = rnorm <= btol * s["bnorm"] + atol * anorm * ynorm
        s2 = arnorm <= atol * anorm * jnp.maximum(rnorm, eps)
        # Stagnation (LSQR.hpp stagnation check): for LS problems the
        # residual plateaus at the optimum while the normal-equation
        # residual (arnorm) keeps falling, so stagnation requires BOTH to
        # stop improving for several consecutive iterations.
        no_progress = (phibar_new >= s["phibar"] * (1 - 10 * eps)) & (
            arnorm >= s["arnorm_best"] * (1 - 1e3 * eps)
        )
        stag = jnp.where(no_progress, s["stag"] + 1, 0)
        done = s["done"] | s1 | s2 | (stag >= 5)
        return dict(
            it=s["it"] + 1,
            Y=Y,
            U=U,
            V=V,
            W=W,
            alpha=alpha_new,
            beta=beta,
            rhobar=rhobar,
            phibar=phibar_new,
            anorm=anorm,
            done=done,
            stag=stag,
            arnorm_best=jnp.minimum(s["arnorm_best"], arnorm),
            bnorm=s["bnorm"],
        )

    def extract_result(s):
        X = N.apply(s["Y"])
        if x0 is not None:
            X = X + x0
        info = {
            "iterations": s["it"],
            "flag": jnp.where(jnp.all(s["done"]), 0, 1),
            "resid": s["phibar"],
        }
        return (X[:, 0] if squeeze else X), info

    return ChunkedSolver(
        init_state=init_state,
        step_chunk=_chunk_stepper(
            body, params.iter_lim, done_of=lambda st: jnp.all(st["done"])
        ),
        extract_result=extract_result,
        is_done=lambda s: int(s["it"]) >= params.iter_lim
        or bool(jnp.all(s["done"])),
        iteration=lambda s: int(s["it"]),
        kind="lsqr",
    )


def lsqr(A, B, precond=None, params: KrylovParams | None = None, x0=None):
    """Preconditioned LSQR for ``min_X ||A X - B||`` (per column).

    ``precond`` is a *right* preconditioner N (≙ ``outplace_precond_t``):
    LSQR runs on A·N and returns ``X = N·Y`` (Blendenpik/LSRN use this).
    Returns ``(X, info)`` with ``info = {"iterations", "flag", "resid"}``;
    flag 0 = converged, 1 = iter limit, per column 2 = stagnated.
    """
    params = params or KrylovParams()
    return _one_shot(lsqr_chunked(A, B, precond, params, x0), params.iter_lim)


def cg_chunked(
    A, B, precond=None, params: KrylovParams | None = None, x0=None
) -> ChunkedSolver:
    """Chunkable preconditioned CG (see :func:`cg`)."""
    params = params or KrylovParams()
    M = precond or IdPrecond()
    matvec, _ = _ops(A)
    B, squeeze = _as2d(B)
    dtype = B.dtype
    tol = jnp.asarray(params.tolerance, dtype)
    bnorm = _colnorm(B)

    def init_state():
        X = jnp.zeros_like(B) if x0 is None else jnp.asarray(x0).reshape(B.shape)
        R = B - matvec(X) if x0 is not None else B
        Z = M.apply(R)
        return dict(
            it=jnp.zeros((), jnp.int32),
            X=X,
            R=R,
            P=Z,
            rz=jnp.sum(R * Z, axis=0),
            done=_colnorm(R) <= tol * jnp.maximum(bnorm, 1e-30),
        )

    def body(s):
        Q = matvec(s["P"])
        denom = jnp.sum(s["P"] * Q, axis=0)
        alpha = jnp.where(s["done"], 0.0, s["rz"] / jnp.where(denom != 0, denom, 1))
        X = s["X"] + alpha[None, :] * s["P"]
        R = s["R"] - alpha[None, :] * Q
        Z = M.apply(R)
        rz_new = jnp.sum(R * Z, axis=0)
        beta = rz_new / jnp.where(s["rz"] != 0, s["rz"], 1)
        P = Z + beta[None, :] * s["P"]
        done = s["done"] | (_colnorm(R) <= tol * jnp.maximum(bnorm, 1e-30))
        return dict(it=s["it"] + 1, X=X, R=R, P=P, rz=rz_new, done=done)

    def extract_result(s):
        info = {
            "iterations": s["it"],
            "flag": jnp.where(jnp.all(s["done"]), 0, 1),
            "resid": _colnorm(s["R"]),
        }
        return (s["X"][:, 0] if squeeze else s["X"]), info

    return ChunkedSolver(
        init_state=init_state,
        step_chunk=_chunk_stepper(
            body, params.iter_lim, done_of=lambda st: jnp.all(st["done"])
        ),
        extract_result=extract_result,
        is_done=lambda s: int(s["it"]) >= params.iter_lim
        or bool(jnp.all(s["done"])),
        iteration=lambda s: int(s["it"]),
        kind="cg",
    )


def cg(A, B, precond=None, params: KrylovParams | None = None, x0=None):
    """Preconditioned conjugate gradient for SPD ``A X = B`` (multi-RHS).

    ≙ ``algorithms/Krylov/CG.hpp:24-150`` (with ``precond`` the outplace
    M ≈ A⁻¹ as in ``FasterKernelRidge``'s feature-map preconditioner).
    """
    params = params or KrylovParams()
    return _one_shot(cg_chunked(A, B, precond, params, x0), params.iter_lim)


def flexible_cg_chunked(
    A, B, precond=None, params: KrylovParams | None = None, memory: int = 5
) -> ChunkedSolver:
    """Chunkable FlexibleCG (see :func:`flexible_cg`).  The ring buffers of
    past directions ride the state pytree, so a resumed run keeps the same
    re-orthogonalization window."""
    params = params or KrylovParams()
    matvec, _ = _ops(A)
    B, squeeze = _as2d(B)
    dtype = B.dtype
    tol = jnp.asarray(params.tolerance, dtype)
    m, k = B.shape

    if precond is None:
        apply_M = lambda R, it: R
    elif callable(precond) and not hasattr(precond, "apply"):
        apply_M = precond
    else:
        apply_M = lambda R, it: precond.apply(R)

    bnorm = _colnorm(B)

    def init_state():
        return dict(
            it=jnp.zeros((), jnp.int32),
            X=jnp.zeros_like(B),
            R=B,
            # Ring buffers of past directions P and A·P, per RHS column.
            Pbuf=jnp.zeros((memory, m, k), dtype),
            Qbuf=jnp.zeros((memory, m, k), dtype),
            pq=jnp.ones((memory, k), dtype),  # pᵀAp normalizers (1 avoids 0-div)
            done=bnorm <= tol,
        )

    def body(s):
        Z = apply_M(s["R"], s["it"])
        # Orthogonalize Z against stored directions (A-inner product).
        coeffs = jnp.einsum("smk,mk->sk", s["Qbuf"], Z) / s["pq"]
        P = Z - jnp.einsum("smk,sk->mk", s["Pbuf"], coeffs)
        Q = matvec(P)
        denom = jnp.sum(P * Q, axis=0)
        denom = jnp.where(jnp.abs(denom) > 0, denom, 1)
        alpha = jnp.where(s["done"], 0.0, jnp.sum(P * s["R"], axis=0) / denom)
        X = s["X"] + alpha[None, :] * P
        R = s["R"] - alpha[None, :] * Q
        slot = s["it"] % memory
        Pbuf = s["Pbuf"].at[slot].set(P)
        Qbuf = s["Qbuf"].at[slot].set(Q)
        pq = s["pq"].at[slot].set(denom)
        done = s["done"] | (_colnorm(R) <= tol * jnp.maximum(bnorm, 1e-30))
        return dict(
            it=s["it"] + 1, X=X, R=R, Pbuf=Pbuf, Qbuf=Qbuf, pq=pq, done=done
        )

    def extract_result(s):
        info = {
            "iterations": s["it"],
            "flag": jnp.where(jnp.all(s["done"]), 0, 1),
            "resid": _colnorm(s["R"]),
        }
        return (s["X"][:, 0] if squeeze else s["X"]), info

    return ChunkedSolver(
        init_state=init_state,
        step_chunk=_chunk_stepper(
            body, params.iter_lim, done_of=lambda st: jnp.all(st["done"])
        ),
        extract_result=extract_result,
        is_done=lambda s: int(s["it"]) >= params.iter_lim
        or bool(jnp.all(s["done"])),
        iteration=lambda s: int(s["it"]),
        kind="flexible_cg",
    )


def flexible_cg(
    A, B, precond=None, params: KrylovParams | None = None, memory: int = 5
):
    """Flexible CG: supports a *varying* preconditioner by re-orthogonalizing
    the search direction against the last ``memory`` directions.

    ≙ ``algorithms/Krylov/FlexibleCG.hpp:23`` (used with the inexact/
    randomized inner preconditioners of AsyFCG, ``algorithms/asynch/
    AsyFCG.hpp``).  ``precond`` may be a function ``(R, it) -> Z`` for
    iteration-dependent preconditioning, or a fixed precond object.
    """
    params = params or KrylovParams()
    return _one_shot(
        flexible_cg_chunked(A, B, precond, params, memory), params.iter_lim
    )


def chebyshev_chunked(
    A, B, sigma_lo: float, sigma_hi: float, params: KrylovParams | None = None
) -> ChunkedSolver:
    """Chunkable Chebyshev semi-iteration (see :func:`chebyshev`).  The
    recurrence depends only on the absolute iteration index, which rides
    the state, so chunk boundaries don't disturb the polynomial."""
    params = params or KrylovParams()
    matvec, _ = _ops(A)
    B, squeeze = _as2d(B)
    dtype = B.dtype
    d = jnp.asarray((sigma_hi + sigma_lo) / 2, dtype)
    c = jnp.asarray((sigma_hi - sigma_lo) / 2, dtype)

    def init_state():
        X0 = jnp.zeros_like(B)
        return dict(
            it=jnp.zeros((), jnp.int32),
            X=X0,
            Xprev=X0,
            alpha=jnp.asarray(0, dtype),
        )

    def body(s):
        i, X, Xprev = s["it"], s["X"], s["Xprev"]
        R = B - matvec(X)
        alpha = jnp.where(
            i == 0,
            1.0 / d,
            jnp.where(
                i == 1,
                d / (d * d - c * c / 2),
                1.0 / (d - s["alpha"] * c * c / 4),
            ),
        ).astype(dtype)
        beta = jnp.where(i == 0, 0.0, alpha * d - 1.0).astype(dtype)
        Xnew = X + alpha * R + beta * (X - Xprev)
        return dict(it=i + 1, X=Xnew, Xprev=X, alpha=alpha)

    def extract_result(s):
        info = {"iterations": s["it"], "flag": jnp.asarray(0)}
        return (s["X"][:, 0] if squeeze else s["X"]), info

    return ChunkedSolver(
        init_state=init_state,
        step_chunk=_chunk_stepper(body, params.iter_lim),
        extract_result=extract_result,
        is_done=lambda s: int(s["it"]) >= params.iter_lim,
        iteration=lambda s: int(s["it"]),
        kind="chebyshev",
    )


def chebyshev(A, B, sigma_lo: float, sigma_hi: float, params: KrylovParams | None = None):
    """Chebyshev semi-iteration for SPD ``A X = B`` given eigenvalue bounds
    ``[sigma_lo, sigma_hi]`` (≙ ``algorithms/Krylov/Chebyshev.hpp`` — the
    reference also takes singular-value bounds).  No inner products — the
    TPU-friendliest Krylov method (no reductions → no collectives at all
    for row-sharded A beyond the matvec itself).
    """
    params = params or KrylovParams()
    return _one_shot(
        chebyshev_chunked(A, B, sigma_lo, sigma_hi, params), params.iter_lim
    )
