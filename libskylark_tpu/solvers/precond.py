"""Preconditioner interface (≙ ``algorithms/Krylov/precond.hpp:14-135``).

The reference's ``inplace_precond_t`` / ``outplace_precond_t`` hierarchy
(id, mat, tri_inverse) becomes three small functional classes; JAX arrays
are immutable so everything is "outplace".  All applies are jit-compatible.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax.scipy.linalg import solve_triangular

__all__ = ["IdPrecond", "MatPrecond", "TriInversePrecond"]


class IdPrecond:
    """Identity (≙ ``id_precond_t``)."""

    def apply(self, x):
        return x

    def apply_adjoint(self, x):
        return x


class MatPrecond:
    """Multiply by a fixed matrix M (≙ ``mat_precond_t``): e.g. LSRN's
    V·Σ⁻¹."""

    def __init__(self, M):
        self.M = jnp.asarray(M)

    def apply(self, x):
        return self.M @ x

    def apply_adjoint(self, x):
        return self.M.T.conj() @ x


class TriInversePrecond:
    """Solve against a triangular factor R (≙ ``tri_inverse_precond_t``):
    Blendenpik's R from QR(SA), applied as R⁻¹ / R⁻ᵀ."""

    def __init__(self, R, lower: bool = False):
        self.R = jnp.asarray(R)
        self.lower = bool(lower)

    def apply(self, x):
        return solve_triangular(self.R, x, lower=self.lower)

    def apply_adjoint(self, x):
        return solve_triangular(self.R.T.conj(), x, lower=not self.lower)
