"""Host-level chunked driver: checkpoint between jitted solver segments.

``ResilientRunner`` is the piece the reference never had (SURVEY §5: MPI
fail-stop, "no checkpoint-restart of solver state"): it drives any
:class:`~libskylark_tpu.resilient.chunked.ChunkedSolver` in rounds of
``checkpoint_every`` device iterations, committing a rotated, CRC-guarded
checkpoint after every round.  A preempted process restarts with
``resume=True`` and loses at most one chunk of work; a corrupt newest
checkpoint falls back to the previous rotation slot; transient IO errors
are retried with exponential backoff; NaN/Inf divergence halts the run
with the best iterate attached instead of returning garbage.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass

import jax
import jax.numpy as jnp

from .. import telemetry
from ..core.params import Params
from ..utils.checkpoint import CheckpointStore
from ..utils.exceptions import CheckpointError, ConvergenceError
from .faults import with_retries

__all__ = ["ResilientParams", "ResilientRunner"]


@dataclass
class ResilientParams(Params):
    """Runtime knobs for a preemption-safe solve.

    ``checkpoint_every`` is K, the device iterations per host round: the
    trade between preemption loss (≤ K iterations) and the per-round host
    sync + save cost.  ``keep_last`` sizes the rotation window that the
    corrupt-checkpoint fallback can reach back through.
    """

    checkpoint_dir: str | None = None
    checkpoint_every: int = 10
    keep_last: int = 3
    resume: bool = False
    io_retries: int = 3
    io_backoff: float = 0.05
    check_divergence: bool = True
    max_chunks: int | None = None  # backstop against non-terminating solvers
    # Elastic resumes pin restores to one repartition epoch: a slot written
    # under any other epoch raises StaleEpochError (111) instead of loading.
    expect_epoch: int | None = None


def _residual_of(state):
    """Best-effort residual read at a chunk boundary: the chunked solver
    states that track one keep it under a conventional key (LSQR's
    ``phibar``, generic ``resid``/``rnorm``).  Returns a float (max over
    targets) or None — never raises, never adds a sync for states that
    carry no residual."""
    if not isinstance(state, dict):
        return None
    for key in ("phibar", "resid", "rnorm", "residual"):
        if key in state:
            try:
                return float(jnp.max(jnp.abs(jnp.asarray(state[key]))))
            except (TypeError, ValueError):
                return None
    return None


def _all_finite(state) -> bool:
    """One host sync per float leaf — called once per chunk, not per
    iteration, so the cost stays off the device hot path."""
    for leaf in jax.tree.leaves(state):
        a = jnp.asarray(leaf)
        if jnp.issubdtype(a.dtype, jnp.floating) or jnp.issubdtype(
            a.dtype, jnp.complexfloating
        ):
            if not bool(jnp.all(jnp.isfinite(a))):
                return False
    return True


class ResilientRunner:
    """Drive ``solver`` to completion with checkpoint/resume + guards.

    ``fault_plan`` (a :class:`~libskylark_tpu.resilient.faults.FaultPlan`)
    injects preemptions / IO errors / divergence for tests; ``sleep``
    feeds the retry backoff and is injectable for the same reason.
    """

    def __init__(
        self,
        solver,
        params: ResilientParams | None = None,
        metadata: dict | None = None,
        fault_plan=None,
        sleep=time.sleep,
    ):
        self.solver = solver
        self.params = params or ResilientParams()
        if self.params.checkpoint_every < 1:
            raise ValueError(
                f"checkpoint_every must be >= 1, got "
                f"{self.params.checkpoint_every}"
            )
        self.metadata = dict(metadata or {})
        self.fault_plan = fault_plan
        self.sleep = sleep
        self.store = (
            CheckpointStore(self.params.checkpoint_dir, self.params.keep_last)
            if self.params.checkpoint_dir
            else None
        )

    def _resume_state(self, state):
        # Two-phase: load flat leaves first so the solver-kind check runs
        # BEFORE any structural validation — "wrong solver" beats
        # "wrong leaf count" as a diagnosis.
        loaded = self.store.load_latest(
            expect_epoch=self.params.expect_epoch
        )
        if loaded is None:
            return state
        leaves, meta, step = loaded
        kind = meta.get("solver_kind")
        want = getattr(self.solver, "kind", None)
        if kind is not None and want is not None and kind != want:
            raise CheckpointError(
                f"checkpoint in {self.params.checkpoint_dir} was written by "
                f"solver kind {kind!r}, refusing to resume {want!r}"
            )
        treedef = jax.tree.structure(state)
        if treedef.num_leaves != len(leaves):
            raise CheckpointError(
                f"checkpoint step {step} has {len(leaves)} leaves, solver "
                f"state has {treedef.num_leaves}"
            )
        self.params.log(1, f"resumed from checkpoint step {step}")
        if telemetry.enabled():
            # crc_ok is True by construction here: load_latest only
            # returns slots whose per-leaf CRC32 validated.
            telemetry.event(
                "checkpoint", "restore",
                {"step": step, "crc_ok": True, "kind": kind},
            )
            telemetry.inc("checkpoint.restores")
        return jax.tree.unflatten(treedef, leaves)

    def _commit(self, state, chunk: int) -> None:
        meta = dict(self.metadata)
        meta["solver_kind"] = getattr(self.solver, "kind", "chunked_solver")
        step = int(self.solver.iteration(state))

        def attempt():
            if self.fault_plan is not None:
                self.fault_plan.before_save(chunk)
            return self.store.save(state, step=step, metadata=meta)

        t0 = time.perf_counter()
        path = with_retries(
            attempt,
            retries=self.params.io_retries,
            backoff=self.params.io_backoff,
            sleep=self.sleep,
        )
        self.params.log(2, f"checkpoint committed at iteration {step}")
        if telemetry.enabled():
            try:
                nbytes = os.path.getsize(path)
            except OSError:
                nbytes = None
            telemetry.event(
                "checkpoint", "save",
                {
                    "step": step,
                    "chunk": chunk,
                    "bytes": nbytes,
                    "crc": "crc32-per-leaf",
                    "seconds": round(time.perf_counter() - t0, 6),
                },
            )
            telemetry.inc("checkpoint.saves")
            if nbytes:
                telemetry.inc("checkpoint.bytes", nbytes)

    def run(self):
        p = self.params
        solver = self.solver
        state = solver.init_state()
        if self.store is not None and p.resume:
            state = self._resume_state(state)

        chunk = 0
        while not solver.is_done(state):
            if p.max_chunks is not None and chunk >= p.max_chunks:
                break
            new_state = solver.step_chunk(state, p.checkpoint_every)
            if self.fault_plan is not None:
                new_state = self.fault_plan.poison(chunk, new_state)
            if p.check_divergence and not _all_finite(new_state):
                # Graceful degradation: halt, hand back the best (= last
                # finite) iterate, never silently return NaN-poisoned X.
                raise ConvergenceError(
                    "solver diverged (non-finite iterate) in chunk "
                    f"{chunk} near iteration {int(solver.iteration(state))}",
                    result=solver.extract_result(state),
                    iteration=int(solver.iteration(state)),
                )
            state = new_state
            if telemetry.enabled():
                attrs = {
                    "chunk": chunk,
                    "iteration": int(solver.iteration(state)),
                }
                resid = _residual_of(state)
                if resid is not None:
                    attrs["resid"] = resid
                telemetry.event(
                    "solver", getattr(solver, "kind", "chunked_solver"), attrs
                )
            if self.store is not None:
                self._commit(state, chunk)
            if self.fault_plan is not None:
                self.fault_plan.after_commit(chunk)
            chunk += 1
        return solver.extract_result(state)
