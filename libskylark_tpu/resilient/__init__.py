"""Preemption-safe solver runtime (no reference counterpart — SURVEY §5
notes the MPI fail-stop model; this subsystem is the TPU-production answer).

- ``chunked``: the ``ChunkedSolver`` contract (init_state / step_chunk /
  extract_result) that krylov / ADMM / randomized-SVD expose
- ``runner``: ``ResilientRunner`` — host rounds of K device iterations with
  rotated CRC-guarded checkpoints, resume, retry, divergence guards
- ``faults``: deterministic fault injection (preemption, corruption,
  transient IO) + ``with_retries`` backoff
"""

from .chunked import ChunkedSolver
from .faults import (
    FaultPlan,
    FleetFaultPlan,
    HostFaultPlan,
    SimulatedPreemption,
    corrupt_checkpoint,
    corrupt_manifest,
    tear_ledger_tail,
    with_retries,
)
from .runner import ResilientParams, ResilientRunner

__all__ = [
    "ChunkedSolver",
    "ResilientParams",
    "ResilientRunner",
    "FaultPlan",
    "FleetFaultPlan",
    "HostFaultPlan",
    "SimulatedPreemption",
    "corrupt_checkpoint",
    "corrupt_manifest",
    "tear_ledger_tail",
    "with_retries",
]
