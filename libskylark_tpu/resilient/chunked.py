"""The chunked-execution contract between solvers and the runner.

A preemption-safe solve is a host-level loop over *chunks* of K device
iterations: each chunk is one jitted ``lax.while_loop``/``fori_loop``
segment whose carry pytree is exported back to the host, so a checkpoint
can be committed between chunks without breaking jit.  Solvers expose this
by returning a :class:`ChunkedSolver` from a ``*_chunked`` factory
(``solvers.krylov.lsqr_chunked``, ``ml.BlockADMMSolver.chunked``,
``linalg.approximate_svd_chunked``); the one-shot APIs are thin wrappers
that run a single chunk of the full iteration budget.

The contract the callables must satisfy for resume to be *bit-for-bit*:

- ``init_state()`` is deterministic given the factory's inputs (counter-
  based RNG, no wall-clock, no fresh PRNG keys), so a resumed process can
  rebuild everything that is NOT in the checkpoint (operators, cached
  factors) identically.
- ``step_chunk(state, k)`` advances AT MOST k device iterations and is a
  pure function of ``state`` — running chunks ``[0,k), [k,2k), ...`` in one
  process gives bit-identical state to running ``[0,k)`` in one process and
  ``[k,2k), ...`` in another that loaded the chunk-1 checkpoint.
- ``state`` is a pytree of arrays (checkpointable by
  ``utils.save_solver_state``); anything non-array lives in the factory
  closure and is rebuilt on resume.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

__all__ = ["ChunkedSolver"]


@dataclass
class ChunkedSolver:
    """Host-driveable solver: state-out/state-in chunks of device work.

    ``iteration``/``is_done`` read the state's on-device counters (one
    scalar host sync each — the price of a checkpointable boundary, paid
    once per chunk rather than once per iteration).
    """

    init_state: Callable[[], Any]
    step_chunk: Callable[[Any, int], Any]
    extract_result: Callable[[Any], Any]
    is_done: Callable[[Any], bool]
    iteration: Callable[[Any], int]
    #: stable tag recorded in checkpoint metadata; a resume refuses to load
    #: a checkpoint written by a different solver kind.
    kind: str = "chunked_solver"
