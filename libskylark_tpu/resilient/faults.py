"""Fault injection + retry machinery for the resilient runner.

The reference's answer to faults is MPI fail-stop: any failure kills the
world.  A production TPU job instead sees three recoverable fault classes,
each of which this module can *inject* deterministically so the recovery
paths are exercised on every PR (tests/test_resilient.py, ``faults``
marker):

- **Preemption**: the process dies at an arbitrary point.  Simulated at a
  chunk boundary (the only place the runner's recovery guarantee applies)
  by raising :class:`SimulatedPreemption` from the plan's hook.
- **Checkpoint corruption**: storage flips bits at rest.
  :func:`corrupt_checkpoint` damages a committed file in place; recovery is
  the store's newest-valid fallback.
- **Transient IO errors**: flaky filesystem/network during a save.
  Injected as ``OSError`` on the first attempts of a save; recovery is
  :func:`with_retries` exponential backoff.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field

__all__ = [
    "SimulatedPreemption",
    "FaultPlan",
    "corrupt_checkpoint",
    "with_retries",
]


class SimulatedPreemption(RuntimeError):
    """Stands in for the process being killed: raised from a fault-plan
    hook, it unwinds the runner exactly as a preemption would leave it —
    committed checkpoints on disk, nothing else."""


def corrupt_checkpoint(path, nbytes: int = 64, offset: int | None = None):
    """Flip ``nbytes`` bytes of a committed checkpoint file in place.

    ``path`` is the ``.npz`` file (as returned by ``CheckpointStore.save``).
    Default offset targets the middle of the file — inside the zip members,
    so either a leaf CRC or the container itself fails validation.
    """
    size = os.path.getsize(path)
    if offset is None:
        offset = size // 2
    nbytes = min(nbytes, size - offset)
    with open(path, "r+b") as f:
        f.seek(offset)
        chunk = f.read(nbytes)
        f.seek(offset)
        f.write(bytes(b ^ 0xFF for b in chunk))


def with_retries(
    fn,
    retries: int = 3,
    backoff: float = 0.05,
    exceptions=(OSError,),
    sleep=time.sleep,
):
    """Call ``fn()`` with exponential backoff: up to ``retries`` additional
    attempts after the first, sleeping ``backoff * 2**attempt`` between.
    ``sleep`` is injectable so tests don't wait out the backoff."""
    attempt = 0
    while True:
        try:
            return fn()
        except exceptions:
            if attempt >= retries:
                raise
            sleep(backoff * (2**attempt))
            attempt += 1


@dataclass
class FaultPlan:
    """Deterministic fault schedule, keyed by chunk index (0-based, counted
    from the start of *this process*, so a resumed run has its own chunk 0).

    - ``preempt_after_chunk``: raise :class:`SimulatedPreemption` right
      after that chunk's checkpoint is committed.
    - ``io_errors_on_save``: ``{chunk: n}`` — the first ``n`` save attempts
      of that chunk's checkpoint raise ``OSError``.
    - ``nan_after_chunk``: make the *solver* diverge by poisoning the state
      the runner hands to the next chunk (the runner consults
      :meth:`poison` — used to exercise the divergence guard end-to-end).

    Numerical-fault kinds (consumed by the ``guard`` layer, ONE-SHOT —
    injected on the live pass only, so a guard replay/resketch of the
    same index sees clean data, modeling a transient fault):

    - ``nan_at``: NaN-poison the payload at that index — for streaming
      passes the index is the BATCH index (the block is NaN-filled before
      the fold); for in-core sketch-and-solve it is the ladder ATTEMPT
      index (the sketched ``S·A`` comes back all-NaN).
    - ``bad_sketch_at``: corrupt the sketch at that index into a rank-
      collapsed one — in-core, every row of ``S·A`` past the first is
      zeroed (certification sees a numerically singular sketch); for
      streaming, the block at that batch index is Inf-scaled (the chunk
      sentinel trips and the accumulation replays).
    """

    preempt_after_chunk: int | None = None
    io_errors_on_save: dict = field(default_factory=dict)
    nan_after_chunk: int | None = None
    nan_at: int | None = None
    bad_sketch_at: int | None = None
    _save_attempts: dict = field(default_factory=dict, repr=False)
    _consumed: set = field(default_factory=set, repr=False)

    def before_save(self, chunk: int) -> None:
        budget = self.io_errors_on_save.get(chunk, 0)
        seen = self._save_attempts.get(chunk, 0)
        self._save_attempts[chunk] = seen + 1
        if seen < budget:
            raise OSError(f"injected transient IO error (chunk {chunk}, attempt {seen})")

    def after_commit(self, chunk: int) -> None:
        if self.preempt_after_chunk is not None and chunk == self.preempt_after_chunk:
            raise SimulatedPreemption(f"injected preemption after chunk {chunk}")

    def poison(self, chunk: int, state):
        if self.nan_after_chunk is None or chunk != self.nan_after_chunk:
            return state
        import jax
        import jax.numpy as jnp

        return jax.tree.map(
            lambda l: jnp.full_like(l, jnp.nan)
            if jnp.issubdtype(jnp.asarray(l).dtype, jnp.floating)
            else l,
            state,
        )

    def _fire(self, kind: str, scheduled, index: int) -> bool:
        """One-shot trigger: True the FIRST time ``index`` matches."""
        if scheduled is None or index != scheduled:
            return False
        key = (kind, index)
        if key in self._consumed:
            return False
        self._consumed.add(key)
        return True

    @staticmethod
    def _map_floats(tree, fn):
        import jax
        import jax.numpy as jnp

        return jax.tree.map(
            lambda l: fn(l)
            if jnp.issubdtype(jnp.asarray(l).dtype, jnp.floating)
            else l,
            tree,
        )

    def corrupt_block(self, index: int, block):
        """Streaming injection point: corrupt the batch at ``index``
        (one-shot — the guard's replay of the same batch gets the clean
        block)."""
        import jax.numpy as jnp

        if self._fire("nan_block", self.nan_at, index):
            return self._map_floats(block, lambda l: jnp.full_like(l, jnp.nan))
        if self._fire("bad_block", self.bad_sketch_at, index):
            return self._map_floats(block, lambda l: jnp.full_like(l, jnp.inf))
        return block

    def corrupt_sketch(self, attempt: int, SA):
        """In-core injection point: corrupt the sketched ``S·A`` of ladder
        attempt ``attempt`` (one-shot per attempt index)."""
        import jax.numpy as jnp

        if self._fire("nan_sketch", self.nan_at, attempt):
            return jnp.full_like(SA, jnp.nan)
        if self._fire("bad_sketch", self.bad_sketch_at, attempt):
            # Rank collapse, not NaN: the finiteness sentinel passes and
            # the CERTIFICATION path has to catch it.
            return SA.at[1:].set(0.0) if SA.shape[0] > 1 else SA * 0.0
        return SA
