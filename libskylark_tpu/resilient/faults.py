"""Fault injection + retry machinery for the resilient runner.

The reference's answer to faults is MPI fail-stop: any failure kills the
world.  A production TPU job instead sees three recoverable fault classes,
each of which this module can *inject* deterministically so the recovery
paths are exercised on every PR (tests/test_resilient.py, ``faults``
marker):

- **Preemption**: the process dies at an arbitrary point.  Simulated at a
  chunk boundary (the only place the runner's recovery guarantee applies)
  by raising :class:`SimulatedPreemption` from the plan's hook.
- **Checkpoint corruption**: storage flips bits at rest.
  :func:`corrupt_checkpoint` damages a committed file in place; recovery is
  the store's newest-valid fallback.
- **Transient IO errors**: flaky filesystem/network during a save.
  Injected as ``OSError`` on the first attempts of a save; recovery is
  :func:`with_retries` exponential backoff.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field

__all__ = [
    "SimulatedPreemption",
    "FaultPlan",
    "HostFaultPlan",
    "FleetFaultPlan",
    "JournalFaultPlan",
    "corrupt_checkpoint",
    "corrupt_manifest",
    "tear_ledger_tail",
    "with_retries",
]


class SimulatedPreemption(RuntimeError):
    """Stands in for the process being killed: raised from a fault-plan
    hook, it unwinds the runner exactly as a preemption would leave it —
    committed checkpoints on disk, nothing else."""


def corrupt_checkpoint(path, nbytes: int = 64, offset: int | None = None):
    """Flip ``nbytes`` bytes of a committed checkpoint file in place.

    ``path`` is the ``.npz`` file (as returned by ``CheckpointStore.save``).
    Default offset targets the middle of the file — inside the zip members,
    so either a leaf CRC or the container itself fails validation.
    """
    size = os.path.getsize(path)
    if offset is None:
        offset = size // 2
    nbytes = min(nbytes, size - offset)
    with open(path, "r+b") as f:
        f.seek(offset)
        chunk = f.read(nbytes)
        f.seek(offset)
        f.write(bytes(b ^ 0xFF for b in chunk))


def corrupt_manifest(host_directory) -> str:
    """Flip the bytes of a host's elastic ``manifest.json`` in place —
    the corrupt-at-rest / hostile-host scenario.  The repartition
    scanner must treat the host as uncertifiable (its coverage is
    dropped and its batches re-fold) instead of trusting its stores.
    Returns the manifest path."""
    path = os.path.join(str(host_directory), "manifest.json")
    with open(path, "r+b") as f:
        data = f.read()
        f.seek(0)
        f.write(bytes(b ^ 0xFF for b in data))
    return path


def tear_ledger_tail(ledger_path) -> None:
    """Append a torn (half-written, unterminated) record to a host's
    ``progress.jsonl`` — what a SIGKILL mid-``write`` leaves behind.
    ``read_progress`` must skip it without losing the intact prefix."""
    with open(str(ledger_path), "a", encoding="utf-8") as f:
        f.write('{"ts": 0.0, "seq": 99999, "kind": "elas')


def with_retries(
    fn,
    retries: int = 3,
    backoff: float = 0.05,
    exceptions=(OSError,),
    sleep=time.sleep,
):
    """Call ``fn()`` with exponential backoff: up to ``retries`` additional
    attempts after the first, sleeping ``backoff * 2**attempt`` between.
    ``sleep`` is injectable so tests don't wait out the backoff."""
    attempt = 0
    while True:
        try:
            return fn()
        except exceptions:
            if attempt >= retries:
                raise
            sleep(backoff * (2**attempt))
            attempt += 1


@dataclass
class FaultPlan:
    """Deterministic fault schedule, keyed by chunk index (0-based, counted
    from the start of *this process*, so a resumed run has its own chunk 0).

    - ``preempt_after_chunk``: raise :class:`SimulatedPreemption` right
      after that chunk's checkpoint is committed.
    - ``io_errors_on_save``: ``{chunk: n}`` — the first ``n`` save attempts
      of that chunk's checkpoint raise ``OSError``.
    - ``nan_after_chunk``: make the *solver* diverge by poisoning the state
      the runner hands to the next chunk (the runner consults
      :meth:`poison` — used to exercise the divergence guard end-to-end).

    Numerical-fault kinds (consumed by the ``guard`` layer, ONE-SHOT —
    injected on the live pass only, so a guard replay/resketch of the
    same index sees clean data, modeling a transient fault):

    - ``nan_at``: NaN-poison the payload at that index — for streaming
      passes the index is the BATCH index (the block is NaN-filled before
      the fold); for in-core sketch-and-solve it is the ladder ATTEMPT
      index (the sketched ``S·A`` comes back all-NaN).
    - ``bad_sketch_at``: corrupt the sketch at that index into a rank-
      collapsed one — in-core, every row of ``S·A`` past the first is
      zeroed (certification sees a numerically singular sketch); for
      streaming, the block at that batch index is Inf-scaled (the chunk
      sentinel trips and the accumulation replays).
    """

    preempt_after_chunk: int | None = None
    io_errors_on_save: dict = field(default_factory=dict)
    nan_after_chunk: int | None = None
    nan_at: int | None = None
    bad_sketch_at: int | None = None
    _save_attempts: dict = field(default_factory=dict, repr=False)
    _consumed: set = field(default_factory=set, repr=False)

    def before_save(self, chunk: int) -> None:
        budget = self.io_errors_on_save.get(chunk, 0)
        seen = self._save_attempts.get(chunk, 0)
        self._save_attempts[chunk] = seen + 1
        if seen < budget:
            raise OSError(f"injected transient IO error (chunk {chunk}, attempt {seen})")

    def after_commit(self, chunk: int) -> None:
        if self.preempt_after_chunk is not None and chunk == self.preempt_after_chunk:
            raise SimulatedPreemption(f"injected preemption after chunk {chunk}")

    def poison(self, chunk: int, state):
        if self.nan_after_chunk is None or chunk != self.nan_after_chunk:
            return state
        import jax
        import jax.numpy as jnp

        return jax.tree.map(
            lambda l: jnp.full_like(l, jnp.nan)
            if jnp.issubdtype(jnp.asarray(l).dtype, jnp.floating)
            else l,
            state,
        )

    def _fire(self, kind: str, scheduled, index: int) -> bool:
        """One-shot trigger: True the FIRST time ``index`` matches."""
        if scheduled is None or index != scheduled:
            return False
        key = (kind, index)
        if key in self._consumed:
            return False
        self._consumed.add(key)
        return True

    @staticmethod
    def _map_floats(tree, fn):
        import jax
        import jax.numpy as jnp

        return jax.tree.map(
            lambda l: fn(l)
            if jnp.issubdtype(jnp.asarray(l).dtype, jnp.floating)
            else l,
            tree,
        )

    def corrupt_block(self, index: int, block):
        """Streaming injection point: corrupt the batch at ``index``
        (one-shot — the guard's replay of the same batch gets the clean
        block)."""
        import jax.numpy as jnp

        if self._fire("nan_block", self.nan_at, index):
            return self._map_floats(block, lambda l: jnp.full_like(l, jnp.nan))
        if self._fire("bad_block", self.bad_sketch_at, index):
            return self._map_floats(block, lambda l: jnp.full_like(l, jnp.inf))
        return block

    def corrupt_sketch(self, attempt: int, SA):
        """In-core injection point: corrupt the sketched ``S·A`` of ladder
        attempt ``attempt`` (one-shot per attempt index)."""
        import jax.numpy as jnp

        if self._fire("nan_sketch", self.nan_at, attempt):
            return jnp.full_like(SA, jnp.nan)
        if self._fire("bad_sketch", self.bad_sketch_at, attempt):
            # Rank collapse, not NaN: the finiteness sentinel passes and
            # the CERTIFICATION path has to catch it.
            return SA.at[1:].set(0.0) if SA.shape[0] > 1 else SA * 0.0
        return SA


@dataclass
class HostFaultPlan(FaultPlan):
    """Host-level chaos schedule for the elastic streaming layer — the
    failure modes of a *machine*, not a computation.  The elastic engine
    binds the plan to this rank's on-disk state (:meth:`bind_host`) and
    consults :meth:`before_batch` before folding each LOCAL batch, so
    every scenario is deterministic and driveable from a child process
    (``tests/_elastic_child.py``) via environment variables:

    - ``die_at_batch``: **rank death** — SIGKILL this process (a real
      kill, not an exception) just before folding local batch k.  With
      ``torn_ledger=True`` a half-written ledger record is appended
      first, modeling a kill mid-``write``.
    - ``die_after_commit``: rank death right after chunk k's checkpoint
      commits — the survivor-visible state is exactly k+1 chunks.
    - ``slow_at_batch`` / ``slow_seconds``: **straggler** — sleep before
      folding local batch k (drives peers into their collective
      deadline → ``CollectiveTimeoutError``).
    - ``corrupt_manifest_at``: **hostile host** — flip every byte of our
      own ``manifest.json`` before folding local batch k; a later
      repartition must drop this host's coverage, not trust it.
    - ``bump_epoch_at``: **stale-epoch writer** — advance the shared
      root's epoch marker before folding local batch k, simulating the
      rest of the world repartitioning while this host lags.  The
      host's own next ledger record then raises ``StaleEpochError``.

    Inherits every :class:`FaultPlan` knob (chunk-boundary preemption,
    transient IO errors, guard-layer numerical faults), so host chaos
    composes with the existing injection points.
    """

    die_at_batch: int | None = None
    die_after_commit: int | None = None
    torn_ledger: bool = False
    slow_at_batch: int | None = None
    slow_seconds: float = 0.0
    corrupt_manifest_at: int | None = None
    bump_epoch_at: int | None = None
    host_dir: str | None = None
    root: str | None = None
    epoch: int = 0
    sleep: object = time.sleep  # injectable for tests

    def bind_host(self, *, hdir: str, root: str, epoch: int = 0) -> None:
        """Called by the elastic engine once the rank's host directory
        is known — the file-targeting faults need paths to aim at."""
        self.host_dir = str(hdir)
        self.root = str(root)
        self.epoch = int(epoch)

    def _suicide(self) -> None:
        import signal

        os.kill(os.getpid(), signal.SIGKILL)

    def before_batch(self, index: int) -> None:
        if self._fire("slow_batch", self.slow_at_batch, index):
            self.sleep(float(self.slow_seconds))
        if (
            self._fire("corrupt_manifest", self.corrupt_manifest_at, index)
            and self.host_dir
        ):
            try:
                corrupt_manifest(self.host_dir)
            except OSError:
                pass
        if self._fire("bump_epoch", self.bump_epoch_at, index) and self.root:
            from ..streaming.elastic import RowPartition
            from ..streaming.repartition import read_epoch, write_epoch

            est = read_epoch(self.root)
            cur = int(est["epoch"]) if est else int(self.epoch)
            write_epoch(
                self.root,
                epoch=cur + 1,
                partition=RowPartition(
                    nrows=1, batch_rows=1, world_size=1
                ),
                kind=(est or {}).get("kind", "chaos"),
            )
        if self.die_at_batch is not None and index == self.die_at_batch:
            if self.torn_ledger and self.host_dir:
                try:
                    tear_ledger_tail(
                        os.path.join(self.host_dir, "progress.jsonl")
                    )
                except OSError:
                    pass
            self._suicide()

    def after_commit(self, chunk: int) -> None:
        if (
            self.die_after_commit is not None
            and chunk == self.die_after_commit
        ):
            if self.torn_ledger and self.host_dir:
                try:
                    tear_ledger_tail(
                        os.path.join(self.host_dir, "progress.jsonl")
                    )
                except OSError:
                    pass
            self._suicide()
        super().after_commit(chunk)


@dataclass
class JournalFaultPlan(FaultPlan):
    """Write-ahead-journal chaos schedule for the serve registry's
    durability layer — the failure modes of a *disk write*, keyed by
    journal append index (0-based, counted from the ``Journal``'s
    construction in this process).  ``serve.journal.Journal`` consults
    the plan at its two crash-window edges, so both halves of the
    write-ahead contract are driveable from a SIGKILL'd child process
    (``tests/_journal_child.py``):

    - ``torn_journal_at``: **kill mid-append** — write only the first
      half of append k's CRC frame (fsync'd, so the torn bytes are
      really on disk) and SIGKILL.  Recovery must truncate the torn
      tail, count it, and land at append k-1's epoch.
    - ``die_after_journal_before_publish``: **kill inside the commit
      window** — append k reaches the disk durably, then SIGKILL
      *before* the mutation publishes to the in-memory registry.
      Recovery must REPLAY that journaled record: the recovered
      registry lands at append k's epoch — ahead of what the dying
      process ever served, never behind it.

    Both are real ``SIGKILL``s (no atexit, no flush — the crash model
    the journal's fsync discipline is built for), one-shot via the
    inherited ``_fire`` ledger.  Inherits every :class:`FaultPlan`
    knob, so journal chaos composes with the existing injection
    points.
    """

    torn_journal_at: int | None = None
    die_after_journal_before_publish: int | None = None

    def _suicide(self) -> None:
        import signal

        os.kill(os.getpid(), signal.SIGKILL)

    def kill(self) -> None:
        """Die NOW — a real SIGKILL, nothing runs after this."""
        self._suicide()

    def torn_fires(self, index: int) -> bool:
        """True exactly once, at append ``index``: the journal writes a
        half frame and then calls :meth:`kill`."""
        return self._fire("torn_journal", self.torn_journal_at, index)

    def die_after_fires(self, index: int) -> bool:
        """True exactly once, at append ``index``: the journal has
        fsync'd the full frame and calls :meth:`kill` before the
        in-memory publish."""
        return self._fire(
            "die_after_journal", self.die_after_journal_before_publish,
            index,
        )


@dataclass
class FleetFaultPlan(HostFaultPlan):
    """Replica-level chaos schedule for the serve fleet — the failure
    modes of a *membership*, not a machine.  Keyed by autoscaler control
    tick (1-based, the :meth:`before_tick` argument), every fault fires
    exactly once at its scheduled tick, so a chaos drill replays the same
    membership history on every run:

    - ``die_under_load_at``: **replica death under traffic** — the bound
      ``kill`` callback abruptly stops a busy replica's workers (no
      drain, no leave); the router's next heartbeat sweep must eject it
      and surviving replicas must absorb its keys.
    - ``slow_heartbeat_at`` / ``slow_heartbeat_s``: **stale-but-alive**
      — the bound ``slow_report`` callback makes one replica's
      ``load_report`` lag by ``slow_heartbeat_s``; the router must stamp
      ``report_age_s`` and keep placing on it, NOT eject it (ejection is
      for real silence past the heartbeat timeout).
    - ``join_storm_at`` / ``join_storm_size``: **join storm** — the
      bound ``spawn`` callback is invoked ``join_storm_size`` times in
      one tick; every joiner must clear the registry-signature fence and
      prime before taking traffic.
    - ``flap_at`` / ``flap_times``: **flapping replica** — alternating
      kill/spawn ``flap_times`` times starting at ``flap_at`` (one
      transition per tick); membership must converge without shedding
      admitted work.

    The plan is bound to a concrete fleet with :meth:`bind_fleet` —
    the callbacks own the HOW (which replica, how it dies), the plan
    owns the WHEN.  Inherits every :class:`HostFaultPlan` /
    :class:`FaultPlan` knob, so fleet chaos composes with host and
    numerical injection in one schedule.
    """

    die_under_load_at: int | None = None
    slow_heartbeat_at: int | None = None
    slow_heartbeat_s: float = 0.0
    join_storm_at: int | None = None
    join_storm_size: int = 2
    flap_at: int | None = None
    flap_times: int = 2
    _kill: object = field(default=None, repr=False)
    _spawn: object = field(default=None, repr=False)
    _slow_report: object = field(default=None, repr=False)
    _flaps_left: int = field(default=0, repr=False)
    _flap_next: str = field(default="kill", repr=False)

    def bind_fleet(self, *, kill=None, spawn=None, slow_report=None) -> None:
        """Attach the drill's fleet actuators: ``kill()`` stops a busy
        replica abruptly, ``spawn()`` builds+joins a fresh one,
        ``slow_report(seconds)`` delays one replica's next report."""
        self._kill = kill
        self._spawn = spawn
        self._slow_report = slow_report

    def before_tick(self, tick: int) -> None:
        """Autoscaler hook: fire every fault scheduled for this control
        tick (each one-shot via the inherited ``_fire`` ledger)."""
        if self._fire("die_under_load", self.die_under_load_at, tick):
            if self._kill is not None:
                self._kill()
        if self._fire("slow_heartbeat", self.slow_heartbeat_at, tick):
            if self._slow_report is not None:
                self._slow_report(float(self.slow_heartbeat_s))
        if self._fire("join_storm", self.join_storm_at, tick):
            if self._spawn is not None:
                for _ in range(int(self.join_storm_size)):
                    self._spawn()
        if self.flap_at is not None and tick == self.flap_at:
            self._flaps_left = int(self.flap_times)
        if self._flaps_left > 0:
            self._flaps_left -= 1
            actor = self._kill if self._flap_next == "kill" else self._spawn
            self._flap_next = "spawn" if self._flap_next == "kill" else "kill"
            if actor is not None:
                actor()
