"""Simple undirected graph container built from an arc list.

≙ ``simple_unweighted_graph_t`` (``ml/skylark_graph_se.cpp``) and the
arc-list reader (``utility/io``): text lines ``u v`` (comments ``#``/``%``),
symmetrized, self-loops dropped, duplicate edges collapsed.  Vertex names
may be arbitrary hashables; ``index`` maps name → contiguous id.

The constructor is vectorized: interning runs through one C-speed
``dict.fromkeys`` pass (first-seen order, scanning ``u`` then ``v`` per
edge — identical to the original per-edge loop), and symmetrization /
dedup / CSR assembly are numpy ``unique``/``lexsort``/``bincount`` calls,
so building a multi-hundred-thousand-edge graph costs milliseconds of
interpreter time instead of seconds.
"""

from __future__ import annotations

import numpy as np

__all__ = ["SimpleGraph", "read_arc_list"]


class SimpleGraph:
    def __init__(self, edges):
        """edges: iterable of (u, v) pairs (strings or ints)."""
        # Self-loops drop before interning: a vertex appearing only in
        # self-loops gets no id (pinned by tests).
        pairs = [(u, v) for u, v in edges if u != v]
        flat = [w for pair in pairs for w in pair]
        # dict.fromkeys dedups in insertion order in one C call.
        names = {w: i for i, w in enumerate(dict.fromkeys(flat))}
        self.vertices = list(names)
        self.index = names
        n = len(names)
        self.n = n
        if not pairs:
            self.indptr = np.zeros(n + 1, dtype=np.int64)
            self.indices = np.empty(0, dtype=np.int64)
            return
        ids = np.fromiter(
            (names[w] for w in flat), dtype=np.int64, count=len(flat)
        ).reshape(-1, 2)
        lo = ids.min(axis=1)
        hi = ids.max(axis=1)
        und = np.unique(np.stack([lo, hi], axis=1), axis=0)
        rows = np.concatenate([und[:, 0], und[:, 1]])
        cols = np.concatenate([und[:, 1], und[:, 0]])
        order = np.lexsort((cols, rows))
        rows, cols = rows[order], cols[order]
        counts = np.bincount(rows, minlength=n)
        self.indptr = np.concatenate(
            [np.zeros(1, dtype=np.int64), np.cumsum(counts, dtype=np.int64)]
        )
        self.indices = cols

    # -- accessors (≙ the GraphType concept used by the algorithms) ---------

    def degree(self, i: int) -> int:
        return int(self.indptr[i + 1] - self.indptr[i])

    @property
    def degrees(self) -> np.ndarray:
        return np.diff(self.indptr)

    def neighbors(self, i: int) -> np.ndarray:
        return self.indices[self.indptr[i] : self.indptr[i + 1]]

    @property
    def volume(self) -> int:
        """Total volume Σ deg = 2·|E| (≙ ``G.num_edges()`` as used in the
        conductance denominator)."""
        return int(self.indices.size)

    def adjacency(self, dtype=np.float64):
        """Dense (n, n) adjacency (for moderate graphs / ASE input)."""
        A = np.zeros((self.n, self.n), dtype=dtype)
        A[np.repeat(np.arange(self.n), self.degrees), self.indices] = 1.0
        return A

    def adjacency_bcoo(self, dtype=None):
        """Sparse BCOO adjacency."""
        import jax.numpy as jnp
        from jax.experimental import sparse as jsparse

        dtype = dtype or jnp.asarray(0.0).dtype
        rows = np.repeat(np.arange(self.n), self.degrees)
        idx = np.stack([rows, self.indices], axis=1).astype(np.int32)
        data = np.ones(self.indices.size)
        return jsparse.BCOO(
            (jnp.asarray(data, dtype), jnp.asarray(idx)),
            shape=(self.n, self.n),
        )


def read_arc_list(path) -> SimpleGraph:
    """Build a :class:`SimpleGraph` from an arc list.

    Accepts anything ``io.open_source`` does: a local path, ``file://``
    or fsspec URL, raw bytes, or a ``ByteSource``.  For graphs too large
    to hold, use ``io.stream_arc_list`` and the streamed sketch path
    (``graph.stream``) instead.
    """
    from ..io.arclist import _chunk_lines, _parse_edge_block
    from ..io.source import open_source

    src = open_source(path)
    edges: list[tuple[str, str]] = []
    for block in _chunk_lines(src, 8 << 20):
        us, vs = _parse_edge_block(block)
        edges.extend(zip(us, vs))
    return SimpleGraph(edges)
