"""Simple undirected graph container built from an arc list.

≙ ``simple_unweighted_graph_t`` (``ml/skylark_graph_se.cpp``) and the
arc-list reader (``utility/io``): text lines ``u v`` (comments ``#``/``%``),
symmetrized, self-loops dropped, duplicate edges collapsed.  Vertex names
may be arbitrary hashables; ``index`` maps name → contiguous id.

The constructor is vectorized: interning runs through one C-speed
``dict.fromkeys`` pass (first-seen order, scanning ``u`` then ``v`` per
edge — identical to the original per-edge loop), and symmetrization /
dedup / CSR assembly are numpy ``unique``/``lexsort``/``bincount`` calls,
so building a multi-hundred-thousand-edge graph costs milliseconds of
interpreter time instead of seconds.
"""

from __future__ import annotations

import numpy as np

__all__ = ["SimpleGraph", "read_arc_list"]


class SimpleGraph:
    def __init__(self, edges):
        """edges: iterable of (u, v) pairs (strings or ints)."""
        # Self-loops drop before interning: a vertex appearing only in
        # self-loops gets no id (pinned by tests).
        pairs = [(u, v) for u, v in edges if u != v]
        flat = [w for pair in pairs for w in pair]
        # dict.fromkeys dedups in insertion order in one C call.
        names = {w: i for i, w in enumerate(dict.fromkeys(flat))}
        self.vertices = list(names)
        self.index = names
        n = len(names)
        self.n = n
        if not pairs:
            self.indptr = np.zeros(n + 1, dtype=np.int64)
            self.indices = np.empty(0, dtype=np.int64)
            return
        ids = np.fromiter(
            (names[w] for w in flat), dtype=np.int64, count=len(flat)
        ).reshape(-1, 2)
        lo = ids.min(axis=1)
        hi = ids.max(axis=1)
        und = np.unique(np.stack([lo, hi], axis=1), axis=0)
        rows = np.concatenate([und[:, 0], und[:, 1]])
        cols = np.concatenate([und[:, 1], und[:, 0]])
        order = np.lexsort((cols, rows))
        rows, cols = rows[order], cols[order]
        counts = np.bincount(rows, minlength=n)
        self.indptr = np.concatenate(
            [np.zeros(1, dtype=np.int64), np.cumsum(counts, dtype=np.int64)]
        )
        self.indices = cols

    def with_edges(self, pairs):
        """New graph absorbing extra edges over the EXISTING vertex set.

        ``pairs``: iterable of (u, v) vertex ids or names.  Returns
        ``(G2, new_pairs)``: the merged graph — same vertex interning,
        same ids, CSR rebuilt — and the (r, 2) int64 array of undirected
        (lo, hi) pairs that were genuinely NEW (self-loops and edges
        already present are dropped, duplicates collapsed).  The live
        serve registry folds exactly ``new_pairs`` into its retained
        adjacency sketch, so the delta fold counts each edge once —
        the same dedup the constructor applies from scratch.

        Vertices must already exist: sketch domains are sized to the
        registered vertex set, so growth is rejected (register with
        isolated capacity vertices if the universe must grow).
        """
        ids = []
        for u, v in pairs:
            iu = u if isinstance(u, (int, np.integer)) else self.index.get(u)
            iv = v if isinstance(v, (int, np.integer)) else self.index.get(v)
            if iu is None or iv is None or not (
                0 <= int(iu) < self.n and 0 <= int(iv) < self.n
            ):
                raise KeyError(
                    f"with_edges: unknown vertex in ({u!r}, {v!r}); live "
                    "folds are over the registered vertex set"
                )
            if int(iu) != int(iv):
                ids.append((int(iu), int(iv)))
        g2 = object.__new__(SimpleGraph)
        g2.vertices = self.vertices
        g2.index = self.index
        g2.n = self.n
        if not ids:
            g2.indptr = self.indptr
            g2.indices = self.indices
            return g2, np.empty((0, 2), np.int64)
        arr = np.asarray(ids, np.int64)
        lo = arr.min(axis=1)
        hi = arr.max(axis=1)
        cand = np.unique(np.stack([lo, hi], axis=1), axis=0)
        # Drop pairs already present (CSR membership on the lo row).
        old_rows = np.repeat(np.arange(self.n, dtype=np.int64),
                             np.diff(self.indptr))
        have = set(zip(old_rows.tolist(), self.indices.tolist()))
        fresh = np.asarray(
            [p for p in cand.tolist() if (p[0], p[1]) not in have], np.int64
        ).reshape(-1, 2)
        if not fresh.size:
            g2.indptr = self.indptr
            g2.indices = self.indices
            return g2, fresh
        rows = np.concatenate([old_rows, fresh[:, 0], fresh[:, 1]])
        cols = np.concatenate([self.indices, fresh[:, 1], fresh[:, 0]])
        order = np.lexsort((cols, rows))
        rows, cols = rows[order], cols[order]
        counts = np.bincount(rows, minlength=self.n)
        g2.indptr = np.concatenate(
            [np.zeros(1, dtype=np.int64), np.cumsum(counts, dtype=np.int64)]
        )
        g2.indices = cols
        return g2, fresh

    # -- accessors (≙ the GraphType concept used by the algorithms) ---------

    def degree(self, i: int) -> int:
        return int(self.indptr[i + 1] - self.indptr[i])

    @property
    def degrees(self) -> np.ndarray:
        return np.diff(self.indptr)

    def neighbors(self, i: int) -> np.ndarray:
        return self.indices[self.indptr[i] : self.indptr[i + 1]]

    @property
    def volume(self) -> int:
        """Total volume Σ deg = 2·|E| (≙ ``G.num_edges()`` as used in the
        conductance denominator)."""
        return int(self.indices.size)

    def adjacency(self, dtype=np.float64):
        """Dense (n, n) adjacency (for moderate graphs / ASE input)."""
        A = np.zeros((self.n, self.n), dtype=dtype)
        A[np.repeat(np.arange(self.n), self.degrees), self.indices] = 1.0
        return A

    def adjacency_bcoo(self, dtype=None):
        """Sparse BCOO adjacency."""
        import jax.numpy as jnp
        from jax.experimental import sparse as jsparse

        dtype = dtype or jnp.asarray(0.0).dtype
        rows = np.repeat(np.arange(self.n), self.degrees)
        idx = np.stack([rows, self.indices], axis=1).astype(np.int32)
        data = np.ones(self.indices.size)
        return jsparse.BCOO(
            (jnp.asarray(data, dtype), jnp.asarray(idx)),
            shape=(self.n, self.n),
        )


def read_arc_list(path) -> SimpleGraph:
    """Build a :class:`SimpleGraph` from an arc list.

    Accepts anything ``io.open_source`` does: a local path, ``file://``
    or fsspec URL, raw bytes, or a ``ByteSource``.  For graphs too large
    to hold, use ``io.stream_arc_list`` and the streamed sketch path
    (``graph.stream``) instead.
    """
    from ..io.arclist import _chunk_lines, _parse_edge_block
    from ..io.source import open_source

    src = open_source(path)
    edges: list[tuple[str, str]] = []
    for block in _chunk_lines(src, 8 << 20):
        us, vs = _parse_edge_block(block)
        edges.extend(zip(us, vs))
    return SimpleGraph(edges)
