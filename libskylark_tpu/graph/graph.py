"""Simple undirected graph container built from an arc list.

≙ ``simple_unweighted_graph_t`` (``ml/skylark_graph_se.cpp``) and the
arc-list reader (``utility/io``): text lines ``u v`` (comments ``#``/``%``),
symmetrized, self-loops dropped, duplicate edges collapsed.  Vertex names
may be arbitrary hashables; ``index`` maps name → contiguous id.
"""

from __future__ import annotations

import numpy as np

__all__ = ["SimpleGraph", "read_arc_list"]


class SimpleGraph:
    def __init__(self, edges):
        """edges: iterable of (u, v) pairs (strings or ints)."""
        names = {}
        pairs = set()
        for u, v in edges:
            if u == v:
                continue
            for w in (u, v):
                if w not in names:
                    names[w] = len(names)
            a, b = names[u], names[v]
            pairs.add((min(a, b), max(a, b)))
        self.vertices = list(names)
        self.index = names
        n = len(names)
        rows = np.empty(2 * len(pairs), dtype=np.int64)
        cols = np.empty(2 * len(pairs), dtype=np.int64)
        for i, (a, b) in enumerate(pairs):
            rows[2 * i], cols[2 * i] = a, b
            rows[2 * i + 1], cols[2 * i + 1] = b, a
        order = np.lexsort((cols, rows))
        rows, cols = rows[order], cols[order]
        self.indptr = np.zeros(n + 1, dtype=np.int64)
        np.add.at(self.indptr, rows + 1, 1)
        self.indptr = np.cumsum(self.indptr)
        self.indices = cols
        self.n = n

    # -- accessors (≙ the GraphType concept used by the algorithms) ---------

    def degree(self, i: int) -> int:
        return int(self.indptr[i + 1] - self.indptr[i])

    @property
    def degrees(self) -> np.ndarray:
        return np.diff(self.indptr)

    def neighbors(self, i: int) -> np.ndarray:
        return self.indices[self.indptr[i] : self.indptr[i + 1]]

    @property
    def volume(self) -> int:
        """Total volume Σ deg = 2·|E| (≙ ``G.num_edges()`` as used in the
        conductance denominator)."""
        return int(self.indices.size)

    def adjacency(self, dtype=np.float64):
        """Dense (n, n) adjacency (for moderate graphs / ASE input)."""
        A = np.zeros((self.n, self.n), dtype=dtype)
        A[np.repeat(np.arange(self.n), self.degrees), self.indices] = 1.0
        return A

    def adjacency_bcoo(self, dtype=None):
        """Sparse BCOO adjacency."""
        import jax.numpy as jnp
        from jax.experimental import sparse as jsparse

        dtype = dtype or jnp.asarray(0.0).dtype
        rows = np.repeat(np.arange(self.n), self.degrees)
        idx = np.stack([rows, self.indices], axis=1).astype(np.int32)
        data = np.ones(self.indices.size)
        return jsparse.BCOO(
            (jnp.asarray(data, dtype), jnp.asarray(idx)),
            shape=(self.n, self.n),
        )


def read_arc_list(path) -> SimpleGraph:
    edges = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line[0] in "#%":
                continue
            parts = line.split()
            if len(parts) < 2:
                continue
            edges.append((parts[0], parts[1]))
    return SimpleGraph(edges)
