"""Seed-set local community detection via time-dependent personalized
PageRank diffusion.

≙ ``TimeDependentPPR`` + ``FindLocalCluster``
(``ml/graph/local_computations.hpp:50-374``; Avron-Horesh ICML'15): solve
the diffusion ODE

    dy/dt = −(I − α·A·D⁻¹)·y,   y(0) = s,   t ∈ [0, γ]

by Chebyshev spectral collocation in time (N points from the Bessel-bound
of the reference, ``local_computations.hpp:64-77``), then sweep-cut the
degree-normalized y at NX time samples by conductance.

Locality re-design (round 2): the reference's push queue exists so that
work scales with the *cluster's* volume, not the graph
(``local_computations.hpp:140-250``: per-vertex residuals, queue
membership gated on the bound ``B = C·deg(v)``).  The same locality is
reproduced here in vectorized form: the collocation fixed point
``Y ← G₀⁻¹(α·W·Y + BC)`` runs restricted to an *active support* (the
vertices the reference's rymap would hold), and after each converged
restricted solve the frontier residual ``α·(W·Y)|_inactive`` is compared
against the reference's per-vertex truncation bound ``C·deg`` — violating
neighbors join the support and the solve repeats.  Total work is
O(vol(support)·N·sweeps): a planted cluster in a 10⁶-edge graph touches
only the cluster's neighborhood.  The sweep-cut is likewise vectorized
(cumulative-volume / internal-edge-count formulation) so it costs
O(vol(support)), not O(vol·deg) of Python set probes.
"""

from __future__ import annotations

import numpy as np

from ..linalg.spectral import chebyshev_diff_matrix
from ..utils.deps import require

__all__ = ["time_dependent_ppr", "find_local_cluster"]


def _min_chebyshev_points(gamma: float, epsilon: float) -> int:
    """Bessel-function bound for the number of time collocation points
    (≙ local_computations.hpp:64-77)."""
    iv = require("scipy.special").iv

    minN = 10
    C = 20.0 * np.sqrt(minN) * np.exp(-gamma / 2)
    while (
        C * iv(minN, gamma) * 0.8**minN
        > epsilon / (gamma * (1 + (2 / np.pi) * np.log(minN - 1)))
    ):
        minN += 1
    return minN


def _truncation_constant(alpha, gamma, epsilon, N) -> float:
    """Per-vertex residual truncation scale C: a vertex participates when
    its residual exceeds ``C·deg`` (≙ local_computations.hpp:126-131)."""
    LC = 1 + (2 / np.pi) * np.log(N - 1)
    if alpha < 1:
        return (1 - alpha) * epsilon / ((1 - np.exp((alpha - 1) * gamma)) * LC)
    return epsilon / (gamma * LC)


def _active_edges(G, act):
    """(src_local, nbr_global) concatenated adjacency of the active set —
    O(vol(act)), no Python per-vertex loop."""
    counts = (G.indptr[act + 1] - G.indptr[act]).astype(np.int64)
    total = int(counts.sum())
    if total == 0:
        return np.empty(0, np.int64), np.empty(0, np.int64)
    # Concatenated [indptr[v], indptr[v]+counts[v]) ranges via one iota.
    cum = np.concatenate([[0], np.cumsum(counts)[:-1]])
    flat = np.arange(total) + np.repeat(G.indptr[act] - cum, counts)
    return np.repeat(np.arange(len(act)), counts), G.indices[flat]


def time_dependent_ppr(
    G,
    seeds: dict,
    alpha: float = 0.85,
    gamma: float = 5.0,
    epsilon: float = 0.001,
    NX: int = 4,
    max_fp_iters: int = 1000,
):
    """Returns ``(times, Y)``: Y (NX, n) diffusion values at NX times.

    ``seeds``: vertex-id → initial mass (≙ the s map).  Y is dense over
    the graph but only the active support's columns are nonzero; the
    computation never touches vertices outside support ∪ frontier.
    """
    sp = require("scipy.sparse")

    n = G.n
    minN = _min_chebyshev_points(gamma, epsilon)
    N = minN if minN % NX == 0 else (minN // NX + 1) * NX
    NR = N // NX

    D, x = chebyshev_diff_matrix(N, 0.0, gamma)  # x descending γ → 0
    i0 = N - 1  # collocation row for t = 0 (initial condition)

    # G0·Y = α·(W·yᵗ rows) + BC, with W = A·D⁻¹ applied via neighbor sums.
    G0 = D + np.eye(N)
    G0[i0, :] = 0.0
    G0[i0, i0] = 1.0
    G0inv = np.linalg.inv(G0)

    C_bound = _truncation_constant(alpha, gamma, epsilon, N)
    deg_full = G.degrees.astype(np.float64)

    seed_ids = np.asarray(sorted(int(v) for v in seeds), np.int64)
    seed_mass = np.asarray([float(seeds[int(v)]) for v in seed_ids])

    # Inner solve tighter than the discretization error by 1e-3, floored so
    # loose --epsilon still converges the fixed point reasonably.
    tol = max(epsilon * 1e-3, 1e-12)

    act = seed_ids.copy()  # active support, sorted
    Y = np.zeros((N, len(act)))
    pos = np.full(n, -1, np.int64)

    max_rounds = 64  # support spreads ≤ 1 hop per round
    for _round in range(max_rounds):
        k = len(act)
        pos[:] = -1
        pos[act] = np.arange(k)
        deg_act = np.maximum(deg_full[act], 1.0)
        src, nbr = _active_edges(G, act)
        npos = pos[nbr]
        inside = npos >= 0

        # Restricted W|SS (k×k): (W y)_v = Σ_{u∈N(v)∩S} y_u/deg_u.
        W_SS = sp.csr_matrix(
            (
                1.0 / deg_act[npos[inside]],
                (src[inside], npos[inside]),
            ),
            shape=(k, k),
        )
        s_vec = np.zeros(k)
        s_vec[pos[seed_ids]] = seed_mass

        # Converge the fixed point on the current support.
        delta = np.inf
        for _ in range(max_fp_iters):
            RHS = alpha * (W_SS @ Y.T).T
            RHS[i0] = s_vec
            Y_new = G0inv @ RHS
            delta = np.max(np.abs(Y_new - Y)) if Y.size else 0.0
            Y = Y_new
            if delta < tol:
                break
        else:
            import warnings

            warnings.warn(
                f"time_dependent_ppr fixed point not converged "
                f"(delta={delta:.2e} > tol={tol:.2e} after "
                f"{max_fp_iters} iters)"
            )

        # Frontier residual: inactive u gets α Σ_{v∈N(u)∩S} y_v/deg_v;
        # activate where any component exceeds C·deg(u)
        # (≙ the |r_j| > B = C·odeg queue test, local_computations.hpp:
        # 180-196, 238-249).
        out_nbr = nbr[~inside]
        if out_nbr.size == 0:
            break
        uniq, inv = np.unique(out_nbr, return_inverse=True)
        Rf = np.zeros((N, len(uniq)))
        contrib = (Y / deg_act[None, :])[:, src[~inside]]
        np.add.at(Rf.T, inv, contrib.T)
        bound = C_bound * np.maximum(deg_full[uniq], 1.0)
        viol = uniq[np.max(np.abs(alpha * Rf), axis=0) > bound]
        if viol.size == 0:
            break
        act_new = np.union1d(act, viol)
        # Re-seat Y columns into the grown support.
        Y_grown = np.zeros((N, len(act_new)))
        Y_grown[:, np.searchsorted(act_new, act)] = Y
        act, Y = act_new, Y_grown
    else:
        import warnings

        warnings.warn(
            f"time_dependent_ppr support still growing after {max_rounds} "
            f"rounds ({viol.size} frontier vertices above the truncation "
            "bound); returning the truncated diffusion — increase epsilon "
            "or expect reduced accuracy"
        )

    sample_idx = np.arange(NX) * NR
    Y_full = np.zeros((NX, n))
    Y_full[:, act] = Y[sample_idx]
    return x[sample_idx], Y_full


def _sweep_cut(G, vals, Gvol):
    """Best-conductance prefix of the support of ``vals`` (degree-normalized
    diffusion values), vectorized (≙ the per-node loop of
    ``local_computations.hpp:316-352``).

    Returns ``(order, best_prefix, best_cond)``; ``order`` is the support
    sorted by descending value (ties by vertex id, matching the
    reference's pair sort)."""
    deg = G.degrees
    support = np.flatnonzero(vals > 1e-12)
    if support.size == 0:
        return support, 0, 1.0
    order = support[np.argsort(-vals[support], kind="stable")]
    k = len(order)
    prefix_pos = np.full(G.n, -1, np.int64)
    prefix_pos[order] = np.arange(k)

    volS = np.cumsum(deg[order].astype(np.int64))
    # An edge (u, v) with both endpoints in the support becomes internal
    # at prefix index max(pos_u, pos_v); each undirected edge appears
    # twice in the arc list, so the bincount counts 2·internal — exactly
    # the -2 the serial loop applies per internal edge.
    src, nbr = _active_edges(G, order)
    npos = prefix_pos[nbr]
    both = npos >= 0
    t_at = np.maximum(src[both], npos[both])
    intern2 = np.cumsum(np.bincount(t_at, minlength=k))
    cutS = volS - intern2
    denom = np.minimum(volS, Gvol - volS)
    cond = np.where(denom > 0, cutS / np.maximum(denom, 1), np.inf)
    best = int(np.argmin(cond))
    best_cond = float(cond[best])
    if best_cond >= 1.0:  # reference keeps bestprefix=0, bestcond=1.0
        return order, 0, 1.0
    return order, best, best_cond


def find_local_cluster(
    G,
    seeds,
    alpha: float = 0.85,
    gamma: float = 5.0,
    epsilon: float = 0.001,
    NX: int = 4,
    recursive: bool = False,
):
    """Returns ``(cluster, conductance)``; cluster is a set of vertex ids.

    ≙ ``FindLocalCluster`` (local_computations.hpp:288-374): run the
    diffusion from the (uniform-mass) seed set, sweep the
    degree-normalized values at each time sample for the best-conductance
    prefix; optionally recurse with the found cluster as the new seed.
    """
    cluster = set(int(v) for v in seeds)
    current_cond = None
    deg = G.degrees
    Gvol = G.volume

    while True:
        s = {v: 1.0 / len(cluster) for v in cluster}
        _, Y = time_dependent_ppr(G, s, alpha, gamma, epsilon, NX)
        improve = False
        for t in range(Y.shape[0]):
            vals = Y[t] / np.maximum(deg, 1)
            order, best_prefix, best_cond = _sweep_cut(G, vals, Gvol)
            if order.size == 0:
                continue
            if current_cond is None or best_cond < 0.999999 * current_cond:
                improve = True
                cluster = set(int(v) for v in order[: best_prefix + 1])
                current_cond = best_cond
        if not (recursive and improve):
            break

    return cluster, current_cond
