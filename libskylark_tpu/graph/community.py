"""Seed-set local community detection via time-dependent personalized
PageRank diffusion.

≙ ``TimeDependentPPR`` + ``FindLocalCluster``
(``ml/graph/local_computations.hpp:50-374``; Avron-Horesh ICML'15): solve
the diffusion ODE

    dy/dt = −(I − α·A·D⁻¹)·y,   y(0) = s,   t ∈ [0, γ]

by Chebyshev spectral collocation in time (N points from the Bessel-bound
of the reference, ``local_computations.hpp:64-77``), then sweep-cut the
degree-normalized y at NX time samples by conductance.

Schedule re-design: the reference integrates with a push-style queue that
keeps the solution support local (host pointer loops — it abandons
Elemental for this).  Here the collocation system is solved globally as a
damped fixed-point iteration ``Y ← G₀⁻¹(α·Y·Wᵀ + BC)`` (contraction rate
~α) over the whole graph — simpler, vectorized, and exact w.r.t. the same
discretization; appropriate for host-sized graphs, which is the regime
the reference's CLI serves (interactive seeds over one arc-list file).
"""

from __future__ import annotations

import numpy as np

from ..linalg.spectral import chebyshev_diff_matrix

__all__ = ["time_dependent_ppr", "find_local_cluster"]


def _min_chebyshev_points(gamma: float, epsilon: float) -> int:
    """Bessel-function bound for the number of time collocation points
    (≙ local_computations.hpp:64-77)."""
    from scipy.special import iv

    minN = 10
    C = 20.0 * np.sqrt(minN) * np.exp(-gamma / 2)
    while (
        C * iv(minN, gamma) * 0.8**minN
        > epsilon / (gamma * (1 + (2 / np.pi) * np.log(minN - 1)))
    ):
        minN += 1
    return minN


def time_dependent_ppr(
    G,
    seeds: dict,
    alpha: float = 0.85,
    gamma: float = 5.0,
    epsilon: float = 0.001,
    NX: int = 4,
    max_fp_iters: int = 1000,
):
    """Returns ``(times, Y)``: Y (NX, n) diffusion values at NX times.

    ``seeds``: vertex-id → initial mass (≙ the s map).
    """
    n = G.n
    minN = _min_chebyshev_points(gamma, epsilon)
    N = minN if minN % NX == 0 else (minN // NX + 1) * NX
    NR = N // NX

    D, x = chebyshev_diff_matrix(N, 0.0, gamma)  # x descending γ → 0
    i0 = N - 1  # collocation row for t = 0 (initial condition)

    # G0·Y = α·(W·yᵗ rows) + BC, with W = A·D⁻¹ applied via neighbor sums.
    G0 = D + np.eye(N)
    G0[i0, :] = 0.0
    G0[i0, i0] = 1.0
    G0inv = np.linalg.inv(G0)

    s = np.zeros(n)
    for v, val in seeds.items():
        s[v] = val

    deg = G.degrees.astype(np.float64)
    deg[deg == 0] = 1.0

    # Fixed point: Y ← G0inv·(α·(Y/deg)·Aᵀ masked at BC row + e_{i0}·s).
    Y = np.zeros((N, n))
    Y[i0] = s
    indptr, indices = G.indptr, G.indices
    rows_rep = np.repeat(np.arange(n), np.diff(indptr))
    # Inner solve tighter than the discretization error by 1e-3, floored so
    # loose --epsilon still converges the fixed point reasonably.
    tol = max(epsilon * 1e-3, 1e-12)
    delta = np.inf
    for _ in range(max_fp_iters):
        Z = Y / deg[None, :]
        # (W·y) per time-row: sum over neighbors — scatter-add by target.
        WY = np.zeros_like(Y)
        np.add.at(WY.T, rows_rep, Z.T[indices])
        RHS = alpha * WY
        RHS[i0] = s
        Y_new = G0inv @ RHS
        delta = np.max(np.abs(Y_new - Y))
        Y = Y_new
        if delta < tol:
            break
    else:
        import warnings

        warnings.warn(
            f"time_dependent_ppr fixed point not converged "
            f"(delta={delta:.2e} > tol={tol:.2e} after {max_fp_iters} iters)"
        )

    sample_idx = np.arange(NX) * NR
    return x[sample_idx], Y[sample_idx]


def find_local_cluster(
    G,
    seeds,
    alpha: float = 0.85,
    gamma: float = 5.0,
    epsilon: float = 0.001,
    NX: int = 4,
    recursive: bool = False,
):
    """Returns ``(cluster, conductance)``; cluster is a set of vertex ids.

    ≙ ``FindLocalCluster`` (local_computations.hpp:288-374): run the
    diffusion from the (uniform-mass) seed set, sweep the
    degree-normalized values at each time sample for the best-conductance
    prefix; optionally recurse with the found cluster as the new seed.
    """
    cluster = set(int(v) for v in seeds)
    current_cond = None
    deg = G.degrees
    Gvol = G.volume

    while True:
        s = {v: 1.0 / len(cluster) for v in cluster}
        _, Y = time_dependent_ppr(G, s, alpha, gamma, epsilon, NX)
        improve = False
        for t in range(Y.shape[0]):
            vals = Y[t] / np.maximum(deg, 1)
            support = np.flatnonzero(vals > 1e-12)
            if support.size == 0:
                continue
            order = support[np.argsort(-vals[support], kind="stable")]
            best_cond, best_prefix = 1.0, 0
            volS = cutS = 0
            current = set()
            for i, node in enumerate(order):
                volS += int(deg[node])
                for o in G.neighbors(node):
                    cutS += -1 if int(o) in current else 1
                denom = min(volS, Gvol - volS)
                if denom > 0:
                    cond = cutS / denom
                    if cond < best_cond:
                        best_cond, best_prefix = cond, i
                current.add(int(node))
            if current_cond is None or best_cond < 0.999999 * current_cond:
                improve = True
                cluster = set(int(v) for v in order[: best_prefix + 1])
                current_cond = best_cond
        if not (recursive and improve):
            break

    return cluster, current_cond
