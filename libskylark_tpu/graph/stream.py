"""Streamed graph sketching: adjacency folds over edge blocks.

The graph layer re-founded on the streaming + sparse substrate (PR-5
engine, PR-9 elastic worlds, PR-12 sharded COO schedules): work scales
with edges *streamed*, not adjacency *held*.  Three routes share one
bitwise contract:

- :func:`streamed_adjacency_sketch` folds COO edge blocks (from
  ``io.stream_arc_list`` or :func:`graph_block_source`) into ``S·A``
  through the per-hash ``segment_sum`` scatter — the same
  ``_segment_sum`` dispatcher the in-core BCOO apply uses, so the TPU
  ``pallas_scatter`` route engages per the coverage matrix wherever it
  does in-core.
- :func:`incore_adjacency_sketch` is the reference:
  ``S.apply(A_bcoo, dense_output=True)``.
- :func:`chained_adjacency_sketch` composes ``S₂·(S₁·A)`` either
  on-device through the sharded sparse-out schedule
  (``columnwise_sharded_sparse_out`` → ``ShardedBCOO.sketch_columnwise``)
  or by sketching the streamed fold.

**Why streamed ≡ in-core is bitwise, not approximate**: an unweighted
adjacency has 0/1 entries and hash-sketch values are ±1 (CWT) or ±2⁻¹
(SJLT, nnz=4) — every partial sum is an exact dyadic rational far below
2⁵³, so IEEE-754 addition is exact and the fold is order-invariant.
Block boundaries, batch sizes, rank partitions, and summation schedules
cannot change a single bit.  (Weighted graphs would lose this; the graph
layer is unweighted.)

:func:`streaming_ase` rebuilds ``approximate_ase`` as a ONE-PASS
streaming randomized symmetric eigensolve (Nyström): the only touch of
``A`` is the streamed fold ``SA = Ω·A``; the core ``Ω·A·Ωᵀ`` and the
whitened small eigenproblems are deterministic replicated (s, s)/(n, s)
math.  Exact for exactly-low-rank adjacencies once ``s ≥ rank`` (the
oversampled default), Nyström-approximate otherwise.  Elastic worlds
fold per-rank edge partitions via ``elastic_run_stream`` and merge with
one ``cross_host_psum`` — repartition-on-resume comes with the engine.
"""

from __future__ import annotations

import numpy as np

from ..utils.exceptions import InvalidParameters

__all__ = [
    "graph_block_source",
    "adjacency_sketch_fold",
    "incore_adjacency_sketch",
    "streamed_adjacency_sketch",
    "chained_adjacency_sketch",
    "ase_from_sketch",
    "streaming_ase",
]


def graph_block_source(G, batch_edges: int = 65536, dtype=np.float64):
    """Checkpointable block factory over an in-core graph's edges.

    Yields the same ``{"rows", "cols", "vals"}`` symmetrized COO blocks
    as ``io.stream_arc_list`` — here in canonical CSR (sorted) edge
    order rather than file order; the folds are order-invariant (module
    docstring) so both sources produce bit-identical sketches.
    """
    rows_full = np.repeat(np.arange(G.n, dtype=np.int64), G.degrees)
    upper = rows_full < G.indices
    lo = rows_full[upper]
    hi = G.indices[upper].astype(np.int64)

    def factory(start_batch: int = 0):
        for b0 in range(start_batch * batch_edges, lo.size, batch_edges):
            l, h = lo[b0 : b0 + batch_edges], hi[b0 : b0 + batch_edges]
            yield {
                "rows": np.concatenate([l, h]),
                "cols": np.concatenate([h, l]),
                "vals": np.ones(2 * l.size, dtype=dtype),
            }

    return factory


def adjacency_sketch_fold(S, ncols: int, dtype=np.float64):
    """(init_at, step) for folding edge blocks into columnwise ``S·A``.

    ``step`` scatters each block's entries through the per-hash
    ``segment_sum`` keyed by ``bucket·ncols + col`` — entry-for-entry
    the kernel of the in-core BCOO dense-out apply, addressed by GLOBAL
    vertex ids (edge partitions need no row offsets: the scatter key is
    position-independent, unlike the row-window folds of
    ``distributed_sketch``).  The accumulator's ``"edge"`` leaf counts
    folded undirected edges for the partition end-check.
    """
    import jax.numpy as jnp

    from ..sketch.hash import HashSketch, _segment_sum

    if not isinstance(S, HashSketch):
        raise InvalidParameters(
            f"graph sketch folds need a hash sketch (CWT/SJLT), got "
            f"{type(S).__name__}"
        )
    jdt = jnp.dtype(dtype)
    # Hoist the full bucket/value windows once (O(nnz·n) — the vertex
    # set fits by contract; the edge file need not).
    bs = [S.buckets(h * S.n, S.n) for h in range(S.nnz)]
    vs = [S.values(jdt, h * S.n, S.n) for h in range(S.nnz)]

    def init_at(edge0: int):
        return {
            "sa": jnp.zeros((S.s, int(ncols)), jdt),
            "edge": np.asarray(edge0, np.int64),
        }

    def step(acc, block, index):
        rows = jnp.asarray(block["rows"]).astype(jnp.int32)
        cols = jnp.asarray(block["cols"]).astype(jnp.int32)
        vals = jnp.asarray(block["vals"]).astype(jdt)
        sa = acc["sa"]
        for h in range(S.nnz):
            key = bs[h][rows] * jnp.int32(ncols) + cols
            sa = sa + _segment_sum(
                vals * vs[h][rows], key, S.s * int(ncols)
            ).astype(jdt).reshape(S.s, int(ncols))
        folded = int(block["rows"].shape[0]) // 2
        return {
            "sa": sa,
            "edge": np.asarray(int(acc["edge"]) + folded, np.int64),
        }

    return init_at, step


def incore_adjacency_sketch(G, S, dtype=None):
    """The bitwise reference: ``S.apply(A_bcoo, dense_output=True)``.

    ``G`` may be a ``SimpleGraph`` or a BCOO adjacency.
    """
    from jax.experimental import sparse as jsparse

    from .graph import SimpleGraph

    A = G.adjacency_bcoo(dtype) if isinstance(G, SimpleGraph) else G
    if not isinstance(A, jsparse.BCOO):
        raise InvalidParameters(
            f"incore_adjacency_sketch needs a SimpleGraph or BCOO "
            f"adjacency, got {type(G).__name__}"
        )
    return S.apply(A, "columnwise", dense_output=True)


def streamed_adjacency_sketch(
    source,
    S,
    *,
    ncols: int,
    dtype=np.float64,
    partition=None,
    params=None,
    fault_plan=None,
    epoch: int = 0,
):
    """One-pass columnwise ``S·A`` over an edge-block stream.

    ``source``: a block factory (``io.arc_list_source``,
    :func:`graph_block_source`) or iterable of edge blocks.  With
    ``partition=None`` this is the single-process resilient fold
    (checkpoint/resume via ``StreamParams``); with an edge
    :class:`~libskylark_tpu.streaming.elastic.RowPartition`
    (``nrows`` = unique undirected edges) every process of a real
    ``jax.distributed`` world folds its edge share and partials merge
    with one psum — simulated ranks drive ``elastic_run_stream`` +
    :func:`adjacency_sketch_fold` directly and merge explicitly.
    Bit-identical to :func:`incore_adjacency_sketch` in every
    configuration (module docstring).
    """
    import jax.numpy as jnp

    from .. import guard
    from ..sketch.base import Dimension

    init_at, step = adjacency_sketch_fold(S, ncols, dtype)
    kind = "graph_streaming_sketch"
    report = guard.RecoveryReport(stage=kind)

    if partition is None:
        from ..streaming.engine import StreamParams, run_stream

        params = params or StreamParams()
        acc, _ = run_stream(
            source, step, init_at(0), params,
            kind=kind, fault_plan=fault_plan, report=report,
        )
        partial = acc["sa"]
        merged = partial
    else:
        from ..parallel.collectives import cross_host_psum
        from ..streaming.elastic import (
            ElasticParams,
            _make_watchdog,
            _require_real_world,
            _resolve_world,
            elastic_run_stream,
        )

        _require_real_world(partition)
        params = params or ElasticParams()
        rank, world = _resolve_world(params)
        partition.validate_world(rank, world)
        e0, e1 = partition.row_range(rank)
        kind = "graph_distributed_sketch"
        acc, _ = elastic_run_stream(
            source, step, init_at(e0), partition, params,
            kind=kind, fault_plan=fault_plan, report=report, epoch=epoch,
        )
        edges = int(acc["edge"])
        if edges != e1:
            raise ValueError(
                f"rank {rank} folded edges [{e0}, {edges}) but its "
                f"partition share is [{e0}, {e1}); the source and "
                "partition disagree"
            )
        watchdog = (
            _make_watchdog(params, params.checkpoint_dir, rank, world, epoch)
            if params.checkpoint_dir
            else None
        )
        merged = cross_host_psum({"sa": acc["sa"]}, watchdog=watchdog)["sa"]
    out = S.finalize_slices(jnp.asarray(merged), Dimension.COLUMNWISE)
    if guard.enabled():
        guard.check_finite(out, kind, report=report)
    return out


def chained_adjacency_sketch(
    G,
    S1,
    S2,
    *,
    mesh=None,
    streamed: bool = False,
    batch_edges: int = 65536,
    dtype=None,
):
    """``S₂·(S₁·A)`` without materializing the intermediate off-device.

    In-core (default): the BCOO adjacency rides
    ``columnwise_sharded_sparse_out`` — ``S₁·A`` lands ROW-BLOCK-SHARDED
    and ``ShardedBCOO.sketch_columnwise`` hashes it in place (one psum,
    no host exit, no densified intermediate).  ``streamed=True`` folds
    ``S₁·A`` from edge blocks first, then applies ``S₂`` — same bits,
    by the exactness argument in the module docstring.  Requires
    ``S2.n == S1.s``.
    """
    from .graph import SimpleGraph

    if S2.n != S1.s:
        raise InvalidParameters(
            f"chained sketch needs S2.n == S1.s, got S2.n={S2.n}, "
            f"S1.s={S1.s}"
        )
    if streamed:
        ddt = np.float64 if dtype is None else dtype
        SA1 = streamed_adjacency_sketch(
            graph_block_source(G, batch_edges=batch_edges, dtype=ddt),
            S1, ncols=G.n, dtype=ddt,
        )
        return S2.apply(SA1, "columnwise")
    from ..parallel.collectives import columnwise_sharded_sparse_out

    if not isinstance(G, SimpleGraph):
        raise InvalidParameters(
            "chained_adjacency_sketch needs a SimpleGraph"
        )
    if mesh is None:
        # 1-D mesh over all visible devices, built directly so the route
        # works regardless of the installed JAX's AxisType support.
        import jax
        from jax.sharding import Mesh

        from ..parallel.mesh import ROWS

        mesh = Mesh(np.array(jax.devices()), (ROWS,))
    sharded = columnwise_sharded_sparse_out(S1, G.adjacency_bcoo(dtype), mesh)
    return sharded.sketch_columnwise(S2, dense_output=True)


def ase_from_sketch(SA, S, k: int):
    """Nyström symmetric eigensolve from the one-pass sketch ``SA = Ω·A``.

    With ``Y = AΩᵀ = SAᵀ`` and core ``C = ΩAΩᵀ`` (one more sketch apply
    — no second pass over ``A``), ``A ≈ Y C⁺ Yᵀ``; whitening ``Y`` by
    ``C``'s floored inverse-sqrt and orthogonalizing through Gram
    eigensolves (the ``gram_orth`` floor discipline of ``linalg/svd.py``)
    turns that into an eigendecomposition.  Signed: ``C``'s negative
    eigenvalues carry through, so bipartite-like spectra (λ < 0) are
    recovered — exact when ``rank(A) ≤ s``.  All (s, s) math is
    replicated and deterministic: every rank computes identical bits
    from the merged ``SA``.  Returns ``(V, lam)``, top-k by |λ|.
    """
    import jax
    import jax.numpy as jnp

    from ..parallel.mesh import fully_replicated

    dtype = SA.dtype
    s = SA.shape[0]
    Y = SA.T  # (n, s) = A·Ωᵀ (A symmetric)
    C = S.apply(Y, "columnwise")  # (s, s) = Ω·A·Ωᵀ
    C = fully_replicated((C + C.T) / 2)
    c, Uc = jnp.linalg.eigh(C)
    abs_c = jnp.abs(c)
    eps = jnp.finfo(dtype).eps
    floor = jnp.max(abs_c) * eps * s
    cscale = jnp.where(
        abs_c > floor, jax.lax.rsqrt(jnp.maximum(abs_c, floor)),
        jnp.zeros((), dtype),
    )
    sgn = jnp.where(abs_c > floor, jnp.sign(c), jnp.zeros((), dtype))
    M = jnp.dot(Y, Uc * cscale[None, :], precision="highest")
    Gm = fully_replicated(jnp.dot(M.T, M, precision="highest"))
    g, Vg = jnp.linalg.eigh(Gm)
    gfloor = jnp.maximum(g[-1], 0) * eps * s
    gscale = jnp.where(
        g > gfloor, jax.lax.rsqrt(jnp.maximum(g, gfloor)),
        jnp.zeros((), dtype),
    )
    Q = jnp.dot(M, Vg * gscale[None, :], precision="highest")  # M ≈ Q·R
    R = jnp.sqrt(jnp.maximum(g, 0))[:, None] * Vg.T
    T = jnp.dot(R * sgn[None, :], R.T, precision="highest")
    T = fully_replicated((T + T.T) / 2)
    lam, W = jnp.linalg.eigh(T)
    order = jnp.argsort(-jnp.abs(lam))[:k]
    V = jnp.dot(Q, W, precision="highest")[:, order]
    return V, lam[order]


def streaming_ase(
    source,
    n: int,
    k: int,
    context,
    params=None,
    *,
    dtype=np.float64,
    partition=None,
    fault_plan=None,
    epoch: int = 0,
):
    """Streaming randomized ASE: ``(X, lam)`` from ONE pass over edges.

    The only O(edges) work is the streamed fold ``SA = Ω·A`` (SJLT Ω,
    oversampled width from the shared ``_sketch_size`` sizing); the
    embedding follows from :func:`ase_from_sketch`'s replicated small
    math, ``X = V·√|λ|``.  One-pass by construction — subspace
    iteration would need re-streaming, so ``num_iterations > 0`` is
    rejected; use the in-core route for polished spectra of graphs that
    fit.
    """
    import jax.numpy as jnp

    from ..linalg.svd import SVDParams, _sketch_size
    from ..sketch.hash import SJLT

    params = params or SVDParams()
    if getattr(params, "num_iterations", 0):
        raise InvalidParameters(
            "streaming ASE is one-pass: subspace iteration "
            f"(num_iterations={params.num_iterations}) would re-stream "
            "the edges; use the in-core route or num_iterations=0"
        )
    k, s = _sketch_size(k, params, n)
    S = SJLT(n, s, context)
    SA = streamed_adjacency_sketch(
        source, S, ncols=n, dtype=dtype,
        partition=partition, fault_plan=fault_plan, epoch=epoch,
    )
    V, lam = ase_from_sketch(SA, S, k)
    X = V * jnp.sqrt(jnp.abs(lam))[None, :]
    return X, lam
