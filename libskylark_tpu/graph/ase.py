"""Approximate adjacency spectral embedding (ASE).

≙ ``ApproximateASE`` (``ml/graph/spectral_embedding.hpp:19-94``, Lyzinski
et al): randomized symmetric SVD of the adjacency matrix, embeddings
``X = V·diag(√|λ|)``.  The SVD is the TPU-heavy part and reuses
``approximate_symmetric_svd`` (sharded subspace iteration).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from ..core.context import SketchContext
from ..linalg.svd import SVDParams, approximate_symmetric_svd
from .graph import SimpleGraph

__all__ = ["ASEParams", "approximate_ase"]


@dataclass
class ASEParams(SVDParams):
    """≙ ``approximate_ase_params_t`` (inherits the SVD oversampling/
    iteration knobs).

    ``streamed=True`` routes a ``SimpleGraph`` through the one-pass
    streaming eigensolve (``graph.stream.streaming_ase``): the adjacency
    is never materialized — edge blocks of ``batch_edges`` undirected
    edges fold into ``Ω·A`` and the embedding follows from replicated
    small math.  One-pass, so it requires ``num_iterations == 0``.
    """

    sparse: bool = False  # use BCOO adjacency
    streamed: bool = False  # fold edge blocks; never build A
    batch_edges: int = 65536  # undirected edges per streamed block


def approximate_ase(
    G,
    k: int,
    context: SketchContext,
    params: ASEParams | None = None,
):
    """Returns (X, lam): X (n, k) embeddings, lam the eigenvalues.

    ``G`` may be a ``SimpleGraph`` or an (n, n) adjacency array/BCOO.
    """
    params = params or ASEParams()
    if isinstance(G, SimpleGraph) and params.streamed:
        from .stream import graph_block_source, streaming_ase

        return streaming_ase(
            graph_block_source(G, batch_edges=params.batch_edges),
            G.n, k, context, params,
        )
    if isinstance(G, SimpleGraph):
        A = G.adjacency_bcoo() if params.sparse else jnp.asarray(G.adjacency())
    else:
        A = G
    V, lam = approximate_symmetric_svd(A, k, context, params)
    X = V * jnp.sqrt(jnp.abs(lam))[None, :]
    return X, lam
