"""Graph analytics (≙ reference ``ml/graph/``): adjacency spectral
embedding and seed-set local community detection."""

from .ase import ASEParams, approximate_ase
from .community import find_local_cluster, time_dependent_ppr
from .graph import SimpleGraph, read_arc_list
from .stream import (
    adjacency_sketch_fold,
    ase_from_sketch,
    chained_adjacency_sketch,
    graph_block_source,
    incore_adjacency_sketch,
    streamed_adjacency_sketch,
    streaming_ase,
)

__all__ = [
    "SimpleGraph",
    "read_arc_list",
    "ASEParams",
    "approximate_ase",
    "time_dependent_ppr",
    "find_local_cluster",
    "graph_block_source",
    "adjacency_sketch_fold",
    "incore_adjacency_sketch",
    "streamed_adjacency_sketch",
    "chained_adjacency_sketch",
    "ase_from_sketch",
    "streaming_ase",
]
