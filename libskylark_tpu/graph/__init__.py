"""Graph analytics (≙ reference ``ml/graph/``): adjacency spectral
embedding and seed-set local community detection."""

from .ase import ASEParams, approximate_ase
from .community import find_local_cluster, time_dependent_ppr
from .graph import SimpleGraph, read_arc_list

__all__ = [
    "SimpleGraph",
    "read_arc_list",
    "ASEParams",
    "approximate_ase",
    "time_dependent_ppr",
    "find_local_cluster",
]
