"""Repartition-on-resume: remap ledgered elastic progress onto a new world.

PR 6's elastic layer fails fast (``WorldMismatchError``, code 109) when a
stream is resumed under a different world size or row partition — safe,
but it discards every host's durable partial sketch and restarts the job
from batch 0.  Because columnwise ``S·A`` is a pure SUM of counter-
addressed window applies (``apply_slice`` with global row offsets), a
partial sketch checkpoint covering global batches ``[s, e)`` is valid
under ANY partition: linearity lets a new world adopt the old world's
durable partials wholesale and re-fold only what was never committed.

The flow (``resume_policy="repartition"``):

1. :func:`replan_resume` scans the shared checkpoint root WITHOUT
   communication: every ``host-*/`` manifest + ``progress.jsonl`` of the
   current epoch (or the persisted plan of an already-repartitioned
   epoch) is read, kind/signature coherence is verified, and each host's
   newest CRC-valid checkpoint slot becomes a **coverage ref** — a
   global batch range ``[start, start+step)`` backed by a durable file.
   Hosts with unreadable manifests or corrupt slots simply contribute no
   coverage (their batches are re-folded); a readable manifest for a
   DIFFERENT kind or a mix of partitions raises 109.
2. The globally-completed set is the union of refs; the **residual** is
   its complement in ``[0, num_batches)``.  A deterministic greedy
   assignment (refs round-robin by start order; residual ranges split to
   a per-rank quota and packed least-loaded-first, ties to the lowest
   rank) maps both onto the new world — pure arithmetic on the scanned
   state, so every rank computes the IDENTICAL plan independently.
3. The plan and a root-level ``epoch.json`` marker are persisted with
   canonical bytes (every rank writes the same content, so racing
   ``os.replace`` is benign) and the epoch is bumped: stale writers from
   the old world are fenced at their next ledger record
   (:class:`~libskylark_tpu.utils.exceptions.StaleEpochError`, 111).
4. :func:`execute_rank_plan` runs one rank's share: merge assigned refs
   (exact-slot loads, CRC + epoch validated), re-fold assigned residual
   segments through the ordinary checkpointable ``run_stream`` (each
   segment has its own store under ``epoch-<e>/host-<rank>/seg-*``, so a
   second kill mid-recovery resumes *the recovery*), and hand back the
   float partial for the usual single ``cross_host_psum``.

The merged result equals the uninterrupted new-world run's sum of the
same window applies — exactly, up to floating-point reassociation of the
commutative merge (bitwise when the summands are exactly representable,
e.g. integer-valued data under a ±1-valued CountSketch).
"""

from __future__ import annotations

import json
import os
import zlib
from dataclasses import dataclass, field
from itertools import islice

import numpy as np

from .. import telemetry
from ..utils.checkpoint import CheckpointStore, load_solver_state
from ..utils.exceptions import (
    CheckpointError,
    InvalidParameters,
    StaleEpochError,
    WorldMismatchError,
)
from .engine import as_block_factory, run_stream

__all__ = [
    "EPOCH_NAME",
    "PlanRef",
    "RankAssignment",
    "ResumePlan",
    "read_epoch",
    "write_epoch",
    "plan_path",
    "load_plan",
    "scan_coverage",
    "replan_resume",
    "resolve_resume",
    "execute_rank_plan",
    "merge_ranges",
    "complement_ranges",
]

EPOCH_NAME = "epoch.json"
_PLAN_VERSION = 1
_EPOCH_VERSION = 1


def _atomic_write_json(path: str, payload: dict) -> None:
    """Canonical-bytes atomic write: every rank of a repartitioning world
    writes the identical content, so concurrent ``os.replace`` races are
    benign (last writer wins with the same bytes)."""
    data = json.dumps(payload, sort_keys=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as fh:
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


def read_epoch(root) -> dict | None:
    """The root-level epoch marker, or ``None`` for a pre-repartition
    (epoch 0) root.  Unreadable marker → ``None`` — the strict manifest
    checks downstream still guard against merging mismatched state."""
    try:
        with open(os.path.join(str(root), EPOCH_NAME), encoding="utf-8") as fh:
            d = json.load(fh)
    except (OSError, UnicodeDecodeError, json.JSONDecodeError):
        return None
    if d.get("skylark_object_type") != "elastic_epoch":
        return None
    return d


def write_epoch(root, *, epoch: int, partition, kind: str) -> None:
    _atomic_write_json(
        os.path.join(str(root), EPOCH_NAME),
        {
            "skylark_object_type": "elastic_epoch",
            "format_version": _EPOCH_VERSION,
            "epoch": int(epoch),
            "kind": str(kind),
            "partition": partition.to_json(),
            "signature": int(partition.signature()),
        },
    )


def current_epoch(root) -> int:
    est = read_epoch(root)
    return int(est["epoch"]) if est else 0


def plan_path(root, epoch: int) -> str:
    return os.path.join(str(root), f"plan-{int(epoch):04d}.json")


def merge_ranges(ranges) -> list[tuple[int, int]]:
    """Union of half-open int ranges, sorted and coalesced."""
    out: list[list[int]] = []
    for s, e in sorted((int(s), int(e)) for s, e in ranges if e > s):
        if out and s <= out[-1][1]:
            out[-1][1] = max(out[-1][1], e)
        else:
            out.append([s, e])
    return [(s, e) for s, e in out]


def complement_ranges(ranges, total: int) -> list[tuple[int, int]]:
    """Complement of (merged) ``ranges`` within ``[0, total)``."""
    out = []
    pos = 0
    for s, e in merge_ranges(ranges):
        if s > pos:
            out.append((pos, s))
        pos = max(pos, e)
    if pos < total:
        out.append((pos, total))
    return out


@dataclass(frozen=True)
class PlanRef:
    """A durable partial-sketch checkpoint covering global batches
    ``[start, end)``.  ``directory`` is the store directory RELATIVE to
    the shared root; ``step`` pins the exact slot (refs never chase a
    store's newest slot — the plan is a frozen snapshot)."""

    directory: str
    step: int
    start: int
    end: int
    epoch: int

    def to_json(self) -> dict:
        return {
            "dir": self.directory,
            "step": int(self.step),
            "start": int(self.start),
            "end": int(self.end),
            "epoch": int(self.epoch),
        }

    @classmethod
    def from_json(cls, d: dict) -> "PlanRef":
        return cls(
            directory=d["dir"], step=int(d["step"]), start=int(d["start"]),
            end=int(d["end"]), epoch=int(d["epoch"]),
        )


@dataclass
class RankAssignment:
    refs: list = field(default_factory=list)
    segments: list = field(default_factory=list)  # [(start, end)) to re-fold

    def to_json(self) -> dict:
        return {
            "refs": [r.to_json() for r in self.refs],
            "segments": [[int(s), int(e)] for s, e in self.segments],
        }

    @classmethod
    def from_json(cls, d: dict) -> "RankAssignment":
        return cls(
            refs=[PlanRef.from_json(r) for r in d.get("refs", [])],
            segments=[(int(s), int(e)) for s, e in d.get("segments", [])],
        )


@dataclass
class ResumePlan:
    """The world-deterministic repartition plan: what every rank of the
    NEW world merges and re-folds.  Serialized to ``plan-<epoch>.json``
    under the root so chained resizes (and a kill during recovery) can
    re-derive coverage without rescanning superseded layouts."""

    kind: str
    source_epoch: int
    epoch: int
    partition: object  # RowPartition of the NEW world
    old_partition: dict | None
    assignments: dict  # rank -> RankAssignment
    completed: list  # merged [(s, e)) durable at plan time
    residual: list  # merged [(s, e)) to re-fold
    lost_hosts: list = field(default_factory=list)

    def to_json(self) -> dict:
        return {
            "skylark_object_type": "elastic_resume_plan",
            "format_version": _PLAN_VERSION,
            "kind": self.kind,
            "source_epoch": int(self.source_epoch),
            "epoch": int(self.epoch),
            "partition": self.partition.to_json(),
            "signature": int(self.partition.signature()),
            "old_partition": self.old_partition,
            "assignments": {
                str(r): a.to_json() for r, a in sorted(self.assignments.items())
            },
            "completed": [[int(s), int(e)] for s, e in self.completed],
            "residual": [[int(s), int(e)] for s, e in self.residual],
            "lost_hosts": [int(r) for r in self.lost_hosts],
        }

    @classmethod
    def from_json(cls, d: dict) -> "ResumePlan":
        from .elastic import RowPartition

        if d.get("skylark_object_type") != "elastic_resume_plan":
            raise CheckpointError(
                f"not an elastic resume plan: {d.get('skylark_object_type')!r}"
            )
        return cls(
            kind=d["kind"],
            source_epoch=int(d["source_epoch"]),
            epoch=int(d["epoch"]),
            partition=RowPartition.from_json(d["partition"]),
            old_partition=d.get("old_partition"),
            assignments={
                int(r): RankAssignment.from_json(a)
                for r, a in d.get("assignments", {}).items()
            },
            completed=[(int(s), int(e)) for s, e in d.get("completed", [])],
            residual=[(int(s), int(e)) for s, e in d.get("residual", [])],
            lost_hosts=[int(r) for r in d.get("lost_hosts", [])],
        )

    def signature(self) -> int:
        """CRC32 of the canonical plan bytes — carried in the resume
        handshake so ranks that somehow derived different plans fail
        fast instead of merging mismatched recoveries."""
        return zlib.crc32(json.dumps(self.to_json(), sort_keys=True).encode())

    def replay_info(self) -> dict:
        """World-deterministic ``info["replay"]`` accounting: identical
        on every rank because it is pure plan arithmetic."""
        return {
            "epoch": int(self.epoch),
            "source_epoch": int(self.source_epoch),
            "from_world": (
                int(self.old_partition["world_size"])
                if self.old_partition
                else None
            ),
            "to_world": int(self.partition.world_size),
            "completed_batches": sum(e - s for s, e in self.completed),
            "replayed_batches": sum(e - s for s, e in self.residual),
            "replayed": [[int(s), int(e)] for s, e in self.residual],
            "merged_refs": sum(
                len(a.refs) for a in self.assignments.values()
            ),
            "lost_hosts": [int(r) for r in self.lost_hosts],
        }


def load_plan(root, epoch: int) -> ResumePlan | None:
    try:
        with open(plan_path(root, epoch), encoding="utf-8") as fh:
            return ResumePlan.from_json(json.load(fh))
    except (OSError, UnicodeDecodeError, json.JSONDecodeError):
        return None


def _newest_valid_step(directory: str) -> tuple[int, int] | None:
    """``(step, epoch)`` of the newest CRC-valid slot of a store, or
    ``None``.  Loads (and discards) the leaves — validity of a coverage
    ref means its bytes check out NOW, not that a file merely exists."""
    if not os.path.isdir(directory):
        return None
    store = CheckpointStore(directory)
    try:
        loaded = store.load_latest()
    except CheckpointError:
        return None
    if loaded is None:
        return None
    _, meta, step = loaded
    return int(step), CheckpointStore.slot_epoch(meta)


def scan_coverage(root, *, kind: str) -> dict:
    """Scan the shared root's CURRENT epoch without communication.

    Returns ``{"epoch", "old_partition" (dict | None), "refs"
    (list[PlanRef], durable coverage), "lost_hosts" (ranks whose state
    could not be certified and contributes nothing)}``.  Raises
    :class:`WorldMismatchError` when readable state belongs to a
    different ``kind`` or mixes partitions — repartitioning across jobs
    would merge unrelated sketches.
    """
    from .elastic import MANIFEST_NAME, host_dir

    root = str(root)
    epoch = current_epoch(root)
    refs: list[PlanRef] = []
    lost: list[int] = []

    if epoch > 0:
        plan = load_plan(root, epoch)
        if plan is None:
            raise WorldMismatchError(
                f"epoch marker at {root} names epoch {epoch} but "
                f"{plan_path(root, epoch)} is missing/unreadable; the "
                "root's repartition history cannot be certified",
                expected=epoch,
                got=None,
            )
        if plan.kind != str(kind):
            raise WorldMismatchError(
                f"checkpoint root {root} holds a "
                f"{plan.kind!r} stream, refusing to repartition it into "
                f"a {kind!r} resume",
                expected=plan.kind,
                got=str(kind),
            )
        # Inherited refs: re-validate each (corrupt-at-rest since the
        # last plan → its range degrades to residual).
        for rank, asg in sorted(plan.assignments.items()):
            for ref in asg.refs:
                slot = os.path.join(
                    root, ref.directory, f"ckpt-{ref.step:012d}"
                )
                try:
                    load_solver_state(slot)
                except CheckpointError:
                    lost.append(rank)  # corrupt since planning: re-fold
                    continue
                refs.append(ref)
            # Segment stores: whatever the recovery durably folded.
            hdir = host_dir(root, rank, epoch)
            for s, e in asg.segments:
                seg = os.path.join(hdir, f"seg-{int(s):06d}")
                probe = _newest_valid_step(seg)
                if probe is None:
                    continue
                step, slot_epoch = probe
                if slot_epoch != epoch or step <= 0:
                    continue
                refs.append(
                    PlanRef(
                        directory=os.path.relpath(seg, root),
                        step=min(step, e - s),
                        start=s,
                        end=s + min(step, e - s),
                        epoch=epoch,
                    )
                )
        return {
            "epoch": epoch,
            "old_partition": plan.partition.to_json(),
            "refs": refs,
            "lost_hosts": sorted(set(lost)),
        }

    # Epoch 0: bare host-<rank>/ dirs written by plain elastic runs.
    old_partition = None
    old_signature = None
    hosts = []
    try:
        names = sorted(os.listdir(root))
    except OSError:
        names = []
    for name in names:
        if not name.startswith("host-"):
            continue
        try:
            rank = int(name.split("-", 1)[1])
        except ValueError:
            continue
        hosts.append((rank, os.path.join(root, name)))
    for rank, hdir in hosts:
        mpath = os.path.join(hdir, MANIFEST_NAME)
        try:
            with open(mpath, encoding="utf-8") as fh:
                manifest = json.load(fh)
        except (OSError, UnicodeDecodeError, json.JSONDecodeError):
            # Hostile/corrupt host: certify nothing, re-fold its range.
            lost.append(rank)
            continue
        if manifest.get("kind") != str(kind):
            raise WorldMismatchError(
                f"host state {hdir} belongs to kind "
                f"{manifest.get('kind')!r}, refusing to repartition into "
                f"a {kind!r} resume",
                expected=manifest.get("kind"),
                got=str(kind),
            )
        if int(manifest.get("epoch", 0)) != 0:
            lost.append(rank)
            continue
        sig = manifest.get("signature")
        if old_signature is None:
            old_signature, old_partition = sig, manifest.get("partition")
        elif sig != old_signature:
            raise WorldMismatchError(
                f"host manifests under {root} mix partitions "
                f"(signatures {old_signature} and {sig}); the root "
                "cannot be repartitioned coherently",
                expected=old_signature,
                got=sig,
            )
        probe = _newest_valid_step(hdir)
        if probe is None:
            lost.append(rank)
            continue
        step, slot_epoch = probe
        if slot_epoch != 0 or step <= 0:
            lost.append(rank)
            continue
        part = manifest.get("partition") or {}
        try:
            from .elastic import RowPartition

            start_b, end_b = RowPartition.from_json(part).batch_range(rank)
        except (KeyError, TypeError, InvalidParameters):
            lost.append(rank)
            continue
        covered = min(step, end_b - start_b)
        if covered > 0:
            refs.append(
                PlanRef(
                    directory=os.path.relpath(hdir, root),
                    step=covered,
                    start=start_b,
                    end=start_b + covered,
                    epoch=0,
                )
            )
    return {
        "epoch": 0,
        "old_partition": old_partition,
        "refs": refs,
        "lost_hosts": sorted(set(lost)),
    }


def _assign(refs, residual, world: int) -> dict:
    """Deterministic greedy assignment: pure arithmetic on the scanned
    state, so every rank derives the identical plan with no
    communication.  Refs (cheap merges) go round-robin in start order;
    residual ranges (real re-folds) are split to a per-rank quota and
    packed onto the least-loaded rank, ties to the lowest rank."""
    assignments = {r: RankAssignment() for r in range(world)}
    for i, ref in enumerate(sorted(refs, key=lambda r: (r.start, r.directory))):
        assignments[i % world].refs.append(ref)
    total = sum(e - s for s, e in residual)
    if total:
        quota = -(-total // world)
        load = [0] * world
        for s, e in residual:
            while s < e:
                piece = min(e - s, quota)
                rank = min(range(world), key=lambda r: (load[r], r))
                assignments[rank].segments.append((s, s + piece))
                load[rank] += piece
                s += piece
        for asg in assignments.values():
            asg.segments.sort()
    return assignments


def replan_resume(root, new_partition, *, kind: str) -> ResumePlan:
    """Compute (and persist) the repartition plan that adopts the current
    epoch's durable coverage under ``new_partition``, then bump the
    root's epoch marker to fence stale writers.  Deterministic: every
    rank calling this against the same root state writes byte-identical
    ``plan-<epoch>.json`` / ``epoch.json``."""
    scan = scan_coverage(root, kind=kind)
    source_epoch = int(scan["epoch"])
    new_epoch = source_epoch + 1
    nb = new_partition.num_batches
    refs = [r for r in scan["refs"] if r.start < r.end]
    completed = merge_ranges((r.start, r.end) for r in refs)
    if any(e > nb for _, e in completed):
        raise WorldMismatchError(
            f"durable coverage reaches batch "
            f"{max(e for _, e in completed)} but the new partition has "
            f"only {nb} batches; nrows/batch_rows changed, not just the "
            "world size — restart from scratch",
            expected=nb,
            got=max(e for _, e in completed),
        )
    residual = complement_ranges(completed, nb)
    plan = ResumePlan(
        kind=str(kind),
        source_epoch=source_epoch,
        epoch=new_epoch,
        partition=new_partition,
        old_partition=scan["old_partition"],
        assignments=_assign(refs, residual, new_partition.world_size),
        completed=completed,
        residual=residual,
        lost_hosts=scan["lost_hosts"],
    )
    _atomic_write_json(plan_path(root, new_epoch), plan.to_json())
    write_epoch(root, epoch=new_epoch, partition=new_partition, kind=kind)
    if telemetry.enabled():
        telemetry.inc("elastic.replans")
        telemetry.event("elastic", "replan", plan.replay_info())
    return plan


def resolve_resume(root, partition, *, kind: str, params) -> tuple:
    """Decide this resume's ``(epoch, plan)``.

    ``resume_policy="strict"`` (the default) pins ``(0, None)``: the
    pre-repartition behavior — bare ``host-*/`` layout, manifest checks,
    code 109 on any world change — bit-for-bit.

    ``"repartition"`` (with ``resume=True`` and a checkpoint root):

    - fresh root → ``(0, None)``;
    - disk partition == ours → normal resume at the disk epoch
      (re-executing the persisted plan idempotently when that epoch was
      itself a repartition);
    - disk partition differs → :func:`replan_resume` at a bumped epoch.
    """
    policy = getattr(params, "resume_policy", "strict") or "strict"
    if policy not in ("strict", "repartition"):
        raise InvalidParameters(
            f"resume_policy must be 'strict' or 'repartition', got "
            f"{policy!r}"
        )
    if policy == "strict" or not root or not getattr(params, "resume", False):
        return 0, None
    est = read_epoch(root)
    ours = int(partition.signature())
    if est is not None:
        if est.get("kind") != str(kind):
            raise WorldMismatchError(
                f"checkpoint root {root} holds a {est.get('kind')!r} "
                f"stream, this resume is {kind!r}",
                expected=est.get("kind"),
                got=str(kind),
            )
        epoch = int(est["epoch"])
        if int(est.get("signature", -1)) == ours:
            plan = load_plan(root, epoch)
            return epoch, plan  # idempotent re-execution (or plain resume)
        return epoch + 1, replan_resume(root, partition, kind=kind)
    # Epoch-0 root: repartition only when the on-disk manifests disagree
    # with our partition; matching manifests resume the normal way.
    scan_needed = False
    from .elastic import MANIFEST_NAME

    try:
        names = sorted(os.listdir(str(root)))
    except OSError:
        names = []
    for name in names:
        if not name.startswith("host-"):
            continue
        mpath = os.path.join(str(root), name, MANIFEST_NAME)
        try:
            with open(mpath, encoding="utf-8") as fh:
                manifest = json.load(fh)
        except (OSError, UnicodeDecodeError, json.JSONDecodeError):
            scan_needed = True  # uncertifiable host: replan around it
            continue
        if manifest.get("signature") != ours or manifest.get("kind") != str(
            kind
        ):
            scan_needed = True
    if not scan_needed:
        return 0, None
    return 1, replan_resume(root, partition, kind=kind)


def _add_float_leaves(total: dict | None, acc: dict) -> dict:
    """Running sum of a driver accumulator's float leaves (the int
    bookkeeping cursor — ``"row"`` — is partition-relative and
    meaningless across plan pieces, so it is dropped)."""
    floats = {
        k: np.asarray(v)
        for k, v in acc.items()
        if np.issubdtype(np.asarray(v).dtype, np.floating)
    }
    if total is None:
        return floats
    if set(total) != set(floats):
        raise CheckpointError(
            f"plan pieces disagree on accumulator leaves: {sorted(total)} "
            f"vs {sorted(floats)}"
        )
    return {k: total[k] + floats[k] for k in total}


def execute_rank_plan(
    plan: ResumePlan,
    source,
    *,
    params,
    root,
    init_at,
    step_fn,
    kind: str,
    fault_plan=None,
    report=None,
):
    """Run THIS rank's share of ``plan``; returns ``(float_partial,
    replay_info)`` ready for the usual single ``cross_host_psum``.

    ``init_at(row0)`` builds the driver accumulator with its row cursor
    at global row ``row0`` (the same closure shape the drivers already
    use); ``step_fn`` is the unchanged driver fold.  Residual segments
    run through the ordinary checkpointable ``run_stream`` with a
    per-segment store under this rank's NEW-epoch host directory, so a
    preemption during recovery resumes the recovery.
    """
    from .elastic import (
        PROGRESS_NAME,
        HostLedger,
        _check_manifest,
        _epoch_fence,
        _handshake,
        _local_params,
        _make_watchdog,
        _resolve_world,
        host_dir,
    )

    rank, world = _resolve_world(params)
    plan.partition.validate_world(rank, world)
    batch_rows = plan.partition.batch_rows
    epoch = int(plan.epoch)
    root = str(root)
    hdir = host_dir(root, rank, epoch)
    _check_manifest(hdir, plan.partition, rank, kind, epoch, True)
    fence = _epoch_fence(root, epoch)
    ledger = HostLedger(
        os.path.join(hdir, PROGRESS_NAME), rank=rank, epoch=epoch,
        fence=fence,
    )
    watchdog = _make_watchdog(params, root, rank, world, epoch)
    _handshake(
        plan.partition, rank, world, kind, epoch,
        extra=plan.signature(), watchdog=watchdog,
    )
    if fault_plan is not None and hasattr(fault_plan, "bind_host"):
        fault_plan.bind_host(hdir=hdir, root=root, epoch=epoch)
    asg = plan.assignments.get(rank, RankAssignment())
    proto = {"batch": np.asarray(0, np.int64), "acc": init_at(0)}
    total = None

    for ref in asg.refs:
        slot = os.path.join(root, ref.directory, f"ckpt-{ref.step:012d}")
        state, meta = load_solver_state(slot, like=proto)
        slot_epoch = CheckpointStore.slot_epoch(meta)
        if slot_epoch != ref.epoch:
            raise StaleEpochError(
                f"plan ref {slot} was written at epoch {slot_epoch}, the "
                f"plan recorded epoch {ref.epoch}; the store was mutated "
                "since planning — replan",
                expected=ref.epoch,
                got=slot_epoch,
            )
        folded = int(state["batch"])
        if folded != ref.end - ref.start:
            raise CheckpointError(
                f"plan ref {slot} holds {folded} folded batches but "
                f"covers [{ref.start}, {ref.end}); the store was mutated "
                "since planning — replan"
            )
        total = _add_float_leaves(total, state["acc"])
        ledger.record(
            "merge_ref", start=int(ref.start), end=int(ref.end),
            source=ref.directory, source_epoch=int(ref.epoch),
        )
        if telemetry.enabled():
            telemetry.inc("elastic.ref_merges")

    global_factory = as_block_factory(source)
    for s, e in asg.segments:
        seg_dir = os.path.join(hdir, f"seg-{int(s):06d}")
        local_params = _local_params(params, seg_dir, expect_epoch=epoch)
        local_params.resume = True  # a killed recovery resumes itself

        def seg_factory(local_start: int, s=s, e=e):
            if not 0 <= local_start <= e - s:
                raise ValueError(
                    f"segment start {local_start} outside [0, {e - s}]"
                )
            return islice(
                iter(global_factory(s + local_start)), e - s - local_start
            )

        last = {"b": -1}

        def seg_step(acc, block, b, s=s, last=last):
            fence()
            if fault_plan is not None and hasattr(fault_plan, "before_batch"):
                fault_plan.before_batch(b)
            out = step_fn(acc, block, b)
            if b > last["b"]:
                ledger.record("batch", batch=int(s + b), local=int(b))
                last["b"] = b
            return out

        meta = {
            "elastic": {
                "rank": rank, "world": world, "epoch": epoch,
                "signature": int(plan.partition.signature()),
                "segment": [int(s), int(e)],
            }
        }
        acc, nb = run_stream(
            seg_factory, seg_step, init_at(s * batch_rows), local_params,
            kind=kind, metadata=meta, fault_plan=fault_plan, report=report,
        )
        if nb != e - s:
            raise ValueError(
                f"rank {rank} re-folded {nb} batches of segment "
                f"[{s}, {e}); the source and partition disagree"
            )
        total = _add_float_leaves(total, acc)
        ledger.record("segment_done", start=int(s), end=int(e))

    if total is None:
        # A rank with no assignment still contributes (zeros) to the
        # psum — build them from the prototype.
        total = _add_float_leaves(None, init_at(0))
        total = {k: np.zeros_like(v) for k, v in total.items()}
    ledger.record(
        "replayed",
        segments=[[int(s), int(e)] for s, e in asg.segments],
        refs=len(asg.refs),
    )
    ledger.close()
    info = plan.replay_info()
    if telemetry.enabled():
        telemetry.event("elastic", "repartition_done", info)
    return total, info
