"""The streaming accumulation engine: batches → checkpointable reduction.

One engine behind every streaming driver (``sketch``, sketch-and-solve
least squares, KRR feature accumulation): an order-preserving left fold

    acc ← step_fn(acc, batch, index)        index = 0, 1, 2, ...

over a batch source, adapted to the :class:`~libskylark_tpu.resilient.
chunked.ChunkedSolver` contract so the existing ``ResilientRunner`` /
``CheckpointStore`` machinery provides checkpoint/resume, IO retries,
fault injection, and divergence guards unchanged.  The state pytree is
``{"batch": int64 scalar, "acc": <driver pytree>}``; a killed pass
resumed from its newest checkpoint re-folds the remaining batches in the
same order, so the final accumulator is BIT-FOR-BIT identical to the
uninterrupted run (same floating-point summation order — the counter
contract's streaming analogue).

Sources are *re-openable*: a source is either a plain iterable (single
pass, no resume) or a callable ``factory(start_batch) -> iterator`` that
yields batches from ``start_batch`` onward.  Factories over seekable
storage (HDF5 row slices) can skip cheaply; line-parsed sources
(``stream_libsvm``) re-parse and drop the prefix — resume cost is
bounded by the skipped bytes, not by recomputation of the sketch.
"""

from __future__ import annotations

from itertools import islice

import numpy as np

from .. import guard, plans, telemetry
from . import overlap as _overlap
from ..resilient import ChunkedSolver, ResilientParams, ResilientRunner
from .pipeline import Prefetcher, device_placer

__all__ = ["StreamParams", "as_block_factory", "run_stream"]


class StreamParams(ResilientParams):
    """Runtime knobs of a streaming pass — the resilient runner's params
    (checkpointing, retries, divergence) plus the pipeline's:
    ``prefetch`` staged batches (0 disables the pipeline thread), the
    staging ``placer`` (host→device by default), ``fused_chunks``
    — whether planned accumulate steps trace the transform's fused
    chunk body (``apply_slice_kernel_acc``: one kernel launch per
    chunk where supported; bitwise equal to the two-step composite
    either way; ``None`` defers to the process default
    ``plans.fused_enabled`` / ``SKYLARK_NO_FUSED_CHUNKS``) — and
    ``overlap``: whether the fold rides async dispatch and syncs only
    at chunk boundaries (:mod:`~libskylark_tpu.streaming.overlap`;
    ``None`` defers to the default-on resolution, ``SKYLARK_NO_OVERLAP=1``
    kills it everywhere).  Overlap is bitwise-free: same blocks, same
    order, same IEEE accumulation — only the host's wait points move.

    ``checkpoint_every`` counts BATCHES per checkpoint round here.
    """

    def __init__(
        self, *, prefetch: int = 2, placer=device_placer,
        fused_chunks: bool | None = None, overlap: bool | None = None, **kw,
    ):
        super().__init__(**kw)
        self.prefetch = int(prefetch)
        self.placer = placer
        self.fused_chunks = fused_chunks
        self.overlap = overlap


def as_block_factory(source):
    """Normalize a batch source to ``factory(start_batch) -> iterator``.

    Callables pass through (they own the skip); iterables become a
    single-use factory that can only start at batch 0 — fine for a fresh
    pass, but resume needs a real factory.
    """
    if callable(source):
        return source
    state = {"used": False}

    def factory(start: int):
        if state["used"] or start:
            raise ValueError(
                "this source is a one-shot iterable and cannot be "
                f"re-opened (requested start batch {start}); pass a "
                "factory `lambda start: ...` for resumable streams"
            )
        state["used"] = True
        return iter(source)

    return factory


class _Cursor:
    """Lazily-opened, position-tracked view over the batch stream with a
    one-item lookahead (so ``is_done`` needs no side channel) and the
    prefetch pipeline wrapped around the remaining tail."""

    def __init__(self, factory, prefetch: int, placer):
        self._factory = factory
        self._prefetch = prefetch
        self._placer = placer
        self._it = None
        self._prefetcher = None
        self.pos = -1  # batch index of the lookahead item
        self.pending = None

    def ensure(self, at: int):
        if self._it is not None:
            if self.pos != at:
                raise RuntimeError(
                    f"stream cursor at batch {self.pos}, state wants {at}; "
                    "streaming passes must be driven sequentially"
                )
            return
        raw = iter(self._factory(at))
        if self._prefetch > 0:
            self._prefetcher = Prefetcher(
                raw, depth=self._prefetch, placer=self._placer
            )
            self._it = self._prefetcher
        elif self._placer is not None:
            self._it = (self._placer(b) for b in raw)
        else:
            self._it = raw
        self.pos = at - 1
        self.advance()

    def advance(self):
        try:
            self.pending = next(self._it)
        except StopIteration:
            self.pending = None
        self.pos += 1

    @property
    def stats(self):
        return self._prefetcher.stats if self._prefetcher is not None else None

    def close(self):
        if self._prefetcher is not None:
            self._prefetcher.close()


def _rows_of(acc):
    """Row count of a driver accumulator, when it carries one (the
    streaming drivers keep a ``"row"``/``"rows"`` bookkeeping leaf)."""
    if isinstance(acc, dict):
        for key in ("row", "rows"):
            if key in acc:
                try:
                    return int(acc[key])
                except (TypeError, ValueError):
                    return None
    return None


def skip_batches(it, k: int):
    """Drop the first ``k`` items — the generic (re-parse) skip for
    factories over non-seekable sources."""
    return islice(it, k, None)


def run_stream(
    source,
    step_fn,
    init_acc,
    params: StreamParams | None = None,
    *,
    kind: str = "streaming_pass",
    metadata: dict | None = None,
    fault_plan=None,
    report=None,
):
    """Fold ``step_fn`` over ``source`` with resilient checkpoints.

    Returns ``(acc, batches)``.  ``init_acc`` must be buildable without
    consuming the stream (fixed-shape reductions — the streaming drivers
    know their output shapes up front), because it doubles as the resume
    prototype the checkpoint is validated against.

    Guarding (``SKYLARK_GUARD``, on by default): sum-style accumulators
    absorb NaNs, so ONE finiteness probe per chunk — read at the chunk
    boundary, where the runner syncs anyway — observes a poisoned batch
    from anywhere inside the chunk.  When it trips, the chunk's
    accumulation REPLAYS from the chunk-entry accumulator over the
    buffered (clean) blocks instead of restarting the whole pass; a
    replay that stays non-finite raises ``NumericalHealthError``.  The
    clean-block buffer holds at most ``checkpoint_every`` batches and
    exists only while guarding is enabled.  ``report`` (a
    ``guard.RecoveryReport``) collects replay attempts for the caller's
    ``info["recovery"]``.
    """
    params = params or StreamParams()
    overlapped = _overlap.enabled(getattr(params, "overlap", None))
    cursor = _Cursor(
        as_block_factory(source), params.prefetch, params.placer
    )

    def init_state():
        return {"batch": np.asarray(0, np.int64), "acc": init_acc}

    def _entry_acc(state):
        acc = state["acc"]
        if plans.donation_enabled():
            # Donating step plans consume the accumulator buffers; the
            # runner still reads the chunk-entry state afterwards (the
            # divergence guard re-runs chunks from it), so snapshot it
            # once per chunk before the first donation can land.
            acc = plans.copy_for_donation(acc)
        return acc

    def _fold_chunk(state, k, sp):
        guarded = guard.enabled()
        b0 = int(state["batch"])
        cursor.ensure(b0)
        acc = _entry_acc(state)
        blocks = [] if guarded else None
        b = b0
        for _ in range(k):
            if cursor.pending is None:
                break
            block = cursor.pending
            if blocks is not None:
                blocks.append(block)
            if fault_plan is not None:
                block = fault_plan.corrupt_block(b, block)
            acc = step_fn(acc, block, b)
            if not overlapped:
                # Serial reference path: strictly alternate transfer and
                # compute (the bitwise comparison target of overlap runs).
                _overlap.step_sync(acc)
            b += 1
            cursor.advance()
        if overlapped and b > b0:
            # Overlap mode's ONE barrier per chunk: drain the device
            # queue before the guard sentinel reads the accumulator and
            # before the runner can checkpoint this state — a checkpoint
            # never captures an in-flight donated buffer.
            _overlap.chunk_sync(acc)
        if sp is not None:
            sp.attrs["batches"] = b - b0
            if guarded and b > b0:
                telemetry.inc("stream.sentinel_checks")
        if guarded and b > b0 and not guard.tree_all_finite(acc):
            # Chunk sentinel tripped: replay this chunk's fold from the
            # chunk-entry accumulator over the clean buffered blocks
            # (the faults above are one-shot, so the replay folds clean
            # data — same blocks, same order, bit-identical to an
            # unfaulted chunk).
            if report is not None:
                report.record(
                    "replay", chunk=b0,
                    detail="non-finite accumulator; re-folding chunk",
                )
            telemetry.inc("stream.replays")
            acc = _entry_acc(state)
            for j, block in enumerate(blocks):
                if fault_plan is not None:
                    block = fault_plan.corrupt_block(b0 + j, block)
                acc = step_fn(acc, block, b0 + j)
            if not guard.tree_all_finite(acc):
                raise guard.NumericalHealthError(
                    f"streaming accumulator non-finite after replay of "
                    f"batches [{b0}, {b})",
                    stage=kind,
                    report=report,
                )
            if report is not None:
                report.recovered = True
        return {"batch": np.asarray(b, np.int64), "acc": acc}

    def step_chunk(state, k):
        if not telemetry.enabled():
            return _fold_chunk(state, k, None)
        b0 = int(state["batch"])
        with telemetry.span("stream.chunk", kind=kind, chunk=b0) as sp:
            new_state = _fold_chunk(state, k, sp)
            # PhaseTimer discipline: sync the folded accumulator so the
            # span (and its derived rows/s) measures device time.
            sp.result = new_state["acc"]
            rows = _rows_of(new_state["acc"])
            if rows is not None:
                entry = _rows_of(state["acc"]) or 0
                sp.attrs["rows"] = rows - entry
        sp2 = sp.seconds
        if sp2 and sp.attrs.get("rows"):
            telemetry.inc("stream.rows", sp.attrs["rows"])
            telemetry.set_gauge(
                "stream.rows_per_s", round(sp.attrs["rows"] / sp2, 3)
            )
        telemetry.inc("stream.batches", sp.attrs.get("batches", 0))
        return new_state

    def is_done(state):
        cursor.ensure(int(state["batch"]))
        return cursor.pending is None

    solver = ChunkedSolver(
        init_state=init_state,
        step_chunk=step_chunk,
        extract_result=lambda state: (state["acc"], int(state["batch"])),
        is_done=is_done,
        iteration=lambda state: int(state["batch"]),
        kind=kind,
    )
    meta = dict(metadata or {})
    try:
        return ResilientRunner(
            solver, params, metadata=meta, fault_plan=fault_plan
        ).run()
    finally:
        st = cursor.stats
        if st is not None and telemetry.enabled():
            # Fold the pipeline's overlap evidence into the registry so
            # snapshot()'s prefetch_overlap survives the cursor teardown;
            # ``waits`` are the consumer-side backpressure stalls.
            telemetry.inc("prefetch.produced", st.produced)
            telemetry.inc("prefetch.consumed", st.consumed)
            telemetry.inc("prefetch.hits", st.hits)
            telemetry.inc("prefetch.waits", st.waits)
            # Time-weighted overlap evidence: producer_seconds is the
            # staging (parse + transfer-issue) cost, wait_seconds the
            # part the consumer stalled on — snapshot() derives the
            # compute-hidden transfer fraction from these two.
            telemetry.inc(
                "prefetch.producer_seconds", round(st.producer_seconds, 6)
            )
            telemetry.inc(
                "prefetch.wait_seconds", round(st.wait_seconds, 6)
            )
            gets = st.hits + st.waits
            telemetry.event(
                "stream", "prefetch",
                {
                    "kind": kind,
                    "produced": st.produced,
                    "consumed": st.consumed,
                    "hits": st.hits,
                    "waits": st.waits,
                    "producer_seconds": round(st.producer_seconds, 6),
                    "wait_seconds": round(st.wait_seconds, 6),
                    "overlapped": overlapped,
                    "overlap": round(st.hits / gets, 6) if gets else None,
                },
            )
        cursor.close()
