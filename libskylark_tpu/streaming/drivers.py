"""Streaming drivers: one-pass sketching and solvers over batch sources.

The out-of-core face of the sketch layer (≙ the reference's reason for
owning streaming LIBSVM/HDFS readers, ``utility/io/libsvm_io.hpp:1495-
1638``): every sketch here is a counter-addressed linear (or linear-then-
pointwise) map, so ``S·A`` decomposes exactly into per-batch partial
sketches (``SketchTransform.apply_slice``) merged by sum (COLUMNWISE) or
concat (ROWWISE) — datasets bigger than device memory stream through in
bounded space, with the prefetch pipeline overlapping host parse +
host→device transfer against the sketch compute of the previous batch.

Batch conventions (matching ``io.stream_libsvm`` / ``io.stream_hdf5``):

- :func:`sketch` consumes plain array blocks (rows of A);
- :func:`sketch_least_squares` and :func:`kernel_ridge` consume
  ``(X_block, y_block)`` pairs.

All three accept either an iterable or a re-openable factory
``f(start_batch) -> iterator`` (required for checkpoint/resume — see
``engine.as_block_factory``).
"""

from __future__ import annotations

import time

import jax.numpy as jnp
import numpy as np

from .. import guard, plans, telemetry
from ..sketch.base import Dimension
from .engine import StreamParams, run_stream
from .pipeline import BucketedBatch

__all__ = ["sketch", "sketch_batches", "sketch_least_squares", "kernel_ridge"]


def _unwrap(block):
    """(raw_block, true_rows) — transparent over ``bucketed_placer``'s
    host-padded batches."""
    if isinstance(block, BucketedBatch):
        return block.block, int(block.true_rows)
    return block, int(block.shape[0])


def _result_dtype(requested, default=None):
    if requested is not None:
        return jnp.dtype(requested)
    if default is not None:
        return jnp.dtype(default)
    import jax

    return jnp.dtype(jnp.float64 if jax.config.jax_enable_x64 else jnp.float32)


def sketch(
    source,
    S,
    dim: Dimension | str = Dimension.COLUMNWISE,
    *,
    ncols: int | None = None,
    dtype=None,
    params: StreamParams | None = None,
    fault_plan=None,
    partition=None,
):
    """One-pass ``S·A`` (COLUMNWISE) or ``A·Ωᵀ`` (ROWWISE) over row
    blocks of A, without ever materializing A.

    COLUMNWISE: blocks are consecutive row slices of the (N, m) input
    whose row counts sum to ``S.n``; ``ncols`` (= m) sizes the (S, m)
    accumulator up front (required — it doubles as the resume prototype).
    Partial sketches merge by sum, then ``S.finalize_slices`` (identity
    for linear sketches, the cos epilogue for RFT).  This path supports
    checkpoint/resume through ``StreamParams``: a killed pass resumed
    from its newest checkpoint is bit-for-bit the uninterrupted run.

    ROWWISE: blocks are row blocks of the (m, N) input (each carries the
    full feature axis); finished per-block sketches concatenate in
    stream order.  The output grows with the stream, so this path keeps
    no checkpointable fixed-shape state — ``params.checkpoint_dir`` is
    rejected; use :func:`sketch_batches` to keep the result out-of-core
    too.

    ``partition`` (a :class:`~libskylark_tpu.streaming.RowPartition`)
    routes to the multi-host elastic path (COLUMNWISE only): each
    process of the ``jax.distributed`` world folds its own row range
    and one psum merges — see ``docs/distributed_streaming.md``.
    """
    dim = Dimension.of(dim)
    if partition is not None:
        if dim is not Dimension.COLUMNWISE:
            raise ValueError(
                "distributed streaming is columnwise-only (rowwise "
                "output concatenates in stream order, which has no "
                "cross-rank merge)"
            )
        if ncols is None:
            raise ValueError(
                "columnwise streaming needs ncols (the width m of A) to "
                "size the (S, m) accumulator"
            )
        from .elastic import distributed_sketch

        return distributed_sketch(
            source, S, ncols=int(ncols), partition=partition, dtype=dtype,
            params=params, fault_plan=fault_plan,
        )
    params = params or StreamParams()
    if dim is Dimension.ROWWISE:
        if params.checkpoint_dir:
            raise ValueError(
                "rowwise streaming concatenates (no fixed-shape "
                "accumulator to checkpoint); stream columnwise or drop "
                "checkpoint_dir"
            )
        blocks = [
            Z for Z in sketch_batches(source, S, params=params)
        ]
        if not blocks:
            raise ValueError("empty stream: no rows to sketch")
        return jnp.concatenate(blocks, axis=0)

    if ncols is None:
        raise ValueError(
            "columnwise streaming needs ncols (the width m of A) to "
            "size the (S, m) accumulator"
        )
    dt = _result_dtype(dtype)
    init = {
        "sa": jnp.zeros((S.s, int(ncols)), dt),
        "row": np.asarray(0, np.int64),
    }

    def step(acc, block, index):
        row = int(acc["row"])
        block, k = _unwrap(block)
        return {
            "sa": plans.accumulate_slice(
                S, acc["sa"], block, row, true_rows=k,
                fused=params.fused_chunks,
            ),
            "row": np.asarray(row + k, np.int64),
        }

    report = guard.RecoveryReport(stage="streaming_sketch")
    acc, nbatches = run_stream(
        source, step, init, params, kind="streaming_sketch",
        fault_plan=fault_plan, report=report,
    )
    rows = int(acc["row"])
    if rows != S.n:
        raise ValueError(
            f"stream covered {rows} rows but the sketch domain is "
            f"{S.n}; the source and transform disagree"
        )
    out = S.finalize_slices(acc["sa"], Dimension.COLUMNWISE)
    if guard.enabled():
        guard.check_finite(out, "streaming_sketch", report=report)
    return out


def sketch_batches(source, S, *, params: StreamParams | None = None):
    """Generator of finished ROWWISE sketches, one per input block —
    the fully out-of-core form (input AND output streamed).  Each block
    goes through a bucketed plan (``plans.apply_rowwise_bucketed``): the
    counter-realized operands are hoisted once per process, ragged batch
    sizes pad up to the bucket ladder, and one executable per bucket
    serves the whole stream."""
    from .engine import as_block_factory
    from .pipeline import Prefetcher

    params = params or StreamParams()
    it = iter(as_block_factory(source)(0))
    pf = None
    if params.prefetch > 0:
        pf = Prefetcher(it, depth=params.prefetch, placer=params.placer)
        it = pf
    elif params.placer is not None:
        it = (params.placer(b) for b in it)
    try:
        for block in it:
            block, k = _unwrap(block)
            yield plans.apply_rowwise_bucketed(S, block, true_rows=k)
    finally:
        if pf is not None:
            pf.close()


def sketch_least_squares(
    source,
    S,
    *,
    ncols: int,
    targets: int = 1,
    alg: str = "qr",
    dtype=None,
    params: StreamParams | None = None,
    fault_plan=None,
    partition=None,
    policy_decision: dict | None = None,
):
    """Streaming sketch-and-solve least squares: accumulate the sketched
    system ``(S·A, S·b)`` over ``(A_block, b_block)`` batches in one
    pass, then solve the small (s, n) problem exactly.

    ``policy_decision`` (the adaptive policy's
    ``RouteDecision.to_dict()``, threaded down by
    ``linalg.streaming_least_squares``) lands in ``info["policy"]``
    *before* the terminal ``telemetry.run_summary`` — the ledgered
    summary and the returned ``info`` must carry identical keys.

    ``partition`` (a :class:`~libskylark_tpu.streaming.RowPartition`)
    routes to the multi-host elastic path: each process of the
    ``jax.distributed`` world folds its own row range, one psum merges
    the partials, guard verdicts psum so all ranks take the same ladder
    rung, and ``(x, info)`` is identical on every rank — see
    ``docs/distributed_streaming.md``.

    ≙ ``ApproximateLeastSquares`` (``nla/least_squares.hpp:42-184``) with
    the sketch applies decomposed over row blocks — A never resident.
    ``S`` must be a LINEAR sketch (JLT/CT/CWT/SJLT/MMT/WZT/FJLT-free
    slices...); a feature map (RFT) would not preserve the LS geometry.
    Returns ``(x, info)`` with
    ``info = {"rows", "batches", "seconds", "recovery"}``;
    ``info["recovery"]`` is the guard layer's recovery report (chunk
    replays, sketch certification, small-solve fallback — see
    ``docs/numerical_health.md``), ``{"guarded": False}``-shaped when
    ``SKYLARK_GUARD=0``.
    """
    from ..linalg.least_squares import exact_least_squares

    if partition is not None:
        from .elastic import distributed_sketch_least_squares

        return distributed_sketch_least_squares(
            source, S, ncols=int(ncols), partition=partition,
            targets=targets, alg=alg, dtype=dtype, params=params,
            fault_plan=fault_plan, policy_decision=policy_decision,
        )
    params = params or StreamParams()
    dt = _result_dtype(dtype)
    init = {
        "sa": jnp.zeros((S.s, int(ncols)), dt),
        "sb": jnp.zeros((S.s, int(targets)), dt),
        "row": np.asarray(0, np.int64),
    }

    def step(acc, batch, index):
        A_b, b_b = batch
        row = int(acc["row"])
        b2 = b_b[:, None] if getattr(b_b, "ndim", 1) == 1 else b_b
        return {
            "sa": plans.accumulate_slice(
                S, acc["sa"], A_b, row, fused=params.fused_chunks
            ),
            "sb": plans.accumulate_slice(
                S, acc["sb"], b2, row, fused=params.fused_chunks
            ),
            "row": np.asarray(row + A_b.shape[0], np.int64),
        }

    guarded = guard.enabled()
    report = (
        guard.RecoveryReport(stage="streaming_lsq")
        if guarded
        else guard.RecoveryReport.disabled("streaming_lsq")
    )
    t0 = time.perf_counter()
    acc, nbatches = run_stream(
        source, step, init, params, kind="streaming_lsq",
        fault_plan=fault_plan, report=report,
    )
    rows = int(acc["row"])
    if rows != S.n:
        raise ValueError(
            f"stream covered {rows} rows but the sketch domain is {S.n}"
        )
    SA = S.finalize_slices(acc["sa"], Dimension.COLUMNWISE)
    SB = S.finalize_slices(acc["sb"], Dimension.COLUMNWISE)
    if guarded:
        # A streaming sketch is fixed after its one pass — no resketch
        # rung exists here (that is the ladder's in-core privilege), so a
        # failed certificate degrades the SMALL solve to the SVD
        # pseudoinverse path, which is rank-deficiency-proof.
        cert = guard.certify_sketch(SA, stage="streaming_lsq")
        report.record(
            "initial", verdict=cert.verdict, detail=cert.detail,
            cond=cert.cond, sketch_size=int(SA.shape[0]),
        )
        if not cert.ok:
            alg = "svd"
            report.record(
                "fallback", verdict=guard.FALLBACK,
                detail="svd pseudoinverse small solve",
            )
            report.recovered = True
    X = exact_least_squares(SA, SB, alg=alg)
    if guarded:
        guard.check_finite(X, "streaming_lsq", report=report)
    x = X[:, 0] if targets == 1 else X
    seconds = time.perf_counter() - t0
    info = {"rows": rows, "batches": nbatches,
            "seconds": round(seconds, 6),
            "recovery": report.to_dict()}
    if policy_decision is not None:
        info["policy"] = policy_decision
    telemetry.run_summary("streaming_lsq", info)
    return x, info


def kernel_ridge(
    source,
    kernel,
    lam: float,
    s: int,
    context,
    *,
    targets: int = 1,
    krr_params=None,
    params: StreamParams | None = None,
    fault_plan=None,
    dtype=None,
):
    """Streaming approximate KRR: per-batch feature Gram accumulation.

    One pass over ``(X_block, y_block)`` batches maintains the (s, s)
    normal equations of ``approximate_kernel_ridge``:

        G += Z_bᵀ Z_b,   c += Z_bᵀ y_b,      Z_b = S(X_block)  rowwise

    then solves ``(G + λI) W = c`` once.  X is never resident; the
    feature map's counter-realized operands are hoisted once per pass.
    Returns the same ``FeatureMapModel`` as the in-core solver (trained
    on the same ``context`` seed it is allclose-interchangeable, modulo
    per-batch summation order).  ``model.info["recovery"]`` carries the
    guard layer's recovery report (chunk replays, Cholesky fallback).
    """
    from jax.scipy.linalg import cho_factor, cho_solve

    from ..ml.krr import KrrParams, _psd_gram, _tag
    from ..ml.model import FeatureMapModel
    from ..parallel.mesh import fully_replicated

    params = params or StreamParams()
    krr_params = krr_params or KrrParams()
    S = kernel.create_rft(s, _tag(krr_params), context)
    dt = _result_dtype(dtype)
    acc_dt = jnp.promote_types(dt, jnp.float32)
    init = {
        "g": jnp.zeros((s, s), acc_dt),
        "c": jnp.zeros((s, int(targets)), acc_dt),
        "rows": np.asarray(0, np.int64),
    }

    # One fixed-shape donated update per bucket: Z comes back padded with
    # its dead rows zeroed (pad_out=True), so the Gram/moment matmuls see
    # one shape per bucket and the (s, s) accumulators update in place
    # where the backend honors donation.
    def _update(g, c, Zp, y2p):
        return (
            g + _psd_gram(Zp.T, Zp).astype(acc_dt),
            c + (Zp.T @ y2p.astype(Zp.dtype)).astype(acc_dt),
        )

    update = plans.donating_jit(_update, donate_argnums=(0, 1))

    def step(acc, batch, index):
        X_b, y_b = batch
        y2 = y_b[:, None] if getattr(y_b, "ndim", 1) == 1 else y_b
        Zp, k = plans.apply_rowwise_bucketed(S, X_b, pad_out=True)
        y2 = jnp.asarray(y2)
        if Zp.shape[0] != y2.shape[0]:
            y2 = plans.pad_rows(y2, Zp.shape[0])
        g, c = update(acc["g"], acc["c"], Zp, y2)
        return {
            "g": g,
            "c": c,
            "rows": np.asarray(int(acc["rows"]) + X_b.shape[0], np.int64),
        }

    guarded = guard.enabled()
    report = (
        guard.RecoveryReport(stage="streaming_krr")
        if guarded
        else guard.RecoveryReport.disabled("streaming_krr")
    )
    acc, nbatches = run_stream(
        source, step, init, params, kind="streaming_krr",
        fault_plan=fault_plan, report=report,
    )
    G = fully_replicated(
        acc["g"] + jnp.asarray(lam, acc_dt) * jnp.eye(s, dtype=acc_dt)
    )
    c, low = cho_factor(G, lower=True)
    if guarded and not guard.tree_all_finite(c):
        # Singular/indefinite-by-rounding Gram: cho_factor NaNs silently;
        # degrade to the eigh pseudoinverse rung instead of returning a
        # poisoned model.
        W = guard.pinv_psd_solve(G, acc["c"]).astype(dt)
        report.record(
            "fallback", verdict=guard.FALLBACK,
            detail="non-finite Cholesky factor; eigh pseudoinverse solve",
        )
        report.recovered = True
    else:
        W = cho_solve((c, low), acc["c"]).astype(dt)
    if guarded:
        guard.check_finite(W, "streaming_krr", report=report)
    model = FeatureMapModel([S], W)
    model.info = {"rows": int(acc["rows"]), "batches": nbatches,
                  "recovery": report.to_dict()}
    telemetry.run_summary("streaming_krr", model.info)
    return model
