"""Prefetch pipeline: overlap host IO + host→device transfer with compute.

The reference overlaps IO and compute with MPI rank parallelism (rank 0
reads and ships chunks while workers sketch, ``ml/io.hpp:529-889``); a
single-process JAX program gets the same overlap from one background
thread plus JAX's async dispatch:

- a producer thread pulls batches from the source iterator (file parse /
  decompress — host work) and issues ``jax.device_put`` for each, which
  *starts* the host→device copy and returns immediately;
- a bounded queue (``depth`` slots) hands the staged batches to the
  consumer, so batch k+1's parse+transfer runs while the jitted sketch of
  batch k executes on device;
- the queue bound is the backpressure: the producer blocks once ``depth``
  batches are staged, keeping host memory at O(depth · batch) instead of
  O(stream).

``PrefetchStats`` records enough to *prove* the overlap (used by the
tier-1 smoke test and the micro-benchmark): ``hits`` counts consumer gets
that found a batch already staged (zero in a serialized pipeline).
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass, field
from typing import Any, NamedTuple

__all__ = [
    "Prefetcher",
    "PrefetchStats",
    "device_placer",
    "pinned_placer",
    "BucketedBatch",
    "bucketed_placer",
]


def device_placer(batch, device=None):
    """The ONE host→device staging path: start the transfer of every
    array leaf (async — returns as soon as the copies are issued).
    ``device`` pins the destination explicitly (elastic ranks pass their
    own addressable device so a multi-host pass never stages onto the
    implicit default); ``None`` keeps JAX's default placement."""
    import jax

    if device is None:
        return jax.device_put(batch)
    return jax.device_put(batch, device)


def pinned_placer(device):
    """A :func:`device_placer` bound to one destination device — the
    placer elastic ranks install so every staged batch lands on the
    rank's own chip."""

    def placer(batch):
        return device_placer(batch, device)

    return placer


class BucketedBatch(NamedTuple):
    """A staged batch padded up to the bucket ladder, with the real row
    count riding alongside (the streaming drivers unwrap it for row
    accounting; the plan layer treats the padded rows as exact zeros)."""

    block: Any
    true_rows: int


def bucketed_placer(gates: tuple = (), device=None):
    """Staging function that pads 2-D host batches up to the bucket
    ladder BEFORE the host→device transfer, so the copy itself — not
    just the compute — settles into one shape per ladder rung (the
    transfer of a ragged tail batch otherwise gets its own XLA transfer
    program).  Pass the consuming transform's ``batch_size_gates`` as
    ``gates`` so thin batches stay unpadded on the eager algorithm's
    side of a gate.  Non-2-D and sparse batches stage unpadded.  Both
    branches route through :func:`device_placer`, so ``device`` pinning
    behaves identically to the unbucketed path."""
    from .. import plans

    def placer(batch):
        if (
            getattr(batch, "ndim", 0) == 2
            and not hasattr(batch, "todense")
            and plans.enabled()
        ):
            padded, k = plans.pad_rows_to_bucket(batch, gates)
            return BucketedBatch(device_placer(padded, device), k)
        return device_placer(batch, device)

    return placer


@dataclass
class PrefetchStats:
    """Counters for pipeline introspection; ``hits``/``waits`` partition
    the consumer's ``get`` calls by whether a staged batch was ready,
    and ``wait_seconds`` totals the time those stalls actually cost —
    against ``producer_seconds`` it yields the compute-hidden transfer
    fraction (``telemetry.snapshot()["overlap_efficiency"]``)."""

    produced: int = 0
    consumed: int = 0
    hits: int = 0
    waits: int = 0
    producer_seconds: float = 0.0
    wait_seconds: float = 0.0
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)


class _Done:
    """Queue sentinel; carries the producer's exception if it died."""

    def __init__(self, error=None):
        self.error = error


class Prefetcher:
    """Iterator wrapper: stage up to ``depth`` batches ahead of consumption.

    ``placer`` maps each raw batch to its staged form (default:
    :func:`device_placer`); pass ``placer=None`` to stage raw batches
    (pure IO prefetch).  Always either exhaust the iterator or call
    :meth:`close` (it is also a context manager) so the producer thread
    is released.
    """

    def __init__(self, source, depth: int = 2, placer=device_placer):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self._source = iter(source)
        self._placer = placer
        self._queue: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self.stats = PrefetchStats()
        self._finished = False
        self._thread = threading.Thread(
            target=self._produce, name="skylark-prefetch", daemon=True
        )
        self._thread.start()

    def _produce(self):
        import time

        try:
            for batch in self._source:
                if self._stop.is_set():
                    return
                t0 = time.perf_counter()
                staged = batch if self._placer is None else self._placer(batch)
                with self.stats._lock:
                    self.stats.produced += 1
                    self.stats.producer_seconds += time.perf_counter() - t0
                # put() blocks when `depth` batches are staged: backpressure.
                while not self._stop.is_set():
                    try:
                        self._queue.put(staged, timeout=0.1)
                        break
                    except queue.Full:
                        continue
            self._queue.put(_Done())
        except BaseException as e:  # noqa: BLE001 — surfaced to the consumer
            try:
                self._queue.put(_Done(e), timeout=1.0)
            except queue.Full:
                pass

    def __iter__(self):
        return self

    def __next__(self):
        import time

        if self._finished:
            raise StopIteration
        waited = 0.0
        try:
            item = self._queue.get_nowait()
            ready = True
        except queue.Empty:
            t0 = time.perf_counter()
            item = self._queue.get()
            waited = time.perf_counter() - t0
            ready = False
        with self.stats._lock:
            if ready:
                self.stats.hits += 1
            else:
                self.stats.waits += 1
                self.stats.wait_seconds += waited
        if isinstance(item, _Done):
            self._finished = True
            if item.error is not None:
                raise item.error
            raise StopIteration
        with self.stats._lock:
            self.stats.consumed += 1
        return item

    def close(self):
        """Stop the producer and drop staged batches (idempotent)."""
        self._stop.set()
        self._finished = True
        while True:
            try:
                self._queue.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=5.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
