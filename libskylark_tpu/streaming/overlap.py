"""Async device-overlap discipline for the streaming fold.

JAX dispatch is asynchronous: ``step_fn(acc, block, b)`` returns a
future-backed accumulator as soon as the work is *enqueued*, so while
chunk k's fused sketch-accumulate executes on device, the prefetch
thread's ``jax.device_put`` for chunk k+1 (``pipeline.Prefetcher``)
runs its host→device copy concurrently — the device-level analogue of
the reference's asynchronous solver tier (AsyRGS/AsyFCG in
``algorithms/``).  The engine's job is therefore NOT to create overlap
but to place the synchronization points that bound it:

- **overlap mode** (default): the fold never blocks mid-chunk; one
  :func:`chunk_sync` at the chunk boundary drains the device queue
  before the guard sentinel reads the accumulator and before the
  resilient runner captures the state for a checkpoint.  Donating step
  plans ping-pong between two physical buffers (the chunk-entry
  snapshot ``plans.copy_for_donation`` takes plus the donated step
  output), and the boundary sync guarantees a checkpoint never
  serializes an in-flight donated buffer.
- **serial mode** (``SKYLARK_NO_OVERLAP=1`` or
  ``StreamParams(overlap=False)``): :func:`step_sync` blocks after
  EVERY step, so transfer and compute strictly alternate — the
  reference path overlap runs are compared against.

Both modes fold the same blocks in the same order with the same IEEE
accumulation order — overlap changes *when* the host waits, never what
the device computes — so overlapped ≡ serial is bitwise by
construction (asserted over every hash sketch type in
``tests/test_overlap.py``).

Overlap efficiency is derived from the pipeline stats the engine folds
into telemetry at stream close: ``producer_seconds`` is the staging
(parse + transfer-issue) time, ``wait_seconds`` the part of it the
consumer actually stalled on — so ``1 - wait/producer`` is the
compute-hidden transfer fraction (``snapshot()["overlap_efficiency"]``).
"""

from __future__ import annotations

import os
import time

from .. import telemetry

__all__ = ["enabled", "step_sync", "chunk_sync"]


def enabled(flag: bool | None = None) -> bool:
    """Resolve the overlap knob: the ``SKYLARK_NO_OVERLAP=1`` kill
    switch wins over everything, then an explicit
    ``StreamParams(overlap=)`` value, then the default — ON (overlap is
    bitwise-free, so there is no accuracy reason to serialize)."""
    if os.environ.get("SKYLARK_NO_OVERLAP", "0") == "1":
        return False
    if flag is None:
        return True
    return bool(flag)


def step_sync(acc):
    """Serial-mode barrier: block until this step's accumulator is
    materialized before touching the next batch."""
    import jax

    jax.block_until_ready(acc)
    return acc


def chunk_sync(acc):
    """Overlap-mode boundary barrier: drain the device queue once per
    chunk — before the guard sentinel reads the accumulator and before
    the runner checkpoints the state — and record how long the host
    actually waited (``stream.sync_seconds``; near-zero when transfers
    hid behind compute)."""
    import jax

    t0 = time.perf_counter()
    jax.block_until_ready(acc)
    if telemetry.enabled():
        telemetry.inc("stream.sync_chunks")
        telemetry.inc(
            "stream.sync_seconds", round(time.perf_counter() - t0, 6)
        )
    return acc
