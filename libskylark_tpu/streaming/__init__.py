"""Out-of-core streaming sketching engine (no single reference
counterpart — this is the consumer half the reference's streaming IO
layer implies: ``utility/io/libsvm_io.hpp:1495-1638`` reads bounded
batches, and every counter-addressed sketch decomposes exactly over
them).

- ``pipeline``: double-buffered host→device prefetch (bounded queue,
  backpressure, overlap proof counters)
- ``engine``: the checkpointable accumulation fold, riding the
  ``resilient`` runtime (resume is bit-for-bit)
- ``drivers``: one-pass ``sketch`` (S·A / A·Ωᵀ), streaming
  sketch-and-solve least squares, streaming KRR Gram accumulation
- ``elastic``: the multi-host face — each rank of a ``jax.distributed``
  world folds its deterministic row range (``RowPartition``) with
  per-host checkpoints + a JSONL progress ledger, merges by psum, and
  resumes elastically (``docs/distributed_streaming.md``)

See ``docs/streaming.md`` for the partial-sketch math and the merge
rules; the transform-side protocol is ``SketchTransform.apply_slice`` /
``finalize_slices`` (``sketch/base.py``).
"""

from .drivers import kernel_ridge, sketch, sketch_batches, sketch_least_squares
from .elastic import (
    ElasticParams,
    HostLedger,
    RowPartition,
    distributed_sketch,
    distributed_sketch_least_squares,
    elastic_run_stream,
    host_dir,
    read_progress,
    world_info,
)
from .engine import StreamParams, as_block_factory, run_stream, skip_batches
from .pipeline import Prefetcher, PrefetchStats, device_placer, pinned_placer
from .repartition import (
    ResumePlan,
    execute_rank_plan,
    read_epoch,
    replan_resume,
    resolve_resume,
)

__all__ = [
    "sketch",
    "sketch_batches",
    "sketch_least_squares",
    "kernel_ridge",
    "StreamParams",
    "run_stream",
    "as_block_factory",
    "skip_batches",
    "Prefetcher",
    "PrefetchStats",
    "device_placer",
    "pinned_placer",
    "ElasticParams",
    "RowPartition",
    "HostLedger",
    "read_progress",
    "world_info",
    "host_dir",
    "elastic_run_stream",
    "distributed_sketch",
    "distributed_sketch_least_squares",
    "ResumePlan",
    "replan_resume",
    "resolve_resume",
    "execute_rank_plan",
    "read_epoch",
]
