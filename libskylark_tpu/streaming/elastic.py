"""Multi-host elastic streaming: preemption-safe out-of-core sketching
across a ``jax.distributed`` world.

The reference distributes sketching over MPI (CombBLAS/Elemental) under
a fail-stop model: any rank failure restarts the whole job.  Here the
stream itself is sharded and rank loss is a LOCAL replay:

- :class:`RowPartition` assigns each host a deterministic, contiguous
  batch range of the global stream (derived from ``(nrows, batch_rows,
  world_size)`` alone, so every process — and every restart — computes
  the same split without communication).
- Each host folds its range through the unchanged single-process
  :func:`~libskylark_tpu.streaming.engine.run_stream` engine, with the
  accumulator's row cursor started at the host's global row offset: the
  counter contract makes the partial sketch operands identical to what
  an unsharded pass would realize for those rows (columnwise ``S·A`` is
  a SUM of window applies — ``apply_slice``).
- Partials merge with ONE cross-process psum
  (:func:`~libskylark_tpu.parallel.collectives.cross_host_psum`), then
  ``finalize_slices`` runs on the merged sum (identity for linear
  sketches, the RFT epilogue otherwise).

Robustness model: each host owns a private subdirectory of the shared
checkpoint root — ``host-<rank:05d>/`` holding its ``CheckpointStore``
slots, a ``manifest.json`` (world size, row partition, epoch, kind) and
a ``progress.jsonl`` ledger in the telemetry run-ledger schema (``{ts,
seq, pid, kind, name, attrs}``).  SIGKILL one rank mid-stream, restart
the world with ``resume=True``, and every rank reloads its own newest
checkpoint: the killed rank re-folds only its uncheckpointed batches
(bit-identically — same blocks, same order), the survivors re-fold
nothing, and the merged result is bit-for-bit the uninterrupted run's.
Resuming under a DIFFERENT world size or row partition is detected two
ways — the on-disk manifest check and a pre-fold allgather handshake of
``(world, partition signature, epoch, kind)`` — and fails fast with
:class:`~libskylark_tpu.utils.exceptions.WorldMismatchError` (code 109)
instead of silently merging stale partials.

Elastic-resize layer (``resume_policy="repartition"``): instead of the
109 fail-fast, the drivers hand the mismatched root to
``streaming.repartition`` — durable partial-sketch checkpoints from the
old world are adopted as-is (linearity of the counter-addressed sum)
and only the never-committed batches re-fold, under a bumped **epoch**
that fences the old world's stragglers out
(:class:`~libskylark_tpu.utils.exceptions.StaleEpochError`, code 111).
Epoch ``e > 0`` state lives under ``epoch-<e:04d>/host-<rank:05d>/``;
the bare layout is epoch 0, so pre-repartition roots read unchanged.
Collectives are deadline-bounded by
:class:`~libskylark_tpu.parallel.collectives.CollectiveWatchdog` when
``collective_timeout_s`` (or ``SKYLARK_COLLECTIVE_TIMEOUT_S``) is set —
a hung peer raises
:class:`~libskylark_tpu.utils.exceptions.CollectiveTimeoutError` (code
110) with heartbeat-derived straggler evidence instead of blocking the
world forever.  See ``docs/distributed_streaming.md``.
"""

from __future__ import annotations

import json
import os
import time
import zlib
from dataclasses import dataclass
from itertools import islice

import numpy as np

from .. import guard, telemetry
from ..utils.exceptions import (
    InvalidParameters,
    StaleEpochError,
    WorldMismatchError,
)
from .engine import StreamParams, as_block_factory, run_stream
from .pipeline import device_placer, pinned_placer

__all__ = [
    "RowPartition",
    "ElasticParams",
    "HostLedger",
    "read_progress",
    "world_info",
    "host_dir",
    "elastic_run_stream",
    "distributed_sketch",
    "distributed_sketch_least_squares",
]

MANIFEST_NAME = "manifest.json"
PROGRESS_NAME = "progress.jsonl"
_MANIFEST_VERSION = 1


def world_info() -> tuple[int, int]:
    """``(rank, world_size)`` of the current process.

    Reads ``jax.process_index()/process_count()`` — ``(0, 1)`` in an
    uninitialized (single-process) runtime, so single-process code paths
    need no special casing.
    """
    import jax

    return int(jax.process_index()), int(jax.process_count())


@dataclass(frozen=True)
class RowPartition:
    """Deterministic contiguous split of a batched row stream over ranks.

    The global stream is ``num_batches = ceil(nrows / batch_rows)``
    batches of ``batch_rows`` rows (last batch ragged).  Rank ``r`` owns
    batches ``[batch_range(r))`` — balanced contiguous ranges, the first
    ``num_batches % world_size`` ranks taking one extra — and therefore
    rows ``[row_range(r))``.  Pure arithmetic on ``(nrows, batch_rows,
    world_size)``: every process computes the identical split, which is
    what makes restarted ranks re-address the same counter windows.
    """

    nrows: int
    batch_rows: int
    world_size: int

    def __post_init__(self):
        for name in ("nrows", "batch_rows", "world_size"):
            v = getattr(self, name)
            if int(v) != v or int(v) < 1:
                raise InvalidParameters(
                    f"RowPartition.{name} must be a positive int, got {v!r}"
                )
            object.__setattr__(self, name, int(v))

    @property
    def num_batches(self) -> int:
        return -(-self.nrows // self.batch_rows)

    def batch_range(self, rank: int) -> tuple[int, int]:
        """Global batch indices ``[start, end)`` owned by ``rank``."""
        if not 0 <= rank < self.world_size:
            raise InvalidParameters(
                f"rank {rank} outside world of {self.world_size}"
            )
        base, extra = divmod(self.num_batches, self.world_size)
        start = rank * base + min(rank, extra)
        return start, start + base + (1 if rank < extra else 0)

    def row_range(self, rank: int) -> tuple[int, int]:
        """Global row indices ``[start, end)`` owned by ``rank``."""
        b0, b1 = self.batch_range(rank)
        return (
            b0 * self.batch_rows,
            min(b1 * self.batch_rows, self.nrows),
        )

    def to_json(self) -> dict:
        return {
            "nrows": self.nrows,
            "batch_rows": self.batch_rows,
            "world_size": self.world_size,
        }

    @classmethod
    def from_json(cls, d: dict) -> "RowPartition":
        return cls(
            nrows=d["nrows"],
            batch_rows=d["batch_rows"],
            world_size=d["world_size"],
        )

    def signature(self) -> int:
        """CRC32 of the canonical JSON — the partition's identity in
        manifests and the barrier handshake."""
        payload = json.dumps(self.to_json(), sort_keys=True).encode()
        return zlib.crc32(payload)

    def validate_world(self, rank: int, world_size: int) -> None:
        """Fail fast (code 109) when the resolved world disagrees with
        this partition — the resume-under-a-different-world guard."""
        if world_size != self.world_size:
            raise WorldMismatchError(
                f"stream partitioned for world size {self.world_size} "
                f"but this process resolves a world of {world_size}; "
                "repartition (and restart from scratch) instead of "
                "merging mismatched partials",
                expected=self.world_size,
                got=world_size,
            )
        if not 0 <= rank < world_size:
            raise WorldMismatchError(
                f"rank {rank} outside world of {world_size}",
                expected=f"0 <= rank < {world_size}",
                got=rank,
            )


class ElasticParams(StreamParams):
    """:class:`~libskylark_tpu.streaming.StreamParams` plus the world
    overrides of an elastic pass.

    ``rank``/``world_size`` default to the live ``jax.distributed``
    world (:func:`world_info`); tests override them to exercise a
    simulated rank's local fold — manifest, ledger and partition checks
    included — inside one process.  ``checkpoint_dir`` is the SHARED
    root; each rank derives its private ``host-<rank:05d>/`` under it.

    ``resume_policy`` decides what a resume does when the on-disk state
    was written for a DIFFERENT world/partition: ``"strict"`` (default)
    fails fast with code 109 exactly as before; ``"repartition"`` adopts
    the old world's durable partials and re-folds only the uncommitted
    batches (``streaming.repartition``).  ``collective_timeout_s``
    deadline-bounds the handshake and merge collectives (code 110 on
    expiry; ``None`` = blocking, env ``SKYLARK_COLLECTIVE_TIMEOUT_S``
    applies when unset).
    """

    def __init__(
        self,
        *,
        rank: int | None = None,
        world_size: int | None = None,
        resume_policy: str = "strict",
        collective_timeout_s: float | None = None,
        **kw,
    ):
        super().__init__(**kw)
        self.rank = rank
        self.world_size = world_size
        if resume_policy not in ("strict", "repartition"):
            raise InvalidParameters(
                f"resume_policy must be 'strict' or 'repartition', got "
                f"{resume_policy!r}"
            )
        self.resume_policy = resume_policy
        self.collective_timeout_s = collective_timeout_s


def _resolve_world(params) -> tuple[int, int]:
    live_rank, live_world = world_info()
    rank = getattr(params, "rank", None)
    world = getattr(params, "world_size", None)
    return (
        live_rank if rank is None else int(rank),
        live_world if world is None else int(world),
    )


def host_dir(root, rank: int, epoch: int = 0) -> str:
    """The per-host state directory under the shared checkpoint root.

    Epoch 0 keeps the bare pre-repartition layout (``host-<rank>/``
    directly under the root); repartitioned epochs namespace their state
    under ``epoch-<e:04d>/`` so a new world never overwrites the old
    world's durable partials while it is still merging them.
    """
    base = str(root)
    if int(epoch) > 0:
        base = os.path.join(base, f"epoch-{int(epoch):04d}")
    return os.path.join(base, f"host-{int(rank):05d}")


class HostLedger:
    """Per-host JSONL progress ledger, one record per FOLDED batch.

    Rides the telemetry run-ledger schema (``{ts, seq, pid, kind, name,
    attrs}``, ``kind="elastic"``) so the same tooling reads both.  Lines
    are flushed per record: after a SIGKILL the file shows exactly which
    batches this incarnation folded (at most one torn trailing line,
    which :func:`read_progress` skips).  ``seq`` continues from the
    existing file so restart records stay totally ordered per host.

    ``fence`` (optional zero-arg callable) runs before every record —
    the elastic layer passes the epoch fence, so a writer from a world
    that has since repartitioned dies with
    :class:`~libskylark_tpu.utils.exceptions.StaleEpochError` at its
    next ledger write instead of silently mutating superseded state.
    """

    def __init__(self, path, *, rank: int, epoch: int = 0, fence=None):
        self.path = str(path)
        self.rank = int(rank)
        self.epoch = int(epoch)
        self.fence = fence
        self._seq = 0
        for rec in read_progress(self.path):
            self._seq = max(self._seq, int(rec.get("seq", 0)))
        self._fh = open(self.path, "a", encoding="utf-8")

    def record(self, name: str, **attrs) -> int:
        if self.fence is not None:
            self.fence()
        self._seq += 1
        rec = {
            "ts": round(time.time(), 6),
            "seq": self._seq,
            "pid": os.getpid(),
            "kind": "elastic",
            "name": name,
            "attrs": {"rank": self.rank, "epoch": self.epoch, **attrs},
        }
        self._fh.write(json.dumps(rec) + "\n")
        self._fh.flush()
        return self._seq

    def close(self) -> None:
        try:
            self._fh.close()
        except OSError:
            pass


def read_progress(path) -> list[dict]:
    """Parse a ``progress.jsonl`` — tolerant of the torn trailing line a
    SIGKILL mid-write can leave.  Missing file → ``[]``.

    Hardened against duplicate / out-of-order ``seq`` entries (a crash
    during a guard replay can append the same batch record twice, and a
    hostile host can interleave epochs): records are deduplicated by
    ``(epoch, seq)`` — keeping the LAST occurrence, the rewrite wins —
    and returned ordered by ``(epoch, seq)``.  Records without a usable
    ``seq`` are kept in file order after the sequenced ones.
    """
    sequenced: dict[tuple[int, int], dict] = {}
    stray = []
    try:
        # errors="replace": a torn tail can end mid-UTF-8-sequence; the
        # mangled line must fail json.loads (and be skipped), not abort
        # the whole read with UnicodeDecodeError.
        with open(path, encoding="utf-8", errors="replace") as fh:
            for line in fh:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if not isinstance(rec, dict):
                    continue
                try:
                    attrs = rec.get("attrs") or {}
                    key = (int(attrs.get("epoch", 0)), int(rec["seq"]))
                except (AttributeError, KeyError, TypeError, ValueError):
                    stray.append(rec)
                    continue
                sequenced[key] = rec
    except OSError:
        pass
    return [sequenced[k] for k in sorted(sequenced)] + stray


def _manifest_payload(partition, rank, kind, epoch) -> dict:
    return {
        "skylark_object_type": "elastic_manifest",
        "format_version": _MANIFEST_VERSION,
        "kind": str(kind),
        "epoch": int(epoch),
        "rank": int(rank),
        "partition": partition.to_json(),
        "signature": partition.signature(),
    }


def _check_manifest(hdir, partition, rank, kind, epoch, resume) -> None:
    """Verify (on resume) then (re)write the per-host manifest.

    The manifest is the on-disk half of the mismatch guard: checkpoints
    under this directory were written for exactly one ``(partition,
    rank, kind)``; resuming under any other raises code 109 BEFORE a
    stale slot can be loaded into a differently-partitioned fold.
    """
    os.makedirs(hdir, exist_ok=True)
    path = os.path.join(hdir, MANIFEST_NAME)
    want = _manifest_payload(partition, rank, kind, epoch)
    if resume and os.path.exists(path):
        try:
            with open(path, encoding="utf-8") as fh:
                have = json.load(fh)
        except (OSError, UnicodeDecodeError, json.JSONDecodeError) as e:
            # UnicodeDecodeError: corrupt-at-rest manifests are arbitrary
            # bytes, which fail at decode before json.load sees them.
            raise WorldMismatchError(
                f"unreadable elastic manifest {path}: {e}; the host "
                "directory cannot be certified against this partition",
                expected=want,
                got=None,
            )
        for key in ("kind", "rank", "partition", "signature"):
            if have.get(key) != want[key]:
                raise WorldMismatchError(
                    "elastic resume mismatch: checkpoint state in "
                    f"{hdir} was written for {key}={have.get(key)!r}, "
                    f"this run wants {key}={want[key]!r} (world size or "
                    "row partition changed; restart from scratch)",
                    expected={k: have.get(k) for k in ("kind", "rank",
                                                       "partition",
                                                       "signature")},
                    got={k: want[k] for k in ("kind", "rank", "partition",
                                              "signature")},
                )
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(want, fh)
    os.replace(tmp, path)


def _epoch_fence(root, epoch: int):
    """A zero-arg callable that raises
    :class:`~libskylark_tpu.utils.exceptions.StaleEpochError` (111) when
    the shared root's epoch marker has advanced past ``epoch`` — i.e.
    the world repartitioned without this process.  Installed on the host
    ledger (checked before every record, which precedes every commit) so
    a stale writer dies before it can mutate superseded state."""
    from .repartition import read_epoch

    root = str(root)
    epoch = int(epoch)

    def fence():
        est = read_epoch(root)
        if est is not None and int(est.get("epoch", 0)) > epoch:
            if telemetry.enabled():
                telemetry.inc("elastic.fenced")
                telemetry.event(
                    "elastic", "fenced",
                    {"epoch": epoch, "root_epoch": int(est["epoch"])},
                )
            raise StaleEpochError(
                f"this writer runs at elastic epoch {epoch} but the "
                f"shared root advanced to epoch {est.get('epoch')}: the "
                "world repartitioned past this process; its partials "
                "are superseded and must not be written",
                expected=epoch,
                got=int(est.get("epoch", 0)),
            )

    return fence


def _make_watchdog(params, root, rank, world, epoch):
    """Build the collective watchdog for a real multi-process world (a
    single process has no peers to wait on — and must not pay file
    writes the pre-watchdog code never made)."""
    import jax

    if jax.process_count() <= 1:
        return None
    from ..parallel.collectives import CollectiveWatchdog

    return CollectiveWatchdog(
        root,
        rank=rank,
        world=world,
        epoch=epoch,
        deadline_s=getattr(params, "collective_timeout_s", None),
    )


def _handshake(
    partition, rank, world, kind, epoch, extra: int = 0, watchdog=None
) -> None:
    """Barrier/epoch handshake: every live process allgathers its
    ``(world, partition signature, epoch, kind crc)`` tuple and checks
    the others'.  A drifted rank (stale restart script, wrong epoch,
    different partition constants) is detected by EVERY rank before any
    work or merge happens — and the allgather doubles as the barrier
    that keeps a fast rank from merging before a slow one joined.

    Single-process worlds (including simulated-rank tests) skip the
    collective — there is nobody to disagree with.

    ``extra`` folds one more world-deterministic word into the gathered
    tuple (the repartition path passes the plan CRC, so ranks that
    somehow derived different recovery plans fail here, before any
    merge).  ``watchdog`` deadline-bounds the allgather — a peer that
    never arrives raises code 110 instead of hanging the handshake.
    """
    import jax

    if jax.process_count() <= 1:
        return
    from jax.experimental import multihost_utils

    mine = np.asarray(
        [
            int(world),
            int(partition.signature()),
            int(epoch),
            zlib.crc32(str(kind).encode()),
            int(extra) & 0xFFFFFFFF,
        ],
        np.int64,
    )

    def _gather():
        return np.atleast_2d(
            np.asarray(multihost_utils.process_allgather(mine))
        )

    # Straggler attribution: the allgather IS the barrier, so its wall
    # time is this rank's wait-for-peers; the fastest-arriving rank
    # waits longest and the straggler's own wait is ~0.
    t_wait = time.monotonic() if telemetry.enabled() else None
    if watchdog is not None:
        theirs = watchdog.guard("handshake", _gather)
    else:
        theirs = _gather()
    if t_wait is not None:
        wait_ms = (time.monotonic() - t_wait) * 1e3
        telemetry.observe_phase("collective_wait", wait_ms)
        telemetry.set_gauge("collective.last_wait_ms", round(wait_ms, 4))
        telemetry.set_gauge("collective.rank", rank)
    for r in range(theirs.shape[0]):
        if not np.array_equal(theirs[r], mine):
            raise WorldMismatchError(
                f"elastic handshake failed: rank {rank} sees (world, "
                f"partition, epoch, kind) = {mine.tolist()} but process "
                f"{r} announced {theirs[r].tolist()}; refusing to merge "
                "across mismatched worlds",
                expected=mine.tolist(),
                got=theirs[r].tolist(),
            )
    if telemetry.enabled():
        telemetry.event(
            "elastic", "handshake",
            {"rank": rank, "world": world, "epoch": int(epoch),
             "signature": int(partition.signature()), "kind": kind},
        )


def _local_params(params, hdir, expect_epoch: int | None = None) -> StreamParams:
    """This rank's private view of the shared params: same knobs, but
    checkpoints under the rank's host directory (and restores pinned to
    the rank's elastic epoch when one is set).  The default placer is
    re-bound to the rank's own first addressable device so staged
    batches land on this rank's chip, never the implicit process
    default; a caller-supplied placer is kept verbatim."""
    placer = params.placer
    if placer is device_placer:
        import jax

        local = jax.local_devices()
        if local:
            placer = pinned_placer(local[0])
    return StreamParams(
        prefetch=params.prefetch,
        placer=placer,
        expect_epoch=expect_epoch,
        fused_chunks=getattr(params, "fused_chunks", None),
        overlap=getattr(params, "overlap", None),
        checkpoint_dir=hdir,
        checkpoint_every=params.checkpoint_every,
        keep_last=params.keep_last,
        resume=params.resume,
        io_retries=params.io_retries,
        io_backoff=params.io_backoff,
        check_divergence=params.check_divergence,
        max_chunks=params.max_chunks,
        am_i_printing=params.am_i_printing,
        log_level=params.log_level,
        prefix=params.prefix,
        debug_level=params.debug_level,
        log_stream=params.log_stream,
    )


def elastic_run_stream(
    source,
    step_fn,
    init_acc,
    partition: RowPartition,
    params: ElasticParams | StreamParams | None = None,
    *,
    kind: str = "elastic_pass",
    metadata: dict | None = None,
    fault_plan=None,
    report=None,
    epoch: int = 0,
):
    """This rank's share of a partitioned stream fold.

    ``source`` is the GLOBAL batch factory (``f(start_batch) ->
    iterator`` over all ``partition.num_batches`` batches — the same
    factory every rank gets); the rank's window is carved out here with
    a seek-and-bound (``factory(global_start)`` + ``islice``), riding
    the ``io/source.py`` byte-source seam: factories over seekable
    stores skip in O(1), line-parsed ones re-parse the prefix.

    ``step_fn(acc, block, local_index)`` sees LOCAL batch indices
    ``0..nlocal-1`` (checkpoint/resume and fault-plan indices are local
    to the rank); global addressing lives in the accumulator's row
    cursor, which the caller must start at the rank's global row offset
    (the distributed drivers do).

    Returns ``(acc, local_batches)`` — the UNMERGED partial.  Callers
    merge float accumulators via ``parallel.cross_host_psum`` and
    validate row counts themselves.  Raises
    :class:`~libskylark_tpu.utils.exceptions.WorldMismatchError` (code
    109) when the resolved world disagrees with ``partition``, when the
    on-disk manifest was written for a different partition, or when the
    pre-fold handshake sees a drifted rank.
    """
    params = params or ElasticParams()
    rank, world = _resolve_world(params)
    partition.validate_world(rank, world)
    start_b, end_b = partition.batch_range(rank)
    nlocal = end_b - start_b
    global_factory = as_block_factory(source)

    def local_factory(local_start: int):
        if not 0 <= local_start <= nlocal:
            raise ValueError(
                f"local start batch {local_start} outside this rank's "
                f"range of {nlocal} batches"
            )
        return islice(
            iter(global_factory(start_b + local_start)),
            nlocal - local_start,
        )

    ledger = None
    fence = None
    watchdog = None
    local_params = _local_params(params, None)
    if params.checkpoint_dir:
        root = params.checkpoint_dir
        fence = _epoch_fence(root, epoch)
        fence()  # a stale incarnation dies before touching any state
        hdir = host_dir(root, rank, epoch)
        _check_manifest(hdir, partition, rank, kind, epoch, params.resume)
        local_params = _local_params(params, hdir, expect_epoch=epoch)
        ledger = HostLedger(
            os.path.join(hdir, PROGRESS_NAME), rank=rank, epoch=epoch,
            fence=fence,
        )
        watchdog = _make_watchdog(params, root, rank, world, epoch)
        if fault_plan is not None and hasattr(fault_plan, "bind_host"):
            fault_plan.bind_host(hdir=hdir, root=str(root), epoch=epoch)

    host_hooks = fault_plan is not None and hasattr(fault_plan, "before_batch")
    step = step_fn
    if ledger is not None or host_hooks:
        last = {"b": -1}

        def step(acc, block, b):
            if host_hooks:
                fault_plan.before_batch(b)
            out = step_fn(acc, block, b)
            # Ledgered at FOLD time (not at prefetch), once per index:
            # a guard replay re-folds the same indices and must not
            # double-count the batch.
            if ledger is not None and b > last["b"]:
                ledger.record("batch", batch=int(start_b + b), local=int(b))
                last["b"] = b
            return out

    _handshake(partition, rank, world, kind, epoch, watchdog=watchdog)
    if telemetry.enabled():
        r0, r1 = partition.row_range(rank)
        telemetry.inc("elastic.runs")
        telemetry.event(
            "elastic", "partition",
            {"kind": kind, "rank": rank, "world": world, "epoch": int(epoch),
             "batches": [start_b, end_b], "rows": [r0, r1],
             "signature": int(partition.signature())},
        )
    meta = dict(metadata or {})
    meta.update(
        elastic={"rank": rank, "world": world, "epoch": int(epoch),
                 "signature": int(partition.signature())}
    )
    acc, nbatches = run_stream(
        local_factory, step, init_acc, local_params, kind=kind,
        metadata=meta, fault_plan=fault_plan, report=report,
    )
    if ledger is not None:
        ledger.record("done", batches=int(nbatches))
        ledger.close()
    return acc, nbatches


def _require_real_world(partition) -> None:
    """The distributed drivers MERGE across processes, so a simulated
    (single-process, world_size > 1) configuration would silently return
    an unmerged partial as if it were the global result.  Simulated-rank
    tests fold through :func:`elastic_run_stream` and merge by hand."""
    import jax

    if partition.world_size != jax.process_count():
        raise InvalidParameters(
            f"distributed drivers need a live jax.distributed world of "
            f"{partition.world_size} processes (found "
            f"{jax.process_count()}); for simulated ranks use "
            "elastic_run_stream and merge partials explicitly"
        )


def distributed_sketch(
    source,
    S,
    *,
    ncols: int,
    partition: RowPartition,
    dtype=None,
    params: ElasticParams | None = None,
    fault_plan=None,
    epoch: int = 0,
):
    """Distributed one-pass columnwise ``S·A`` over a partitioned stream.

    Every process calls this with the same arguments; each folds its
    partition share locally (global row offsets address the counter
    windows, so partials are exactly the rows an unsharded pass would
    realize), partials merge with one psum, and the merged sum is
    finalized — sum-then-epilogue, the same contract as
    ``finalize_slices`` in-core.  Returns the full (s, ncols) sketch,
    identical on every process.
    """
    import jax.numpy as jnp

    from ..parallel.collectives import cross_host_psum
    from ..plans import accumulate_slice
    from ..sketch.base import Dimension
    from .drivers import _result_dtype, _unwrap

    if partition.nrows != S.n:
        raise InvalidParameters(
            f"partition covers {partition.nrows} rows but the sketch "
            f"domain is {S.n}"
        )
    _require_real_world(partition)
    params = params or ElasticParams()
    rank, world = _resolve_world(params)
    partition.validate_world(rank, world)
    r0, r1 = partition.row_range(rank)
    dt = _result_dtype(dtype)
    kind = "distributed_streaming_sketch"

    def init_at(row0: int):
        return {
            "sa": jnp.zeros((S.s, int(ncols)), dt),
            "row": np.asarray(row0, np.int64),
        }

    def step(acc, block, index):
        row = int(acc["row"])
        block, k = _unwrap(block)
        return {
            "sa": accumulate_slice(
                S, acc["sa"], block, row, true_rows=k,
                fused=getattr(params, "fused_chunks", None),
            ),
            "row": np.asarray(row + k, np.int64),
        }

    report = guard.RecoveryReport(stage=kind)
    plan = None
    if getattr(params, "resume_policy", "strict") == "repartition":
        from .repartition import execute_rank_plan, resolve_resume

        epoch, plan = resolve_resume(
            params.checkpoint_dir, partition, kind=kind, params=params
        )
    watchdog = (
        _make_watchdog(params, params.checkpoint_dir, rank, world, epoch)
        if params.checkpoint_dir
        else None
    )
    if plan is not None:
        partial, _replay = execute_rank_plan(
            plan, source, params=params, root=params.checkpoint_dir,
            init_at=init_at, step_fn=step, kind=kind,
            fault_plan=fault_plan, report=report,
        )
        partial = {"sa": jnp.asarray(partial["sa"])}
    else:
        acc, nbatches = elastic_run_stream(
            source, step, init_at(r0), partition, params,
            kind=kind, fault_plan=fault_plan, report=report, epoch=epoch,
        )
        rows = int(acc["row"])
        if rows != r1:
            raise ValueError(
                f"rank {rank} folded rows [{r0}, {rows}) but its "
                f"partition share is [{r0}, {r1}); the source and "
                "partition disagree"
            )
        partial = {"sa": acc["sa"]}
    merged = cross_host_psum(partial, watchdog=watchdog)
    out = S.finalize_slices(jnp.asarray(merged["sa"]), Dimension.COLUMNWISE)
    if guard.enabled():
        guard.check_finite(out, "distributed_streaming_sketch",
                           report=report)
    return out


def distributed_sketch_least_squares(
    source,
    S,
    *,
    ncols: int,
    partition: RowPartition,
    targets: int = 1,
    alg: str = "qr",
    dtype=None,
    params: ElasticParams | None = None,
    fault_plan=None,
    epoch: int = 0,
    policy_decision: dict | None = None,
):
    """Distributed streaming sketch-and-solve least squares.

    One partitioned pass accumulates per-rank partials of ``(S·A,
    S·b)``, one psum merges them, and every rank solves the identical
    small (s, n) problem — so ``x`` is bit-identical across ranks with
    no broadcast.  Guard verdicts are WORLD decisions: each rank
    certifies the merged ``S·A`` locally, the ok/not-ok flags (plus the
    ranks' chunk-sentinel replay counts) psum across the world, and a
    bad certificate on ANY rank sends EVERY rank down the same ladder
    rung (the SVD pseudoinverse small solve) — ranks must agree on
    ``SKYLARK_GUARD`` for the collective order to match.

    Returns ``(x, info)``; ``info`` carries only world-deterministic
    fields (global ``rows``/``batches``, the rank's own
    ``local_batches``, ``world_size``, ``rank``, ``recovery``) so an
    interrupted-and-resumed run reproduces an uninterrupted run's
    ``(x, info)`` bit-for-bit.
    """
    import jax.numpy as jnp

    from ..linalg.least_squares import exact_least_squares
    from ..parallel.collectives import cross_host_psum
    from ..plans import accumulate_slice
    from ..sketch.base import Dimension
    from .drivers import _result_dtype

    if partition.nrows != S.n:
        raise InvalidParameters(
            f"partition covers {partition.nrows} rows but the sketch "
            f"domain is {S.n}"
        )
    _require_real_world(partition)
    params = params or ElasticParams()
    rank, world = _resolve_world(params)
    partition.validate_world(rank, world)
    r0, r1 = partition.row_range(rank)
    dt = _result_dtype(dtype)
    kind = "distributed_streaming_lsq"

    def init_at(row0: int):
        return {
            "sa": jnp.zeros((S.s, int(ncols)), dt),
            "sb": jnp.zeros((S.s, int(targets)), dt),
            "row": np.asarray(row0, np.int64),
        }

    def step(acc, batch, index):
        A_b, b_b = batch
        row = int(acc["row"])
        b2 = b_b[:, None] if getattr(b_b, "ndim", 1) == 1 else b_b
        return {
            "sa": accumulate_slice(
                S, acc["sa"], A_b, row,
                fused=getattr(params, "fused_chunks", None),
            ),
            "sb": accumulate_slice(
                S, acc["sb"], b2, row,
                fused=getattr(params, "fused_chunks", None),
            ),
            "row": np.asarray(row + A_b.shape[0], np.int64),
        }

    guarded = guard.enabled()
    report = (
        guard.RecoveryReport(stage=kind)
        if guarded
        else guard.RecoveryReport.disabled(kind)
    )
    plan = None
    replay = None
    if getattr(params, "resume_policy", "strict") == "repartition":
        from .repartition import execute_rank_plan, resolve_resume

        epoch, plan = resolve_resume(
            params.checkpoint_dir, partition, kind=kind, params=params
        )
    watchdog = (
        _make_watchdog(params, params.checkpoint_dir, rank, world, epoch)
        if params.checkpoint_dir
        else None
    )
    if plan is not None:
        partial, replay = execute_rank_plan(
            plan, source, params=params, root=params.checkpoint_dir,
            init_at=init_at, step_fn=step, kind=kind,
            fault_plan=fault_plan, report=report,
        )
        nbatches = replay["replayed_batches"]
        partial = {
            "sa": jnp.asarray(partial["sa"]),
            "sb": jnp.asarray(partial["sb"]),
        }
    else:
        acc, nbatches = elastic_run_stream(
            source, step, init_at(r0), partition, params,
            kind=kind, fault_plan=fault_plan, report=report, epoch=epoch,
        )
        rows = int(acc["row"])
        if rows != r1:
            raise ValueError(
                f"rank {rank} folded rows [{r0}, {rows}) but its "
                f"partition share is [{r0}, {r1}); the source and "
                "partition disagree"
            )
        partial = {"sa": acc["sa"], "sb": acc["sb"]}
    merged = cross_host_psum(partial, watchdog=watchdog)
    SA = S.finalize_slices(jnp.asarray(merged["sa"]), Dimension.COLUMNWISE)
    SB = S.finalize_slices(jnp.asarray(merged["sb"]), Dimension.COLUMNWISE)
    if guarded:
        # No resketch rung exists for a one-pass stream (the data is
        # gone), so a failed certificate degrades the SMALL solve — and
        # the degradation is a WORLD decision: psum the verdict so every
        # rank takes the same rung even when only one saw the failure.
        cert = guard.certify_sketch(SA, stage="distributed_streaming_lsq")
        local_replays = sum(
            1 for a in report.attempts if a.action == "replay"
        )
        votes = cross_host_psum(
            np.asarray([0.0 if cert.ok else 1.0, float(local_replays)],
                       np.float64),
            watchdog=watchdog,
            phase="verdict",
        )
        world_bad, world_replays = int(votes[0]), int(votes[1])
        report.record(
            "initial", verdict=cert.verdict, detail=cert.detail,
            cond=cert.cond, sketch_size=int(SA.shape[0]),
        )
        report.record(
            "world",
            detail=(
                f"psum verdict over {world} rank(s): bad_certs="
                f"{world_bad}, chunk_replays={world_replays}"
            ),
        )
        if world_bad:
            alg = "svd"
            report.record(
                "fallback", verdict=guard.FALLBACK,
                detail="svd pseudoinverse small solve (world verdict)",
            )
            report.recovered = True
    X = exact_least_squares(SA, SB, alg=alg)
    if guarded:
        guard.check_finite(X, "distributed_streaming_lsq", report=report)
    x = X[:, 0] if targets == 1 else X
    info = {
        "rows": int(partition.nrows),
        "batches": int(partition.num_batches),
        "local_batches": int(nbatches),
        "world_size": int(partition.world_size),
        "rank": int(rank),
        "recovery": report.to_dict(),
        # None on the normal path; a repartitioned resume reports the
        # plan-global accounting (identical on every rank): which batch
        # ranges were re-folded, how many durable refs merged, the
        # epoch transition — "only the dead hosts' batches replayed".
        "replay": replay,
    }
    if policy_decision is not None:
        # Threaded down by linalg.streaming_least_squares so the ledgered
        # run_summary and the returned info carry identical keys; the
        # decision is deterministic given the (shared) profile store, so
        # world-determinism of info is preserved when ranks share one.
        info["policy"] = policy_decision
    telemetry.run_summary(kind, info)
    return x, info
