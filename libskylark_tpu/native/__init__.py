"""Native C core loader (≙ the reference's ``capi/`` shared library).

Builds ``libskylark_native.so`` from ``src/skylark_native.cpp`` on first
use (g++, cached by mtime) and exposes it through ctypes.  Everything
degrades gracefully: ``available()`` is False when no compiler exists and
all Python paths fall back to pure JAX/numpy.

Precision note: the native core computes in float64, so it matches the
JAX path bit-for-integer-draws and to ~1e-14 for transcendentals **when
jax_enable_x64 is on**.  With x64 off, normal/cauchy/exp draws use the
f32 bit constructions (docs/counter_contract.md) and are *different
stream values* — by design, not drift.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

__all__ = [
    "available",
    "lib",
    "parse_libsvm_bytes",
    "supported_sketch_transforms",
    "kernel_gram",
    "approximate_svd",
    "approximate_least_squares",
    "model_predict",
    "NativeModel",
    "NativeSketch",
    "NativeContext",
]

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "src", "skylark_native.cpp")
_SO = os.path.join(_DIR, "libskylark_native.so")
_lock = threading.Lock()
_lib = None
_tried = False


def _build() -> bool:
    try:
        if os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
            return True
        cmd = [
            "g++", "-O3", "-shared", "-fPIC", "-fopenmp", "-std=c++17",
            _SRC, "-o", _SO,
        ]
        subprocess.run(cmd, check=True, capture_output=True, timeout=300)
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def lib():
    """The loaded CDLL, or None if unavailable."""
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if not _build():
            return None
        try:
            L = ctypes.CDLL(_SO)
        except OSError:
            # Corrupt/stale/incompatible cached .so: rebuild once, then
            # degrade gracefully.
            try:
                os.remove(_SO)
            except OSError:
                pass
            if not _build():
                return None
            try:
                L = ctypes.CDLL(_SO)
            except OSError:
                return None
        L.sl_create_context.restype = ctypes.c_void_p
        L.sl_create_context.argtypes = [ctypes.c_uint64]
        L.sl_free_context.argtypes = [ctypes.c_void_p]
        L.sl_context_counter.restype = ctypes.c_uint64
        L.sl_context_counter.argtypes = [ctypes.c_void_p]
        L.sl_create_sketch_transform.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_long, ctypes.c_long,
            ctypes.c_double, ctypes.POINTER(ctypes.c_void_p),
        ]
        L.sl_create_sketch_transform2.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_long, ctypes.c_long,
            ctypes.c_double, ctypes.c_double, ctypes.POINTER(ctypes.c_void_p),
        ]
        L.sl_create_sketch_transform_ex.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_long, ctypes.c_long,
            ctypes.c_double, ctypes.c_double, ctypes.c_double,
            ctypes.POINTER(ctypes.c_void_p),
        ]
        L.sl_free_sketch_transform.argtypes = [ctypes.c_void_p]
        L.sl_apply_sketch_transform.argtypes = [
            ctypes.c_void_p,
            np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
            ctypes.c_long, ctypes.c_long, ctypes.c_int,
            np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
        ]
        L.sl_serialize_sketch_transform.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_char_p)
        ]
        L.sl_deserialize_sketch_transform.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_void_p)
        ]
        L.sl_free_str.argtypes = [ctypes.c_char_p]
        L.sl_supported_sketch_transforms.argtypes = [
            ctypes.POINTER(ctypes.c_char_p)
        ]
        f64 = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
        L.sl_kernel_gram.argtypes = [
            ctypes.c_int, ctypes.c_double, ctypes.c_double, ctypes.c_double,
            f64, ctypes.c_long, f64, ctypes.c_long, ctypes.c_long, f64,
        ]
        L.sl_approximate_svd.argtypes = [
            ctypes.c_void_p, f64, ctypes.c_long, ctypes.c_long,
            ctypes.c_long, ctypes.c_int, f64, f64, f64,
        ]
        L.sl_approximate_least_squares.argtypes = [
            ctypes.c_void_p, f64, f64, ctypes.c_long, ctypes.c_long,
            ctypes.c_long, ctypes.c_long, f64,
        ]
        L.sl_model_info.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_long),
            ctypes.POINTER(ctypes.c_long),
        ]
        L.sl_model_predict.argtypes = [
            ctypes.c_char_p, f64, ctypes.c_long, ctypes.c_long, f64,
        ]
        L.sl_model_load.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_void_p)
        ]
        L.sl_model_free.argtypes = [ctypes.c_void_p]
        L.sl_model_predict_handle.argtypes = [
            ctypes.c_void_p, f64, ctypes.c_long, ctypes.c_long, f64,
        ]
        L.sl_model_stream_version.restype = ctypes.c_int
        L.sl_model_stream_version.argtypes = [ctypes.c_void_p]
        L.sl_stream_revision.restype = ctypes.c_int
        L.sl_stream_revision.argtypes = []
        L.sl_error_string.restype = ctypes.c_char_p
        L.sl_error_string.argtypes = [ctypes.c_int]
        L.sl_sample.argtypes = [
            ctypes.c_uint64, ctypes.c_uint64, ctypes.c_long, ctypes.c_int,
            ctypes.c_uint32,
            np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
        ]
        L.sl_libsvm_count.argtypes = [
            ctypes.c_char_p, ctypes.c_long,
            ctypes.POINTER(ctypes.c_long), ctypes.POINTER(ctypes.c_long),
            ctypes.POINTER(ctypes.c_long),
        ]
        L.sl_libsvm_parse.argtypes = [
            ctypes.c_char_p, ctypes.c_long,
            np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
        ]
        _lib = L
        return _lib


def available() -> bool:
    return lib() is not None


def supported_sketch_transforms():
    """(type, input, output, direction) tuples the native C API supports
    (≙ ``sl_supported_sketch_transforms``, capi/csketch.cpp:74+)."""
    out = ctypes.c_char_p()
    _check(lib().sl_supported_sketch_transforms(ctypes.byref(out)))
    s = out.value.decode()
    lib().sl_free_str(out)
    return [tuple(line.split()) for line in s.splitlines()]


_KERNEL_CODES = {
    "linear": 0, "gaussian": 1, "polynomial": 2,
    "laplacian": 3, "expsemigroup": 4, "matern": 5,
}


def kernel_gram(kernel: str, X, Y=None, p1=0.0, p2=0.0, p3=0.0):
    """Native kernel Gram K[i, j] = k(X[i], Y[j]) (≙ ``capi/ckernel.cpp``).

    Params by kernel: gaussian/laplacian p1=sigma; polynomial p1=q, p2=c,
    p3=gamma; expsemigroup p1=beta; matern p1=nu (half-integer), p2=l.
    """
    X = np.ascontiguousarray(X, np.float64)
    Y = X if Y is None else np.ascontiguousarray(Y, np.float64)
    if X.ndim != 2 or Y.ndim != 2 or X.shape[1] != Y.shape[1]:
        raise ValueError(f"bad gram shapes {X.shape} vs {Y.shape}")
    # Required scale parameters: a forgotten one would silently produce
    # NaN/zero grams (exp(-d/0)) deep inside downstream solves.
    if kernel not in _KERNEL_CODES:
        raise ValueError(
            f"unknown kernel {kernel!r}; choose from {sorted(_KERNEL_CODES)}"
        )
    if kernel == "polynomial" and not p3 > 0:
        raise ValueError(f"polynomial kernel needs gamma = p3 > 0, got {p3}")
    if kernel in ("gaussian", "laplacian") and not p1 > 0:
        raise ValueError(f"{kernel} kernel needs sigma = p1 > 0, got {p1}")
    if kernel == "expsemigroup" and not p1 > 0:
        raise ValueError(f"expsemigroup kernel needs beta = p1 > 0, got {p1}")
    if kernel == "matern" and (not p1 > 0 or not p2 > 0):
        raise ValueError(
            f"matern kernel needs nu = p1 > 0 and l = p2 > 0, got {p1}, {p2}"
        )
    K = np.empty((X.shape[0], Y.shape[0]), np.float64)
    _check(lib().sl_kernel_gram(
        _KERNEL_CODES[kernel], p1, p2, p3,
        X, X.shape[0], Y, Y.shape[0], X.shape[1], K,
    ))
    return K


def approximate_svd(ctx, A, rank: int, num_iterations: int = 1):
    """Native randomized truncated SVD (≙ ``capi/cnla.cpp``): returns
    (U, S, V) with A ≈ U @ diag(S) @ V.T.  ``ctx`` is a NativeContext."""
    A = np.ascontiguousarray(A, np.float64)
    m, n = A.shape
    k = int(rank)
    U = np.empty((m, k), np.float64)
    S = np.empty((k,), np.float64)
    V = np.empty((n, k), np.float64)
    _check(lib().sl_approximate_svd(
        ctx._h, A, m, n, k, num_iterations, U, S, V
    ))
    return U, S, V


def approximate_least_squares(ctx, A, b, sketch_size: int = 0):
    """Native sketch-and-solve least squares (≙ ``capi/cnla.cpp``):
    argmin_x ||Ax - b|| via a CWT sketch (default size 4n)."""
    A = np.ascontiguousarray(A, np.float64)
    b = np.ascontiguousarray(b, np.float64)
    squeeze = b.ndim == 1
    if squeeze:
        b = b[:, None]
    if b.ndim != 2 or A.ndim != 2 or b.shape[0] != A.shape[0]:
        raise ValueError(
            f"shape mismatch: A {A.shape} needs b with {A.shape[0]} rows, "
            f"got {b.shape}"
        )
    m, n = A.shape
    t = b.shape[1]
    x = np.empty((n, t), np.float64)
    _check(lib().sl_approximate_least_squares(
        ctx._h, A, b, m, n, t, sketch_size, x
    ))
    return x[:, 0] if squeeze else x


class NativeModel:
    """Load-once handle on a saved ``FeatureMapModel`` for repeated native
    prediction (≙ ``capi/cml.cpp`` + the streaming-predict consumer: the
    reference CLI loads the model once, then predicts per batch)."""

    def __init__(self, path):
        import json
        import os

        path = os.fspath(path)
        h = ctypes.c_void_p()
        _check(lib().sl_model_load(path.encode(), ctypes.byref(h)))
        self._h = h
        self._free = lib().sl_model_free
        with open(path) as f:
            meta = json.load(f)
        # The native handle parses the version itself (sl_model_stream_
        # version), so pure-C consumers see the same diagnostic signal.
        ver = lib().sl_model_stream_version(self._h)
        if ver < lib().sl_stream_revision():
            import warnings

            warnings.warn(
                f"model serialized under stream revision {ver} "
                f"(current {lib().sl_stream_revision()}): "
                "f32-uniform-derived map values reproduce differently "
                "(docs/counter_contract.md, Stream revisions)",
                stacklevel=2,
            )
        # (D,) coefficients predict to (n,), matching Python's
        # FeatureMapModel.predict broadcasting.  The metadata already
        # carries the dims — no extra native info round-trip needed.
        shape = meta.get("coef_shape", [0, 0])
        self._squeeze = len(shape) == 1
        self.input_dim = meta.get("input_dim")
        self.num_outputs = 1 if self._squeeze else int(shape[1])

    def predict(self, X):
        X = np.ascontiguousarray(X, np.float64)
        if X.ndim != 2:
            raise ValueError(f"X must be 2-D, got {X.shape}")
        out = np.empty((X.shape[0], self.num_outputs), np.float64)
        _check(lib().sl_model_predict_handle(
            self._h, X, X.shape[0], X.shape[1], out
        ))
        return out[:, 0] if self._squeeze else out

    def __del__(self):
        h, self._h = getattr(self, "_h", None), None
        if h:
            self._free(h)


def model_predict(path, X):
    """One-shot native prediction from a saved ``FeatureMapModel``; for
    repeated batches use :class:`NativeModel` (loads once)."""
    return NativeModel(path).predict(X)


def _check(code: int):
    if code:
        from ..utils.exceptions import SkylarkError

        msg = lib().sl_error_string(code).decode()
        raise SkylarkError(f"native error {code}: {msg}")


def parse_libsvm_bytes(data: bytes):
    """(labels, rows, cols, vals, n_features) from LIBSVM text bytes."""
    L = lib()
    n_rows = ctypes.c_long()
    n_nnz = ctypes.c_long()
    max_col = ctypes.c_long()
    _check(L.sl_libsvm_count(data, len(data), ctypes.byref(n_rows),
                             ctypes.byref(n_nnz), ctypes.byref(max_col)))
    labels = np.empty(n_rows.value, np.float64)
    rows = np.empty(n_nnz.value, np.int64)
    cols = np.empty(n_nnz.value, np.int64)
    vals = np.empty(n_nnz.value, np.float64)
    _check(L.sl_libsvm_parse(data, len(data), labels, rows, cols, vals))
    return labels, rows, cols, vals, int(max_col.value)


class NativeContext:
    """≙ ``sl_create_context`` handle."""

    def __init__(self, seed: int):
        L = lib()
        self._h = L.sl_create_context(seed)
        # Cache the free function: module globals may already be cleared
        # when __del__ runs at interpreter shutdown.
        self._free = L.sl_free_context

    @property
    def counter(self) -> int:
        return int(lib().sl_context_counter(self._h))

    def __del__(self):
        h, self._h = getattr(self, "_h", None), None
        if h:
            self._free(h)


class NativeSketch:
    """≙ ``sl_create_sketch_transform`` + apply/serialize handles."""

    def __init__(self, handle, n, s):
        self._h = handle
        self.n, self.s = n, s
        self._free = lib().sl_free_sketch_transform

    @classmethod
    def create(cls, ctx: NativeContext, sketch_type: str, n: int, s: int,
               param: float = 0.0, param2: float = 0.0, param3: float = 0.0):
        out = ctypes.c_void_p()
        _check(lib().sl_create_sketch_transform_ex(
            ctx._h, sketch_type.encode(), n, s, param, param2, param3,
            ctypes.byref(out)))
        return cls(out, n, s)

    @classmethod
    def from_json(cls, js: str):
        out = ctypes.c_void_p()
        _check(lib().sl_deserialize_sketch_transform(js.encode(), ctypes.byref(out)))
        import json

        d = json.loads(js)
        return cls(out, int(d["N"]), int(d["S"]))

    def apply(self, A: np.ndarray, dim: str = "columnwise") -> np.ndarray:
        A = np.ascontiguousarray(A, np.float64)
        cw = dim == "columnwise"
        if cw:
            out = np.empty((self.s, A.shape[1]), np.float64)
        else:
            out = np.empty((A.shape[0], self.s), np.float64)
        _check(lib().sl_apply_sketch_transform(
            self._h, A, A.shape[0], A.shape[1], 0 if cw else 1, out))
        return out

    def to_json(self) -> str:
        out = ctypes.c_char_p()
        _check(lib().sl_serialize_sketch_transform(self._h, ctypes.byref(out)))
        s = out.value.decode()
        lib().sl_free_str(out)
        return s

    def __del__(self):
        h, self._h = getattr(self, "_h", None), None
        if h:
            self._free(h)
