// Native C core: counter-based RNG, local sketch applies, C API, LIBSVM
// parser.
//
// TPU-native framework's counterpart of the reference's C API layer
// (capi/sketchc.hpp:21-54, capi/basec.hpp:36-58) and chunked LIBSVM
// reader (utility/io/libsvm_io.hpp:529+).  The compute path of the
// framework is JAX/XLA; this library provides (a) a standalone C entry
// point for host applications (the reference's capi is the same bridge),
// and (b) a fast multithreaded parser feeding the Python IO layer.
//
// RNG compatibility contract: Threefry-2x32 with the same key schedule and
// counter layout as libskylark_tpu.core.random (sample i of a stream is a
// pure function of (seed, lane, base+i)); integer-derived draws
// (rademacher, uniform_int, uniform bits) are BIT-identical to the JAX
// path, transcendental ones (normal via Box-Muller, cauchy, exp) match
// to ~1 ulp in float64.
//
// Build: g++ -O3 -shared -fPIC -fopenmp (see ../build.py).

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <cmath>
#include <complex>
#include <string>
#include <vector>
#include <algorithm>
#include <thread>

extern "C" {

// ---------------------------------------------------------------------------
// Threefry-2x32 (matches jax.extend.random.threefry_2x32)
// ---------------------------------------------------------------------------

static inline uint32_t rotl32(uint32_t x, int r) {
    return (x << r) | (x >> (32 - r));
}

static void threefry2x32(uint32_t k0, uint32_t k1, uint32_t c0, uint32_t c1,
                         uint32_t* o0, uint32_t* o1) {
    static const int rot[8] = {13, 15, 26, 6, 17, 29, 16, 24};
    uint32_t ks2 = k0 ^ k1 ^ 0x1BD11BDAu;
    uint32_t x0 = c0 + k0, x1 = c1 + k1;

#define SK_ROUND4(a, b, c, d)                                                 \
    x0 += x1; x1 = rotl32(x1, a); x1 ^= x0;                                   \
    x0 += x1; x1 = rotl32(x1, b); x1 ^= x0;                                   \
    x0 += x1; x1 = rotl32(x1, c); x1 ^= x0;                                   \
    x0 += x1; x1 = rotl32(x1, d); x1 ^= x0;

    SK_ROUND4(rot[0], rot[1], rot[2], rot[3]);
    x0 += k1; x1 += ks2 + 1u;
    SK_ROUND4(rot[4], rot[5], rot[6], rot[7]);
    x0 += ks2; x1 += k0 + 2u;
    SK_ROUND4(rot[0], rot[1], rot[2], rot[3]);
    x0 += k0; x1 += k1 + 3u;
    SK_ROUND4(rot[4], rot[5], rot[6], rot[7]);
    x0 += k1; x1 += ks2 + 4u;
    SK_ROUND4(rot[0], rot[1], rot[2], rot[3]);
    x0 += ks2; x1 += k0 + 5u;
#undef SK_ROUND4

    *o0 = x0;
    *o1 = x1;
}

static const uint32_t SK_GOLDEN = 0x9E3779B9u;

// 64 random bits for counter `ctr` under (seed, lane).
static inline void sk_bits(uint64_t seed, uint32_t lane, uint64_t ctr,
                           uint32_t* hi, uint32_t* lo) {
    uint32_t k0 = (uint32_t)(seed & 0xFFFFFFFFu);
    uint32_t k1 = (uint32_t)((seed >> 32) ^ (uint64_t)(lane * SK_GOLDEN));
    threefry2x32(k0, k1, (uint32_t)(ctr >> 32), (uint32_t)(ctr & 0xFFFFFFFFu),
                 hi, lo);
}

// ---------------------------------------------------------------------------
// bits -> distributions (matching core/random.py)
// ---------------------------------------------------------------------------

static inline double sk_uniform01(uint32_t hi, uint32_t lo) {
    uint64_t top = (uint64_t)(hi >> 7);   // 25 bits
    uint64_t bot = (uint64_t)(lo >> 5);   // 27 bits
    uint64_t k = (top << 27) | bot;       // 52 bits
    return ((double)k + 0.5) * 0x1p-52;
}

static inline float sk_uniform01_f32(uint32_t hi) {
    // HI's top bits — the same leading bits as sk_uniform01's f64 value,
    // so f32 and f64 streams agree to ~2^-24 (cross-precision parity;
    // mirrors core/random.py::_uniform01).
    uint32_t k = hi >> 8;  // 24 bits
    return ((float)k + 0.5f) * 0x1p-24f;
}

// Cephes ndtri (inverse normal CDF) — same algorithm jax.scipy.special
// uses, so float64 values agree to ~1 ulp.  Used by the QMC (inverse-CDF)
// feature maps.
static double sk_ndtri(double y0) {
    static const double P0[5] = {
        -5.99633501014107895267e1, 9.80010754185999661536e1,
        -5.66762857469070293439e1, 1.39312609387279679503e1,
        -1.23916583867381258016e0};
    static const double Q0[8] = {
        1.95448858338141759834e0, 4.67627912898881538453e0,
        8.63602421390890590575e1, -2.25462687854119370527e2,
        2.00260212380060660359e2, -8.20372256168538034e1,
        1.59056225126211695515e1, -1.18331621121330003142e0};
    static const double P1[9] = {
        4.05544892305962419923e0, 3.15251094599893866154e1,
        5.71628192246421288162e1, 4.408050738932008347e1,
        1.46849561928858024014e1, 2.18663306850790267539e0,
        -1.40256079171354495875e-1, -3.50424626827848203418e-2,
        -8.57456785154685413611e-4};
    static const double Q1[8] = {
        1.57799883256466749731e1, 4.53907635128879210584e1,
        4.13172038254672030440e1, 1.50425385692907503408e1,
        2.50464946208309415979e0, -1.42182922854787788574e-1,
        -3.80806407691578277194e-2, -9.33259480895457427372e-4};
    static const double P2[9] = {
        3.23774891776946035970e0, 6.91522889068984211695e0,
        3.93881025292474443415e0, 1.33303460815807542389e0,
        2.01485389549179081538e-1, 1.23716634817820021358e-2,
        3.01581553508235416007e-4, 2.65806974686737550832e-6,
        6.23974539184983651783e-9};
    static const double Q2[8] = {
        6.02427039364742014255e0, 3.67983563856160859403e0,
        1.37702099489081330271e0, 2.16236993594496635890e-1,
        1.34204006088543189037e-2, 3.28014464682127739104e-4,
        2.89247864745380683936e-6, 6.79019408009981274425e-9};

    const double s2pi = 2.50662827463100050242;
    if (y0 <= 0.0) return -INFINITY;
    if (y0 >= 1.0) return INFINITY;
    int code = 1;
    double y = y0;
    if (y > 1.0 - 0.13533528323661269189) {  // 1 - exp(-2)
        y = 1.0 - y;
        code = 0;
    }
    if (y > 0.13533528323661269189) {
        y = y - 0.5;
        double y2 = y * y;
        double num = P0[0], den = 1.0;
        for (int i = 1; i < 5; i++) num = num * y2 + P0[i];
        for (int i = 0; i < 8; i++) den = den * y2 + Q0[i];
        double x = y + y * (y2 * num / den);
        return x * s2pi;
    }
    double x = std::sqrt(-2.0 * std::log(y));
    double x0 = x - std::log(x) / x;
    double z = 1.0 / x;
    double x1;
    if (x < 8.0) {
        double num = P1[0], den = 1.0;
        for (int i = 1; i < 9; i++) num = num * z + P1[i];
        for (int i = 0; i < 8; i++) den = den * z + Q1[i];
        x1 = z * num / den;
    } else {
        double num = P2[0], den = 1.0;
        for (int i = 1; i < 9; i++) num = num * z + P2[i];
        for (int i = 0; i < 8; i++) den = den * z + Q2[i];
        x1 = z * num / den;
    }
    x = x0 - x1;
    if (code) x = -x;
    return x;
}


static inline uint32_t sk_uniform_int(uint32_t hi, uint32_t lo, uint32_t lo_b,
                                      uint32_t hi_b) {
    uint64_t span = (uint64_t)(hi_b - lo_b) + 1;
    uint64_t p1 = (uint64_t)hi * span;
    uint64_t p2 = (uint64_t)lo * span;
    uint64_t s = p1 + (p2 >> 32);
    return lo_b + (uint32_t)(s >> 32);
}

// dist codes
enum { SK_DIST_NORMAL = 0, SK_DIST_CAUCHY = 1, SK_DIST_RADEMACHER = 2,
       SK_DIST_EXP = 3, SK_DIST_UNIFORM = 4 };

// Box-Muller normal from the two counter words (matches core/random.py
// _normal: f64 path, 32 uniform bits per word).
static inline double sk_normal(uint32_t hi, uint32_t lo) {
    double u1 = ((double)hi + 0.5) * 0x1p-32;
    double u2 = ((double)lo + 0.5) * 0x1p-32;
    return std::sqrt(-2.0 * std::log(u1)) * std::cos(2.0 * M_PI * u2);
}

static inline double sk_draw(int dist, uint32_t hi, uint32_t lo) {
    switch (dist) {
        case SK_DIST_NORMAL: return sk_normal(hi, lo);
        case SK_DIST_CAUCHY: return std::tan(M_PI * (sk_uniform01(hi, lo) - 0.5));
        case SK_DIST_RADEMACHER: return (lo & 1u) ? 1.0 : -1.0;
        case SK_DIST_EXP: return -std::log(sk_uniform01(hi, lo));
        default: return sk_uniform01(hi, lo);
    }
}

int sl_sample(uint64_t seed, uint64_t base, long num, int dist, uint32_t lane,
              double* out) {
#pragma omp parallel for schedule(static)
    for (long i = 0; i < num; i++) {
        uint32_t hi, lo;
        sk_bits(seed, lane, base + (uint64_t)i, &hi, &lo);
        out[i] = sk_draw(dist, hi, lo);
    }
    return 0;
}

// ---------------------------------------------------------------------------
// Context + sketch transforms (C API ≙ capi/sketchc.hpp)
// ---------------------------------------------------------------------------

struct sl_context_t {
    uint64_t seed;
    uint64_t counter;
};

enum sl_type_t { SL_JLT = 0, SL_CT = 1, SL_CWT = 2, SL_MMT = 3, SL_WZT = 4,
                 SL_UST = 5, SL_FJLT = 6, SL_GRFT = 7, SL_LRFT = 8,
                 SL_RLT = 9, SL_MRFT = 10, SL_FGRFT = 11, SL_FMRFT = 12,
                 SL_GQRFT = 13, SL_LQRFT = 14, SL_QRLT = 15, SL_PPT = 16,
                 SL_NUM_SKETCH_TYPES = 17 };

// ---------------------------------------------------------------------------
// Leaped Halton QMC (≙ core/quasirand.py)
// ---------------------------------------------------------------------------

static void sk_primes(int k, std::vector<long>& out) {
    out.clear();
    long c = 2;
    while ((int)out.size() < k) {
        bool p = true;
        for (long d = 2; d * d <= c; d++)
            if (c % d == 0) { p = false; break; }
        if (p) out.push_back(c);
        c++;
    }
}

// Van der Corput radical inverse of (idx + 1) in `base`, 41 digits —
// identical accumulation order to core/quasirand.radical_inverse (f64).
static double sk_radical_inverse(long base, unsigned long long idx) {
    unsigned long long res = idx + 1ull;
    double r = 0.0, m = 1.0;
    for (int d = 0; d < 41; d++) {
        m /= (double)base;
        r += m * (double)(res % (unsigned long long)base);
        res /= (unsigned long long)base;
    }
    return r;
}

// ---------------------------------------------------------------------------
// Complex FFT matching jnp.fft.fft's sign convention
// (X_k = sum_n x_n e^{-2*pi*i*n*k/N}).  sk_fft is the radix-2 kernel;
// sk_fft_any extends it to ARBITRARY length via Bluestein's chirp-z
// (round 3 — removes the former pow2-S restriction on native PPT, whose
// FFTW-backed reference handles any S).
// ---------------------------------------------------------------------------

static void sk_fft(std::complex<double>* x, long nfft, bool inverse) {
    // bit reversal
    for (long i = 1, j = 0; i < nfft; i++) {
        long bit = nfft >> 1;
        for (; j & bit; bit >>= 1) j ^= bit;
        j ^= bit;
        if (i < j) std::swap(x[i], x[j]);
    }
    for (long len = 2; len <= nfft; len <<= 1) {
        double ang = 2.0 * M_PI / (double)len * (inverse ? 1.0 : -1.0);
        std::complex<double> wl(std::cos(ang), std::sin(ang));
        for (long i = 0; i < nfft; i += len) {
            std::complex<double> w(1.0, 0.0);
            for (long j = 0; j < len / 2; j++) {
                std::complex<double> u = x[i + j];
                std::complex<double> v = x[i + j + len / 2] * w;
                x[i + j] = u + v;
                x[i + j + len / 2] = u - v;
                w *= wl;
            }
        }
    }
    if (inverse)
        for (long i = 0; i < nfft; i++) x[i] /= (double)nfft;
}

static long sk_next_pow2(long n);  // defined below

// Bluestein chirp-z: length-n DFT as a pow2 circular convolution.
// X_k = w_k * IFFT(FFT(x.w padded) * FFT(chirp))_k with w_k =
// e^{-pi i k^2/n}; k^2 is reduced mod 2n (the chirp's true period)
// before the angle computation so large n keeps full double-precision
// phase accuracy.  inverse rides the conj identity ifft(x) =
// conj(fft(conj(x)))/n.
static void sk_fft_any(std::complex<double>* x, long n, bool inverse) {
    if (n <= 1) return;
    if ((n & (n - 1)) == 0) {
        sk_fft(x, n, inverse);
        return;
    }
    if (inverse) {
        for (long i = 0; i < n; i++) x[i] = std::conj(x[i]);
        sk_fft_any(x, n, false);
        for (long i = 0; i < n; i++) x[i] = std::conj(x[i]) / (double)n;
        return;
    }
    const long m = sk_next_pow2(2 * n - 1);
    // The chirp table and FFT(b) depend only on n: cache them
    // per-thread (PPT applies call this q+1 times per column under the
    // OpenMP loop — rebuilding them per call would double the FFT work).
    thread_local long plan_n = -1;
    thread_local std::vector<std::complex<double>> w, Bf;
    if (plan_n != n) {
        w.assign(n, {});
        Bf.assign(m, {});
        for (long k = 0; k < n; k++) {
            long long k2 = ((long long)k * k) % (2LL * n);
            double ang = -M_PI * (double)k2 / (double)n;
            w[k] = std::complex<double>(std::cos(ang), std::sin(ang));
        }
        Bf[0] = std::conj(w[0]);
        for (long k = 1; k < n; k++) Bf[k] = Bf[m - k] = std::conj(w[k]);
        sk_fft(Bf.data(), m, false);
        plan_n = n;
    }
    std::vector<std::complex<double>> a(m);
    for (long k = 0; k < n; k++) a[k] = x[k] * w[k];
    sk_fft(a.data(), m, false);
    for (long i = 0; i < m; i++) a[i] *= Bf[i];
    sk_fft(a.data(), m, true);
    for (long k = 0; k < n; k++) x[k] = a[k] * w[k];
}

struct sl_sketch_t {
    int type;
    long n, s;
    long nb;  // FJLT/Fastfood: padded pow2 size
    uint64_t seed;
    uint64_t ctx_counter;  // creation-time counter (serialization)
    // reserved counter bases
    uint64_t base0, base1, base2, base3;
    double param;   // CT: C, WZT: p, UST: replace, RFT: sigma, RLT: beta,
                    // Matern: nu
    double param2;  // Matern: l
};

void* sl_create_context(uint64_t seed) {
    sl_context_t* c = new sl_context_t{seed, 0};
    return c;
}

void sl_free_context(void* ctx) { delete (sl_context_t*)ctx; }

uint64_t sl_context_counter(void* ctx) {
    return ((sl_context_t*)ctx)->counter;
}

static int sk_type_from_name(const char* name) {
    if (!strcmp(name, "JLT")) return SL_JLT;
    if (!strcmp(name, "CT")) return SL_CT;
    if (!strcmp(name, "CWT")) return SL_CWT;
    if (!strcmp(name, "MMT")) return SL_MMT;
    if (!strcmp(name, "WZT")) return SL_WZT;
    if (!strcmp(name, "UST")) return SL_UST;
    if (!strcmp(name, "FJLT")) return SL_FJLT;
    if (!strcmp(name, "GaussianRFT")) return SL_GRFT;
    if (!strcmp(name, "LaplacianRFT")) return SL_LRFT;
    if (!strcmp(name, "ExpSemigroupRLT")) return SL_RLT;
    if (!strcmp(name, "MaternRFT")) return SL_MRFT;
    if (!strcmp(name, "FastGaussianRFT")) return SL_FGRFT;
    if (!strcmp(name, "FastMaternRFT")) return SL_FMRFT;
    if (!strcmp(name, "GaussianQRFT")) return SL_GQRFT;
    if (!strcmp(name, "LaplacianQRFT")) return SL_LQRFT;
    if (!strcmp(name, "ExpSemigroupQRLT")) return SL_QRLT;
    if (!strcmp(name, "PPT")) return SL_PPT;
    return -1;
}

static const char* sk_name_from_type(int t) {
    static const char* names[SL_NUM_SKETCH_TYPES] = {
        "JLT", "CT", "CWT", "MMT", "WZT", "UST",
        "FJLT", "GaussianRFT", "LaplacianRFT",
        "ExpSemigroupRLT", "MaternRFT",
        "FastGaussianRFT", "FastMaternRFT",
        "GaussianQRFT", "LaplacianQRFT",
        "ExpSemigroupQRLT", "PPT"};
    return (t >= 0 && t < SL_NUM_SKETCH_TYPES) ? names[t] : "?";
}

static long sk_next_pow2(long n) {
    long p = 1;
    while (p < n) p *= 2;
    return p;
}

// Reservation schedule mirrors the Python classes exactly.
static void sk_reserve(sl_sketch_t* t, sl_context_t* ctx) {
    switch (t->type) {
        case SL_JLT:
        case SL_CT:
            t->base0 = ctx->counter;
            ctx->counter += (uint64_t)t->n * t->s;
            break;
        case SL_CWT:
        case SL_MMT:
            t->base0 = ctx->counter; ctx->counter += t->n;  // idx
            t->base1 = ctx->counter; ctx->counter += t->n;  // val
            break;
        case SL_WZT:
            t->base0 = ctx->counter; ctx->counter += t->n;
            t->base1 = ctx->counter; ctx->counter += t->n;
            t->base2 = ctx->counter; ctx->counter += t->n;  // rademacher
            break;
        case SL_UST:
            t->base0 = ctx->counter;
            ctx->counter += (t->param != 0.0) ? t->s : t->n;
            break;
        case SL_FJLT:
            // RFUT diagonal (N), then UST(replace) samples (S).
            t->base0 = ctx->counter; ctx->counter += t->n;
            t->base1 = ctx->counter; ctx->counter += t->s;
            break;
        case SL_GRFT:
        case SL_LRFT:
            // dense W (N·S), then shifts (S) — ≙ RFT_data_t::build.
            t->base0 = ctx->counter;
            ctx->counter += (uint64_t)t->n * t->s;
            t->base1 = ctx->counter; ctx->counter += t->s;
            break;
        case SL_RLT:
            t->base0 = ctx->counter;
            ctx->counter += (uint64_t)t->n * t->s;
            break;
        case SL_MRFT:
            // W (N·S), shifts (S), chi2 scales (S; lanes 1..2nu).
            t->base0 = ctx->counter;
            ctx->counter += (uint64_t)t->n * t->s;
            t->base1 = ctx->counter; ctx->counter += t->s;
            t->base2 = ctx->counter; ctx->counter += t->s;
            break;
        case SL_GQRFT:
        case SL_LQRFT:
        case SL_QRLT:
            break;  // QMC types consume no counters (skip-based)
        case SL_PPT: {
            // q CWTs (2N each), then hash idx (q) and val (q)
            // (≙ sketch/ppt.py reservation order).
            long q = (long)t->nb;  // q stashed in nb for PPT
            t->base0 = ctx->counter;
            ctx->counter += (uint64_t)(2 * t->n) * q;
            t->base1 = ctx->counter; ctx->counter += q;
            t->base2 = ctx->counter; ctx->counter += q;
            break;
        }
        case SL_FGRFT:
        case SL_FMRFT: {
            // ≙ FastRFT_data_t::build: shifts (S), B, G, P (numblks·NB
            // each); FastMatern adds chi2 (numblks·NB).
            long numblks = 1 + (t->s - 1) / t->nb;
            t->base0 = ctx->counter; ctx->counter += t->s;
            t->base1 = ctx->counter; ctx->counter += numblks * t->nb;  // B
            t->base2 = ctx->counter; ctx->counter += numblks * t->nb;  // G
            t->base3 = ctx->counter; ctx->counter += numblks * t->nb;  // P
            if (t->type == SL_FMRFT) {
                // chi base stored by re-deriving: it is base3 + blk·NB.
                ctx->counter += numblks * t->nb;
            }
            break;
        }
    }
}

int sl_create_sketch_transform_ex(void* ctx_, const char* type, long n,
                                  long s, double param, double param2,
                                  double param3, void** out) {
    int ty = sk_type_from_name(type);
    if (ty < 0) return 103;  // SketchError
    sl_context_t* ctx = (sl_context_t*)ctx_;
    sl_sketch_t* t = new sl_sketch_t();
    t->type = ty;
    t->n = n;
    t->s = s;
    t->nb = (ty == SL_FJLT || ty == SL_FGRFT || ty == SL_FMRFT)
                ? sk_next_pow2(n)
                : n;
    if (ty == SL_PPT) {
        // c (param) and gamma (param2) may legitimately be 0 — no
        // zero-means-default coercion here (unlike sigma/beta, where 0 is
        // invalid).  q=0 is invalid, so 0 selects the reference default.
        long q = (long)(param3 != 0.0 ? param3 : 3.0);
        if (q < 1 || s < 1) { delete t; return 104; }
        t->nb = q;  // PPT stashes q here
    }
    t->seed = ctx->seed;
    t->ctx_counter = ctx->counter;
    t->param = param;
    t->param2 = param2;
    if ((ty == SL_GRFT || ty == SL_LRFT || ty == SL_FGRFT ||
         ty == SL_GQRFT || ty == SL_LQRFT) && param == 0.0)
        t->param = 1.0;
    if ((ty == SL_RLT || ty == SL_QRLT) && param == 0.0) t->param = 1.0;
    if (ty == SL_MRFT || ty == SL_FMRFT) {
        if (t->param == 0.0) t->param = 1.0;   // nu
        if (t->param2 == 0.0) t->param2 = 1.0; // l
        double two_nu = 2.0 * t->param;
        if (std::fabs(two_nu - std::round(two_nu)) > 1e-9 ||
            std::round(two_nu) < 1) {
            delete t;
            return 102;
        }
    }
    if (ty == SL_UST && param == 0.0 && s > n) { delete t; return 102; }
    sk_reserve(t, ctx);
    *out = t;
    return 0;
}

int sl_create_sketch_transform2(void* ctx_, const char* type, long n, long s,
                                double param, double param2, void** out) {
    return sl_create_sketch_transform_ex(ctx_, type, n, s, param, param2, 0.0,
                                         out);
}

int sl_create_sketch_transform(void* ctx_, const char* type, long n, long s,
                               double param, void** out) {
    return sl_create_sketch_transform_ex(ctx_, type, n, s, param, 0.0, 0.0,
                                         out);
}

void sl_free_sketch_transform(void* t) { delete (sl_sketch_t*)t; }

// Dense columnwise apply: out (s, m) = Omega (s, n) @ A (n, m), row-major.
static void sk_apply_dense_cw(const sl_sketch_t* t, const double* A, long m,
                              double* out) {
    const long n = t->n, s = t->s;
    const int dist = (t->type == SL_JLT) ? SK_DIST_NORMAL : SK_DIST_CAUCHY;
    const double scale =
        (t->type == SL_JLT) ? 1.0 / std::sqrt((double)s) : t->param / (double)s;
#pragma omp parallel for schedule(static)
    for (long i = 0; i < s; i++) {
        double* orow = out + i * m;
        for (long c = 0; c < m; c++) orow[c] = 0.0;
        for (long j = 0; j < n; j++) {
            uint32_t hi, lo;
            sk_bits(t->seed, 0, t->base0 + (uint64_t)(i * n + j), &hi, &lo);
            double w = sk_draw(dist, hi, lo) * scale;
            const double* arow = A + j * m;
            for (long c = 0; c < m; c++) orow[c] += w * arow[c];
        }
    }
}

static double sk_hash_value(const sl_sketch_t* t, long i) {
    uint32_t hi, lo;
    switch (t->type) {
        case SL_CWT:
            sk_bits(t->seed, 0, t->base1 + (uint64_t)i, &hi, &lo);
            return (lo & 1u) ? 1.0 : -1.0;
        case SL_MMT:
            sk_bits(t->seed, 0, t->base1 + (uint64_t)i, &hi, &lo);
            return std::tan(M_PI * (sk_uniform01(hi, lo) - 0.5));
        case SL_WZT: {
            sk_bits(t->seed, 0, t->base1 + (uint64_t)i, &hi, &lo);
            double e = -std::log(sk_uniform01(hi, lo));
            uint32_t h2, l2;
            sk_bits(t->seed, 0, t->base2 + (uint64_t)i, &h2, &l2);
            double pm = (l2 & 1u) ? 1.0 : -1.0;
            return pm * std::pow(1.0 / e, 1.0 / t->param);
        }
    }
    return 0.0;
}

static void sk_apply_hash_cw(const sl_sketch_t* t, const double* A, long m,
                             double* out) {
    const long n = t->n, s = t->s;
    std::memset(out, 0, sizeof(double) * s * m);
    for (long i = 0; i < n; i++) {
        uint32_t hi, lo;
        sk_bits(t->seed, 0, t->base0 + (uint64_t)i, &hi, &lo);
        long b = (long)sk_uniform_int(hi, lo, 0, (uint32_t)(s - 1));
        double v = sk_hash_value(t, i);
        const double* arow = A + i * m;
        double* orow = out + b * m;
        for (long c = 0; c < m; c++) orow[c] += v * arow[c];
    }
}

static void sk_ust_samples(const sl_sketch_t* t, std::vector<long>& idx) {
    idx.resize(t->s);
    if (t->param != 0.0) {  // with replacement
        for (long i = 0; i < t->s; i++) {
            uint32_t hi, lo;
            sk_bits(t->seed, 0, t->base0 + (uint64_t)i, &hi, &lo);
            idx[i] = (long)sk_uniform_int(hi, lo, 0, (uint32_t)(t->n - 1));
        }
    } else {  // argsort of n f32 keys, keep first s (matches UST)
        std::vector<std::pair<float, long>> keys(t->n);
        for (long i = 0; i < t->n; i++) {
            uint32_t hi, lo;
            sk_bits(t->seed, 0, t->base0 + (uint64_t)i, &hi, &lo);
            keys[i] = {sk_uniform01_f32(hi), i};
        }
        std::stable_sort(keys.begin(), keys.end(),
                         [](const std::pair<float, long>& a,
                            const std::pair<float, long>& b) {
                             return a.first < b.first;
                         });
        for (long i = 0; i < t->s; i++) idx[i] = keys[i].second;
    }
}

static void sk_apply_ust_cw(const sl_sketch_t* t, const double* A, long m,
                            double* out) {
    std::vector<long> idx;
    sk_ust_samples(t, idx);
    for (long i = 0; i < t->s; i++)
        std::memcpy(out + i * m, A + idx[i] * m, sizeof(double) * m);
}

// In-place orthonormal FWHT over a length-nb (pow2) buffer, Sylvester
// (natural) order — matches sketch/fut.py wht().
static void sk_fwht(double* x, long nb) {
    for (long h = 1; h < nb; h *= 2)
        for (long i = 0; i < nb; i += 2 * h)
            for (long j = i; j < i + h; j++) {
                double a = x[j], b = x[j + h];
                x[j] = a + b;
                x[j + h] = a - b;
            }
    double scale = 1.0 / std::sqrt((double)nb);
    for (long i = 0; i < nb; i++) x[i] *= scale;
}

// FJLT columnwise: out (s, m) = sqrt(nb/s) · sample(H·(D ⊙ A)) per column.
static void sk_apply_fjlt_cw(const sl_sketch_t* t, const double* A, long m,
                             double* out) {
    const long n = t->n, nb = t->nb, s = t->s;
    std::vector<double> D(n);
    std::vector<long> samples(s);
    for (long i = 0; i < n; i++) {
        uint32_t hi, lo;
        sk_bits(t->seed, 0, t->base0 + (uint64_t)i, &hi, &lo);
        D[i] = (lo & 1u) ? 1.0 : -1.0;
    }
    for (long i = 0; i < s; i++) {
        uint32_t hi, lo;
        sk_bits(t->seed, 0, t->base1 + (uint64_t)i, &hi, &lo);
        samples[i] = (long)sk_uniform_int(hi, lo, 0, (uint32_t)(nb - 1));
    }
    const double scale = std::sqrt((double)nb / (double)s);
#pragma omp parallel
    {
        std::vector<double> work(nb);
#pragma omp for schedule(static)
        for (long c = 0; c < m; c++) {
            for (long i = 0; i < n; i++) work[i] = D[i] * A[i * m + c];
            std::fill(work.begin() + n, work.end(), 0.0);
            sk_fwht(work.data(), nb);
            for (long i = 0; i < s; i++)
                out[i * m + c] = scale * work[samples[i]];
        }
    }
}

// χ²_{2ν}(i) as a sum over lanes 1..2ν — MUST match
// core.random.chi2_lanes (shared by Matérn and Fastfood-Matérn).
static double sk_chi2(uint64_t seed, uint64_t base, uint64_t i, int two_nu) {
    double chi2 = 0.0;
    for (int lane = 1; lane <= two_nu; lane++) {
        uint32_t hi, lo;
        sk_bits(seed, (uint32_t)lane, base + i, &hi, &lo);
        double z = sk_normal(hi, lo);
        chi2 += z * z;
    }
    return chi2;
}

// RFT columnwise: out = outscale·cos(scale_i·(inscale·W·A)_i + shift);
// W normal (Gaussian/Matérn) or cauchy (Laplacian); Matérn multiplies
// per-row multivariate-t corrections sqrt(2ν/χ²_{2ν}) (chi2 from lanes
// 1..2ν on base2, matching core.random.chi2_lanes).  RLT:
// out = outscale·exp(−inscale·W·A) with W ~ Lévy.
// ≙ RFT_Elemental.hpp:85-120 / RFT_data.hpp:336-345 / RLT_Elemental.hpp:77.
static void sk_apply_rft_cw(const sl_sketch_t* t, const double* A, long m,
                            double* out) {
    const long n = t->n, s = t->s;
    const bool rlt = t->type == SL_RLT;  // rlt branch never reads dist
    const bool matern = t->type == SL_MRFT;
    const int dist =
        (t->type == SL_LRFT) ? SK_DIST_CAUCHY : SK_DIST_NORMAL;
    const double inscale =
        rlt ? (t->param * t->param / 2.0)
            : (1.0 / (matern ? t->param2 : t->param));
    const double outscale =
        rlt ? std::sqrt(1.0 / (double)s) : std::sqrt(2.0 / (double)s);
#pragma omp parallel for schedule(static)
    for (long i = 0; i < s; i++) {
        double* orow = out + i * m;
        for (long c = 0; c < m; c++) orow[c] = 0.0;
        for (long j = 0; j < n; j++) {
            uint32_t hi, lo;
            sk_bits(t->seed, 0, t->base0 + (uint64_t)(i * n + j), &hi, &lo);
            double w;
            if (rlt) {
                double z = sk_normal(hi, lo);
                w = 1.0 / (z * z);  // standard Lévy = 1/Z²
            } else {
                w = sk_draw(dist, hi, lo);
            }
            w *= inscale;
            const double* arow = A + j * m;
            for (long c = 0; c < m; c++) orow[c] += w * arow[c];
        }
        if (rlt) {
            for (long c = 0; c < m; c++)
                orow[c] = outscale * std::exp(-orow[c]);
        } else {
            if (matern) {
                int two_nu = (int)std::llround(2.0 * t->param);
                double chi2 = sk_chi2(t->seed, t->base2, (uint64_t)i, two_nu);
                double sc = std::sqrt(2.0 * t->param / chi2);
                for (long c = 0; c < m; c++) orow[c] *= sc;
            }
            uint32_t hi, lo;
            sk_bits(t->seed, 0, t->base1 + (uint64_t)i, &hi, &lo);
            double shift = sk_uniform01(hi, lo) * 2.0 * M_PI;
            for (long c = 0; c < m; c++)
                orow[c] = outscale * std::cos(orow[c] + shift);
        }
    }
}

// QMC feature maps (≙ sketch/rft.py QRFT / sketch/rlt.py QRLT): W rows
// from the leaped Halton sequence through inverse CDFs; no counters.
static void sk_apply_qmc_cw(const sl_sketch_t* t, const double* A, long m,
                            double* out) {
    const long n = t->n, s = t->s;
    const bool rlt = t->type == SL_QRLT;
    const long seq_d = rlt ? n : n + 1;  // QRFT uses dim n for the shift
    const long skip = (long)t->param2;
    std::vector<long> primes;
    sk_primes((int)seq_d + 1, primes);
    const long leap = primes[seq_d];  // (d+1)-th prime ≙ quasirand.py
    const double inscale =
        rlt ? (t->param * t->param / 2.0) : (1.0 / t->param);
    const double outscale =
        rlt ? std::sqrt(1.0 / (double)s) : std::sqrt(2.0 / (double)s);
#pragma omp parallel for schedule(static)
    for (long i = 0; i < s; i++) {
        double* orow = out + i * m;
        for (long c = 0; c < m; c++) orow[c] = 0.0;
        unsigned long long idx = (unsigned long long)(skip + i) *
                                 (unsigned long long)leap;
        for (long j = 0; j < n; j++) {
            double u = sk_radical_inverse(primes[j], idx);
            double w;
            if (rlt) {
                double z = sk_ndtri(u / 2.0);
                w = 1.0 / (z * z);  // Lévy quantile
            } else if (t->type == SL_LQRFT) {
                w = std::tan(M_PI * (u - 0.5));
            } else {
                w = sk_ndtri(u);
            }
            w *= inscale;
            const double* arow = A + j * m;
            for (long c = 0; c < m; c++) orow[c] += w * arow[c];
        }
        if (rlt) {
            for (long c = 0; c < m; c++)
                orow[c] = outscale * std::exp(-orow[c]);
        } else {
            double shift =
                2.0 * M_PI * sk_radical_inverse(primes[n], idx);
            for (long c = 0; c < m; c++)
                orow[c] = outscale * std::cos(orow[c] + shift);
        }
    }
}

// PPT / TensorSketch columnwise (≙ sketch/ppt.py): q CountSketches
// composed in the FFT domain; any S (Bluestein for non-pow2).
static void sk_apply_ppt_cw(const sl_sketch_t* t, const double* A, long m,
                            double* out) {
    const long n = t->n, s = t->s, q = (long)t->nb;
    const double sqrt_c = std::sqrt(t->param);
    const double sqrt_g = std::sqrt(t->param2);
    // Per-level CWT hash arrays + the constant-term hash.
    std::vector<long> buckets(q * n);
    std::vector<double> values(q * n);
    std::vector<long> hidx(q);
    std::vector<double> hval(q);
    for (long l = 0; l < q; l++) {
        uint64_t idx_base = t->base0 + (uint64_t)(l * 2 * n);
        uint64_t val_base = idx_base + (uint64_t)n;
        for (long i = 0; i < n; i++) {
            uint32_t hi, lo;
            sk_bits(t->seed, 0, idx_base + (uint64_t)i, &hi, &lo);
            buckets[l * n + i] =
                (long)sk_uniform_int(hi, lo, 0, (uint32_t)(s - 1));
            sk_bits(t->seed, 0, val_base + (uint64_t)i, &hi, &lo);
            values[l * n + i] = (lo & 1u) ? 1.0 : -1.0;
        }
        uint32_t hi, lo;
        sk_bits(t->seed, 0, t->base1 + (uint64_t)l, &hi, &lo);
        hidx[l] = (long)sk_uniform_int(hi, lo, 0, (uint32_t)(s - 1));
        sk_bits(t->seed, 0, t->base2 + (uint64_t)l, &hi, &lo);
        hval[l] = (lo & 1u) ? 1.0 : -1.0;
    }
#pragma omp parallel
    {
        std::vector<std::complex<double>> P(s), W(s);
#pragma omp for schedule(static)
        for (long c = 0; c < m; c++) {
            for (long k = 0; k < s; k++) P[k] = {1.0, 0.0};
            for (long l = 0; l < q; l++) {
                for (long k = 0; k < s; k++) W[k] = {0.0, 0.0};
                for (long i = 0; i < n; i++)
                    W[buckets[l * n + i]] +=
                        sqrt_g * values[l * n + i] * A[i * m + c];
                W[hidx[l]] += sqrt_c * hval[l];
                sk_fft_any(W.data(), s, false);
                for (long k = 0; k < s; k++) P[k] *= W[k];
            }
            sk_fft_any(P.data(), s, true);
            for (long k = 0; k < s; k++) out[k * m + c] = P[k].real();
        }
    }
}

// Fastfood columnwise (≙ FRFT_Elemental.hpp / sketch/frft.py _features):
// per block: H·(B⊙x) → permute → G⊙ → H → Sm⊙; first S coords; cos.
static void sk_apply_frft_cw(const sl_sketch_t* t, const double* A, long m,
                             double* out) {
    const long n = t->n, nb = t->nb, s = t->s;
    const long numblks = 1 + (s - 1) / nb;
    const bool matern = t->type == SL_FMRFT;
    const uint64_t chi_base = t->base3 + (uint64_t)(numblks * nb);

    // Counter-derived per-block data.
    std::vector<double> B(numblks * nb), G(numblks * nb);
    std::vector<long> perm(numblks * nb);
    std::vector<double> Sm(numblks * nb);
    for (long i = 0; i < numblks * nb; i++) {
        uint32_t hi, lo;
        sk_bits(t->seed, 0, t->base1 + (uint64_t)i, &hi, &lo);
        B[i] = (lo & 1u) ? 1.0 : -1.0;
        sk_bits(t->seed, 0, t->base2 + (uint64_t)i, &hi, &lo);
        G[i] = sk_normal(hi, lo);
    }
    for (long b = 0; b < numblks; b++) {
        // argsort (stable) of f32 uniform keys, matching jnp.argsort.
        std::vector<std::pair<float, long>> keys(nb);
        for (long j = 0; j < nb; j++) {
            uint32_t hi, lo;
            sk_bits(t->seed, 0, t->base3 + (uint64_t)(b * nb + j), &hi, &lo);
            keys[j] = {sk_uniform01_f32(hi), j};
        }
        std::stable_sort(keys.begin(), keys.end(),
                         [](const std::pair<float, long>& a,
                            const std::pair<float, long>& x) {
                             return a.first < x.first;
                         });
        for (long j = 0; j < nb; j++) perm[b * nb + j] = keys[j].second;
    }
    for (long i = 0; i < numblks * nb; i++) {
        if (matern) {
            int two_nu = (int)std::llround(2.0 * t->param);
            double chi2 = sk_chi2(t->seed, chi_base, (uint64_t)i, two_nu);
            Sm[i] = std::sqrt(2.0 * t->param / chi2) *
                    (std::sqrt((double)nb) / t->param2);
        } else {
            Sm[i] = std::sqrt((double)nb) / t->param;  // sqrt(NB)/sigma
        }
    }
    std::vector<double> shifts(s);
    for (long i = 0; i < s; i++) {
        uint32_t hi, lo;
        sk_bits(t->seed, 0, t->base0 + (uint64_t)i, &hi, &lo);
        shifts[i] = sk_uniform01(hi, lo) * 2.0 * M_PI;
    }
    const double outscale = std::sqrt(2.0 / (double)s);
#pragma omp parallel
    {
        std::vector<double> work(nb), tmp(nb);
#pragma omp for schedule(static)
        for (long c = 0; c < m; c++) {
            // The block writes below cover exactly rows [0, s).
            for (long b = 0; b < numblks; b++) {
                for (long j = 0; j < n; j++)
                    work[j] = B[b * nb + j] * A[j * m + c];
                std::fill(work.begin() + n, work.end(), 0.0);
                sk_fwht(work.data(), nb);
                for (long j = 0; j < nb; j++)
                    tmp[j] = G[b * nb + j] * work[perm[b * nb + j]];
                sk_fwht(tmp.data(), nb);
                for (long j = 0; j < nb && b * nb + j < s; j++)
                    out[(b * nb + j) * m + c] =
                        outscale * std::cos(tmp[j] * Sm[b * nb + j] +
                                            shifts[b * nb + j]);
            }
        }
    }
}

// dim: 0 = columnwise (A (n, m) -> (s, m)), 1 = rowwise (A (m, n) -> (m, s)).
int sl_apply_sketch_transform(void* t_, const double* A, long rows, long cols,
                              int dim, double* out) {
    const sl_sketch_t* t = (sl_sketch_t*)t_;
    if (dim == 0) {
        if (rows != t->n) return 102;
        switch (t->type) {
            case SL_JLT: case SL_CT: sk_apply_dense_cw(t, A, cols, out); break;
            case SL_UST: sk_apply_ust_cw(t, A, cols, out); break;
            case SL_FJLT: sk_apply_fjlt_cw(t, A, cols, out); break;
            case SL_GRFT: case SL_LRFT: case SL_RLT: case SL_MRFT:
                sk_apply_rft_cw(t, A, cols, out); break;
            case SL_FGRFT: case SL_FMRFT:
                sk_apply_frft_cw(t, A, cols, out); break;
            case SL_GQRFT: case SL_LQRFT: case SL_QRLT:
                sk_apply_qmc_cw(t, A, cols, out); break;
            case SL_PPT: sk_apply_ppt_cw(t, A, cols, out); break;
            default: sk_apply_hash_cw(t, A, cols, out); break;
        }
        return 0;
    }
    if (cols != t->n) return 102;
    // rowwise = columnwise on the transpose.
    std::vector<double> AT((size_t)rows * cols), OT((size_t)t->s * rows);
    for (long r = 0; r < rows; r++)
        for (long c = 0; c < cols; c++) AT[(size_t)c * rows + r] = A[(size_t)r * cols + c];
    int rc = sl_apply_sketch_transform((void*)t, AT.data(), cols, rows, 0,
                                       OT.data());
    if (rc) return rc;
    for (long r = 0; r < rows; r++)
        for (long i = 0; i < t->s; i++)
            out[(size_t)r * t->s + i] = OT[(size_t)i * rows + r];
    return 0;
}

// JSON schema identical to sketch.base.SketchTransform.to_dict().
int sl_serialize_sketch_transform(void* t_, char** out) {
    const sl_sketch_t* t = (sl_sketch_t*)t_;
    char extra[96] = "";
    if (t->type == SL_CT)
        snprintf(extra, sizeof extra, ", \"C\": %.17g", t->param);
    else if (t->type == SL_WZT)
        snprintf(extra, sizeof extra, ", \"P\": %.17g", t->param);
    else if (t->type == SL_UST)
        snprintf(extra, sizeof extra, ", \"replace\": %s",
                 t->param != 0.0 ? "true" : "false");
    else if (t->type == SL_FJLT)
        snprintf(extra, sizeof extra, ", \"fut\": \"wht\"");
    else if (t->type == SL_GRFT || t->type == SL_LRFT ||
             t->type == SL_FGRFT)
        snprintf(extra, sizeof extra, ", \"sigma\": %.17g", t->param);
    else if (t->type == SL_RLT)
        snprintf(extra, sizeof extra, ", \"beta\": %.17g", t->param);
    else if (t->type == SL_MRFT || t->type == SL_FMRFT)
        snprintf(extra, sizeof extra, ", \"nu\": %.17g, \"l\": %.17g",
                 t->param, t->param2);
    else if (t->type == SL_GQRFT || t->type == SL_LQRFT)
        snprintf(extra, sizeof extra, ", \"sigma\": %.17g, \"skip\": %ld",
                 t->param, (long)t->param2);
    else if (t->type == SL_QRLT)
        snprintf(extra, sizeof extra, ", \"beta\": %.17g, \"skip\": %ld",
                 t->param, (long)t->param2);
    else if (t->type == SL_PPT)
        snprintf(extra, sizeof extra,
                 ", \"q\": %ld, \"c\": %.17g, \"gamma\": %.17g", (long)t->nb,
                 t->param, t->param2);
    char* buf = (char*)malloc(512);
    snprintf(buf, 512,
             "{\"skylark_object_type\": \"sketch\", \"skylark_version\": 2, "
             "\"sketch_type\": \"%s\", \"N\": %ld, \"S\": %ld, "
             "\"creation_context\": {\"skylark_object_type\": \"context\", "
             "\"skylark_version\": 2, \"seed\": %llu, \"counter\": %llu}%s}",
             sk_name_from_type(t->type), t->n, t->s,
             (unsigned long long)t->seed, (unsigned long long)t->ctx_counter,
             extra);
    *out = buf;
    return 0;
}

void sl_free_str(char* s) { free(s); }

// Introspection (≙ sl_supported_sketch_transforms, capi/csketch.cpp:74+).
// The reference enumerates ~190 (type, input-dist, output-dist) combos;
// per-distribution specializations collapse here (host arrays, sharding
// handled by the JAX layer), so each type supports one matrix kind in
// both directions.  One "TYPE Matrix Matrix direction" line per combo.
int sl_supported_sketch_transforms(char** out) {
    std::string s;
    for (int t = 0; t < SL_NUM_SKETCH_TYPES; ++t) {
        s += sk_name_from_type(t);
        s += " Matrix Matrix columnwise\n";
        s += sk_name_from_type(t);
        s += " Matrix Matrix rowwise\n";
    }
    char* buf = (char*)malloc(s.size() + 1);
    if (!buf) return 101;
    memcpy(buf, s.c_str(), s.size() + 1);
    *out = buf;
    return 0;
}

// Minimal JSON field extraction (flat schema written by ourselves/Python).
static bool js_find_num(const char* js, const char* key, double* val) {
    std::string pat = std::string("\"") + key + "\":";
    const char* p = strstr(js, pat.c_str());
    if (!p) return false;
    p += pat.size();
    *val = strtod(p, nullptr);
    return true;
}

// Span of the "maps": [ ... ] array: *key_pos = start of the "maps" key,
// *arr_open / *arr_close = the bracket positions.  Shared by the map-
// object splitter and the top-level-key scoping below so the two never
// drift on bracket-matching rules.  Returns false when no complete array
// exists; an unterminated array reports close = npos with key/open set.
static bool js_maps_span(const std::string& js, size_t* key_pos,
                         size_t* arr_open, size_t* arr_close) {
    *arr_open = *arr_close = std::string::npos;
    *key_pos = js.find("\"maps\":");
    if (*key_pos == std::string::npos) return false;
    *arr_open = js.find('[', *key_pos);
    if (*arr_open == std::string::npos) return false;
    int depth = 0;
    for (size_t i = *arr_open + 1; i < js.size(); i++) {
        char ch = js[i];
        if (ch == '{') depth++;
        else if (ch == '}') depth--;
        else if (ch == ']' && depth == 0) {
            *arr_close = i;
            return true;
        }
    }
    return false;
}

// Model JSON with the "maps" array excised: top-level keys only.  A
// per-map "skylark_version" in a hand-edited / foreign-writer file whose
// top-level key is absent or ordered after "maps" must not masquerade as
// the model's stream version (round-2 advisor finding).
static std::string js_without_maps(const std::string& js) {
    size_t key, open, close;
    if (!js_maps_span(js, &key, &open, &close))
        // No maps key/bracket: nothing to excise.  Unterminated array:
        // keep the prefix only (close == npos distinguishes the cases).
        return key == std::string::npos || open == std::string::npos
                   ? js
                   : js.substr(0, key);
    return js.substr(0, key) + js.substr(close + 1);
}

// Full 64-bit precision (seed/counter can exceed 2^53).
static bool js_find_u64(const char* js, const char* key, uint64_t* val) {
    std::string pat = std::string("\"") + key + "\":";
    const char* p = strstr(js, pat.c_str());
    if (!p) return false;
    p += pat.size();
    *val = strtoull(p, nullptr, 10);
    return true;
}

static bool js_find_str(const char* js, const char* key, char* out, size_t cap) {
    std::string pat = std::string("\"") + key + "\":";
    const char* p = strstr(js, pat.c_str());
    if (!p) return false;
    p += pat.size();
    while (*p == ' ') p++;
    if (*p != '"') return false;
    p++;
    size_t i = 0;
    while (*p && *p != '"' && i + 1 < cap) out[i++] = *p++;
    out[i] = 0;
    return true;
}

int sl_deserialize_sketch_transform(const char* json, void** out) {
    // Python json.dumps uses ", " / ": " separators; normalize spaces.
    std::string norm;
    norm.reserve(strlen(json));
    for (const char* p = json; *p; p++)
        if (*p != ' ' && *p != '\n') norm.push_back(*p);
    char type[32];
    double n, s;
    uint64_t seed, counter;
    if (!js_find_str(norm.c_str(), "sketch_type", type, sizeof type) ||
        !js_find_num(norm.c_str(), "N", &n) ||
        !js_find_num(norm.c_str(), "S", &s) ||
        !js_find_u64(norm.c_str(), "seed", &seed) ||
        !js_find_u64(norm.c_str(), "counter", &counter))
        return 103;
    double param = 0.0;
    if (!strcmp(type, "CT")) { js_find_num(norm.c_str(), "C", &param); if (param == 0) param = 1.0; }
    else if (!strcmp(type, "WZT")) { js_find_num(norm.c_str(), "P", &param); if (param == 0) param = 2.0; }
    else if (!strcmp(type, "UST")) {
        param = strstr(norm.c_str(), "\"replace\":false") ? 0.0 : 1.0;
    }
    double param2 = 0.0;
    if (!strcmp(type, "GaussianRFT") || !strcmp(type, "LaplacianRFT") ||
        !strcmp(type, "FastGaussianRFT")) {
        js_find_num(norm.c_str(), "sigma", &param);
        if (param == 0) param = 1.0;
    }
    else if (!strcmp(type, "ExpSemigroupRLT")) {
        js_find_num(norm.c_str(), "beta", &param);
        if (param == 0) param = 1.0;
    }
    else if (!strcmp(type, "MaternRFT") || !strcmp(type, "FastMaternRFT")) {
        js_find_num(norm.c_str(), "nu", &param);
        js_find_num(norm.c_str(), "l", &param2);
    }
    else if (!strcmp(type, "FJLT")) {
        if (strstr(norm.c_str(), "\"fut\":\"dct\"")) return 104;  // wht only
    }
    double param3 = 0.0;
    if (!strcmp(type, "GaussianQRFT") || !strcmp(type, "LaplacianQRFT")) {
        js_find_num(norm.c_str(), "sigma", &param);
        if (param == 0) param = 1.0;
        js_find_num(norm.c_str(), "skip", &param2);
    }
    else if (!strcmp(type, "ExpSemigroupQRLT")) {
        js_find_num(norm.c_str(), "beta", &param);
        if (param == 0) param = 1.0;
        js_find_num(norm.c_str(), "skip", &param2);
    }
    else if (!strcmp(type, "PPT")) {
        // Absent keys default to the reference's (c=1, gamma=1, q=3);
        // present zeros are preserved (c=0 / gamma=0 are legal).
        if (!js_find_num(norm.c_str(), "c", &param)) param = 1.0;
        if (!js_find_num(norm.c_str(), "gamma", &param2)) param2 = 1.0;
        if (!js_find_num(norm.c_str(), "q", &param3)) param3 = 3.0;
    }
    sl_context_t ctx{seed, counter};
    return sl_create_sketch_transform_ex(&ctx, type, (long)n, (long)s, param,
                                         param2, param3, out);
}

const char* sl_error_string(int code) {
    switch (code) {
        case 0: return "ok";
        case 100: return "skylark error";
        case 101: return "allocation error";
        case 102: return "invalid parameters";
        case 103: return "sketch error";
        case 104: return "unsupported";
        case 105: return "io error";
    }
    return "unknown";
}

// ---------------------------------------------------------------------------
// LIBSVM parser (multithreaded two-pass; ≙ utility/io/libsvm_io.hpp)
// ---------------------------------------------------------------------------

struct sk_chunk_stats { long rows, nnz, max_col; };

static void sk_count_chunk(const char* buf, size_t lo, size_t hi,
                           sk_chunk_stats* st) {
    long rows = 0, nnz = 0, max_col = 0;
    for (size_t i = lo; i < hi;) {
        // one line
        size_t eol = i;
        while (eol < hi && buf[eol] != '\n') eol++;
        // skip blank / comment-only
        size_t j = i;
        while (j < eol && (buf[j] == ' ' || buf[j] == '\t')) j++;
        if (j < eol && buf[j] != '#') {
            rows++;
            for (size_t p = j; p < eol; p++) {
                if (buf[p] == '#') break;
                if (buf[p] == ':') {
                    nnz++;
                    // walk back to read the column index
                    size_t q = p;
                    while (q > j && buf[q - 1] >= '0' && buf[q - 1] <= '9') q--;
                    long col = strtol(buf + q, nullptr, 10);
                    if (col > max_col) max_col = col;
                }
            }
        }
        i = eol + 1;
    }
    st->rows = rows;
    st->nnz = nnz;
    st->max_col = max_col;
}

int sl_libsvm_count(const char* buf, long len, long* n_rows, long* n_nnz,
                    long* max_col) {
    int nt = std::max(1u, std::thread::hardware_concurrency());
    if (len < 1 << 16) nt = 1;
    std::vector<size_t> bounds(nt + 1, 0);
    bounds[nt] = (size_t)len;
    for (int t = 1; t < nt; t++) {
        size_t pos = (size_t)len * t / nt;
        while (pos < (size_t)len && buf[pos] != '\n') pos++;
        bounds[t] = pos < (size_t)len ? pos + 1 : (size_t)len;
    }
    std::vector<sk_chunk_stats> stats(nt);
    std::vector<std::thread> th;
    for (int t = 0; t < nt; t++)
        th.emplace_back(sk_count_chunk, buf, bounds[t], bounds[t + 1],
                        &stats[t]);
    for (auto& x : th) x.join();
    long rows = 0, nnz = 0, mc = 0;
    for (auto& s : stats) {
        rows += s.rows;
        nnz += s.nnz;
        mc = std::max(mc, s.max_col);
    }
    *n_rows = rows;
    *n_nnz = nnz;
    *max_col = mc;
    return 0;
}

// Parse into preallocated arrays.  Row order is file order; two passes
// (count per chunk, then fill with per-chunk offsets).
struct sk_parse_job {
    const char* buf;
    size_t lo, hi;
    long row0, nnz0;
    double* labels;
    long* rows;
    long* cols;
    double* vals;
    long expect_nnz;
    int* status;  // 0 ok, nonzero = malformed chunk
};

static void sk_parse_chunk(sk_parse_job job) {
    long r = job.row0, k = job.nnz0;
    const char* buf = job.buf;
    int bad = 0;
    for (size_t i = job.lo; i < job.hi;) {
        size_t eol = i;
        while (eol < job.hi && buf[eol] != '\n') eol++;
        size_t j = i;
        while (j < eol && (buf[j] == ' ' || buf[j] == '\t')) j++;
        if (j < eol && buf[j] != '#') {
            char* end;
            job.labels[r] = strtod(buf + j, &end);
            const char* p = end;
            while (p < buf + eol) {
                while (p < buf + eol && (*p == ' ' || *p == '\t')) p++;
                if (p >= buf + eol || *p == '#') break;
                long col = strtol(p, &end, 10);
                if (end == p) { bad = 1; break; }  // non-numeric token
                p = end;
                if (*p != ':') { bad = 1; break; }
                if (col < 1) bad = 1;  // 1-based indices only
                p++;
                double v = strtod(p, &end);
                p = end;
                job.rows[k] = r;
                job.cols[k] = col - 1;
                job.vals[k] = v;
                k++;
            }
            r++;
        }
        i = eol + 1;
    }
    // Any count/parse disagreement (malformed tokens) invalidates the
    // chunk: the caller falls back to the strict Python parser.
    if (k - job.nnz0 != job.expect_nnz) bad = 1;
    *job.status = bad;
}

int sl_libsvm_parse(const char* buf, long len, double* labels, long* rows,
                    long* cols, double* vals) {
    int nt = std::max(1u, std::thread::hardware_concurrency());
    if (len < 1 << 16) nt = 1;
    std::vector<size_t> bounds(nt + 1, 0);
    bounds[nt] = (size_t)len;
    for (int t = 1; t < nt; t++) {
        size_t pos = (size_t)len * t / nt;
        while (pos < (size_t)len && buf[pos] != '\n') pos++;
        bounds[t] = pos < (size_t)len ? pos + 1 : (size_t)len;
    }
    std::vector<sk_chunk_stats> stats(nt);
    {
        std::vector<std::thread> th;
        for (int t = 0; t < nt; t++)
            th.emplace_back(sk_count_chunk, buf, bounds[t], bounds[t + 1],
                            &stats[t]);
        for (auto& x : th) x.join();
    }
    std::vector<std::thread> th;
    std::vector<int> status(nt, 0);
    long row0 = 0, nnz0 = 0;
    for (int t = 0; t < nt; t++) {
        sk_parse_job job{buf,  bounds[t], bounds[t + 1], row0, nnz0,
                         labels, rows,     cols,          vals,
                         stats[t].nnz, &status[t]};
        th.emplace_back(sk_parse_chunk, job);
        row0 += stats[t].rows;
        nnz0 += stats[t].nnz;
    }
    for (auto& x : th) x.join();
    for (int t = 0; t < nt; t++)
        if (status[t]) return 105;  // IO error -> caller falls back
    return 0;
}



// ---------------------------------------------------------------------------
// Kernel grams + randomized NLA (≙ capi/ckernel.cpp, capi/cnla.cpp).
// Dense row-major f64 host arrays; OpenMP loops (the C consumers the
// reference serves are CPU-side; the TPU path lives in the JAX layer).
// ---------------------------------------------------------------------------

static void sk_matmul(const double* A, const double* B, double* C,
                      long m, long k, long n, bool transA, bool transB) {
    // C (m x n) = op(A) op(B), all row-major.
#pragma omp parallel for schedule(static)
    for (long i = 0; i < m; i++) {
        double* crow = C + i * n;
        for (long j = 0; j < n; j++) crow[j] = 0.0;
        for (long p = 0; p < k; p++) {
            double a = transA ? A[p * m + i] : A[i * k + p];
            if (a == 0.0) continue;
            for (long j = 0; j < n; j++) {
                double b = transB ? B[j * k + p] : B[p * n + j];
                crow[j] += a * b;
            }
        }
    }
}

static int sk_cholesky(double* G, long s) {
    // In-place lower Cholesky of s x s row-major G; 0 on success.
    for (long j = 0; j < s; j++) {
        double d = G[j * s + j];
        for (long p = 0; p < j; p++) d -= G[j * s + p] * G[j * s + p];
        if (d <= 0.0) return 103;
        d = std::sqrt(d);
        G[j * s + j] = d;
        for (long i = j + 1; i < s; i++) {
            double v = G[i * s + j];
            for (long p = 0; p < j; p++) v -= G[i * s + p] * G[j * s + p];
            G[i * s + j] = v / d;
        }
    }
    return 0;
}

static void sk_chol_solve_inplace(const double* L, double* B, long s, long t) {
    // Solve (L L^T) X = B for X in-place; B is s x t row-major.
    for (long c = 0; c < t; c++) {
        for (long i = 0; i < s; i++) {
            double v = B[i * t + c];
            for (long p = 0; p < i; p++) v -= L[i * s + p] * B[p * t + c];
            B[i * t + c] = v / L[i * s + i];
        }
        for (long i = s - 1; i >= 0; i--) {
            double v = B[i * t + c];
            for (long p = i + 1; p < s; p++) v -= L[p * s + i] * B[p * t + c];
            B[i * t + c] = v / L[i * s + i];
        }
    }
}

static int sk_cholqr(double* Y, long m, long s) {
    // Orthonormalize columns of Y (m x s row-major) via CholeskyQR2 with
    // a relative ridge: exactly rank-deficient Y (sketches of low-rank A)
    // would break plain Cholesky; ridged null directions come out with
    // ~zero singular content and are dropped by the rank-k truncation
    // (same rationale as the JAX layer's eigh floor in gram_orth).
    std::vector<double> G(s * s);
    for (int pass = 0; pass < 2; pass++) {
        sk_matmul(Y, Y, G.data(), s, m, s, true, false);
        double trace = 0.0;
        for (long i = 0; i < s; i++) trace += G[i * s + i];
        double ridge = 1e-12 * (trace > 0 ? trace / s : 1.0);
        for (long i = 0; i < s; i++) G[i * s + i] += ridge;
        int rc = sk_cholesky(G.data(), s);
        if (rc) return rc;
        // Y <- Y L^{-T}: solve row-wise x L^T = y.
#pragma omp parallel for schedule(static)
        for (long i = 0; i < m; i++) {
            double* row = Y + i * s;
            for (long j = 0; j < s; j++) {
                double v = row[j];
                for (long p = 0; p < j; p++) v -= G[j * s + p] * row[p];
                row[j] = v / G[j * s + j];
            }
        }
    }
    return 0;
}

static void sk_jacobi_svd(double* M, double* V, double* sig, long n, long s) {
    // One-sided Jacobi: M (n x s, row-major) -> M = U diag(sig) V^T with
    // the orthonormal U overwriting M's columns and V (s x s) accumulated.
    for (long i = 0; i < s; i++)
        for (long j = 0; j < s; j++) V[i * s + j] = (i == j) ? 1.0 : 0.0;
    const double tol = 1e-14;
    for (int sweep = 0; sweep < 60; sweep++) {
        double off = 0.0;
        for (long p = 0; p < s - 1; p++)
            for (long q = p + 1; q < s; q++) {
                double app = 0, aqq = 0, apq = 0;
                for (long i = 0; i < n; i++) {
                    double x = M[i * s + p], y = M[i * s + q];
                    app += x * x; aqq += y * y; apq += x * y;
                }
                if (std::fabs(apq) <= tol * std::sqrt(app * aqq)) continue;
                off = std::max(off, std::fabs(apq));
                double tau = (aqq - app) / (2.0 * apq);
                double t = (tau >= 0 ? 1.0 : -1.0) /
                           (std::fabs(tau) + std::sqrt(1.0 + tau * tau));
                double c = 1.0 / std::sqrt(1.0 + t * t), sn = c * t;
                for (long i = 0; i < n; i++) {
                    double x = M[i * s + p], y = M[i * s + q];
                    M[i * s + p] = c * x - sn * y;
                    M[i * s + q] = sn * x + c * y;
                }
                for (long i = 0; i < s; i++) {
                    double x = V[i * s + p], y = V[i * s + q];
                    V[i * s + p] = c * x - sn * y;
                    V[i * s + q] = sn * x + c * y;
                }
            }
        if (off == 0.0) break;
    }
    for (long j = 0; j < s; j++) {
        double nrm = 0.0;
        for (long i = 0; i < n; i++) nrm += M[i * s + j] * M[i * s + j];
        sig[j] = std::sqrt(nrm);
        if (sig[j] > 0)
            for (long i = 0; i < n; i++) M[i * s + j] /= sig[j];
    }
}

int sl_kernel_gram(int kernel_type, double p1, double p2, double p3,
                   const double* X, long nx, const double* Y, long ny,
                   long d, double* K) {
    // K[i, j] = k(X[i], Y[j]); X (nx x d), Y (ny x d) row-major.
    if (!X || !Y || !K || nx <= 0 || ny <= 0 || d <= 0) return 102;
    if (kernel_type < 0 || kernel_type > 5) return 104;
    // Matern coefficients depend only on p = floor(nu): hoist the
    // factorial table out of the entry loops.
    long mat_p = 0;
    double mat_scale = 1.0;
    std::vector<double> mat_coef;
    if (kernel_type == 5) {
        mat_p = (long)std::floor(p1);  // nu = p + 1/2
        double fact_p = 1.0, fact_2p = 1.0;
        for (long u = 2; u <= mat_p; u++) fact_p *= u;
        for (long u = 2; u <= 2 * mat_p; u++) fact_2p *= u;
        mat_scale = fact_p / fact_2p;
        mat_coef.resize(mat_p + 1);
        for (long i2 = 0; i2 <= mat_p; i2++) {
            double num = 1.0, di = 1.0, dpi = 1.0;
            for (long u = 2; u <= mat_p + i2; u++) num *= u;
            for (long u = 2; u <= i2; u++) di *= u;
            for (long u = 2; u <= mat_p - i2; u++) dpi *= u;
            mat_coef[i2] = num / (di * dpi);
        }
    }
#pragma omp parallel for schedule(static)
    for (long i = 0; i < nx; i++) {
        const double* xi = X + i * d;
        for (long j = 0; j < ny; j++) {
            const double* yj = Y + j * d;
            double v = 0.0;
            switch (kernel_type) {
                case 0: {  // linear
                    for (long c = 0; c < d; c++) v += xi[c] * yj[c];
                    break;
                }
                case 1: {  // gaussian, p1 = sigma
                    double d2 = 0.0;
                    for (long c = 0; c < d; c++) {
                        double t = xi[c] - yj[c]; d2 += t * t;
                    }
                    v = std::exp(-d2 / (2.0 * p1 * p1));
                    break;
                }
                case 2: {  // polynomial, p1 = q, p2 = c, p3 = gamma
                    double ip = 0.0;
                    for (long c = 0; c < d; c++) ip += xi[c] * yj[c];
                    v = std::pow(p3 * ip + p2, p1);
                    break;
                }
                case 3: {  // laplacian, p1 = sigma
                    double l1 = 0.0;
                    for (long c = 0; c < d; c++) l1 += std::fabs(xi[c] - yj[c]);
                    v = std::exp(-l1 / p1);
                    break;
                }
                case 4: {  // expsemigroup, p1 = beta (nonnegative inputs)
                    double sg = 0.0;
                    for (long c = 0; c < d; c++) {
                        double a = xi[c] + yj[c];
                        sg += std::sqrt(a > 0 ? a : 0.0);
                    }
                    v = std::exp(-p1 * sg);
                    break;
                }
                case 5: {  // matern, p1 = nu (half-integer), p2 = l
                    double d2 = 0.0;
                    for (long c = 0; c < d; c++) {
                        double t = xi[c] - yj[c]; d2 += t * t;
                    }
                    double a = std::sqrt(2.0 * p1) * std::sqrt(d2) / p2;
                    // k = exp(-a) * p!/(2p)! * sum_i coef[i] (2a)^{p-i}
                    double sum = 0.0;
                    for (long i2 = 0; i2 <= mat_p; i2++)
                        sum += mat_coef[i2] *
                               std::pow(2.0 * a, (double)(mat_p - i2));
                    v = std::exp(-a) * mat_scale * sum;
                    break;
                }
            }
            K[i * ny + j] = v;
        }
    }
    return 0;
}

int sl_approximate_svd(void* vctx, const double* A, long m, long n, long k,
                       int num_iterations, double* U, double* S, double* V) {
    // Randomized truncated SVD (≙ capi/cnla.cpp ApproximateSVD): A (m x n)
    // row-major; U (m x k), S (k), V (n x k).  Oversampling 2k, CholQR2,
    // one-sided Jacobi on the small factor.
    if (!vctx || !A || !U || !S || !V) return 102;
    if (k <= 0 || k > (m < n ? m : n)) return 102;
    sl_context_t* ctx = (sl_context_t*)vctx;
    long s = 2 * k; if (s > n) s = n;
    // Omega (n x s) from the context stream (counter-deterministic).
    std::vector<double> Om((size_t)n * s);
    uint64_t base = ctx->counter; ctx->counter += (uint64_t)(n * s);
#pragma omp parallel for schedule(static)
    for (long i = 0; i < n * s; i++) {
        uint32_t hi, lo;
        sk_bits(ctx->seed, 0, base + (uint64_t)i, &hi, &lo);
        Om[i] = sk_draw(SK_DIST_NORMAL, hi, lo);
    }
    std::vector<double> Y((size_t)m * s), W((size_t)n * s);
    sk_matmul(A, Om.data(), Y.data(), m, n, s, false, false);
    for (int it = 0; it < num_iterations; it++) {
        sk_matmul(A, Y.data(), W.data(), n, m, s, true, false);  // W = A^T Y
        int rc = sk_cholqr(W.data(), n, s);
        if (rc) return rc;
        sk_matmul(A, W.data(), Y.data(), m, n, s, false, false);  // Y = A W
    }
    int rc = sk_cholqr(Y.data(), m, s);  // Q in Y
    if (rc) return rc;
    // B = Q^T A (s x n); Jacobi on B^T (n x s).
    std::vector<double> Bt((size_t)n * s), Vs((size_t)s * s), sig(s);
    {
        std::vector<double> B((size_t)s * n);
        sk_matmul(Y.data(), A, B.data(), s, m, n, true, false);
        for (long i = 0; i < s; i++)
            for (long j = 0; j < n; j++) Bt[j * s + i] = B[i * n + j];
    }
    sk_jacobi_svd(Bt.data(), Vs.data(), sig.data(), n, s);
    // B = Vs diag(sig) Bt^T: left vectors Vs, right vectors Bt columns.
    std::vector<long> ord(s);
    for (long i = 0; i < s; i++) ord[i] = i;
    std::sort(ord.begin(), ord.end(),
              [&](long a, long b) { return sig[a] > sig[b]; });
    for (long j = 0; j < k; j++) {
        long c = ord[j];
        S[j] = sig[c];
        for (long i = 0; i < n; i++) V[i * k + j] = Bt[i * s + c];
    }
    // U = Q (m x s) * Vs[:, ord[:k]]
#pragma omp parallel for schedule(static)
    for (long i = 0; i < m; i++) {
        for (long j = 0; j < k; j++) {
            long c = ord[j];
            double v = 0.0;
            for (long p = 0; p < s; p++) v += Y[i * s + p] * Vs[p * s + c];
            U[i * k + j] = v;
        }
    }
    return 0;
}

int sl_approximate_least_squares(void* vctx, const double* A, const double* b,
                                 long m, long n, long t, long sketch_size,
                                 double* x) {
    // Sketch-and-solve LS (≙ capi/cnla.cpp): CWT sketch of [A b] to
    // sketch_size rows, then normal equations on the small problem.
    // A (m x n), b (m x t), x (n x t), all row-major.
    if (!vctx || !A || !b || !x) return 102;
    if (m <= 0 || n <= 0 || t <= 0) return 102;
    long ss = sketch_size > 0 ? sketch_size : 4 * n;
    if (ss > m) ss = m;
    sl_context_t* ctx = (sl_context_t*)vctx;
    void* st = nullptr;
    int rc = sl_create_sketch_transform(vctx, "CWT", m, ss, 0.0, &st);
    if (rc || !st) return rc ? rc : 103;
    std::vector<double> SA((size_t)ss * n), Sb((size_t)ss * t);
    // Columnwise apply: inputs are (m x cols) row-major, exactly A and b.
    rc = sl_apply_sketch_transform(st, A, m, n, 0, SA.data());
    if (!rc) rc = sl_apply_sketch_transform(st, b, m, t, 0, Sb.data());
    sl_free_sketch_transform(st);
    if (rc) return rc;
    std::vector<double> G((size_t)n * n), rhs((size_t)n * t);
    sk_matmul(SA.data(), SA.data(), G.data(), n, ss, n, true, false);
    sk_matmul(SA.data(), Sb.data(), rhs.data(), n, ss, t, true, false);
    // Tiny ridge for numerical safety on rank-deficient sketches.
    double trace = 0.0;
    for (long i = 0; i < n; i++) trace += G[i * n + i];
    double eps = 1e-12 * (trace > 0 ? trace / n : 1.0);
    for (long i = 0; i < n; i++) G[i * n + i] += eps;
    rc = sk_cholesky(G.data(), n);
    if (rc) return rc;
    sk_chol_solve_inplace(G.data(), rhs.data(), n, t);
    std::copy(rhs.begin(), rhs.end(), x);
    return 0;
}



// ---------------------------------------------------------------------------
// Model IO + prediction (≙ capi/cml.cpp + ml/model.hpp:50-276 predict path
// and python-skylark ml/modeling.py LinearizedKernelModel).  Reads the
// FeatureMapModel JSON (+ .coef.npy), rebuilds the feature-map chain with
// the native sketch core, and predicts: out = [Z_1 .. Z_J] @ W.
// ---------------------------------------------------------------------------

static bool sk_read_file(const char* path, std::string& out) {
    FILE* f = fopen(path, "rb");
    if (!f) return false;
    long sz = -1;
    if (fseek(f, 0, SEEK_END) == 0) sz = ftell(f);
    if (sz < 0 || fseek(f, 0, SEEK_SET) != 0) {  // pipes/FIFOs/ftell failure
        fclose(f);
        return false;
    }
    out.resize(sz);
    bool ok = sz == 0 || fread(&out[0], 1, sz, f) == (size_t)sz;
    fclose(f);
    return ok;
}

static bool sk_npy_header(const std::string& buf, bool* f32,
                          size_t* data_off, long* rows, long* cols) {
    if (buf.size() < 10) return false;
    if (memcmp(buf.data(), "\x93NUMPY", 6) != 0) return false;
    int major = (unsigned char)buf[6];
    size_t hlen, hoff;
    if (major == 1) {
        hlen = (unsigned char)buf[8] | ((unsigned char)buf[9] << 8);
        hoff = 10;
    } else {
        if (buf.size() < 12) return false;
        hlen = (unsigned char)buf[8] | ((unsigned char)buf[9] << 8) |
               ((size_t)(unsigned char)buf[10] << 16) |
               ((size_t)(unsigned char)buf[11] << 24);
        hoff = 12;
    }
    if (buf.size() < hoff + hlen) return false;
    std::string hdr = buf.substr(hoff, hlen);
    *f32 = hdr.find("'<f4'") != std::string::npos;
    if (!*f32 && hdr.find("'<f8'") == std::string::npos) return false;
    if (hdr.find("'fortran_order': False") == std::string::npos) return false;
    const char* sh = strstr(hdr.c_str(), "'shape':");
    if (!sh) return false;
    long r = 0, c = 1;
    if (sscanf(sh, "'shape': (%ld, %ld)", &r, &c) < 1) return false;
    if (r <= 0 || c <= 0) return false;
    *data_off = hoff + hlen;
    *rows = r;
    *cols = c;
    return true;
}

static bool sk_npy_read_f64(const char* path, std::vector<double>& data,
                            long* rows, long* cols) {
    // Minimal NumPy v1/v2 .npy reader for C-order f64/f32 2-D arrays
    // (models trained without x64 save float32 coefficients).
    std::string buf;
    if (!sk_read_file(path, buf)) return false;
    bool f32; size_t off;
    if (!sk_npy_header(buf, &f32, &off, rows, cols)) return false;
    size_t cnt = (size_t)(*rows) * (*cols);
    size_t need = cnt * (f32 ? sizeof(float) : sizeof(double));
    if (buf.size() < off + need) return false;
    data.resize(cnt);
    if (f32) {
        const float* src = (const float*)(buf.data() + off);
        for (size_t i = 0; i < cnt; i++) data[i] = src[i];
    } else {
        memcpy(data.data(), buf.data() + off, need);
    }
    return true;
}

static bool sk_json_map_objects(const std::string& js,
                                std::vector<std::string>& out) {
    // Split the top-level {...} objects inside "maps": [ ... ] (bounds
    // from js_maps_span — the one bracket-matching implementation).
    size_t key, open, close;
    if (!js_maps_span(js, &key, &open, &close)) return false;
    int depth = 0;
    size_t start = 0;
    for (size_t i = open + 1; i < close; i++) {
        char ch = js[i];
        if (ch == '{') {
            if (depth == 0) start = i;
            depth++;
        } else if (ch == '}') {
            depth--;
            if (depth == 0) out.push_back(js.substr(start, i - start + 1));
        }
    }
    return true;
}

int sl_model_info(const char* path, long* input_dim, long* num_outputs) {
    if (!path || !input_dim || !num_outputs) return 102;
    std::string js;
    if (!sk_read_file(path, js)) return 105;
    double v = 0.0;
    // -1 = input_dim absent/null in the JSON (linear models constructed
    // without input_dim); callers must treat it as unknown, not a width.
    *input_dim = js_find_num(js.c_str(), "input_dim", &v) ? (long)v : -1;
    // Header-only peek at the coefficients: no full-file read here.
    FILE* f = fopen((std::string(path) + ".coef.npy").c_str(), "rb");
    if (!f) return 105;
    std::string head(4096, '\0');
    size_t got = fread(&head[0], 1, head.size(), f);
    fclose(f);
    head.resize(got);
    bool f32; size_t off; long r, c;
    if (!sk_npy_header(head, &f32, &off, &r, &c)) return 105;
    *num_outputs = c;
    return 0;
}

struct sl_model_t {
    std::vector<void*> maps;   // deserialized sketch handles (owned)
    std::vector<double> W;     // (D, k) row-major
    long D, k;
    bool scale_maps;
    int version;               // skylark_version the model was saved under
};

// Current RNG stream revision: revision 2 made the f32 uniform stream
// share the f64 value's leading bits.  Models saved under revision 1
// reproduce f32-uniform-derived map internals (UST/NURST selections,
// Fastfood permutations) differently; consumers should compare
// sl_model_stream_version() against sl_stream_revision() and warn, as
// the Python NativeModel wrapper does.
static const int SL_STREAM_REVISION = 2;

int sl_stream_revision(void) { return SL_STREAM_REVISION; }

int sl_model_stream_version(void* m_) {
    // Stream revision the loaded model was serialized under (1 when the
    // JSON predates version tagging); < 0 on a null handle.
    if (!m_) return -1;
    return ((sl_model_t*)m_)->version;
}

void sl_model_free(void* m_) {
    sl_model_t* m = (sl_model_t*)m_;
    if (!m) return;
    for (void* st : m->maps) sl_free_sketch_transform(st);
    delete m;
}

int sl_model_load(const char* path, void** out) {
    // Load-once handle: JSON + coefficients parsed a single time, feature
    // maps deserialized once; batch consumers predict repeatedly
    // (≙ the reference CLI loading the model once for streaming predict).
    if (!path || !out) return 102;
    std::string js;
    if (!sk_read_file(path, js)) return 105;
    sl_model_t* m = new sl_model_t{};
    if (!sk_npy_read_f64((std::string(path) + ".coef.npy").c_str(), m->W,
                         &m->D, &m->k)) {
        delete m;
        return 105;
    }
    double ver = 0.0;
    std::string toplevel = js_without_maps(js);
    m->version =
        js_find_num(toplevel.c_str(), "skylark_version", &ver) ? (int)ver : 1;
    std::vector<std::string> mapjs;
    if (!sk_json_map_objects(js, mapjs)) {
        delete m;
        return 105;
    }
    m->scale_maps =
        toplevel.find("\"scale_maps\": true") != std::string::npos ||
        toplevel.find("\"scale_maps\":true") != std::string::npos;
    long off = 0;
    for (const std::string& mjs : mapjs) {
        void* st = nullptr;
        int rc = sl_deserialize_sketch_transform(mjs.c_str(), &st);
        if (rc) {
            sl_model_free(m);
            return rc;
        }
        off += ((sl_sketch_t*)st)->s;
        m->maps.push_back(st);
    }
    if (!m->maps.empty() && off != m->D) {
        sl_model_free(m);
        return 102;
    }
    *out = m;
    return 0;
}

int sl_model_predict_handle(void* m_, const double* X, long n, long d,
                            double* out) {
    // out (n x k) = features(X) @ W, row-major.
    if (!m_ || !X || !out || n <= 0 || d <= 0) return 102;
    sl_model_t* m = (sl_model_t*)m_;
    long k = m->k;
    for (long i = 0; i < n * k; i++) out[i] = 0.0;
    if (m->maps.empty()) {
        if (m->D != d) return 102;  // linear model on raw features
        sk_matmul(X, m->W.data(), out, n, d, k, false, false);
        return 0;
    }
    long off = 0;
    for (void* st : m->maps) {
        sl_sketch_t* t = (sl_sketch_t*)st;
        long sj = t->s;
        if (t->n != d) return 102;
        std::vector<double> Z((size_t)n * sj);
        int rc = sl_apply_sketch_transform(st, X, n, d, 1, Z.data());
        if (rc) return rc;
        double blk = m->scale_maps ? std::sqrt((double)sj / (double)d) : 1.0;
        // out += blk * Z @ W[off:off+sj]
#pragma omp parallel for schedule(static)
        for (long i = 0; i < n; i++) {
            const double* zrow = Z.data() + (size_t)i * sj;
            double* orow = out + (size_t)i * k;
            for (long p = 0; p < sj; p++) {
                double zv = blk * zrow[p];
                const double* wrow = m->W.data() + (size_t)(off + p) * k;
                for (long j = 0; j < k; j++) orow[j] += zv * wrow[j];
            }
        }
        off += sj;
    }
    return 0;
}

int sl_model_predict(const char* path, const double* X, long n, long d,
                     double* out) {
    // One-shot convenience: load, predict, free.
    void* m = nullptr;
    int rc = sl_model_load(path, &m);
    if (rc) return rc;
    rc = sl_model_predict_handle(m, X, n, d, out);
    sl_model_free(m);
    return rc;
}

}  // extern "C"
