"""Sampling sketches: UST (uniform) and NURST (non-uniform).

Re-design of ``sketch/UST_data.hpp:18-113`` / ``sketch/UST_Elemental.hpp``
(pure coordinate selection, no rescaling: ``sa[i] = a[samples[i]]``) and the
python-only non-uniform variant ``NURST``
(``python-skylark/skylark/sketch.py`` URST/NURST classes).

Without-replacement sampling: the reference runs an incremental Fisher-Yates
shuffle over all N indices and keeps the first S
(``sketch/UST_data.hpp:95-104``).  Here we instead rank N counter-derived
uniform keys and keep the argmin-S — also an exchangeable uniform draw of S
distinct indices, but random-access/shard-computable and vectorized (a
sequential Fisher-Yates would defeat the counter design on TPU).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np  # noqa: F401  (host-side prob preprocessing)

from ..core.context import SketchContext
from ..core.random import sample
from .base import Dimension, SketchTransform, register_sketch

__all__ = ["UST", "NURST"]


@register_sketch
class UST(SketchTransform):
    """Uniform sampling transform, with or without replacement."""

    sketch_type = "UST"

    def __init__(
        self, n: int, s: int, context: SketchContext, replace: bool = True
    ):
        self.replace = bool(replace)
        super().__init__(n, s, context)
        self._seed = context.seed
        if self.replace:
            self._base = context.reserve(s)
        else:
            if s > n:
                raise ValueError(
                    f"cannot sample {s} of {n} without replacement"
                )
            self._base = context.reserve(n)

    @property
    def samples(self):
        """The S selected input coordinates (deterministic)."""
        if self.replace:
            return sample(
                "uniform_int",
                self._seed,
                self._base,
                self.s,
                dtype=jnp.int32,
                low=0,
                high=self.n - 1,
            )
        # S smallest of N uniform keys == uniform S-subset, in random order.
        keys = sample("uniform", self._seed, self._base, self.n)
        return jnp.argsort(keys)[: self.s].astype(jnp.int32)

    def apply(self, A, dim: Dimension | str = Dimension.COLUMNWISE):
        dim = Dimension.of(dim)
        A = jnp.asarray(A)
        idx = self.samples
        if dim is Dimension.COLUMNWISE:
            if A.shape[0] != self.n:
                raise ValueError(
                    f"columnwise apply needs A with {self.n} rows, got {A.shape}"
                )
            return A[idx, :] if A.ndim > 1 else A[idx]
        if A.shape[-1] != self.n:
            raise ValueError(
                f"rowwise apply needs A with {self.n} columns, got {A.shape}"
            )
        return A[..., idx]

    def _param_dict(self):
        return {"replace": self.replace}

    @classmethod
    def _from_param_dict(cls, d, context):
        return cls(d["N"], d["S"], context, replace=d.get("replace", True))


@register_sketch
class NURST(SketchTransform):
    """Non-uniform (weighted, with-replacement) row sampling transform.

    ≙ python-skylark's NURST (pure-python; not exposed in the C API).
    Selection uses inverse-CDF over the provided probability vector with S
    counter-derived uniforms; like UST, pure selection without rescaling.
    """

    sketch_type = "NURST"

    def __init__(self, n, s, context: SketchContext, probs):
        super().__init__(n, s, context)
        self.probs = np.asarray(probs, dtype=np.float64)
        if self.probs.shape != (n,):
            raise ValueError(f"probs must have shape ({n},), got {self.probs.shape}")
        if (self.probs < 0).any():
            raise ValueError("probs must be nonnegative")
        total = self.probs.sum()
        if total <= 0:
            raise ValueError("probs must sum to a positive value")
        self.probs = self.probs / total
        self._seed = context.seed
        self._base = context.reserve(s)

    @property
    def samples(self):
        u = sample("uniform", self._seed, self._base, self.s, dtype=jnp.float32)
        cdf = jnp.asarray(np.cumsum(self.probs))
        return jnp.clip(
            jnp.searchsorted(cdf, u.astype(cdf.dtype)), 0, self.n - 1
        ).astype(jnp.int32)

    def apply(self, A, dim: Dimension | str = Dimension.COLUMNWISE):
        dim = Dimension.of(dim)
        A = jnp.asarray(A)
        idx = self.samples
        if dim is Dimension.COLUMNWISE:
            return A[idx, :] if A.ndim > 1 else A[idx]
        return A[..., idx]

    def _param_dict(self):
        return {"probs": self.probs.tolist()}

    @classmethod
    def _from_param_dict(cls, d, context):
        return cls(d["N"], d["S"], context, probs=d["probs"])
