"""Random Laplace feature maps for semigroup kernels (Yang et al CVPR'14).

≙ ``sketch/RLT_data.hpp`` / ``sketch/RLT.hpp`` (apply:
``Z = outscale · exp(−(W·X))``, RLT_Elemental.hpp:77) and the QMC variant
``sketch/QRLT_data.hpp``.  ExpSemigroupRLT: W ~ standard Lévy scaled by
β²/2, outscale √(1/S) (``RLT_data.hpp:97-115``) — features for the
exponential semigroup kernel k(x, y) = exp(−β Σ_i √(x_i + y_i)) on
histograms (non-negative inputs).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.context import SketchContext
from ..core.quasirand import LeapedHaltonSequence
from .base import Dimension, SketchTransform, register_sketch
from .dense import DenseSketch

__all__ = ["ExpSemigroupRLT", "ExpSemigroupQRLT"]


class _UnderlyingLevy(DenseSketch):
    dist = "levy"

    def __init__(self, n, s, context, scale):
        super().__init__(n, s, context, scale=scale)


@register_sketch
class ExpSemigroupRLT(SketchTransform):
    """Z = √(1/S) · exp(−(β²/2)·(W·X)), W ~ standard Lévy."""

    sketch_type = "ExpSemigroupRLT"

    def __init__(self, n: int, s: int, context: SketchContext, beta: float = 1.0):
        super().__init__(n, s, context)
        self.beta = float(beta)
        self.outscale = np.sqrt(1.0 / s)
        self._underlying = _UnderlyingLevy(
            n, s, context, scale=self.beta * self.beta / 2.0
        )

    def apply(self, A, dim: Dimension | str = Dimension.COLUMNWISE):
        WX = self._underlying.apply(A, Dimension.of(dim))
        return jnp.asarray(self.outscale, WX.dtype) * jnp.exp(-WX)

    def _param_dict(self):
        return {"beta": self.beta}

    @classmethod
    def _from_param_dict(cls, d, context):
        return cls(d["N"], d["S"], context, beta=d["beta"])


def _levy_quantile(u):
    """Standard Lévy inverse CDF: F(x) = erfc(1/√(2x)) ⇒ x = 1/ndtri(u/2)²
    (consistent with the counter sampler's 1/Z² construction)."""
    z = jax.scipy.special.ndtri(u / 2.0)
    return 1.0 / (z * z)


@register_sketch
class ExpSemigroupQRLT(SketchTransform):
    """QMC variant: W rows from a leaped Halton sequence through the Lévy
    inverse CDF (≙ ``ExpSemigroupQRLT_data_t``, QRLT_data.hpp:35+)."""

    sketch_type = "ExpSemigroupQRLT"

    def __init__(
        self, n: int, s: int, context: SketchContext, beta: float = 1.0, skip: int = 0
    ):
        super().__init__(n, s, context)
        self.beta = float(beta)
        self.skip = int(skip)
        self.outscale = np.sqrt(1.0 / s)
        self._sequence = LeapedHaltonSequence(n)

    def realize(self, dtype=jnp.float32):
        U = self._sequence.window(self.skip, self.s, dtype=dtype)  # (S, N)
        return (self.beta * self.beta / 2.0) * _levy_quantile(U)

    def apply(self, A, dim: Dimension | str = Dimension.COLUMNWISE):
        dim = Dimension.of(dim)
        A = jnp.asarray(A)
        dtype = A.dtype if jnp.issubdtype(A.dtype, jnp.floating) else jnp.float32
        W = self.realize(dtype)
        if dim is Dimension.COLUMNWISE:
            WX = W @ A
        else:
            WX = A @ W.T
        return jnp.asarray(self.outscale, dtype) * jnp.exp(-WX)

    def _param_dict(self):
        return {"beta": self.beta, "skip": self.skip}

    @classmethod
    def _from_param_dict(cls, d, context):
        return cls(d["N"], d["S"], context, beta=d["beta"], skip=d.get("skip", 0))
