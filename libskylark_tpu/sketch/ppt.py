"""PPT — Pham-Pagh TensorSketch for the polynomial kernel.

≙ ``sketch/PPT_data.hpp:24-90`` + ``sketch/PPT_Elemental.hpp:131-188``:
features for k(x, y) = (γ·xᵀy + c)^q via q CountSketches composed in the
FFT domain —

    Z(x) = IFFT( Π_{l<q} FFT( √γ·CWT_l(x) + √c·s_l·e_{h_l} ) )

where the ``√c·s_l·e_{h_l}`` term (one extra hashed coordinate per level,
``PPT_Elemental.hpp:165-166``) carries the additive constant of the
kernel.  The FFTs ride XLA's native complex FFT (TPU-supported); the
reference's explicit 1/S scaling + unnormalized c2r inverse collapse to
the normalized ``jnp.fft.ifft``.

Counter budget ≙ ``PPT_data_t::build``: q CWTs (2N each), then q hash
indices and q hash values.

TPU cost (round 3, v5e, 131072×4096→1024 q=3): the f32 FFT path runs
149 ms — ~50 ms in the three split-CWT matmuls, ~50 ms in the four c64
FFTs (~12-14 ms each, axis layout immaterial; measured), the rest in
complex products.  For **bf16** inputs the S-point DFT is instead done
as explicit (S, S) cos/sin MXU matmuls in real arithmetic (complex64
never materializes; ~1.4 ms per half-transform vs 12.5 ms per FFT),
measured 101→~45 ms.  f32 keeps the exact-precision FFT: a split-matmul
DFT needs ≥8 bf16 passes (data split3 × matrix split2 per real part) and
measures no faster than XLA's FFT.  ``jnp.fft.irfft`` is UNIMPLEMENTED
on the TPU backend (probed) — only full complex ``fft``/``ifft`` and the
real matmul-DFT are used.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

from ..core.context import SketchContext
from ..core.random import sample
from .base import Dimension, SketchTransform, register_sketch
from .hash import CWT

__all__ = ["PPT"]

# bf16 matmul-DFT gate: the (S, S) cos+sin pair costs 2·S²·m MXU flops
# per level vs ~6 HBM passes of (S, m) complex for the FFT; the matmul
# wins for S up to several thousand and batches wide enough to amortize
# building the two (S, S) tables in-graph.
_DFT_MAX_S = 1 << 12
_DFT_MIN_BATCH = 4096
_DFT_MAX_Q = 8  # bf16 table rounding compounds ~linearly in q; see _dft_wins


@register_sketch
class PPT(SketchTransform):
    """TensorSketch feature map for the polynomial kernel (γ·xᵀy + c)^q."""

    sketch_type = "PPT"

    def __init__(
        self,
        n: int,
        s: int,
        context: SketchContext,
        q: int = 3,
        c: float = 1.0,
        gamma: float = 1.0,
    ):
        super().__init__(n, s, context)
        if q < 1:
            raise ValueError(f"PPT needs q >= 1, got {q}")
        self.q = int(q)
        self.c = float(c)
        self.gamma = float(gamma)
        self._seed = context.seed
        self._cwts = [CWT(n, s, context) for _ in range(self.q)]
        self._hidx_base = context.reserve(self.q)
        self._hval_base = context.reserve(self.q)

    def _hash_consts(self, dtype):
        idx = sample(
            "uniform_int", self._seed, self._hidx_base, self.q,
            dtype=jnp.int32, low=0, high=self.s - 1,
        )
        val = sample("rademacher", self._seed, self._hval_base, self.q, dtype=dtype)
        return idx, val

    def _dft_wins(self, dtype, batch: int) -> bool:
        """Gate for the bf16 matmul-DFT path (one predicate for both
        orientations — mirrors FastRFT._realize_wins).  TPU-only by
        default (v5e-measured crossover; CPU FFTs beat emulated bf16
        matmuls); ``SKYLARK_PPT_DFT=1`` forces it on for cross-backend
        tests, ``SKYLARK_NO_PPT_DFT=1`` forces it off."""
        if os.environ.get("SKYLARK_NO_PPT_DFT", "0") == "1":
            return False
        if (
            jax.default_backend() != "tpu"
            and os.environ.get("SKYLARK_PPT_DFT", "0") != "1"
        ):
            return False
        return (
            dtype == jnp.bfloat16
            and 2 <= self.s <= _DFT_MAX_S
            and batch >= _DFT_MIN_BATCH
            # Each of the q forward transforms + the inverse rounds its
            # (S, S) table to bf16 (~2^-8 relative per pass) and the
            # level products compound it, so worst-case feature error
            # grows ~linearly in q: measured ≤0.4% max-norm at q=3,
            # extrapolating past ~2% beyond q=8 — above the parity
            # tolerance.  High-degree kernels keep the exact FFT path.
            and self.q <= _DFT_MAX_Q
        )

    def _features(self, X):
        """Columnwise features for X (n, m) → (S, m) real."""
        dtype = X.dtype
        if self._dft_wins(dtype, X.shape[1]):
            return self._features_dft(X)
        sqrt_g = jnp.asarray(np.sqrt(self.gamma), dtype)
        sqrt_c = jnp.asarray(np.sqrt(self.c), dtype)
        idx, val = self._hash_consts(dtype)
        # Seed the frequency-domain product with level 0 (one multiply —
        # and one eager complex-ones allocation — fewer than starting
        # from ones; the axon TPU backend can't even create a complex
        # array outside jit).
        P = None
        for l, cwt in enumerate(self._cwts):
            W = sqrt_g * cwt.apply(X, Dimension.COLUMNWISE)
            W = W.at[idx[l], :].add(sqrt_c * val[l])
            F = jnp.fft.fft(W, axis=0)
            P = F if P is None else P * F
        return jnp.real(jnp.fft.ifft(P, axis=0)).astype(dtype)

    # -- bf16 matmul-DFT fast path (TPU) -----------------------------------

    def _dft_tables(self):
        """(cos, sin) (S, S) DFT tables in bf16, built in-graph.  The
        index product j·k stays below 2^24 for S ≤ 2^12 (int32-exact,
        reduced mod S before the float conversion)."""
        j = jnp.arange(self.s, dtype=jnp.int32)
        jk = (j[:, None] * j[None, :]) % jnp.int32(self.s)
        theta = jnp.float32(2.0 * np.pi / self.s) * jk.astype(jnp.float32)
        return (
            jnp.cos(theta).astype(jnp.bfloat16),
            jnp.sin(theta).astype(jnp.bfloat16),
        )

    def _features_dft(self, X, rowwise: bool = False):
        """bf16 features via explicit real-arithmetic DFT matmuls: each
        level's S-point transform is a (cos, sin) MXU matmul pair, the
        level products run as (Re, Im) f32 pairs, and the inverse
        transform is one more pair — complex64 never materializes.
        Values match the FFT path to bf16 feature accuracy (the DFT
        tables round to bf16; inputs are already bf16).  ``rowwise``
        keeps the batch on the major axis ((m, S) layout, transform on
        the minor axis) so rowwise applies skip two full-batch
        transposes — the DFT tables are symmetric, so the same (cos,
        sin) pair serves both orientations."""
        C, Sn = self._dft_tables()
        sqrt_g = jnp.asarray(np.sqrt(self.gamma), jnp.bfloat16)
        sqrt_c = jnp.asarray(np.sqrt(self.c), jnp.float32)
        idx, val = self._hash_consts(jnp.float32)
        dim = Dimension.ROWWISE if rowwise else Dimension.COLUMNWISE

        def mm(W, M):
            # Contracts the S axis of W (axis 1 rowwise / 0 columnwise)
            # with the symmetric (S, S) table, preserving W's layout.
            args = (W, M) if rowwise else (M, W)
            return jax.lax.dot_general(
                *args, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

        def add_const(W, l):
            loc = (slice(None), idx[l]) if rowwise else (idx[l], slice(None))
            return W.astype(jnp.float32).at[loc].add(sqrt_c * val[l])

        Pr = Pi = None
        for l, cwt in enumerate(self._cwts):
            W = sqrt_g * cwt.apply(X, dim)  # (m, S) rowwise / (S, m) col.
            Wb = add_const(W, l).astype(jnp.bfloat16)
            Re, Im = mm(Wb, C), -mm(Wb, Sn)
            if Pr is None:
                Pr, Pi = Re, Im
            else:
                Pr, Pi = Pr * Re - Pi * Im, Pr * Im + Pi * Re
        # ifft real part: (1/S)·(C@Pr − Sn@Pi)  (e^{+iθ} = C + i·Sn).
        Z = mm(Pr.astype(jnp.bfloat16), C) - mm(Pi.astype(jnp.bfloat16), Sn)
        return (Z * jnp.float32(1.0 / self.s)).astype(jnp.bfloat16)

    def apply(self, A, dim: Dimension | str = Dimension.COLUMNWISE):
        dim = Dimension.of(dim)
        A = jnp.asarray(A)
        dtype = A.dtype if jnp.issubdtype(A.dtype, jnp.floating) else jnp.float32
        A = A.astype(dtype)
        squeeze = A.ndim == 1
        if dim is Dimension.COLUMNWISE:
            X = A[:, None] if squeeze else A
            if X.shape[0] != self.n:
                raise ValueError(f"columnwise apply needs {self.n} rows, got {A.shape}")
            Z = self._features(X)
            return Z[:, 0] if squeeze else Z
        X = A[None, :] if squeeze else A
        if X.shape[-1] != self.n:
            raise ValueError(f"rowwise apply needs {self.n} cols, got {A.shape}")
        if not squeeze and self._dft_wins(dtype, X.shape[0]):
            return self._features_dft(X, rowwise=True)
        return self._features(X.T).T if not squeeze else self._features(X.T)[:, 0]

    def _param_dict(self):
        return {"q": self.q, "c": self.c, "gamma": self.gamma}

    @classmethod
    def _from_param_dict(cls, d, context):
        return cls(
            d["N"], d["S"], context, q=d["q"], c=d["c"], gamma=d["gamma"]
        )
