"""PPT — Pham-Pagh TensorSketch for the polynomial kernel.

≙ ``sketch/PPT_data.hpp:24-90`` + ``sketch/PPT_Elemental.hpp:131-188``:
features for k(x, y) = (γ·xᵀy + c)^q via q CountSketches composed in the
FFT domain —

    Z(x) = IFFT( Π_{l<q} FFT( √γ·CWT_l(x) + √c·s_l·e_{h_l} ) )

where the ``√c·s_l·e_{h_l}`` term (one extra hashed coordinate per level,
``PPT_Elemental.hpp:165-166``) carries the additive constant of the
kernel.  The FFTs ride XLA's native complex FFT (TPU-supported); the
reference's explicit 1/S scaling + unnormalized c2r inverse collapse to
the normalized ``jnp.fft.ifft``.

Counter budget ≙ ``PPT_data_t::build``: q CWTs (2N each), then q hash
indices and q hash values.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.context import SketchContext
from ..core.random import sample
from .base import Dimension, SketchTransform, register_sketch
from .hash import CWT

__all__ = ["PPT"]


@register_sketch
class PPT(SketchTransform):
    """TensorSketch feature map for the polynomial kernel (γ·xᵀy + c)^q."""

    sketch_type = "PPT"

    def __init__(
        self,
        n: int,
        s: int,
        context: SketchContext,
        q: int = 3,
        c: float = 1.0,
        gamma: float = 1.0,
    ):
        super().__init__(n, s, context)
        if q < 1:
            raise ValueError(f"PPT needs q >= 1, got {q}")
        self.q = int(q)
        self.c = float(c)
        self.gamma = float(gamma)
        self._seed = context.seed
        self._cwts = [CWT(n, s, context) for _ in range(self.q)]
        self._hidx_base = context.reserve(self.q)
        self._hval_base = context.reserve(self.q)

    def _hash_consts(self, dtype):
        idx = sample(
            "uniform_int", self._seed, self._hidx_base, self.q,
            dtype=jnp.int32, low=0, high=self.s - 1,
        )
        val = sample("rademacher", self._seed, self._hval_base, self.q, dtype=dtype)
        return idx, val

    def _features(self, X):
        """Columnwise features for X (n, m) → (S, m) real."""
        dtype = X.dtype
        cdtype = jnp.complex128 if dtype == jnp.float64 else jnp.complex64
        sqrt_g = jnp.asarray(np.sqrt(self.gamma), dtype)
        sqrt_c = jnp.asarray(np.sqrt(self.c), dtype)
        idx, val = self._hash_consts(dtype)
        P = jnp.ones((self.s, X.shape[1]), cdtype)
        for l, cwt in enumerate(self._cwts):
            W = sqrt_g * cwt.apply(X, Dimension.COLUMNWISE)
            W = W.at[idx[l], :].add(sqrt_c * val[l])
            P = P * jnp.fft.fft(W, axis=0)
        return jnp.real(jnp.fft.ifft(P, axis=0)).astype(dtype)

    def apply(self, A, dim: Dimension | str = Dimension.COLUMNWISE):
        dim = Dimension.of(dim)
        A = jnp.asarray(A)
        dtype = A.dtype if jnp.issubdtype(A.dtype, jnp.floating) else jnp.float32
        A = A.astype(dtype)
        squeeze = A.ndim == 1
        if dim is Dimension.COLUMNWISE:
            X = A[:, None] if squeeze else A
            if X.shape[0] != self.n:
                raise ValueError(f"columnwise apply needs {self.n} rows, got {A.shape}")
            Z = self._features(X)
            return Z[:, 0] if squeeze else Z
        X = A[None, :] if squeeze else A
        if X.shape[-1] != self.n:
            raise ValueError(f"rowwise apply needs {self.n} cols, got {A.shape}")
        return self._features(X.T).T if not squeeze else self._features(X.T)[:, 0]

    def _param_dict(self):
        return {"q": self.q, "c": self.c, "gamma": self.gamma}

    @classmethod
    def _from_param_dict(cls, d, context):
        return cls(
            d["N"], d["S"], context, q=d["q"], c=d["c"], gamma=d["gamma"]
        )
