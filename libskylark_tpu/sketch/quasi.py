"""Quasirandom dense sketch: Halton-driven JLT rows (QJLT).

A dense l2 subspace embedding whose rows come from a leaped Halton
sequence (``core.quasirand.LeapedHaltonSequence``) pushed through the
normal inverse CDF, instead of iid counter draws: row ``j`` of the
logical (S, N) sketch matrix is ``ndtri(seq(skip + j, ·)) / sqrt(S)``.

QMC rows cover the sphere more evenly than iid rows, so the same
embedding distortion is reached at a smaller sketch dimension S — which
is exactly the axis the policy layer's sketch-dim shrink loop probes.
Like the QRFT family the transform consumes NO counters: reproducibility
is carried by ``(d, leap, skip)``, all of which ride the standard sketch
JSON interchange (plan cache, serve registry, native parity surface).

Unlike the counter stream (integer threefry, bit-stable under jit), the
radical-inverse/ndtri float pipeline drifts ~1 ulp between jitted and
eager execution, so windows are realized under
``jax.ensure_compile_time_eval``: Omega is computed eagerly even while a
plan traces, and the planned apply stays BITWISE identical to the eager
apply (the plan embeds the concrete window as a constant).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.context import SketchContext
from ..core.quasirand import LeapedHaltonSequence, primes, radical_inverse
from .base import Dimension, SketchTransform, register_sketch
from .dense import _matmul

__all__ = ["QJLT"]


@register_sketch
class QJLT(SketchTransform):
    """Quasirandom Johnson-Lindenstrauss: Halton rows through ndtri,
    scale ``sqrt(1/S)`` — the QMC sibling of :class:`~.dense.JLT`.

    Any window of the logical (S, N) matrix is a pure function of
    ``(leap, skip, i, j)`` — the same shard-local realization invariant
    the counter-based dense engine guarantees (P5), with the Halton
    index replacing the counter.
    """

    sketch_type = "QJLT"

    def __init__(
        self,
        n: int,
        s: int,
        context: SketchContext,
        leap: int | None = None,
        skip: int | None = None,
    ):
        super().__init__(n, s, context)
        self._sequence = LeapedHaltonSequence(
            n, -1 if leap is None else int(leap)
        )
        self.leap = self._sequence.leap
        # The sequence itself is deterministic, so the SEED must move the
        # rows or the guard ladder's fresh-seed resketch would reproduce
        # the identical sketch.  The default skip is seed-derived (and
        # then serialized explicitly, so JSON round-trips are exact).
        self.skip = (
            int(context.seed) % (1 << 20) if skip is None else int(skip)
        )
        self.scale = (1.0 / s) ** 0.5

    # -- lazy realization ---------------------------------------------------

    def realize(
        self,
        dtype=jnp.float32,
        offset: tuple[int, int] = (0, 0),
        shape: tuple[int, int] | None = None,
    ):
        """Materialize a window of the logical (S, N) sketch matrix;
        bit-identical to the same slice of the full matrix (the radical
        inverse is evaluated per entry at the full 41-digit bound) AND
        bit-identical whether the caller is eager or mid-trace (the
        window is forced to compile-time evaluation)."""
        r0, c0 = offset
        h, w = shape if shape is not None else (self.s - r0, self.n - c0)
        if w <= 0 or h <= 0:
            return jnp.zeros((max(h, 0), max(w, 0)), dtype)
        itype = jnp.int64 if jax.config.jax_enable_x64 else jnp.int32
        with jax.ensure_compile_time_eval():
            p = jnp.asarray(primes(self.n)[c0 : c0 + w], itype)
            idx = (self.skip + r0 + jnp.arange(h, dtype=itype)) * self.leap
            u = radical_inverse(p[None, :], idx[:, None])
            omega = jax.scipy.special.ndtri(u) * jnp.asarray(
                self.scale, u.dtype
            )
            return omega.astype(dtype)

    # -- apply --------------------------------------------------------------

    def apply(self, A, dim: Dimension | str = Dimension.COLUMNWISE):
        dim = Dimension.of(dim)
        A = jnp.asarray(A) if not hasattr(A, "todense") else A
        dtype = A.dtype
        if not jnp.issubdtype(dtype, jnp.floating):
            dtype = jnp.float32
        if dim is Dimension.COLUMNWISE:
            if A.shape[0] != self.n:
                raise ValueError(
                    f"columnwise apply needs A with {self.n} rows, got {A.shape}"
                )
            return _matmul(self.realize(dtype), A)
        if A.shape[-1] != self.n:
            raise ValueError(
                f"rowwise apply needs A with {self.n} columns, got {A.shape}"
            )
        return _matmul(A, self.realize(dtype).T)

    def _apply_slice_columnwise(self, A_block, start: int):
        k = A_block.shape[0]
        dtype = A_block.dtype
        if not jnp.issubdtype(dtype, jnp.floating):
            dtype = jnp.float32
        w = self.realize(dtype, offset=(0, start), shape=(self.s, k))
        if hasattr(A_block, "todense"):
            return _matmul(w, A_block)
        return _matmul(w, A_block.astype(dtype))

    def hoistable_operands(self, dtype):
        """The realized (S, N) Omega, memoized per dtype (the transform
        is immutable; realization is compile-time anyway, so this just
        saves recomputing the radical inverses)."""
        dtype = jnp.dtype(dtype)
        if not jnp.issubdtype(dtype, jnp.floating):
            dtype = jnp.dtype(jnp.float32)
        cache = self.__dict__.setdefault("_hoist_cache", {})
        hit = cache.get(dtype.name)
        if hit is None:
            hit = cache[dtype.name] = self.realize(dtype)
        return hit

    def apply_with_operands(
        self, ops, A, dim: Dimension | str = Dimension.COLUMNWISE
    ):
        if ops is None:
            return self.apply(A, dim)
        dim = Dimension.of(dim)
        A = jnp.asarray(A) if not hasattr(A, "todense") else A
        dtype = A.dtype
        if not jnp.issubdtype(dtype, jnp.floating):
            dtype = jnp.float32
        if ops.dtype != dtype:
            ops = self.realize(dtype)
        if dim is Dimension.COLUMNWISE:
            return _matmul(ops, A)
        return _matmul(A, ops.T)

    # -- serialization ------------------------------------------------------

    def _param_dict(self):
        return {"leap": self.leap, "skip": self.skip}

    @classmethod
    def _from_param_dict(cls, d, context):
        return cls(
            d["N"], d["S"], context,
            leap=d.get("leap"), skip=d.get("skip", 0),
        )
