"""Fused Pallas RFUT kernel: one HBM pass for D-multiply + WHT.

The XLA lowering of the Kronecker WHT makes several full HBM round-trips
(mul, per-factor contraction, scale) — at FJLT's shapes the transform is
bandwidth-bound, so passes are everything.  This kernel performs

    out = H_NB · (D ⊙ pad(x))      (orthonormal, per row)

in a single read + single write per (TM, NB) VMEM tile, using the
mixed-product factorization ``H_NB = (H_f1 ⊗ I_128) · (I_f1 ⊗ H_128)``:

1. the ``I ⊗ H_128`` half is a contract-last ``dot_general`` against a
   dense ±1 H_128 on the MXU (128 = native lane width, the one reshape
   Mosaic supports);
2. the ``H_f1 ⊗ I`` half is a decimation butterfly on *contiguous* lane
   halves — ``H_{2k}⊗I x = [H_k⊗I (a+b); H_k⊗I (a−b)]`` — pure VPU
   add/sub on static slices, no transposes, and it leaves the output in
   natural Sylvester order (bit-compatible with :func:`fut.wht`).

Used automatically by RFUT/FJLT on TPU when shapes qualify (2-D input,
transform on the last axis, 256 ≤ NB ≤ 2^15, rows divisible by a tile
size); everything else falls back to the XLA path.  CPU tests run the
kernel in ``interpret=True`` mode.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .fut import _hadamard

__all__ = [
    "rfut_rowwise",
    "rfut_rowwise_sampled",
    "supported",
    "supported_sampled",
]

_F2 = 256  # minor factor (lane-multiple; 256² H keeps the MXU busy)
_TILE_CANDIDATES = (512, 256, 128, 64, 32, 16, 8)


def _tile_rows(m: int, nb: int) -> int | None:
    """Largest tile that divides m and keeps ~4 f32 working buffers of
    (tm, nb) within the 16 MB VMEM budget."""
    # The butterfly keeps ~log2(f1) live (tm, nb) f32 intermediates on the
    # Mosaic stack; ~2 MB per buffer fits the measured sweet spot
    # (tm=128 at nb=4096 with F2=256: 5.5 ms / 388 GB/s on v5e).
    budget = (2 << 20) // (nb * 4)
    for t in _TILE_CANDIDATES:
        if t <= max(budget, 8) and m % t == 0:
            return t
    return None


def supported(m: int, n: int, nb: int) -> bool:
    k = nb.bit_length() - 1
    if nb != (1 << k) or nb < 2 * _F2 or nb > (1 << 15):
        return False
    return _tile_rows(m, nb) is not None


def _butterfly_kron_eye(x, f1: int):
    """(H_f1 ⊗ I_w)·x over the lane axis of x (tm, f1·w), natural order."""
    parts = [x]
    level = f1
    while level > 1:
        nxt = []
        for blk in parts:
            half = blk.shape[1] // 2
            a = blk[:, :half]
            b = blk[:, half:]
            nxt.append(a + b)
            nxt.append(a - b)
        parts = nxt
        level //= 2
    return jnp.concatenate(parts, axis=1)


def supported_sampled(m: int, n: int, nb: int, s: int) -> bool:
    """Gate for the sampled-epilogue variant: the base kernel's gate
    plus a lane-aligned sample count (the (tm, S) output block) and a
    VMEM budget that carries the extra selected block."""
    if s < 128 or s % 128:
        return False
    if not supported(m, n, nb):
        return False
    tm = _tile_rows(m, nb)
    return tm is not None and tm * (nb + s) * 4 * 4 < (12 << 20)


def _sampled_epilogue(z, idx_row):
    """Select the S sample lanes of z (tm, nb) → (tm, S).

    ``idx_row`` is a (1, S) int32 VMEM block (pallas_call rejects
    captured constant arrays, so the host-static samples arrive as an
    input), making this a lane gather.  Whether Mosaic lowers it is
    TPU-generation-dependent — callers gate the kernel behind a
    compiled probe (``fjlt._sampled_kernel_compiles``) and fall back to
    the two-step WHT + XLA gather when it doesn't."""
    return jnp.take(z, idx_row[0], axis=1)


def _dwht_tile(nb, n, x_ref, d_ref, h2_ref):
    """Shared transform body of both kernels: D-multiply → zero-pad →
    (I⊗H_F2) MXU contraction → (H_f1⊗I) butterfly.  Returns the f32
    (tm, nb) un-normalized WHT tile."""
    tm = x_ref.shape[0]
    f1 = nb // _F2
    xdtype = x_ref.dtype
    x = x_ref[:] * d_ref[:]
    if n < nb:
        x = jnp.concatenate([x, jnp.zeros((tm, nb - n), xdtype)], axis=1)
    # (I_f1 ⊗ H_F2): contract the minor factor on the MXU.  bf16 operands
    # are exact here (H is ±1; products are just sign flips) and run the
    # MXU at full rate; accumulation is f32 via preferred_element_type.
    x3 = x.reshape(tm, f1, _F2)
    h = h2_ref[:].astype(xdtype) if xdtype == jnp.bfloat16 else h2_ref[:]
    # f32 inputs pin full precision: the MXU default truncates f32
    # operands to bf16 mantissas (silent ~1e-2 abs error on hardware —
    # caught by tests/test_pallas_hw.py; H is ±1 so only the input
    # mantissa matters).  bf16 inputs are exact already.
    y = jax.lax.dot_general(
        x3.astype(h.dtype), h,
        (((2,), (0,)), ((), ())),
        precision=None if xdtype == jnp.bfloat16 else jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    ).reshape(tm, nb)
    # (H_f1 ⊗ I_F2): contiguous-halves butterfly on the VPU, f32.
    return _butterfly_kron_eye(y, f1)


def _kernel_sampled(nb, n, s, x_ref, d_ref, h2_ref, i_ref, o_ref):
    """The fused FJLT kernel: D-multiply → WHT → STATIC sample selection
    → rescale, writing only (tm, S) to HBM.  Saves the full (m, NB)
    round-trip (write + re-read + gather) of the two-step path — the
    f32 large-S floor was bandwidth in exactly that round-trip
    (VERDICT r4 item 5; reference: ``sketch/FJLT_Elemental.hpp:144-186``
    applies the same sample-and-rescale after its local FUT)."""
    z = _dwht_tile(nb, n, x_ref, d_ref, h2_ref)
    sel = _sampled_epilogue(z, i_ref[:])
    # 1/√NB (orthonormal WHT) × √(NB/S) (sample rescale) = 1/√S.
    o_ref[:] = (sel * jnp.float32(1.0 / np.sqrt(s))).astype(o_ref.dtype)


def rfut_rowwise_sampled(x, diag, nb: int, idx, interpret: bool = False):
    """out (m, S) = FJLT(x) rowwise in ONE HBM pass: read x, write only
    the S sampled, rescaled WHT lanes.  ``idx`` must be a host/static
    integer array (the UST samples — counter-derived constants)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    idx = np.asarray(idx, np.int32)
    s = int(idx.shape[0])
    m, n = x.shape
    tm = _tile_rows(m, nb)
    if tm is None:
        raise ValueError(
            f"shape unsupported; check supported_sampled: no VMEM-fitting "
            f"row tile divides m={m} at nb={nb}"
        )
    dtype = x.dtype
    H2 = jnp.asarray(_hadamard(_F2.bit_length() - 1), jnp.float32)
    d2 = diag.astype(dtype).reshape(1, n)

    return pl.pallas_call(
        partial(_kernel_sampled, nb, n, s),
        grid=(m // tm,),
        in_specs=[
            pl.BlockSpec((tm, n), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((_F2, _F2), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, s), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (tm, s), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((m, s), dtype),
        interpret=interpret,
    )(x, d2, H2, jnp.asarray(idx).reshape(1, s))


def _kernel(nb, n, x_ref, d_ref, h2_ref, o_ref):
    z = _dwht_tile(nb, n, x_ref, d_ref, h2_ref)
    o_ref[:] = (z * jnp.float32(1.0 / np.sqrt(nb))).astype(o_ref.dtype)


def rfut_rowwise(x, diag, nb: int, interpret: bool = False):
    """out (m, NB) = orthonormal-WHT(pad(x ⊙ diag)) rowwise, natural
    Sylvester order (bit-compatible with the XLA ``wht``).

    ``x`` (m, n) float; ``diag`` (n,).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m, n = x.shape
    tm = _tile_rows(m, nb)
    if tm is None:
        raise ValueError(
            f"shape unsupported; check supported: no VMEM-fitting row "
            f"tile divides m={m} at nb={nb}"
        )
    dtype = x.dtype
    H2 = jnp.asarray(_hadamard(_F2.bit_length() - 1), jnp.float32)
    d2 = diag.astype(dtype).reshape(1, n)

    return pl.pallas_call(
        partial(_kernel, nb, n),
        grid=(m // tm,),
        in_specs=[
            pl.BlockSpec((tm, n), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((_F2, _F2), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (tm, nb), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((m, nb), dtype),
        interpret=interpret,
    )(x, d2, H2)
