"""Sketching layer: random dimensionality-reducing linear/feature maps.

TPU-native re-design of the reference's ``sketch/`` layer (~19.7 kLoC of
per-distribution template specializations collapse to one GSPMD-sharded
implementation per transform family).
"""

from .base import (
    COLUMNWISE,
    ROWWISE,
    Dimension,
    SketchTransform,
    create_sketch,
    from_dict,
    from_json,
    register_sketch,
    sketch_registry,
)
from .dense import CT, JLT, DenseSketch
from .hash import CWT, MMT, WZT, HashSketch
from .sampling import NURST, UST

__all__ = [
    "Dimension",
    "COLUMNWISE",
    "ROWWISE",
    "SketchTransform",
    "create_sketch",
    "from_dict",
    "from_json",
    "register_sketch",
    "sketch_registry",
    "DenseSketch",
    "JLT",
    "CT",
    "HashSketch",
    "CWT",
    "MMT",
    "WZT",
    "UST",
    "NURST",
]
