"""Sketching layer: random dimensionality-reducing linear/feature maps.

TPU-native re-design of the reference's ``sketch/`` layer (~19.7 kLoC of
per-distribution template specializations collapse to one GSPMD-sharded
implementation per transform family).
"""

from .base import (
    COLUMNWISE,
    ROWWISE,
    Dimension,
    SketchTransform,
    create_sketch,
    deserialize_sketch,
    from_dict,
    from_json,
    register_sketch,
    sketch_registry,
)
from .dense import CT, JLT, DenseSketch
from .fjlt import FJLT
from .frft import FastGaussianRFT, FastMaternRFT, FastRFT
from .fut import RFUT, dct, next_pow2, wht
from .hash import CWT, MMT, SJLT, WZT, HashSketch
from .ppt import PPT
from .quasi import QJLT
from .rft import (
    RFT,
    GaussianQRFT,
    GaussianRFT,
    LaplacianQRFT,
    LaplacianRFT,
    MaternRFT,
)
from .rlt import ExpSemigroupQRLT, ExpSemigroupRLT
from .sampling import NURST, UST

__all__ = [
    "Dimension",
    "COLUMNWISE",
    "ROWWISE",
    "SketchTransform",
    "create_sketch",
    "from_dict",
    "from_json",
    "deserialize_sketch",
    "SUPPORTED_SKETCH_TRANSFORMS",
    "register_sketch",
    "sketch_registry",
    "DenseSketch",
    "JLT",
    "QJLT",
    "CT",
    "HashSketch",
    "CWT",
    "MMT",
    "WZT",
    "SJLT",
    "UST",
    "NURST",
    "RFUT",
    "FJLT",
    "wht",
    "dct",
    "next_pow2",
    "RFT",
    "GaussianRFT",
    "LaplacianRFT",
    "MaternRFT",
    "GaussianQRFT",
    "LaplacianQRFT",
    "FastRFT",
    "FastGaussianRFT",
    "FastMaternRFT",
    "ExpSemigroupRLT",
    "ExpSemigroupQRLT",
    "PPT",
]

# ≙ python-skylark's SUPPORTED_SKETCH_TRANSFORMS (sketch.py:25-28): the
# per-distribution matrix-type axis collapses to one kind here.
SUPPORTED_SKETCH_TRANSFORMS = [
    (T, "Matrix", "Matrix") for T in sorted(sketch_registry())
]
