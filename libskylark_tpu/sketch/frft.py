"""Fastfood feature maps (Le-Sarlós-Smola ICML'13).

≙ ``sketch/FRFT_data.hpp`` / ``sketch/FRFT_Elemental.hpp``: the dense
Gaussian W of the RFT is replaced per block by ``Sm·H·G·Π·H·B`` — B a
Rademacher diagonal, Π a random permutation, G a Gaussian diagonal, H the
fast unitary transform, Sm a kernel-dependent scaling
(``FRFT_data.hpp:100-140``); features are then
``√(2/S)·cos(V·x + shift)``.

Counter budget mirrors ``FastRFT_data_t::build`` (shifts S; B, G, Π each
numblks·NB).  The reference's Fisher-Yates permutation
(``FRFT_data.hpp:115-125``) becomes an argsort of counter-derived uniform
keys — same distribution, shard-local computable, O(NB log NB) on device.

With the orthonormal WHT, Var((H·G·Π·H·B x)_i) = ‖x‖²/NB, so the Gaussian
scaling is ``Sm = √NB/σ`` (the reference's ``1/(σ√N)`` compensates its
*unnormalized* FUT); FastMatern multiplies per-row ``sqrt(2ν/χ²_{2ν})``
like MaternRFT (``FRFT_data.hpp:208+``).

TPU fast path (round 3): for batched bf16/f32 inputs the per-block chain
``Sm·H·G·Π·H·B`` is **realized as a dense (S, n) matrix in-graph** (two
nb×nb WHTs — cheap next to the batch) and applied as one MXU matmul.
The streaming form's permutation is a lane gather over the whole batch —
far below HBM streaming rate on TPU — while the realized form folds Π
into the matrix for free; measured 34.0→16.1 ms bf16 and 65.1→51.2 ms
f32 at 131072×4096→2048 on v5e (at S=4096 f32 the four split passes
lose to the S-independent streaming sweep — see ``_REALIZE_MAX_RATIO``).
f32 rides a 4-pass bf16 split (A's three split
parts against W_hi, plus A_hi against W_lo): unlike FJLT's ±1 operand,
W is Gaussian-valued, so bf16 needs the W_lo correction too; the dropped
``W_lo·(A_lo+A_lo2)`` terms leave ~2^-16-relative pre-cos error — below
the feature map's own O(1/√S) Monte-Carlo error by orders of magnitude
(guarded on hardware in tests/test_pallas_hw.py).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

from ..core.context import SketchContext
from ..core.precision import bf16_split3
from ..core.random import chi2_lanes, sample
from .base import Dimension, SketchTransform, register_sketch
from .fut import next_pow2, wht

__all__ = ["FastRFT", "FastGaussianRFT", "FastMaternRFT"]

_TWO_PI = 2.0 * np.pi

# Realized-W gate: the in-graph W build costs two nb×nb-column WHTs (the
# streaming form pays the same per nb batch columns), so the matmul form
# pays off once the batch is several nb wide; the cap bounds W's (padded
# S × nb) f32 footprint (64M entries = 256 MB) so huge s×nb combinations
# keep the O(nb·m)-resident streaming form.
_REALIZE_MIN_BATCH_BLOCKS = 4
_REALIZE_MAX_ELEMENTS = 64 << 20
# Measured v5e crossover (131072×4096, r3 probe): the realized matmul
# costs ~(passes)·2·n·S·m MXU flops while the streaming form costs two
# WHT HBM/compute sweeps + a permutation gather per nb-block, ∝
# numblks·nb·m.  Realized wins while S·n ≤ K·numblks·nb; fitting the
# measurements (bf16 16.1 ms at S=2048 vs 34.0 streaming, 30.3 vs 38.0
# at S=4096; f32 51.2 vs 65.1 at S=2048 but 102 vs 66.8 at S=4096 — the
# four split passes lose to the S-independent streaming sweep) gives
# K≈4340 bf16 / ≈2670 f32; rounded down conservatively.
_REALIZE_MAX_RATIO = {jnp.bfloat16: 4096.0, jnp.float32: 2560.0}


class FastRFT(SketchTransform):
    """Base Fastfood engine; subclasses set the Sm scaling."""

    def __init__(self, n: int, s: int, context: SketchContext):
        super().__init__(n, s, context)
        self._seed = context.seed
        self._nb = next_pow2(n)
        self.numblks = 1 + (s - 1) // self._nb
        self.outscale = np.sqrt(2.0 / s)
        # ≙ FastRFT_data_t::build reserve order: shifts, B, G, P.
        self._shift_base = context.reserve(s)
        self._b_base = context.reserve(self.numblks * self._nb)
        self._g_base = context.reserve(self.numblks * self._nb)
        self._p_base = context.reserve(self.numblks * self._nb)

    # -- counter-derived pieces --------------------------------------------

    def _shifts(self, dtype):
        return sample(
            "uniform", self._seed, self._shift_base, self.s,
            dtype=dtype, low=0.0, high=_TWO_PI,
        )

    def _B(self, dtype):
        return sample(
            "rademacher", self._seed, self._b_base, self.numblks * self._nb, dtype=dtype
        ).reshape(self.numblks, self._nb)

    def _G(self, dtype):
        return sample(
            "normal", self._seed, self._g_base, self.numblks * self._nb, dtype=dtype
        ).reshape(self.numblks, self._nb)

    def _perms(self):
        keys = sample(
            "uniform", self._seed, self._p_base, self.numblks * self._nb,
            dtype=jnp.float32,
        ).reshape(self.numblks, self._nb)
        return jnp.argsort(keys, axis=1)

    def _sm(self, dtype):
        """Kernel scaling, shape (numblks·NB,) (≙ Sm; 1.0 in the base)."""
        return jnp.ones((self.numblks * self._nb,), dtype)

    def _features(self, X):
        """V·X for columnwise X (n, m) → (S, m) pre-cos features."""
        nb = self._nb
        Xp = jnp.pad(X, ((0, nb - self.n), (0, 0))) if nb != self.n else X
        B = self._B(X.dtype)
        G = self._G(X.dtype)
        perms = self._perms()
        # All blocks at once: (blk, nb, m) — vmapped butterfly-free WHT.
        T = wht(B[:, :, None] * Xp[None, :, :], axis=1)
        T = jnp.take_along_axis(T, perms[:, :, None], axis=1)
        T = G[:, :, None] * T
        T = wht(T, axis=1)
        V = T.reshape(self.numblks * nb, -1) * self._sm(X.dtype)[:, None]
        return V[: self.s]

    # -- realized-W fast path ----------------------------------------------

    def _realize_wins(self, dtype, batch: int) -> bool:
        """Gate for realizing Sm·H·G·Π·H·B as a dense (S, n) matrix and
        applying it as one MXU matmul (see module docstring).  TPU-only
        by default (the crossover constants are v5e-measured, and on CPU
        the f32 4-pass split is both slower and less accurate than the
        exact streaming form); ``SKYLARK_FRFT_GEMM=1`` forces it on for
        cross-backend tests, ``SKYLARK_NO_FRFT_GEMM=1`` forces it off."""
        if os.environ.get("SKYLARK_NO_FRFT_GEMM", "0") == "1":
            return False
        if (
            jax.default_backend() != "tpu"
            and os.environ.get("SKYLARK_FRFT_GEMM", "0") != "1"
        ):
            return False
        key = jnp.dtype(dtype).type
        if key not in _REALIZE_MAX_RATIO:
            return False  # f64 (CPU parity) keeps the exact streaming form
        if self.numblks * self._nb * self._nb > _REALIZE_MAX_ELEMENTS:
            return False
        if self.s * self.n > _REALIZE_MAX_RATIO[key] * self.numblks * self._nb:
            return False
        return batch >= _REALIZE_MIN_BATCH_BLOCKS * self._nb

    def _realized_w(self):
        """(S, n) f32 matrix of the full per-block chain, built in-graph
        from the counter stream (same windows as the streaming form, so
        values match it exactly up to matmul rounding).  Columns beyond n
        would multiply padding zeros and are sliced away."""
        return self._features(jnp.eye(self.n, dtype=jnp.float32)).astype(
            jnp.float32  # belt-and-braces: subclass _sm dtype leaks
        )

    def hoistable_operands(self, dtype):
        """(realized W, shifts) for streaming consumers.  No backend or
        batch gate: a hoisting consumer amortizes the in-graph W build
        over its whole panel loop, which dominates both crossovers (the
        per-call ``_realize_wins`` gates exist because plain ``apply``
        rebuilds W every call)."""
        key = jnp.dtype(dtype).type
        if key not in (jnp.bfloat16, jnp.float32):
            return None  # f64 keeps the exact streaming form
        if self.numblks * self._nb * self._nb > _REALIZE_MAX_ELEMENTS:
            return None
        return (self._realized_w(), self._shifts(jnp.float32))

    def apply_with_operands(
        self, ops, A, dim: Dimension | str = Dimension.COLUMNWISE
    ):
        dim = Dimension.of(dim)
        A = jnp.asarray(A) if not hasattr(A, "todense") else A
        if (
            ops is None
            or hasattr(A, "todense")
            or A.ndim != 2
            or A.dtype not in (jnp.bfloat16, jnp.float32)
        ):
            return self.apply(A, dim)
        rowwise = dim is Dimension.ROWWISE
        if A.shape[1 if rowwise else 0] != self.n:
            raise ValueError(
                f"{dim.value} apply needs {self.n} on the sketched axis, "
                f"got {A.shape}"
            )
        return self._apply_realized(A, rowwise=rowwise, dtype=A.dtype, ops=ops)

    def _apply_realized(self, A, rowwise: bool, dtype, ops=None):
        """V = W·X (or X·Wᵀ rowwise) on the MXU; bf16 inputs take one
        bf16 matmul, f32 a 4-pass bf16 split (A_hi/lo/lo2 × W_hi plus
        A_hi × W_lo — the W_lo·A_lo tail is ~2^-16-relative, dropped)."""
        W, sh = ops if ops is not None else (self._realized_w(), None)
        # rowwise: X (m, n)·Wᵀ → contract X₁ with W₁; columnwise:
        # W (S, n)·X (n, m) → contract W₁ with X₀.
        contract = (((1,), (1,)), ((), ())) if rowwise else (((1,), (0,)), ((), ()))

        def mm(x, w):
            args = (x, w) if rowwise else (w, x)
            return jax.lax.dot_general(
                *args, contract, preferred_element_type=jnp.float32
            )

        if dtype == jnp.bfloat16:
            V = mm(A, W.astype(jnp.bfloat16))
        else:
            w_hi, w_lo, _ = bf16_split3(W)
            a_hi, a_lo, a_lo2 = bf16_split3(A)
            V = mm(a_hi, w_hi) + mm(a_lo, w_hi) + mm(a_lo2, w_hi) + mm(a_hi, w_lo)
        if sh is None:
            sh = self._shifts(jnp.float32)
        Z = self.outscale * jnp.cos(V + (sh[None, :] if rowwise else sh[:, None]))
        return Z.astype(dtype)

    def apply(self, A, dim: Dimension | str = Dimension.COLUMNWISE):
        dim = Dimension.of(dim)
        A = jnp.asarray(A)
        dtype = A.dtype if jnp.issubdtype(A.dtype, jnp.floating) else jnp.float32
        A = A.astype(dtype)
        squeeze = A.ndim == 1
        if dim is Dimension.COLUMNWISE:
            X = A[:, None] if squeeze else A
            if X.shape[0] != self.n:
                raise ValueError(f"columnwise apply needs {self.n} rows, got {A.shape}")
            if X.ndim == 2 and self._realize_wins(dtype, X.shape[1]):
                Z = self._apply_realized(X, rowwise=False, dtype=dtype)
                return Z[:, 0] if squeeze else Z
            V = self._features(X)
            Z = self.outscale * jnp.cos(V + self._shifts(dtype)[:, None])
            return Z[:, 0] if squeeze else Z
        X = A[None, :] if squeeze else A
        if X.shape[-1] != self.n:
            raise ValueError(f"rowwise apply needs {self.n} cols, got {A.shape}")
        if X.ndim == 2 and self._realize_wins(dtype, X.shape[0]):
            Z = self._apply_realized(X, rowwise=True, dtype=dtype)
            return Z[0] if squeeze else Z
        V = self._features(X.T).T
        Z = self.outscale * jnp.cos(V + self._shifts(dtype)[None, :])
        return Z[0] if squeeze else Z


@register_sketch
class FastGaussianRFT(FastRFT):
    """≙ ``FastGaussianRFT_data_t`` (FRFT_data.hpp:147-205)."""

    sketch_type = "FastGaussianRFT"

    def __init__(self, n, s, context, sigma: float = 1.0):
        self.sigma = float(sigma)
        super().__init__(n, s, context)

    def _sm(self, dtype):
        return jnp.full(
            (self.numblks * self._nb,), np.sqrt(self._nb) / self.sigma, dtype
        )

    def _param_dict(self):
        return {"sigma": self.sigma}

    @classmethod
    def _from_param_dict(cls, d, context):
        return cls(d["N"], d["S"], context, sigma=d["sigma"])


@register_sketch
class FastMaternRFT(FastRFT):
    """≙ ``FastMaternRFT_data_t``: per-row multivariate-t correction."""

    sketch_type = "FastMaternRFT"

    def __init__(self, n, s, context, nu: float = 1.0, l: float = 1.0):
        two_nu = 2.0 * nu
        if abs(two_nu - round(two_nu)) > 1e-9 or round(two_nu) < 1:
            raise ValueError(f"FastMaternRFT needs 2*nu a positive integer, got nu={nu}")
        self.nu = float(nu)
        self.l = float(l)
        super().__init__(n, s, context)
        self._chi_base = context.reserve(self.numblks * self._nb)

    def _sm(self, dtype):
        two_nu = int(round(2 * self.nu))
        size = self.numblks * self._nb
        chi2 = chi2_lanes(self._seed, self._chi_base, size, two_nu, dtype)
        # Scalar as a typed jnp value: a bare np.float64 would promote the
        # whole Sm (and then W / the streaming features) to f64 under x64.
        scale = jnp.asarray(np.sqrt(self._nb) / self.l, dtype)
        return jnp.sqrt(2.0 * self.nu / chi2) * scale

    def _param_dict(self):
        return {"nu": self.nu, "l": self.l}

    @classmethod
    def _from_param_dict(cls, d, context):
        return cls(d["N"], d["S"], context, nu=d["nu"], l=d["l"])
