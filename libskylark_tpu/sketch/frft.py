"""Fastfood feature maps (Le-Sarlós-Smola ICML'13).

≙ ``sketch/FRFT_data.hpp`` / ``sketch/FRFT_Elemental.hpp``: the dense
Gaussian W of the RFT is replaced per block by ``Sm·H·G·Π·H·B`` — B a
Rademacher diagonal, Π a random permutation, G a Gaussian diagonal, H the
fast unitary transform, Sm a kernel-dependent scaling
(``FRFT_data.hpp:100-140``); features are then
``√(2/S)·cos(V·x + shift)``.

Counter budget mirrors ``FastRFT_data_t::build`` (shifts S; B, G, Π each
numblks·NB).  The reference's Fisher-Yates permutation
(``FRFT_data.hpp:115-125``) becomes an argsort of counter-derived uniform
keys — same distribution, shard-local computable, O(NB log NB) on device.

With the orthonormal WHT, Var((H·G·Π·H·B x)_i) = ‖x‖²/NB, so the Gaussian
scaling is ``Sm = √NB/σ`` (the reference's ``1/(σ√N)`` compensates its
*unnormalized* FUT); FastMatern multiplies per-row ``sqrt(2ν/χ²_{2ν})``
like MaternRFT (``FRFT_data.hpp:208+``).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.context import SketchContext
from ..core.random import chi2_lanes, sample
from .base import Dimension, SketchTransform, register_sketch
from .fut import next_pow2, wht

__all__ = ["FastRFT", "FastGaussianRFT", "FastMaternRFT"]

_TWO_PI = 2.0 * np.pi


class FastRFT(SketchTransform):
    """Base Fastfood engine; subclasses set the Sm scaling."""

    def __init__(self, n: int, s: int, context: SketchContext):
        super().__init__(n, s, context)
        self._seed = context.seed
        self._nb = next_pow2(n)
        self.numblks = 1 + (s - 1) // self._nb
        self.outscale = np.sqrt(2.0 / s)
        # ≙ FastRFT_data_t::build reserve order: shifts, B, G, P.
        self._shift_base = context.reserve(s)
        self._b_base = context.reserve(self.numblks * self._nb)
        self._g_base = context.reserve(self.numblks * self._nb)
        self._p_base = context.reserve(self.numblks * self._nb)

    # -- counter-derived pieces --------------------------------------------

    def _shifts(self, dtype):
        return sample(
            "uniform", self._seed, self._shift_base, self.s,
            dtype=dtype, low=0.0, high=_TWO_PI,
        )

    def _B(self, dtype):
        return sample(
            "rademacher", self._seed, self._b_base, self.numblks * self._nb, dtype=dtype
        ).reshape(self.numblks, self._nb)

    def _G(self, dtype):
        return sample(
            "normal", self._seed, self._g_base, self.numblks * self._nb, dtype=dtype
        ).reshape(self.numblks, self._nb)

    def _perms(self):
        keys = sample(
            "uniform", self._seed, self._p_base, self.numblks * self._nb,
            dtype=jnp.float32,
        ).reshape(self.numblks, self._nb)
        return jnp.argsort(keys, axis=1)

    def _sm(self, dtype):
        """Kernel scaling, shape (numblks·NB,) (≙ Sm; 1.0 in the base)."""
        return jnp.ones((self.numblks * self._nb,), dtype)

    def _features(self, X):
        """V·X for columnwise X (n, m) → (S, m) pre-cos features."""
        nb = self._nb
        Xp = jnp.pad(X, ((0, nb - self.n), (0, 0))) if nb != self.n else X
        B = self._B(X.dtype)
        G = self._G(X.dtype)
        perms = self._perms()
        # All blocks at once: (blk, nb, m) — vmapped butterfly-free WHT.
        T = wht(B[:, :, None] * Xp[None, :, :], axis=1)
        T = jnp.take_along_axis(T, perms[:, :, None], axis=1)
        T = G[:, :, None] * T
        T = wht(T, axis=1)
        V = T.reshape(self.numblks * nb, -1) * self._sm(X.dtype)[:, None]
        return V[: self.s]

    def apply(self, A, dim: Dimension | str = Dimension.COLUMNWISE):
        dim = Dimension.of(dim)
        A = jnp.asarray(A)
        dtype = A.dtype if jnp.issubdtype(A.dtype, jnp.floating) else jnp.float32
        A = A.astype(dtype)
        squeeze = A.ndim == 1
        if dim is Dimension.COLUMNWISE:
            X = A[:, None] if squeeze else A
            if X.shape[0] != self.n:
                raise ValueError(f"columnwise apply needs {self.n} rows, got {A.shape}")
            V = self._features(X)
            Z = self.outscale * jnp.cos(V + self._shifts(dtype)[:, None])
            return Z[:, 0] if squeeze else Z
        X = A[None, :] if squeeze else A
        if X.shape[-1] != self.n:
            raise ValueError(f"rowwise apply needs {self.n} cols, got {A.shape}")
        V = self._features(X.T).T
        Z = self.outscale * jnp.cos(V + self._shifts(dtype)[None, :])
        return Z[0] if squeeze else Z


@register_sketch
class FastGaussianRFT(FastRFT):
    """≙ ``FastGaussianRFT_data_t`` (FRFT_data.hpp:147-205)."""

    sketch_type = "FastGaussianRFT"

    def __init__(self, n, s, context, sigma: float = 1.0):
        self.sigma = float(sigma)
        super().__init__(n, s, context)

    def _sm(self, dtype):
        return jnp.full(
            (self.numblks * self._nb,), np.sqrt(self._nb) / self.sigma, dtype
        )

    def _param_dict(self):
        return {"sigma": self.sigma}

    @classmethod
    def _from_param_dict(cls, d, context):
        return cls(d["N"], d["S"], context, sigma=d["sigma"])


@register_sketch
class FastMaternRFT(FastRFT):
    """≙ ``FastMaternRFT_data_t``: per-row multivariate-t correction."""

    sketch_type = "FastMaternRFT"

    def __init__(self, n, s, context, nu: float = 1.0, l: float = 1.0):
        two_nu = 2.0 * nu
        if abs(two_nu - round(two_nu)) > 1e-9 or round(two_nu) < 1:
            raise ValueError(f"FastMaternRFT needs 2*nu a positive integer, got nu={nu}")
        self.nu = float(nu)
        self.l = float(l)
        super().__init__(n, s, context)
        self._chi_base = context.reserve(self.numblks * self._nb)

    def _sm(self, dtype):
        two_nu = int(round(2 * self.nu))
        size = self.numblks * self._nb
        chi2 = chi2_lanes(self._seed, self._chi_base, size, two_nu, dtype)
        return jnp.sqrt(2.0 * self.nu / chi2) * (np.sqrt(self._nb) / self.l)

    def _param_dict(self):
        return {"nu": self.nu, "l": self.l}

    @classmethod
    def _from_param_dict(cls, d, context):
        return cls(d["N"], d["S"], context, nu=d["nu"], l=d["l"])
