"""Random Fourier feature maps (Rahimi-Recht) and QMC variants.

≙ ``sketch/RFT_data.hpp`` / ``sketch/RFT_Elemental.hpp`` (the apply is
``Z = outscale · cos(scale_i · (W·X)_i + shift_i)`` with W the underlying
counter-based dense transform pre-scaled by ``inscale``,
``RFT_Elemental.hpp:85-120``) and ``sketch/QRFT_data.hpp`` (W from a
leaped Halton sequence through the inverse CDF; shifts from the sequence's
extra dimension N, ``QRFT_data.hpp:29-107``).

Concrete kernels (constructor params ≙ the reference's data classes):

- GaussianRFT(sigma):   W ~ N, inscale 1/σ, outscale √(2/S)
- LaplacianRFT(sigma):  W ~ Cauchy, inscale 1/σ, outscale √(2/S)
- MaternRFT(nu, l):     W ~ N with per-row multivariate-t correction
  ``sqrt(2ν/χ²_{2ν})`` (``RFT_data.hpp:336-345``), inscale 1/l
- GaussianQRFT / LaplacianQRFT(sigma, skip): QMC rows

The W·X product is the MXU-heavy op; shifts/cos fuse into its epilogue
under XLA (the reference hand-loops this with OpenMP + an inexact-cosine
fallback — unnecessary on TPU, the VPU does cos at full throughput).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..core.context import SketchContext
from ..core.quasirand import LeapedHaltonSequence
from ..core.random import chi2_lanes, sample
from .base import Dimension, SketchTransform, register_sketch
from .dense import DenseSketch

__all__ = [
    "RFT",
    "GaussianRFT",
    "LaplacianRFT",
    "MaternRFT",
    "GaussianQRFT",
    "LaplacianQRFT",
]

_TWO_PI = 2.0 * np.pi


@partial(jax.jit, static_argnames=("outscale", "columnwise"))
def _epilogue_kernel(WX, shifts, scales, *, outscale, columnwise):
    """The feature-map epilogue as one compiled kernel (``scales`` may be
    None — it drops out of the pytree).  Both the eager apply and the
    plan layer's fused executables inline this same chain, keeping them
    bit-identical."""
    if columnwise:
        if scales is not None:
            WX = WX * (scales[:, None] if WX.ndim > 1 else scales)
        WX = WX + (shifts[:, None] if WX.ndim > 1 else shifts)
    else:
        if scales is not None:
            WX = WX * scales
        WX = WX + shifts
    return jnp.asarray(outscale, WX.dtype) * jnp.cos(WX)


class RFT(SketchTransform):
    """Base engine: Z = outscale · cos(scales ⊙ (W·X) + shifts)."""

    w_dist = "normal"

    def __init__(
        self,
        n: int,
        s: int,
        context: SketchContext,
        inscale: float,
        outscale: float,
    ):
        super().__init__(n, s, context)
        self._seed = context.seed
        self.inscale = float(inscale)
        self.outscale = float(outscale)
        # Counter budget ≙ RFT_data_t::build: N*S for W, then S shifts.
        self._underlying = _Underlying(n, s, context, inscale, self.w_dist)
        self._shift_base = context.reserve(s)

    def shifts(self, dtype=jnp.float32):
        """The S phase shifts, memoized per dtype as a CONCRETE array
        (computed eagerly even when called mid-trace, where it enters
        the trace as a tiny (S,) constant).  Concreteness matters beyond
        speed: regenerated inside a jit fusion, the uniform conversion's
        ``bits·scale + low`` contracts with the epilogue's add into an
        FMA, and the planned apply would drift a ulp from eager."""
        dtype = jnp.dtype(dtype)
        cache = self.__dict__.setdefault("_shift_cache", {})
        hit = cache.get(dtype.name)
        if hit is None:
            with jax.ensure_compile_time_eval():
                hit = cache[dtype.name] = sample(
                    "uniform",
                    self._seed,
                    self._shift_base,
                    self.s,
                    dtype=dtype,
                    low=0.0,
                    high=_TWO_PI,
                )
        return hit

    def scales(self, dtype=jnp.float32):
        """Per-feature scaling; identity unless a subclass overrides
        (≙ ``_scales`` filled with 1, ``RFT_data.hpp:88-90``)."""
        return None

    def apply(self, A, dim: Dimension | str = Dimension.COLUMNWISE):
        dim = Dimension.of(dim)
        WX = self._underlying.apply(A, dim)
        return self._epilogue(WX, dim)

    def _epilogue(self, WX, dim: Dimension):
        """outscale · cos(scales ⊙ WX + shifts) — via the shared jitted
        kernel so the eager and planned paths run the SAME fused
        elementwise chain (op-by-op eager dispatch skips the FMA
        contraction a jit fusion applies to ``WX·scales + shifts``, and
        the two would differ by a ulp)."""
        dtype = WX.dtype
        return _epilogue_kernel(
            WX,
            self.shifts(dtype),
            self.scales(dtype),
            outscale=self.outscale,
            columnwise=dim is Dimension.COLUMNWISE,
        )

    def _apply_slice_columnwise(self, A_block, start: int):
        """Partial W·A over the coordinate block: the LINEAR half of the
        feature map decomposes over row blocks exactly like the dense
        engine; the nonlinear cos epilogue must wait for the full sum and
        runs in :meth:`finalize_slices`."""
        return self._underlying._apply_slice_columnwise(A_block, start)

    supports_slice_kernel = True

    def apply_slice_kernel(self, A_block, start):
        """jit-safe linear half with traced ``start`` — same delegation
        as :meth:`_apply_slice_columnwise` (the cos epilogue still runs
        in :meth:`finalize_slices` once the slice-sums are merged)."""
        return self._underlying.apply_slice_kernel(A_block, start)

    def finalize_slices(self, acc, dim: Dimension | str = Dimension.COLUMNWISE):
        """COLUMNWISE slice-sums hold the merged W·A — apply the
        ``outscale·cos(scales ⊙ · + shifts)`` epilogue once here.
        ROWWISE blocks were finished by :meth:`apply` already."""
        dim = Dimension.of(dim)
        if dim is Dimension.ROWWISE:
            return acc
        return self._epilogue(acc, dim)

    def hoistable_operands(self, dtype):
        """The realized (S, N) W — loop-invariant, and the expensive
        part of the apply to re-derive (Box-Muller per visit).
        Delegates to the underlying dense engine (one gate, one realize
        — and JLT/CT streaming consumers get the same seam)."""
        return self._underlying.hoistable_operands(dtype)

    def apply_with_operands(
        self, ops, A, dim: Dimension | str = Dimension.COLUMNWISE
    ):
        dim = Dimension.of(dim)
        WX = self._underlying.apply_with_operands(ops, A, dim)
        return self._epilogue(WX, dim)


class _Underlying(DenseSketch):
    """The dense W (pre-scaled by inscale); not registered — internal."""

    def __init__(self, n, s, context, scale, dist):
        self.dist = dist
        super().__init__(n, s, context, scale=scale)


@register_sketch
class GaussianRFT(RFT):
    """Feature map for the Gaussian kernel exp(−‖x−y‖²/(2σ²))
    (≙ ``GaussianRFT_data_t``, RFT_data.hpp:103-172)."""

    sketch_type = "GaussianRFT"
    w_dist = "normal"

    def __init__(self, n: int, s: int, context: SketchContext, sigma: float = 1.0):
        self.sigma = float(sigma)
        super().__init__(n, s, context, 1.0 / sigma, np.sqrt(2.0 / s))

    def _param_dict(self):
        return {"sigma": self.sigma}

    @classmethod
    def _from_param_dict(cls, d, context):
        return cls(d["N"], d["S"], context, sigma=d["sigma"])


@register_sketch
class LaplacianRFT(RFT):
    """Feature map for the Laplacian kernel exp(−‖x−y‖₁/σ)
    (≙ ``LaplacianRFT_data_t``, RFT_data.hpp:175-255: Cauchy W)."""

    sketch_type = "LaplacianRFT"
    w_dist = "cauchy"

    def __init__(self, n: int, s: int, context: SketchContext, sigma: float = 1.0):
        self.sigma = float(sigma)
        super().__init__(n, s, context, 1.0 / sigma, np.sqrt(2.0 / s))

    def _param_dict(self):
        return {"sigma": self.sigma}

    @classmethod
    def _from_param_dict(cls, d, context):
        return cls(d["N"], d["S"], context, sigma=d["sigma"])


@register_sketch
class MaternRFT(RFT):
    """Feature map for the Matérn(ν, ℓ) kernel: rows are multivariate-t —
    Gaussian row × ``sqrt(2ν/χ²_{2ν})`` (≙ ``MaternRFT_data_t::build``,
    RFT_data.hpp:336-345).

    The χ²_{2ν} draw needs integer 2ν (sum of squares of 2ν normals from
    independent counter lanes); all common Matérn orders (ν = ½, 1, 3/2,
    5/2, ...) qualify.
    """

    sketch_type = "MaternRFT"
    w_dist = "normal"

    def __init__(
        self, n: int, s: int, context: SketchContext, nu: float = 1.0, l: float = 1.0
    ):
        two_nu = 2.0 * nu
        if abs(two_nu - round(two_nu)) > 1e-9 or round(two_nu) < 1:
            raise ValueError(f"MaternRFT needs 2*nu a positive integer, got nu={nu}")
        self.nu = float(nu)
        self.l = float(l)
        super().__init__(n, s, context, 1.0 / l, np.sqrt(2.0 / s))
        self._scales_base = context.reserve(s)

    def scales(self, dtype=jnp.float32):
        dtype = jnp.dtype(dtype)
        cache = self.__dict__.setdefault("_scale_cache", {})
        hit = cache.get(dtype.name)
        if hit is None:
            with jax.ensure_compile_time_eval():
                two_nu = int(round(2 * self.nu))
                # χ²_{2ν} per feature row: sum over 2ν independent lanes.
                chi2 = chi2_lanes(
                    self._seed, self._scales_base, self.s, two_nu, dtype
                )
                hit = cache[dtype.name] = jnp.sqrt(2.0 * self.nu / chi2)
        return hit

    def _param_dict(self):
        return {"nu": self.nu, "l": self.l}

    @classmethod
    def _from_param_dict(cls, d, context):
        return cls(d["N"], d["S"], context, nu=d["nu"], l=d["l"])


class QRFT(SketchTransform):
    """Quasi-Monte-Carlo random features (Yang et al, ICML'14).

    W[j, d] = invCDF(seq(skip+j, d)) · inscale; shift_j = 2π·seq(skip+j, N)
    (≙ ``QRFT_data_t::build``, QRFT_data.hpp:84-95; sequence dim = N+1).
    Consumes no counters — reproducibility is carried by (sequence, skip).
    """

    w_dist = "normal"  # inverse-CDF target

    def __init__(
        self,
        n: int,
        s: int,
        context: SketchContext,
        inscale: float,
        outscale: float,
        skip: int = 0,
    ):
        super().__init__(n, s, context)
        self.inscale = float(inscale)
        self.outscale = float(outscale)
        self.skip = int(skip)
        self._sequence = LeapedHaltonSequence(n + 1)

    def _inv_cdf(self, u):
        if self.w_dist == "normal":
            return jax.scipy.special.ndtri(u)
        if self.w_dist == "cauchy":
            return jnp.tan(jnp.pi * (u - 0.5))
        raise ValueError(f"no inverse CDF for {self.w_dist}")

    def realize(self, dtype=jnp.float32):
        """(W, shifts): W is (S, N)."""
        U = self._sequence.window(self.skip, self.s, dtype=dtype)  # (S, N+1)
        W = self._inv_cdf(U[:, : self.n]) * jnp.asarray(self.inscale, dtype)
        shifts = _TWO_PI * U[:, self.n]
        return W.astype(dtype), shifts.astype(dtype)

    def apply(self, A, dim: Dimension | str = Dimension.COLUMNWISE):
        dim = Dimension.of(dim)
        A = jnp.asarray(A)
        dtype = A.dtype if jnp.issubdtype(A.dtype, jnp.floating) else jnp.float32
        W, shifts = self.realize(dtype)
        if dim is Dimension.COLUMNWISE:
            if A.shape[0] != self.n:
                raise ValueError(f"columnwise apply needs {self.n} rows, got {A.shape}")
            WX = W @ A
            WX = WX + (shifts[:, None] if WX.ndim > 1 else shifts)
        else:
            if A.shape[-1] != self.n:
                raise ValueError(f"rowwise apply needs {self.n} cols, got {A.shape}")
            WX = A @ W.T + shifts
        return jnp.asarray(self.outscale, dtype) * jnp.cos(WX)

    def _param_dict(self):
        return {"skip": self.skip}


@register_sketch
class GaussianQRFT(QRFT):
    """≙ ``GaussianQRFT_data_t`` (QRFT_data.hpp:118-140)."""

    sketch_type = "GaussianQRFT"
    w_dist = "normal"

    def __init__(self, n, s, context, sigma: float = 1.0, skip: int = 0):
        self.sigma = float(sigma)
        super().__init__(n, s, context, 1.0 / sigma, np.sqrt(2.0 / s), skip)

    def _param_dict(self):
        return {"sigma": self.sigma, "skip": self.skip}

    @classmethod
    def _from_param_dict(cls, d, context):
        return cls(d["N"], d["S"], context, sigma=d["sigma"], skip=d.get("skip", 0))


@register_sketch
class LaplacianQRFT(QRFT):
    """≙ ``LaplacianQRFT_data_t``: Cauchy inverse CDF."""

    sketch_type = "LaplacianQRFT"
    w_dist = "cauchy"

    def __init__(self, n, s, context, sigma: float = 1.0, skip: int = 0):
        self.sigma = float(sigma)
        super().__init__(n, s, context, 1.0 / sigma, np.sqrt(2.0 / s), skip)

    def _param_dict(self):
        return {"sigma": self.sigma, "skip": self.skip}

    @classmethod
    def _from_param_dict(cls, d, context):
        return cls(d["N"], d["S"], context, sigma=d["sigma"], skip=d.get("skip", 0))
