"""Fast unitary transforms: Walsh-Hadamard + DCT, and the RFUT sketch.

≙ the reference's FUT layer (``sketch/FUT.hpp:26-110``, FFTW DCT wrappers
``utility/fft/fftw_futs.h:10-140``, SpiralWHT) and ``RFUT_t``
(``sketch/RFUT.hpp:17``, ``sketch/RFUT_Elemental.hpp``).

TPU design: the Hadamard transform is computed by **Kronecker
factorization** — ``H_{2^k} = H_a ⊗ H_b ⊗ ...`` with each factor a dense
±1 matrix of size ≤ 256 — so the whole transform is a few MXU matmuls
(tensordots) instead of a log₂(n)-pass butterfly that would make log₂(n)
trips through HBM.  This is the TPU answer to SpiralWHT's cache-blocked
recursion.  DCT rides XLA's native FFT (``jax.scipy.fft.dct``), matching
the reference's FFTW ``REDFT10`` path.

All transforms here are orthonormal (Hᵀ·H = I), unlike FFTW's unnormalized
r2r kernels — scale factors in FJLT/Fastfood account for this explicitly.
"""

from __future__ import annotations

from functools import lru_cache

import jax.numpy as jnp
import jax.scipy.fft as jfft
import numpy as np

from ..core.context import SketchContext
from ..core.random import sample
from .base import Dimension, SketchTransform

__all__ = ["wht", "dct", "next_pow2", "RFUT"]

_MAX_FACTOR_LOG2 = 8  # dense Hadamard factors up to 256x256


def next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


@lru_cache(maxsize=16)
def _hadamard(k: int) -> np.ndarray:
    """Dense 2^k × 2^k Sylvester Hadamard matrix (unnormalized, ±1)."""
    H = np.array([[1.0]])
    for _ in range(k):
        H = np.block([[H, H], [H, -H]])
    return H


def wht(x, axis: int = 0):
    """Orthonormal Walsh-Hadamard transform along ``axis`` (size 2^k).

    Sylvester (natural) ordering: row-major index factorization matches
    ``H = H_{f0} ⊗ H_{f1} ⊗ ...``, so the transform is a chain of small
    dense einsum contractions that XLA maps onto the MXU.  The factor
    axes are expanded *in place* (no moveaxis of the whole array): for
    multi-GB operands a front-transpose would cost two extra full HBM
    passes per factor.
    """
    x = jnp.asarray(x)
    axis = axis % x.ndim
    n = x.shape[axis]
    k = n.bit_length() - 1
    if n != (1 << k):
        raise ValueError(f"wht needs a power-of-2 size, got {n}")
    if n == 1:
        return x
    chunks = []
    rem = k
    while rem > 0:
        c = min(rem, _MAX_FACTOR_LOG2)
        chunks.append(c)
        rem -= c
    factors = [1 << c for c in chunks]
    lead = x.shape[:axis]
    trail = x.shape[axis + 1 :]
    x = x.reshape(*lead, *factors, *trail)
    # Einsum letters: leading dims, factor dims, trailing dims.
    nlead, nfac, ntrail = len(lead), len(factors), len(trail)
    letters = "abcdefghijklmnopqrstuvw"
    lead_l = letters[:nlead]
    fac_l = letters[nlead : nlead + nfac]
    trail_l = letters[nlead + nfac : nlead + nfac + ntrail]
    # f32/f64 inputs pin full matmul precision: the TPU MXU's default
    # drops f32 operands to bf16 mantissas, which silently degraded the
    # transform to ~1e-2 absolute error on hardware (caught by the
    # compiled-kernel parity test, tests/test_pallas_hw.py).  H is ±1, so
    # only the input mantissa width matters.  (A bf16_split3 chain was
    # measured SLOWER than precision="highest" here — the factor einsums
    # are layout-bound, not MXU-bound — so the simple pin stays; the
    # split pays only in the single big-GEMM paths, fjlt.py/hash.py.)
    prec = None if x.dtype == jnp.bfloat16 else "highest"
    for i, c in enumerate(chunks):
        H = jnp.asarray(_hadamard(c), x.dtype)
        in_sub = lead_l + fac_l + trail_l
        out_sub = in_sub.replace(fac_l[i], "z")
        x = jnp.einsum(
            f"{in_sub},z{fac_l[i]}->{out_sub}", x, H, precision=prec
        )
    x = x.reshape(*lead, n, *trail)
    return x * jnp.asarray(1.0 / np.sqrt(n), x.dtype)


def dct(x, axis: int = 0):
    """Orthonormal DCT-II (≙ FFTW ``REDFT10`` with ortho scaling,
    ``utility/fft/fftw_futs.h:118-126``)."""
    return jfft.dct(x, type=2, norm="ortho", axis=axis)


_FUTS = {"wht": wht, "dct": dct}


def get_fut(name: str):
    if name not in _FUTS:
        raise ValueError(f"unknown FUT {name!r}; known: {sorted(_FUTS)}")
    return _FUTS[name]


class RFUT(SketchTransform):
    """Randomized fast unitary transform: X → F·(D ⊙ X), D a random
    diagonal (default Rademacher).

    ≙ ``RFUT_t`` (``sketch/RFUT.hpp:17``): the mixing building block of
    FJLT and Fastfood.  For the WHT backend with non-power-of-2 N the
    input is zero-padded to ``next_pow2(N)``, so S = the padded size; the
    DCT backend keeps S = N exactly (the reference's FFTW path).

    Not in the string-typed registry: like the reference's C API (16
    types, ``capi/csketch.cpp:15-58``), RFUT is a building block, not a
    standalone sketch — and its (n, context) signature differs from the
    factory's (n, s, context).
    """

    sketch_type = "RFUT"
    diag_dist = "rademacher"

    def __init__(
        self, n: int, context: SketchContext, fut: str = "wht"
    ):
        self._fut_name = fut
        self._nb = next_pow2(n) if fut == "wht" else n
        super().__init__(n, self._nb, context)
        self._seed = context.seed
        self._d_base = context.reserve(n)

    def diagonal(self, dtype=jnp.float32):
        return sample(self.diag_dist, self._seed, self._d_base, self.n, dtype=dtype)

    def apply(self, A, dim: Dimension | str = Dimension.COLUMNWISE):
        dim = Dimension.of(dim)
        A = jnp.asarray(A)
        if not jnp.issubdtype(A.dtype, jnp.floating):
            A = A.astype(jnp.float32)
        squeeze = A.ndim == 1
        if squeeze:
            A = A[:, None] if dim is Dimension.COLUMNWISE else A[None, :]
        axis = 0 if dim is Dimension.COLUMNWISE else A.ndim - 1
        if A.shape[axis] != self.n:
            raise ValueError(
                f"{dim.value} apply needs {self.n} on axis {axis}, got {A.shape}"
            )
        D = self.diagonal(A.dtype)
        shape = [1] * A.ndim
        shape[axis] = self.n
        X = A * D.reshape(shape)
        if self._nb != self.n:
            pad = [(0, 0)] * A.ndim
            pad[axis] = (0, self._nb - self.n)
            X = jnp.pad(X, pad)
        out = get_fut(self._fut_name)(X, axis=axis)
        if squeeze:
            out = out[:, 0] if dim is Dimension.COLUMNWISE else out[0]
        return out

    def _param_dict(self):
        return {"fut": self._fut_name}

    @classmethod
    def _from_param_dict(cls, d, context):
        return cls(d["N"], context, fut=d.get("fut", "wht"))
