"""Fast Johnson-Lindenstrauss transform (FJLT).

≙ ``sketch/FJLT_data.hpp:19-95`` + ``sketch/FJLT_Elemental.hpp``:
D (Rademacher diagonal) → fast unitary transform → uniform row sample with
rescale.  Counter budget matches the reference's build order: N for the
RFUT diagonal, then S for the sample indices
(``FJLT_data.hpp:80-86``).

TPU mapping (≙ the ``[VC,*] → [*,*]`` redistribute + local-FUT plan of
``FJLT_Elemental.hpp:144-186``): under GSPMD the FUT along the sketched
axis wants that axis unsharded; XLA inserts the all-to-all the reference
hand-codes as an Elemental redistribution.  Sampling and scaling are
elementwise/gather — local.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

from ..core.context import SketchContext
from ..core.precision import bf16_split3
from .base import Dimension, SketchTransform, register_sketch
from .fut import RFUT
from .sampling import UST
from . import pallas_window

__all__ = ["FJLT"]


def _use_pallas() -> bool:
    return (
        os.environ.get("SKYLARK_NO_PALLAS", "0") != "1"
        and jax.default_backend() == "tpu"
    )


_GATHER_COMPILES: bool | None = None


def _gather_compiles() -> bool:
    """One-time compiled self-test of the scaled-row-gather kernel
    (:func:`pallas_window.self_check_gather`) on the default backend —
    the ``hash._window_compiles`` probe pattern: scalar-indexed sublane
    addressing is the piece Mosaic may refuse, the verdict is cached
    unconditionally (it bakes into callers' jit executables either way),
    and transient device errors get two bounded retries."""
    global _GATHER_COMPILES
    for attempt in range(3):
        if _GATHER_COMPILES is not None:
            break
        import warnings

        try:
            with jax.ensure_compile_time_eval():
                err = pallas_window.self_check_gather()
            # Pure selection + identical multiply: the kernel is bitwise
            # equal to the XLA gather, so any nonzero error means the
            # dynamic addressing mis-resolved.
            _GATHER_COMPILES = err == 0.0
            if not _GATHER_COMPILES:
                warnings.warn(
                    "Pallas gather kernel compiled but miscomputed "
                    f"(rel err {err:g} vs XLA gather); falling back to "
                    "the XLA sampled gather for this process",
                    RuntimeWarning,
                    stacklevel=2,
                )
        except Exception as e:  # noqa: BLE001 — any lowering failure → XLA
            msg = repr(e)
            transient = any(
                tok in msg
                for tok in ("UNAVAILABLE", "DEADLINE", "RESOURCE_EXHAUSTED")
            )
            if transient and attempt < 2:
                import time

                time.sleep(3.0)
                continue
            warnings.warn(
                "Pallas gather kernel probe failed; falling back to the "
                f"XLA sampled gather for this process: {msg[:300]}",
                RuntimeWarning,
                stacklevel=2,
            )
            _GATHER_COMPILES = False
    return _GATHER_COMPILES


def _gather_mode(nrows: int, s: int, m: int, dtype) -> str:
    """STATIC routing for the sampled-transform epilogue gather — shape,
    dtype, env, and the one-time probe only, never values (the
    ``hash._window_mode`` discipline, so planned≡eager holds by
    construction).  f32 only: the full-source VMEM tile is padded to the
    f32 (8, 128) grain.  ``SKYLARK_PALLAS_GATHER=1`` forces the kernel,
    ``=interpret`` runs it in interpret mode (CPU tests), ``=0`` forces
    XLA."""
    mode = os.environ.get("SKYLARK_PALLAS_GATHER", "")
    forced = mode in ("1", "interpret")
    ok = (
        jnp.dtype(dtype) == jnp.float32
        and pallas_window.supported_gather(nrows, s, m)
    )
    if not ok or mode == "0":
        return "xla"
    if forced:
        return "interpret" if mode == "interpret" else "kernel"
    if (
        jax.default_backend() == "tpu"
        and pallas_window.worthwhile_gather(nrows, s, m)
        and _gather_compiles()
    ):
        return "kernel"
    return "xla"


_SAMPLED_KERNEL_OK: dict = {}


def _sampled_kernel_compiles(
    dtype=jnp.float32, nb: int = 512, s: int = 128, tm: int = 8
) -> bool:
    """Compiled self-test of the fused sampled-FJLT kernel at the REAL
    call's (dtype, NB, S, tile) — Mosaic lowering of the lane gather can
    vary with vector layout, and the layout depends on the block's
    sublane count too, so the probe runs at m = the production tile
    (``_tile_rows(tm, nb) == tm`` for any tile the caller selected).
    Verdict cached per configuration; transient device errors get two
    bounded retries — same pattern and rationale as
    ``hash._kernel_compiles``."""
    key = (jnp.dtype(dtype).name, nb, s, tm)
    for attempt in range(3):
        if key in _SAMPLED_KERNEL_OK:
            break
        import warnings

        from . import pallas_fut

        try:
            with jax.ensure_compile_time_eval():
                rng = np.random.default_rng(0)
                x = jnp.asarray(
                    rng.standard_normal((tm, nb)).astype(np.float32)
                ).astype(dtype)
                d = jnp.asarray(
                    rng.choice([-1.0, 1.0], nb).astype(np.float32)
                ).astype(dtype)
                idx = rng.integers(0, nb, s).astype(np.int32)
                out = pallas_fut.rfut_rowwise_sampled(x, d, nb, idx)
                ref = pallas_fut.rfut_rowwise(x, d, nb)[:, idx] * jnp.asarray(
                    np.sqrt(nb / s), dtype
                )
                jax.block_until_ready((out, ref))
                err = float(
                    jnp.max(jnp.abs(out.astype(jnp.float32) - ref))
                )
                scale = float(jnp.max(jnp.abs(ref))) or 1.0
            # f32 threshold matches the hardware guard's 1e-5 bar (the
            # fused and two-step paths run identical ops modulo the
            # scale-multiply order, so real error is ~1 ulp).
            ok = err < 1e-2 * scale if dtype == jnp.bfloat16 else (
                err < 1e-5 * scale
            )
            _SAMPLED_KERNEL_OK[key] = ok
            if not ok:
                warnings.warn(
                    "fused sampled-FJLT kernel compiled but miscomputed "
                    f"at {key} (err {err:g}); using the two-step WHT + "
                    "gather path",
                    RuntimeWarning,
                    stacklevel=2,
                )
        except Exception as e:  # noqa: BLE001 — lowering failure → 2-step
            msg = repr(e)
            if attempt < 2 and any(
                tok in msg
                for tok in ("UNAVAILABLE", "DEADLINE", "RESOURCE_EXHAUSTED")
            ):
                import time

                time.sleep(3.0)
                continue
            warnings.warn(
                "fused sampled-FJLT kernel probe failed at "
                f"{key}; using the two-step WHT + gather path: {msg[:300]}",
                RuntimeWarning,
                stacklevel=2,
            )
            _SAMPLED_KERNEL_OK[key] = False
    return _SAMPLED_KERNEL_OK[key]


# Effective MXU flops-per-HBM-byte at which the explicit subsampled-
# Hadamard matmul overtakes the streamed WHT + lane gather, per matmul
# dtype (measured on v5e: the gather runs far below streaming bandwidth,
# so the crossover favors the matmul strongly for bf16).  f32 inputs ride
# a THREE-PASS bf16 SPLIT (A = hi + lo + lo2 exactly; G is ±1 — exact in
# bf16 — so each pass is an exact selection-and-accumulate in f32 and the
# sum reproduces full f32 precision): 3 bf16 matmuls at ~95% MFU beat
# both the 6-pass f32 matmul and the WHT+gather path (measured r2, the
# VERDICT item-2 fix).  Thresholds per bf16-equivalent pass.
_GEMM_FPB = {
    jnp.bfloat16: 500.0,
    jnp.float32: 500.0 / 3.0,
    jnp.float64: 80.0,  # CPU parity runs: exact matmul, old gate
}
# Element cap on the realized (n, S) ±1 matrix: its transient (plus the
# int32 popcount broadcast) must stay far below HBM capacity — beyond
# this the streamed WHT path is used regardless of the flops gate
# (ADVICE r1: the gate modeled flops-per-byte only and could transiently
# allocate ~1 GB at n=128K, S=1024).
_GEMM_MAX_ELEMENTS = 64 << 20  # 64M entries ≈ 256 MB of int32 transient


@register_sketch
class FJLT(SketchTransform):
    """S·F·D: sample S coordinates of a randomized fast unitary transform.

    With the (orthonormal) FUT the sampled coordinates are rescaled by
    ``sqrt(NB/S)`` so that E‖sketch‖² = ‖x‖² (the reference's
    ``sqrt(N/S)``, ``FJLT_Elemental.hpp:160``, with NB the padded size).
    """

    sketch_type = "FJLT"

    def __init__(self, n: int, s: int, context: SketchContext, fut: str = "wht"):
        super().__init__(n, s, context)
        self._fut_name = fut
        # Counter layout ≙ FJLT_data_t::build: RFUT diagonal (N), then the
        # S sample indices — here a composed UST over the padded space.
        self._rfut = RFUT(n, context, fut=fut)
        self._nb = self._rfut._nb
        self._ust = UST(self._nb, s, context, replace=True)

    @property
    def sample_indices(self):
        """S uniform coordinates in [0, NB) (with replacement)."""
        return self._ust.samples

    def apply(self, A, dim: Dimension | str = Dimension.COLUMNWISE):
        dim = Dimension.of(dim)
        if self._fut_name == "wht" and not hasattr(A, "todense"):
            A2 = jnp.asarray(A)
            if A2.ndim == 2 and jnp.issubdtype(A2.dtype, jnp.floating):
                rowwise = dim is Dimension.ROWWISE
                sk_axis = 1 if rowwise else 0
                if A2.shape[sk_axis] == self.n and self._gemm_wins(A2.dtype):
                    return self._apply_srht_gemm(A2, rowwise)
            if (
                A2.ndim == 2
                and A2.dtype in (jnp.float32, jnp.bfloat16)
                and _use_pallas()
            ):
                from . import pallas_fut

                # Normalize to rowwise: columnwise = transpose in/out (two
                # extra passes; the fused kernel saves more than that vs
                # the XLA WHT lowering).  Gate on A2's dims before forming
                # the transpose so a failed gate costs nothing.
                rowwise = dim is Dimension.ROWWISE
                sk_axis, batch_axis = (1, 0) if rowwise else (0, 1)
                if A2.shape[sk_axis] == self.n and pallas_fut.supported(
                    A2.shape[batch_axis], self.n, self._nb
                ):
                    out = self._apply_pallas(A2 if rowwise else A2.T)
                    return out if rowwise else out.T
        T = self._rfut.apply(A, dim)
        scale = jnp.asarray(np.sqrt(self._nb / self.s), T.dtype)
        if (
            dim is Dimension.COLUMNWISE
            and not hasattr(T, "todense")
            and getattr(T, "ndim", 0) == 2
        ):
            # Sampled-transform epilogue: ``scale * T[idx, :]`` is a row
            # (sublane) gather — the window module's scaled-copy kernel
            # serves it bitwise-identically to XLA (pure selection plus
            # the same elementwise multiply).  Rowwise sampling gathers
            # along lanes, where XLA already wins — it stays put.
            gmode = _gather_mode(T.shape[0], self.s, T.shape[1], T.dtype)
            if gmode != "xla":
                return pallas_window.gather_scaled_rows(
                    T, self.sample_indices, scale,
                    interpret=(gmode == "interpret"),
                )
        return scale * self._ust.apply(T, dim)

    def _gemm_wins(self, dtype) -> bool:
        """Gate for the subsampled-Hadamard-as-matmul path: per input
        row/column the streamed WHT + gather moves ~(n + 2·NB + S)
        itemsize bytes of HBM while the matmul does 2·n·S flops (per
        bf16-equivalent pass — f32 runs the 3-pass bf16 split), so the
        matmul wins whenever its flop/byte ratio stays under the dtype's
        effective MXU-to-bandwidth ratio (``_GEMM_FPB``).  The realized
        ±1 matrix is additionally capped at ``_GEMM_MAX_ELEMENTS``."""
        if os.environ.get("SKYLARK_NO_SRHT_GEMM", "0") == "1":
            return False
        if self.n * self.s > _GEMM_MAX_ELEMENTS:
            return False
        fpb = _GEMM_FPB.get(jnp.dtype(dtype).type)
        if fpb is None:
            # Unknown float dtypes route to the exact precision="highest"
            # matmul branch in _apply_srht_gemm, so gate them at the
            # exact-matmul rate (f64's), not the bf16-split rate.
            fpb = _GEMM_FPB[jnp.float64]
        itemsize = jnp.dtype(dtype).itemsize
        return 2.0 * self.n * self.s <= fpb * itemsize * (
            self.n + 2 * self._nb + self.s
        )

    def _srht_matrix(self, dtype):
        """(n, S) matrix G with G[j, i] = D[j]·(-1)^popcount(j & r_i):
        the S sampled columns of H_NB restricted to the first n rows (the
        padding rows multiply zeros), with the Rademacher diagonal folded
        in.  Entries are ±1 — exact in bf16 — so the 1/√S · √(NB/NB)
        normalization is applied *after* the matmul in f32."""
        idx = self.sample_indices  # (S,) in [0, NB)
        j = jnp.arange(self.n, dtype=jnp.int32)
        bits = jax.lax.population_count(j[:, None] & idx[None, :])
        signs = (1 - 2 * (bits & 1)).astype(dtype)
        return self._rfut.diagonal(dtype)[:, None] * signs

    def _apply_srht_gemm(self, A2, rowwise: bool, G16=None):
        """out = scale · (sampled WHT columns of A ⊙ D) as dense matmul —
        same values as the WHT+gather path (same samples, same diagonal),
        chosen by :meth:`_gemm_wins` when S is small enough that the
        matmul beats the streamed transform + lane gather.

        bf16 inputs: ONE bf16 matmul (G is ±1, exact).  f32/f64 inputs:
        a 3-pass bf16 SPLIT — ``A = hi + lo + lo2`` with each part the
        bf16 rounding of the running residual (the split is exact; 8+8+8
        leading mantissa bits cover f32's 24) — so each pass is an exact
        ±select-and-f32-accumulate and the summed result carries full
        input precision at bf16 MXU rate (~3x faster than the 6-pass f32
        matmul the round-1 gate priced, and ~2x the WHT+gather path)."""
        dtype = A2.dtype
        acc = jnp.promote_types(dtype, jnp.float32)
        contract = (((1,), (0,)), ((), ())) if rowwise else (((0,), (0,)), ((), ()))

        def mm(x, g):
            args = (x, g) if rowwise else (g, x)
            return jax.lax.dot_general(
                *args, contract, preferred_element_type=acc
            )

        if dtype == jnp.bfloat16:
            out = mm(A2, G16 if G16 is not None else self._srht_matrix(dtype))
        elif dtype == jnp.float32:
            if G16 is None:
                G16 = self._srht_matrix(jnp.bfloat16)  # ±1: exact in bf16
            # Bit-mask split (NOT astype round-trips — XLA's excess-
            # precision rules elide f32→bf16→f32 convert pairs, which
            # zeroed lo/lo2 on hardware; see core/precision.py).
            hi, lo, lo2 = bf16_split3(A2)
            out = mm(hi, G16) + mm(lo, G16) + mm(lo2, G16)
        else:  # f64 (CPU parity): exact full-precision matmul
            out = jax.lax.dot_general(
                *((A2, self._srht_matrix(dtype)) if rowwise
                  else (self._srht_matrix(dtype), A2)),
                contract,
                precision="highest",
                preferred_element_type=acc,
            )
        # orthonormal WHT (1/√NB) × sample rescale √(NB/S) = 1/√S.
        return (out * acc.type(1.0 / np.sqrt(self.s))).astype(dtype)

    def hoistable_operands(self, dtype):
        """The (n, S) ±1 subsampled-Hadamard matrix (bf16 — exact), the
        expensive-to-rebuild operand of the SRHT-gemm path.  One matrix
        serves both bf16 and f32 inputs (f32 rides the 3-pass split
        against it)."""
        dt = jnp.dtype(dtype)
        if dt.type not in (jnp.bfloat16, jnp.float32):
            return None  # f64 keeps the exact paths
        if self._fut_name != "wht" or not self._gemm_wins(dt.type):
            # apply_with_operands would fall back to the streamed path —
            # don't realize a dead (n, S) matrix (it can reach 128 MB+).
            return None
        return self._srht_matrix(jnp.bfloat16)

    def apply_with_operands(
        self, ops, A, dim: Dimension | str = Dimension.COLUMNWISE
    ):
        dim = Dimension.of(dim)
        if ops is None or hasattr(A, "todense"):
            return self.apply(A, dim)
        A = jnp.asarray(A)
        if A.ndim != 2 or A.dtype not in (jnp.bfloat16, jnp.float32):
            return self.apply(A, dim)
        if not self._gemm_wins(A.dtype):
            # Per-apply flops still favor the streamed WHT (the hoist
            # only amortizes the matrix BUILD, which the gate never
            # priced) — keep the gate's verdict.
            return self.apply(A, dim)
        rowwise = dim is Dimension.ROWWISE
        if A.shape[1 if rowwise else 0] != self.n:
            raise ValueError(
                f"{dim.value} apply needs {self.n} on the sketched axis, "
                f"got {A.shape}"
            )
        return self._apply_srht_gemm(A, rowwise, G16=ops)

    def _apply_pallas(self, A, interpret: bool = False):
        """Fused one-pass D·x → WHT kernel (natural order, matching the
        XLA path).  When the sampled-epilogue variant is supported (and
        its compiled probe passes on this backend), the S-sample
        selection + rescale happen IN the kernel and only (m, S) ever
        reaches HBM — the f32 large-S fix (VERDICT r4 item 5); otherwise
        the full (m, NB) transform is written and the usual XLA sampled
        gather follows."""
        from . import pallas_fut

        if not jnp.issubdtype(A.dtype, jnp.floating):
            A = A.astype(jnp.float32)
        D = self._rfut.diagonal(A.dtype)
        mode = os.environ.get("SKYLARK_PALLAS_FJLT_SAMPLED", "")
        if (
            mode != "0"
            and pallas_fut.supported_sampled(
                A.shape[0], self.n, self._nb, self.s
            )
            and (
                interpret
                or mode == "1"
                or _sampled_kernel_compiles(
                    A.dtype,
                    self._nb,
                    self.s,
                    pallas_fut._tile_rows(A.shape[0], self._nb),
                )
            )
        ):
            with jax.ensure_compile_time_eval():
                idx = np.asarray(self._ust.samples, np.int32)
            return pallas_fut.rfut_rowwise_sampled(
                A, D, self._nb, idx, interpret=interpret
            )
        T = pallas_fut.rfut_rowwise(A, D, self._nb, interpret=interpret)
        scale = jnp.asarray(np.sqrt(self._nb / self.s), T.dtype)
        return scale * self._ust.apply(T, Dimension.ROWWISE)

    def _param_dict(self):
        return {"fut": self._fut_name}

    @classmethod
    def _from_param_dict(cls, d, context):
        return cls(d["N"], d["S"], context, fut=d.get("fut", "wht"))
