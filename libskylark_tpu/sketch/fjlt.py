"""Fast Johnson-Lindenstrauss transform (FJLT).

≙ ``sketch/FJLT_data.hpp:19-95`` + ``sketch/FJLT_Elemental.hpp``:
D (Rademacher diagonal) → fast unitary transform → uniform row sample with
rescale.  Counter budget matches the reference's build order: N for the
RFUT diagonal, then S for the sample indices
(``FJLT_data.hpp:80-86``).

TPU mapping (≙ the ``[VC,*] → [*,*]`` redistribute + local-FUT plan of
``FJLT_Elemental.hpp:144-186``): under GSPMD the FUT along the sketched
axis wants that axis unsharded; XLA inserts the all-to-all the reference
hand-codes as an Elemental redistribution.  Sampling and scaling are
elementwise/gather — local.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

from ..core.context import SketchContext
from .base import Dimension, SketchTransform, register_sketch
from .fut import RFUT
from .sampling import UST

__all__ = ["FJLT"]


def _use_pallas() -> bool:
    return (
        os.environ.get("SKYLARK_NO_PALLAS", "0") != "1"
        and jax.default_backend() == "tpu"
    )


@register_sketch
class FJLT(SketchTransform):
    """S·F·D: sample S coordinates of a randomized fast unitary transform.

    With the (orthonormal) FUT the sampled coordinates are rescaled by
    ``sqrt(NB/S)`` so that E‖sketch‖² = ‖x‖² (the reference's
    ``sqrt(N/S)``, ``FJLT_Elemental.hpp:160``, with NB the padded size).
    """

    sketch_type = "FJLT"

    def __init__(self, n: int, s: int, context: SketchContext, fut: str = "wht"):
        super().__init__(n, s, context)
        self._fut_name = fut
        # Counter layout ≙ FJLT_data_t::build: RFUT diagonal (N), then the
        # S sample indices — here a composed UST over the padded space.
        self._rfut = RFUT(n, context, fut=fut)
        self._nb = self._rfut._nb
        self._ust = UST(self._nb, s, context, replace=True)

    @property
    def sample_indices(self):
        """S uniform coordinates in [0, NB) (with replacement)."""
        return self._ust.samples

    def apply(self, A, dim: Dimension | str = Dimension.COLUMNWISE):
        dim = Dimension.of(dim)
        if self._fut_name == "wht" and not hasattr(A, "todense"):
            A2 = jnp.asarray(A)
            if (
                A2.ndim == 2
                and A2.dtype in (jnp.float32, jnp.bfloat16)
                and _use_pallas()
            ):
                from . import pallas_fut

                # Normalize to rowwise: columnwise = transpose in/out (two
                # extra passes; the fused kernel saves more than that vs
                # the XLA WHT lowering).  Gate on A2's dims before forming
                # the transpose so a failed gate costs nothing.
                rowwise = dim is Dimension.ROWWISE
                sk_axis, batch_axis = (1, 0) if rowwise else (0, 1)
                if A2.shape[sk_axis] == self.n and pallas_fut.supported(
                    A2.shape[batch_axis], self.n, self._nb
                ):
                    out = self._apply_pallas(A2 if rowwise else A2.T)
                    return out if rowwise else out.T
        T = self._rfut.apply(A, dim)
        scale = jnp.asarray(np.sqrt(self._nb / self.s), T.dtype)
        return scale * self._ust.apply(T, dim)

    def _apply_pallas(self, A, interpret: bool = False):
        """Fused one-pass D·x → WHT kernel (natural order, matching the
        XLA path), then the usual sampled gather."""
        from . import pallas_fut

        if not jnp.issubdtype(A.dtype, jnp.floating):
            A = A.astype(jnp.float32)
        D = self._rfut.diagonal(A.dtype)
        T = pallas_fut.rfut_rowwise(A, D, self._nb, interpret=interpret)
        scale = jnp.asarray(np.sqrt(self._nb / self.s), T.dtype)
        return scale * self._ust.apply(T, Dimension.ROWWISE)

    def _param_dict(self):
        return {"fut": self._fut_name}

    @classmethod
    def _from_param_dict(cls, d, context):
        return cls(d["N"], d["S"], context, fut=d.get("fut", "wht"))
