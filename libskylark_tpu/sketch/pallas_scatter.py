"""Pallas TPU segment-sum (scatter-add) for the sparse hash sketches.

XLA's TPU scatter lowering runs ~28 M nnz/s (BASELINE.md round 3) — an
order of magnitude off the HBM roofline for the CWT/SJLT BCOO
``dense_output`` path (``hash.py::_apply_sparse_dense_out``), whose work
is one flat ``out[key[i]] += val[i]`` over 1e7-1e8 entries into up to
1e8 slots (≙ the queue-then-finalize CSC build of
``hash_transform_local_sparse.hpp:88-152`` / the mixed sparse→dense
apply of ``hash_transform_Mixed.hpp``).

TPU has no vector scatter, so the kernel restructures the problem around
what the hardware does have:

1. **partition pass** (grid over entry chunks): each chunk of C entries
   is sorted by destination PARTITION (``key // V``, V = slot span per
   partition).  The rank/offset arithmetic is pure VPU work (one-hot +
   cumsum); the final in-chunk permutation is a C-trip scalar loop in
   VMEM.  The sorted chunk and its per-partition histogram row go back
   to HBM.  Padding entries get the tail partition and are never read
   again.
2. **accumulate pass** (grid (P, K), K fastest): partition p owns slot
   range [p·V, (p+1)·V) as an f32 VMEM scratch accumulator shaped
   (V/128, 128) — lane-tiled, so no 8× sublane padding.  For each chunk
   it walks the chunk's p-span (contiguous after pass 1; bounds come in
   as (1, 1) blocks of the span table) with a scalar accumulate loop —
   every entry is touched exactly ONCE across the whole grid — and at
   the last chunk writes the accumulator to its output block.

Total scalar work is 2 touches/entry (pass-1 permutation + pass-2
accumulate); everything else is vector/DMA.  Fallback: anything
unsupported (gate below) takes ``jax.ops.segment_sum``;
``SKYLARK_NO_PALLAS=1`` forces the fallback.  C and P are module
constants; ``experiments/scatter_probe.py`` measures the pieces on
hardware.
"""

from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp

__all__ = ["segment_sum_flat", "supported", "self_check"]

# Entries per chunk (pass-1 grid step).  Larger C cuts pass-2 grid-step
# count and chunk-revisit overhead at the cost of pass-1 VMEM (the
# (C, P+1) one-hot/cumsum pair); env-tunable so the hardware probe can
# sweep it (experiments/scatter_probe.py).
_C = int(os.environ.get("SKYLARK_SCATTER_CHUNK", "2048"))
_P = 64  # target partition count; V = ceil(T / P) rounded to 1024
_VMEM_SLOTS = 2_097_152  # max V: an 8 MB f32 accumulator


def _plan(nnz: int, num_segments: int):
    V = -(-num_segments // _P)
    V = max(-(-V // 1024) * 1024, 1024)  # (V/128, 128) stays sublane-tiled
    P = -(-num_segments // V)
    K = -(-nnz // _C)
    return K, P, V


# The two passes hold (keys, vals) plus their sorted copies in HBM
# (~16 B/entry beyond the caller's input); past this entry count the
# working set crowds a 16 GB chip and the XLA path (in-place scatter)
# is the safer choice (SJLT nnz=4 at 1e8 input nonzeros = 4e8 entries).
_MAX_NNZ = 150_000_000


def supported(nnz: int, num_segments: int) -> bool:
    if os.environ.get("SKYLARK_NO_PALLAS", "0") == "1":
        return False
    if nnz < 4 * _C or num_segments < 1024:
        return False  # too small to amortize two passes
    if nnz > _MAX_NNZ:
        return False
    _, P, V = _plan(nnz, num_segments)
    return V <= _VMEM_SLOTS and (P + 1) * V < (1 << 31)


# ---------------------------------------------------------------------------
# pass 1: chunk-sort by partition
# ---------------------------------------------------------------------------


def _cumsum_sublanes(x):
    """Inclusive cumsum along axis 0 via log-step shifted adds — static
    slices + pads only (Mosaic has no native cumulative-sum lowering;
    jnp.cumsum inside a TPU kernel is not guaranteed to lower)."""
    n, s = x.shape[0], 1
    while s < n:
        x = x + jnp.pad(x[:-s], ((s, 0), (0, 0)))
        s *= 2
    return x


def _excl_cumsum_lanes(row):
    """Exclusive cumsum along axis 1 of a (1, n) row, same log-step
    construction (lane-axis shifts are static slices)."""
    n, s = row.shape[1], 1
    out = row
    while s < n:
        out = out + jnp.pad(out[:, :-s], ((0, 0), (s, 0)))
        s *= 2
    return out - row


def _partition_kernel(
    V, PP, keys_ref, vals_ref, sk_ref, sv_ref, cnt_ref, dest_ref
):
    """Sort one (1, C) chunk by partition id; emit its histogram row."""
    C = keys_ref.shape[1]
    keys = keys_ref[0, :]
    pid = jnp.minimum(keys // V, PP - 1)  # padding keys -> tail partition
    iota_p = jax.lax.broadcasted_iota(jnp.int32, (C, PP), 1)
    onehot = (pid[:, None] == iota_p).astype(jnp.int32)
    # dtype pinned: under x64 (interpret-mode CPU tests) jnp.sum would
    # promote int32 to int64, which the int32 refs reject.
    counts_row = jnp.sum(
        onehot, axis=0, keepdims=True, dtype=jnp.int32
    )  # (1, PP)
    cnt_ref[0, :] = counts_row[0, :]
    # exclusive start of each partition's span within the sorted chunk,
    # plus each entry's rank among same-pid entries before it
    pstart_row = _excl_cumsum_lanes(counts_row)  # (1, PP)
    inc = _cumsum_sublanes(onehot)  # (C, PP)
    rank = jnp.sum(onehot * inc, axis=1, dtype=jnp.int32) - 1  # (C,)
    dest_ref[0, :] = (
        jnp.sum(onehot * pstart_row, axis=1, dtype=jnp.int32) + rank
    )

    def body(i, c):
        d = dest_ref[0, i]
        sk_ref[0, d] = keys_ref[0, i]
        sv_ref[0, d] = vals_ref[0, i]
        return c

    jax.lax.fori_loop(0, C, body, 0)


# ---------------------------------------------------------------------------
# pass 2: per-partition scalar accumulate
# ---------------------------------------------------------------------------


def _accumulate_kernel(
    V, lanemask, base_ref, sk_ref, sv_ref, start_ref, stop_ref, out_ref,
    acc_ref
):
    from jax.experimental import pallas as pl

    k = pl.program_id(1)
    K = pl.num_programs(1)

    @pl.when(k == 0)
    def _zero():
        acc_ref[:, :] = jnp.zeros_like(acc_ref)

    base = base_ref[0, 0]
    s = start_ref[0, 0]
    e = stop_ref[0, 0]

    if lanemask:
        # Lane-masked RMW: dynamic sublane index + full-lane vector ops
        # only (no dynamic LANE addressing, which Mosaic may not lower
        # for scalar stores) — ~4 vector ops per entry.
        lane_iota = jax.lax.broadcasted_iota(jnp.int32, (1, 128), 1)

        def entry(i, c):
            local = sk_ref[0, i] - base
            row, lane = local // 128, local % 128
            acc_row = acc_ref[pl.ds(row, 1), :]
            acc_ref[pl.ds(row, 1), :] = acc_row + jnp.where(
                lane_iota == lane, sv_ref[0, i], jnp.float32(0)
            )
            return c

    else:

        def entry(i, c):
            local = sk_ref[0, i] - base
            row, lane = local // 128, local % 128
            acc_ref[row, lane] = acc_ref[row, lane] + sv_ref[0, i]
            return c

    jax.lax.fori_loop(s, e, entry, 0)

    @pl.when(k == K - 1)
    def _emit():
        out_ref[:, :] = acc_ref[:, :]


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------


def self_check(
    nnz: int = 40_000, num_segments: int = 1 << 17, interpret: bool = False
) -> float:
    """Max *relative* error of the kernel vs ``jax.ops.segment_sum`` on
    random keys/values — the ONE validator shared by the library's
    TPU-default probe (``hash._kernel_compiles``) and the hardware guard
    (``tests/_hw_guards.py::guard_pallas_scatter_compiled``), so the two
    cannot drift apart.  Raises on lowering failure; callers decide the
    tolerance (1e-5 is the established hardware bar)."""
    k1, k2 = jax.random.split(jax.random.PRNGKey(3))
    keys = jax.random.randint(k1, (nnz,), 0, num_segments, dtype=jnp.int32)
    vals = jax.random.normal(k2, (nnz,), jnp.float32)
    out = segment_sum_flat(vals, keys, num_segments, interpret=interpret)
    ref = jax.ops.segment_sum(vals, keys, num_segments=num_segments)
    jax.block_until_ready((out, ref))
    scale = jnp.maximum(jnp.max(jnp.abs(ref)), 1e-30)
    return float(jnp.max(jnp.abs(out - ref)) / scale)


def segment_sum_flat(vals, keys, num_segments: int, interpret: bool = False):
    """``out[t] = sum(vals[keys == t])`` for flat int32 keys in
    [0, num_segments).  Caller gates with :func:`supported`; ``vals``
    and ``keys`` are 1-D and equal length.

    Non-f32 floating ``vals`` (bf16/f16/f64) take the f32-accumulate
    boundary cast: exact on the way in for the narrow types, one
    rounding on the way out — so the precision ladders
    (``core/precision.py``) no longer force the XLA scatter lowering.
    Callers gate the f64 demotion through
    ``precision.f32_accumulable(demote_f64=...)``."""
    # Accumulate mode: "scalar" (1 scalar RMW/entry — needs dynamic-lane
    # addressing) or "lanemask" (vector RMW, no dynamic lanes).  Read
    # OUTSIDE the jitted impl so a mode switch is a fresh trace, not a
    # stale cache hit.
    lanemask = os.environ.get("SKYLARK_SCATTER_ACCUM", "scalar") == "lanemask"
    out_dtype = vals.dtype
    out = _segment_sum_impl(vals, keys, num_segments, interpret, lanemask)
    if out_dtype != jnp.float32 and jnp.issubdtype(out_dtype, jnp.floating):
        return out.astype(out_dtype)
    return out


@partial(
    jax.jit, static_argnames=("num_segments", "interpret", "lanemask")
)
def _segment_sum_impl(
    vals, keys, num_segments: int, interpret: bool, lanemask: bool
):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nnz = vals.shape[0]
    K, P, V = _plan(nnz, num_segments)
    PP = P + 1  # + tail partition for padding entries
    pad = K * _C - nnz
    keys_p = jnp.pad(
        keys.astype(jnp.int32), (0, pad), constant_values=PP * V - 1
    ).reshape(K, _C)
    vals_p = jnp.pad(vals.astype(jnp.float32), (0, pad)).reshape(K, _C)

    sk, sv, counts = pl.pallas_call(
        partial(_partition_kernel, V, PP),
        grid=(K,),
        in_specs=[
            pl.BlockSpec((1, _C), lambda k: (k, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, _C), lambda k: (k, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, _C), lambda k: (k, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, _C), lambda k: (k, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, PP), lambda k: (k, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((K, _C), jnp.int32),
            jax.ShapeDtypeStruct((K, _C), jnp.float32),
            jax.ShapeDtypeStruct((K, PP), jnp.int32),
        ],
        scratch_shapes=[pltpu.VMEM((1, _C), jnp.int32)],
        interpret=interpret,
    )(keys_p, vals_p)

    # span bounds per (chunk, partition): prefix sums along PP (XLA side)
    stops = jnp.cumsum(counts, axis=1)
    starts = stops - counts
    bases = (jnp.arange(P, dtype=jnp.int32) * V).reshape(P, 1)

    out = pl.pallas_call(
        partial(_accumulate_kernel, V, lanemask),
        grid=(P, K),  # K fastest: accumulator persists across chunks
        in_specs=[
            pl.BlockSpec((1, 1), lambda p, k: (p, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, _C), lambda p, k: (k, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, _C), lambda p, k: (k, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda p, k: (k, p),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda p, k: (k, p),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (V // 128, 128), lambda p, k: (p, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((P * V // 128, 128), jnp.float32),
        scratch_shapes=[pltpu.VMEM((V // 128, 128), jnp.float32)],
        interpret=interpret,
    )(bases, sk, sv, starts, stops)

    return out.reshape(-1)[:num_segments]
