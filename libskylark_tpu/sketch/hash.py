"""Hash (CountSketch-family) sketches: CWT, MMT, WZT.

Re-design of the reference's hash_transform engine
(``sketch/hash_transform_data.hpp:21-104`` + the Elemental / local-sparse /
CombBLAS apply specializations, ``sketch/hash_transform_Elemental.hpp``,
``hash_transform_local_sparse.hpp``, ``hash_transform_CombBLAS.hpp``):
each input coordinate i in [0, N) is hashed to one output slot
``bucket[i] ~ U{0..S-1}`` with a random scaling ``value[i]`` (±1 for CWT,
Cauchy for MMT, signed reciprocal-exponential for WZT).  Columnwise,

    SA[r, :] = sum_{i : bucket[i] == r} value[i] * A[i, :]

Both arrays are counter-derived (two reserved blocks of N), so any shard can
compute its own slice of (bucket, value) without communication — the same
"hash arrays precomputed from the context" design as the reference, minus
the materialized std::vectors.

TPU mapping: the scatter-add becomes ``jax.ops.segment_sum`` (XLA scatter,
which GSPMD handles sharded); for BCOO sparse inputs the hash relabels
row/col indices directly and defers duplicate summation — exactly the
queue-then-finalize CSC build of ``hash_transform_local_sparse.hpp:88-152``.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..core.context import SketchContext
from ..core.precision import bf16_split3, f32_accumulable
from ..core.random import sample
from . import pallas_scatter, pallas_window
from .base import Dimension, SketchTransform, register_sketch


_KERNEL_COMPILES: bool | None = None
_WINDOW_COMPILES: bool | None = None


def _kernel_compiles() -> bool:
    """One-time compiled self-test of the Pallas scatter kernel on the
    default backend.  The kernel's scalar-accumulate stores are the part
    Mosaic may refuse to lower on some TPU generations; running the
    shared validator once here (under ``ensure_compile_time_eval`` so it
    executes eagerly even when the caller is mid-trace) turns a
    would-be compile-time crash of every CWT/SJLT dense apply into a
    warned, process-wide XLA fallback."""
    global _KERNEL_COMPILES
    for attempt in range(3):
        if _KERNEL_COMPILES is not None:
            break
        import warnings

        try:
            # Shared validator (random keys across the full segment
            # range — a kernel that lowers but mis-resolves dynamic-lane
            # addressing must fail the comparison); same code path as
            # the hardware guard, so the two cannot drift.  The verdict
            # is cached unconditionally: callers sit inside jit traces,
            # so whichever branch the first trace takes is baked into
            # the compiled program anyway — a per-call re-probe would be
            # an illusion (and nnz probes per SJLT trace, a stampede).
            # ensure_compile_time_eval: under omnistaging the probe's
            # ops would otherwise be staged into the *caller's* trace
            # and the float() readback would raise ConcretizationError.
            with jax.ensure_compile_time_eval():
                err = pallas_scatter.self_check()
            _KERNEL_COMPILES = err < 1e-5
            if not _KERNEL_COMPILES:
                warnings.warn(
                    "Pallas scatter kernel compiled but miscomputed "
                    f"(rel err {err:g} vs segment_sum); falling back to "
                    "jax.ops.segment_sum for this process",
                    RuntimeWarning,
                    stacklevel=2,
                )
        except Exception as e:  # noqa: BLE001 — any lowering failure → XLA
            # Transient device errors (tunnel flap) get two bounded
            # in-probe retries; the final verdict is still cached
            # unconditionally — it gets baked into callers' jit caches
            # either way, so a post-hoc re-probe would be an illusion.
            msg = repr(e)
            transient = any(
                tok in msg
                for tok in ("UNAVAILABLE", "DEADLINE", "RESOURCE_EXHAUSTED")
            )
            if transient and attempt < 2:
                import time

                time.sleep(3.0)
                continue
            warnings.warn(
                "Pallas scatter kernel probe failed; falling back to "
                f"jax.ops.segment_sum for this process: {msg[:300]}",
                RuntimeWarning,
                stacklevel=2,
            )
            _KERNEL_COMPILES = False
    return _KERNEL_COMPILES


def _segment_sum(addends, key, num_segments: int):
    """Flat scatter-add: the Pallas two-pass kernel on TPU (an order of
    magnitude past XLA's scatter lowering at 1e7+ nnz — see
    ``pallas_scatter``), ``jax.ops.segment_sum`` everywhere else.
    ``SKYLARK_PALLAS_SCATTER=1`` forces the kernel, ``=interpret`` runs
    it in interpret mode (CPU tests), ``SKYLARK_NO_PALLAS=1`` forces the
    XLA path.  The TPU-default branch only engages after a one-time
    compiled probe confirms Mosaic can lower the kernel (ADVICE r4).

    Dtype gate: f32 natively; bf16/f16 ride the kernel's f32-accumulate
    boundary cast (``precision.f32_accumulable``); f64 engages the
    (demoting) cast only under a forced mode — x64 parity runs keep
    XLA's full-precision lowering by default."""
    mode = os.environ.get("SKYLARK_PALLAS_SCATTER", "")
    forced = mode in ("1", "interpret")
    ok = f32_accumulable(
        addends.dtype, demote_f64=forced
    ) and pallas_scatter.supported(addends.shape[0], num_segments)
    if ok and forced:
        return pallas_scatter.segment_sum_flat(
            addends, key, num_segments, interpret=(mode == "interpret")
        )
    if (
        ok
        and mode != "0"
        and jax.default_backend() == "tpu"
        and _kernel_compiles()
    ):
        return pallas_scatter.segment_sum_flat(addends, key, num_segments)
    return jax.ops.segment_sum(addends, key, num_segments=num_segments)


def _window_compiles() -> bool:
    """One-time compiled self-test of the Pallas WINDOW kernel on the
    default backend — same probe discipline (and the same shared
    validator + cached-verdict rationale) as :func:`_kernel_compiles`:
    the scalar-indexed vector RMW is the piece Mosaic may refuse on
    some TPU generations, and callers sit inside jit traces, so the
    first verdict is baked into their executables either way."""
    global _WINDOW_COMPILES
    for attempt in range(3):
        if _WINDOW_COMPILES is not None:
            break
        import warnings

        try:
            with jax.ensure_compile_time_eval():
                err = pallas_window.self_check()
            _WINDOW_COMPILES = err < 1e-5
            if not _WINDOW_COMPILES:
                warnings.warn(
                    "Pallas window kernel compiled but miscomputed "
                    f"(rel err {err:g} vs segment_sum); falling back to "
                    "jax.ops.segment_sum for this process",
                    RuntimeWarning,
                    stacklevel=2,
                )
        except Exception as e:  # noqa: BLE001 — any lowering failure → XLA
            msg = repr(e)
            transient = any(
                tok in msg
                for tok in ("UNAVAILABLE", "DEADLINE", "RESOURCE_EXHAUSTED")
            )
            if transient and attempt < 2:
                import time

                time.sleep(3.0)
                continue
            warnings.warn(
                "Pallas window kernel probe failed; falling back to "
                f"jax.ops.segment_sum for this process: {msg[:300]}",
                RuntimeWarning,
                stacklevel=2,
            )
            _WINDOW_COMPILES = False
    return _WINDOW_COMPILES


def _window_mode(k: int, m: int, num_segments: int, dtype, nnz: int = 1) -> str:
    """STATIC routing decision for the windowed row scatter-add — shape,
    dtype, env, and the one-time probe only, never values.  Returns
    ``"xla"``, ``"kernel"``, or ``"interpret"``.  Because every input is
    static, the eager apply_slice path and the planned slice-kernel path
    of the same (shape, dtype) block resolve to the SAME branch — the
    bitwise planned≡eager contract holds by construction, whichever
    kernel wins.  ``nnz > 1`` rates the stacked (SJLT/OSNAP) launch,
    whose entry count is nnz·k.  ``SKYLARK_PALLAS_WINDOW=1`` forces the
    kernel, ``=interpret`` runs it in interpret mode (CPU tests), ``=0``
    (or ``SKYLARK_NO_PALLAS=1``) forces the XLA path."""
    mode = os.environ.get("SKYLARK_PALLAS_WINDOW", "")
    forced = mode in ("1", "interpret")
    ok = f32_accumulable(
        dtype, demote_f64=forced
    ) and pallas_window.supported(k, num_segments, m, nnz)
    if not ok or mode == "0":
        return "xla"
    if forced:
        return "interpret" if mode == "interpret" else "kernel"
    if (
        jax.default_backend() == "tpu"
        and pallas_window.worthwhile(k, num_segments, m, nnz)
        and _window_compiles()
    ):
        return "kernel"
    return "xla"


def _segment_sum_rows(A_block, b, v, num_segments: int, mode: str, acc=None):
    """Row scatter-add ``out[b[i], :] += v[i] * A_block[i, :]`` — the
    windowed analogue of :func:`_segment_sum`, and the ONE dispatcher
    both the eager ``_apply_slice_columnwise`` and the jit-safe
    ``apply_slice_kernel`` call (with ``mode`` decided up front by
    :func:`_window_mode`), so the plans slice path and the eager path
    pick the same kernel by construction.  ``b``/``v`` may be stacked
    (nnz, k) — every hash function accumulates in ONE kernel launch (or
    one flat XLA scatter).  ``v`` must carry the caller's compute dtype
    on the XLA branch and f32 on the kernel branches (the value
    realization dtype is part of the routing decision, not of this
    function).  ``acc`` (f32, kernel modes only) folds the streaming
    accumulator add into the kernel's emit — the fused stream-chunk
    path.  Kernel output is f32; the caller casts at the boundary."""
    if mode == "xla":
        if b.ndim == 2:
            m = A_block.shape[1]
            stacked = (v[:, :, None] * A_block[None, :, :]).reshape(-1, m)
            return jax.ops.segment_sum(
                stacked, b.reshape(-1), num_segments=num_segments
            )
        return jax.ops.segment_sum(
            v[:, None] * A_block, b, num_segments=num_segments
        )
    return pallas_window.scatter_rows(
        A_block, b, v, num_segments, acc=acc,
        interpret=(mode == "interpret"),
    )

__all__ = ["HashSketch", "CWT", "MMT", "WZT", "SJLT"]


class HashSketch(SketchTransform):
    """Base engine: bucket ~ uniform_int(0, S-1), value ~ ``value_dist``.

    ``nnz`` hash functions per input coordinate generalize the engine from
    CountSketch (nnz=1) to OSNAP/SJLT (nnz>1): coordinate i contributes at
    nnz hashed slots.  The counter layout is (nnz·N indices, nnz·N values)
    — identical to the reference's two reserved blocks for nnz=1
    (``hash_transform_data.hpp:66-73``).
    """

    value_dist: str = "rademacher"

    # _apply_dense switches algorithm (one-hot matmul vs scatter) at
    # batch 16; plan bucketing must not pad a thin batch across it, or
    # the planned result would take a different (non-bit-identical)
    # code path than the eager apply of the same block.
    batch_size_gates = (16,)

    def __init__(self, n: int, s: int, context: SketchContext, nnz: int = 1):
        if nnz < 1:
            raise ValueError(f"hash sketch needs nnz >= 1, got {nnz}")
        self.nnz = int(nnz)
        super().__init__(n, s, context)
        self._seed = context.seed
        self._idx_base = context.reserve(self.nnz * n)
        self._val_base = context.reserve(self.nnz * n)

    # -- counter-derived hash arrays ---------------------------------------

    def _window(self, start, num, total):
        """(static_base_add, traced_offset, num) for a counter window.
        ``start`` may be a traced scalar (shard-dependent under
        ``shard_map``), in which case ``num`` is required — traced starts
        must stay below 2^32 (``raw_bits`` offset contract).  A
        ``(static_int, traced)`` pair splits a large window start exactly:
        the static part is folded into the 64-bit counter base, only the
        shard-local remainder is traced."""
        if isinstance(start, tuple):
            static, traced = start
            if num is None:
                raise ValueError("num is required when start is traced")
            return int(static), traced, num
        if isinstance(start, (int, np.integer)):
            return int(start), 0, (total - int(start) if num is None else num)
        if num is None:
            raise ValueError("num is required when start is traced")
        return 0, start, num

    def buckets(self, start=0, num: int | None = None):
        """bucket[i] for i in [start, start+num) of the flat (nnz·N)
        layout — shard-local computable, traced ``start`` supported."""
        static, offset, num = self._window(start, num, total=self.nnz * self.n)
        return sample(
            "uniform_int",
            self._seed,
            self._idx_base + static,
            num,
            dtype=jnp.int32,
            offset=offset,
            low=0,
            high=self.s - 1,
        )

    def values(self, dtype=jnp.float32, start=0, num: int | None = None):
        """Signed values, same flat layout and traced-``start`` support as
        :meth:`buckets`."""
        static, offset, num = self._window(start, num, total=self.nnz * self.n)
        return sample(
            self.value_dist,
            self._seed,
            self._val_base + static,
            num,
            dtype=dtype,
            offset=offset,
        )

    # -- apply --------------------------------------------------------------

    def apply(
        self,
        A,
        dim: Dimension | str = Dimension.COLUMNWISE,
        *,
        dense_output: bool = False,
    ):
        """Apply the sketch.  For BCOO inputs, ``dense_output=True``
        accumulates straight into a dense result (≙ the reference's
        mixed sparse→dense apply, ``hash_transform_Mixed.hpp``) with a
        sort-free per-hash ``segment_sum`` — measured 1.2–1.6× the
        relabel+``sum_duplicates`` BCOO build at 1e7–1e8 nnz on v5e, and
        it never materializes the nnz·H relabeled triplets (whose lexsort
        OOMed SJLT nnz=4 at 1e8 input nonzeros).  Dense inputs ignore the
        flag (their output is already dense)."""
        dim = Dimension.of(dim)
        if not isinstance(A, jsparse.BCOO):
            A = jnp.asarray(A)
        if A.ndim == 1:
            # Vectors are columns columnwise / rows rowwise (as in Gemv);
            # handled here once so dense and BCOO behave identically.
            A2 = A[:, None] if dim is Dimension.COLUMNWISE else A[None, :]
            out = self.apply(A2, dim, dense_output=dense_output)
            if isinstance(out, jsparse.BCOO):
                out = out.todense()
            return out[:, 0] if dim is Dimension.COLUMNWISE else out[0, :]
        if isinstance(A, jsparse.BCOO):
            if dense_output:
                return self._apply_sparse_dense_out(A, dim)
            return self._apply_sparse(A, dim)
        return self._apply_dense(A, dim)

    def _apply_slice_columnwise(self, A_block, start: int):
        """Partial scatter-add over the hash windows of coordinates
        [start, start+k): each hash function's (bucket, value) slice is a
        counter window (flat index ``h·N + i``), so a streaming pass
        regenerates exactly the k-coordinate slice per block — never the
        full N-length hash arrays.  BCOO blocks take the same per-hash
        ``segment_sum`` keyed through their local row indices."""
        k = A_block.shape[0]
        sparse_in = isinstance(A_block, jsparse.BCOO)
        in_dtype = A_block.data.dtype if sparse_in else A_block.dtype
        dtype = in_dtype if jnp.issubdtype(in_dtype, jnp.floating) else jnp.float32
        out = jnp.zeros((self.s, A_block.shape[1]), dtype)
        if sparse_in:
            rows, cols = A_block.indices[:, 0], A_block.indices[:, 1]
            data = A_block.data.astype(dtype)
            m = A_block.shape[1]
            for h in range(self.nnz):
                b = self.buckets(h * self.n + start, k)
                v = self.values(dtype, h * self.n + start, k)
                key = b[rows] * jnp.int32(m) + cols
                out = out + _segment_sum(
                    data * v[rows], key, self.s * m
                ).astype(dtype).reshape(self.s, m)
            return out
        A_block = A_block.astype(dtype)
        mode = _window_mode(k, A_block.shape[1], self.s, dtype, self.nnz)
        if mode != "xla":
            # Stacked single launch: every hash window rides ONE kernel
            # call (the A tile streams through VMEM once for all nnz
            # hashes) — the jit slice path below builds the identical
            # stack, so planned≡eager holds for nnz>1 too.
            b = jnp.stack(
                [self.buckets(h * self.n + start, k) for h in range(self.nnz)]
            )
            v = jnp.stack(
                [
                    self.values(jnp.float32, h * self.n + start, k)
                    for h in range(self.nnz)
                ]
            )
            return _segment_sum_rows(A_block, b, v, self.s, mode).astype(dtype)
        for h in range(self.nnz):
            b = self.buckets(h * self.n + start, k)
            v = self.values(dtype, h * self.n + start, k)
            out = out + _segment_sum_rows(
                A_block, b, v, self.s, mode
            ).astype(dtype)
        return out

    supports_slice_kernel = True

    def _slice_kernel_impl(self, A_block, start, acc):
        """Shared body of :meth:`apply_slice_kernel` (``acc=None``) and
        :meth:`apply_slice_kernel_acc`: the per-hash windowed row
        scatter-add with TRACED ``start`` (the ``(static, traced)``
        window split keeps the 64-bit counter base exact) and values
        past the sketch domain zeroed — an out-of-domain counter stream
        can hold non-finite draws (WZT's 1/Exp), and inf·0 from a
        padded row would poison the sum.

        When an ``acc`` is given and the single-launch gate admits
        (f32 block and f32 accumulator, window kernel engaged — any
        nnz, since the stacked layout folds every hash into one
        launch), the accumulator add is folded into the kernel's emit —
        one launch per stream chunk, bitwise equal to the unfused
        ``acc + part`` composite (a single IEEE add of the same
        partial, so the plan layer's planned≡eager contract holds)."""
        k = A_block.shape[0]
        dtype = A_block.dtype
        if not jnp.issubdtype(dtype, jnp.floating):
            dtype = jnp.float32
        A_block = A_block.astype(dtype)
        m = A_block.shape[1]
        mode = _window_mode(k, m, self.s, dtype, self.nnz)
        valid = start + jnp.arange(k, dtype=jnp.int32) < self.n
        if mode != "xla":
            # Stacked single launch — same stack as the eager slice
            # path, so planned≡eager holds for every nnz.
            b = jnp.stack(
                [self.buckets((h * self.n, start), k) for h in range(self.nnz)]
            )
            v = jnp.stack(
                [
                    self.values(jnp.float32, (h * self.n, start), k)
                    for h in range(self.nnz)
                ]
            )
            v = jnp.where(valid[None, :], v, jnp.zeros((), jnp.float32))
            fuse = (
                acc is not None
                and dtype == jnp.float32
                and acc.dtype == jnp.float32
            )
            if fuse:
                return _segment_sum_rows(A_block, b, v, self.s, mode, acc=acc)
            out = _segment_sum_rows(A_block, b, v, self.s, mode).astype(dtype)
            if acc is not None:
                return acc + out.astype(acc.dtype)
            return out
        out = jnp.zeros((self.s, m), dtype)
        for h in range(self.nnz):
            b = self.buckets((h * self.n, start), k)
            v = self.values(dtype, (h * self.n, start), k)
            v = jnp.where(valid, v, jnp.zeros((), dtype))
            out = out + _segment_sum_rows(
                A_block, b, v, self.s, mode
            ).astype(dtype)
        if acc is not None:
            return acc + out.astype(acc.dtype)
        return out

    def apply_slice_kernel(self, A_block, start):
        """jit-safe COLUMNWISE partial with TRACED ``start`` — the same
        per-hash windowed scatter-add as ``_apply_slice_columnwise``,
        routed through the same :func:`_segment_sum_rows` dispatcher so
        the plans slice path and the eager path pick the same kernel
        (bitwise-identical by construction)."""
        return self._slice_kernel_impl(A_block, start, None)

    def apply_slice_kernel_acc(self, acc, A_block, start):
        """Fused streaming chunk step: ``acc + apply_slice_kernel``
        folded into a single kernel launch when the gate in
        :meth:`_slice_kernel_impl` admits; the base composite (same
        bits) otherwise."""
        return self._slice_kernel_impl(A_block, start, acc)

    # Above this many (S·N) entries the materialized one-hot hashing
    # matrix no longer pays for itself; fall back to scatter-add.
    _ONEHOT_LIMIT = 1 << 27

    def _hash_matrix(self, dtype):
        """Dense (N, S) hashing matrix M with M[i, b[h,i]] += v[h,i].

        TPU note: for dense inputs the sketch is then a plain MXU matmul
        — an order of magnitude faster than XLA's scatter-add lowering,
        at the cost of the same O(S·N) window memory a dense sketch uses.
        Built by broadcast-compare (vectorized one-hot on the VPU) rather
        than scatter, which on TPU costs more than the matmul itself.
        BCOO inputs keep the scatter path (input-sparsity time).
        """
        b = self.buckets().reshape(self.nnz, self.n)
        v = self.values(dtype).reshape(self.nnz, self.n)
        iota = jnp.arange(self.s, dtype=b.dtype)
        M = jnp.zeros((self.n, self.s), dtype)
        for h in range(self.nnz):
            M = M + jnp.where(
                b[h][:, None] == iota[None, :], v[h][:, None], jnp.zeros((), dtype)
            )
        return M

    def _sign_scale(self):
        """Scalar c such that the hash matrix is ``c · M_int`` with
        small-integer entries (collision counts with signs) — exact in
        bf16 — or None when the values aren't sign-structured.  Lets the
        one-hot matmul ride the bf16 MXU at full precision."""
        if self.value_dist != "rademacher":
            return None
        return 1.0

    def _apply_dense(self, A, dim: Dimension):
        dtype = A.dtype if jnp.issubdtype(A.dtype, jnp.floating) else jnp.float32
        if dim is Dimension.COLUMNWISE:
            if A.shape[0] != self.n:
                raise ValueError(
                    f"columnwise apply needs A with {self.n} rows, got {A.shape}"
                )
        elif A.shape[-1] != self.n:
            raise ValueError(
                f"rowwise apply needs A with {self.n} columns, got {A.shape}"
            )
        # One-hot matmul only pays when the O(N·S) matrix build amortizes
        # over enough batch vectors; thin inputs keep the O(N·nnz) scatter.
        batch = A.shape[1] if dim is Dimension.COLUMNWISE else A.shape[0]
        if self.n * self.s <= self._ONEHOT_LIMIT and batch >= 16:
            c = self._sign_scale()
            if dtype in (jnp.bfloat16, jnp.float32):
                if c is not None:
                    return self._apply_onehot_bf16(A, dim, dtype, c)
                # Non-sign values (MMT Cauchy, WZT reciprocal-exp): fold
                # the value array into A — one elementwise pass — so the
                # hash matrix is PURE 0/1 (exact in bf16) and the matmul
                # rides the same bf16 MXU machinery as CWT.
                return self._apply_onehot_scaled(A, dim, dtype)
            M = self._hash_matrix(dtype)
            if dim is Dimension.COLUMNWISE:
                return M.T @ A.astype(dtype)
            return A.astype(dtype) @ M
        b = self.buckets().reshape(self.nnz, self.n)
        if dim is Dimension.COLUMNWISE:
            # The scatter-add IS the windowed row scatter, so the full
            # dense apply rides the same dispatcher (and the same Pallas
            # kernel, when engaged) as the streaming slices; nnz>1
            # stacks every hash function into one launch.
            mode = _window_mode(self.n, A.shape[1], self.s, dtype, self.nnz)
            if mode != "xla":
                v = self.values(jnp.float32).reshape(self.nnz, self.n)
                return _segment_sum_rows(A, b, v, self.s, mode).astype(dtype)
            # SA[r, c] = Σ_{h,i: b[h,i]=r} v[h,i]·A[i, c] — one scatter-add.
            v = self.values(dtype).reshape(self.nnz, self.n)
            stacked = (v[:, :, None] * A[None, :, :]).reshape(-1, A.shape[1])
            return jax.ops.segment_sum(
                stacked, b.reshape(-1), num_segments=self.s
            )
        # ROWWISE: (A·S^T) = (S·A^T)^T — one transpose normalizes the
        # lane-axis scatter into the kernel's sublane-dynamic form, so
        # rowwise applies ride the same window kernel.
        mode = _window_mode(self.n, A.shape[0], self.s, dtype, self.nnz)
        if mode != "xla":
            v = self.values(jnp.float32).reshape(self.nnz, self.n)
            return _segment_sum_rows(
                A.astype(dtype).T, b, v, self.s, mode
            ).T.astype(dtype)
        v = self.values(dtype).reshape(self.nnz, self.n)
        stacked = (A[:, None, :] * v[None, :, :]).reshape(A.shape[0], -1)
        return jax.ops.segment_sum(
            stacked.T, b.reshape(-1), num_segments=self.s
        ).T

    def _bf16_onehot_contract(self, X, M, dim: Dimension, dtype):
        """Shared MXU scaffolding of the one-hot paths: contract X's
        n-axis with a bf16-EXACT (N, S) matrix M, f32 accumulation; f32
        X rides the 3-pass bit-mask split (astype round-trips get elided
        by XLA's excess-precision rules on TPU — core/precision.py; any
        integer input must be value-converted before the bitcast split).
        Returns f32, (S, batch) columnwise / (batch, S) rowwise."""
        contract = (
            (((0,), (0,)), ((), ()))
            if dim is Dimension.COLUMNWISE
            else (((1,), (0,)), ((), ()))
        )

        def mm(x):
            return jax.lax.dot_general(
                x, M, contract, preferred_element_type=jnp.float32
            )

        if dtype == jnp.bfloat16:
            out = mm(X.astype(jnp.bfloat16))
        else:
            hi, lo, lo2 = bf16_split3(X.astype(jnp.float32))
            out = mm(hi) + mm(lo) + mm(lo2)
        return out.T if dim is Dimension.COLUMNWISE else out

    def _sign_matrix_bf16(self, c):
        """The (N, S) integer sign matrix ·(1/c), built directly in bf16
        (entries are signed collision counts — exact): one bf16 pass
        instead of an f32 build + rescale + round + cast chain (halves
        the build's HBM traffic at CWT's 128K x 1024 bench shape)."""
        b = self.buckets().reshape(self.nnz, self.n)
        v = self.values(jnp.float32).reshape(self.nnz, self.n)
        iota = jnp.arange(self.s, dtype=b.dtype)
        Mi = jnp.zeros((self.n, self.s), jnp.bfloat16)
        for h in range(self.nnz):
            vi = jnp.round(v[h] * jnp.float32(1.0 / c)).astype(jnp.bfloat16)
            Mi = Mi + jnp.where(
                b[h][:, None] == iota[None, :],
                vi[:, None],
                jnp.zeros((), jnp.bfloat16),
            )
        return Mi

    def hoistable_operands(self, dtype):
        """The bf16-exact one-hot matrices (sign matrix for CWT/SJLT,
        per-hash (P01, v) pairs for MMT/WZT) — the O(N·S) build a
        streaming consumer should not repeat per panel visit.  Memoized
        per dtype (sketches are immutable); mid-trace calls skip the
        cache both ways — a cached concrete matrix returned into a trace
        would be baked into the caller's executable as a constant."""
        dt = jnp.dtype(dtype)
        if dt.type not in (jnp.bfloat16, jnp.float32):
            return None
        if self.n * self.s > self._ONEHOT_LIMIT:
            return None

        def build():
            c = self._sign_scale()
            if c is not None:
                return ("sign", c, self._sign_matrix_bf16(c))
            return ("scaled", self._scaled_pairs())

        if not jax.core.trace_state_clean():
            return build()
        cache = self.__dict__.setdefault("_hoist_cache", {})
        hit = cache.get(dt.name)
        if hit is None:
            hit = cache[dt.name] = build()
        return hit

    def _scaled_pairs(self):
        """Per-hash (0/1 bucket matrix in bf16, value row) pairs — the
        operands of the scaled-one-hot path (MMT/WZT)."""
        b = self.buckets().reshape(self.nnz, self.n)
        v = self.values(jnp.float32).reshape(self.nnz, self.n)
        iota = jnp.arange(self.s, dtype=b.dtype)
        return tuple(
            (
                jnp.where(
                    b[h][:, None] == iota[None, :],
                    jnp.ones((), jnp.bfloat16),
                    jnp.zeros((), jnp.bfloat16),
                ),
                v[h],
            )
            for h in range(self.nnz)
        )

    def _scaled_contract(self, pairs, A, dim: Dimension, dtype):
        """out = Σ_h contract(v_h ⊙ A, P01_h) — the one scaled-one-hot
        loop behind both the per-call path and the hoisted path."""
        A32 = A.astype(jnp.float32)
        out = None
        for P01, vh in pairs:
            scaled = A32 * (
                vh[:, None] if dim is Dimension.COLUMNWISE else vh[None, :]
            )
            part = self._bf16_onehot_contract(scaled, P01, dim, dtype)
            out = part if out is None else out + part
        return out.astype(dtype)

    def apply_with_operands(
        self, ops, A, dim: Dimension | str = Dimension.COLUMNWISE
    ):
        dim = Dimension.of(dim)
        if ops is None or isinstance(A, jsparse.BCOO):
            return self.apply(A, dim)
        A = jnp.asarray(A)
        if A.ndim != 2:
            return self.apply(A, dim)
        dtype = A.dtype if jnp.issubdtype(A.dtype, jnp.floating) else jnp.float32
        if dtype not in (jnp.bfloat16, jnp.float32):
            # f64/f16 take apply's full-precision matmul — the hoisted
            # bf16 operands would silently downgrade them.
            return self.apply(A, dim)
        axis = 0 if dim is Dimension.COLUMNWISE else 1
        if A.shape[axis] != self.n:
            raise ValueError(
                f"{dim.value} apply needs A with {self.n} on axis {axis}, "
                f"got {A.shape}"
            )
        if A.shape[1 - axis] < 16:
            # Thin batches take apply's scatter path — same gate, so the
            # bit-identical-to-apply contract holds everywhere.
            return self.apply(A, dim)
        if ops[0] == "sign":
            _, c, Mi = ops
            out = self._bf16_onehot_contract(A, Mi, dim, dtype)
            return (out * jnp.float32(c)).astype(dtype)
        _, pairs = ops
        return self._scaled_contract(pairs, A, dim, dtype)

    def _apply_onehot_bf16(self, A, dim: Dimension, dtype, c):
        """Sign-valued hash sketches on the bf16 MXU at full precision:
        the hash matrix is c·M_int with small-integer entries (exact in
        bf16); bf16 inputs take one matmul, f32 inputs the 3-pass split,
        ~3x the f32 matmul rate on v5e.  Same trick as FJLT's
        subsampled-Hadamard gemm (``fjlt.py``)."""
        out = self._bf16_onehot_contract(A, self._sign_matrix_bf16(c), dim, dtype)
        return (out * jnp.float32(c)).astype(dtype)

    def _apply_onehot_scaled(self, A, dim: Dimension, dtype):
        """General-valued hash sketches (MMT/WZT) on the bf16 MXU:
        ``SA = P01ᵀ·(v ⊙ A)`` columnwise (``(A ⊙ v)·P01`` rowwise) with
        P01 the 0/1 bucket matrix — exact in bf16 — and the value array
        folded into A by one elementwise pass.  f32 inputs split the
        scaled operand ``hi + lo + lo2`` (3 exact bf16 passes), which is
        *more* accurate than the old f32 matmul (whose MXU default
        silently truncated operands to bf16 mantissas) and ~3× faster.
        Replaces the round-2 ``_hash_matrix`` f32 path (VERDICT item 2).
        """
        return self._scaled_contract(self._scaled_pairs(), A, dim, dtype)

    # Dense outputs above this many elements would not fit comfortably
    # next to the input triplets on a 16 GB chip; callers beyond it keep
    # the BCOO path (or shard via parallel.collectives).
    _DENSE_OUT_LIMIT = 1 << 28

    def _apply_sparse_dense_out(self, A: jsparse.BCOO, dim: Dimension):
        """BCOO → dense: one flat ``segment_sum`` per hash function keyed
        by the hashed destination — no concat, no sort, O(S·batch)
        resident (the sharded P6 schedules in ``parallel/collectives.py``
        use the same kernel per shard)."""
        axis = 0 if dim is Dimension.COLUMNWISE else 1
        if A.shape[axis] != self.n:
            raise ValueError(
                f"{dim.value} apply needs A with {self.n} on axis {axis}, "
                f"got {A.shape}"
            )
        batch = A.shape[1 - axis]
        if self.s * batch > self._DENSE_OUT_LIMIT:
            raise ValueError(
                f"dense_output needs S*batch <= {self._DENSE_OUT_LIMIT} "
                f"elements, got {self.s}*{batch}; use the BCOO path or a "
                "sharded schedule (parallel.collectives)"
            )
        dtype = (
            A.data.dtype
            if jnp.issubdtype(A.data.dtype, jnp.floating)
            else jnp.float32
        )
        data = A.data.astype(dtype)
        rows, cols = A.indices[:, 0], A.indices[:, 1]
        hashed = rows if axis == 0 else cols
        b = self.buckets().reshape(self.nnz, self.n)
        v = self.values(dtype).reshape(self.nnz, self.n)
        out = jnp.zeros((self.s * batch,), dtype)
        for h in range(self.nnz):
            if dim is Dimension.COLUMNWISE:
                key = b[h][hashed] * jnp.int32(batch) + cols
            else:
                key = rows * jnp.int32(self.s) + b[h][hashed]
            out = out + _segment_sum(
                data * v[h][hashed], key, self.s * batch
            ).astype(dtype)
        shape = (self.s, batch) if axis == 0 else (batch, self.s)
        return out.reshape(shape)

    def _apply_sparse(self, A: jsparse.BCOO, dim: Dimension):
        """BCOO → BCOO: relabel hashed indices per hash function, scale
        data, sum duplicates (≙ the queue-then-finalize CSC build of
        hash_transform_local_sparse.hpp:88-152)."""
        dtype = A.data.dtype
        axis = 0 if dim is Dimension.COLUMNWISE else 1
        if A.shape[axis] != self.n:
            raise ValueError(
                f"{dim.value} apply needs A with {self.n} on axis {axis}, "
                f"got {A.shape}"
            )
        b = self.buckets().reshape(self.nnz, self.n)
        v = self.values(dtype).reshape(self.nnz, self.n)
        hashed = A.indices[:, axis]
        idx_parts, data_parts = [], []
        for h in range(self.nnz):
            idx_parts.append(A.indices.at[:, axis].set(b[h][hashed]))
            data_parts.append(A.data * v[h][hashed])
        new_idx = jnp.concatenate(idx_parts, axis=0)
        new_data = jnp.concatenate(data_parts, axis=0)
        shape = (
            (self.s, A.shape[1]) if axis == 0 else (A.shape[0], self.s)
        )
        out = jsparse.BCOO((new_data, new_idx), shape=shape)
        return out.sum_duplicates(nse=min(out.nse, shape[0] * shape[1]))


@register_sketch
class CWT(HashSketch):
    """Clarkson-Woodruff (CountSketch, OSNAP s=1): bucket + Rademacher sign —
    l2 embedding in input-sparsity time (≙ ``sketch/CWT_data.hpp:23-42``)."""

    sketch_type = "CWT"
    value_dist = "rademacher"


@register_sketch
class SJLT(HashSketch):
    """Sparse JLT / OSNAP with ``nnz`` nonzeros per column: coordinate i
    contributes ±1/√nnz at nnz hashed output slots.

    ≙ python-skylark's pure-Python SJLT (``python-skylark/skylark/
    sketch.py``, not in the C API); CWT is the nnz=1, unscaled special
    case of the same hash engine.
    """

    sketch_type = "SJLT"
    value_dist = "rademacher"

    def __init__(self, n: int, s: int, context: SketchContext, nnz: int = 4):
        super().__init__(n, s, context, nnz=nnz)

    def values(self, dtype=jnp.float32, start: int = 0, num: int | None = None):
        v = super().values(dtype, start, num)
        return v / jnp.sqrt(jnp.asarray(float(self.nnz), dtype))

    def _sign_scale(self):
        return 1.0 / float(np.sqrt(self.nnz))

    def _param_dict(self):
        return {"nnz": self.nnz}

    @classmethod
    def _from_param_dict(cls, d, context):
        return cls(d["N"], d["S"], context, nnz=d.get("nnz", 4))


@register_sketch
class MMT(HashSketch):
    """Meng-Mahoney: bucket + Cauchy values — l1 embedding
    (≙ ``sketch/MMT_data.hpp:21-44``)."""

    sketch_type = "MMT"
    value_dist = "cauchy"


@register_sketch
class WZT(HashSketch):
    """Woodruff-Zhang: bucket + signed reciprocal-exponential values — lp
    embedding, 1 <= p <= 2 (≙ ``sketch/WZT_data.hpp:45-127``: value =
    ±(1/Exp)^(1/p), an extra Rademacher block of N reserved after the base
    two)."""

    sketch_type = "WZT"
    value_dist = "exponential"

    def __init__(self, n: int, s: int, context: SketchContext, p: float = 2.0):
        if not 1.0 <= p <= 2.0:
            raise ValueError(f"WZT parameter p must be in [1, 2], got {p}")
        self.p = float(p)
        super().__init__(n, s, context)
        self._pm_base = context.reserve(n)

    def values(self, dtype=jnp.float32, start=0, num: int | None = None):
        static, offset, num = self._window(start, num, total=self.n)
        e = sample(
            "exponential", self._seed, self._val_base + static, num,
            dtype=dtype, offset=offset,
        )
        pm = sample(
            "rademacher", self._seed, self._pm_base + static, num,
            dtype=dtype, offset=offset,
        )
        return pm * (1.0 / e) ** jnp.asarray(1.0 / self.p, dtype)

    def _param_dict(self):
        return {"P": self.p}

    @classmethod
    def _from_param_dict(cls, d, context):
        return cls(d["N"], d["S"], context, p=d.get("P", 2.0))
