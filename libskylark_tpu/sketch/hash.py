"""Hash (CountSketch-family) sketches: CWT, MMT, WZT.

Re-design of the reference's hash_transform engine
(``sketch/hash_transform_data.hpp:21-104`` + the Elemental / local-sparse /
CombBLAS apply specializations, ``sketch/hash_transform_Elemental.hpp``,
``hash_transform_local_sparse.hpp``, ``hash_transform_CombBLAS.hpp``):
each input coordinate i in [0, N) is hashed to one output slot
``bucket[i] ~ U{0..S-1}`` with a random scaling ``value[i]`` (±1 for CWT,
Cauchy for MMT, signed reciprocal-exponential for WZT).  Columnwise,

    SA[r, :] = sum_{i : bucket[i] == r} value[i] * A[i, :]

Both arrays are counter-derived (two reserved blocks of N), so any shard can
compute its own slice of (bucket, value) without communication — the same
"hash arrays precomputed from the context" design as the reference, minus
the materialized std::vectors.

TPU mapping: the scatter-add becomes ``jax.ops.segment_sum`` (XLA scatter,
which GSPMD handles sharded); for BCOO sparse inputs the hash relabels
row/col indices directly and defers duplicate summation — exactly the
queue-then-finalize CSC build of ``hash_transform_local_sparse.hpp:88-152``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..core.context import SketchContext
from ..core.random import sample
from .base import Dimension, SketchTransform, register_sketch

__all__ = ["HashSketch", "CWT", "MMT", "WZT"]


class HashSketch(SketchTransform):
    """Base engine: bucket ~ uniform_int(0, S-1), value ~ ``value_dist``."""

    value_dist: str = "rademacher"

    def __init__(self, n: int, s: int, context: SketchContext):
        super().__init__(n, s, context)
        self._seed = context.seed
        # ≙ hash_transform_data_t::build: two generate_random_samples_array(N)
        # calls (idx then value), hash_transform_data.hpp:66-73.
        self._idx_base = context.reserve(n)
        self._val_base = context.reserve(n)

    # -- counter-derived hash arrays ---------------------------------------

    def buckets(self, start: int = 0, num: int | None = None):
        """bucket[i] for i in [start, start+num) — shard-local computable."""
        num = self.n - start if num is None else num
        return sample(
            "uniform_int",
            self._seed,
            self._idx_base + start,
            num,
            dtype=jnp.int32,
            low=0,
            high=self.s - 1,
        )

    def values(self, dtype=jnp.float32, start: int = 0, num: int | None = None):
        num = self.n - start if num is None else num
        return sample(self.value_dist, self._seed, self._val_base + start, num, dtype=dtype)

    # -- apply --------------------------------------------------------------

    def apply(self, A, dim: Dimension | str = Dimension.COLUMNWISE):
        dim = Dimension.of(dim)
        if not isinstance(A, jsparse.BCOO):
            A = jnp.asarray(A)
        if A.ndim == 1:
            # Vectors are columns columnwise / rows rowwise (as in Gemv);
            # handled here once so dense and BCOO behave identically.
            A2 = A[:, None] if dim is Dimension.COLUMNWISE else A[None, :]
            out = self.apply(A2, dim)
            if isinstance(out, jsparse.BCOO):
                out = out.todense()
            return out[:, 0] if dim is Dimension.COLUMNWISE else out[0, :]
        if isinstance(A, jsparse.BCOO):
            return self._apply_sparse(A, dim)
        return self._apply_dense(A, dim)

    def _apply_dense(self, A, dim: Dimension):
        dtype = A.dtype if jnp.issubdtype(A.dtype, jnp.floating) else jnp.float32
        buckets = self.buckets()
        values = self.values(dtype)
        if dim is Dimension.COLUMNWISE:
            if A.shape[0] != self.n:
                raise ValueError(
                    f"columnwise apply needs A with {self.n} rows, got {A.shape}"
                )
            # SA[r, c] = sum_{i: b[i]=r} v[i] A[i, c]  — one XLA scatter-add.
            return jax.ops.segment_sum(
                values[:, None] * A, buckets, num_segments=self.s
            )
        if A.shape[-1] != self.n:
            raise ValueError(
                f"rowwise apply needs A with {self.n} columns, got {A.shape}"
            )
        # AS[r, c] = sum_{j: b[j]=c} v[j] A[r, j]: segment over columns.
        return jax.ops.segment_sum(
            (A * values[None, :]).T, buckets, num_segments=self.s
        ).T

    def _apply_sparse(self, A: jsparse.BCOO, dim: Dimension):
        """BCOO → BCOO: relabel hashed indices, scale data, sum duplicates
        (≙ the local CSC build of hash_transform_local_sparse.hpp:88-152)."""
        dtype = A.data.dtype
        axis = 0 if dim is Dimension.COLUMNWISE else 1
        if A.shape[axis] != self.n:
            raise ValueError(
                f"{dim.value} apply needs A with {self.n} on axis {axis}, "
                f"got {A.shape}"
            )
        buckets = self.buckets()
        values = self.values(dtype)
        hashed = A.indices[:, axis]
        new_idx = A.indices.at[:, axis].set(buckets[hashed])
        new_data = A.data * values[hashed]
        shape = (
            (self.s, A.shape[1]) if axis == 0 else (A.shape[0], self.s)
        )
        out = jsparse.BCOO((new_data, new_idx), shape=shape)
        return out.sum_duplicates(nse=min(out.nse, shape[0] * shape[1]))


@register_sketch
class CWT(HashSketch):
    """Clarkson-Woodruff (CountSketch, OSNAP s=1): bucket + Rademacher sign —
    l2 embedding in input-sparsity time (≙ ``sketch/CWT_data.hpp:23-42``)."""

    sketch_type = "CWT"
    value_dist = "rademacher"


@register_sketch
class MMT(HashSketch):
    """Meng-Mahoney: bucket + Cauchy values — l1 embedding
    (≙ ``sketch/MMT_data.hpp:21-44``)."""

    sketch_type = "MMT"
    value_dist = "cauchy"


@register_sketch
class WZT(HashSketch):
    """Woodruff-Zhang: bucket + signed reciprocal-exponential values — lp
    embedding, 1 <= p <= 2 (≙ ``sketch/WZT_data.hpp:45-127``: value =
    ±(1/Exp)^(1/p), an extra Rademacher block of N reserved after the base
    two)."""

    sketch_type = "WZT"
    value_dist = "exponential"

    def __init__(self, n: int, s: int, context: SketchContext, p: float = 2.0):
        if not 1.0 <= p <= 2.0:
            raise ValueError(f"WZT parameter p must be in [1, 2], got {p}")
        self.p = float(p)
        super().__init__(n, s, context)
        self._pm_base = context.reserve(n)

    def values(self, dtype=jnp.float32, start: int = 0, num: int | None = None):
        num = self.n - start if num is None else num
        e = sample("exponential", self._seed, self._val_base + start, num, dtype=dtype)
        pm = sample("rademacher", self._seed, self._pm_base + start, num, dtype=dtype)
        return pm * (1.0 / e) ** jnp.asarray(1.0 / self.p, dtype)

    def _param_dict(self):
        return {"P": self.p}

    @classmethod
    def _from_param_dict(cls, d, context):
        return cls(d["N"], d["S"], context, p=d.get("P", 2.0))
