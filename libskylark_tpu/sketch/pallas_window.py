"""Pallas TPU windowed scatter-accumulate for the hash-sketch hot loop.

The streaming COLUMNWISE apply of the hash sketches (CWT/MMT/WZT —
``hash.py::_apply_slice_columnwise`` / ``apply_slice_kernel``) is a ROW
scatter-add per hash window:

    out[b[i], :] += v[i] * A[i, :]        i in [0, k)

XLA lowers this (via ``jax.ops.segment_sum``) to a TPU scatter — the
measured laggard of the bench suite (CWT 0.90x / MMT 0.84x vs baseline,
BENCH_r03) — and the flat two-pass kernel in ``pallas_scatter`` cannot
serve it: flattening a (k, m) block into k·m entries re-pays the
partition sort per column.  TPU has no vector scatter, but the row form
needs none: one scalar-indexed VECTOR accumulate per entry —
``scratch[b[i], :] += v[i] * a_row`` — touches all m lanes at once, so
the scalar-loop cost amortizes over the row width instead of per
element.

Layout: grid ``(Tm, Kc)`` with the entry-chunk axis Kc fastest.  Each
grid step owns a (ck, TM) tile of A and the (1, ck) bucket/value rows
for that chunk; a persistent f32 VMEM scratch of shape (S_pad, TM) is
the accumulator for the current lane tile, zeroed at the first chunk and
emitted at the last.  The optional ``acc`` operand is folded into the
emit (``out = acc + scratch``) — a single IEEE f32 add of the same
partial the unfused composite would produce, so fusing the streaming
accumulator add changes no bits (the plan layer's planned≡eager
contract rides on exactly this).

Padding is value-preserving by construction: padded entries carry
``v = 0`` and zero A rows, so each contributes an exact ``+0.0``.
Out-of-domain counter draws (WZT's 1/Exp can be inf) must be zeroed by
the CALLER in ``v`` before the call — inf·0 would otherwise poison the
row — which the hash dispatcher already does for traced windows.

Stacked hashes: ``b``/``v`` may be (nnz, k) — the OSNAP/SJLT layout —
in which case every hash function's entries accumulate into the SAME
persistent scratch in one launch (the A tile streams through VMEM once
for all nnz hashes instead of once per hash).  The 1-D form is exactly
the nnz=1 special case of the stacked kernel, so the generated op
sequence for nnz=1 is unchanged.

The module also carries the FJLT sampled-transform epilogue
(:func:`gather_scaled_rows`): ``out[j, :] = scale · T[idx[j], :]`` — a
scalar-indexed vector COPY instead of an RMW, same sublane-dynamic
addressing, bitwise equal to the XLA ``scale * T[idx, :]`` gather (pure
selection + the same elementwise multiply in the same dtype).

Fallback: anything unsupported (gate below) keeps the XLA path;
``SKYLARK_NO_PALLAS=1`` forces it.  ``hash._window_compiles`` runs
:func:`self_check` once per process before the TPU-default route
engages (the ``_kernel_compiles`` probe pattern);
``fjlt._gather_compiles`` does the same with :func:`self_check_gather`.
"""

from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp

__all__ = [
    "scatter_rows",
    "supported",
    "worthwhile",
    "self_check",
    "gather_scaled_rows",
    "supported_gather",
    "worthwhile_gather",
    "self_check_gather",
]

# Entries per grid step along the chunk axis.  Larger chunks cut
# grid-step overhead at the cost of the (ck, TM) A-tile VMEM; the
# effective chunk shrinks to the (128-aligned) entry count for small
# windows so tests and thin streams don't pay 8x padding.
_CK = int(os.environ.get("SKYLARK_WINDOW_CHUNK", "1024"))
# Lane-tile width of the accumulator (and of each A tile).
_TM = 512
# Scratch accumulator budget: S_pad * TM f32 elements (4 MB at 1<<20 —
# out + acc blocks ride alongside it, keeping total VMEM well under the
# ~16 MB arena).
_VMEM_ELEMS = 1 << 20
# Entry count past which HBM staging of the padded copies stops paying.
_MAX_K = 150_000_000
# Default-on threshold: below this many entries the launch overhead of
# the scalar-loop kernel is not worth it over XLA's scatter.
_MIN_K = int(os.environ.get("SKYLARK_WINDOW_MIN_K", "4096"))
# Default-on threshold for the sampled-epilogue gather: below this many
# sampled rows XLA's gather is already launch-bound cheap.
_MIN_GATHER = int(os.environ.get("SKYLARK_WINDOW_MIN_GATHER", "512"))


def _ceil_to(x: int, q: int) -> int:
    return -(-x // q) * q


def _tiles(k: int, num_segments: int, m: int):
    """(ck, Kc, TM, Tm, S_pad) for a (k, m) block into num_segments rows."""
    ck = min(_ceil_to(_CK, 128), _ceil_to(k, 128))
    Kc = -(-k // ck)
    TM = min(_TM, _ceil_to(m, 128))
    Tm = -(-m // TM)
    S_pad = _ceil_to(num_segments, 8)
    return ck, Kc, TM, Tm, S_pad


def supported(k: int, num_segments: int, m: int, nnz: int = 1) -> bool:
    """Hard feasibility of the window kernel for a (k, m) block with
    ``nnz`` stacked hash functions — shape and VMEM only.  Forced modes
    (``SKYLARK_PALLAS_WINDOW=1|interpret``) honor this gate but not
    :func:`worthwhile`."""
    if os.environ.get("SKYLARK_NO_PALLAS", "0") == "1":
        return False
    if k < 1 or num_segments < 1 or m < 1 or nnz < 1:
        return False
    if nnz * k > _MAX_K:
        return False
    _, _, TM, _, S_pad = _tiles(k, num_segments, m)
    return S_pad * TM <= _VMEM_ELEMS


def worthwhile(k: int, num_segments: int, m: int, nnz: int = 1) -> bool:
    """Amortization gate for the TPU-DEFAULT route (forced modes skip
    it): enough entries to pay the launch + scalar-loop setup."""
    return nnz * k >= _MIN_K


def _window_kernel(with_acc: bool, *refs):
    from jax.experimental import pallas as pl

    if with_acc:
        b_ref, v_ref, a_ref, acc_ref, out_ref, sc_ref = refs
    else:
        b_ref, v_ref, a_ref, out_ref, sc_ref = refs
        acc_ref = None
    kc = pl.program_id(1)

    @pl.when(kc == 0)
    def _zero():
        sc_ref[:, :] = jnp.zeros_like(sc_ref)

    nnz, ck = b_ref.shape

    def entry(i, c):
        # One scalar-indexed VECTOR accumulate per (hash, entry): dynamic
        # sublane addressing only (pl.ds on the second-minor axis —
        # the same RMW shape Mosaic lowers in pallas_scatter's
        # lane-masked mode); the full TM-lane row rides the VPU.  The
        # hash axis is a STATIC unroll — the A row loads once per entry
        # and feeds all nnz accumulates.
        row = a_ref[pl.ds(i, 1), :].astype(jnp.float32)
        for h in range(nnz):
            r = b_ref[h, i]
            sc_ref[pl.ds(r, 1), :] = (
                sc_ref[pl.ds(r, 1), :] + v_ref[h, i] * row
            )
        return c

    jax.lax.fori_loop(0, ck, entry, 0)

    @pl.when(kc == pl.num_programs(1) - 1)
    def _emit():
        if acc_ref is not None:
            out_ref[:, :] = acc_ref[:, :] + sc_ref[:, :]
        else:
            out_ref[:, :] = sc_ref[:, :]


@partial(jax.jit, static_argnames=("num_segments", "interpret", "with_acc"))
def _scatter_rows_impl(A, b, v, acc, num_segments, interpret, with_acc):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    k, m = A.shape
    nnz = b.shape[0]
    ck, Kc, TM, Tm, S_pad = _tiles(k, num_segments, m)
    if A.dtype not in (jnp.float32, jnp.bfloat16):
        # f32-accumulate boundary cast (f64 arrives only via callers
        # that accepted the demotion — core.precision.f32_accumulable).
        A = A.astype(jnp.float32)
    kp, mp = Kc * ck - k, Tm * TM - m
    A_p = jnp.pad(A, ((0, kp), (0, mp)))
    # Stacked-hash layout: chunk-major rows, (nnz, ck) per chunk, so one
    # (nnz, ck) block per grid step lands contiguously at block index kc.
    b_p = (
        jnp.pad(b.astype(jnp.int32), ((0, 0), (0, kp)))
        .reshape(nnz, Kc, ck).transpose(1, 0, 2).reshape(Kc * nnz, ck)
    )
    v_p = (
        jnp.pad(v.astype(jnp.float32), ((0, 0), (0, kp)))
        .reshape(nnz, Kc, ck).transpose(1, 0, 2).reshape(Kc * nnz, ck)
    )

    in_specs = [
        pl.BlockSpec((nnz, ck), lambda tm, kc: (kc, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((nnz, ck), lambda tm, kc: (kc, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((ck, TM), lambda tm, kc: (kc, tm),
                     memory_space=pltpu.VMEM),
    ]
    operands = [b_p, v_p, A_p]
    if with_acc:
        acc_p = jnp.pad(acc, ((0, S_pad - num_segments), (0, mp)))
        in_specs.append(
            pl.BlockSpec((S_pad, TM), lambda tm, kc: (0, tm),
                         memory_space=pltpu.VMEM)
        )
        operands.append(acc_p)

    out = pl.pallas_call(
        partial(_window_kernel, with_acc),
        grid=(Tm, Kc),  # Kc fastest: scratch persists across chunks
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (S_pad, TM), lambda tm, kc: (0, tm), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((S_pad, Tm * TM), jnp.float32),
        scratch_shapes=[pltpu.VMEM((S_pad, TM), jnp.float32)],
        interpret=interpret,
    )(*operands)

    return out[:num_segments, :m]


def scatter_rows(A, b, v, num_segments: int, *, acc=None, interpret=False):
    """``out[t, :] = sum_{i: b[i]==t} v[i] * A[i, :]`` (f32), optionally
    ``+ acc`` folded into the kernel's emit.  ``A`` is (k, m) f32/bf16
    (other floats boundary-cast to f32), ``b`` int32 in
    [0, num_segments), ``v`` f32 with any out-of-domain entries already
    zeroed by the caller.  ``acc``, when given, must be (num_segments,
    m) f32 — the fused result is bitwise equal to ``acc + scatter_rows(
    ...)`` (one IEEE add of the same partial).  ``b``/``v`` may also be
    stacked (nnz, k) — every hash row scatters into the same output in
    one launch.  Caller gates with :func:`supported`."""
    if acc is not None and acc.dtype != jnp.float32:
        raise TypeError(
            f"fused acc must be float32, got {acc.dtype}; the unfused "
            "composite handles other accumulator dtypes"
        )
    if b.ndim == 1:
        b, v = b[None, :], v[None, :]
    if b.shape != v.shape:
        raise ValueError(f"b/v shape mismatch: {b.shape} vs {v.shape}")
    return _scatter_rows_impl(
        A, b, v, acc if acc is not None else jnp.zeros((), jnp.float32),
        num_segments, interpret, acc is not None,
    )


def self_check(
    k: int = 16384, num_segments: int = 1000, m: int = 320,
    interpret: bool = False, nnz: int = 1,
) -> float:
    """Max *relative* error of the window kernel vs the XLA reference on
    random buckets/values — the ONE validator shared by the TPU-default
    probe (``hash._window_compiles``) and the hardware guard
    (``tests/_hw_guards.py``), so the two cannot drift.  The off-tile
    shape (S=1000, m=320) exercises every padding seam.  ``nnz > 1``
    validates the stacked-hash layout.  Raises on lowering failure;
    callers decide the tolerance (1e-5 is the established hardware
    bar)."""
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(7), 3)
    shape = (k,) if nnz == 1 else (nnz, k)
    b = jax.random.randint(k1, shape, 0, num_segments, dtype=jnp.int32)
    v = jax.random.normal(k2, shape, jnp.float32)
    A = jax.random.normal(k3, (k, m), jnp.float32)
    out = scatter_rows(A, b, v, num_segments, interpret=interpret)
    ref = jax.ops.segment_sum(
        (v.reshape(nnz, k)[:, :, None] * A[None, :, :]).reshape(-1, m),
        b.reshape(-1), num_segments=num_segments,
    )
    jax.block_until_ready((out, ref))
    scale = jnp.maximum(jnp.max(jnp.abs(ref)), 1e-30)
    return float(jnp.max(jnp.abs(out - ref)) / scale)


# ---------------------------------------------------------------------------
# FJLT sampled-transform epilogue: scaled row gather.
# ---------------------------------------------------------------------------


def _gather_tiles(nrows: int, s: int, m: int):
    """(cs, Sc, TM, Tm, R_pad) for sampling s rows of a (nrows, m) T."""
    cs = min(_ceil_to(1024, 128), _ceil_to(s, 128))
    Sc = -(-s // cs)
    TM = min(_TM, _ceil_to(m, 128))
    Tm = -(-m // TM)
    R_pad = _ceil_to(nrows, 8)
    return cs, Sc, TM, Tm, R_pad


def supported_gather(nrows: int, s: int, m: int) -> bool:
    """Hard feasibility of the gather kernel: the full (R_pad, TM)
    source tile must fit the VMEM budget alongside the (cs, TM) out."""
    if os.environ.get("SKYLARK_NO_PALLAS", "0") == "1":
        return False
    if nrows < 1 or s < 1 or m < 1 or s > _MAX_K:
        return False
    _, _, TM, _, R_pad = _gather_tiles(nrows, s, m)
    return R_pad * TM <= _VMEM_ELEMS


def worthwhile_gather(nrows: int, s: int, m: int) -> bool:
    """Amortization gate for the TPU-DEFAULT route: enough sampled rows
    to beat XLA's already-cheap gather."""
    return s >= _MIN_GATHER


def _gather_kernel(idx_ref, t_ref, scale_ref, out_ref):
    from jax.experimental import pallas as pl

    _, cs = idx_ref.shape
    scale = scale_ref[0, 0]

    def entry(i, c):
        # Scalar-indexed vector COPY: pure selection plus the same
        # elementwise multiply XLA's ``scale * T[idx, :]`` performs, in
        # the same dtype — bitwise equal to the gather composite.
        r = idx_ref[0, i]
        out_ref[pl.ds(i, 1), :] = t_ref[pl.ds(r, 1), :] * scale
        return c

    jax.lax.fori_loop(0, cs, entry, 0)


@partial(jax.jit, static_argnames=("interpret",))
def _gather_rows_impl(T, idx, scale, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nrows, m = T.shape
    (s,) = idx.shape
    cs, Sc, TM, Tm, R_pad = _gather_tiles(nrows, s, m)
    sp, mp = Sc * cs - s, Tm * TM - m
    T_p = jnp.pad(T, ((0, R_pad - nrows), (0, mp)))
    # Padded indices select row 0 of T; those rows are cropped below.
    idx_p = jnp.pad(idx.astype(jnp.int32), (0, sp)).reshape(Sc, cs)
    scale_arr = jnp.asarray(scale, T.dtype).reshape(1, 1)

    out = pl.pallas_call(
        _gather_kernel,
        grid=(Tm, Sc),
        in_specs=[
            pl.BlockSpec((1, cs), lambda tm, sc: (sc, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((R_pad, TM), lambda tm, sc: (0, tm),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda tm, sc: (0, 0),
                         memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec(
            (cs, TM), lambda tm, sc: (sc, tm), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((Sc * cs, Tm * TM), T.dtype),
        interpret=interpret,
    )(idx_p, T_p, scale_arr)

    return out[:s, :m]


def gather_scaled_rows(T, idx, scale, *, interpret=False):
    """``out[j, :] = scale * T[idx[j], :]`` — the FJLT sampled-transform
    epilogue as one scalar-indexed vector-copy kernel.  ``T`` is
    (nrows, m) float, ``idx`` int in [0, nrows), ``scale`` a python
    float / 0-d array cast to ``T.dtype``.  Bitwise equal to the XLA
    composite ``scale * T[idx, :]`` (selection plus the identical
    elementwise multiply).  Caller gates with
    :func:`supported_gather`."""
    return _gather_rows_impl(T, idx, scale, interpret)


def self_check_gather(
    nrows: int = 3000, s: int = 4096, m: int = 320,
    interpret: bool = False,
) -> float:
    """Max relative error of the gather kernel vs ``scale * T[idx, :]``
    on a padding-seam shape.  Expected 0.0 exactly (pure selection +
    identical multiply); raises on lowering failure."""
    k1, k2 = jax.random.split(jax.random.PRNGKey(11))
    T = jax.random.normal(k1, (nrows, m), jnp.float32)
    idx = jax.random.randint(k2, (s,), 0, nrows, dtype=jnp.int32)
    scale = 0.3125
    out = gather_scaled_rows(T, idx, scale, interpret=interpret)
    ref = jnp.asarray(scale, T.dtype) * T[idx, :]
    jax.block_until_ready((out, ref))
    scale_r = jnp.maximum(jnp.max(jnp.abs(ref)), 1e-30)
    return float(jnp.max(jnp.abs(out - ref)) / scale_r)
