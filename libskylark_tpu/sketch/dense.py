"""Dense counter-based sketches: JLT, CT, and the lazy dense-transform engine.

Re-design of the reference's dense_transform machinery
(``sketch/dense_transform_data.hpp:22-152`` + the ~13
``dense_transform_Elemental_*.hpp`` apply specializations): the sketch
matrix ``Omega`` (shape (S, N)) is *never stored and never communicated* —
any window of it is a pure function of ``(seed, base_counter, i, j)``
(reference invariant P5, ``base/randgen.hpp:98-115``).  Here that is
``core.random.sample_window``; entry (i, j) uses counter
``base + i*N + j`` (row-major over the logical (S, N) matrix).

Distribution-aware apply specializations collapse to a single einsum:
under ``jit``/GSPMD the window generation is elementwise over an iota, so
XLA shards Omega's generation to match whatever sharding the matmul wants,
and the communication schedule (reduce-scatter within mesh rows/cols ≙
``dense_transform_Elemental_mc_mr.hpp:179,302,599``; communication-free for
the replicated-axis case ≙ ``doc/sphinx/sketching.rst:104-118``) is chosen
by the compiler.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import sparse as jsparse

from ..core.context import SketchContext
from ..core.random import sample_window
from ..utils.exceptions import UnsupportedError
from .base import Dimension, SketchTransform, register_sketch

__all__ = ["DenseSketch", "JLT", "CT", "MAX_REALIZE_ELEMENTS"]

# Above this many Omega entries, apply() switches to panel-blocked
# accumulation so the realized window stays bounded (≙ the reference's
# panel-blocked GEMM with sketch_params block-size knobs,
# ``sketch/dense_transform_Elemental_mc_mr.hpp:87-120``): Omega is
# realized panel-by-panel along N and accumulated, never materialized
# whole.  128M entries ≈ 0.5 GB in f32.
MAX_REALIZE_ELEMENTS = 1 << 27


class DenseSketch(SketchTransform):
    """Sketch with iid entries ``scale * dist()`` — the dense engine.

    ``dist`` is a key of ``core.random.DISTRIBUTIONS``; ``scale`` is a
    deterministic scalar (e.g. 1/sqrt(S) for JLT).
    """

    dist: str = "normal"

    def __init__(
        self,
        n: int,
        s: int,
        context: SketchContext,
        scale: float = 1.0,
        dist_params: dict[str, Any] | None = None,
    ):
        super().__init__(n, s, context)
        self.scale = float(scale)
        self._dist_params = dict(dist_params or {})
        self._seed = context.seed
        # ≙ context.allocate_random_samples_array(N*S) (base/context.hpp:94-101)
        self._base = context.reserve(n * s)

    # -- lazy realization (≙ realize_matrix_view) ---------------------------

    def realize(
        self,
        dtype=jnp.float32,
        offset: tuple[int, int] = (0, 0),
        shape: tuple[int, int] | None = None,
    ):
        """Materialize a window of the logical (S, N) sketch matrix.

        Any window is bit-identical to the corresponding slice of the full
        matrix (shard-local realization, ``dense_transform_data.hpp:79-152``).
        """
        w = sample_window(
            self.dist,
            self._seed,
            self._base,
            (self.s, self.n),
            dtype=dtype,
            offset=offset,
            shape=shape,
            **self._dist_params,
        )
        return w * jnp.asarray(self.scale, dtype)

    # -- apply --------------------------------------------------------------

    def apply(self, A, dim: Dimension | str = Dimension.COLUMNWISE):
        return self._apply_impl(A, Dimension.of(dim), omega=None)

    def _apply_slice_columnwise(self, A_block, start: int):
        """Partial product of the Omega column window [start, start+k):
        realized directly from the counter stream (P5 — any window is
        bit-identical to the same slice of the full matrix), so streaming
        over row blocks never materializes more than one (S, k) window."""
        k = A_block.shape[0]
        dtype = A_block.dtype
        if not jnp.issubdtype(dtype, jnp.floating):
            dtype = jnp.float32
        w = self.realize(dtype, offset=(0, start), shape=(self.s, k))
        if hasattr(A_block, "todense"):
            return _matmul(w, A_block)
        return _matmul(w, A_block.astype(dtype))

    supports_slice_kernel = True

    def apply_slice_kernel(self, A_block, start):
        """jit-safe COLUMNWISE partial with TRACED ``start`` (the P5
        counter window addresses traced offsets exactly); columns past
        the sketch domain are zeroed so a bucket-padded block overruns
        N with contribution exactly 0 (the out-of-domain stream could
        hold non-finite draws — inf·0 would poison the sum)."""
        k = A_block.shape[0]
        dtype = A_block.dtype
        if not jnp.issubdtype(dtype, jnp.floating):
            dtype = jnp.float32
        w = self.realize(dtype, offset=(0, start), shape=(self.s, k))
        valid = start + jnp.arange(k, dtype=jnp.int32) < self.n
        w = jnp.where(valid[None, :], w, jnp.zeros((), dtype))
        return _matmul(w, A_block.astype(dtype))

    def hoistable_operands(self, dtype):
        """The realized (S, N) Omega, for streaming consumers to hoist
        out of panel loops (see SketchTransform.hoistable_operands);
        None on the panel-blocked path (no single realized Omega).
        Memoized per dtype — sketches are immutable, so the realization
        never invalidates.  Mid-trace calls (the streaming-KRR chunk
        programs realize W inside their own jit) skip the cache both
        ways: a cached concrete Omega returned into a trace would be
        baked into the caller's executable as a constant."""
        if self.n * self.s > MAX_REALIZE_ELEMENTS:
            return None
        dtype = jnp.dtype(dtype)
        if not jnp.issubdtype(dtype, jnp.floating):
            dtype = jnp.dtype(jnp.float32)
        if not jax.core.trace_state_clean():
            return self.realize(dtype)
        cache = self.__dict__.setdefault("_hoist_cache", {})
        hit = cache.get(dtype.name)
        if hit is None:
            hit = cache[dtype.name] = self.realize(dtype)
        return hit

    def apply_with_operands(
        self, ops, A, dim: Dimension | str = Dimension.COLUMNWISE
    ):
        return self._apply_impl(A, Dimension.of(dim), omega=ops)

    def _apply_impl(self, A, dim: Dimension, omega):
        """One implementation behind apply / apply_with_operands: same
        coercion, validation, and matmul dispatch, with ``omega``
        optionally pre-realized (bit-identical either way — realize is a
        pure function of the counter stream)."""
        A = jnp.asarray(A) if not hasattr(A, "todense") else A
        dtype = A.dtype
        if not jnp.issubdtype(dtype, jnp.floating):
            dtype = jnp.float32
        if dim is Dimension.COLUMNWISE:
            if A.shape[0] != self.n:
                raise ValueError(
                    f"columnwise apply needs A with {self.n} rows, "
                    f"got {A.shape}"
                )
        elif A.shape[-1] != self.n:
            raise ValueError(
                f"rowwise apply needs A with {self.n} columns, got {A.shape}"
            )
        if omega is None:
            if self.n * self.s > MAX_REALIZE_ELEMENTS:
                if hasattr(A, "todense"):
                    raise UnsupportedError(
                        f"dense sketch of a sparse input needs the full "
                        f"({self.s}, {self.n}) Omega materialized "
                        f"(> MAX_REALIZE_ELEMENTS); use an input-sparsity "
                        f"sketch (CWT/SJLT) at this scale"
                    )
                return self._apply_blocked(A, dim, dtype)
            omega = self.realize(dtype)
        elif omega.dtype != dtype:
            # Dtype-mismatched hoist: re-realize rather than astype — a
            # value-converted Omega (e.g. bf16-rounded then upcast) would
            # silently break the bit-identical-to-apply contract.
            omega = self.realize(dtype)
        if dim is Dimension.COLUMNWISE:
            return _matmul(omega, A)
        return _matmul(A, omega.T)

    def _apply_blocked(self, A, dim: Dimension, dtype):
        """Panel-blocked apply: realize Omega in column panels along N and
        accumulate — peak extra memory is one (S, panel) window.  Equal
        panels run in a ``fori_loop`` (one traced body regardless of
        panel count); a ragged remainder panel is handled outside."""
        panel = max(1, MAX_REALIZE_ELEMENTS // self.s)
        nfull = self.n // panel
        rem0 = nfull * panel
        cw = dim is Dimension.COLUMNWISE
        A = A.astype(dtype)
        out_shape = (
            (self.s,) + A.shape[1:] if cw else A.shape[:-1] + (self.s,)
        )
        acc = jnp.zeros(out_shape, dtype)

        def body(p, acc):
            p0 = p * panel
            w = self.realize(dtype, offset=(0, p0), shape=(self.s, panel))
            if cw:
                blk = lax.dynamic_slice_in_dim(A, p0, panel, axis=0)
                return acc + _matmul(w, blk)
            blk = lax.dynamic_slice_in_dim(A, p0, panel, axis=A.ndim - 1)
            return acc + _matmul(blk, w.T)

        if nfull:
            acc = lax.fori_loop(0, nfull, body, acc)
        if rem0 < self.n:
            pc = self.n - rem0
            w = self.realize(dtype, offset=(0, rem0), shape=(self.s, pc))
            if cw:
                acc = acc + _matmul(w, A[rem0:])
            else:
                acc = acc + _matmul(A[..., rem0:], w.T)
        return acc


def _matmul(x, y):
    """Dense@dense or mixed dense/BCOO matmul (≙ base::Gemm dispatch)."""
    if isinstance(x, jsparse.BCOO) or isinstance(y, jsparse.BCOO):
        return x @ y
    return jnp.matmul(x, y)


@register_sketch
class JLT(DenseSketch):
    """Johnson-Lindenstrauss: iid N(0, 1/S) dense sketch — l2 subspace
    embedding (≙ ``sketch/JLT_data.hpp:17-48``: normal entries, scale
    sqrt(1/S))."""

    sketch_type = "JLT"
    dist = "normal"

    def __init__(self, n: int, s: int, context: SketchContext):
        super().__init__(n, s, context, scale=(1.0 / s) ** 0.5)


@register_sketch
class CT(DenseSketch):
    """Cauchy transform: iid Cauchy entries scaled C/S — l1 embedding
    (Sohler-Woodruff; ≙ ``sketch/CT_data.hpp:20-47``: scale C/S)."""

    sketch_type = "CT"
    dist = "cauchy"

    def __init__(self, n: int, s: int, context: SketchContext, C: float = 1.0):
        self.C = float(C)
        super().__init__(n, s, context, scale=self.C / s)

    def _param_dict(self):
        return {"C": self.C}

    @classmethod
    def _from_param_dict(cls, d, context):
        return cls(d["N"], d["S"], context, C=d.get("C", 1.0))
