"""Sketch transform protocol, type registry, and JSON serialization.

TPU-native re-design of the reference's sketch layer scaffolding:

- ``SketchTransform`` ≙ ``sketch_transform_t<In, Out>``
  (``sketch/sketch_transform.hpp:16-48``), with the C++ tag dispatch
  (``columnwise_tag``/``rowwise_tag``) replaced by a ``Dimension`` enum and
  the per-(input-type × output-type) template specializations replaced by a
  single JAX implementation that works for any sharding under GSPMD.
- The JSON registry ≙ ``sketch/sketch_add.hpp:15-52`` — every concrete
  transform registers its ``sketch_type`` string so serialized sketches can
  be reconstructed by name (used by the C API / Python layer in the
  reference, and by model persistence here).
- Serialization keeps the reference's property-tree schema in spirit
  (``sketch/sketch_transform_data.hpp:64-71``): a sketch is reconstructible
  from ``(sketch_type, N, S, creation_context, params)`` — ~100 bytes of
  JSON — because all randomness is counter-derived.

Conventions (fixing the reference's math in array terms):

- A transform maps R^N -> R^S.  Its logical sketch matrix ``Omega`` has
  shape ``(S, N)``.
- ``apply(A, Dimension.COLUMNWISE)``: ``A`` is ``(N, m)``; result is
  ``Omega @ A`` with shape ``(S, m)`` — each *column* of A is sketched.
- ``apply(A, Dimension.ROWWISE)``: ``A`` is ``(m, N)``; result is
  ``A @ Omega.T`` with shape ``(m, S)`` — each *row* of A is sketched.

This matches ``sketch/transforms.hpp:12-18`` (S·A columnwise, A·Sᵀ rowwise).
"""

from __future__ import annotations

import abc
import enum
import json
from typing import Any, Callable, ClassVar

from ..core.context import SketchContext

__all__ = [
    "Dimension",
    "SketchTransform",
    "register_sketch",
    "sketch_registry",
    "create_sketch",
    "from_dict",
    "from_json",
    "SERIAL_VERSION",
]

# Version 2: the f32 uniform stream switched to hi-leading bits (see
# docs/counter_contract.md "Stream revisions") — version-1 artifacts whose
# f32-uniform-derived values matter (UST/NURST selections, RFT shifts,
# Fastfood permutations realized in f32) reproduce differently.
SERIAL_VERSION = 2


class Dimension(enum.Enum):
    """Which dimension of A is sketched (≙ columnwise_tag / rowwise_tag)."""

    COLUMNWISE = "columnwise"
    ROWWISE = "rowwise"

    @classmethod
    def of(cls, d: "Dimension | str") -> "Dimension":
        if isinstance(d, Dimension):
            return d
        return cls(str(d).lower())


COLUMNWISE = Dimension.COLUMNWISE
ROWWISE = Dimension.ROWWISE

_REGISTRY: dict[str, type["SketchTransform"]] = {}


def register_sketch(cls: type["SketchTransform"]) -> type["SketchTransform"]:
    """Class decorator: register under ``cls.sketch_type`` (≙ sketch_add.hpp)."""
    _REGISTRY[cls.sketch_type] = cls
    return cls


def sketch_registry() -> dict[str, type["SketchTransform"]]:
    return dict(_REGISTRY)


class SketchTransform(abc.ABC):
    """A random linear (or feature) map R^N -> R^S, reconstructible from JSON.

    Subclass contract:
    - ``__init__(n, s, ..., context)`` must snapshot ``context`` (seed +
      counter) *before* reserving, into ``self._creation_context``, then
      reserve all counter blocks it needs.  The helper ``_snapshot`` does
      the first part.
    - ``_param_dict()`` returns the extra JSON fields (e.g. ``sigma``).
    - ``_from_param_dict(d, ctx)`` (classmethod) rebuilds from those fields.
    """

    sketch_type: ClassVar[str] = "Abstract"

    # Batch sizes at which the apply switches algorithms (bucketed plans
    # must not pad across one — the planned batch has to take the same
    # code path, and produce the same bits, as the eager ragged apply).
    batch_size_gates: ClassVar[tuple] = ()

    # True when apply_slice_kernel is implemented (jit-safe traced-start
    # COLUMNWISE partials — the enabler for bucketed streaming plans).
    supports_slice_kernel: ClassVar[bool] = False

    def __init__(self, n: int, s: int, context: SketchContext):
        if n <= 0 or s <= 0:
            raise ValueError(f"sketch dims must be positive, got N={n}, S={s}")
        self.n = int(n)
        self.s = int(s)
        self._creation_context = SketchContext(
            seed=context.seed, counter=context.counter
        )

    # -- core op ------------------------------------------------------------

    @abc.abstractmethod
    def apply(self, A, dim: Dimension | str = Dimension.COLUMNWISE):
        """Sketch ``A`` along ``dim``; returns a new array (functional)."""

    def __call__(self, A, dim: Dimension | str = Dimension.COLUMNWISE):
        return self.apply(A, dim)

    def apply_planned(self, A, dim: Dimension | str = Dimension.COLUMNWISE):
        """Plan-aware apply: route through the process-wide plan cache
        (one fused jit executable per ``(sketch JSON, dim, shape, dtype,
        sharding)`` — bitwise identical to :meth:`apply`; see
        ``libskylark_tpu.plans``).  ``SKYLARK_NO_PLANS=1`` makes this a
        plain eager :meth:`apply`."""
        from .. import plans

        return plans.apply(self, A, dim)

    # -- partial-sketch protocol (streaming / out-of-core) -------------------
    #
    # Every transform here is a linear map (or linear-then-pointwise feature
    # map) whose randomness is counter-addressable, so ``S·A`` decomposes
    # exactly into per-block contributions that never need the full A (or
    # the full Omega) resident:
    #
    # - COLUMNWISE (A is (N, m), sketched axis = rows): the block of rows
    #   [start, start+k) contributes ``Omega[:, start:start+k] @ A_block``;
    #   block contributions MERGE BY SUM, then :meth:`finalize_slices`
    #   (identity for linear sketches; the cos epilogue for RFT).
    # - ROWWISE (A is (m, N), sketched axis = columns): a block of rows
    #   (examples) carries the full feature axis, so its contribution is
    #   the finished sketch of the block; contributions MERGE BY CONCAT
    #   along axis 0 in stream order.
    #
    # ``streaming.sketch`` drives this over ``io`` batch sources with a
    # prefetch pipeline and resilient checkpoints (docs/streaming.md).

    def apply_slice(self, A_block, start: int, dim: Dimension | str = Dimension.COLUMNWISE):
        """Exact contribution of the block of A starting at row ``start``
        of the sketched axis (``start`` must be a host int — it addresses
        the counter stream, not a traced value).

        COLUMNWISE: ``A_block`` is rows [start, start+k) of the (N, m)
        input; returns the (S, m) partial ``Omega[:, start:start+k] @
        A_block``.  Sum the results over a disjoint cover of [0, N) and
        pass the total through :meth:`finalize_slices` to get ``apply(A)``
        (bit-equal modulo floating-point summation order).

        ROWWISE: ``A_block`` is any row block of the (m, N) input; returns
        the finished (k, S) sketch of those rows (``start`` only records
        stream position).  Concatenate in stream order.
        """
        dim = Dimension.of(dim)
        if dim is Dimension.ROWWISE:
            return self.apply(A_block, dim)
        start = int(start)
        k = A_block.shape[0]
        if start < 0 or start + k > self.n:
            raise ValueError(
                f"slice [{start}, {start + k}) outside the sketch domain "
                f"[0, {self.n})"
            )
        squeeze = getattr(A_block, "ndim", 2) == 1
        if squeeze:
            A_block = A_block[:, None]
        out = self._apply_slice_columnwise(A_block, start)
        return out[:, 0] if squeeze else out

    def _apply_slice_columnwise(self, A_block, start: int):
        """Subclass hook for the COLUMNWISE partial product; ``A_block``
        is 2-D and bounds-checked."""
        from ..utils.exceptions import UnsupportedError

        raise UnsupportedError(
            f"{self.sketch_type} has no columnwise partial-sketch rule; "
            "stream ROWWISE, or use a dense (JLT/CT), hash "
            "(CWT/SJLT/MMT/WZT), or RFT transform"
        )

    def apply_slice_kernel(self, A_block, start):
        """jit-safe COLUMNWISE partial: like the COLUMNWISE
        :meth:`apply_slice` but ``start`` may be a TRACED scalar (< 2^32
        — the counter-window offset contract) and the window may run
        past the sketch domain: out-of-domain operand entries are zeroed
        inside the kernel, so a zero-padded ``A_block`` contributes
        exactly the in-domain partial.  This is what lets the plan layer
        compile ONE executable per bucket that serves every ragged
        streaming batch.  No host-side bounds check (start is traced);
        implemented by the dense, hash, and RFT engines
        (``supports_slice_kernel``)."""
        from ..utils.exceptions import UnsupportedError

        raise UnsupportedError(
            f"{self.sketch_type} has no jit-safe slice kernel; planned "
            "streaming falls back to the eager apply_slice path"
        )

    def apply_slice_kernel_acc(self, acc, A_block, start):
        """One streaming chunk step as a single traced body:
        ``acc + apply_slice_kernel(A_block, start)`` cast to
        ``acc.dtype``.  This default composite is exactly what the plan
        layer always compiled; engines with a device-fused kernel (the
        hash sketches) override it to fold the accumulator add into the
        kernel's emit — REQUIRED to stay bitwise equal to this
        composite (a single IEEE add of the same partial), so the
        planned≡eager contract never depends on which path won."""
        part = self.apply_slice_kernel(A_block, start)
        return acc + part.astype(acc.dtype)

    def finalize_slices(self, acc, dim: Dimension | str = Dimension.COLUMNWISE):
        """Turn the merged COLUMNWISE slice-sum into the final sketch
        (identity for linear transforms; feature maps apply their
        pointwise epilogue here).  ROWWISE concatenations are already
        final and pass through unchanged."""
        return acc

    # -- loop-invariant operand hoisting ------------------------------------

    def hoistable_operands(self, dtype):
        """Counter-derived arrays the apply realizes that do NOT depend
        on the input (the sketch operand, shifts, ...), or None.

        XLA does not hoist this realization out of a ``lax.fori_loop``
        body even though it is loop-invariant — measured ~11 ms per
        8M-draw W per panel visit in the streaming-KRR sweep (round 3).
        Streaming consumers call this ONCE per jitted program (outside
        their panel loop) and pass the result to
        :meth:`apply_with_operands`.  Default: nothing to hoist.
        """
        return None

    def apply_with_operands(
        self, ops, A, dim: Dimension | str = Dimension.COLUMNWISE
    ):
        """Apply using pre-realized :meth:`hoistable_operands` (``ops``
        may be None → plain apply).  Default ignores ``ops``."""
        return self.apply(A, dim)

    # Convenience mirroring the python-skylark operator sugar
    # (python-skylark/skylark/sketch.py: __mul__ = columnwise, __div__ = rowwise).
    def __mul__(self, A):
        return self.apply(A, Dimension.COLUMNWISE)

    def __truediv__(self, A):
        return self.apply(A, Dimension.ROWWISE)

    # -- serialization ------------------------------------------------------

    def _param_dict(self) -> dict[str, Any]:
        return {}

    def to_dict(self) -> dict[str, Any]:
        """≙ ``sketch_transform_data_t::add_common`` + subclass fields."""
        d = {
            "skylark_object_type": "sketch",
            "skylark_version": SERIAL_VERSION,
            "sketch_type": self.sketch_type,
            "N": self.n,
            "S": self.s,
            "creation_context": self._creation_context.to_dict(),
        }
        d.update(self._param_dict())
        return d

    def to_json(self) -> str:
        return json.dumps(self.to_dict())

    # python-skylark compatibility surface (sketch.py:94-232).
    def serialize(self) -> dict[str, Any]:
        """≙ python-skylark ``serialize()`` (dict form of the transform)."""
        return self.to_dict()

    def getindim(self) -> int:
        """≙ python-skylark ``getindim()``."""
        return self.n

    def getsketchdim(self) -> int:
        """≙ python-skylark ``getsketchdim()``."""
        return self.s

    @classmethod
    def _from_param_dict(
        cls, d: dict[str, Any], context: SketchContext
    ) -> "SketchTransform":
        return cls(d["N"], d["S"], context)  # type: ignore[call-arg]

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "SketchTransform":
        ctx = SketchContext.from_dict(d["creation_context"])
        return cls._from_param_dict(d, ctx)

    @classmethod
    def from_json(cls, s: str) -> "SketchTransform":
        return cls.from_dict(json.loads(s))

    def __repr__(self):
        return f"{type(self).__name__}(N={self.n}, S={self.s})"


def from_dict(d: dict[str, Any]) -> SketchTransform:
    """Reconstruct any registered sketch from its dict (≙ from_ptree registry)."""
    t = d["sketch_type"]
    if t not in _REGISTRY:
        raise ValueError(
            f"unknown sketch_type {t!r}; known: {sorted(_REGISTRY)}"
        )
    if d.get("skylark_version", 1) < SERIAL_VERSION:
        import warnings

        warnings.warn(
            f"sketch serialized under stream revision "
            f"{d.get('skylark_version', 1)} (current {SERIAL_VERSION}): "
            "f32-uniform-derived values reproduce differently "
            "(docs/counter_contract.md, Stream revisions)",
            stacklevel=2,
        )
    return _REGISTRY[t].from_dict(d)


def from_json(s: str) -> SketchTransform:
    return from_dict(json.loads(s))


def deserialize_sketch(sketch_dict: dict[str, Any]) -> SketchTransform:
    """≙ python-skylark ``deserialize_sketch`` (sketch.py:33-42): rebuild a
    transform from its ``serialize()`` dict."""
    return from_dict(sketch_dict)


def create_sketch(
    sketch_type: str, n: int, s: int, context: SketchContext, **params: Any
) -> SketchTransform:
    """String-typed factory (≙ ``capi/csketch.cpp:15-58`` / ``create_sketch``)."""
    if sketch_type not in _REGISTRY:
        raise ValueError(
            f"unknown sketch_type {sketch_type!r}; known: {sorted(_REGISTRY)}"
        )
    return _REGISTRY[sketch_type](n, s, context=context, **params)
