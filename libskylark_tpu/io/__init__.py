"""Dataset IO (≙ reference ``ml/io.hpp``, ``utility/io/libsvm_io.hpp``;
byte-source seam ≙ the HDFS reader variants at ``libsvm_io.hpp:1495-1638``)."""

from .arclist import arc_list_source, scan_arc_list, stream_arc_list
from .hdf5 import read_hdf5, stream_hdf5, write_hdf5
from .libsvm import read_libsvm, scan_libsvm_dims, stream_libsvm, write_libsvm
from .source import (
    ByteSource,
    FsspecSource,
    LocalSource,
    MemorySource,
    open_source,
    register_scheme,
)

__all__ = [
    "read_libsvm",
    "write_libsvm",
    "stream_libsvm",
    "scan_libsvm_dims",
    "read_hdf5",
    "write_hdf5",
    "stream_hdf5",
    "scan_arc_list",
    "stream_arc_list",
    "arc_list_source",
    "ByteSource",
    "LocalSource",
    "MemorySource",
    "FsspecSource",
    "open_source",
    "register_scheme",
]
