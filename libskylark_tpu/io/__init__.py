"""Dataset IO (≙ reference ``ml/io.hpp``, ``utility/io/libsvm_io.hpp``)."""

from .hdf5 import read_hdf5, write_hdf5
from .libsvm import read_libsvm, stream_libsvm, write_libsvm

__all__ = [
    "read_libsvm",
    "write_libsvm",
    "stream_libsvm",
    "read_hdf5",
    "write_hdf5",
]
