"""Dataset IO (≙ reference ``ml/io.hpp``, ``utility/io/libsvm_io.hpp``)."""

from .libsvm import read_libsvm, write_libsvm

__all__ = ["read_libsvm", "write_libsvm"]
