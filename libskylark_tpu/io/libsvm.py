"""LIBSVM-format reader/writer.

≙ the reference's chunked MPI LIBSVM reader
(``utility/io/libsvm_io.hpp:529+``, ``ml/io.hpp:529-889``): rank 0 reads and
ships chunks over MPI.  On TPU the host reads once and ``jax.device_put``
with a sharding distributes — there is no per-rank file chunking to port.

Convention: examples are **rows** — X is (n_examples, n_features) — the
idiomatic JAX layout (the reference stores examples as columns of a d×n
Elemental matrix; its columnwise/rowwise sketch tags already abstract this).
"""

from __future__ import annotations

import io

import numpy as np

__all__ = ["read_libsvm", "write_libsvm", "stream_libsvm", "scan_libsvm_dims"]


def scan_libsvm_dims(path, chunk_bytes: int = 8 << 20) -> tuple[int, int]:
    """One cheap pass over a LIBSVM source → ``(n_examples, n_features)``.

    Streaming consumers must know the global shape up front (the row
    count addresses a columnwise sketch's counter stream; the feature
    count sizes the batches), and an out-of-core file cannot be read
    whole to find out.  This scan only tokenizes — no float parsing, no
    arrays — so it is bounded-memory and IO-dominated.
    """
    from .source import open_source

    n = 0
    d = 0
    with open_source(path).open() as f:
        carry = b""
        eof = False
        while not eof:
            data = f.read(chunk_bytes)
            eof = not data
            block = carry + data
            carry = b""
            if not eof:
                cut = block.rfind(b"\n")
                if cut < 0:
                    carry = block
                    continue
                carry, block = block[cut + 1 :], block[: cut + 1]
            for line in block.decode().splitlines():
                line = line.split("#", 1)[0].strip()
                if not line:
                    continue
                n += 1
                for tok in line.split()[1:]:
                    idx = int(tok.split(":", 1)[0])
                    if idx > d:
                        d = idx
    return n, d


def read_libsvm(
    path: str,
    n_features: int | None = None,
    sparse: bool = False,
    dtype=np.float64,
    max_rows: int | None = None,
):
    """Read a LIBSVM file → ``(X, y)``.

    ``sparse=True`` returns a ``jax.experimental.sparse.BCOO``; otherwise a
    dense ndarray.  ``n_features`` pads/clips the feature dimension (the
    reference's ``min_d`` flag, ``ml/io.hpp:534``); ``max_rows`` caps the
    number of examples read (the reference's ``max_n``,
    ``capi/cio.cpp sl_readlibsvm``).  Indices are 1-based in the file
    (LIBSVM standard, matching the reference reader).

    Parsing uses the native multithreaded C++ parser when built
    (``libskylark_tpu.native``, ≙ the reference's native chunked reader);
    otherwise the pure-Python path below.
    """
    from .. import native
    from .source import open_source

    src = open_source(path)

    # max_rows must bound both the result AND the parsing work (the
    # reference's reader stops early), so it bypasses the slurp-everything
    # native fast path and breaks out of the line loop.
    parsed = None
    if native.available() and max_rows is None:
        with src.open() as f:
            data = f.read()
        try:
            parsed = native.parse_libsvm_bytes(data)
        except Exception:
            parsed = None  # malformed for the fast path; strict parser below
    if parsed is not None:
        y_all, rows_a, cols_a, vals_a = parsed[:4]
        n = len(y_all)
        y = y_all.astype(dtype)
        vals_a = vals_a.astype(dtype)
    else:
        labels: list[float] = []
        rows: list[int] = []
        cols: list[int] = []
        vals: list[float] = []
        with src.open() as f:
            for line in io.TextIOWrapper(f, encoding="utf-8"):
                if max_rows is not None and len(labels) >= max_rows:
                    break
                _parse_line(line, labels, rows, cols, vals)
        n = len(labels)
        y = np.asarray(labels, dtype=dtype)
        rows_a = np.asarray(rows, dtype=np.int64)
        cols_a = np.asarray(cols, dtype=np.int64)
        vals_a = np.asarray(vals, dtype=dtype)
    # Feature dimension is inferred AFTER the row cap, so columns that
    # appear only in discarded rows don't widen X.
    max_col = int(cols_a.max()) + 1 if len(cols_a) else 0
    d = n_features if n_features is not None else max_col
    keep = cols_a < d
    rows_a, cols_a, vals_a = rows_a[keep], cols_a[keep], vals_a[keep]
    if sparse:
        from jax.experimental import sparse as jsparse
        import jax.numpy as jnp

        idx = np.stack([rows_a, cols_a], axis=1).astype(np.int32)
        X = jsparse.BCOO(
            (jnp.asarray(vals_a), jnp.asarray(idx)), shape=(n, d)
        )
        return X, y
    X = np.zeros((n, d), dtype=dtype)
    X[rows_a, cols_a] = vals_a
    return X, y


def _parse_line(line, labels, rows, cols, vals) -> None:
    """Parse one LIBSVM line into the accumulator lists (shared by the
    batch reader's Python fallback and the streaming reader)."""
    line = line.split("#", 1)[0].strip()
    if not line:
        return
    parts = line.split()
    labels.append(float(parts[0]))
    r = len(labels) - 1
    for tok in parts[1:]:
        idx, val = tok.split(":", 1)
        c = int(idx) - 1
        if c < 0:
            raise ValueError(f"bad LIBSVM index {idx!r} (1-based)")
        rows.append(r)
        cols.append(c)
        vals.append(float(val))


def _assemble_batch(labels, rows, cols, vals, n_features, sparse, dtype):
    """(labels, triplet arrays with batch-local rows) → (X, y)."""
    n = len(labels)
    y = np.asarray(labels, dtype=dtype)
    rows = np.asarray(rows, dtype=np.int64)
    cols = np.asarray(cols, dtype=np.int64)
    vals = np.asarray(vals, dtype=dtype)
    keep = cols < n_features
    rows, cols, vals = rows[keep], cols[keep], vals[keep]
    if sparse:
        from jax.experimental import sparse as jsparse
        import jax.numpy as jnp

        idx = np.stack([rows, cols], axis=1).astype(np.int32)
        X = jsparse.BCOO(
            (jnp.asarray(vals), jnp.asarray(idx)), shape=(n, n_features)
        )
        return X, y
    X = np.zeros((n, n_features), dtype)
    X[rows, cols] = vals
    return X, y


def stream_libsvm(
    path, n_features: int, batch: int = 4096, sparse: bool = False,
    dtype=np.float64, chunk_bytes: int = 8 << 20,
):
    """Yield ``(X, y)`` batches of up to ``batch`` examples (dense ndarray,
    or BCOO when ``sparse``).

    ≙ the reference's streaming line-by-line predict IO (``ml/io.hpp``)
    and its HDFS readers (``utility/io/libsvm_io.hpp:1495-1638``):
    bounded memory for files larger than RAM, from any byte source —
    ``path`` may be a local path, a ``scheme://`` URL, raw bytes, or a
    :class:`~libskylark_tpu.io.source.ByteSource`.  Byte chunks go through
    the native multithreaded parser when built; the pure-Python per-line
    parser is the fallback.
    """
    from .. import native
    from .source import open_source

    def parse_chunk(block: bytes):
        """Parse a newline-aligned byte chunk → numpy arrays.  The native
        parser is preferred; malformed chunks re-parse through the strict
        Python path so the exception type (ValueError) matches
        read_libsvm regardless of whether the .so is built."""
        if native.available():
            try:
                labels, rows, cols, vals, _ = native.parse_libsvm_bytes(block)
                return labels, rows, cols, vals
            except ValueError:
                raise
            except Exception:
                pass
        l: list = []
        r: list = []
        c: list = []
        v: list = []
        for line in block.decode().splitlines():
            _parse_line(line, l, r, c, v)
        return (
            np.asarray(l, np.float64),
            np.asarray(r, np.int64),
            np.asarray(c, np.int64),
            np.asarray(v, np.float64),
        )

    # Pending examples carried across chunks, kept as numpy arrays (no
    # per-element Python boxing on the hot path).
    p_lab = np.empty(0, np.float64)
    p_rows = np.empty(0, np.int64)
    p_cols = np.empty(0, np.int64)
    p_vals = np.empty(0, np.float64)

    with open_source(path).open() as f:
        carry = b""
        eof = False
        while not eof:
            data = f.read(chunk_bytes)
            eof = not data
            block = carry + data
            carry = b""
            if not eof:
                cut = block.rfind(b"\n")
                if cut < 0:
                    carry = block
                    continue
                carry, block = block[cut + 1 :], block[: cut + 1]
            if block:
                labels, rows, cols, vals = parse_chunk(block)
                p_rows = np.concatenate([p_rows, rows + len(p_lab)])
                p_lab = np.concatenate([p_lab, labels])
                p_cols = np.concatenate([p_cols, cols])
                p_vals = np.concatenate([p_vals, vals])
            while len(p_lab) >= batch:
                split = int(np.searchsorted(p_rows, batch))
                yield _assemble_batch(
                    p_lab[:batch], p_rows[:split], p_cols[:split],
                    p_vals[:split], n_features, sparse, dtype,
                )
                p_lab = p_lab[batch:]
                p_rows = p_rows[split:] - batch
                p_cols = p_cols[split:]
                p_vals = p_vals[split:]
    if len(p_lab):
        yield _assemble_batch(
            p_lab, p_rows, p_cols, p_vals, n_features, sparse, dtype
        )


def write_libsvm(path: str, X, y) -> None:
    """Write dense or BCOO ``X`` with labels ``y`` in LIBSVM format."""
    X = np.asarray(X.todense()) if hasattr(X, "todense") else np.asarray(X)
    y = np.asarray(y)
    with open(path, "w") as f:
        for i in range(X.shape[0]):
            label = y[i]
            lab = (
                str(int(label))
                if float(label).is_integer()
                else repr(float(label))
            )
            feats = " ".join(
                f"{j + 1}:{X[i, j]:.17g}"
                for j in range(X.shape[1])
                if X[i, j] != 0
            )
            f.write(f"{lab} {feats}\n".rstrip() + "\n")
