"""LIBSVM-format reader/writer.

≙ the reference's chunked MPI LIBSVM reader
(``utility/io/libsvm_io.hpp:529+``, ``ml/io.hpp:529-889``): rank 0 reads and
ships chunks over MPI.  On TPU the host reads once and ``jax.device_put``
with a sharding distributes — there is no per-rank file chunking to port.

Convention: examples are **rows** — X is (n_examples, n_features) — the
idiomatic JAX layout (the reference stores examples as columns of a d×n
Elemental matrix; its columnwise/rowwise sketch tags already abstract this).
"""

from __future__ import annotations

import numpy as np

__all__ = ["read_libsvm", "write_libsvm", "stream_libsvm"]


def read_libsvm(
    path: str,
    n_features: int | None = None,
    sparse: bool = False,
    dtype=np.float64,
):
    """Read a LIBSVM file → ``(X, y)``.

    ``sparse=True`` returns a ``jax.experimental.sparse.BCOO``; otherwise a
    dense ndarray.  ``n_features`` pads/clips the feature dimension (the
    reference's ``min_d`` flag, ``ml/io.hpp:534``).  Indices are 1-based in
    the file (LIBSVM standard, matching the reference reader).

    Parsing uses the native multithreaded C++ parser when built
    (``libskylark_tpu.native``, ≙ the reference's native chunked reader);
    otherwise the pure-Python path below.
    """
    from .. import native

    parsed = None
    if native.available():
        with open(path, "rb") as f:
            data = f.read()
        try:
            parsed = native.parse_libsvm_bytes(data)
        except Exception:
            parsed = None  # malformed for the fast path; strict parser below
    if parsed is not None:
        y_all, rows_a, cols_a, vals_a = parsed[:4]
        n = len(y_all)
        max_col = int(cols_a.max()) + 1 if len(cols_a) else 0
        d = n_features if n_features is not None else max_col
        y = y_all.astype(dtype)
        vals_a = vals_a.astype(dtype)
    else:
        labels: list[float] = []
        rows: list[int] = []
        cols: list[int] = []
        vals: list[float] = []
        max_col = 0
        with open(path, "r") as f:
            for line in f:
                line = line.split("#", 1)[0].strip()
                if not line:
                    continue
                parts = line.split()
                labels.append(float(parts[0]))
                r = len(labels) - 1
                for tok in parts[1:]:
                    idx, val = tok.split(":", 1)
                    c = int(idx) - 1
                    if c < 0:
                        raise ValueError(f"bad LIBSVM index {idx!r} (1-based)")
                    max_col = max(max_col, c + 1)
                    rows.append(r)
                    cols.append(c)
                    vals.append(float(val))
        n = len(labels)
        d = n_features if n_features is not None else max_col
        y = np.asarray(labels, dtype=dtype)
        rows_a = np.asarray(rows, dtype=np.int64)
        cols_a = np.asarray(cols, dtype=np.int64)
        vals_a = np.asarray(vals, dtype=dtype)
    keep = cols_a < d
    rows_a, cols_a, vals_a = rows_a[keep], cols_a[keep], vals_a[keep]
    if sparse:
        from jax.experimental import sparse as jsparse
        import jax.numpy as jnp

        idx = np.stack([rows_a, cols_a], axis=1).astype(np.int32)
        X = jsparse.BCOO(
            (jnp.asarray(vals_a), jnp.asarray(idx)), shape=(n, d)
        )
        return X, y
    X = np.zeros((n, d), dtype=dtype)
    X[rows_a, cols_a] = vals_a
    return X, y


def stream_libsvm(
    path, n_features: int, batch: int = 4096, sparse: bool = False,
    dtype=np.float64,
):
    """Yield ``(X, y)`` batches of up to ``batch`` examples (dense ndarray,
    or BCOO when ``sparse``).

    ≙ the reference's streaming line-by-line predict IO (``ml/io.hpp``):
    bounded memory for test files larger than RAM.
    """
    ridx: list[int] = []
    cidx: list[int] = []
    vals: list[float] = []
    labels: list[float] = []

    def flush():
        n = len(labels)
        y = np.asarray(labels, dtype=dtype)
        if sparse:
            from jax.experimental import sparse as jsparse
            import jax.numpy as jnp

            idx = np.stack(
                [np.asarray(ridx), np.asarray(cidx)], axis=1
            ).astype(np.int32) if ridx else np.zeros((0, 2), np.int32)
            X = jsparse.BCOO(
                (jnp.asarray(np.asarray(vals, dtype=dtype)), jnp.asarray(idx)),
                shape=(n, n_features),
            )
        else:
            X = np.zeros((n, n_features), dtype)
            if ridx:
                X[np.asarray(ridx), np.asarray(cidx)] = np.asarray(vals, dtype)
        ridx.clear(); cidx.clear(); vals.clear(); labels.clear()
        return X, y

    with open(path, "r") as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split()
            r = len(labels)
            labels.append(float(parts[0]))
            for tok in parts[1:]:
                idx, val = tok.split(":", 1)
                c = int(idx) - 1
                if c < 0:
                    raise ValueError(f"bad LIBSVM index {idx!r} (1-based)")
                if c < n_features:
                    ridx.append(r)
                    cidx.append(c)
                    vals.append(float(val))
            if len(labels) >= batch:
                yield flush()
    if labels:
        yield flush()


def write_libsvm(path: str, X, y) -> None:
    """Write dense or BCOO ``X`` with labels ``y`` in LIBSVM format."""
    X = np.asarray(X.todense()) if hasattr(X, "todense") else np.asarray(X)
    y = np.asarray(y)
    with open(path, "w") as f:
        for i in range(X.shape[0]):
            label = y[i]
            lab = (
                str(int(label))
                if float(label).is_integer()
                else repr(float(label))
            )
            feats = " ".join(
                f"{j + 1}:{X[i, j]:.17g}"
                for j in range(X.shape[1])
                if X[i, j] != 0
            )
            f.write(f"{lab} {feats}\n".rstrip() + "\n")
