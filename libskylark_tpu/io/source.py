"""Byte-stream sources: the remote-filesystem seam of the IO layer.

≙ the reference's HDFS variants of the LIBSVM readers
(``utility/io/libsvm_io.hpp:1495-1638``: the same parse loop over an
``hdfsFS`` handle instead of an ``ifstream``).  The TPU build expresses
that idea as a tiny fsspec-style interface: every reader that consumes
bytes (``read_libsvm`` / ``stream_libsvm``) accepts a *source* — anything
with ``open() -> binary file-like`` — and a URL-scheme registry picks the
backend, so remote stores plug in without touching the parsers.

Built-in backends:

- ``LocalSource`` — plain paths and ``file://`` URLs.
- ``MemorySource`` — in-memory bytes (tests, generated data).
- ``FsspecSource`` — any scheme fsspec knows (``memory://``, ``hdfs://``,
  ``s3://``, ``gs://`` …) when the optional ``fsspec`` package is
  importable (it is in this environment; schemes whose extra backend
  deps are missing raise their own clear errors at ``open()``).

``register_scheme`` lets applications add their own backends.
"""

from __future__ import annotations

import io
import os
import random
import time
from typing import Callable

__all__ = [
    "ByteSource",
    "LocalSource",
    "MemorySource",
    "FsspecSource",
    "open_source",
    "register_scheme",
]


class ByteSource:
    """Interface: a named, re-openable stream of bytes."""

    name: str = "<bytes>"

    def open(self):  # -> binary file-like (context manager)
        raise NotImplementedError

    def size(self) -> int | None:
        """Total bytes if cheaply known, else None (streaming-only)."""
        return None


class LocalSource(ByteSource):
    def __init__(self, path):
        self.path = os.fspath(path)
        self.name = self.path

    def open(self):
        return open(self.path, "rb")

    def size(self):
        return os.path.getsize(self.path)


class MemorySource(ByteSource):
    def __init__(self, data: bytes, name: str = "<memory>"):
        self._data = bytes(data)
        self.name = name

    def open(self):
        return io.BytesIO(self._data)

    def size(self):
        return len(self._data)


class FsspecSource(ByteSource):
    """Remote store via fsspec (covers the reference's HDFS role).

    Instantiating raises ImportError with a pointer when fsspec is not
    installed; schemes fsspec knows but whose backend deps are absent
    (e.g. hdfs without a JVM) raise their own error at ``open()``.

    ``open()`` retries transient ``OSError``/``IOError`` with jittered
    exponential backoff (``retries`` extra attempts after the first) —
    a flaky remote store must not kill a multi-hour stream over one
    dropped connection.  Non-OSError failures (missing backend deps,
    auth errors) propagate immediately.  Retries are counted in the
    telemetry registry (``io.open_retries``) with one ledger event per
    retried attempt.
    """

    def __init__(self, url: str, *, retries: int = 3, backoff: float = 0.2):
        from ..utils.deps import require

        self._fsspec = require("fsspec")
        self.url = url
        self.name = url
        self.retries = int(retries)
        self.backoff = float(backoff)
        self._sleep = time.sleep  # injectable: tests skip the real wait
        self._jitter = random.random  # likewise

    def open(self):
        from .. import telemetry

        attempt = 0
        while True:
            try:
                return self._fsspec.open(self.url, "rb").open()
            except OSError as e:
                if attempt >= self.retries:
                    raise
                # Full jitter on the exponential step: concurrent hosts
                # re-opening the same store must not thunder in lockstep.
                delay = self.backoff * (2**attempt) * (0.5 + self._jitter())
                if telemetry.enabled():
                    telemetry.inc("io.open_retries")
                    telemetry.event(
                        "io", "open_retry",
                        {
                            "url": self.url,
                            "attempt": attempt,
                            "delay": round(delay, 4),
                            "error": f"{type(e).__name__}: {e}"[:200],
                        },
                    )
                self._sleep(delay)
                attempt += 1


_SCHEMES: dict[str, Callable[[str], ByteSource]] = {}


def register_scheme(scheme: str, factory: Callable[[str], ByteSource]):
    """Route ``scheme://...`` URLs to ``factory(url)``."""
    _SCHEMES[scheme.lower()] = factory


def open_source(src) -> ByteSource:
    """Coerce a path / URL / bytes / ByteSource to a ByteSource.

    - ByteSource: returned as-is
    - bytes: MemorySource
    - ``file://`` URL or plain path: LocalSource
    - ``scheme://`` URL: registered factory, else FsspecSource
    """
    if isinstance(src, ByteSource):
        return src
    if isinstance(src, (bytes, bytearray)):
        return MemorySource(bytes(src))
    path = os.fspath(src)
    if "://" in path:
        scheme, rest = path.split("://", 1)
        scheme = scheme.lower()
        if scheme == "file":
            return LocalSource(rest)
        if scheme in _SCHEMES:
            return _SCHEMES[scheme](path)
        return FsspecSource(path)
    return LocalSource(path)
