"""Streamed arc-list reader: chunked COO edge blocks over ByteSources.

≙ the reference's arc-list loaders (``utility/io``) re-founded on the
same checkpointable-fold contract as ``stream_libsvm``: a billion-edge
file is parsed chunk-by-chunk from any :class:`~.source.ByteSource`
(local path, ``file://``, fsspec URL, in-memory bytes) and yielded as
fixed-size COO edge blocks — symmetrized, globally deduped, self-loops
dropped — without ever materializing the graph.

Contract (what makes the streamed fold bitwise-reproducible):

- **Deterministic blocks.** Given the same ``(source, index, batch_edges)``
  the generator yields the identical block sequence — chunk boundaries
  (``chunk_bytes``) never change *which* edges appear or their order,
  only how many file reads it takes to find them.  This is what lets
  ``streaming.engine.run_stream`` re-open the source at batch *k* on
  resume and replay into a bit-identical accumulator.
- **First-occurrence dedup.** Duplicate and reversed duplicates of an
  undirected edge (``u v`` then ``v u``) collapse to the first
  occurrence, in file order — matching ``SimpleGraph``'s ``set``-of-
  canonical-pairs semantics edge-for-edge.
- **Self-loops dropped by name** (before any id lookup), matching
  ``SimpleGraph.__init__``; a vertex appearing only in self-loops gets
  no id.

Dedup state is a sorted ``int64`` array of packed ``(lo << 32) | hi``
keys — O(unique undirected edges) host memory, the one thing that does
scale with the graph (ids, not the edge file, must fit; the adjacency
never does).
"""

from __future__ import annotations

from itertools import islice

import numpy as np

from .source import open_source

__all__ = [
    "scan_arc_list",
    "stream_arc_list",
    "arc_list_source",
]

# Vertex ids are packed two-per-int64 for the dedup set.
_MAX_VERTICES = 1 << 32


def _parse_edge_block(block: bytes):
    """Parse complete lines into (us, vs) name lists.

    Comment lines (``#``/``%``), blanks, and short lines are skipped;
    self-loops are dropped by *name* (``SimpleGraph`` semantics).  Extra
    columns (weights) are ignored — the graph layer is unweighted.
    """
    us: list[str] = []
    vs: list[str] = []
    for raw in block.decode().splitlines():
        line = raw.strip()
        if not line or line[0] in "#%":
            continue
        parts = line.split()
        if len(parts) < 2:
            continue
        u, v = parts[0], parts[1]
        if u == v:
            continue
        us.append(u)
        vs.append(v)
    return us, vs


def _chunk_lines(src, chunk_bytes: int):
    """Yield byte blocks of complete lines (torn-tail carry, as
    ``stream_libsvm`` does): a line split across two reads is re-joined
    before parsing, and a final line without a trailing newline is still
    delivered."""
    with src.open() as f:
        carry = b""
        eof = False
        while not eof:
            data = f.read(chunk_bytes)
            eof = not data
            block = carry + data
            carry = b""
            if not eof:
                cut = block.rfind(b"\n")
                if cut < 0:
                    carry = block
                    continue
                carry, block = block[cut + 1 :], block[: cut + 1]
            if block:
                yield block


def _pack(lo: np.ndarray, hi: np.ndarray) -> np.ndarray:
    return (lo.astype(np.int64) << 32) | hi.astype(np.int64)


def scan_arc_list(path, chunk_bytes: int = 8 << 20):
    """One cheap pass over the file: returns ``(index, num_edges)``.

    ``index`` maps vertex name → contiguous id in first-seen order
    (scanning ``u`` then ``v`` per edge — identical to
    ``SimpleGraph.__init__``); ``num_edges`` counts unique undirected
    edges, i.e. the ``nrows`` an elastic ``RowPartition`` over the edge
    stream should be built with.
    """
    src = open_source(path)
    index: dict = {}
    seen = np.empty(0, dtype=np.int64)
    for block in _chunk_lines(src, chunk_bytes):
        us, vs = _parse_edge_block(block)
        if not us:
            continue
        for u, v in zip(us, vs):
            if u not in index:
                index[u] = len(index)
            if v not in index:
                index[v] = len(index)
        ids = np.fromiter(
            (index[w] for pair in zip(us, vs) for w in pair),
            dtype=np.int64,
            count=2 * len(us),
        ).reshape(-1, 2)
        lo, hi = ids.min(axis=1), ids.max(axis=1)
        seen = np.union1d(seen, _pack(lo, hi))
    if len(index) >= _MAX_VERTICES:
        raise ValueError(
            f"arc list has {len(index)} vertices; the packed dedup key "
            f"supports < {_MAX_VERTICES}"
        )
    return index, int(seen.size)


def stream_arc_list(
    path,
    *,
    index=None,
    batch_edges: int = 65536,
    chunk_bytes: int = 8 << 20,
    dtype=np.float64,
):
    """Yield symmetrized COO edge blocks from an arc list.

    Each block is ``{"rows", "cols", "vals"}`` holding ``2*k`` entries
    for ``k`` undirected edges (both directions, ``vals`` all ones in
    ``dtype``).  Every block carries exactly ``batch_edges`` undirected
    edges except the final one, which may be short.  Blocks appear in
    file order after first-occurrence dedup, so the sequence is
    deterministic and independent of ``chunk_bytes``.

    ``index``: vertex name → id mapping (from :func:`scan_arc_list` or a
    ``SimpleGraph``).  ``None`` runs the scan pass here first.
    """
    if index is None:
        index, _ = scan_arc_list(path, chunk_bytes=chunk_bytes)
    if len(index) >= _MAX_VERTICES:
        raise ValueError(
            f"index has {len(index)} vertices; the packed dedup key "
            f"supports < {_MAX_VERTICES}"
        )
    src = open_source(path)
    seen = np.empty(0, dtype=np.int64)
    plo = np.empty(0, dtype=np.int64)
    phi = np.empty(0, dtype=np.int64)

    def _block(lo: np.ndarray, hi: np.ndarray):
        k = lo.size
        return {
            "rows": np.concatenate([lo, hi]),
            "cols": np.concatenate([hi, lo]),
            "vals": np.ones(2 * k, dtype=dtype),
        }

    for block in _chunk_lines(src, chunk_bytes):
        us, vs = _parse_edge_block(block)
        if not us:
            continue
        ids = np.fromiter(
            (index[w] for pair in zip(us, vs) for w in pair),
            dtype=np.int64,
            count=2 * len(us),
        ).reshape(-1, 2)
        lo, hi = ids.min(axis=1), ids.max(axis=1)
        keys = _pack(lo, hi)
        # Within-chunk + cross-chunk dedup, keeping file order of first
        # occurrences: np.unique sorts by key, so re-sort the surviving
        # first-occurrence positions.
        uk, first = np.unique(keys, return_index=True)
        fresh = ~np.isin(uk, seen)
        firsts = np.sort(first[fresh])
        seen = np.union1d(seen, uk[fresh])
        plo = np.concatenate([plo, lo[firsts]])
        phi = np.concatenate([phi, hi[firsts]])
        while plo.size >= batch_edges:
            yield _block(plo[:batch_edges], phi[:batch_edges])
            plo, phi = plo[batch_edges:], phi[batch_edges:]
    if plo.size:
        yield _block(plo, phi)


def arc_list_source(
    path,
    *,
    index,
    batch_edges: int = 65536,
    chunk_bytes: int = 8 << 20,
    dtype=np.float64,
):
    """Checkpointable block factory over an arc list.

    Returns ``factory(start_batch)`` suitable for
    ``streaming.engine.run_stream`` / ``elastic_run_stream``: resume at
    batch *k* re-parses the file and skips the first *k* blocks (the
    generic re-parse skip — arc lists are not seekable by batch).  The
    vertex ``index`` is required here: a resumed rank must not re-derive
    it from a partial read.
    """

    def factory(start_batch: int = 0):
        it = stream_arc_list(
            path,
            index=index,
            batch_edges=batch_edges,
            chunk_bytes=chunk_bytes,
            dtype=dtype,
        )
        return islice(it, start_batch, None)

    return factory
