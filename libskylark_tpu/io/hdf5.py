"""HDF5 dataset IO (≙ ``ml/io.hpp`` ReadHDF5/WriteHDF5 paths).

Layout matches the reference's skylark_ml HDF5 format: dense data in
datasets ``X`` (n × d) and ``Y`` (n,); sparse data in CSR-style datasets
``dimensions``/``indptr``/``indices``/``values`` + ``Y``
(``ml/io.hpp:256-520``).
"""

from __future__ import annotations

import numpy as np

from ..utils.deps import require

__all__ = ["read_hdf5", "write_hdf5", "stream_hdf5"]


def write_hdf5(path, X, y, sparse: bool = False) -> None:
    h5py = require("h5py")

    with h5py.File(path, "w") as f:
        y = np.asarray(y)
        if sparse or hasattr(X, "todense"):
            if hasattr(X, "todense"):  # BCOO
                idx = np.asarray(X.indices)
                data = np.asarray(X.data)
                n, d = X.shape
                order = np.lexsort((idx[:, 1], idx[:, 0]))
                rows, cols = idx[order, 0], idx[order, 1]
                vals = data[order]
            else:
                Xd = np.asarray(X)
                rows, cols = np.nonzero(Xd)
                vals = Xd[rows, cols]
                n, d = Xd.shape
            indptr = np.zeros(n + 1, dtype=np.int64)
            np.add.at(indptr, rows + 1, 1)
            indptr = np.cumsum(indptr)
            # Reference order: [num_features, num_examples, nnz]
            # (ml/io.hpp writes dimensions[0]=height=d, [1]=width=n; indptr
            # runs over examples in both layouts).
            f.create_dataset("dimensions", data=np.asarray([d, n, len(vals)]))
            f.create_dataset("indptr", data=indptr)
            f.create_dataset("indices", data=cols.astype(np.int64))
            f.create_dataset("values", data=vals)
        else:
            f.create_dataset("X", data=np.asarray(X))
        f.create_dataset("Y", data=y)


def read_hdf5(path, sparse: bool | None = None):
    """Returns (X, y); X is BCOO if the file holds sparse data (or
    ``sparse=True`` forces conversion of dense data)."""
    h5py = require("h5py")

    with h5py.File(path, "r") as f:
        y = np.asarray(f["Y"])
        if "X" in f:
            X = np.asarray(f["X"])
            if sparse:
                import jax.numpy as jnp
                from jax.experimental import sparse as jsparse

                return jsparse.BCOO.fromdense(jnp.asarray(X)), y
            return X, y
        d, n, nnz = (int(v) for v in f["dimensions"][:])
        indptr = np.asarray(f["indptr"])
        indices = np.asarray(f["indices"])
        values = np.asarray(f["values"])
    rows = np.repeat(np.arange(n), np.diff(indptr))
    if sparse is False:
        X = np.zeros((n, d), dtype=values.dtype)
        X[rows, indices] = values
        return X, y
    import jax.numpy as jnp
    from jax.experimental import sparse as jsparse

    idx = np.stack([rows, indices], axis=1).astype(np.int32)
    X = jsparse.BCOO((jnp.asarray(values), jnp.asarray(idx)), shape=(n, d))
    return X, y


def stream_hdf5(path, batch: int, sparse: bool | None = None):
    """Yield ``(X_batch, y_batch)`` row batches with bounded memory — the
    HDF5 face of :func:`..libsvm.stream_libsvm` (≙ the reference's
    chunked test-predict IO, ``ml/io.hpp:869-889``).  Dense files yield
    ndarray batches; CSR-style sparse files yield per-batch BCOO (each
    batch's indptr window is sliced straight from disk)."""
    h5py = require("h5py")

    with h5py.File(path, "r") as f:
        y = f["Y"]
        if "X" in f:
            X = f["X"]
            n = X.shape[0]
            for lo in range(0, n, batch):
                hi = min(lo + batch, n)
                Xb = np.asarray(X[lo:hi])
                if sparse:
                    import jax.numpy as jnp
                    from jax.experimental import sparse as jsparse

                    yield jsparse.BCOO.fromdense(jnp.asarray(Xb)), np.asarray(
                        y[lo:hi]
                    )
                else:
                    yield Xb, np.asarray(y[lo:hi])
            return
        d, n, _ = (int(v) for v in f["dimensions"][:])
        indptr = np.asarray(f["indptr"])
        indices = f["indices"]
        values = f["values"]
        for lo in range(0, n, batch):
            hi = min(lo + batch, n)
            p0, p1 = int(indptr[lo]), int(indptr[hi])
            cols = np.asarray(indices[p0:p1])
            vals = np.asarray(values[p0:p1])
            rows = np.repeat(
                np.arange(hi - lo), np.diff(indptr[lo : hi + 1])
            )
            if sparse is False:
                Xb = np.zeros((hi - lo, d), dtype=vals.dtype)
                Xb[rows, cols] = vals
                yield Xb, np.asarray(y[lo:hi])
                continue
            import jax.numpy as jnp
            from jax.experimental import sparse as jsparse

            idx = np.stack([rows, cols], axis=1).astype(np.int32)
            yield (
                jsparse.BCOO(
                    (jnp.asarray(vals), jnp.asarray(idx)), shape=(hi - lo, d)
                ),
                np.asarray(y[lo:hi]),
            )
