"""Randomized NLA primitives (≙ reference ``nla/``).

- ``approximate_svd`` / ``approximate_symmetric_svd`` / ``power_iteration``
  ≙ ``nla/svd.hpp``
- ``exact_least_squares`` (QR/SNE/NE/SVD paths) ≙
  ``algorithms/regression/linearl2_regression_solver_Elemental.hpp``
- ``approximate_least_squares`` (sketch-and-solve) ≙
  ``nla/least_squares.hpp:42-184``
- ``faster_least_squares`` (Blendenpik) and ``cond_est`` live in
  ``solvers``-backed modules and are re-exported here once built.
"""

from ..solvers.accelerated import (
    FasterLeastSquaresParams,
    faster_least_squares,
    lsrn_least_squares,
)
from ..solvers.cond_est import CondEstParams, CondEstResult, cond_est
from .least_squares import (
    LeastSquaresParams,
    approximate_least_squares,
    exact_least_squares,
    streaming_least_squares,
)
from .svd import (
    SVDParams,
    approximate_svd,
    approximate_svd_chunked,
    approximate_symmetric_svd,
    power_iteration,
    streaming_approximate_svd,
    synthetic_lowrank_blocks,
)

__all__ = [
    "SVDParams",
    "approximate_svd",
    "approximate_svd_chunked",
    "approximate_symmetric_svd",
    "power_iteration",
    "streaming_approximate_svd",
    "synthetic_lowrank_blocks",
    "LeastSquaresParams",
    "approximate_least_squares",
    "exact_least_squares",
    "streaming_least_squares",
    "FasterLeastSquaresParams",
    "faster_least_squares",
    "lsrn_least_squares",
    "cond_est",
    "CondEstParams",
    "CondEstResult",
]
