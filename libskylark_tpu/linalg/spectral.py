"""Chebyshev spectral collocation utilities (≙ ``nla/spectral.hpp:17-96``).

Host-side numpy: these are tiny (N ≲ 100) matrices consumed by the
time-dependent PPR community detection, which the reference itself runs
outside Elemental (``ml/graph/local_computations.hpp:131``).
"""

from __future__ import annotations

import numpy as np

__all__ = ["chebyshev_points", "chebyshev_diff_matrix"]


def chebyshev_points(N: int, a: float = -1.0, b: float = 1.0) -> np.ndarray:
    """N Chebyshev points of the second kind mapped to [a, b], descending
    (x_j = (cos(jπ/(N−1)) + a + 1)·(b−a)/2, ≙ ``ChebyshevPoints``)."""
    n = N - 1
    j = np.arange(N)
    # Standard affine map a + (cos+1)(b−a)/2 (the reference's inline
    # formula is only correct for a ∈ {−1, 0}, its rescale path uses this).
    x = a + (np.cos(j * np.pi / n) + 1.0) * (b - a) / 2.0
    if n % 2 == 0:
        # Midpoint exactly centred (≙ the Set(N/2, 0.0) for [-1, 1]).
        x[n // 2] = (a + b) / 2.0
    return x


def chebyshev_diff_matrix(
    N: int, a: float = -1.0, b: float = 1.0
) -> tuple[np.ndarray, np.ndarray]:
    """(D, x): spectral differentiation matrix on N Chebyshev points with
    p' = D·p for polynomial values p at x (≙ ``ChebyshevDiffMatrix``)."""
    n = N - 1
    xc = chebyshev_points(N)  # on [-1, 1]
    c = np.ones(N)
    c[0] = c[n] = 2.0
    sign = np.where((np.arange(N)) % 2 == 0, 1.0, -1.0)
    w = c * sign  # Trefethen weights
    X = xc[:, None] - xc[None, :]
    D = (w[:, None] / w[None, :]) / (X + np.eye(N))
    D = D - np.diag(D.sum(axis=1))
    D = D * (2.0 / (b - a))
    x = a + (xc + 1.0) * (b - a) / 2.0
    return D, x
