"""Randomized SVD (Halko-Martinsson-Tropp) — ≙ ``nla/svd.hpp``.

TPU design notes:

- The sketch ``Y = A·Omegaᵀ`` uses the counter-based JLT, so under GSPMD the
  test matrix is realized shard-locally and never communicated (invariant P5).
- Power iteration and QR re-orthonormalization are large tall-skinny
  matmuls/QRs: XLA maps the matmuls to the MXU and (for sharded A) inserts
  the reduce-scatter/all-gather schedule the reference hand-codes in
  Elemental (``sketch/dense_transform_Elemental_mc_mr.hpp:179,302,599``).
- The trailing small factorization (s×s / n×s) mirrors the reference's
  rank-replicated ``[*,*]`` matrices: it is computed replicated.
- Everything is jit-compatible: static shapes, ``lax.fori_loop`` for the
  iteration count.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from .. import guard
from ..core.context import SketchContext
from ..core.matrices import gaussian_matrix
from ..core.params import Params
from ..parallel.mesh import fully_replicated
from ..resilient.chunked import ChunkedSolver
from ..sketch.base import Dimension
from ..sketch.dense import JLT

__all__ = [
    "SVDParams",
    "power_iteration",
    "approximate_svd",
    "approximate_svd_chunked",
    "approximate_symmetric_svd",
    "streaming_approximate_svd",
    "synthetic_lowrank_blocks",
    "gram_orth",
]


@dataclass
class SVDParams(Params):
    """≙ ``nla/svd.hpp:22-48`` (``approximate_svd_params_t``)."""

    oversampling_ratio: int = 2
    oversampling_additive: int = 0
    num_iterations: int = 0
    skip_qr: bool = False


def gram_orth(Y, passes: int = 2):
    """Orthonormalize the columns of tall-skinny ``Y`` via its Gram matrix.

    TPU-native replacement for the reference's distributed Householder QR /
    TSQR (``El::qr::ExplicitUnitary`` inside ``PowerIteration``,
    ``nla/svd.hpp:105-148``): per pass, ``G = YᵀY`` (one sharded matmul +
    psum), a replicated s×s ``eigh``, and ``Y ← Y·V·diag(lam^-1/2)``.  All
    heavy ops are MXU matmuls that GSPMD shards with Y; nothing tall is ever
    gathered (Householder QR would force a gather — JAX rejects sharded QR).
    Two passes give CholeskyQR2-grade orthogonality; the eigh (instead of
    Cholesky) keeps rank-deficient Y (sketches of exactly-low-rank A) from
    producing NaNs: clamped directions come out with tiny norm and are
    dropped by the rank-k truncation downstream.
    """
    for _ in range(passes):
        # precision='highest' is load-bearing on BOTH products: the TPU
        # MXU default truncates f32 operands to bf16 mantissas, which
        # caps the achievable orthogonality at ~2e-3 no matter how many
        # passes run (caught by tests/test_pallas_hw.py round 3).
        G = fully_replicated(jnp.dot(Y.T, Y, precision="highest"))
        lam, V = jnp.linalg.eigh(G)
        eps = jnp.asarray(jnp.finfo(Y.dtype).eps, G.dtype)
        floor = jnp.maximum(lam[-1], 0) * eps * G.shape[0]
        scale = jnp.where(lam > floor, jax.lax.rsqrt(jnp.maximum(lam, floor)), 0.0)
        Y = jnp.dot(Y, V * scale[None, :], precision="highest")
    return Y


_orth = gram_orth


def _sketch_size(k: int, params: SVDParams, n: int, m: int | None = None):
    """Validated (k, s): oversampled sketch width clamped to n
    (≙ ``nla/svd.hpp`` sizing, shared by all three SVD entry points)."""
    k = int(k)
    lim = n if m is None else min(m, n)
    if k > lim:
        raise ValueError(f"rank {k} exceeds min matrix dimension {lim}")
    s = min(k * params.oversampling_ratio + params.oversampling_additive, n)
    return k, max(s, k)


def power_iteration(A, Q, num_iterations: int, orthogonalize: bool = True):
    """Subspace iteration ``Q <- orth((A·Aᵀ)·Q)``, repeated.

    ≙ ``PowerIteration`` (``nla/svd.hpp:71-149``): the reference's four
    orientation variants collapse to this one (pass ``A.T`` for the adjoint
    flavor).  ``orthogonalize`` toggles the per-step QR (``ortho`` flag).
    """
    if num_iterations <= 0:
        return Q

    def body(_, Q):
        Q = A @ (A.T @ Q)
        return _orth(Q) if orthogonalize else Q

    return lax.fori_loop(0, num_iterations, body, Q)


def approximate_svd_chunked(
    A,
    rank: int,
    context: SketchContext,
    params: SVDParams | None = None,
) -> ChunkedSolver:
    """Chunkable randomized SVD: the power-iteration sweeps (the long part
    for ``num_iterations > 0``) run as jitted ≤ k-step segments whose state
    (iteration counter + current basis Y) checkpoints between chunks; the
    sketch in ``init_state`` is counter-based (JLT), so a resumed process
    rebuilds the identical test matrix and the resumed run is bit-identical
    to the uninterrupted chunked run.  ``extract_result`` performs the
    trailing QR → small SVD → truncate of :func:`approximate_svd`.
    """
    params = params or SVDParams()
    if not hasattr(A, "todense"):  # keep BCOO sparse inputs as-is
        A = jnp.asarray(A)
    m, n = A.shape
    k, s = _sketch_size(rank, params, n, m)
    niter = max(params.num_iterations, 0)
    orthogonalize = not params.skip_qr

    def init_state():
        # Q = A·Omegaᵀ — rowwise JLT sketch (nla/svd.hpp:255-257).
        omega = JLT(n, s, context)
        return dict(
            it=jnp.zeros((), jnp.int32),
            Y=omega.apply(A, Dimension.ROWWISE),
        )

    # A enters as an ARGUMENT (dense array or BCOO pytree) so jit
    # references a device buffer instead of baking A into the program.
    @partial(jax.jit, static_argnames=("num_iters",))
    def _chunk(st, A, num_iters: int):
        stop = jnp.minimum(st["it"] + num_iters, niter)

        def cond(c):
            return c["it"] < stop

        def body(c):
            Y = A @ (A.T @ c["Y"])
            return dict(it=c["it"] + 1, Y=_orth(Y) if orthogonalize else Y)

        return lax.while_loop(cond, body, st)

    def step_chunk(st, num_iters: int):
        return _chunk(st, A, num_iters)

    def extract_result(st):
        Y = st["Y"]
        # The power-iteration body already ends orthonormalized unless
        # skip_qr, so only orthonormalize here when the loop didn't.
        Q = Y if (niter > 0 and orthogonalize) else _orth(Y)

        # B = Aᵀ·Q (n, s); small SVD; rotate back (nla/svd.hpp:266-285).
        # Both products pinned: the MXU default would put ~2e-3 (bf16)
        # error into the singular values (via B) and U's orthogonality
        # (via the rotation) on hardware.  The power-iteration sweeps keep
        # the fast default — they only steer the subspace.
        # (BCOO has no precision knob and does not ride the MXU bf16 path —
        # its matmul keeps the sparse dispatch.)
        AtQ = A.T @ Q if hasattr(A, "todense") else jnp.dot(
            A.T, Q, precision="highest"
        )
        B = fully_replicated(AtQ)
        W, sv, Zt = jnp.linalg.svd(B, full_matrices=False)  # B = W·sv·Zt
        # A ≈ Q·Bᵀ = (Q·Ztᵀ)·diag(sv)·Wᵀ
        U = jnp.dot(Q, Zt.T, precision="highest")
        return U[:, :k], sv[:k], W[:, :k]

    return ChunkedSolver(
        init_state=init_state,
        step_chunk=step_chunk,
        extract_result=extract_result,
        is_done=lambda st: int(st["it"]) >= niter,
        iteration=lambda st: int(st["it"]),
        kind="approximate_svd",
    )


def approximate_svd(
    A,
    rank: int,
    context: SketchContext,
    params: SVDParams | None = None,
    *,
    return_info: bool = False,
):
    """Randomized truncated SVD: returns ``(U, s, V)`` with
    ``A ≈ U @ diag(s) @ V.T``, U: (m, rank), V: (n, rank).

    ≙ ``ApproximateSVD`` (``nla/svd.hpp:222-318``): JLT sketch of the row
    space → power iteration → QR → small SVD → truncate.  One chunk of the
    full sweep budget through :func:`approximate_svd_chunked`.

    Guarding (``SKYLARK_GUARD``, on by default): the factors are certified
    posteriorly (``guard.certify_svd`` — finiteness + one-matvec residual
    check on the leading triplet); a failed certificate climbs the ladder
    (fresh-seed resketch → grown oversampling → dense ``jnp.linalg.svd``
    fallback).  Attempt 0 reuses the caller's context, so healthy runs are
    bit-identical to the unguarded path.  ``return_info=True`` returns
    ``((U, s, V), info)`` with the attempts in ``info["recovery"]``.
    """
    params = params or SVDParams()

    def run(ctx, p):
        sol = approximate_svd_chunked(A, rank, ctx, p)
        st = sol.step_chunk(sol.init_state(), max(p.num_iterations, 1))
        return sol.extract_result(st)

    # Under an enclosing jit trace the host-side certificate reads and
    # ladder control flow cannot run — emit the plain unguarded graph.
    if not guard.enabled() or guard.is_traced(A):
        out = run(context, params)
        if return_info:
            report = guard.RecoveryReport.disabled("randomized_svd")
            return out, {"recovery": report.to_dict()}
        return out

    m, n = A.shape
    report = guard.RecoveryReport(stage="randomized_svd")
    retries = guard.max_retries()
    out = None
    for i in range(retries + 1):
        if i == 0:
            action, ctx, p = "initial", context, params
        elif i == 1:
            action, ctx, p = "resketch", guard.derived_context(context, i), params
        else:
            # Grow the sketch width geometrically through the additive
            # oversampling term (clamped to n by _sketch_size).
            action, ctx = "grow", guard.derived_context(context, i)
            p = replace(
                params,
                oversampling_additive=params.oversampling_additive
                + rank * (2 ** (i - 1)),
            )
        U, sv, V = run(ctx, p)
        cert = guard.certify_svd(A, U, sv, V)
        _, width = _sketch_size(rank, p, n, m)
        report.record(
            action, verdict=cert.verdict, detail=cert.detail,
            sketch_size=width,
        )
        if cert.ok:
            report.recovered = i > 0
            out = (U, sv, V)
            break
    if out is None:
        Ad = A.todense() if hasattr(A, "todense") else A
        Uf, svf, Vtf = jnp.linalg.svd(jnp.asarray(Ad), full_matrices=False)
        out = (Uf[:, :rank], svf[:rank], Vtf[:rank].T)
        report.record(
            "fallback", verdict=guard.FALLBACK, detail="dense jnp.linalg.svd"
        )
        report.recovered = True
    if return_info:
        return out, {"recovery": report.to_dict()}
    return out


def approximate_symmetric_svd(
    A,
    rank: int,
    context: SketchContext,
    params: SVDParams | None = None,
):
    """Randomized eigendecomposition of symmetric A: ``(V, lam)`` with
    ``A ≈ V @ diag(lam) @ V.T`` (eigenvalues sorted by |lam| descending).

    ≙ ``ApproximateSymmetricSVD`` (``nla/svd.hpp:321-392``): explicit
    Gaussian test matrix, subspace iteration, Schur-Rayleigh-Ritz step
    (the reference's ``HermitianEig`` on the compressed ``QᵀAQ``).
    """
    params = params or SVDParams()
    if not hasattr(A, "todense"):
        A = jnp.asarray(A)
    n = A.shape[0]
    k, s = _sketch_size(rank, params, n)

    omega = JLT(n, s, context)
    Y = omega.apply(A, Dimension.ROWWISE)  # A·Omegaᵀ (symmetric A)
    Y = power_iteration(A, Y, params.num_iterations, not params.skip_qr)
    Q = Y if (params.num_iterations > 0 and not params.skip_qr) else _orth(Y)

    # Rayleigh-Ritz on the subspace (≙ nla/svd.hpp:360-380); pinned —
    # T's error lands directly in the eigenvalues and V's orthogonality.
    AQ = A @ Q if hasattr(A, "todense") else jnp.dot(
        A, Q, precision="highest"
    )
    T = fully_replicated(jnp.dot(Q.T, AQ, precision="highest"))
    T = (T + T.T) / 2
    lam, W = jnp.linalg.eigh(T)
    order = jnp.argsort(-jnp.abs(lam))
    lam = lam[order][:k]
    V = jnp.dot(Q, W, precision="highest")[:, order[:k]]
    return V, lam


# ---------------------------------------------------------------------------
# Streaming (matrix-free) randomized SVD — the n=1e7-row regime.
#
# ≙ the scale `skylark_svd --profile` exists for (nla/skylark_svd.cpp:37-60):
# A too large for one memory, processed in row panels.  The reference's
# answer is Elemental's distributed storage; on a single TPU chip the
# counter-RNG design gives a better one — row blocks are *regenerated* (or
# re-streamed) per sweep inside one compiled program, so only one (B, n)
# block plus (n, s)/(s, s) accumulators are ever resident.  This is the
# same memory-bounded pattern as ml's large_scale_kernel_ridge.


def streaming_approximate_svd(
    block_fn,
    shape: tuple[int, int],
    rank: int,
    context: SketchContext,
    params: SVDParams | None = None,
    block_rows: int = 65536,
    materialize_u: bool = False,
    mesh=None,
):
    """Randomized truncated SVD of a row-streamed A (m, n).

    With ``mesh`` (a ``jax.sharding.Mesh`` with Auto axes), each panel is
    sharded over the mesh's row axis (≙ the ``[VC,*]`` long-dimension
    distribution, P2): panel generation and the panel matmuls run
    distributed, and GSPMD inserts the psum for the small replicated
    accumulators — the streamed schedule composes with multi-chip without
    code changes in ``block_fn``.  Explicit-axes meshes are rejected (the
    accumulator contractions would each need an ``out_sharding``).

    ``block_fn(start_row, rows)`` returns the (rows, n) panel of A; it must
    be jit-traceable with a traced ``start_row`` (counter-generated
    matrices and sharded arrays qualify; see
    :func:`synthetic_lowrank_blocks`), and must return *bit-identical*
    panels every time it is called — it is re-traced into more than one
    compiled program, and the whitening step amplifies any cross-program
    drift by 1/σ_min (avoid default-precision matmuls inside it).  Each
    sweep re-requests every panel — O(q+2) passes over A, O(B·n + n·s)
    resident memory.

    Returns ``(u_block, s, V)`` where ``u_block(i)`` yields rows
    ``[i·B, (i+1)·B)`` of U (the factored form keeps U off-memory for huge
    m); with ``materialize_u=True`` the first element is U itself (m, k).

    Math ≙ ``ApproximateSVD`` with explicit Gaussian test matrix: sweeps of
    ``W ← Aᵀ(A·Ω)`` with Gram orthonormalization (power iteration), then a
    fused pass accumulating ``G = YᵀY`` and ``M = YᵀA`` (Y = A·Ω), a second
    streamed whitening pass (CholeskyQR2), and a small SVD of ``B = QᵀA``.

    f32 note: with ``num_iterations=0`` on a noisy spectrum the Gram
    whitening's f32 error mixes signal into the oversampling directions
    and the rank-k truncation can lose real signal (measured ~0.3 relative
    sv error on hardware); for that reason the streaming path defaults to
    ``num_iterations=1`` when ``params`` is omitted (pass explicit params
    to override).
    """
    params = params or SVDParams(num_iterations=1)
    if mesh is not None and any(
        t == jax.sharding.AxisType.Explicit
        for t in getattr(mesh, "axis_types", ())
    ):
        raise ValueError(
            "streaming_approximate_svd needs an Auto-axes mesh "
            "(make_mesh(..., explicit=False)); explicit typed-sharding "
            "would require out_sharding on every accumulator contraction"
        )
    m, n = shape
    k, s = _sketch_size(rank, params, n, m)
    if block_rows <= 0:
        raise ValueError(f"block_rows must be positive, got {block_rows}")
    if m % block_rows:
        raise ValueError(f"m={m} not divisible by block_rows={block_rows}")
    nblocks = m // block_rows

    # Accumulator dtype follows the panels (f64 panels → f64 accumulators
    # and eps — the x64 parity path must not silently demote to f32).
    panel_dtype = jax.eval_shape(
        lambda s0: block_fn(s0, block_rows),
        jax.ShapeDtypeStruct((), jnp.int32),
    ).dtype
    acc = jnp.promote_types(panel_dtype, jnp.float32)

    Om = gaussian_matrix(context, (n, s), dtype=acc)

    def _shard_panel(Ab):
        """Row-shard a panel over the mesh (no-op without a mesh)."""
        if mesh is None:
            return Ab
        from ..parallel.mesh import constrain_rows

        return constrain_rows(Ab, mesh)

    def _panel_y(Ab, Om):
        """Y panel = A_b·Ω at full f32 precision.  'highest' is load-
        bearing: the whitener amplifies Y errors by 1/σ_min(kept), and Y
        must be numerically IDENTICAL between the factor program and
        ``u_block``'s separately-compiled program — default-precision
        (bf16-pass) matmuls can differ across compilations, which showed
        up as O(1) orthogonality loss in U on real hardware."""
        return jnp.dot(Ab, Om.astype(Ab.dtype), precision="highest")

    def _sweep(Om):
        """One power pass: Aᵀ(A·Ω) accumulated over row panels.  Default
        matmul precision — the sweep only steers the subspace (any Ω
        works); the resulting Omq is computed once and reused as an array,
        so the cross-program consistency that forces ``_panel_y`` to
        'highest' elsewhere does not apply here."""

        def body(i, W):
            Ab = _shard_panel(block_fn(i * block_rows, block_rows))
            return W + jnp.dot(
                Ab.T, Ab @ Om.astype(Ab.dtype),
                preferred_element_type=acc,
            )

        return lax.fori_loop(0, nblocks, body, jnp.zeros((n, s), acc))

    @jax.jit
    def _power_and_factor():
        W = Om
        for _ in range(max(params.num_iterations, 0)):
            # skip_qr ≙ the reference's ortho flag: raw power sweeps
            # (overflow-prone for spread spectra — the user's choice).
            W = _sweep(W) if params.skip_qr else _orth(_sweep(W))
        Omq = W if params.num_iterations > 0 else Om

        def body(i, carry):
            G, M = carry
            Ab = _shard_panel(block_fn(i * block_rows, block_rows))
            Yb = _panel_y(Ab, Omq)
            G = G + jnp.dot(
                Yb.T, Yb, precision="highest",
                preferred_element_type=acc,
            )
            M = M + jnp.dot(
                Yb.T, Ab, precision="highest",
                preferred_element_type=acc,
            )
            return G, M

        G, M = lax.fori_loop(
            0,
            nblocks,
            body,
            (jnp.zeros((s, s), acc), jnp.zeros((s, n), acc)),
        )
        # Whiten: Q = (Y·T1)·T2, both factors eigh-based V·lam^{-1/2}.
        def whiten(G, rel_floor):
            lam, V = jnp.linalg.eigh(G)
            floor = jnp.maximum(lam[-1], 0) * rel_floor
            scale = jnp.where(
                lam > floor, jax.lax.rsqrt(jnp.maximum(lam, floor)), 0.0
            )
            return V * scale[None, :]

        # Stage 1: loose floor (4·eps) — keep marginal directions whose
        # Gram eigenvalues are only a few× the f32 representation noise;
        # stage 2 either repairs or rejects them.
        eps = jnp.finfo(acc).eps
        T1 = whiten(G, 4.0 * eps)  # (s, s)
        # Stage 2 (streamed CholeskyQR2): one-pass Gram whitening leaves
        # ~eps·cond(G) orthogonality error — O(1) in f32 when Y mixes
        # signal and noise-level directions.  Re-accumulate the Gram of
        # the *whitened* panels: genuine directions land near 1 and are
        # re-whitened exactly; directions whose stage-1 estimate was pure
        # representation noise land far below 1 and are dropped (0.25
        # reliability floor).  Exactly-rank-deficient A never reaches
        # stage 2 (true zero eigenvalues are below even the loose floor).
        def body2(i, G2):
            Ab = _shard_panel(block_fn(i * block_rows, block_rows))
            Qb = jnp.dot(
                _panel_y(Ab, Omq), T1.astype(Ab.dtype), precision="highest"
            )
            return G2 + jnp.dot(
                Qb.T, Qb, precision="highest",
                preferred_element_type=acc,
            )

        G2 = lax.fori_loop(0, nblocks, body2, jnp.zeros((s, s), acc))
        T2 = whiten(G2, 0.25)
        # CRITICAL: T1 and T2 stay FACTORED.  T1's columns span orders of
        # magnitude; forming T1·T2 mixes those scales before the O(1)
        # whitening of Y·T1 happens, and the associativity error destroys
        # Q's orthonormality.  Apply left-to-right: ((Y·T1)·T2)·Ub.
        # precision='highest' on the small factor products too: a
        # default-precision (bf16-mantissa) rot2 alone puts ~4e-3 of
        # non-orthogonality into U on hardware (round-3 hw guard).
        B = jnp.dot(
            T2.T, jnp.dot(T1.T, M, precision="highest"), precision="highest"
        )  # = Qᵀ·A  (s, n)
        Ub, sv, Vt = jnp.linalg.svd(B, full_matrices=False)
        rot2 = jnp.dot(T2, Ub[:, :k], precision="highest")  # (Y·T1)·rot2 = U
        return Omq, T1, rot2, sv[:k], Vt[:k].T

    Omq, T1, rot2, sv, V = _power_and_factor()

    @jax.jit
    def u_block_traced(start):
        Ab = _shard_panel(block_fn(start, block_rows))
        Q1 = jnp.dot(_panel_y(Ab, Omq), T1.astype(Ab.dtype), precision="highest")
        return jnp.dot(Q1, rot2.astype(Ab.dtype), precision="highest")

    def u_block(i: int):
        """Rows [i·block_rows, (i+1)·block_rows) of U."""
        return u_block_traced(i * block_rows)

    if materialize_u:
        U = jnp.concatenate([u_block(i) for i in range(nblocks)], axis=0)
        return U, sv, V
    return u_block, sv, V


def synthetic_lowrank_blocks(
    context: SketchContext,
    m: int,
    n: int,
    r: int,
    noise: float = 0.0,
    dtype=jnp.float32,
    decay: float = 1.0,
):
    """Jit-traceable row-panel generator for A = L·diag(w)·Rᵀ + noise·E,
    with L (m, r), R (n, r), E (m, n) counter-generated (any panel is a
    window of the logical stream — ``core/random.py::sample_window``) and
    ``w[j] = decay^j``.  ≙ the synthetic ``--profile`` matrix of
    ``nla/skylark_svd.cpp:37-60``, but never materialized.
    """
    from ..core.random import sample_window

    base_L = context.reserve(m * r)
    base_E = context.reserve(m * n)
    R = gaussian_matrix(context, (n, r), dtype=dtype)
    wdtype = jnp.promote_types(dtype, jnp.float32)
    w = jnp.asarray(decay, wdtype) ** jnp.arange(r)
    Rw = (R * w[None, :].astype(dtype)).T  # (r, n)

    def block_fn(start_row, rows: int):
        Lb = sample_window(
            "normal", context.seed, base_L, (m, r),
            offset=(start_row, 0), shape=(rows, r), dtype=dtype,
        )
        # highest: panels must be BIT-IDENTICAL across separately compiled
        # programs (streaming_approximate_svd's contract) — a default-
        # precision matmul can fuse differently per program and break it.
        Ab = jnp.dot(Lb, Rw, precision="highest")
        if noise:
            Eb = sample_window(
                "normal", context.seed, base_E, (m, n),
                offset=(start_row, 0), shape=(rows, n), dtype=dtype,
            )
            Ab = Ab + jnp.asarray(noise, dtype) * Eb
        return Ab

    return block_fn
