"""Randomized SVD (Halko-Martinsson-Tropp) — ≙ ``nla/svd.hpp``.

TPU design notes:

- The sketch ``Y = A·Omegaᵀ`` uses the counter-based JLT, so under GSPMD the
  test matrix is realized shard-locally and never communicated (invariant P5).
- Power iteration and QR re-orthonormalization are large tall-skinny
  matmuls/QRs: XLA maps the matmuls to the MXU and (for sharded A) inserts
  the reduce-scatter/all-gather schedule the reference hand-codes in
  Elemental (``sketch/dense_transform_Elemental_mc_mr.hpp:179,302,599``).
- The trailing small factorization (s×s / n×s) mirrors the reference's
  rank-replicated ``[*,*]`` matrices: it is computed replicated.
- Everything is jit-compatible: static shapes, ``lax.fori_loop`` for the
  iteration count.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax import lax

from ..core.context import SketchContext
from ..core.params import Params
from ..parallel.mesh import fully_replicated
from ..sketch.base import Dimension
from ..sketch.dense import JLT

__all__ = [
    "SVDParams",
    "power_iteration",
    "approximate_svd",
    "approximate_symmetric_svd",
    "gram_orth",
]


@dataclass
class SVDParams(Params):
    """≙ ``nla/svd.hpp:22-48`` (``approximate_svd_params_t``)."""

    oversampling_ratio: int = 2
    oversampling_additive: int = 0
    num_iterations: int = 0
    skip_qr: bool = False


def gram_orth(Y, passes: int = 2):
    """Orthonormalize the columns of tall-skinny ``Y`` via its Gram matrix.

    TPU-native replacement for the reference's distributed Householder QR /
    TSQR (``El::qr::ExplicitUnitary`` inside ``PowerIteration``,
    ``nla/svd.hpp:105-148``): per pass, ``G = YᵀY`` (one sharded matmul +
    psum), a replicated s×s ``eigh``, and ``Y ← Y·V·diag(lam^-1/2)``.  All
    heavy ops are MXU matmuls that GSPMD shards with Y; nothing tall is ever
    gathered (Householder QR would force a gather — JAX rejects sharded QR).
    Two passes give CholeskyQR2-grade orthogonality; the eigh (instead of
    Cholesky) keeps rank-deficient Y (sketches of exactly-low-rank A) from
    producing NaNs: clamped directions come out with tiny norm and are
    dropped by the rank-k truncation downstream.
    """
    for _ in range(passes):
        G = fully_replicated(Y.T @ Y)
        lam, V = jnp.linalg.eigh(G)
        eps = jnp.asarray(jnp.finfo(Y.dtype).eps, G.dtype)
        floor = jnp.maximum(lam[-1], 0) * eps * G.shape[0]
        scale = jnp.where(lam > floor, jax.lax.rsqrt(jnp.maximum(lam, floor)), 0.0)
        Y = Y @ (V * scale[None, :])
    return Y


_orth = gram_orth


def power_iteration(A, Q, num_iterations: int, orthogonalize: bool = True):
    """Subspace iteration ``Q <- orth((A·Aᵀ)·Q)``, repeated.

    ≙ ``PowerIteration`` (``nla/svd.hpp:71-149``): the reference's four
    orientation variants collapse to this one (pass ``A.T`` for the adjoint
    flavor).  ``orthogonalize`` toggles the per-step QR (``ortho`` flag).
    """
    if num_iterations <= 0:
        return Q

    def body(_, Q):
        Q = A @ (A.T @ Q)
        return _orth(Q) if orthogonalize else Q

    return lax.fori_loop(0, num_iterations, body, Q)


def approximate_svd(
    A,
    rank: int,
    context: SketchContext,
    params: SVDParams | None = None,
):
    """Randomized truncated SVD: returns ``(U, s, V)`` with
    ``A ≈ U @ diag(s) @ V.T``, U: (m, rank), V: (n, rank).

    ≙ ``ApproximateSVD`` (``nla/svd.hpp:222-318``): JLT sketch of the row
    space → power iteration → QR → small SVD → truncate.
    """
    params = params or SVDParams()
    if not hasattr(A, "todense"):  # keep BCOO sparse inputs as-is
        A = jnp.asarray(A)
    m, n = A.shape
    k = int(rank)
    if k > min(m, n):
        raise ValueError(f"rank {k} exceeds min(A.shape) = {min(m, n)}")
    s = min(k * params.oversampling_ratio + params.oversampling_additive, n)
    s = max(s, k)

    # Q = A·Omegaᵀ — rowwise JLT sketch (nla/svd.hpp:255-257).
    omega = JLT(n, s, context)
    Y = omega.apply(A, Dimension.ROWWISE)

    # Power iteration on the sketched basis (nla/svd.hpp:260);
    # its body already ends orthonormalized unless skip_qr, so only
    # orthonormalize here when the loop didn't.
    Y = power_iteration(A, Y, params.num_iterations, not params.skip_qr)
    Q = Y if (params.num_iterations > 0 and not params.skip_qr) else _orth(Y)

    # B = Aᵀ·Q (n, s); small SVD; rotate back (nla/svd.hpp:266-285).
    B = fully_replicated(A.T @ Q)
    W, sv, Zt = jnp.linalg.svd(B, full_matrices=False)  # B = W·sv·Zt
    # A ≈ Q·Bᵀ = (Q·Ztᵀ)·diag(sv)·Wᵀ
    U = Q @ Zt.T
    return U[:, :k], sv[:k], W[:, :k]


def approximate_symmetric_svd(
    A,
    rank: int,
    context: SketchContext,
    params: SVDParams | None = None,
):
    """Randomized eigendecomposition of symmetric A: ``(V, lam)`` with
    ``A ≈ V @ diag(lam) @ V.T`` (eigenvalues sorted by |lam| descending).

    ≙ ``ApproximateSymmetricSVD`` (``nla/svd.hpp:321-392``): explicit
    Gaussian test matrix, subspace iteration, Schur-Rayleigh-Ritz step
    (the reference's ``HermitianEig`` on the compressed ``QᵀAQ``).
    """
    params = params or SVDParams()
    if not hasattr(A, "todense"):
        A = jnp.asarray(A)
    n = A.shape[0]
    k = int(rank)
    if k > n:
        raise ValueError(f"rank {k} exceeds matrix dimension {n}")
    s = min(k * params.oversampling_ratio + params.oversampling_additive, n)
    s = max(s, k)

    omega = JLT(n, s, context)
    Y = omega.apply(A, Dimension.ROWWISE)  # A·Omegaᵀ (symmetric A)
    Y = power_iteration(A, Y, params.num_iterations, not params.skip_qr)
    Q = Y if (params.num_iterations > 0 and not params.skip_qr) else _orth(Y)

    # Rayleigh-Ritz on the subspace (≙ nla/svd.hpp:360-380).
    T = fully_replicated(Q.T @ (A @ Q))
    T = (T + T.T) / 2
    lam, W = jnp.linalg.eigh(T)
    order = jnp.argsort(-jnp.abs(lam))
    lam = lam[order][:k]
    V = (Q @ W)[:, order[:k]]
    return V, lam
