"""Least-squares solvers: exact l2 paths + sketch-and-solve.

- ``exact_least_squares`` ≙ the ``regression_solver_t`` l2 specializations
  (``algorithms/regression/linearl2_regression_solver_Elemental.hpp:23-631``)
  with the tag dispatch (``qr/sne/ne/svd_l2_solver_tag``) as a string arg.
- ``approximate_least_squares`` ≙ sketch-and-solve
  (``nla/least_squares.hpp:42-184`` + ``sketched_regression_solver_Elemental
  .hpp:29-104``): sketch A and B columnwise once, exact-solve the small
  problem.  Like the reference, defaults to FJLT (sketch size 4·width) for
  dense inputs; sparse (BCOO) inputs auto-select CWT (input-sparsity time).

TPU notes: QR/Cholesky of the (sketched) s×n problem is replicated-small
(≙ the reference's ``[*,*]`` matrices); the sketch itself is the sharded
MXU-heavy op.  All functions are jit-compatible.
"""

from __future__ import annotations

from dataclasses import dataclass, replace

import jax
import jax.numpy as jnp
from jax.scipy.linalg import cho_factor, cho_solve, solve_triangular

from .. import guard, plans, telemetry
from ..core.context import SketchContext
from ..core.params import Params
from ..sketch.base import Dimension, create_sketch

__all__ = [
    "LeastSquaresParams",
    "exact_least_squares",
    "approximate_least_squares",
    "streaming_least_squares",
]


@dataclass
class LeastSquaresParams(Params):
    """≙ ``nla/least_squares.hpp`` params: sketch choice + size."""

    sketch_type: str | None = None  # None → FJLT dense / CWT sparse
    sketch_size: int | None = None  # default 4 * width (least_squares.hpp:60)


def _svd_lstsq(A, B):
    """Pseudoinverse path shared by ``alg="svd"`` and the guarded ``ne``
    fallback (rank-deficiency-proof)."""
    U, s, Vt = jnp.linalg.svd(A, full_matrices=False)
    cutoff = jnp.finfo(A.dtype).eps * max(A.shape) * s[0]
    sinv = jnp.where(s > cutoff, 1.0 / s, 0.0)
    return Vt.T @ (sinv[:, None] * (U.T @ B))


def exact_least_squares(A, B, alg: str = "qr"):
    """Solve ``min_X ||A X - B||_F`` for tall A; returns X (n, k).

    ``alg`` ∈ {"qr", "sne", "ne", "svd"} ≙ the reference's
    ``qr/sne/ne/svd_l2_solver_tag`` solver tags.

    ``ne`` note: ``cho_factor`` on a singular/indefinite Gram matrix
    returns NaNs WITHOUT error.  Under the guard layer (default) a
    non-finite factor reroutes to the ``svd`` pseudoinverse path (inside
    jit: a ``lax.cond`` branch, so the function stays jit-compatible);
    with ``SKYLARK_GUARD=0`` the eager path raises
    ``NumericalHealthError`` instead of returning silent NaNs.
    """
    A = jnp.asarray(A)
    B = jnp.asarray(B)
    squeeze = B.ndim == 1
    if squeeze:
        B = B[:, None]
    if alg == "qr":
        # Householder QR; X = R⁻¹ Qᵀ B (≙ El::qr::ApplyQ path).
        Q, R = jnp.linalg.qr(A, mode="reduced")
        X = solve_triangular(R, Q.T @ B, lower=False)
    elif alg == "sne":
        # Semi-normal equations: R from QR(A), solve RᵀR X = Aᵀ B
        # (≙ El::qr::ExplicitTS + two triangular solves).
        R = jnp.linalg.qr(A, mode="r")
        Y = solve_triangular(R.T, A.T @ B, lower=True)
        X = solve_triangular(R, Y, lower=False)
    elif alg == "ne":
        # Normal equations via Cholesky (≙ ne_l2_solver_tag).
        G = A.T @ A
        c, low = cho_factor(G)
        AtB = A.T @ B
        finite = jnp.all(jnp.isfinite(c))
        guarded = guard.enabled()
        if isinstance(finite, jax.core.Tracer):
            if guarded:
                X = jax.lax.cond(
                    finite,
                    lambda: cho_solve((c, low), AtB),
                    lambda: _svd_lstsq(A, B),
                )
            else:
                X = cho_solve((c, low), AtB)
        elif bool(finite):
            X = cho_solve((c, low), AtB)
        elif guarded:
            X = _svd_lstsq(A, B)
        else:
            from ..utils.exceptions import NumericalHealthError

            raise NumericalHealthError(
                "cho_factor returned non-finite factors (singular or "
                "indefinite Gram matrix) in exact_least_squares(alg='ne')",
                stage="exact_ls_ne",
            )
    elif alg == "svd":
        # Pseudoinverse through the SVD (≙ svd_l2_solver_tag).
        X = _svd_lstsq(A, B)
    else:
        raise ValueError(f"unknown exact LS alg {alg!r}")
    return X[:, 0] if squeeze else X


def approximate_least_squares(
    A,
    B,
    context: SketchContext,
    params: LeastSquaresParams | None = None,
    alg: str = "qr",
    *,
    route: str | None = None,
    fault_plan=None,
    return_info: bool = False,
):
    """Sketch-and-solve LS: sketch the rows of (A, B), solve exactly.

    ≙ ``ApproximateLeastSquares`` (``nla/least_squares.hpp:42-184``):
    construct S once (columnwise, size s×m), apply to A at build and to B at
    solve (``sketched_regression_solver_Elemental.hpp:60-104``).

    Guarding (``SKYLARK_GUARD``, on by default): each sketch is certified
    (``guard.certify_sketch`` — finiteness + ``cond_est``) and a bad draw
    climbs the recovery ladder (fresh-seed resketch → grow sketch size →
    exact dense ``svd`` solve).  Attempt 0 reuses the caller's context and
    sketch order, so a healthy run returns bit-identical results to the
    unguarded path.  ``fault_plan`` exposes the ladder's injection point
    (``FaultPlan.corrupt_sketch`` — ``nan_at``/``bad_sketch_at`` keyed by
    attempt index).  With ``return_info=True`` returns ``(x, info)`` where
    ``info["recovery"]`` is the :class:`~libskylark_tpu.guard.
    RecoveryReport` dict (``guarded=False`` under ``SKYLARK_GUARD=0``).

    Routing (``SKYLARK_POLICY``, on by default): the call consults
    :func:`~libskylark_tpu.policy.choose_route` with the problem's
    signature.  With no matured profile entry the decision is exactly the
    defaults above (bit-parity contract, ``tests/test_policy.py``); a
    matured entry may reroute to ``blendenpik``/``lsrn``/``refine``/
    ``exact``, shrink the sketch dimension toward the smallest
    certified-OK size, or sketch bf16-first (escalating back to the
    input dtype when attempt 0's certificate is not OK).  ``route`` pins
    the route explicitly (one of ``"sketch"``, ``"refine"``,
    ``"blendenpik"``, ``"lsrn"``, ``"exact"``); pinned ``params`` fields
    always win.  ``info["policy"]`` carries the decision.
    """
    from .. import policy
    from ..policy.decide import LS_ROUTES

    if route is not None and route not in LS_ROUTES:
        raise ValueError(
            f"unknown least-squares route {route!r}; one of {LS_ROUTES}"
        )
    params = params or LeastSquaresParams()
    is_sparse = hasattr(A, "todense")
    if not is_sparse:
        A = jnp.asarray(A)
    B = jnp.asarray(B)
    squeeze = B.ndim == 1
    if squeeze:
        B = B[:, None]
    m, n = A.shape
    guard_on = guard.enabled() and not guard.is_traced(A, B)
    decision = policy.consult(
        "ls",
        m=m,
        n=n,
        targets=B.shape[1],
        dtype=(A.data.dtype.name if is_sparse else A.dtype.name),
        sparse=is_sparse,
        route=route,
        sketch_type=params.sketch_type,
        sketch_size=params.sketch_size,
        guard_on=guard_on,
    )
    s = decision.sketch_size
    stype = decision.sketch_type
    default_size = min(4 * n, m)

    # -- profile-learned reroutes (never taken on an empty store) ------------
    if decision.route == "exact":
        A_dense = A.todense() if is_sparse else A
        X = exact_least_squares(A_dense, B, alg="svd")
        report = (
            guard.RecoveryReport(stage="sketch_and_solve_ls")
            if guard_on
            else guard.RecoveryReport.disabled("sketch_and_solve_ls")
        )
        if guard_on:
            guard.check_finite(X, "exact_ls", report=report)
        out = X[:, 0] if squeeze else X
        info = {"recovery": report.to_dict(), "policy": decision.to_dict()}
        policy.observe(decision, info, default_size=default_size)
        telemetry.run_summary("sketch_and_solve_ls", info)
        return (out, info) if return_info else out
    if decision.route in ("blendenpik", "lsrn"):
        from ..solvers.accelerated import (
            FasterLeastSquaresParams,
            faster_least_squares,
            lsrn_least_squares,
        )

        fls = FasterLeastSquaresParams(sketch_type=params.sketch_type)
        solver = (
            faster_least_squares
            if decision.route == "blendenpik"
            else lsrn_least_squares
        )
        X, rinfo = solver(A, B, context, fls)
        out = X[:, 0] if squeeze else X
        info = dict(rinfo)
        info["policy"] = decision.to_dict()
        policy.observe(decision, info, default_size=default_size)
        telemetry.run_summary("sketch_and_solve_ls", info)
        return (out, info) if return_info else out
    if decision.route == "refine":
        from ..solvers.refine import RefineParams, refine_least_squares

        rp = RefineParams(
            sketch_type=decision.sketch_type,
            sketch_size=decision.sketch_size,
        )
        X, rinfo = refine_least_squares(
            A, B, context, rp, fault_plan=fault_plan
        )
        out = X[:, 0] if squeeze else X
        info = dict(rinfo)
        info["policy"] = decision.to_dict()
        policy.observe(
            decision, info, default_size=default_size,
            refine=rinfo.get("refine"),
        )
        telemetry.run_summary("sketch_and_solve_ls", info)
        return (out, info) if return_info else out

    # Under an enclosing jit trace the host-side certificate reads and
    # ladder control flow cannot run — emit the plain unguarded graph.
    if not guard_on:
        S = create_sketch(stype, m, s, context)
        # Plan-cached applies: repeated sketch-and-solve calls at the same
        # shape (parameter sweeps, restarts) reuse one fused executable.
        SA = plans.apply(S, A, Dimension.COLUMNWISE)
        SB = plans.apply(S, B, Dimension.COLUMNWISE)
        if fault_plan is not None:
            SA = fault_plan.corrupt_sketch(0, SA)
        X = exact_least_squares(SA, SB, alg=alg)
        out = X[:, 0] if squeeze else X
        if return_info:
            report = guard.RecoveryReport.disabled("sketch_and_solve_ls")
            info = {
                "recovery": report.to_dict(),
                "policy": decision.to_dict(),
            }
            telemetry.run_summary("sketch_and_solve_ls", info)
            return out, info
        return out

    def run_guarded(A_in, cast_solve):
        """One trip up the guard ladder; ``cast_solve`` lifts the (narrow)
        sketch output back to B's dtype before certification + solve (the
        small s×n problem always solves at full precision)."""

        def attempt(ctx, s_i, i):
            S = create_sketch(stype, m, s_i, ctx)
            SA = plans.apply(S, A_in, Dimension.COLUMNWISE)
            SB = plans.apply(S, B, Dimension.COLUMNWISE)
            if cast_solve:
                SA = SA.astype(B.dtype)
            if fault_plan is not None:
                SA = fault_plan.corrupt_sketch(i, SA)
            cert = guard.certify_sketch(SA, stage="sketch_and_solve_ls")
            if not cert.ok:
                return None, cert
            X = exact_least_squares(SA, SB, alg=alg)
            if not guard.tree_all_finite(X):
                cert = replace(
                    cert,
                    verdict=guard.RESKETCH,
                    detail="non-finite small-problem solution",
                )
                return None, cert
            return X, cert

        def fallback():
            A_dense = A.todense() if is_sparse else A
            return exact_least_squares(A_dense, B, alg="svd")

        return guard.run_ladder(
            "sketch_and_solve_ls", context, s, m, attempt, fallback
        )

    def _ok0(report):
        attempts = report.to_dict().get("attempts") or []
        return bool(attempts) and attempts[0].get("verdict") == guard.OK

    bf16_note = None
    fp8_note = None
    if decision.compute_dtype == "float8_e4m3fn":
        # fp8-first (one rung below bf16, reached only through a clean
        # bf16 history): the sketch OPERAND is rounded to e4m3 — the
        # rung's precision semantics — then lifted to bf16 so the apply
        # reuses the proven f32-accumulating machinery (on fp8-MXU
        # hardware XLA folds the f8→bf16 convert into the matmul).  The
        # guard certificate checks the lifted sketch; a non-OK attempt 0
        # — or a backend that cannot lower f8 at all — escalates to the
        # input dtype and records ``fp8: fail`` so the policy retires
        # the rung for this key.
        from ..core.precision import fp8_dtype

        X = report = None
        f8 = fp8_dtype()
        if f8 is not None:
            try:
                X, report = run_guarded(
                    A.astype(f8).astype(jnp.bfloat16), True
                )
            except Exception:  # noqa: BLE001 — f8 lowering failure → f32
                X = report = None
        if report is None or not _ok0(report):
            decision.escalated = True
            fp8_note = "fail"
            X, report = run_guarded(A, False)
    elif decision.compute_dtype == "bfloat16":
        # bf16-first: the MXU-heavy sketch runs at bf16 (the
        # f32-accumulable kernel entry points make it nearly free); the
        # guard certificate checks the lifted sketch and a non-OK attempt
        # 0 escalates the whole solve back to the input dtype.
        X, report = run_guarded(A.astype(jnp.bfloat16), True)
        if not _ok0(report):
            decision.escalated = True
            bf16_note = "fail"
            X, report = run_guarded(A, False)
    else:
        X, report = run_guarded(A, False)
    out = X[:, 0] if squeeze else X
    info = {"recovery": report.to_dict(), "policy": decision.to_dict()}
    policy.observe(
        decision, info, default_size=default_size, bf16=bf16_note,
        fp8=fp8_note,
    )
    telemetry.run_summary("sketch_and_solve_ls", info)
    if return_info:
        return out, info
    return out


def streaming_least_squares(
    source,
    nrows: int,
    ncols: int,
    context: SketchContext,
    params: LeastSquaresParams | None = None,
    alg: str = "qr",
    *,
    targets: int = 1,
    sparse: bool = False,
    stream_params=None,
    fault_plan=None,
    partition=None,
):
    """Out-of-core sketch-and-solve LS over ``(A_block, b_block)`` batches.

    The streaming face of :func:`approximate_least_squares`: same sketch
    selection (``sketch_type``/``sketch_size`` from ``params``, defaults
    CWT for sparse streams else JLT — FJLT has no columnwise partial-
    sketch rule), but ``S·A`` / ``S·b`` accumulate per batch through
    ``streaming.sketch_least_squares`` so A never needs to be resident.
    ``nrows``/``ncols`` are A's global shape (rows must be known up front
    to address the sketch's counter stream; ``io.scan_libsvm_dims`` scans
    them in one cheap pass).  ``stream_params`` is a
    :class:`~libskylark_tpu.streaming.StreamParams` (prefetch depth,
    checkpoint/resume).  Returns ``(x, info)``; when guarding is on
    (``SKYLARK_GUARD`` unset or truthy) ``info["recovery"]`` carries the
    guard's :class:`~libskylark_tpu.guard.RecoveryReport` dict — chunk
    replays of NaN-poisoned batches and small-solve fallbacks — and
    ``fault_plan`` (``nan_at``/``bad_sketch_at`` keyed by batch index)
    injects the faults the guard recovers from.

    ``partition`` (a :class:`~libskylark_tpu.streaming.RowPartition`)
    selects the multi-host elastic path: every process of a
    ``jax.distributed`` world calls this with the same arguments, each
    folds only its own row range, and the merged ``(x, info)`` comes
    back identical on every rank (``docs/distributed_streaming.md``).
    """
    from .. import policy, streaming

    params = params or LeastSquaresParams()
    decision = policy.consult(
        "ls_stream",
        m=nrows,
        n=ncols,
        targets=targets,
        dtype="float32",
        sparse=sparse,
        sketch_type=params.sketch_type,
        sketch_size=params.sketch_size,
        guard_on=guard.enabled(),
    )
    s = decision.sketch_size
    stype = decision.sketch_type
    S = create_sketch(stype, nrows, s, context)
    # The decision rides INTO the driver so info["policy"] is present in
    # the ledgered run_summary payload, not appended after it fired (the
    # telemetry acceptance contract: ledgered info keys == returned info
    # keys, and run_summary is the run's terminal ledger event).
    x, info = streaming.sketch_least_squares(
        source, S, ncols=ncols, targets=targets, alg=alg,
        params=stream_params, fault_plan=fault_plan, partition=partition,
        policy_decision=decision.to_dict(),
    )
    seconds = info.get("seconds") or 0.0
    policy.observe(
        decision,
        info,
        default_size=min(4 * ncols, nrows),
        rows_per_s=(info.get("rows", 0) / seconds) if seconds else None,
        batches=info.get("batches"),
    )
    # The driver's own run_summary fired before this observation existed;
    # flush again so the throughput lands in this run's profile write.
    policy.flush("streaming_lsq", info)
    return x, info
