"""Unified telemetry: structured spans, metrics registry, JSONL run ledger.

The observability layer the reference never had (its ``utility/timer.hpp``
macros reduce wall timers over MPI ranks and nothing else): one
process-wide :class:`Registry` of counters/gauges/histograms, nestable
:func:`span` contexts (wall time under the ``PhaseTimer`` sync
discipline, device regions via ``utils.profiling.annotate``), and a
monotonically sequenced JSONL event sink — the *run ledger* — with the
schema ``{ts, seq, pid, kind, name, attrs}``.

Wired through every hot seam: plan-cache hits/misses/compiles
(``plans``), streaming chunk spans + prefetch overlap (``streaming``),
recovery-ladder attempts (``guard``), checkpoint save/restore
(``resilient``), and per-chunk solver progress; every ``(x, info)``
solver entrypoint closes its run with a :func:`run_summary` event.

Gated by ``SKYLARK_TELEMETRY`` (default OFF, read per call): disabled,
every entry point returns before allocating — runs are bit-identical to
a build without this package.  ``SKYLARK_TELEMETRY_DIR`` (or
:func:`configure`, or the CLIs' ``--telemetry-dir``) points the ledger
at a directory; without it events still count in the registry.

End of run: :func:`snapshot` folds the registry with ``plans.stats()``,
the prefetch overlap ratio, and the guard/checkpoint counter groups;
:func:`report` reduces counters min/max/avg over ``jax.distributed``
processes under the same ``process_allgather`` + CRC-signature contract
as ``utils.timer.timer_report``.  See ``docs/observability.md``.
"""

from .config import enabled, ledger_dir
from .ledger import close, configure, emit, event, flush, ledger_path
from .registry import LOCK, REGISTRY, Registry, inc, observe, reset, set_gauge
from .report import report, run_summary, snapshot
from .spans import NOOP_SPAN, Span, span

__all__ = [
    "enabled",
    "ledger_dir",
    "configure",
    "event",
    "emit",
    "ledger_path",
    "flush",
    "close",
    "Registry",
    "REGISTRY",
    "LOCK",
    "inc",
    "set_gauge",
    "observe",
    "reset",
    "span",
    "Span",
    "NOOP_SPAN",
    "snapshot",
    "run_summary",
    "report",
]
