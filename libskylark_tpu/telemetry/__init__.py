"""Unified telemetry: structured spans, metrics registry, JSONL run ledger.

The observability layer the reference never had (its ``utility/timer.hpp``
macros reduce wall timers over MPI ranks and nothing else): one
process-wide :class:`Registry` of counters/gauges/histograms, nestable
:func:`span` contexts (wall time under the ``PhaseTimer`` sync
discipline, device regions via ``utils.profiling.annotate``), and a
monotonically sequenced JSONL event sink — the *run ledger* — with the
schema ``{ts, seq, pid, kind, name, attrs}``.

Wired through every hot seam: plan-cache hits/misses/compiles
(``plans``), streaming chunk spans + prefetch overlap (``streaming``),
recovery-ladder attempts (``guard``), checkpoint save/restore
(``resilient``), and per-chunk solver progress; every ``(x, info)``
solver entrypoint closes its run with a :func:`run_summary` event.

Gated by ``SKYLARK_TELEMETRY`` (default OFF, read per call): disabled,
every entry point returns before allocating — runs are bit-identical to
a build without this package.  ``SKYLARK_TELEMETRY_DIR`` (or
:func:`configure`, or the CLIs' ``--telemetry-dir``) points the ledger
at a directory; without it events still count in the registry.

End of run: :func:`snapshot` folds the registry with ``plans.stats()``,
the prefetch overlap ratio, and the guard/checkpoint counter groups;
:func:`report` reduces counters min/max/avg over ``jax.distributed``
processes under the same ``process_allgather`` + CRC-signature contract
as ``utils.timer.timer_report``.  See ``docs/observability.md``.

The fleet observability plane rides on top: request-scoped traces
minted at serve admission (:mod:`.trace` — TraceContext, the bounded
flight recorder, cross-layer :func:`trace_event` attachment),
``snapshot(fleet=True)`` cross-host aggregation (:mod:`.fleet` —
allgathered registries whose merged counters SUM over ranks, plus the
epoch-fenced ``host-*/progress.jsonl`` ledger fold), and the
Prometheus text exposition (:mod:`.exposition`) the serve ``/metrics``
endpoint and ``skylark-top`` scrape.
"""

from .config import enabled, ledger_dir
from .exposition import prometheus_text
from .fleet import fleet_snapshot, fold_ledgers, merge_snapshots
from .ledger import close, configure, emit, event, flush, ledger_path
from .phases import PHASES, enable_phase_buckets, observe_phase, phases_enabled
from .registry import (
    LOCK,
    REGISTRY,
    Registry,
    enable_buckets,
    inc,
    observe,
    reset,
    set_gauge,
)
from .report import report, run_summary, snapshot
from .slo import observe_slo, reset_slo, slo_report
from .timeline import (
    reset_timeline,
    timeline_state,
    timeline_tick,
    timeline_windows,
)
from .spans import NOOP_SPAN, Span, span
from .trace import (
    RECORDER,
    FlightRecorder,
    TraceContext,
    activate,
    drain_traces,
    dump_traces,
    error_event,
    get_trace,
    is_violating,
    mint,
    trace_enabled,
    trace_event,
    trace_ids,
)
from .trace import finish as finish_trace

__all__ = [
    "enabled",
    "ledger_dir",
    "configure",
    "event",
    "emit",
    "ledger_path",
    "flush",
    "close",
    "Registry",
    "REGISTRY",
    "LOCK",
    "inc",
    "set_gauge",
    "observe",
    "enable_buckets",
    "reset",
    # phase clock + SLO engine + timeline ring
    "PHASES",
    "phases_enabled",
    "observe_phase",
    "enable_phase_buckets",
    "observe_slo",
    "slo_report",
    "reset_slo",
    "timeline_tick",
    "timeline_windows",
    "timeline_state",
    "reset_timeline",
    "span",
    "Span",
    "NOOP_SPAN",
    "snapshot",
    "run_summary",
    "report",
    # tracing + flight recorder
    "TraceContext",
    "FlightRecorder",
    "RECORDER",
    "mint",
    "trace_enabled",
    "is_violating",
    "activate",
    "trace_event",
    "error_event",
    "finish_trace",
    "get_trace",
    "trace_ids",
    "drain_traces",
    "dump_traces",
    # fleet aggregation + exposition
    "merge_snapshots",
    "fold_ledgers",
    "fleet_snapshot",
    "prometheus_text",
]
