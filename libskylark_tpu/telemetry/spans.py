"""Nestable spans: wall time + device regions + ledger events.

A span is the telemetry analogue of one ``PhaseTimer`` phase, and keeps
its sync discipline: assign the span handle's ``result`` inside the
region and the exit path runs ``jax.block_until_ready`` on it before
reading the clock, so the span measures DEVICE time, not dispatch time.
Each span also opens a ``utils.profiling.annotate`` region, so an XProf
trace captured around the run carries the same names as the ledger.

Nesting is tracked per thread: every span records its parent's id (the
``seq`` of the parent's ``span_start`` event) so the ledger reconstructs
the span tree.  ``span(...)`` with telemetry disabled returns a shared
no-op singleton — no allocation, no sync, no events.
"""

from __future__ import annotations

import threading
import time

import jax

from ..utils import profiling
from . import config
from .ledger import event
from .registry import REGISTRY

__all__ = ["span", "Span", "NOOP_SPAN"]

_LOCAL = threading.local()


class _NoopSpan:
    """Shared disabled-path span: accepts ``result`` assignment (ignored,
    never synced) and nests freely."""

    __slots__ = ("result",)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NOOP_SPAN = _NoopSpan()


def _stack() -> list:
    stack = getattr(_LOCAL, "spans", None)
    if stack is None:
        stack = _LOCAL.spans = []
    return stack


class Span:
    """One live span; ``attrs`` may be amended inside the region (the
    ``span_end`` event re-reads them, so late facts — rows folded,
    batches seen — land on the closing record)."""

    def __init__(self, name: str, attrs: dict):
        self.name = name
        self.attrs = attrs
        self.result = None
        self.id = None
        self.seconds = None

    def __enter__(self):
        stack = _stack()
        start_attrs = dict(self.attrs)
        if stack:
            start_attrs["parent"] = stack[-1].id
        start_attrs["depth"] = len(stack)
        self._t0 = time.perf_counter()
        self.id = event("span_start", self.name, start_attrs)
        stack.append(self)
        self._region = profiling.annotate(self.name)
        self._region.__enter__()
        return self

    def __exit__(self, exc_type, exc, tb):
        self._region.__exit__(exc_type, exc, tb)
        if self.result is not None:
            jax.block_until_ready(self.result)
        self.seconds = time.perf_counter() - self._t0
        stack = _stack()
        if stack and stack[-1] is self:
            stack.pop()
        REGISTRY.inc(f"span.{self.name}.calls")
        REGISTRY.inc(f"span.{self.name}.seconds", self.seconds)
        end_attrs = dict(self.attrs)
        end_attrs["span"] = self.id
        end_attrs["seconds"] = round(self.seconds, 6)
        if exc_type is not None:
            end_attrs["error"] = exc_type.__name__
        event("span_end", self.name, end_attrs)
        return False


def span(name: str, **attrs):
    """Open a nestable span (context manager).

    Usage::

        with telemetry.span("stream.chunk", chunk=b0) as sp:
            sp.result = acc        # blocked on at exit (PhaseTimer rule)
            sp.attrs["rows"] = k   # lands on the span_end event

    Disabled (``SKYLARK_TELEMETRY=0``): returns the shared no-op span.
    """
    if not config.enabled():
        return NOOP_SPAN
    return Span(name, attrs)
