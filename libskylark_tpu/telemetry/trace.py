"""Request-scoped tracing and the bounded flight recorder.

A :class:`TraceContext` is minted at serve admission (one per admitted
request) and rides the request end-to-end: coalesce → padded dispatch →
solo-retry → fan-out.  Its event list IS the response's
``trace["events"]`` — the same list object — so everything attached
mid-flight (the batch-dispatch span that links k coalesced requests,
guard-ladder rungs, plan-cache hits/compiles, policy route decisions)
is visible both in the answer the caller receives and in the flight
recorder afterwards.  One batch dispatch mints ONE span id shared by
every request it carried; a solo retry mints a fresh one, so the two
rungs are distinguishable after the fact.

Cross-layer attachment goes through a per-thread *active set*:
:func:`activate` marks the traces the current dispatch serves, and
:func:`trace_event` appends to every active trace.  The seams that
already emit telemetry (``plans/cache.py``, ``guard/ladder.py``,
``policy/record.py``) call :func:`trace_event` next to their ledger
event — with no active trace the call returns before allocating, so
non-serve code paths pay one thread-local read.

The :class:`FlightRecorder` keeps the last ``SKYLARK_TRACE_CAPACITY``
completed traces in a ring PLUS every SLO-violating one (deadline shed,
admission shed, solo-retry, guard escalation, structured errors) in a
larger bounded ring of its own — a quiet server remembers its recent
history, a misbehaving one remembers every incident.  ``drain()`` is
the API pull; error traces are additionally dumped to the run ledger
(kind ``"trace"``) the moment they finish, so a post-mortem needs no
live process.

Everything here rides the ``SKYLARK_TELEMETRY`` gate: disabled,
:func:`mint` returns ``None``, the recorder never sees a record, and no
trace object is allocated anywhere — pinned by
``tests/test_trace.py``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager

from . import config
from .ledger import event, flush
from .registry import LOCK, REGISTRY

__all__ = [
    "TraceContext",
    "FlightRecorder",
    "RECORDER",
    "mint",
    "next_id",
    "trace_enabled",
    "activate",
    "trace_event",
    "error_event",
    "finish",
    "is_violating",
    "get_trace",
    "drain_traces",
    "trace_ids",
    "dump_traces",
]

# Events per trace are bounded so one pathological request (a guard
# ladder that climbs forever, a retry loop) cannot grow its trace
# without bound; the drop is counted on the trace itself.
_MAX_EVENTS = 64

_LOCAL = threading.local()
_SEQ = {"n": 0}

# Statuses that mark a trace SLO-violating: the flight recorder keeps
# ALL of these (not just the last N), because they are exactly the
# answers someone will ask "why?" about after the fact.
VIOLATIONS = ("error", "shed_admission", "shed_deadline")


def _capacity() -> int:
    try:
        return max(8, int(os.environ.get("SKYLARK_TRACE_CAPACITY", "256")))
    except ValueError:
        return 256


def trace_enabled() -> bool:
    """Tracing rides the telemetry gate plus its own ``SKYLARK_TRACE``
    sub-gate (default ON): ``SKYLARK_TRACE=0`` keeps counters/spans/
    ledger but mints no traces — the bench's isolation knob for the
    <5%-QPS tracing-overhead row, and an operator's escape hatch."""
    return config.enabled() and os.environ.get("SKYLARK_TRACE", "1") != "0"


def next_id() -> int:
    """Monotonic id for traces and dispatch spans (shared stream, under
    the registry lock so ids are unique across worker threads)."""
    with LOCK:
        _SEQ["n"] += 1
        return _SEQ["n"]


class TraceContext:
    """One request's trace.  ``events`` aliases the serve entry's
    ``trace["events"]`` list when attached there, so appends land in the
    response envelope and the recorder simultaneously."""

    __slots__ = (
        "trace_id", "op", "key", "request_id", "deadline_ms",
        "t_start", "t_end", "events", "status", "code", "dropped",
        "violation",
    )

    def __init__(self, op, *, key=None, request_id=None, deadline_ms=None,
                 events=None, seq=None):
        pid = os.getpid()
        if seq is None:
            seq = next_id()
        self.trace_id = f"{pid:x}-{seq:08x}"
        self.op = op
        self.key = key
        self.request_id = request_id
        self.deadline_ms = deadline_ms
        self.t_start = time.time()
        self.t_end = None
        self.events = events if events is not None else []
        self.status = None
        self.code = None
        self.dropped = 0
        self.violation = False

    def event(self, kind: str, **attrs) -> None:
        if len(self.events) >= _MAX_EVENTS:
            self.dropped += 1
            return
        self.events.append({"kind": kind, **attrs})

    def to_dict(self) -> dict:
        end = self.t_end if self.t_end is not None else time.time()
        d = {
            "trace_id": self.trace_id,
            "op": self.op,
            "status": self.status,
            "ts": round(self.t_start, 6),
            "ms": round((end - self.t_start) * 1e3, 4),
            "events": list(self.events),
        }
        if self.violation and self.status not in VIOLATIONS:
            d["violation"] = True
        if self.request_id is not None:
            d["request_id"] = self.request_id
        if self.key is not None:
            d["key"] = str(self.key)
        if self.deadline_ms is not None:
            d["deadline_ms"] = self.deadline_ms
        if self.code is not None:
            d["code"] = self.code
        if self.dropped:
            d["events_dropped"] = self.dropped
        return d


def mint(op, *, key=None, request_id=None, deadline_ms=None,
         events=None) -> TraceContext | None:
    """A new trace — or ``None`` (no allocation) with telemetry off."""
    if not trace_enabled():
        return None
    # One lock acquisition for both the id draw and the minted counter:
    # mint sits on the serve admission hot path, where 16 client threads
    # contend with the worker's own counters on the registry LOCK.
    with LOCK:
        _SEQ["n"] += 1
        seq = _SEQ["n"]
        c = REGISTRY.counters
        c["trace.minted"] = c.get("trace.minted", 0) + 1
    return TraceContext(
        op, key=key, request_id=request_id, deadline_ms=deadline_ms,
        events=events, seq=seq,
    )


# -- the per-thread active set ---------------------------------------------


def _active() -> list:
    traces = getattr(_LOCAL, "traces", None)
    if traces is None:
        traces = _LOCAL.traces = []
    return traces


@contextmanager
def activate(traces):
    """Mark ``traces`` (TraceContexts; Nones filtered) as the recipients
    of :func:`trace_event` on this thread for the duration."""
    live = [t for t in traces if t is not None]
    stack = _active()
    stack.append(live)
    try:
        yield live
    finally:
        stack.pop()


def trace_event(kind: str, **attrs) -> None:
    """Append an event to every active trace on this thread.

    The no-trace path is one thread-local read and a truthiness check —
    cheap enough for the plan-cache/guard/policy seams to call
    unconditionally next to their ledger events.
    """
    stack = getattr(_LOCAL, "traces", None)
    if not stack or not stack[-1]:
        return
    for t in stack[-1]:
        t.event(kind, **attrs)


def error_event(name: str, exc: BaseException, **attrs) -> None:
    """The one way an error becomes a telemetry event: kind ``"error"``
    with a MANDATORY ``code`` attr (the 100–114 ladder; foreign
    exceptions degrade to 100) — the static contract in
    ``tests/test_review_regressions.py`` keeps new codes traceable.
    Lands on the ledger, the ``error.code.<n>`` counter, and every
    active trace."""
    if not config.enabled():
        return
    code = int(getattr(exc, "code", 100))
    payload = {"code": code, "type": type(exc).__name__, **attrs}
    REGISTRY.inc(f"error.code.{code}")
    trace_event("error", **payload)
    event("error", name, dict(payload, message=str(exc)))


# -- the flight recorder ----------------------------------------------------


class FlightRecorder:
    """Bounded ring of completed traces + a larger ring of violations.

    ``capacity`` bounds the recent ring (``SKYLARK_TRACE_CAPACITY``,
    default 256); violations keep 8× that.  "All SLO-violating traces"
    is therefore bounded too — a server being DoS'd with poison still
    has finite memory — but the violation window is wide enough that
    every incident of a normal run survives until drained.
    """

    def __init__(self, capacity: int | None = None):
        cap = capacity if capacity is not None else _capacity()
        self._lock = threading.Lock()
        self._recent: deque = deque(maxlen=cap)
        self._violations: deque = deque(maxlen=8 * cap)

    def record(self, trace, violating=None) -> None:
        """Retain a finished trace — a :class:`TraceContext` (converted
        to its dict form lazily, at read time, to keep the serve hot
        path cheap) or an already-built payload dict."""
        if violating is None:
            if isinstance(trace, dict):
                violating = trace.get("status") in VIOLATIONS or trace.get(
                    "violation"
                )
            else:
                violating = trace.status in VIOLATIONS or trace.violation
        with self._lock:
            self._recent.append(trace)
            if violating:
                self._violations.append(trace)

    @staticmethod
    def _tid(p):
        return p.get("trace_id") if isinstance(p, dict) else p.trace_id

    @staticmethod
    def _payload(p) -> dict:
        return p if isinstance(p, dict) else p.to_dict()

    def get(self, trace_id: str) -> dict | None:
        with self._lock:
            for ring in (self._recent, self._violations):
                for p in reversed(ring):
                    if self._tid(p) == trace_id:
                        return self._payload(p)
        return None

    def ids(self) -> dict:
        with self._lock:
            return {
                "recent": [self._tid(p) for p in self._recent],
                "violations": [self._tid(p) for p in self._violations],
            }

    def drain(self) -> dict:
        """Remove and return everything recorded so far."""
        with self._lock:
            recent = list(self._recent)
            violations = list(self._violations)
            self._recent.clear()
            self._violations.clear()
        return {
            "recent": [self._payload(p) for p in recent],
            "violations": [self._payload(p) for p in violations],
        }

    def __len__(self) -> int:
        with self._lock:
            return len(self._recent)

    def dump(self, path) -> int:
        """Write every retained trace as JSONL; returns the line count."""
        with self._lock:
            rows = list(self._recent)
            seen = {id(p) for p in rows}
            rows += [p for p in self._violations if id(p) not in seen]
        with open(path, "w", encoding="utf-8") as fh:
            for p in rows:
                fh.write(json.dumps(self._payload(p), default=str) + "\n")
        return len(rows)


RECORDER = FlightRecorder()


def finish(tctx: TraceContext | None, status: str, *, code=None,
           violation: bool = False) -> None:
    """Close a trace into the flight recorder.  Violating traces (shed,
    error, or ``violation=True`` for solo-retry / guard-escalation runs
    that still answered OK) are retained in the violation ring and
    dumped to the run ledger immediately."""
    if tctx is None:
        return
    tctx.status = status
    tctx.t_end = time.time()
    if code is not None:
        tctx.code = int(code)
    violating = status in VIOLATIONS or violation
    tctx.violation = bool(violation)
    RECORDER.record(tctx, violating=violating)
    # One lock acquisition for both counters (hot path; see mint).
    with LOCK:
        c = REGISTRY.counters
        c["trace.finished"] = c.get("trace.finished", 0) + 1
        if violating:
            c["trace.violations"] = c.get("trace.violations", 0) + 1
    if violating:
        # dump-on-error: the ledger keeps the full trace even if the
        # process dies before anyone drains the recorder — flushed
        # through, since a buffered incident record is no evidence
        event("trace", tctx.op, tctx.to_dict())
        flush()


def is_violating(events) -> bool:
    """Did this event list record an SLO violation — a solo-retry or
    batch fallback, a structured error, or a guard-ladder escalation
    past the first rung?  Such traces are retained in the recorder's
    violation ring even after ``capacity`` newer traces arrive."""
    for ev in events:
        k = ev.get("kind")
        if k in ("fallback", "solo_retry", "error"):
            return True
        if k == "guard" and ev.get("rung", 0):
            return True
    return False


def get_trace(trace_id: str) -> dict | None:
    return RECORDER.get(trace_id)


def trace_ids() -> dict:
    return RECORDER.ids()


def drain_traces() -> dict:
    return RECORDER.drain()


def dump_traces(path) -> int:
    return RECORDER.dump(path)
