"""Telemetry env knobs — read PER CALL (like ``SKYLARK_GUARD`` /
``SKYLARK_NO_PLANS``) so tests and operators can flip them at runtime.

``SKYLARK_TELEMETRY`` gates the whole layer and defaults to OFF: every
entry point short-circuits through :func:`enabled` before touching the
registry or the ledger, so a disabled process pays one dict lookup per
call site and allocates nothing (the ``.lower()`` string copy the other
knobs make is deliberately avoided here — this check sits on per-batch
hot paths).
"""

from __future__ import annotations

import os

__all__ = ["enabled", "ledger_dir"]

_OFF = (None, "", "0", "false", "False", "FALSE", "off", "no")


def enabled() -> bool:
    """True when ``SKYLARK_TELEMETRY`` is set truthy (default: off)."""
    return os.environ.get("SKYLARK_TELEMETRY") not in _OFF


def ledger_dir() -> str | None:
    """Directory for the JSONL run ledger (``SKYLARK_TELEMETRY_DIR`` or
    :func:`~libskylark_tpu.telemetry.configure`); ``None`` means events
    count in the registry but no ledger file is written."""
    return os.environ.get("SKYLARK_TELEMETRY_DIR") or None
