"""The per-request phase clock: canonical phase names + observation helpers.

A slow serve request spends its life in a fixed chain of phases; the
serve plane stamps the monotonic duration of each into the request's
trace envelope (``trace["phases"]``) and into bucketed histograms named
``phase.<name>_ms`` so fleet-wide phase quantiles are queryable from
``/metrics`` as real Prometheus ``_bucket{le=...}`` series.

The clock rides THREE gates: ``SKYLARK_TELEMETRY`` (the whole layer),
``SKYLARK_TRACE`` (phases are only assembled for traced requests), and
``SKYLARK_PHASES`` (default on; lets the bench A/B the clock itself
while tracing stays hot).  With any gate off, no phase dict is
allocated and no timestamp is taken beyond what tracing already does.
"""

from __future__ import annotations

import os

from . import config
from .registry import enable_buckets, observe

__all__ = ["PHASES", "phases_enabled", "observe_phase", "enable_phase_buckets"]

# Canonical phase names, in request-lifetime order.  ``collective_wait``
# is the odd one out: it is recorded per-rank at cross-host collective
# sites (straggler attribution), not per-request.
PHASES = (
    "admit_wait",
    "coalesce_linger",
    "dispatch_queue",
    "plan_compile",
    "device_execute",
    "depad_serialize",
    "collective_wait",
)

_OFF = ("0", "false", "False", "FALSE", "off", "no")

_REGISTERED: set = set()


def phases_enabled() -> bool:
    """True unless ``SKYLARK_PHASES`` is set falsy (and telemetry is on)."""
    if not config.enabled():
        return False
    return os.environ.get("SKYLARK_PHASES") not in _OFF


def enable_phase_buckets() -> None:
    """Register log-spaced buckets for every phase histogram (idempotent)."""
    for p in PHASES:
        name = "phase." + p + "_ms"
        if name not in _REGISTERED:
            enable_buckets(name)
            _REGISTERED.add(name)


def observe_phase(name: str, ms: float) -> None:
    """Record one phase duration (ms) into its bucketed histogram."""
    metric = "phase." + name + "_ms"
    if metric not in _REGISTERED:
        enable_buckets(metric)
        _REGISTERED.add(metric)
    observe(metric, ms)
