"""The process-wide metrics registry: counters, gauges, histograms.

ONE lock (module-level ``LOCK``, shared with the ledger's sequence
counter) guards every mutation — the same single-lock discipline as
``plans.PlanCache`` — and the module-level helpers (:func:`inc`,
:func:`set_gauge`, :func:`observe`) check ``config.enabled()`` FIRST,
so with ``SKYLARK_TELEMETRY=0`` a call returns before any allocation
happens.

Histograms keep streaming moments (count / sum / min / max), not
buckets: enough for min/max/avg reporting without per-event lists.
"""

from __future__ import annotations

import threading

from . import config

__all__ = ["LOCK", "Registry", "REGISTRY", "inc", "set_gauge", "observe", "reset"]

LOCK = threading.Lock()


class Registry:
    """Named counters / gauges / histograms behind the shared lock."""

    def __init__(self):
        self.counters: dict = {}
        self.gauges: dict = {}
        self.histograms: dict = {}

    def inc(self, name: str, amount=1) -> None:
        with LOCK:
            self.counters[name] = self.counters.get(name, 0) + amount

    def set_gauge(self, name: str, value) -> None:
        with LOCK:
            self.gauges[name] = value

    def observe(self, name: str, value) -> None:
        v = float(value)
        with LOCK:
            h = self.histograms.get(name)
            if h is None:
                self.histograms[name] = {
                    "count": 1, "sum": v, "min": v, "max": v,
                }
            else:
                h["count"] += 1
                h["sum"] += v
                h["min"] = min(h["min"], v)
                h["max"] = max(h["max"], v)

    def snapshot(self) -> dict:
        """Point-in-time copy of every metric (safe to mutate)."""
        with LOCK:
            return {
                "counters": dict(self.counters),
                "gauges": dict(self.gauges),
                "histograms": {k: dict(v) for k, v in self.histograms.items()},
            }

    def reset(self) -> None:
        with LOCK:
            self.counters.clear()
            self.gauges.clear()
            self.histograms.clear()


REGISTRY = Registry()


def inc(name: str, amount=1) -> None:
    """Bump counter ``name`` (no-op — and no allocation — when disabled)."""
    if not config.enabled():
        return
    REGISTRY.inc(name, amount)


def set_gauge(name: str, value) -> None:
    """Set gauge ``name`` (no-op when disabled)."""
    if not config.enabled():
        return
    REGISTRY.set_gauge(name, value)


def observe(name: str, value) -> None:
    """Record ``value`` into histogram ``name`` (no-op when disabled)."""
    if not config.enabled():
        return
    REGISTRY.observe(name, value)


def reset() -> None:
    """Zero every metric (test hook; always runs, even disabled)."""
    REGISTRY.reset()
