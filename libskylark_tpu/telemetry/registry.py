"""The process-wide metrics registry: counters, gauges, histograms.

ONE lock (module-level ``LOCK``, shared with the ledger's sequence
counter) guards every mutation — the same single-lock discipline as
``plans.PlanCache`` — and the module-level helpers (:func:`inc`,
:func:`set_gauge`, :func:`observe`) check ``config.enabled()`` FIRST,
so with ``SKYLARK_TELEMETRY=0`` a call returns before any allocation
happens.

Histograms keep streaming moments (count / sum / min / max) by
default: enough for min/max/avg reporting without per-event lists.
Individual histograms can opt into log-spaced cumulative buckets via
:func:`enable_buckets` — bucket bounds are registry *configuration*
(they survive :func:`reset`), while bucket counts are data.  Buckets
stay off per histogram unless registered, so non-serve callers pay
nothing beyond one dict lookup per observe.
"""

from __future__ import annotations

import bisect
import threading

from . import config

__all__ = [
    "LOCK",
    "Registry",
    "REGISTRY",
    "DEFAULT_BUCKETS_MS",
    "inc",
    "set_gauge",
    "observe",
    "enable_buckets",
    "reset",
]

LOCK = threading.Lock()

# Log-spaced latency ladder in milliseconds (an implicit +Inf bucket is
# always appended at exposition time).
DEFAULT_BUCKETS_MS = (
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0,
    100.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0,
)


class Registry:
    """Named counters / gauges / histograms behind the shared lock."""

    def __init__(self):
        self.counters: dict = {}
        self.gauges: dict = {}
        self.histograms: dict = {}
        # name -> sorted tuple of upper bounds (configuration, survives reset)
        self._bucket_bounds: dict = {}

    def inc(self, name: str, amount=1) -> None:
        with LOCK:
            self.counters[name] = self.counters.get(name, 0) + amount

    def set_gauge(self, name: str, value) -> None:
        with LOCK:
            self.gauges[name] = value

    def enable_buckets(self, name: str, bounds=None) -> None:
        """Opt histogram ``name`` into cumulative buckets.

        ``bounds`` are finite upper bounds (``le`` values); +Inf is implied.
        Idempotent; re-registering with different bounds restarts the
        bucket counts (moments are untouched).
        """
        bs = tuple(sorted(float(b) for b in (bounds or DEFAULT_BUCKETS_MS)))
        with LOCK:
            if self._bucket_bounds.get(name) == bs:
                return
            self._bucket_bounds[name] = bs
            h = self.histograms.get(name)
            if h is not None:
                h["bucket_counts"] = [0] * (len(bs) + 1)
                h["bucket_count"] = 0
                h["bucket_sum"] = 0.0

    def observe(self, name: str, value) -> None:
        v = float(value)
        with LOCK:
            h = self.histograms.get(name)
            if h is None:
                h = {"count": 1, "sum": v, "min": v, "max": v}
                self.histograms[name] = h
            else:
                h["count"] += 1
                h["sum"] += v
                h["min"] = min(h["min"], v)
                h["max"] = max(h["max"], v)
            bounds = self._bucket_bounds.get(name)
            if bounds is not None:
                counts = h.get("bucket_counts")
                if counts is None:
                    counts = [0] * (len(bounds) + 1)
                    h["bucket_counts"] = counts
                    h["bucket_count"] = 0
                    h["bucket_sum"] = 0.0
                counts[bisect.bisect_left(bounds, v)] += 1
                h["bucket_count"] += 1
                h["bucket_sum"] += v

    def snapshot(self) -> dict:
        """Point-in-time copy of every metric (safe to mutate).

        Bucketed histograms additionally carry a ``buckets`` dict:
        ``{"le": [...finite bounds...], "counts": [per-bucket counts,
        last entry is the +Inf overflow], "count", "sum"}`` where
        ``count``/``sum`` cover only observations made since buckets
        were enabled (so ``+Inf`` cumulative == ``count`` always holds).
        """
        with LOCK:
            hists = {}
            for k, v in self.histograms.items():
                h = {"count": v["count"], "sum": v["sum"],
                     "min": v["min"], "max": v["max"]}
                counts = v.get("bucket_counts")
                if counts is not None:
                    h["buckets"] = {
                        "le": list(self._bucket_bounds.get(k, ())),
                        "counts": list(counts),
                        "count": v.get("bucket_count", 0),
                        "sum": v.get("bucket_sum", 0.0),
                    }
                hists[k] = h
            return {
                "counters": dict(self.counters),
                "gauges": dict(self.gauges),
                "histograms": hists,
            }

    def reset(self) -> None:
        with LOCK:
            self.counters.clear()
            self.gauges.clear()
            self.histograms.clear()


REGISTRY = Registry()


def inc(name: str, amount=1) -> None:
    """Bump counter ``name`` (no-op — and no allocation — when disabled)."""
    if not config.enabled():
        return
    REGISTRY.inc(name, amount)


def set_gauge(name: str, value) -> None:
    """Set gauge ``name`` (no-op when disabled)."""
    if not config.enabled():
        return
    REGISTRY.set_gauge(name, value)


def observe(name: str, value) -> None:
    """Record ``value`` into histogram ``name`` (no-op when disabled)."""
    if not config.enabled():
        return
    REGISTRY.observe(name, value)


def enable_buckets(name: str, bounds=None) -> None:
    """Register bucket bounds for histogram ``name``.

    Registration is configuration, not data: it always runs (even with
    telemetry disabled) so a server constructed before the gate flips
    still gets buckets once observations start flowing.
    """
    REGISTRY.enable_buckets(name, bounds)


def reset() -> None:
    """Zero every metric (test hook; always runs, even disabled)."""
    REGISTRY.reset()
