"""A bounded in-memory ring of periodic metric-snapshot deltas.

``skylark-top`` (and anything else scraping ``GET /timeline``) wants
"what did the last ten minutes look like", which point-in-time counters
cannot answer.  The timeline rolls the registry forward in fixed
windows: every ``SKYLARK_TIMELINE_INTERVAL_S`` seconds (default 5) a
tick snapshots the registry, records the *delta* of every counter and
histogram (count/sum) against the previous tick plus the current gauge
values, and appends one window record to a ring bounded by
``SKYLARK_TIMELINE_CAPACITY`` (default 120 windows — ten minutes at the
default interval).

Ticks are lazy — there is no thread.  Hot paths (the serve worker loop)
and the ``/timeline`` endpoint call :func:`timeline_tick`; whichever
arrives first past the interval boundary closes the window.  Each
record derives the headline sparkline series: ``qps`` (request delta
over the window), ``p99_ms`` (estimated from ``serve.latency_ms``
bucket deltas when that histogram has buckets enabled — the serve
plane enables them at construction), ``cache_hit_rate``, and whatever
point-in-time extras the caller passes (queue depth).

Rides ``SKYLARK_TELEMETRY``: disabled, :func:`timeline_tick` returns
before taking a timestamp or allocating.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

from . import config
from .registry import REGISTRY, inc

__all__ = ["Timeline", "TIMELINE", "timeline_tick", "timeline_windows",
           "timeline_state", "reset_timeline", "bucket_quantile"]

_DEF_INTERVAL_S = 5.0
_DEF_CAPACITY = 120


def _interval_s() -> float:
    try:
        v = float(os.environ.get("SKYLARK_TIMELINE_INTERVAL_S",
                                 _DEF_INTERVAL_S))
    except ValueError:
        v = _DEF_INTERVAL_S
    return max(0.05, v)


def _capacity() -> int:
    try:
        n = int(os.environ.get("SKYLARK_TIMELINE_CAPACITY", _DEF_CAPACITY))
    except ValueError:
        n = _DEF_CAPACITY
    return max(1, n)


def bucket_quantile(le, counts, q: float):
    """Upper-bound estimate of quantile ``q`` from (non-cumulative)
    bucket counts; returns the containing bucket's ``le`` (the last
    finite bound for the +Inf overflow bucket), or None when empty."""
    total = sum(counts)
    if total <= 0 or not le:
        return None
    rank = q * total
    cum = 0
    for i, c in enumerate(counts):
        cum += c
        if cum >= rank:
            return float(le[i]) if i < len(le) else float(le[-1])
    return float(le[-1])


class Timeline:
    """The ring itself; one module-level instance serves the process."""

    def __init__(self):
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=_capacity())
        self._last_mono: float | None = None
        self._last_counters: dict = {}
        self._last_hist: dict = {}    # name -> (count, sum, bucket_counts)

    def maybe_tick(self, extra: dict | None = None,
                   force: bool = False) -> bool:
        """Close the current window if the interval has elapsed.

        Returns True when a window record was appended.  ``extra`` is a
        dict of point-in-time values (e.g. queue depth) merged into the
        record's ``derived`` map.  ``force`` closes the window
        regardless of the interval (test hook).
        """
        if not config.enabled():
            return False
        now = time.monotonic()
        with self._lock:
            cap = _capacity()
            if self._ring.maxlen != cap:
                self._ring = deque(self._ring, maxlen=cap)
            if self._last_mono is None:
                # First tick just baselines; no window to close yet.
                self._baseline_locked(now)
                return False
            dt = now - self._last_mono
            if not force and dt < _interval_s():
                return False
            snap = REGISTRY.snapshot()
            record = self._delta_locked(snap, dt, extra)
            self._ring.append(record)
            self._baseline_locked(now, snap)
        inc("timeline.ticks")
        return True

    def _baseline_locked(self, now: float, snap: dict | None = None) -> None:
        if snap is None:
            snap = REGISTRY.snapshot()
        self._last_mono = now
        self._last_counters = snap["counters"]
        self._last_hist = {
            k: (v["count"], v["sum"],
                tuple(v["buckets"]["counts"]) if "buckets" in v else None)
            for k, v in snap["histograms"].items()
        }

    def _delta_locked(self, snap: dict, dt: float,
                      extra: dict | None) -> dict:
        counters = {}
        for k, v in snap["counters"].items():
            d = v - self._last_counters.get(k, 0)
            if d:
                counters[k] = d
        hists = {}
        lat_buckets = None
        for k, v in snap["histograms"].items():
            prev = self._last_hist.get(k, (0, 0.0, None))
            dc = v["count"] - prev[0]
            if not dc:
                continue
            hists[k] = {"count": dc, "sum": round(v["sum"] - prev[1], 6)}
            if "buckets" in v:
                prev_counts = prev[2] or (0,) * len(v["buckets"]["counts"])
                if len(prev_counts) == len(v["buckets"]["counts"]):
                    dcounts = [a - b for a, b in
                               zip(v["buckets"]["counts"], prev_counts)]
                    if k == "serve.latency_ms":
                        lat_buckets = (v["buckets"]["le"], dcounts)
        derived = {
            "qps": round(counters.get("serve.requests", 0) / dt, 3),
            "cache_hit_rate": self._hit_rate(counters),
        }
        if lat_buckets is not None:
            p99 = bucket_quantile(lat_buckets[0], lat_buckets[1], 0.99)
            if p99 is not None:
                derived["p99_ms"] = p99
        if extra:
            for k, v in extra.items():
                derived[k] = v
        return {
            "ts": time.time(),
            "dt_s": round(dt, 3),
            "counters": counters,
            "gauges": dict(snap["gauges"]),
            "histograms": hists,
            "derived": derived,
        }

    @staticmethod
    def _hit_rate(counters: dict):
        hits = counters.get("serve.cache.hit", 0)
        lookups = hits + counters.get("serve.cache.miss", 0)
        return round(hits / lookups, 4) if lookups else None

    def windows(self) -> list:
        with self._lock:
            return list(self._ring)

    def state(self) -> dict:
        """The ``/timeline`` response body."""
        return {
            "interval_s": _interval_s(),
            "capacity": _capacity(),
            "windows": self.windows(),
        }

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self._last_mono = None
            self._last_counters = {}
            self._last_hist = {}


TIMELINE = Timeline()


def timeline_tick(extra: dict | None = None, force: bool = False) -> bool:
    """Module-level shorthand for ``TIMELINE.maybe_tick``."""
    return TIMELINE.maybe_tick(extra=extra, force=force)


def timeline_windows() -> list:
    return TIMELINE.windows()


def timeline_state() -> dict:
    return TIMELINE.state()


def reset_timeline() -> None:
    """Test hook: clear the ring and baselines."""
    TIMELINE.reset()
