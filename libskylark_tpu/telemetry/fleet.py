"""Cross-host telemetry aggregation: one fleet view of many registries.

Two independent folds, composable because both produce/consume the
``snapshot()`` dict shape:

- :func:`merge_snapshots` — the pure reduction.  Counters sum (the
  acceptance contract: merged counters EQUAL the sum of per-rank
  snapshots), histograms merge their streaming moments
  (count/sum add, min/max extremize), gauges keep the per-rank values
  under ``gauges_by_rank`` plus a max fold, and the ``plans`` counter
  block sums like counters.  Derived ratios are recomputed from the
  merged numbers, never averaged.
- :func:`fold_ledgers` — the durable half: walks the elastic
  checkpoint root's ``host-*/progress.jsonl`` ledgers (the PR-6
  per-host fold records), epoch-fenced exactly like
  ``streaming.repartition``: only the NEWEST epoch's records merge
  (``epoch.json`` marker when present, else the max epoch observed),
  stale epochs are counted, never folded.  The result is one merged
  timeline ordered by ``(ts, rank, seq)`` plus per-rank progress
  summaries — the single view an elastic run never had.

:func:`fleet_snapshot` composes them: the live-process side gathers
every rank's counter vector with ``multihost_utils.process_allgather``
under the SAME CRC32 name-signature discipline as
``utils.timer.timer_report`` (every rank must bring the same counter
names; a mismatch raises instead of silently misaligning columns), and
the ledger side folds whatever root it is pointed at.  In a
single-process world the gather degenerates to the local snapshot, so
``telemetry.snapshot(fleet=True)`` is always safe to call.
"""

from __future__ import annotations

import glob
import os
import re
import zlib

import numpy as np

from .report import snapshot as _local_snapshot

__all__ = ["merge_snapshots", "fold_ledgers", "fleet_snapshot"]

_HOST_RE = re.compile(r"host-(\d+)$")


def _ratio(num, den):
    return round(num / den, 6) if den else None


def merge_snapshots(snaps: list[dict]) -> dict:
    """Fold per-rank ``snapshot()`` dicts into one fleet snapshot.

    Merged ``counters[k]`` is exactly ``sum(rank_counters[k])`` over the
    ranks that carry ``k`` — the acceptance invariant pinned in
    ``tests/test_trace.py``.
    """
    counters: dict = {}
    histograms: dict = {}
    gauges_by_rank: dict = {}
    plans: dict = {}
    for rank, snap in enumerate(snaps):
        for k, v in (snap.get("counters") or {}).items():
            counters[k] = counters.get(k, 0) + v
        for k, h in (snap.get("histograms") or {}).items():
            m = histograms.get(k)
            if m is None:
                m = dict(h)
                if "buckets" in h:
                    m["buckets"] = {
                        "le": list(h["buckets"]["le"]),
                        "counts": list(h["buckets"]["counts"]),
                        "count": h["buckets"]["count"],
                        "sum": h["buckets"]["sum"],
                    }
                histograms[k] = m
            else:
                m["count"] += h["count"]
                m["sum"] += h["sum"]
                m["min"] = min(m["min"], h["min"])
                m["max"] = max(m["max"], h["max"])
                # Buckets merge in this PURE path only (the allgathered
                # fleet vectors stay 4-row moments so cross-rank CRC
                # signatures are untouched); mismatched bounds drop the
                # buckets rather than sum misaligned bins.
                bm, bh = m.get("buckets"), h.get("buckets")
                if bm is not None:
                    if bh is not None and list(bm["le"]) == list(bh["le"]):
                        bm["counts"] = [a + b for a, b in
                                        zip(bm["counts"], bh["counts"])]
                        bm["count"] += bh["count"]
                        bm["sum"] += bh["sum"]
                    else:
                        m.pop("buckets", None)
        for k, g in (snap.get("gauges") or {}).items():
            gauges_by_rank.setdefault(k, {})[rank] = g
        for k, v in (snap.get("plans") or {}).items():
            if isinstance(v, (int, float)):
                plans[k] = plans.get(k, 0) + v
    gauges = {}
    for k, per_rank in gauges_by_rank.items():
        nums = [v for v in per_rank.values()
                if isinstance(v, (int, float))]
        if nums:
            gauges[k] = max(nums)
    out = {
        "world": len(snaps),
        "counters": counters,
        "gauges": gauges,
        "gauges_by_rank": gauges_by_rank,
        "histograms": histograms,
        "plans": plans,
    }
    lookups = plans.get("hits", 0) + plans.get("misses", 0)
    out["plan_cache_hit_rate"] = _ratio(plans.get("hits", 0), lookups)
    gets = counters.get("prefetch.hits", 0) + counters.get(
        "prefetch.waits", 0
    )
    out["prefetch_overlap"] = _ratio(counters.get("prefetch.hits", 0), gets)
    for group in ("guard", "checkpoint", "policy", "serve"):
        out[group] = {
            k.split(".", 1)[1]: v
            for k, v in counters.items()
            if k.startswith(group + ".")
        }
    return out


def fold_ledgers(root, *, timeline_limit: int = 256) -> dict:
    """Epoch-fenced fold of every ``host-*/progress.jsonl`` under
    ``root`` into per-rank summaries + one merged timeline.

    Returns ``{"epoch", "ranks": {rank: {...}}, "timeline": [...],
    "stale_records", "lost_hosts"}``; a missing/empty root folds to an
    empty view rather than raising (the exposition surface must stay up
    when no elastic run ever wrote here).
    """
    from ..streaming.elastic import PROGRESS_NAME, read_progress
    from ..streaming.repartition import read_epoch

    root = str(root)
    paths = sorted(
        glob.glob(os.path.join(root, "host-*", PROGRESS_NAME))
        + glob.glob(os.path.join(root, "epoch-*", "host-*", PROGRESS_NAME))
    )
    marker = read_epoch(root)
    per_path: list[tuple[int, list[dict]]] = []
    max_epoch = 0
    lost_hosts = []
    for path in paths:
        m = _HOST_RE.search(os.path.dirname(path))
        rank = int(m.group(1)) if m else -1
        try:
            recs = read_progress(path)
        except Exception:  # noqa: BLE001 — a corrupt host is reported, not fatal
            lost_hosts.append(rank)
            continue
        per_path.append((rank, recs))
        for rec in recs:
            max_epoch = max(
                max_epoch, int((rec.get("attrs") or {}).get("epoch", 0))
            )
    epoch = int(marker["epoch"]) if marker else max_epoch
    ranks: dict = {}
    timeline = []
    stale = 0
    for rank, recs in per_path:
        for rec in recs:
            attrs = rec.get("attrs") or {}
            if int(attrs.get("epoch", 0)) != epoch:
                stale += 1
                continue
            r = int(attrs.get("rank", rank))
            summary = ranks.setdefault(
                r,
                {"records": 0, "rows": 0, "batches": 0,
                 "last_seq": 0, "last_ts": 0.0},
            )
            summary["records"] += 1
            summary["rows"] += int(attrs.get("rows", 0) or 0)
            summary["batches"] += int(attrs.get("batches", 1) or 0)
            summary["last_seq"] = max(
                summary["last_seq"], int(rec.get("seq", 0) or 0)
            )
            summary["last_ts"] = max(
                summary["last_ts"], float(rec.get("ts", 0) or 0)
            )
            timeline.append(rec)
    timeline.sort(
        key=lambda rec: (
            float(rec.get("ts", 0) or 0),
            int((rec.get("attrs") or {}).get("rank", -1)),
            int(rec.get("seq", 0) or 0),
        )
    )
    return {
        "epoch": epoch,
        "ranks": ranks,
        "rows_total": sum(r["rows"] for r in ranks.values()),
        "timeline": timeline[-timeline_limit:],
        "stale_records": stale,
        "lost_hosts": lost_hosts,
    }


def _gather_registries(local: dict) -> list[dict]:
    """Allgather every process's counter/histogram vectors, timer_report
    discipline: CRC32 name-signature first, positional columns after."""
    import jax

    if jax.process_count() == 1:
        return [local]
    from jax.experimental import multihost_utils

    names = sorted(local["counters"])
    hnames = sorted(local["histograms"])
    sig = np.asarray(
        [
            zlib.crc32("\x00".join(names).encode()),
            len(names),
            zlib.crc32("\x00".join(hnames).encode()),
            len(hnames),
        ],
        np.int64,
    )
    sigs = np.atleast_2d(np.asarray(multihost_utils.process_allgather(sig)))
    if not (sigs == sigs[0]).all():
        raise RuntimeError(
            "telemetry.snapshot(fleet=True): processes carry different "
            f"counter-name sets (this rank has {len(names)} counters); "
            "every rank must fold the same metrics — the same collective "
            "contract as utils.timer.timer_report(distributed=True)"
        )
    vec = np.asarray(
        [float(local["counters"][n]) for n in names], np.float64
    )
    hvec = np.asarray(
        [
            [local["histograms"][n][f] for n in hnames]
            for f in ("count", "sum", "min", "max")
        ],
        np.float64,
    ).reshape(-1)
    stacked = np.atleast_2d(
        np.asarray(multihost_utils.process_allgather(vec))
    )
    hstacked = np.atleast_2d(
        np.asarray(multihost_utils.process_allgather(hvec))
    )
    snaps = []
    for p in range(stacked.shape[0]):
        h4 = hstacked[p].reshape(4, len(hnames)) if hnames else None
        snaps.append(
            {
                "counters": dict(zip(names, stacked[p].tolist())),
                "histograms": {
                    n: {
                        "count": h4[0, j],
                        "sum": h4[1, j],
                        "min": h4[2, j],
                        "max": h4[3, j],
                    }
                    for j, n in enumerate(hnames)
                }
                if hnames
                else {},
                # gauges/plans are process-local context, not collective
                # state: only rank 0's ride along (plans counters are
                # per-process caches anyway).
                "gauges": local["gauges"] if p == 0 else {},
                "plans": local["plans"] if p == 0 else {},
            }
        )
    return snaps


def fleet_snapshot(root=None) -> dict:
    """The fleet-wide fold ``telemetry.snapshot(fleet=True)`` returns:
    allgathered per-rank registries merged by :func:`merge_snapshots`,
    plus the epoch-fenced ledger fold of ``root`` (or
    ``SKYLARK_TELEMETRY_FLEET_ROOT``) when one is given."""
    local = _local_snapshot()
    merged = merge_snapshots(_gather_registries(local))
    root = root or os.environ.get("SKYLARK_TELEMETRY_FLEET_ROOT")
    if root:
        merged["hosts"] = fold_ledgers(root)
    return merged
