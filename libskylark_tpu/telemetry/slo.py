"""Declarative latency SLOs with rolling error budgets.

An *objective* says "requests of op X (optionally for tenant Y) finish
under T ms at least P% of the time".  Operators declare them in
``SKYLARK_SLO`` as a comma-separated list::

    SKYLARK_SLO="ls_solve:50:99.9,predict@acme:20:99"

i.e. ``key:threshold_ms:target_pct`` where ``key`` is an op name or
``op@tenant`` for a tenant-scoped objective.

The tracker keeps a bounded rolling window of good/bad verdicts per
objective (``SKYLARK_SLO_WINDOW`` samples, default 1024; a shed request
is always bad) and derives the remaining error budget::

    allowed = window_size * (1 - target_pct / 100)
    budget_remaining = 1 - bad / allowed        # 1.0 = untouched, <0 = blown

Each observation refreshes a ``slo.budget_remaining.<key>`` gauge
(exported as ``skylark_slo_budget_remaining{objective="<key>"}`` on
``/metrics``).  When the budget drops below ``SKYLARK_SLO_BURN``
(default 0.25) the tracker mints ONE edge-triggered ``slo_burn``
trace-violation record into the flight recorder's violations ring plus
a ledgered ``slo``/``burn`` event, re-arming only after the budget
recovers above the threshold.  Burn evaluation waits for a small floor
of samples (8) so one unlucky first request cannot page anyone.

Everything rides ``SKYLARK_TELEMETRY``: disabled, :func:`observe_slo`
returns before parsing or allocating anything.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

from . import config, ledger
from .registry import inc, set_gauge
from .trace import RECORDER, next_id

__all__ = [
    "Objective",
    "parse_slos",
    "SloTracker",
    "TRACKER",
    "observe_slo",
    "slo_report",
    "reset_slo",
]

_DEF_WINDOW = 1024
_DEF_BURN = 0.25
_MIN_SAMPLES = 8


class Objective:
    """One parsed SLO: ``key`` (op or ``op@tenant``), threshold, target."""

    __slots__ = ("key", "threshold_ms", "target_pct")

    def __init__(self, key: str, threshold_ms: float, target_pct: float):
        self.key = key
        self.threshold_ms = float(threshold_ms)
        self.target_pct = float(target_pct)

    def to_dict(self) -> dict:
        return {
            "key": self.key,
            "threshold_ms": self.threshold_ms,
            "target_pct": self.target_pct,
        }


def parse_slos(spec: str | None) -> dict:
    """Parse a ``SKYLARK_SLO`` spec into ``{key: Objective}``.

    Malformed entries are skipped (and counted under ``slo.parse_errors``
    when telemetry is on) rather than raised — a typo in an env var must
    not take down a serving process.
    """
    objectives: dict = {}
    if not spec:
        return objectives
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        try:
            if len(fields) != 3:
                raise ValueError(part)
            key = fields[0].strip()
            thr = float(fields[1])
            pct = float(fields[2])
            if not key or thr <= 0 or not (0.0 < pct <= 100.0):
                raise ValueError(part)
        except (ValueError, TypeError):
            inc("slo.parse_errors")
            continue
        objectives[key] = Objective(key, thr, pct)
    return objectives


class SloTracker:
    """Rolling error-budget tracker over the declared objectives.

    The objective table is re-parsed lazily whenever the ``SKYLARK_SLO``
    string changes (read per call, like every other telemetry knob), so
    tests and operators can flip objectives at runtime.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._spec: str | None = None
        self._objectives: dict = {}
        self._windows: dict = {}      # key -> deque of bools (True = bad)
        self._bad: dict = {}          # key -> running bad count in window
        self._burning: dict = {}      # key -> edge-trigger state

    # -- configuration ------------------------------------------------

    def _refresh_locked(self) -> dict:
        spec = os.environ.get("SKYLARK_SLO") or ""
        if spec != self._spec:
            self._spec = spec
            self._objectives = parse_slos(spec)
            for gone in set(self._windows) - set(self._objectives):
                self._windows.pop(gone, None)
                self._bad.pop(gone, None)
                self._burning.pop(gone, None)
        return self._objectives

    @staticmethod
    def _window_size() -> int:
        try:
            n = int(os.environ.get("SKYLARK_SLO_WINDOW", _DEF_WINDOW))
        except ValueError:
            n = _DEF_WINDOW
        return max(1, n)

    @staticmethod
    def _burn_threshold() -> float:
        try:
            return float(os.environ.get("SKYLARK_SLO_BURN", _DEF_BURN))
        except ValueError:
            return _DEF_BURN

    # -- observation --------------------------------------------------

    def observe(self, op: str, latency_ms: float, *, tenant=None,
                shed: bool = False) -> None:
        """Judge one finished (or shed) request against the objectives."""
        if not config.enabled():
            return
        with self._lock:
            objectives = self._refresh_locked()
            if not objectives:
                return
            keys = [op]
            if tenant:
                keys.append(f"{op}@{tenant}")
            for key in keys:
                obj = objectives.get(key)
                if obj is not None:
                    self._observe_one_locked(obj, latency_ms, shed)

    def _observe_one_locked(self, obj, latency_ms: float, shed: bool) -> None:
        size = self._window_size()
        win = self._windows.get(obj.key)
        if win is None or win.maxlen != size:
            win = deque(win or (), maxlen=size)
            self._windows[obj.key] = win
            self._bad[obj.key] = sum(win)
        bad = bool(shed) or float(latency_ms) > obj.threshold_ms
        if len(win) == win.maxlen:
            self._bad[obj.key] -= win[0]
        win.append(bad)
        if bad:
            self._bad[obj.key] += 1
            inc("slo.breaches")
        inc("slo.observed")
        remaining = self._budget_remaining(obj, len(win), self._bad[obj.key])
        set_gauge(f"slo.budget_remaining.{obj.key}", round(remaining, 6))
        burn_min = self._burn_threshold()
        if len(win) >= min(_MIN_SAMPLES, win.maxlen):
            if remaining < burn_min and not self._burning.get(obj.key):
                self._burning[obj.key] = True
                self._mint_burn_locked(obj, remaining, len(win),
                                       self._bad[obj.key])
            elif remaining >= burn_min and self._burning.get(obj.key):
                self._burning[obj.key] = False
                inc("slo.recoveries")

    @staticmethod
    def _budget_remaining(obj, n: int, bad: int) -> float:
        if n == 0:
            return 1.0
        allowed = n * (1.0 - obj.target_pct / 100.0)
        if allowed <= 0.0:
            return 1.0 if bad == 0 else float(-bad)
        return 1.0 - bad / allowed

    def _mint_burn_locked(self, obj, remaining: float, n: int,
                          bad: int) -> None:
        inc("slo.burns")
        payload = {
            "trace_id": f"slo-burn-{next_id()}",
            "op": "slo_burn",
            "status": "slo_burn",
            "violation": True,
            "ts": time.time(),
            "slo": obj.key,
            "threshold_ms": obj.threshold_ms,
            "target_pct": obj.target_pct,
            "budget_remaining": round(remaining, 6),
            "window": n,
            "bad": bad,
        }
        RECORDER.record(payload, violating=True)
        ledger.event("slo", "burn", {
            "slo": obj.key,
            "budget_remaining": round(remaining, 6),
            "window": n,
            "bad": bad,
        })

    # -- reporting ----------------------------------------------------

    def report(self) -> dict:
        """``{key: {...objective, window, bad, burn_rate, budget_remaining,
        burning}}`` for every declared objective (empty when none)."""
        with self._lock:
            objectives = self._refresh_locked()
            out = {}
            for key, obj in objectives.items():
                win = self._windows.get(key)
                n = len(win) if win else 0
                bad = self._bad.get(key, 0)
                out[key] = {
                    **obj.to_dict(),
                    "window": n,
                    "bad": bad,
                    "burn_rate": round(bad / n, 6) if n else 0.0,
                    "budget_remaining": round(
                        self._budget_remaining(obj, n, bad), 6),
                    "burning": bool(self._burning.get(key)),
                }
            return out

    def reset(self) -> None:
        with self._lock:
            self._spec = None
            self._objectives = {}
            self._windows.clear()
            self._bad.clear()
            self._burning.clear()


TRACKER = SloTracker()


def observe_slo(op: str, latency_ms: float, *, tenant=None,
                shed: bool = False) -> None:
    """Module-level shorthand for ``TRACKER.observe`` (no-op when the
    telemetry gate is off or no objectives are declared)."""
    TRACKER.observe(op, latency_ms, tenant=tenant, shed=shed)


def slo_report() -> dict:
    """Current per-objective budget state (empty dict when none declared)."""
    return TRACKER.report()


def reset_slo() -> None:
    """Test hook: drop all windows and edge-trigger state."""
    TRACKER.reset()
