"""End-of-run folds: ``snapshot()``, ``run_summary()``, ``report()``.

``snapshot()`` is the single picture the four private status channels
used to be: the registry's metrics plus ``plans.stats()``, the prefetch
overlap ratio (from the counters the streaming engine folds in when a
pass closes), and the guard / checkpoint / policy counter groups (the
policy group covers decisions made, escalations, and profile
hits/misses — ``docs/autotuning.md``).

``report()`` is the multi-process reduction, and deliberately REUSES
``utils.timer.timer_report``'s gather contract: with
``distributed=True`` every process of the ``jax.distributed`` job must
call it with the same counter-name set — the CRC32 name-signature is
allgathered first and a mismatch raises instead of silently misaligning
columns (tested via the synthetic ``(P, k)`` stacked path in
``tests/test_telemetry.py``).
"""

from __future__ import annotations

from . import config
from .ledger import event, flush
from .registry import REGISTRY

__all__ = ["snapshot", "run_summary", "report"]


def _ratio(num, den):
    return round(num / den, 6) if den else None


def snapshot(fleet: bool = False, root=None) -> dict:
    """Fold every status channel into one dict (works even disabled —
    an empty registry still reports the plan-cache block).

    ``fleet=True`` returns the cross-host fold instead: every rank's
    registry allgathered under the ``timer_report`` CRC name-signature
    discipline and merged so counters SUM over ranks, plus — when
    ``root`` (or ``SKYLARK_TELEMETRY_FLEET_ROOT``) names an elastic
    checkpoint root — the epoch-fenced fold of its
    ``host-*/progress.jsonl`` ledgers under ``"hosts"``.  Collective
    contract: with ``jax.distributed`` initialized EVERY process must
    make the call (see ``telemetry/fleet.py``); single-process worlds
    degenerate to the local snapshot's numbers.
    """
    if fleet:
        from .fleet import fleet_snapshot

        return fleet_snapshot(root)
    from .. import plans

    snap = REGISTRY.snapshot()
    counters = snap["counters"]
    st = plans.stats()
    snap["plans"] = st
    lookups = st["hits"] + st["misses"]
    snap["plan_cache_hit_rate"] = _ratio(st["hits"], lookups)
    gets = counters.get("prefetch.hits", 0) + counters.get("prefetch.waits", 0)
    snap["prefetch_overlap"] = _ratio(counters.get("prefetch.hits", 0), gets)
    # Compute-hidden transfer fraction: of the staging (parse +
    # transfer-issue) seconds the producer spent, how many the consumer
    # never waited for.  1.0 = every transfer hid behind compute;
    # None = no prefetch pipeline ran.
    prod = counters.get("prefetch.producer_seconds", 0.0)
    wait = min(counters.get("prefetch.wait_seconds", 0.0), prod)
    snap["overlap_efficiency"] = _ratio(prod - wait, prod)
    snap["guard"] = {
        k.split(".", 1)[1]: v
        for k, v in counters.items()
        if k.startswith("guard.")
    }
    snap["checkpoint"] = {
        k.split(".", 1)[1]: v
        for k, v in counters.items()
        if k.startswith("checkpoint.")
    }
    snap["policy"] = {
        k.split(".", 1)[1]: v
        for k, v in counters.items()
        if k.startswith("policy.")
    }
    snap["serve"] = {
        k.split(".", 1)[1]: v
        for k, v in counters.items()
        if k.startswith("serve.") and not k.startswith("serve.tenant.")
    }
    # Per-tenant QoS counters fold NESTED (serve.tenant.<t>.<metric> →
    # serve.tenants[t][metric]) instead of flattening into the serve
    # group — the flat group keeps its pre-QoS key set exactly.
    tenants: dict = {}
    for k, v in counters.items():
        if k.startswith("serve.tenant."):
            t, _, metric = k[len("serve.tenant."):].partition(".")
            if metric:
                tenants.setdefault(t, {})[metric] = v
    if tenants:
        snap["serve"]["tenants"] = tenants
    hits = counters.get("serve.cache.hit", 0)
    lookups_c = hits + counters.get("serve.cache.miss", 0)
    if lookups_c:
        snap["serve"]["cache_hit_rate"] = _ratio(hits, lookups_c)
    if snap["serve"]:
        # Derived serving SLOs: fraction of requests that rode a >1
        # coalesced batch, and the latency percentiles from the serve
        # layer's own reservoir (the registry's histograms keep only
        # streaming moments).  The module lookup goes through
        # sys.modules so a run that never imported the serve layer —
        # or a disabled-telemetry run, whose counters stay empty and
        # never reach this branch — folds nothing extra.
        import sys as _sys

        snap["serve"]["coalesce_ratio"] = _ratio(
            counters.get("serve.coalesced", 0),
            counters.get("serve.requests", 0),
        )
        srv = _sys.modules.get("libskylark_tpu.serve")
        if srv is not None:
            snap["serve"].update(srv.latency_percentiles())
    router = {
        k.split(".", 1)[1]: v
        for k, v in counters.items()
        if k.startswith("router.")
    }
    if router:
        # Fleet front-door counters (placements, affinity_hits, joins,
        # ejects, sheds, failovers) fold only when a router actually
        # ran — single-server snapshots keep their exact PR-12 shape.
        router["affinity_ratio"] = _ratio(
            counters.get("router.affinity_hits", 0),
            counters.get("router.placements", 0),
        )
        snap["router"] = router
    autoscale = {
        k.split(".", 1)[1]: v
        for k, v in counters.items()
        if k.startswith("autoscale.")
    }
    if autoscale:
        # Membership control-loop counters (ticks, scale_ups,
        # scale_downs, drains_done, spawn_failures) fold only when an
        # autoscaler ran.
        snap["autoscale"] = autoscale
    registry_live = {
        k.split(".", 1)[1]: v
        for k, v in counters.items()
        if k.startswith("registry.")
    }
    if registry_live:
        # Live-registry epoch counters (epoch.bumps, per-kind mints,
        # epoch.misses = code-116 refusals) — present only once an
        # entity registered or mutated.
        snap["registry"] = registry_live
    train = {
        k.split(".", 1)[1]: v
        for k, v in counters.items()
        if k.startswith("train.")
    }
    if train:
        # Distributed-training counters (runs, iterations, consensus
        # merges, escalations, repartitions, registered hand-offs) —
        # present only when a trainer ran.
        snap["train"] = train
    slo_counters = {
        k.split(".", 1)[1]: v
        for k, v in counters.items()
        if k.startswith("slo.") and not k.startswith("slo.budget_remaining")
    }
    if slo_counters or any(
        k.startswith("slo.budget_remaining.") for k in snap["gauges"]
    ):
        # SLO error-budget state: counters (observed, breaches, burns,
        # recoveries) plus the per-objective budget report — present
        # only once an objective observed traffic.
        from .slo import slo_report

        snap["slo"] = slo_counters
        objectives = slo_report()
        if objectives:
            snap["slo"]["objectives"] = objectives
    timeline_counters = {
        k.split(".", 1)[1]: v
        for k, v in counters.items()
        if k.startswith("timeline.")
    }
    if timeline_counters:
        # Time-series ring counters (ticks) — present only once a
        # window closed.
        snap["timeline"] = timeline_counters
    return snap


def run_summary(name: str, info: dict | None = None, **attrs):
    """Terminal ledger event of one solver run.

    Every ``(x, info)`` solver entrypoint calls this with its ``info``
    dict right before returning (static contract in
    ``tests/test_review_regressions.py``), so the ledger's last word on
    a run carries the recovery ledger, the row/batch accounting, AND the
    registry + plan-cache counters to correlate them against.  Returns
    the event's ``seq`` (None when disabled).

    This is also the policy layer's persistence point: pending profile
    observations flush to the ``SKYLARK_POLICY_DIR`` store here — BEFORE
    the telemetry gate, so profiles persist even with telemetry off
    (``policy.flush`` is an allocation-free no-op when the policy layer
    is disabled or storeless).
    """
    from .. import policy

    policy.flush(name, info)
    if not config.enabled():
        return None
    payload = dict(attrs)
    payload["info"] = dict(info or {})
    payload["snapshot"] = snapshot()
    seq = event("run_summary", name, payload)
    flush()
    return seq


def report(distributed: bool = False) -> str:
    """Counter table, optionally reduced min/max/avg over processes.

    Reuses :func:`~libskylark_tpu.utils.timer.timer_report` wholesale:
    same ``process_allgather`` collective, same CRC32 name-signature
    misalignment guard, same three-column reduction — telemetry counters
    simply ride where phase totals normally do.
    """
    from ..utils.timer import timer_report

    snap = REGISTRY.snapshot()
    totals = {k: float(v) for k, v in snap["counters"].items()}
    for k, g in snap["gauges"].items():
        try:
            totals[f"gauge.{k}"] = float(g)
        except (TypeError, ValueError):
            continue
    return timer_report(totals, distributed=distributed)
