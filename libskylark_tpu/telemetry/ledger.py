"""The JSONL run ledger: one machine-readable event stream per process.

Schema (one object per line, monotonically sequenced within the
process):

    {"ts": <unix seconds>, "seq": <int>, "pid": <int>,
     "kind": <event class>, "name": <event name>, "attrs": {...}}

``seq`` is allocated under the registry's single lock, so the ledger
order is total per process even with concurrent emitters; ``ts`` is
wall clock (informational — ``seq`` is the ordering key).  The file is
``ledger-<pid>.jsonl`` under the configured directory, so multi-process
jobs never interleave writers.

Lifecycle discipline: the sink opens lazily on the FIRST event that has
both telemetry enabled and a directory configured, and only THEN
registers its atexit flush — an import (or a fully disabled run) leaves
the process's atexit table untouched (pinned by
``tests/test_review_regressions.py``).  With no directory configured,
events still sequence and count in the registry; nothing is written.
"""

from __future__ import annotations

import atexit
import json
import os
import time

from . import config
from .registry import LOCK

__all__ = ["configure", "event", "emit", "ledger_path", "flush", "close"]

_STATE = {
    "dir": None,       # configure() override; else SKYLARK_TELEMETRY_DIR
    "path": None,
    "fh": None,
    "seq": 0,
    "atexit": False,
}


def configure(directory) -> None:
    """Point the ledger at ``directory`` (overrides
    ``SKYLARK_TELEMETRY_DIR``; ``None`` reverts to the env knob).  An
    already-open sink is closed so the next event reopens in the new
    location."""
    with LOCK:
        _close_locked()
        _STATE["dir"] = str(directory) if directory else None


def ledger_path() -> str | None:
    """Path of the open ledger file (``None`` before the first write)."""
    return _STATE["path"]


def _coerce(obj):
    # numpy / jax scalars and arrays → plain JSON values.
    item = getattr(obj, "item", None)
    if item is not None and getattr(obj, "ndim", 1) == 0:
        return item()
    tolist = getattr(obj, "tolist", None)
    if tolist is not None:
        return tolist()
    return str(obj)


def _ensure_open_locked():
    if _STATE["fh"] is not None:
        return _STATE["fh"]
    directory = _STATE["dir"] or config.ledger_dir()
    if not directory:
        return None
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f"ledger-{os.getpid()}.jsonl")
    _STATE["fh"] = open(path, "a", encoding="utf-8")
    _STATE["path"] = path
    if not _STATE["atexit"]:
        # Registered only once a file actually opened: disabled imports
        # must leave the atexit table untouched.
        atexit.register(close)
        _STATE["atexit"] = True
    return _STATE["fh"]


def event(kind: str, name: str, attrs: dict | None = None):
    """Emit one ledger event; returns its ``seq`` (None when disabled).

    Call sites on hot paths should gate on ``telemetry.enabled()``
    themselves so the disabled path never builds the ``attrs`` dict.
    """
    if not config.enabled():
        return None
    rec_attrs = attrs or {}
    with LOCK:
        _STATE["seq"] += 1
        seq = _STATE["seq"]
        fh = _ensure_open_locked()
        if fh is not None:
            fh.write(
                json.dumps(
                    {
                        "ts": round(time.time(), 6),
                        "seq": seq,
                        "pid": os.getpid(),
                        "kind": kind,
                        "name": name,
                        "attrs": rec_attrs,
                    },
                    default=_coerce,
                )
                + "\n"
            )
    return seq


def emit(kind: str, name: str, **attrs):
    """Keyword-flavored :func:`event` for cold call sites."""
    if not config.enabled():
        return None
    return event(kind, name, attrs)


def flush() -> None:
    with LOCK:
        if _STATE["fh"] is not None:
            _STATE["fh"].flush()


def _close_locked() -> None:
    if _STATE["fh"] is not None:
        try:
            _STATE["fh"].flush()
            _STATE["fh"].close()
        except OSError:
            pass  # best-effort: a dead filesystem must not mask the run
        _STATE["fh"] = None
        _STATE["path"] = None


def close() -> None:
    """Flush and close the sink (idempotent; re-opens on the next event)."""
    with LOCK:
        _close_locked()
