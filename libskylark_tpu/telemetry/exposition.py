"""Prometheus text exposition of the telemetry snapshot.

Renders ``snapshot()`` (or any dict of its shape) as Prometheus
text-format 0.0.4 — the lingua franca every fleet scraper, the serve
``GET /metrics`` endpoint, and ``skylark-top --url`` share.  Naming
rules, stable so dashboards survive refactors:

- every metric is prefixed ``skylark_``; dots and other non-word
  characters in registry names become underscores;
- distinct raw names that sanitize to the SAME metric name (``a-b`` vs
  ``a.b``) are disambiguated: every member of a colliding group gets a
  short crc32 suffix (``skylark_a_b_3f2a91_total``) so no two raw
  series ever alias each other;
- counters are suffixed ``_total`` (``serve.requests`` →
  ``skylark_serve_requests_total``);
- per-tenant series (``serve.tenant.<tenant>.<metric>``) export with a
  proper ``{tenant="..."}`` label on a shared
  ``skylark_serve_tenant_<metric>`` family instead of a tenant-mangled
  metric name;
- histograms expose their streaming moments as four series:
  ``_count``, ``_sum``, ``_min``, ``_max``; histograms with buckets
  enabled (:func:`~.registry.enable_buckets`) export a real
  ``# TYPE ... histogram`` family with cumulative ``_bucket{le=...}``
  series (``+Inf`` included) whose ``_count``/``_sum`` cover the
  bucketed observations so the family is self-consistent;
- ``slo.budget_remaining.<key>`` gauges export as one
  ``skylark_slo_budget_remaining{objective="<key>"}`` family;
- the plan-cache block exports as ``skylark_plans_<counter>`` and the
  derived ratios (``plan_cache_hit_rate``, ``prefetch_overlap``,
  ``overlap_efficiency``, serve ``coalesce_ratio`` and latency
  percentiles) as gauges, skipped when undefined (``None``) rather
  than exported as NaN.

Rendering reads ONE consistent registry snapshot (one lock
acquisition) and never touches the worker thread — the concurrency
contract pinned by the scrape test in ``tests/test_trace.py``.
"""

from __future__ import annotations

import re
import zlib

__all__ = ["prometheus_text", "CONTENT_TYPE"]

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_SANITIZE = re.compile(r"[^a-zA-Z0-9_]")
_TENANT_RE = re.compile(r"^serve\.tenant\.(.+)\.([a-zA-Z0-9_]+)$")
_SLO_GAUGE_PREFIX = "slo.budget_remaining."


def _name(raw: str) -> str:
    n = _SANITIZE.sub("_", str(raw))
    if not n or n[0].isdigit():
        n = "_" + n
    return f"skylark_{n}"


def _short_hash(raw: str) -> str:
    return format(zlib.crc32(str(raw).encode("utf-8")), "08x")[:6]


def _disambiguate(raws) -> dict:
    """``{raw: base_metric_name}`` — when several raw names sanitize to
    the same metric name, EVERY member of the colliding group gets a
    crc32 suffix (order-independent, stable across renders)."""
    groups: dict = {}
    for r in raws:
        groups.setdefault(_name(r), []).append(r)
    out = {}
    for base, members in groups.items():
        if len(members) == 1:
            out[members[0]] = base
        else:
            for r in members:
                out[r] = f"{base}_{_short_hash(r)}"
    return out


def _esc_label(v) -> str:
    return (
        str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _num(v) -> str:
    f = float(v)
    return repr(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


class _Writer:
    """Accumulates lines, emitting each family's TYPE line exactly once
    (and before its first sample, as the 0.0.4 format requires)."""

    def __init__(self):
        self.lines: list[str] = []
        self._typed: set = set()

    def sample(self, name: str, kind: str, value, labels=None,
               family: str | None = None) -> None:
        """Append one sample; the TYPE line is keyed on ``family`` (for
        histogram ``_bucket``/``_count``/``_sum`` children) or on the
        sample name itself."""
        if value is None:
            return
        fam = family or name
        if fam not in self._typed:
            self._typed.add(fam)
            self.lines.append(f"# TYPE {fam} {kind}")
        if labels:
            lab = ",".join(f'{k}="{_esc_label(v)}"' for k, v in labels)
            self.lines.append(f"{name}{{{lab}}} {_num(value)}")
        else:
            self.lines.append(f"{name} {_num(value)}")


def _split_tenant(items: dict):
    """Partition ``{raw: value}`` into plain entries and
    ``{metric: [(tenant, value), ...]}`` tenant-labeled families."""
    plain: dict = {}
    tenant: dict = {}
    for k, v in items.items():
        m = _TENANT_RE.match(k)
        if m:
            tenant.setdefault(m.group(2), []).append((m.group(1), v))
        else:
            plain[k] = v
    return plain, tenant


def _emit_histogram(w: _Writer, base: str, h: dict, labels=None) -> None:
    buckets = h.get("buckets")
    if buckets and buckets.get("le"):
        le = buckets["le"]
        counts = buckets["counts"]
        cum = 0
        for bound, c in zip(le, counts):
            cum += c
            w.sample(base + "_bucket", "histogram", cum,
                     (labels or []) + [("le", _num(bound))], family=base)
        cum += counts[len(le)] if len(counts) > len(le) else 0
        w.sample(base + "_bucket", "histogram", cum,
                 (labels or []) + [("le", "+Inf")], family=base)
        # _count/_sum cover the bucketed observations so that
        # +Inf bucket == _count always holds within the family.
        w.sample(base + "_count", "histogram", buckets["count"], labels,
                 family=base)
        w.sample(base + "_sum", "histogram", buckets["sum"], labels,
                 family=base)
    else:
        w.sample(base + "_count", "counter", h["count"], labels)
        w.sample(base + "_sum", "counter", h["sum"], labels)
    w.sample(base + "_min", "gauge", h["min"], labels)
    w.sample(base + "_max", "gauge", h["max"], labels)


def prometheus_text(snap: dict | None = None, *, extra_gauges=None) -> str:
    """Prometheus 0.0.4 text body for ``snap`` (default: a fresh
    ``telemetry.snapshot()``).  ``extra_gauges`` lets a caller inject
    point-in-time gauges sampled outside the registry (the serve front
    adds its live queue depth)."""
    if snap is None:
        from .report import snapshot

        snap = snapshot()
    w = _Writer()

    counters, tenant_counters = _split_tenant(dict(snap.get("counters") or {}))
    names = _disambiguate(counters)
    for k in sorted(counters):
        w.sample(names[k] + "_total", "counter", counters[k])
    for metric in sorted(tenant_counters):
        fam = f"skylark_serve_tenant_{_SANITIZE.sub('_', metric)}_total"
        for tenant, v in sorted(tenant_counters[metric]):
            w.sample(fam, "counter", v, [("tenant", tenant)])

    gauges = dict(snap.get("gauges") or {})
    gauges.update(extra_gauges or {})
    slo_gauges = {}
    for k in list(gauges):
        if k.startswith(_SLO_GAUGE_PREFIX):
            slo_gauges[k[len(_SLO_GAUGE_PREFIX):]] = gauges.pop(k)
    plain_gauges = {k: v for k, v in gauges.items()
                    if isinstance(v, (int, float))}
    names = _disambiguate(plain_gauges)
    for k in sorted(plain_gauges):
        w.sample(names[k], "gauge", plain_gauges[k])
    for key in sorted(slo_gauges):
        v = slo_gauges[key]
        if isinstance(v, (int, float)):
            w.sample("skylark_slo_budget_remaining", "gauge", v,
                     [("objective", key)])

    hists, tenant_hists = _split_tenant(dict(snap.get("histograms") or {}))
    names = _disambiguate(hists)
    for k in sorted(hists):
        _emit_histogram(w, names[k], hists[k])
    for metric in sorted(tenant_hists):
        fam = f"skylark_serve_tenant_{_SANITIZE.sub('_', metric)}"
        for tenant, h in sorted(tenant_hists[metric]):
            _emit_histogram(w, fam, h, [("tenant", tenant)])

    for k, v in sorted((snap.get("plans") or {}).items()):
        if isinstance(v, (int, float)):
            w.sample(_name(f"plans_{k}"), "gauge", v)
    for k in ("plan_cache_hit_rate", "prefetch_overlap",
              "overlap_efficiency"):
        w.sample(_name(k), "gauge", snap.get(k))
    serve = snap.get("serve") or {}
    for k in ("coalesce_ratio", "latency_p50_ms", "latency_p99_ms"):
        if k in serve and f"serve.{k}" not in (snap.get("counters") or {}):
            w.sample(_name(f"serve_{k}"), "gauge", serve[k])
    if "world" in snap:
        w.sample(_name("fleet_world"), "gauge", snap["world"])
    return "\n".join(w.lines) + "\n"
