"""Prometheus text exposition of the telemetry snapshot.

Renders ``snapshot()`` (or any dict of its shape) as Prometheus
text-format 0.0.4 — the lingua franca every fleet scraper, the serve
``GET /metrics`` endpoint, and ``skylark-top --url`` share.  Naming
rules, stable so dashboards survive refactors:

- every metric is prefixed ``skylark_``; dots and other non-word
  characters in registry names become underscores;
- counters are suffixed ``_total`` (``serve.requests`` →
  ``skylark_serve_requests_total``);
- histograms expose their streaming moments as four series:
  ``_count``, ``_sum``, ``_min``, ``_max``;
- the plan-cache block exports as ``skylark_plans_<counter>`` and the
  derived ratios (``plan_cache_hit_rate``, ``prefetch_overlap``,
  ``overlap_efficiency``, serve ``coalesce_ratio`` and latency
  percentiles) as gauges, skipped when undefined (``None``) rather
  than exported as NaN.

Rendering reads ONE consistent registry snapshot (one lock
acquisition) and never touches the worker thread — the concurrency
contract pinned by the scrape test in ``tests/test_trace.py``.
"""

from __future__ import annotations

import re

__all__ = ["prometheus_text", "CONTENT_TYPE"]

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_SANITIZE = re.compile(r"[^a-zA-Z0-9_]")


def _name(raw: str) -> str:
    n = _SANITIZE.sub("_", str(raw))
    if not n or n[0].isdigit():
        n = "_" + n
    return f"skylark_{n}"


def _num(v) -> str:
    f = float(v)
    return repr(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def prometheus_text(snap: dict | None = None, *, extra_gauges=None) -> str:
    """Prometheus 0.0.4 text body for ``snap`` (default: a fresh
    ``telemetry.snapshot()``).  ``extra_gauges`` lets a caller inject
    point-in-time gauges sampled outside the registry (the serve front
    adds its live queue depth)."""
    if snap is None:
        from .report import snapshot

        snap = snapshot()
    lines: list[str] = []

    def emit(name, kind, value):
        if value is None:
            return
        lines.append(f"# TYPE {name} {kind}")
        lines.append(f"{name} {_num(value)}")

    for k in sorted(snap.get("counters") or {}):
        emit(_name(k) + "_total", "counter", snap["counters"][k])
    gauges = dict(snap.get("gauges") or {})
    gauges.update(extra_gauges or {})
    for k in sorted(gauges):
        v = gauges[k]
        if isinstance(v, (int, float)):
            emit(_name(k), "gauge", v)
    for k in sorted(snap.get("histograms") or {}):
        h = snap["histograms"][k]
        base = _name(k)
        emit(base + "_count", "counter", h["count"])
        emit(base + "_sum", "counter", h["sum"])
        emit(base + "_min", "gauge", h["min"])
        emit(base + "_max", "gauge", h["max"])
    for k, v in sorted((snap.get("plans") or {}).items()):
        if isinstance(v, (int, float)):
            emit(_name(f"plans_{k}"), "gauge", v)
    for k in ("plan_cache_hit_rate", "prefetch_overlap",
              "overlap_efficiency"):
        emit(_name(k), "gauge", snap.get(k))
    serve = snap.get("serve") or {}
    for k in ("coalesce_ratio", "latency_p50_ms", "latency_p99_ms"):
        if k in serve and f"serve.{k}" not in (snap.get("counters") or {}):
            emit(_name(f"serve_{k}"), "gauge", serve[k])
    if "world" in snap:
        emit(_name("fleet_world"), "gauge", snap["world"])
    return "\n".join(lines) + "\n"
