"""libskylark_tpu — TPU-native randomized numerical linear algebra & sketching.

A from-scratch JAX/XLA/Pallas re-design of the capabilities of libSkylark
(distributed sketching, randomized SVD/least-squares, Krylov solvers,
kernel machines via random features, graph analytics) for TPU meshes:
counter-based shard-local sketch realization, GSPMD/pjit sharding instead of
Elemental distribution templates, `lax.while_loop` solvers, and ICI
collectives instead of MPI.
"""

__version__ = "0.3.0"

from . import (
    core,
    graph,
    guard,
    io,
    linalg,
    ml,
    parallel,
    plans,
    resilient,
    serve,
    sketch,
    solvers,
    streaming,
    telemetry,
    utils,
)
from .core import SketchContext

__all__ = [
    "core",
    "graph",
    "guard",
    "io",
    "linalg",
    "ml",
    "parallel",
    "plans",
    "resilient",
    "serve",
    "sketch",
    "solvers",
    "streaming",
    "telemetry",
    "utils",
    "SketchContext",
    "__version__",
]
