"""skylark-top: a live terminal view of a serving fleet.

Tails whichever observability surfaces it is pointed at — any mix of:

- ``--url``: a ``serve_http`` front end; polls ``/stats``, ``/healthz``
  and ``/metrics`` (queue depth, coalesce ratio, p50/p99, shed and
  fallback counters, flight-recorder violation ids via ``/traces``).
  Repeatable: several ``--url`` flags render one per-replica fleet
  table (queue depth, QPS, primed rungs, heartbeat age); pointing one
  ``--url`` at a router front door expands its membership table the
  same way, plus — when the front door runs an autoscaler — the
  membership control-loop panel (bounds, owned/draining replicas, the
  decision-ledger tail).
- ``--telemetry-dir``: the JSONL run-ledger directory
  (``ledger-<pid>.jsonl``); shows event-kind totals and the most recent
  guard verdicts / dumped traces.
- ``--root``: an elastic checkpoint root; folds the epoch-fenced
  ``host-*/progress.jsonl`` ledgers into per-rank progress
  (``telemetry.fold_ledgers``).

Pure stdlib + the telemetry fold helpers — no server-side dependency
beyond the HTTP endpoints, so it runs on a bastion host against a
remote port forward.  ``--once`` renders a single frame and exits
(scripts, tests); otherwise the screen refreshes every ``--interval``
seconds until Ctrl-C.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time
import urllib.request

__all__ = ["main", "render_frame"]


def _fetch_json(url: str, timeout: float = 2.0) -> dict:
    """GET + parse, ALWAYS returning a dict: transport failures, JSON
    that does not parse (a dying replica truncates mid-body), and JSON
    that parses to a non-object all come back as ``{"_error": ...}`` —
    a displayed fact, never a dashboard traceback."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as fh:
            obj = json.loads(fh.read().decode())
    except Exception as e:  # noqa: BLE001 — a dead server is a displayed fact
        return {"_error": f"{type(e).__name__}: {e}"}
    if not isinstance(obj, dict):
        return {"_error": f"malformed response ({type(obj).__name__})"}
    return obj


def _dict(v) -> dict:
    """A truncated/hostile payload's nested field, dict-or-nothing."""
    return v if isinstance(v, dict) else {}


def _list(v) -> list:
    return list(v) if isinstance(v, (list, tuple)) else []


def _tail_ledgers(telemetry_dir: str, limit: int = 2048) -> dict:
    """Fold the run-ledger files: event-kind totals plus the latest
    guard / trace / error events (the incident feed)."""
    kinds: dict[str, int] = {}
    incidents: list[dict] = []
    for path in sorted(glob.glob(os.path.join(telemetry_dir, "ledger-*.jsonl"))):
        try:
            with open(path, encoding="utf-8", errors="replace") as fh:
                lines = fh.readlines()[-limit:]
        except OSError:
            continue
        for line in lines:
            try:
                rec = json.loads(line)
            except ValueError:  # torn tail
                continue
            kind = rec.get("kind", "?")
            kinds[kind] = kinds.get(kind, 0) + 1
            if kind in ("guard", "error", "trace"):
                incidents.append(rec)
    incidents.sort(key=lambda r: float(r.get("ts", 0) or 0))
    return {"kinds": kinds, "incidents": incidents[-8:]}


def _fmt(v, nd=2):
    if v is None:
        return "n/a"
    if isinstance(v, float):
        return f"{v:.{nd}f}"
    return str(v)


def _serve_lines(stats: dict, health: dict, traces: dict) -> list[str]:
    if "_error" in stats:
        return [f"  server: UNREACHABLE ({stats['_error']})"]
    c = _dict(stats.get("counters"))
    lat = _dict(stats.get("latency"))
    reqs = c.get("requests", 0)
    out = []
    backend = health.get("backend", "?")
    reg = _dict(health.get("registry"))
    out.append(
        f"  backend {backend}  models {reg.get('models', '?')}"
        f"  systems {reg.get('systems', '?')}"
        f"  primed {len(_list(health.get('primed')))}"
        f"  worker {'up' if health.get('worker_alive') else 'DOWN'}"
    )
    coalesce = (c.get("coalesced", 0) / reqs) if reqs else None
    out.append(
        f"  queue {stats.get('queue_depth', '?')}"
        f"  requests {reqs}  ok {c.get('ok', 0)}"
        f"  errors {c.get('errors', 0)}"
        f"  coalesce {_fmt(coalesce)}"
    )
    out.append(
        f"  p50 {_fmt(lat.get('latency_p50_ms'))} ms"
        f"  p99 {_fmt(lat.get('latency_p99_ms'))} ms"
        f"  shed_admission {c.get('shed_admission', 0)}"
        f"  shed_deadline {c.get('shed_deadline', 0)}"
        f"  shed_quota {c.get('shed_quota', 0)}"
        f"  solo_retries {c.get('solo_retries', 0)}"
    )
    hits, misses = c.get("cache.hit", 0), c.get("cache.miss", 0)
    if hits or misses:
        rate = hits / (hits + misses) if (hits + misses) else None
        out.append(
            f"  cache hits {hits}  misses {misses}  hit_rate {_fmt(rate)}"
            f"  evictions {c.get('cache.evictions', 0)}"
            f"  invalidations {c.get('cache.invalidations', 0)}"
        )
    # Per-tenant QoS table from the serve.tenant.<t>.<metric> counters
    # (the /stats counters arrive with the "serve." prefix stripped).
    tenants: dict = {}
    for k, v in c.items():
        if k.startswith("tenant."):
            t, _, metric = k[len("tenant."):].partition(".")
            if metric:
                tenants.setdefault(t, {})[metric] = v
    if tenants:
        out.append(
            "  tenant                 reqs      ok    hits  shed q/a/d"
        )
        for t in sorted(tenants):
            m = tenants[t]
            shed = (
                f"{m.get('shed_quota', 0)}/{m.get('shed_admission', 0)}"
                f"/{m.get('shed_deadline', 0)}"
            )
            out.append(
                f"  {t:<20} {m.get('requests', 0):>6}"
                f"  {m.get('ok', 0):>6}  {m.get('cache_hits', 0):>6}"
                f"  {shed}"
            )
    if traces and "_error" not in traces:
        viol = _list(traces.get("violations"))
        line = (
            f"  traces: {len(_list(traces.get('recent')))} recent, "
            f"{len(viol)} violating"
        )
        if viol:
            line += f"  last: {viol[-1]}"
        out.append(line)
    return out


def _slo_lines(slo: dict) -> list[str]:
    """The SLO error-budget panel.  An older replica without the
    ``/slo`` endpoint (404) — or a dying one — renders ``n/a``; a
    healthy replica with no declared objectives renders nothing."""
    if "_error" in slo:
        return ["  slo: n/a"]
    objectives = _dict(slo.get("objectives"))
    if not objectives:
        return []
    out = [
        "  objective              thr(ms)  target%  window   bad"
        "   budget  state"
    ]
    for key in sorted(objectives):
        o = _dict(objectives[key])
        state = "BURNING" if o.get("burning") else "ok"
        out.append(
            f"  {str(key):<22} {_fmt(o.get('threshold_ms'), 1):>7}"
            f"  {_fmt(o.get('target_pct'), 2):>7}"
            f"  {str(o.get('window', '?')):>6}"
            f"  {str(o.get('bad', '?')):>4}"
            f"  {_fmt(o.get('budget_remaining'), 3):>7}  {state}"
        )
    return out


_SPARK_CHARS = "▁▂▃▄▅▆▇█"


def _spark(values, width: int = 32) -> str:
    """Unicode sparkline over the last ``width`` numeric values."""
    vals = [float(v) for v in values if isinstance(v, (int, float))]
    vals = vals[-width:]
    if not vals:
        return "n/a"
    lo, hi = min(vals), max(vals)
    span = hi - lo
    if span <= 0:
        return _SPARK_CHARS[0] * len(vals)
    return "".join(
        _SPARK_CHARS[min(7, int((v - lo) / span * 8))] for v in vals
    )


def _timeline_lines(timeline: dict) -> list[str]:
    """Sparkline panel over the replica's ``/timeline`` ring.  Missing
    endpoint (older replica) or hostile payloads render ``n/a`` rows,
    never a crash."""
    if "_error" in timeline:
        return ["  timeline: n/a"]
    windows = [_dict(w) for w in _list(timeline.get("windows"))]
    if not windows:
        return ["  timeline: (no windows yet)"]
    derived = [_dict(w.get("derived")) for w in windows]
    out = [
        f"  timeline ({len(windows)} windows @"
        f" {_fmt(timeline.get('interval_s'), 1)}s)"
    ]
    for key, label in (
        ("qps", "qps"),
        ("p99_ms", "p99 ms"),
        ("queue_depth", "queue"),
        ("cache_hit_rate", "cache hit"),
    ):
        vals = [d.get(key) for d in derived]
        nums = [v for v in vals if isinstance(v, (int, float))]
        last = nums[-1] if nums else None
        out.append(f"  {label:<10} {_spark(vals)}  last {_fmt(last)}")
    return out


def _ledger_lines(fold: dict) -> list[str]:
    out = [
        "  events: "
        + (
            "  ".join(f"{k} {v}" for k, v in sorted(fold["kinds"].items()))
            or "(none)"
        )
    ]
    for rec in fold["incidents"]:
        attrs = rec.get("attrs") or {}
        bits = [f"  [{rec.get('kind')}] {rec.get('name')}"]
        for key in ("code", "action", "stage", "rung", "status", "verdict"):
            if key in attrs:
                bits.append(f"{key}={attrs[key]}")
        out.append(" ".join(bits))
    return out


def _rank_lines(hosts: dict) -> list[str]:
    out = [
        f"  epoch {hosts.get('epoch')}  rows {hosts.get('rows_total', 0)}"
        f"  stale {hosts.get('stale_records', 0)}"
        f"  lost {hosts.get('lost_hosts', [])}"
    ]
    for rank in sorted(hosts.get("ranks", {})):
        s = hosts["ranks"][rank]
        age = time.time() - s["last_ts"] if s["last_ts"] else None
        out.append(
            f"  rank {rank:>3}: rows {s['rows']:>10}  batches"
            f" {s['batches']:>6}  seq {s['last_seq']:>6}"
            f"  last write {_fmt(age, 1)}s ago"
        )
    return out


def _autoscale_lines(scale: dict) -> list[str]:
    """The membership control-loop panel: current shape vs targets and
    the tail of the decision ledger."""
    params = _dict(scale.get("params"))
    out = [
        f"  tick {scale.get('tick')}  bounds"
        f" [{params.get('min_replicas')}, {params.get('max_replicas')}]"
        f"  queue_high {_fmt(params.get('queue_high'))}"
        f"  p99_high {_fmt(params.get('p99_high_ms'))} ms"
        f"  cooldown {scale.get('cooldown')}"
    ]
    owned = [str(n) for n in _list(scale.get("owned"))]
    draining = [str(n) for n in _list(scale.get("draining"))]
    out.append(
        f"  owned {', '.join(owned) or '(none)'}"
        f"  draining {', '.join(draining) or '(none)'}"
    )
    for rec in _list(scale.get("ledger"))[-4:]:
        if not isinstance(rec, dict):
            continue
        bits = [f"  tick {str(rec.get('tick', '?')):>4}:"
                f" {rec.get('action', '?')}"]
        if rec.get("replica"):
            bits.append(str(rec["replica"]))
        bits.append(
            f"placeable {rec.get('placeable')}"
            f"  depth {_fmt(rec.get('mean_depth'))}"
            f"  p99 {_fmt(rec.get('p99_ms'))} ms"
        )
        out.append(" ".join(bits))
    return out


def _fleet_table(rows: list) -> list[str]:
    """Per-replica rows of (name, load report | None, heartbeat age)."""
    out = [
        "  replica                        queue    qps  primed  cache"
        "     heartbeat"
    ]
    for name, load, age in rows:
        if not isinstance(load, dict):
            out.append(f"  {name:<30} UNREACHABLE")
            continue
        qps = sum(
            float(_dict(v).get("rows_per_s") or 0.0)
            for v in _dict(load.get("throughput")).values()
        )
        cache = load.get("cache") or {}
        cc = (
            f"{cache.get('hits', 0)}h/{cache.get('entries', 0)}e"
            if isinstance(cache, dict) and cache
            else "n/a"
        )
        beat = "now" if age is None else f"{_fmt(age, 1)}s ago"
        out.append(
            f"  {name:<30} {str(load.get('queue_depth', '?')):>5}"
            f"  {qps:>5.1f}  {len(_list(load.get('primed'))):>6}  {cc:>8}"
            f"  {beat}"
        )
    return out


def render_frame(args, status: dict | None = None) -> str:
    """One full frame as a string (``--once`` prints exactly this).

    ``status`` (optional) is filled with ``{"urls": N, "answered": M}``
    so ``--once`` can report whether ANY replica responded.  A replica
    emitting malformed or truncated JSON renders as an UNREACHABLE-
    style row — one dying member never tracebacks the dashboard."""
    lines = [f"skylark-top  {time.strftime('%H:%M:%S')}"]
    urls = args.url or []
    if isinstance(urls, str):  # programmatic callers with a bare string
        urls = [urls]
    answered = 0
    fleet_rows: list = []
    for base in urls:
        base = base.rstrip("/")
        try:
            health = _fetch_json(base + "/healthz")
            if "_error" not in health:
                answered += 1
            if len(urls) == 1:
                stats = _fetch_json(base + "/stats")
                traces = _fetch_json(base + "/traces")
                lines.append(f"serve {base}")
                lines += _serve_lines(stats, health, traces)
                lines += _slo_lines(_fetch_json(base + "/slo"))
                lines += _timeline_lines(_fetch_json(base + "/timeline"))
            ok = "_error" not in health
            load = health.get("load") if ok else None
            if not isinstance(load, dict):
                load = None
            fleet = _dict(health.get("fleet")) if ok else None
            # A router front door has no load report of its own — it is
            # represented by its expanded members, not an UNREACHABLE
            # row.
            if load is not None or (len(urls) > 1 and not fleet):
                fleet_rows.append((base, load, None))
            if fleet:  # a router front door: expand its membership table
                for name, m in sorted(_dict(fleet.get("members")).items()):
                    m = _dict(m)
                    if m.get("draining"):
                        tag = f"{name} (draining)"
                    elif not m.get("placeable"):
                        tag = f"{name} (unplaceable)"
                    else:
                        tag = name
                    fleet_rows.append(
                        (tag, m.get("report"), m.get("heartbeat_age_s"))
                    )
            scale = health.get("autoscale") if ok else None
            if isinstance(scale, dict) and scale:
                lines.append(f"autoscale {base}")
                lines += _autoscale_lines(scale)
        except Exception as e:  # noqa: BLE001 — last-resort row, never a crash
            lines.append(f"serve {base}")
            lines.append(f"  server: UNREADABLE ({type(e).__name__}: {e})")
    if len(fleet_rows) > 1:
        lines.append(f"fleet ({len(fleet_rows)} replicas)")
        lines += _fleet_table(fleet_rows)
    if args.telemetry_dir:
        lines.append(f"ledger {args.telemetry_dir}")
        lines += _ledger_lines(_tail_ledgers(args.telemetry_dir))
    if args.root:
        from ..telemetry import fold_ledgers

        lines.append(f"fleet {args.root}")
        lines += _rank_lines(fold_ledgers(args.root))
    if status is not None:
        status["urls"] = len(urls)
        status["answered"] = answered
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="skylark-top",
        description="live terminal view of a skylark serving fleet",
    )
    p.add_argument(
        "--url", action="append", default=None,
        help="serve_http base URL to poll (/stats, /healthz, /metrics, "
             "/traces), e.g. http://127.0.0.1:8080; repeatable — "
             "several URLs render a per-replica fleet table",
    )
    p.add_argument(
        "--telemetry-dir", default=None,
        help="run-ledger directory to tail (ledger-<pid>.jsonl)",
    )
    p.add_argument(
        "--root", default=None,
        help="elastic checkpoint root: fold host-*/progress.jsonl into "
             "per-rank progress",
    )
    p.add_argument(
        "--interval", type=float, default=2.0,
        help="refresh period in seconds (default 2)",
    )
    p.add_argument(
        "--once", action="store_true",
        help="render a single frame and exit (no screen clearing)",
    )
    args = p.parse_args(argv)
    if not (args.url or args.telemetry_dir or args.root):
        p.error("nothing to watch: give --url, --telemetry-dir or --root")
    if args.once:
        status: dict = {}
        print(render_frame(args, status))
        # Exit 0 while ANY polled replica answered (a partially-dead
        # fleet is still a rendered fact); 1 only when every --url was
        # unreachable.  Ledger/root-only invocations always exit 0.
        if status.get("urls") and not status.get("answered"):
            return 1
        return 0
    try:
        while True:
            frame = render_frame(args)
            # whole-frame repaint: clear + home, no curses dependency
            sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
