"""skylark-graph-se: approximate adjacency spectral embedding driver.

≙ ``ml/skylark_graph_se.cpp`` (arc-list → ASE → embeddings file).
"""

from __future__ import annotations

import argparse
import sys

import numpy as np


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="skylark-graph-se")
    p.add_argument("graphfile", help="arc-list file")
    p.add_argument("--rank", "-k", type=int, default=8)
    p.add_argument("--seed", type=int, default=38734)
    p.add_argument("--num-iterations", "-i", type=int, default=2)
    p.add_argument("--sparse", action="store_true")
    p.add_argument(
        "--streamed", action="store_true",
        help="one-pass streamed Nystrom ASE: folds edge blocks, never "
        "materializes the adjacency (forces --num-iterations 0)",
    )
    p.add_argument("--batch-edges", type=int, default=65536)
    p.add_argument("--prefix", default="embedding")
    args = p.parse_args(argv)

    from ..core.context import SketchContext
    from ..graph import ASEParams, approximate_ase, read_arc_list

    G = read_arc_list(args.graphfile)
    print(f"Read graph: {G.n} vertices, {G.volume // 2} edges")
    if args.streamed:
        params = ASEParams(
            num_iterations=0, streamed=True, batch_edges=args.batch_edges
        )
    else:
        params = ASEParams(
            num_iterations=args.num_iterations, sparse=args.sparse
        )
    X, lam = approximate_ase(
        G,
        args.rank,
        SketchContext(seed=args.seed),
        params,
    )
    np.save(f"{args.prefix}.X.npy", np.asarray(X))
    with open(f"{args.prefix}.index.txt", "w") as f:
        for v in G.vertices:
            f.write(f"{v}\n")
    print(f"Embeddings ({G.n}x{args.rank}) -> {args.prefix}.X.npy; "
          f"eigenvalues: {np.asarray(lam)}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
