"""skylark-serve: the long-lived multi-tenant sketch-serving daemon.

Front ends (both speak the exact ``serve/protocol.py`` JSON frames —
the ``native/`` parity interchange; docs/serving.md has the schema):

- default: JSON-lines stdio — one request per stdin line, one response
  per stdout line, in order (inetd/systemd-socket style);
- ``--http PORT``: loopback HTTP — ``POST /`` with one request object
  or a list (a list is submitted concurrently and rides the
  cross-request coalescer), ``GET /stats``, ``GET /healthz``.

Warm state is loaded ONCE at startup: ``--model NAME=PATH`` JSON models
(``ml/model.py`` save format) and ``--system NAME=PATH`` least-squares
operators (``.npy``) become device-resident before the first request.
"""

from __future__ import annotations

import argparse
import sys

import numpy as np

from .common import (
    add_perf_args,
    add_policy_args,
    add_telemetry_args,
    print_perf_report,
    print_policy_report,
    print_telemetry_report,
    setup_perf,
    setup_policy,
    setup_telemetry,
)


def _name_path(spec: str, flag: str) -> tuple[str, str]:
    name, sep, path = spec.partition("=")
    if not sep or not name or not path:
        raise SystemExit(f"{flag} expects NAME=PATH, got {spec!r}")
    return name, path


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="skylark-serve")
    p.add_argument(
        "--model", action="append", default=[], metavar="NAME=PATH",
        help="register a saved model (ml/model.py JSON) under NAME; "
             "repeatable",
    )
    p.add_argument(
        "--system", action="append", default=[], metavar="NAME=PATH",
        help="register a least-squares operator A (.npy, tall 2-D) "
             "under NAME; its sketch + QR are precomputed at startup; "
             "repeatable",
    )
    p.add_argument("--sketch-type", default="FJLT",
                   help="sketch registry name for --system operators")
    p.add_argument("--sketch-size", type=int, default=None,
                   help="sketch rows for --system operators "
                        "(default: min(m, max(4n, n+16)))")
    p.add_argument("--seed", type=int, default=38734,
                   help="server SketchContext seed (fresh-sketch requests "
                        "reserve counters from it deterministically)")
    p.add_argument("--http", type=int, default=None, metavar="PORT",
                   help="serve HTTP on 127.0.0.1:PORT (0 picks a free "
                        "port) instead of JSON-lines stdio")
    p.add_argument("--max-queue", type=int, default=256,
                   help="admission bound: requests beyond this depth are "
                        "shed with code 112")
    p.add_argument("--max-coalesce", type=int, default=16,
                   help="max requests fused into one dispatch")
    p.add_argument("--coalesce-window-ms", type=float, default=0.0,
                   help="linger this long after the first request of a "
                        "batch to let coalesce-mates arrive")
    p.add_argument("--deadline-ms", type=float, default=None,
                   help="default per-request deadline; requests whose "
                        "queue wait exceeds it are shed with code 113")
    p.add_argument("--no-prime", dest="prime", action="store_false",
                   help="skip the startup priming dispatches that compile "
                        "the first-rung executables before traffic")
    p.add_argument("--x64", action="store_true")
    add_perf_args(p)
    add_policy_args(p)
    add_telemetry_args(p)
    args = p.parse_args(argv)

    if args.x64:
        import jax

        jax.config.update("jax_enable_x64", True)

    setup_telemetry(args)
    setup_perf(args)
    setup_policy(args)  # warm-starts the process (plan + XLA cache replay)

    from .. import serve
    from ..core import SketchContext

    params = serve.ServeParams(
        max_queue=args.max_queue,
        max_coalesce=args.max_coalesce,
        coalesce_window_ms=args.coalesce_window_ms,
        default_deadline_ms=args.deadline_ms,
        warm_start=False,  # setup_policy above already replayed
        prime=args.prime,
    )
    server = serve.Server(params, seed=args.seed)
    for spec in args.model:
        name, path = _name_path(spec, "--model")
        server.registry.load_model(name, path)
        print(f"model {name!r} <- {path}", file=sys.stderr)
    for spec in args.system:
        name, path = _name_path(spec, "--system")
        A = np.load(path)
        server.registry.register_system(
            name, A,
            context=SketchContext(seed=args.seed + 1),
            sketch_type=args.sketch_type,
            sketch_size=args.sketch_size,
        )
        print(f"system {name!r} <- {path} {A.shape}", file=sys.stderr)

    server.start()
    try:
        if args.http is not None:
            httpd = serve.serve_http(server, port=args.http)
            host, port = httpd.server_address[:2]
            print(f"serving http://{host}:{port}", file=sys.stderr)
            try:
                httpd.serve_forever()
            except KeyboardInterrupt:
                pass
            finally:
                httpd.shutdown()
        else:
            served = serve.serve_stdio(server, sys.stdin, sys.stdout)
            print(f"served {served} requests", file=sys.stderr)
    finally:
        server.stop()
        print_perf_report(args)
        print_policy_report(args)
        print_telemetry_report(args)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
