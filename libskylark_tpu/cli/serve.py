"""skylark-serve: the long-lived multi-tenant sketch-serving daemon.

Front ends (both speak the exact ``serve/protocol.py`` JSON frames —
the ``native/`` parity interchange; docs/serving.md has the schema):

- default: JSON-lines stdio — one request per stdin line, one response
  per stdout line, in order (inetd/systemd-socket style);
- ``--http PORT``: loopback HTTP — ``POST /`` with one request object
  or a list (a list is submitted concurrently and rides the
  cross-request coalescer), ``GET /stats``, ``GET /healthz``.

Warm state is loaded ONCE at startup: ``--model NAME=PATH`` JSON models
(``ml/model.py`` save format) and ``--system NAME=PATH`` least-squares
operators (``.npy``) become device-resident before the first request.

Fleet mode:

- ``--workers K`` pins K batcher threads to disjoint local devices
  (one admission queue, one coalescer — K fused dispatches in flight);
- ``--replicas K`` (HTTP only) runs K full replica servers behind an
  in-process :class:`~..serve.router.Router` front door — ``POST /``
  is placed by key affinity + load, ``GET /fleet`` shows membership;
- ``--join URL`` announces THIS server to a router front door at URL
  once it is primed and serving (zero-downtime rollout: the router
  fences the registry signature and only then places traffic here);
- ``--autoscale`` runs the membership control loop over the router
  front door: replicas spawn against ``--queue-high``/``--p99-high-ms``
  targets (primed before placeable, join-fenced) and drain to zero
  in-flight before leaving, bounded by ``--min-replicas`` /
  ``--max-replicas``; every decision is ledgered and visible on
  ``/healthz`` (the ``skylark-top`` autoscale panel).
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import urllib.error
import urllib.request

import numpy as np

from .common import (
    add_perf_args,
    add_policy_args,
    add_telemetry_args,
    print_perf_report,
    print_policy_report,
    print_telemetry_report,
    setup_perf,
    setup_policy,
    setup_telemetry,
)


def _name_path(spec: str, flag: str) -> tuple[str, str]:
    name, sep, path = spec.partition("=")
    if not sep or not name or not path:
        raise SystemExit(f"{flag} expects NAME=PATH, got {spec!r}")
    return name, path


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="skylark-serve")
    p.add_argument(
        "--model", action="append", default=[], metavar="NAME=PATH",
        help="register a saved model (ml/model.py JSON) under NAME; "
             "repeatable",
    )
    p.add_argument(
        "--system", action="append", default=[], metavar="NAME=PATH",
        help="register a least-squares operator A (.npy, tall 2-D) "
             "under NAME; its sketch + QR are precomputed at startup; "
             "repeatable",
    )
    p.add_argument("--sketch-type", default="FJLT",
                   help="sketch registry name for --system operators")
    p.add_argument("--sketch-size", type=int, default=None,
                   help="sketch rows for --system operators "
                        "(default: min(m, max(4n, n+16)))")
    p.add_argument("--seed", type=int, default=38734,
                   help="server SketchContext seed (fresh-sketch requests "
                        "reserve counters from it deterministically)")
    p.add_argument("--http", type=int, default=None, metavar="PORT",
                   help="serve HTTP on 127.0.0.1:PORT (0 picks a free "
                        "port) instead of JSON-lines stdio")
    p.add_argument("--max-queue", type=int, default=256,
                   help="admission bound: requests beyond this depth are "
                        "shed with code 112")
    p.add_argument("--max-coalesce", type=int, default=16,
                   help="max requests fused into one dispatch")
    p.add_argument("--coalesce-window-ms", type=float, default=0.0,
                   help="linger this long after the first request of a "
                        "batch to let coalesce-mates arrive")
    p.add_argument("--deadline-ms", type=float, default=None,
                   help="default per-request deadline; requests whose "
                        "queue wait exceeds it are shed with code 113")
    p.add_argument("--no-prime", dest="prime", action="store_false",
                   help="skip the startup priming dispatches that compile "
                        "the first-rung executables before traffic")
    p.add_argument("--workers", type=int, default=1,
                   help="batcher worker threads; K>1 pins each to a "
                        "distinct local device so independent batches "
                        "use every chip")
    p.add_argument("--replicas", type=int, default=1,
                   help="run K replica servers behind a router front "
                        "door (requires --http); requests are placed by "
                        "key affinity, live queue depth and profiled "
                        "throughput")
    p.add_argument("--join", default=None, metavar="URL",
                   help="announce this server to a router front door at "
                        "URL after priming (requires --http); registry "
                        "signatures are fenced at join")
    p.add_argument("--autoscale", action="store_true",
                   help="run the membership control loop over the router "
                        "front door (requires --http): replicas are "
                        "spawned against queue-depth/p99 targets and "
                        "drained to zero in-flight when idle")
    p.add_argument("--min-replicas", type=int, default=1,
                   help="autoscale floor: never drain below this many "
                        "placeable replicas")
    p.add_argument("--max-replicas", type=int, default=4,
                   help="autoscale ceiling: never spawn past this many "
                        "placeable replicas")
    p.add_argument("--queue-high", type=float, default=8.0,
                   help="mean placeable queue depth above which the "
                        "autoscaler spawns a replica")
    p.add_argument("--p99-high-ms", type=float, default=None,
                   help="optional p99 latency target; above it the "
                        "autoscaler spawns even with shallow queues")
    p.add_argument("--autoscale-interval", type=float, default=2.0,
                   help="autoscale decision period in seconds")
    p.add_argument("--state-dir", default=None, metavar="DIR",
                   help="durable serve state: every registry mutation is "
                        "journaled (write-ahead, fsync'd) to DIR before "
                        "it publishes, with periodic snapshot compaction; "
                        "with --replicas K each replica journals into "
                        "DIR/replica-i")
    p.add_argument("--recover", action="store_true",
                   help="restore the registry from --state-dir (snapshot "
                        "+ journal tail) before serving — the restarted "
                        "replica rejoins at the exact epoch it died at; "
                        "--model/--system names already recovered are "
                        "skipped")
    p.add_argument("--journal-compact-every", type=int, default=None,
                   metavar="N",
                   help="journal records between snapshot compactions "
                        "(default SKYLARK_JOURNAL_COMPACT_EVERY or 256; "
                        "0 disables compaction)")
    p.add_argument("--x64", action="store_true")
    add_perf_args(p)
    add_policy_args(p)
    add_telemetry_args(p)
    args = p.parse_args(argv)

    if args.x64:
        import jax

        jax.config.update("jax_enable_x64", True)

    setup_telemetry(args)
    setup_perf(args)
    setup_policy(args)  # warm-starts the process (plan + XLA cache replay)

    from .. import serve
    from ..core import SketchContext

    if args.replicas > 1 and args.http is None:
        raise SystemExit("--replicas needs --http (the front door is HTTP)")
    if args.join and args.http is None:
        raise SystemExit("--join needs --http (the router heartbeats this "
                         "server's /healthz)")
    if args.autoscale and args.http is None:
        raise SystemExit("--autoscale needs --http (the control loop runs "
                         "over a router front door)")

    params = serve.ServeParams(
        max_queue=args.max_queue,
        max_coalesce=args.max_coalesce,
        coalesce_window_ms=args.coalesce_window_ms,
        default_deadline_ms=args.deadline_ms,
        warm_start=False,  # setup_policy above already replayed
        prime=args.prime,
        workers=args.workers,
        state_dir=args.state_dir,
        recover=args.recover,
        journal_compact_every=args.journal_compact_every,
    )

    fleet_mode = args.replicas > 1 or args.autoscale
    made = [0]  # per-replica state subdirectories in fleet mode

    def make_server() -> "serve.Server":
        import os as _os
        from dataclasses import replace as _replace

        p_i = params
        if args.state_dir is not None and fleet_mode:
            # One journal per replica: the WAL is single-writer (one
            # append handle, one epoch counter), so fleet members must
            # not share a journal file.
            p_i = _replace(
                params,
                state_dir=_os.path.join(
                    args.state_dir, f"replica-{made[0]}"
                ),
            )
        made[0] += 1
        server = serve.Server(p_i, seed=args.seed)
        recovered = server.registry.describe() if args.recover else None
        for spec in args.model:
            name, path = _name_path(spec, "--model")
            if recovered is not None and name in recovered["models"]:
                print(f"model {name!r} recovered from journal",
                      file=sys.stderr)
                continue
            server.registry.load_model(name, path)
            print(f"model {name!r} <- {path}", file=sys.stderr)
        for spec in args.system:
            name, path = _name_path(spec, "--system")
            if recovered is not None and name in recovered["systems"]:
                print(f"system {name!r} recovered from journal",
                      file=sys.stderr)
                continue
            A = np.load(path)
            server.registry.register_system(
                name, A,
                context=SketchContext(seed=args.seed + 1),
                sketch_type=args.sketch_type,
                sketch_size=args.sketch_size,
            )
            print(f"system {name!r} <- {path} {A.shape}", file=sys.stderr)
        return server

    servers = [make_server() for _ in range(max(1, args.replicas))]
    router = None
    autoscaler = None
    if args.replicas > 1 or args.autoscale:
        router = serve.Router(
            serve.RouterParams(heartbeat_interval_s=1.0)
        ).start()
        for i, s in enumerate(servers):
            s.start()  # primed BEFORE the router can place traffic here
            rec = router.join(f"replica-{i}", server=s)
            print(f"replica-{i} joined (epoch {rec['epoch']})",
                  file=sys.stderr)
        front = router
        if args.autoscale:
            autoscaler = serve.Autoscaler(
                router, lambda name: make_server(),
                serve.AutoscaleParams(
                    min_replicas=args.min_replicas,
                    max_replicas=args.max_replicas,
                    queue_high=args.queue_high,
                    p99_high_ms=args.p99_high_ms,
                    interval_s=args.autoscale_interval,
                ),
            )
            for i, s in enumerate(servers):
                autoscaler.adopt(f"replica-{i}", s)
            router.autoscaler = autoscaler  # /healthz autoscale panel
            autoscaler.start()
            print(f"autoscale [{args.min_replicas}, {args.max_replicas}] "
                  f"queue_high {args.queue_high} every "
                  f"{args.autoscale_interval}s", file=sys.stderr)
    else:
        servers[0].start()
        front = servers[0]
    try:
        if args.http is not None:
            httpd = serve.serve_http(front, port=args.http)
            host, port = httpd.server_address[:2]
            print(f"serving http://{host}:{port}", file=sys.stderr)
            try:
                if args.join:
                    # Serve in the background so the router's join-time
                    # /healthz probe (which checks we are primed and
                    # alive) can reach us before we block.
                    t = threading.Thread(
                        target=httpd.serve_forever, daemon=True
                    )
                    t.start()
                    _announce_join(args.join, host, port)
                    t.join()
                else:
                    httpd.serve_forever()
            except KeyboardInterrupt:
                pass
            finally:
                httpd.shutdown()
        else:
            served = serve.serve_stdio(front, sys.stdin, sys.stdout)
            print(f"served {served} requests", file=sys.stderr)
    finally:
        if autoscaler is not None:
            autoscaler.stop()
        if router is not None:
            router.stop()
        for s in servers:
            s.stop()
        print_perf_report(args)
        print_policy_report(args)
        print_telemetry_report(args)
    return 0


def _announce_join(router_url: str, host: str, port: int) -> None:
    """POST /join to the front door; a code-109 signature fence comes
    back as a structured envelope and exits with its message."""
    url = f"http://{host}:{port}"
    req = urllib.request.Request(
        router_url.rstrip("/") + "/join",
        data=json.dumps({"name": f"{host}:{port}", "url": url}).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            rec = json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        body = e.read().decode(errors="replace")
        raise SystemExit(f"join rejected by {router_url}: {body}") from e
    print(f"joined fleet at {router_url}: epoch {rec.get('epoch')} "
          f"placeable {rec.get('placeable')}", file=sys.stderr)


if __name__ == "__main__":
    raise SystemExit(main())
