"""Shared helpers for the CLI drivers."""

from __future__ import annotations

import numpy as np

__all__ = ["load_classes", "print_test_metrics"]


def load_classes(modelfile):
    """Read the legacy label-decoding sidecar (pre-round-2 models; the
    coding now rides the model JSON itself — ``ml/model.py``)."""
    try:
        return np.load(str(modelfile) + ".classes.npy")
    except FileNotFoundError:
        return None


def print_test_metrics(model, Xt, yt, regression: bool) -> None:
    """Uniform test-set scoring block for all drivers."""
    if regression or getattr(model, "classes", None) is None:
        pred = np.asarray(model.predict(Xt))
        pred = pred[:, 0] if pred.ndim > 1 else pred
        err = np.linalg.norm(pred - yt) / max(np.linalg.norm(yt), 1e-30)
        print(f"Test relative error: {err:.4f}")
    else:
        pred = np.asarray(model.predict_labels(Xt, model.classes))
        acc = float((pred == yt).mean()) * 100
        print(f"Test accuracy: {acc:.2f}%")
