"""Shared helpers for the CLI drivers."""

from __future__ import annotations

import numpy as np

__all__ = ["save_classes", "load_classes", "print_test_metrics"]


def save_classes(modelfile, classes) -> None:
    """Persist the label decoding sidecar next to a saved model."""
    if classes is not None:
        np.save(str(modelfile) + ".classes.npy", np.asarray(classes))


def load_classes(modelfile):
    try:
        return np.load(str(modelfile) + ".classes.npy")
    except FileNotFoundError:
        return None


def print_test_metrics(model, Xt, yt, regression: bool) -> None:
    """Uniform test-set scoring block for all drivers."""
    if regression or getattr(model, "classes", None) is None:
        pred = np.asarray(model.predict(Xt))
        pred = pred[:, 0] if pred.ndim > 1 else pred
        err = np.linalg.norm(pred - yt) / max(np.linalg.norm(yt), 1e-30)
        print(f"Test relative error: {err:.4f}")
    else:
        pred = np.asarray(model.predict_labels(Xt, model.classes))
        acc = float((pred == yt).mean()) * 100
        print(f"Test accuracy: {acc:.2f}%")
