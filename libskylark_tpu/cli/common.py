"""Shared helpers for the CLI drivers."""

from __future__ import annotations

import numpy as np

__all__ = [
    "FILE_FORMATS",
    "add_perf_args",
    "add_policy_args",
    "add_telemetry_args",
    "load_classes",
    "load_dataset",
    "print_perf_report",
    "print_policy_report",
    "print_telemetry_report",
    "print_test_metrics",
    "scan_dims",
    "setup_perf",
    "setup_policy",
    "setup_telemetry",
    "stream_dataset",
]


def add_perf_args(p) -> None:
    """The shared compilation/plan observability flags (every driver)."""
    p.add_argument(
        "--xla-cache-dir", default=None,
        help="persistent XLA compilation cache directory: executables "
             "compiled in one run (plans included) are reloaded in the "
             "next instead of recompiled",
    )
    p.add_argument(
        "--plan-stats", action="store_true",
        help="print the sketch-plan cache counters "
             "(hits/misses/traces/compile time) on exit",
    )


def setup_perf(args) -> None:
    """Apply --xla-cache-dir before the first compilation.  Best-effort:
    jax versions without the persistent-cache knobs just warn."""
    if not getattr(args, "xla_cache_dir", None):
        return
    import warnings

    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", args.xla_cache_dir)
        # Cache everything: plans are often millisecond-compile but
        # high-count, exactly what the default thresholds would skip.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception as e:  # noqa: BLE001 — cache is an optimization
        warnings.warn(
            f"--xla-cache-dir not applied ({e!r}); continuing without "
            "the persistent compilation cache",
            RuntimeWarning,
            stacklevel=2,
        )


def print_perf_report(args) -> None:
    """Emit the plan-cache counter block when --plan-stats was given."""
    if not getattr(args, "plan_stats", False):
        return
    from .. import plans

    st = plans.stats()
    print(
        "plan cache: "
        f"{st['hits']} hits / {st['misses']} misses, "
        f"{st['traces']} traces, {st['compiles']} compiles "
        f"({st['compile_seconds']:.3f}s), "
        f"{st['bypasses']} bypasses, "
        f"{st['size']}/{st['max_size']} plans resident"
        + (f", {st['evictions']} evicted" if st["evictions"] else "")
    )

def add_policy_args(p) -> None:
    """The shared adaptive-policy flags (every driver;
    docs/autotuning.md)."""
    g = p.add_mutually_exclusive_group()
    g.add_argument(
        "--policy", dest="policy", action="store_true", default=None,
        help="enable the adaptive execution policy (the default; "
             "profile-driven routing/autotuning once --policy-dir or "
             "SKYLARK_POLICY_DIR points at a profile store)",
    )
    g.add_argument(
        "--no-policy", dest="policy", action="store_false",
        help="disable the policy layer (sets SKYLARK_POLICY=0): "
             "default routes, no profile reads or writes, no warm start",
    )
    p.add_argument(
        "--policy-dir", default=None,
        help="profile-store directory (profile-<pid>.json per process); "
             "enables persistent autotuning profiles and warm-start "
             "plan/XLA-cache replay across runs",
    )


def setup_policy(args) -> None:
    """Apply the policy flags and warm-start the process.  Call AFTER
    :func:`setup_perf` so an explicit ``--xla-cache-dir`` wins over the
    profile store's remembered one."""
    import os

    from .. import policy

    if getattr(args, "policy", None) is False:
        os.environ["SKYLARK_POLICY"] = "0"
        return
    if getattr(args, "policy", None) is True:
        os.environ["SKYLARK_POLICY"] = "1"
    if getattr(args, "policy_dir", None):
        policy.configure(args.policy_dir)
    ws = policy.warm_start()
    if ws["enabled"] and (ws["plans_replayed"] or ws["xla_cache_dir"]):
        print(
            f"policy warm start: {ws['plans_replayed']} plans replayed "
            f"({ws['plans_skipped']} skipped), "
            f"xla cache {ws['xla_cache_dir'] or 'unset'}, "
            f"{ws['seconds']:.3f}s"
        )


def print_policy_report(args) -> None:
    """Close out a policy run: the decision counters, when any fired."""
    if getattr(args, "policy", None) is False:
        return
    from .. import telemetry

    counters = telemetry.snapshot()["policy"]
    if counters:
        print(f"policy: {counters}")


def add_telemetry_args(p) -> None:
    """The shared telemetry flags (every driver; docs/observability.md)."""
    p.add_argument(
        "--telemetry", action="store_true",
        help="enable the telemetry layer (sets SKYLARK_TELEMETRY=1): "
             "spans + counters in-process, and the JSONL run ledger "
             "when --telemetry-dir is also given",
    )
    p.add_argument(
        "--telemetry-dir", default=None,
        help="directory for the JSONL run ledger "
             "(ledger-<pid>.jsonl; implies --telemetry)",
    )


def _telemetry_requested(args) -> bool:
    return bool(
        getattr(args, "telemetry", False)
        or getattr(args, "telemetry_dir", None)
    )


def setup_telemetry(args) -> None:
    """Apply --telemetry/--telemetry-dir before the solve starts."""
    if not _telemetry_requested(args):
        return
    import os

    from .. import telemetry

    os.environ["SKYLARK_TELEMETRY"] = "1"
    if args.telemetry_dir:
        telemetry.configure(args.telemetry_dir)


def print_telemetry_report(args) -> None:
    """Close out a --telemetry run: one summary line + the ledger path."""
    if not _telemetry_requested(args):
        return
    from .. import telemetry

    snap = telemetry.snapshot()
    hit = snap["plan_cache_hit_rate"]
    overlap = snap["prefetch_overlap"]
    print(
        "telemetry: "
        f"plan-cache hit rate {hit if hit is not None else 'n/a'}, "
        f"prefetch overlap {overlap if overlap is not None else 'n/a'}, "
        f"guard {snap['guard'] or {}}, checkpoint {snap['checkpoint'] or {}}"
    )
    telemetry.flush()
    if telemetry.ledger_path():
        print(f"telemetry ledger -> {telemetry.ledger_path()}")


# ≙ the reference's --fileformat choices (ml/options.hpp:46-47,173-174):
# libsvm covers LIBSVM_DENSE/LIBSVM_SPARSE (the --sparse flag picks the
# container), hdf5_dense/hdf5_sparse name the layout in the file itself
# (ml/io.hpp:869-889).
FILE_FORMATS = ("libsvm", "hdf5_dense", "hdf5_sparse")


def _widen(X, y, n_features):
    """Pad X's feature axis up to ``n_features`` (a test file converted
    from a sparse split can have a smaller max feature index than the
    train file — the libsvm reader pads the same way)."""
    if n_features is None or X.shape[1] >= n_features:
        return X, y
    if hasattr(X, "todense"):  # BCOO: same triplets, wider logical shape
        from jax.experimental import sparse as jsparse

        X = jsparse.BCOO(
            (X.data, X.indices), shape=(X.shape[0], int(n_features))
        )
    else:
        X = np.pad(
            np.asarray(X), ((0, 0), (0, int(n_features) - X.shape[1]))
        )
    return X, y


def load_dataset(path, fileformat: str, sparse: bool, n_features=None):
    """(X, y) under any supported --fileformat.  For hdf5_dense,
    ``sparse`` converts to BCOO after the read (matching the libsvm
    --sparse semantics); hdf5_sparse is sparse by construction."""
    from ..io import read_hdf5, read_libsvm

    if fileformat == "libsvm":
        return read_libsvm(path, n_features=n_features, sparse=sparse)
    if fileformat == "hdf5_dense":
        return _widen(*read_hdf5(path, sparse=sparse), n_features)
    if fileformat == "hdf5_sparse":
        return _widen(*read_hdf5(path, sparse=True), n_features)
    raise ValueError(f"unknown fileformat {fileformat!r}; use {FILE_FORMATS}")


def stream_dataset(path, fileformat: str, d: int, batch: int, sparse: bool):
    """Bounded-memory (X_batch, y_batch) iterator under any
    --fileformat (the streaming-predict IO seam)."""
    from ..io import stream_hdf5, stream_libsvm

    if fileformat == "libsvm":
        return stream_libsvm(path, d, batch, sparse=sparse)
    if fileformat == "hdf5_dense":
        return stream_hdf5(path, batch, sparse=sparse)
    if fileformat == "hdf5_sparse":
        return stream_hdf5(path, batch, sparse=True)
    raise ValueError(f"unknown fileformat {fileformat!r}; use {FILE_FORMATS}")


def scan_dims(path, fileformat: str) -> tuple[int, int]:
    """Global ``(n_examples, n_features)`` of a dataset WITHOUT loading
    it — streaming drivers need the shape up front (rows address the
    sketch counter stream).  LIBSVM takes one tokenize-only pass
    (``io.scan_libsvm_dims``); HDF5 reads the stored shape."""
    if fileformat == "libsvm":
        from ..io import scan_libsvm_dims

        return scan_libsvm_dims(path)
    if fileformat in ("hdf5_dense", "hdf5_sparse"):
        from ..utils.deps import require

        h5py = require("h5py")
        with h5py.File(path, "r") as f:
            if "X" in f:
                return int(f["X"].shape[0]), int(f["X"].shape[1])
            d, n, _ = (int(v) for v in f["dimensions"][:])
            return n, d
    raise ValueError(f"unknown fileformat {fileformat!r}; use {FILE_FORMATS}")


def load_classes(modelfile):
    """Read the legacy label-decoding sidecar (pre-round-2 models; the
    coding now rides the model JSON itself — ``ml/model.py``)."""
    try:
        return np.load(str(modelfile) + ".classes.npy")
    except FileNotFoundError:
        return None


def print_test_metrics(model, Xt, yt, regression: bool) -> None:
    """Uniform test-set scoring block for all drivers."""
    if regression or getattr(model, "classes", None) is None:
        pred = np.asarray(model.predict(Xt))
        pred = pred[:, 0] if pred.ndim > 1 else pred
        err = np.linalg.norm(pred - yt) / max(np.linalg.norm(yt), 1e-30)
        print(f"Test relative error: {err:.4f}")
    else:
        pred = np.asarray(model.predict_labels(Xt, model.classes))
        acc = float((pred == yt).mean()) * 100
        print(f"Test accuracy: {acc:.2f}%")
