"""skylark-convert2hdf5: LIBSVM → HDF5 converter
(≙ ``ml/skylark_convert2hdf5.cpp``)."""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="skylark-convert2hdf5")
    p.add_argument("input", help="LIBSVM file")
    p.add_argument("output", help="HDF5 file")
    p.add_argument("--sparse", action="store_true")
    args = p.parse_args(argv)

    from ..io import read_libsvm, write_hdf5

    X, y = read_libsvm(args.input, sparse=args.sparse)
    write_hdf5(args.output, X, y, sparse=args.sparse)
    print(f"Wrote {args.output}: X {X.shape}, Y {y.shape}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
