"""skylark-linear: accelerated least-squares driver
(≙ ``nla/skylark_linear.cpp:1-201``): reads a problem, runs
``faster_least_squares`` (Blendenpik), writes the solution."""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np

from .common import (
    add_perf_args,
    add_policy_args,
    add_telemetry_args,
    print_perf_report,
    print_policy_report,
    print_telemetry_report,
    setup_perf,
    setup_policy,
    setup_telemetry,
)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="skylark-linear")
    p.add_argument("inputfile", help="LIBSVM file: features = A, labels = b")
    p.add_argument("--solution", default="solution.npy")
    p.add_argument("--seed", type=int, default=38734)
    p.add_argument("--solver", default="accelerated",
                   choices=["exact", "sketched", "accelerated", "lsrn",
                            "refine", "auto"],
                   help="'refine' is certified mixed-precision iterative "
                        "refinement (docs/performance.md); 'auto' lets "
                        "the adaptive policy route between "
                        "sketch-and-solve, refine, Blendenpik, LSRN, and "
                        "exact from the profile store (docs/autotuning.md)")
    p.add_argument("--cond-est", action="store_true",
                   help="print a sketched condition / effective-rank "
                        "report of A before solving — the same numbers "
                        "the serve layer's cond_est endpoint reports "
                        "(docs/serving.md), computed locally")
    p.add_argument("--sparse", action="store_true")
    p.add_argument("--x64", action="store_true")
    p.add_argument("--shard", action="store_true",
                   help="shard the input rows over all visible devices")
    p.add_argument("--stream", action="store_true",
                   help="out-of-core sketch-and-solve: stream the input "
                        "in --batch-rows row blocks instead of reading "
                        "it whole (one pass; A is never resident)")
    p.add_argument("--batch-rows", type=int, default=4096,
                   help="rows per streamed batch (with --stream)")
    p.add_argument("--checkpoint-dir", default=None,
                   help="with --stream: checkpoint the partial sketch "
                        "so a killed pass can resume bit-for-bit")
    p.add_argument("--checkpoint-every", type=int, default=8,
                   help="streamed batches per checkpoint round")
    p.add_argument("--resume", action="store_true",
                   help="resume a streamed pass from the newest valid "
                        "checkpoint in --checkpoint-dir")
    p.add_argument("--distributed", action="store_true",
                   help="with --stream: partition the stream over the "
                        "jax.distributed world (every rank runs this "
                        "same command; each folds its own row range, "
                        "one psum merges; --checkpoint-dir becomes the "
                        "shared root of per-host state, and --resume "
                        "replays only each rank's uncheckpointed "
                        "batches)")
    p.add_argument("--resume-policy", default="strict",
                   choices=["strict", "repartition"],
                   help="with --distributed --resume: 'strict' demands "
                        "the same world size as the interrupted run "
                        "(exit on mismatch, code 109); 'repartition' "
                        "replans — every rank merges the completed "
                        "partial-sketch checkpoints it is assigned and "
                        "re-folds only the batches no host finished, so "
                        "a 4-host run can resume on 2 hosts (or 2 on 4)")
    p.add_argument("--collective-timeout", type=float, default=None,
                   metavar="SECONDS",
                   help="with --distributed: deadline for cross-host "
                        "collectives (handshake, psum merge); a hung or "
                        "straggling peer raises CollectiveTimeoutError "
                        "(code 110) naming the stragglers instead of "
                        "hanging forever (default: no deadline, or "
                        "SKYLARK_COLLECTIVE_TIMEOUT_S)")
    add_perf_args(p)
    add_policy_args(p)
    add_telemetry_args(p)
    args = p.parse_args(argv)

    import jax

    if args.x64:
        jax.config.update("jax_enable_x64", True)
    setup_perf(args)
    setup_policy(args)  # after setup_perf: explicit --xla-cache-dir wins
    setup_telemetry(args)
    import jax.numpy as jnp

    from ..core.context import SketchContext
    from ..io import read_libsvm
    from ..solvers import RegressionProblem, solve_regression

    if args.distributed and not args.stream:
        print("error: --distributed rides the streaming path; add "
              "--stream", file=sys.stderr)
        return 2
    if args.stream:
        return _stream_main(args)
    A, b = read_libsvm(args.inputfile, sparse=args.sparse)
    Aj = A if args.sparse else jnp.asarray(A)
    if args.shard:
        if args.sparse:
            print("warning: --shard ignores sparse inputs (BCOO stays on "
                  "one device)")
        else:
            from ..parallel import default_mesh, shard_rows_padded

            # Zero rows contribute zero residual: the LS solution is
            # unchanged; pad b to match.
            mesh = default_mesh()
            Aj, n_orig = shard_rows_padded(Aj, mesh)
            b = np.concatenate([b, np.zeros(Aj.shape[0] - n_orig)])
    if args.cond_est:
        _print_cond_est(args, Aj)
    t0 = time.perf_counter()
    result = solve_regression(
        RegressionProblem(Aj),
        jnp.asarray(b),
        solver=args.solver,
        context=SketchContext(seed=args.seed),
    )
    x = result[0] if isinstance(result, tuple) else result
    x = np.asarray(x)
    dt = time.perf_counter() - t0
    r = np.linalg.norm(np.asarray(Aj @ jnp.asarray(x)) - b)
    print(f"Solved {A.shape[0]}x{A.shape[1]} ({args.solver}) in {dt:.3f}s; "
          f"residual {r:.6e}")
    info = result[1] if isinstance(result, tuple) else None
    rf = (info or {}).get("refine") if isinstance(info, dict) else None
    if rf:
        gate = rf.get("gate")
        gate_s = f", gate {gate:.3e}" if isinstance(gate, float) else ""
        print(f"Refine: {rf.get('iters')} sweeps (rung {rf.get('rung')}, "
              f"halt {rf.get('halt', 'converged')}{gate_s})")
    np.save(args.solution, x)
    print(f"Solution -> {args.solution}")
    print_perf_report(args)
    print_policy_report(args)
    print_telemetry_report(args)
    return 0


def _print_cond_est(args, Aj) -> None:
    """The serve layer's cond_est report, computed locally: sketch once,
    QR, short-budget ``cond_est`` on R (which carries S·A's singular
    values) plus one small SVD for the effective rank — the full (m, n)
    matrix is never probed directly."""
    import jax.numpy as jnp

    from .. import plans
    from ..core.context import SketchContext
    from ..sketch.base import create_sketch
    from ..solvers.cond_est import CondEstParams, cond_est

    m, n = (int(d) for d in Aj.shape)
    s = min(max(4 * n, n + 16), m)
    S = create_sketch("CWT" if args.sparse else "FJLT", m, s,
                      SketchContext(seed=args.seed))
    R = jnp.linalg.qr(plans.apply(S, Aj, "columnwise"), mode="r")
    rep = cond_est(R, SketchContext(seed=0x5EED),
                   CondEstParams(iter_lim=60, powerits=25))
    sv = np.asarray(jnp.linalg.svd(R, compute_uv=False))
    cutoff = float(np.finfo(sv.dtype).eps) * n * float(sv[0])
    print(f"Cond-est: cond {float(rep.cond):.4e}, "
          f"sigma [{float(rep.sigma_min):.4e}, {float(rep.sigma_max):.4e}], "
          f"effective rank {int((sv > cutoff).sum())}/{n} "
          f"(sketch size {s})")


def _stream_main(args) -> int:
    """Out-of-core path: one streamed sketch-and-solve pass.

    ≙ the whole-file path with ``--solver sketched``, but the sketch
    applies decompose over row blocks (``streaming.sketch_least_squares``)
    so the file never needs to fit in memory.  Other --solver choices
    need the resident matrix and are rejected up front.
    """
    if args.solver not in ("sketched", "accelerated"):
        print(f"error: --stream is sketch-and-solve only; --solver "
              f"{args.solver} needs the resident matrix", file=sys.stderr)
        return 2
    if args.shard:
        print("warning: --shard is a whole-matrix layout; ignored with "
              "--stream", file=sys.stderr)

    from ..core.context import SketchContext
    from ..io import scan_libsvm_dims, stream_libsvm
    from ..linalg import streaming_least_squares
    from ..streaming import (
        ElasticParams,
        RowPartition,
        StreamParams,
        skip_batches,
        world_info,
    )

    nrows, ncols = scan_libsvm_dims(args.inputfile)
    print(f"Streaming {nrows}x{ncols} in batches of {args.batch_rows} rows")

    def batches(start: int):
        it = stream_libsvm(
            args.inputfile, ncols, batch=args.batch_rows,
            sparse=args.sparse,
        )
        return skip_batches(it, start) if start else it

    partition = None
    if args.distributed:
        # The elastic face carries the world knobs the plain stream
        # lacks: resume_policy decides strict-vs-repartition, the
        # collective timeout bounds the merge.
        sp = ElasticParams(
            checkpoint_dir=args.checkpoint_dir,
            checkpoint_every=args.checkpoint_every,
            resume=args.resume,
            resume_policy=args.resume_policy,
            collective_timeout_s=args.collective_timeout,
        )
        rank, world = world_info()
        partition = RowPartition(
            nrows=nrows, batch_rows=args.batch_rows, world_size=world
        )
        b0, b1 = partition.batch_range(rank)
        print(f"Distributed stream: rank {rank}/{world} owns batches "
              f"[{b0}, {b1}) of {partition.num_batches} "
              f"(resume policy: {args.resume_policy})")
    else:
        sp = StreamParams(
            checkpoint_dir=args.checkpoint_dir,
            checkpoint_every=args.checkpoint_every,
            resume=args.resume,
        )
    t0 = time.perf_counter()
    x, info = streaming_least_squares(
        batches, nrows, ncols, SketchContext(seed=args.seed),
        sparse=args.sparse, stream_params=sp, partition=partition,
    )
    x = np.asarray(x)
    dt = time.perf_counter() - t0
    print(f"Solved {nrows}x{ncols} (streamed sketch-and-solve, "
          f"{info['batches']} batches) in {dt:.3f}s")
    np.save(args.solution, x)
    print(f"Solution -> {args.solution}")
    print_perf_report(args)
    print_policy_report(args)
    print_telemetry_report(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
