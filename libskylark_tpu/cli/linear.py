"""skylark-linear: accelerated least-squares driver
(≙ ``nla/skylark_linear.cpp:1-201``): reads a problem, runs
``faster_least_squares`` (Blendenpik), writes the solution."""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="skylark-linear")
    p.add_argument("inputfile", help="LIBSVM file: features = A, labels = b")
    p.add_argument("--solution", default="solution.npy")
    p.add_argument("--seed", type=int, default=38734)
    p.add_argument("--solver", default="accelerated",
                   choices=["exact", "sketched", "accelerated", "lsrn"])
    p.add_argument("--sparse", action="store_true")
    p.add_argument("--x64", action="store_true")
    p.add_argument("--shard", action="store_true",
                   help="shard the input rows over all visible devices")
    args = p.parse_args(argv)

    import jax

    if args.x64:
        jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    from ..core.context import SketchContext
    from ..io import read_libsvm
    from ..solvers import RegressionProblem, solve_regression

    A, b = read_libsvm(args.inputfile, sparse=args.sparse)
    Aj = A if args.sparse else jnp.asarray(A)
    if args.shard:
        if args.sparse:
            print("warning: --shard ignores sparse inputs (BCOO stays on "
                  "one device)")
        else:
            from ..parallel import default_mesh, shard_rows_padded

            # Zero rows contribute zero residual: the LS solution is
            # unchanged; pad b to match.
            mesh = default_mesh()
            Aj, n_orig = shard_rows_padded(Aj, mesh)
            b = np.concatenate([b, np.zeros(Aj.shape[0] - n_orig)])
    t0 = time.perf_counter()
    result = solve_regression(
        RegressionProblem(Aj),
        jnp.asarray(b),
        solver=args.solver,
        context=SketchContext(seed=args.seed),
    )
    x = result[0] if isinstance(result, tuple) else result
    x = np.asarray(x)
    dt = time.perf_counter() - t0
    r = np.linalg.norm(np.asarray(Aj @ jnp.asarray(x)) - b)
    print(f"Solved {A.shape[0]}x{A.shape[1]} ({args.solver}) in {dt:.3f}s; "
          f"residual {r:.6e}")
    np.save(args.solution, x)
    print(f"Solution -> {args.solution}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
