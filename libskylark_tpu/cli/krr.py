"""skylark-krr: KRR/RLSC driver (≙ ``ml/skylark_krr.cpp:20-34,54-160``).

Algorithm choices mirror the reference's -a flag:
  0 exact kernel ridge, 1 faster (precond CG), 2 approximate (feature map),
  3 sketched approximate, 4 large-scale (block coordinate descent).
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np

from .common import (
    FILE_FORMATS,
    add_perf_args,
    add_policy_args,
    add_telemetry_args,
    print_perf_report,
    print_policy_report,
    print_telemetry_report,
    setup_perf,
    setup_policy,
    setup_telemetry,
)

_ALGS = {0: "exact", 1: "faster", 2: "approximate", 3: "sketched", 4: "largescale"}


def _kernel_params(args) -> dict:
    """--kernel flag → ctor kwargs (≙ the reference's per-kernel flags)."""
    return {
        "linear": {},
        "gaussian": {"sigma": args.sigma},
        "polynomial": {"q": args.q, "c": args.c, "gamma": args.gamma},
        "laplacian": {"sigma": args.sigma},
        "expsemigroup": {"beta": args.beta},
        "matern": {"nu": args.nu, "l": args.l},
    }[args.kernel]


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="skylark-krr")
    p.add_argument("--trainfile", required=True)
    p.add_argument("--testfile", default=None)
    p.add_argument("--modelfile", default="model.json")
    p.add_argument("--algorithm", "-a", type=int, default=1, choices=_ALGS)
    p.add_argument("--kernel", "-k", default="gaussian",
                   choices=["linear", "gaussian", "polynomial", "laplacian",
                            "expsemigroup", "matern"])
    p.add_argument("--lambda", dest="lam", type=float, default=0.01)
    p.add_argument("--sigma", "-x", type=float, default=1.0)
    p.add_argument("--q", type=int, default=2)
    p.add_argument("--c", type=float, default=1.0)
    p.add_argument("--gamma", type=float, default=1.0)
    p.add_argument("--beta", type=float, default=1.0)
    p.add_argument("--nu", type=float, default=1.5)
    p.add_argument("--l", type=float, default=1.0)
    p.add_argument("--numfeatures", "-f", type=int, default=1024)
    p.add_argument("--seed", type=int, default=38734)
    p.add_argument("--regression", action="store_true")
    p.add_argument("--use-fast", action="store_true")
    p.add_argument("--tolerance", type=float, default=1e-3)
    p.add_argument("--max-split", type=int, default=0)
    p.add_argument("--sparse", action="store_true")
    p.add_argument("--fileformat", default="libsvm", choices=FILE_FORMATS,
                   help="train/test container (hdf5 via "
                        "skylark-convert2hdf5 or the reference layout)")
    p.add_argument("--x64", action="store_true")
    p.add_argument("--checkpoint-dir", default=None,
                   help="directory for solver checkpoints; enables "
                        "preemption-safe chunked execution of the "
                        "iterative (-a 1) path")
    p.add_argument("--checkpoint-every", type=int, default=25,
                   help="solver iterations per checkpoint round")
    p.add_argument("--resume", action="store_true",
                   help="resume from the newest valid checkpoint in "
                        "--checkpoint-dir")
    p.add_argument("--stream", action="store_true",
                   help="out-of-core training: stream the train file in "
                        "--batch-rows row blocks, accumulating the "
                        "random-feature Gram per batch (approximate "
                        "KRR only; X is never resident)")
    p.add_argument("--batch-rows", type=int, default=4096,
                   help="rows per streamed batch (with --stream)")
    add_perf_args(p)
    add_policy_args(p)
    add_telemetry_args(p)
    args = p.parse_args(argv)

    import jax

    if args.x64:
        jax.config.update("jax_enable_x64", True)
    setup_perf(args)
    setup_policy(args)  # after setup_perf: explicit --xla-cache-dir wins
    setup_telemetry(args)
    import jax.numpy as jnp

    from ..core.context import SketchContext
    from ..ml import KrrParams, kernel_by_name
    from ..ml import krr as krr_mod
    from ..ml import rlsc as rlsc_mod
    from .common import load_dataset

    is_sparse = args.sparse or args.fileformat == "hdf5_sparse"
    if args.stream:
        return _stream_main(args, is_sparse)
    X, y = load_dataset(args.trainfile, args.fileformat, args.sparse)
    n, d = X.shape
    kernel = kernel_by_name(args.kernel, d, **_kernel_params(args))
    ctx = SketchContext(seed=args.seed)
    params = KrrParams(
        am_i_printing=True,
        log_level=1,
        use_fast=args.use_fast,
        tolerance=args.tolerance,
        max_split=args.max_split,
        checkpoint_dir=args.checkpoint_dir,
        checkpoint_every=args.checkpoint_every,
        resume=args.resume,
    )
    if args.checkpoint_dir and args.algorithm != 1:
        print("warning: --checkpoint-dir applies to the iterative "
              "solver (-a 1); other algorithms run unchunked",
              file=sys.stderr)

    Xj = X if is_sparse else jnp.asarray(X)
    t0 = time.perf_counter()
    alg = _ALGS[args.algorithm]
    yj = jnp.asarray(y) if args.regression else y
    if alg == "exact":
        fn = krr_mod.kernel_ridge if args.regression else rlsc_mod.kernel_rlsc
        model = fn(kernel, Xj, yj, args.lam, params)
    elif alg == "faster":
        fn = (krr_mod.faster_kernel_ridge if args.regression
              else rlsc_mod.faster_kernel_rlsc)
        model = fn(kernel, Xj, yj, args.lam, args.numfeatures, ctx, params)
    elif alg == "approximate":
        fn = (krr_mod.approximate_kernel_ridge if args.regression
              else rlsc_mod.approximate_kernel_rlsc)
        model = fn(kernel, Xj, yj, args.lam, args.numfeatures, ctx, params)
    elif alg == "sketched":
        fn = (krr_mod.sketched_approximate_kernel_ridge if args.regression
              else rlsc_mod.sketched_approximate_kernel_rlsc)
        model = fn(kernel, Xj, yj, args.lam, args.numfeatures, ctx, params)
    else:  # largescale (regression path; classification via coded targets)
        if args.regression:
            model = krr_mod.large_scale_kernel_ridge(
                kernel, Xj, yj, args.lam, args.numfeatures, ctx, params
            )
        else:
            from ..ml.coding import dummy_coding

            T, classes = dummy_coding(y)
            model = krr_mod.large_scale_kernel_ridge(
                kernel, Xj, T, args.lam, args.numfeatures, ctx, params
            )
            model.classes = classes
    dt = time.perf_counter() - t0
    print(f"Training ({alg}) took {dt:.3f} sec")

    from .common import print_test_metrics

    # Label coding rides the model JSON (≙ get_column_coding).
    model.save(args.modelfile)
    print(f"Model saved to {args.modelfile}")

    if args.testfile:
        Xt, yt = load_dataset(
            args.testfile, args.fileformat, args.sparse, n_features=d
        )
        Xtj = Xt if is_sparse else jnp.asarray(Xt)
        print_test_metrics(model, Xtj, yt, args.regression)
    print_perf_report(args)
    print_policy_report(args)
    print_telemetry_report(args)
    return 0


def _stream_main(args, is_sparse: bool) -> int:
    """Out-of-core training: one streamed pass of random-feature Gram
    accumulation (``streaming.kernel_ridge``) — the approximate (-a 2)
    path with X never resident.  Classification needs the label coding
    (and so the class set) before the pass; regression only for now."""
    if _ALGS[args.algorithm] != "approximate":
        print("error: --stream supports the approximate feature-map "
              "path only; use -a 2", file=sys.stderr)
        return 2
    if not args.regression:
        print("error: --stream needs --regression (label coding would "
              "need the class set before the pass)", file=sys.stderr)
        return 2

    import jax.numpy as jnp

    from ..core.context import SketchContext
    from ..ml import KrrParams, kernel_by_name
    from ..ml import krr as krr_mod
    from ..streaming import StreamParams, skip_batches
    from .common import load_dataset, print_test_metrics, scan_dims, stream_dataset

    n, d = scan_dims(args.trainfile, args.fileformat)
    print(f"Streaming {n}x{d} in batches of {args.batch_rows} rows")
    kernel = kernel_by_name(args.kernel, d, **_kernel_params(args))
    kparams = KrrParams(am_i_printing=True, log_level=1)

    def batches(start: int):
        it = stream_dataset(
            args.trainfile, args.fileformat, d, args.batch_rows,
            args.sparse or args.fileformat == "hdf5_sparse",
        )
        return skip_batches(it, start) if start else it

    sp = StreamParams(
        checkpoint_dir=args.checkpoint_dir,
        checkpoint_every=args.checkpoint_every,
        resume=args.resume,
    )
    t0 = time.perf_counter()
    model = krr_mod.streaming_approximate_kernel_ridge(
        kernel, batches, args.lam, args.numfeatures,
        SketchContext(seed=args.seed), kparams, stream_params=sp,
    )
    dt = time.perf_counter() - t0
    print(f"Training (streamed approximate, "
          f"{model.info['batches']} batches) took {dt:.3f} sec")
    model.save(args.modelfile)
    print(f"Model saved to {args.modelfile}")
    if args.testfile:
        Xt, yt = load_dataset(
            args.testfile, args.fileformat, args.sparse, n_features=d
        )
        Xtj = Xt if is_sparse else jnp.asarray(Xt)
        print_test_metrics(model, Xtj, yt, args.regression)
    print_perf_report(args)
    print_policy_report(args)
    print_telemetry_report(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
