"""skylark-ml: BlockADMM kernel-machine train/predict driver.

≙ ``ml/skylark_ml.cpp:15-174`` + ``hilbert_options_t``
(``ml/options.hpp:53-381``) + the GetSolver kernel×options → feature-map
factory (``ml/hilbert.hpp:11-219``).
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np

from .common import (
    FILE_FORMATS,
    add_perf_args,
    add_policy_args,
    add_telemetry_args,
    print_perf_report,
    print_policy_report,
    print_telemetry_report,
    setup_perf,
    setup_policy,
    setup_telemetry,
)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="skylark-ml")
    p.add_argument("--trainfile", default=None)
    p.add_argument("--valfile", default=None)
    p.add_argument("--testfile", default=None)
    p.add_argument("--modelfile", default="model.json")
    p.add_argument("--lossfunction", "-l", default="squared",
                   choices=["squared", "lad", "hinge", "logistic"])
    p.add_argument("--regularizer", "-r", default="l2",
                   choices=["none", "l2", "l1"])
    p.add_argument("--kernel", "-k", default="gaussian",
                   choices=["linear", "gaussian", "polynomial", "laplacian",
                            "expsemigroup", "matern"])
    p.add_argument("--kernelparam", "-g", type=float, default=1.0,
                   help="sigma / beta / gamma by kernel")
    p.add_argument("--kernelparam2", type=float, default=1.0)
    p.add_argument("--kernelparam3", type=float, default=1.0)
    p.add_argument("--lambda", dest="lam", type=float, default=0.01)
    p.add_argument("--rho", type=float, default=1.0)
    p.add_argument("--maxiter", "-i", type=int, default=20)
    p.add_argument("--numfeatures", "-f", type=int, default=1024)
    p.add_argument("--numfeaturepartitions", "-n", type=int, default=4)
    p.add_argument("--datapartitions", type=int, default=1)
    p.add_argument("--regression", action="store_true")
    p.add_argument("--usefast", action="store_true")
    p.add_argument("--seed", "-s", type=int, default=12345)
    p.add_argument("--sparse", action="store_true")
    p.add_argument("--fileformat", default="libsvm", choices=FILE_FORMATS,
                   help="train/val/test container (hdf5 via "
                        "skylark-convert2hdf5 or the reference layout)")
    p.add_argument("--x64", action="store_true")
    p.add_argument("--outputfile", "-o", default=None,
                   help="stream test predictions to this file (bounded "
                        "memory; one prediction per line)")
    p.add_argument("--batch", type=int, default=4096,
                   help="streaming predict batch size")
    p.add_argument("--checkpoint-dir", default=None,
                   help="directory for ADMM checkpoints; enables "
                        "preemption-safe chunked training")
    p.add_argument("--checkpoint-every", type=int, default=5,
                   help="ADMM iterations per checkpoint round")
    p.add_argument("--resume", action="store_true",
                   help="resume training from the newest valid checkpoint "
                        "in --checkpoint-dir")
    p.add_argument("--distributed", action="store_true",
                   help="train over the jax.distributed world (every rank "
                        "runs this same command; each streams its own row "
                        "partition of --trainfile, one psum per ADMM "
                        "iteration merges consensus; --checkpoint-dir "
                        "becomes the shared root of per-host stream + "
                        "train state; --datapartitions must align with "
                        "the world so every rank owns whole partitions)")
    p.add_argument("--batch-rows", type=int, default=256,
                   help="with --distributed: rows per streamed training "
                        "batch (partition granularity)")
    p.add_argument("--resume-policy", default="strict",
                   choices=["strict", "repartition"],
                   help="with --distributed --resume: 'strict' demands "
                        "the same world size as the interrupted run "
                        "(exit on mismatch, code 109); 'repartition' "
                        "re-streams each rank's NEW share at a bumped "
                        "epoch (feature buffers are positional, not "
                        "mergeable) and trains fresh under it, keeping "
                        "the recovery itself resumable")
    p.add_argument("--collective-timeout", type=float, default=None,
                   metavar="SECONDS",
                   help="with --distributed: deadline for cross-host "
                        "collectives (handshake, consensus psum); a hung "
                        "or straggling peer raises CollectiveTimeoutError "
                        "(code 110) naming the stragglers instead of "
                        "hanging forever (default: no deadline, or "
                        "SKYLARK_COLLECTIVE_TIMEOUT_S)")
    add_perf_args(p)
    add_policy_args(p)
    add_telemetry_args(p)
    args = p.parse_args(argv)

    import jax

    if args.x64:
        jax.config.update("jax_enable_x64", True)
    setup_telemetry(args)
    setup_perf(args)
    setup_policy(args)  # after setup_perf: explicit --xla-cache-dir wins
    import jax.numpy as jnp

    from ..core.context import SketchContext
    from ..ml import ADMMParams, BlockADMMSolver, FeatureMapModel, kernel_by_name
    from .common import load_dataset, stream_dataset

    if args.trainfile is None and args.testfile is None:
        p.error("need --trainfile (train) or --testfile + --modelfile (predict)")

    # hdf5_sparse yields BCOO regardless of --sparse; unify downstream.
    is_sparse = args.sparse or args.fileformat == "hdf5_sparse"

    if args.trainfile:
        X, y = load_dataset(args.trainfile, args.fileformat, args.sparse)
        n, d = X.shape
        kparams = {
            "linear": {},
            "gaussian": {"sigma": args.kernelparam},
            "polynomial": {"q": int(args.kernelparam), "c": args.kernelparam2,
                           "gamma": args.kernelparam3},
            "laplacian": {"sigma": args.kernelparam},
            "expsemigroup": {"beta": args.kernelparam},
            "matern": {"nu": args.kernelparam, "l": args.kernelparam2},
        }[args.kernel]
        kernel = kernel_by_name(args.kernel, d, **kparams)

        # Split numfeatures across partitions (≙ GetSolver block creation).
        J = max(1, args.numfeaturepartitions)
        sizes = [args.numfeatures // J] * J
        sizes[-1] += args.numfeatures - sum(sizes)
        ctx = SketchContext(seed=args.seed)
        tag = "fast" if args.usefast else "regular"
        maps = [kernel.create_rft(sz, tag, ctx) for sz in sizes if sz > 0]

        solver = BlockADMMSolver(
            args.lossfunction,
            args.regularizer,
            maps,
            ADMMParams(
                am_i_printing=True,
                log_level=1,
                rho=args.rho,
                lam=args.lam,
                maxiter=args.maxiter,
                data_partitions=args.datapartitions,
            ),
        )
        Xv = Yv = None
        if args.valfile:
            Xv, Yv = load_dataset(
                args.valfile, args.fileformat, args.sparse, n_features=d
            )
        t0 = time.perf_counter()
        if args.distributed:
            # Elastic multi-host path: every rank runs this same command,
            # streams its row partition, trains in lockstep (one psum per
            # outer iteration), and holds the identical model at the end.
            from ..ml.distributed import DistributedBlockADMMTrainer
            from ..streaming import ElasticParams, RowPartition, world_info

            if args.valfile:
                print("warning: --valfile is ignored under --distributed "
                      "(score the saved model instead)", file=sys.stderr)
            rank, world = world_info()
            partition = RowPartition(
                nrows=n, batch_rows=args.batch_rows, world_size=world
            )
            b0, b1 = partition.batch_range(rank)
            print(f"Distributed train: rank {rank}/{world} owns batches "
                  f"[{b0}, {b1}) of {partition.num_batches} "
                  f"(resume policy: {args.resume_policy})")
            Xd = np.asarray(X) if not is_sparse else X

            def source(start):
                def it():
                    for bi in range(start, partition.num_batches):
                        lo = bi * args.batch_rows
                        hi = min(lo + args.batch_rows, n)
                        yield Xd[lo:hi], np.asarray(y)[lo:hi]
                return it()

            trainer = DistributedBlockADMMTrainer(
                args.lossfunction, args.regularizer, maps, solver.params,
                ElasticParams(
                    checkpoint_dir=args.checkpoint_dir,
                    checkpoint_every=args.checkpoint_every,
                    resume=args.resume,
                    resume_policy=args.resume_policy,
                    collective_timeout_s=args.collective_timeout,
                ),
            )
            classes = (
                None if args.regression else np.unique(np.asarray(y))
            )
            model, dinfo = trainer.train(
                source, partition, classes=classes,
                regression=args.regression,
            )
            replays = sum(
                1
                for a in dinfo["recovery"]["attempts"]
                if a.get("action") == "replay"
            )
            print(f"Train report: iters={dinfo['iters']} "
                  f"consensus_residual={dinfo['consensus_residual']:.6e} "
                  f"precision={dinfo['precision']} replays={replays}")
        elif args.checkpoint_dir:
            # Preemption-safe path: host rounds of --checkpoint-every ADMM
            # iterations, a rotated CRC-guarded checkpoint after each.
            # Per-iteration validation scoring is a train()-only feature.
            from ..resilient import ResilientParams, ResilientRunner

            if args.valfile:
                print("warning: --valfile is ignored under "
                      "--checkpoint-dir (score the saved model instead)",
                      file=sys.stderr)
            model = ResilientRunner(
                solver.chunked(
                    np.asarray(X) if not is_sparse else X,
                    y,
                    regression=args.regression,
                ),
                ResilientParams(
                    am_i_printing=True,
                    log_level=1,
                    checkpoint_dir=args.checkpoint_dir,
                    checkpoint_every=args.checkpoint_every,
                    resume=args.resume,
                ),
            ).run()
        else:
            model = solver.train(
                np.asarray(X) if not is_sparse else X,
                y,
                regression=args.regression,
                Xv=Xv,
                Yv=Yv,
            )
        print(f"Training took {time.perf_counter() - t0:.3f} sec; "
              f"final objective {model.history[-1]:.6e}")
        # The model JSON embeds the label coding (≙ get_column_coding).
        model.save(args.modelfile)
        print(f"Model saved to {args.modelfile}")
    else:
        from ..ml import load_model
        from .common import load_classes

        model = load_model(args.modelfile)
        if getattr(model, "classes", None) is None:
            # Legacy sidecar from pre-embedded-coding saves.
            model.classes = load_classes(args.modelfile)

    if args.testfile:
        d = model.input_dim
        if args.outputfile:
            # Streaming predict (≙ the reference's line-by-line predict IO).
            n_done = correct = 0
            sq_err = sq_nrm = 0.0
            with open(args.outputfile, "w") as out:
                for Xb, yb in stream_dataset(
                    args.testfile, args.fileformat, d, args.batch, args.sparse
                ):
                    if not is_sparse:
                        Xb = jnp.asarray(Xb)
                    if args.regression or getattr(model, "classes", None) is None:
                        pred = np.asarray(model.predict(Xb))
                        pred = pred[:, 0] if pred.ndim > 1 else pred
                        sq_err += float(np.sum((pred - yb) ** 2))
                        sq_nrm += float(np.sum(yb**2))
                    else:
                        pred = np.asarray(
                            model.predict_labels(Xb, model.classes)
                        )
                        correct += int((pred == yb).sum())
                    n_done += len(yb)
                    out.writelines(f"{v}\n" for v in pred)
            if args.regression or getattr(model, "classes", None) is None:
                print(f"Test relative error: "
                      f"{(sq_err / max(sq_nrm, 1e-30)) ** 0.5:.4f} "
                      f"({n_done} examples)")
            else:
                print(f"Test accuracy: {correct * 100.0 / max(n_done, 1):.2f}% "
                      f"({n_done} examples)")
            print(f"Predictions -> {args.outputfile}")
        else:
            from .common import print_test_metrics

            Xt, yt = load_dataset(
                args.testfile, args.fileformat, args.sparse, n_features=d
            )
            Xtj = Xt if is_sparse else jnp.asarray(Xt)
            print_test_metrics(model, Xtj, yt, args.regression)
    print_perf_report(args)
    print_policy_report(args)
    print_telemetry_report(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
