"""Command-line drivers (≙ reference ``nla/skylark_*.cpp``, ``ml/skylark_*.cpp``).

Run as modules: ``python -m libskylark_tpu.cli.svd ...`` etc.
"""
