"""skylark-svd: randomized SVD driver (≙ ``nla/skylark_svd.cpp:1-477``).

Reads LIBSVM (or .npy), runs ``approximate_svd``, writes U/S/V as .npy.
``--profile`` generates a synthetic low-rank + noise matrix instead of
reading a file (≙ the reference's ``--profile`` synthetic mode,
``nla/skylark_svd.cpp:37-60``).
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="skylark-svd", description="Randomized (approximate) SVD"
    )
    p.add_argument("inputfile", nargs="?", help="LIBSVM or .npy matrix")
    p.add_argument("--rank", "-k", type=int, default=6)
    p.add_argument("--seed", type=int, default=38734)
    p.add_argument("--sparse", action="store_true", help="load as BCOO")
    p.add_argument(
        "--num-iterations", "-i", type=int, default=None,
        help="power-iteration sweeps (default 0; 1 with --stream, where "
        "f32 q=0 is documented-inaccurate on noisy spectra)",
    )
    p.add_argument("--oversampling-ratio", type=int, default=2)
    p.add_argument("--oversampling-additive", type=int, default=0)
    p.add_argument("--skip-qr", action="store_true")
    p.add_argument("--prefix", default="out", help="output prefix for U/S/V")
    p.add_argument(
        "--profile",
        nargs=2,
        type=int,
        metavar=("M", "N"),
        help="synthetic MxN profiling mode (no input file)",
    )
    p.add_argument("--x64", action="store_true", help="enable float64")
    p.add_argument("--shard", action="store_true",
                   help="shard the input rows over all visible devices")
    p.add_argument(
        "--stream",
        type=int,
        metavar="BLOCK_ROWS",
        help="with --profile: stream row panels of this size instead of "
        "materializing A (memory-bounded; any M divisible by BLOCK_ROWS)",
    )
    args = p.parse_args(argv)

    import jax

    if args.x64:
        jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    from ..core.context import SketchContext
    from ..io import read_libsvm
    from ..linalg import SVDParams, approximate_svd

    if args.num_iterations is None:
        args.num_iterations = 1 if args.stream is not None else 0
    params = SVDParams(
        oversampling_ratio=args.oversampling_ratio,
        oversampling_additive=args.oversampling_additive,
        num_iterations=args.num_iterations,
        skip_qr=args.skip_qr,
    )

    if args.stream is not None:
        if not args.profile:
            p.error("--stream requires --profile (streamed file IO: use the "
                    "library API with a custom block_fn)")
        from ..linalg import streaming_approximate_svd, synthetic_lowrank_blocks

        m, n = args.profile
        ctx = SketchContext(seed=args.seed)
        block_fn = synthetic_lowrank_blocks(
            ctx, m, n, args.rank, noise=0.01,
            dtype=jnp.float64 if args.x64 else jnp.float32,
        )
        t0 = time.perf_counter()
        u_block, s, V = streaming_approximate_svd(
            block_fn, (m, n), args.rank, ctx, params, block_rows=args.stream
        )
        jax.block_until_ready((s, V))
        dt = time.perf_counter() - t0
        np.save(f"{args.prefix}.S.npy", np.asarray(s))
        np.save(f"{args.prefix}.V.npy", np.asarray(V))
        print(f"Rank-{args.rank} streaming SVD of {m}x{n} in {dt:.3f}s "
              f"({m // args.stream} panels; U factored, not saved)")
        print(f"Leading singular values: {np.asarray(s)[: min(5, len(s))]}")
        return 0

    if args.profile:
        m, n = args.profile
        rng = np.random.default_rng(args.seed)
        k = args.rank
        A = (rng.standard_normal((m, k)) @ rng.standard_normal((k, n))).astype(
            np.float64 if args.x64 else np.float32
        )
        A += 0.01 * rng.standard_normal((m, n)).astype(A.dtype)
    elif args.inputfile:
        if args.inputfile.endswith(".npy"):
            A = np.load(args.inputfile)
        else:
            A, _ = read_libsvm(args.inputfile, sparse=args.sparse)
    else:
        p.error("need an inputfile or --profile M N")

    n_orig = None
    if args.shard:
        if args.sparse:
            print("warning: --shard ignores sparse inputs (BCOO stays on "
                  "one device)")
        else:
            from ..parallel import default_mesh, shard_rows_padded

            # Zero rows don't affect singular values/V; U is trimmed below.
            A, n_orig = shard_rows_padded(jnp.asarray(A), default_mesh())
    ctx = SketchContext(seed=args.seed)
    t0 = time.perf_counter()
    U, s, V = approximate_svd(A, args.rank, ctx, params)
    jax.block_until_ready((U, s, V))
    dt = time.perf_counter() - t0
    if n_orig is not None:
        U = U[:n_orig]
    np.save(f"{args.prefix}.U.npy", np.asarray(U))
    np.save(f"{args.prefix}.S.npy", np.asarray(s))
    np.save(f"{args.prefix}.V.npy", np.asarray(V))
    print(f"Rank-{args.rank} SVD of {U.shape[0]}x{V.shape[0]} in {dt:.3f}s")
    print(f"Leading singular values: {np.asarray(s)[: min(5, len(s))]}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
