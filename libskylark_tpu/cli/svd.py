"""skylark-svd: randomized SVD driver (≙ ``nla/skylark_svd.cpp:1-477``).

Reads LIBSVM, arc-list graphs (``--filetype arclist`` ≙ the reference's
``ARC_LIST`` + ``ReadArcList``, ``skylark_svd.cpp:169-171,246-248``),
HDF5 (reference layout, ``io/hdf5.py``), or .npy; runs
``approximate_svd`` (or ``approximate_symmetric_svd`` under
``--symmetric`` ≙ ``execute_sym``, ``skylark_svd.cpp:120-222``); writes
U/S/V as .npy, or as the reference's ASCII convention (``El::Write(...,
El::ASCII)`` to ``prefix.U`` / ``prefix.S`` / ``prefix.V``,
``skylark_svd.cpp:110-112``) with ``--ascii``.  ``--profile`` generates a
synthetic low-rank + noise matrix instead of reading a file (≙
``skylark_svd.cpp:37-60``).
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np

from .common import (
    add_perf_args,
    add_policy_args,
    add_telemetry_args,
    print_perf_report,
    print_policy_report,
    print_telemetry_report,
    setup_perf,
    setup_policy,
    setup_telemetry,
)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="skylark-svd", description="Randomized (approximate) SVD"
    )
    p.add_argument(
        "inputfile", nargs="?",
        help="LIBSVM / arc-list / HDF5 / .npy matrix",
    )
    p.add_argument("--rank", "-k", type=int, default=6)
    p.add_argument("--seed", type=int, default=38734)
    p.add_argument("--sparse", action="store_true", help="load as BCOO")
    p.add_argument(
        "--filetype",
        choices=("auto", "libsvm", "arclist", "hdf5", "npy"),
        default="auto",
        help="input format (auto: by extension, arc-lists need an "
        "explicit 'arclist' like the reference's --filetype ARC_LIST)",
    )
    p.add_argument(
        "--symmetric", action="store_true",
        help="treat the matrix as symmetric (eigendecomposition; writes "
        "S and V only, as the reference's execute_sym)",
    )
    p.add_argument(
        "--lower", action="store_true",
        help="with --symmetric: access the lower triangle (symmetrize "
        "from the lower part; upper is the default)",
    )
    p.add_argument(
        "--ascii", action="store_true",
        help="write prefix.U/.S/.V as ASCII text (the reference's "
        "El::Write convention) instead of .npy",
    )
    p.add_argument(
        "--num-iterations", "-i", type=int, default=None,
        help="power-iteration sweeps (default 0; 1 with --stream, where "
        "f32 q=0 is documented-inaccurate on noisy spectra)",
    )
    p.add_argument("--oversampling-ratio", type=int, default=2)
    p.add_argument("--oversampling-additive", type=int, default=0)
    p.add_argument("--skip-qr", action="store_true")
    p.add_argument("--prefix", default="out", help="output prefix for U/S/V")
    p.add_argument(
        "--profile",
        nargs=2,
        type=int,
        metavar=("M", "N"),
        help="synthetic MxN profiling mode (no input file)",
    )
    p.add_argument("--x64", action="store_true", help="enable float64")
    p.add_argument("--shard", action="store_true",
                   help="shard the input rows over all visible devices")
    p.add_argument(
        "--stream",
        type=int,
        metavar="BLOCK_ROWS",
        help="with --profile: stream row panels of this size instead of "
        "materializing A (memory-bounded; any M divisible by BLOCK_ROWS)",
    )
    add_perf_args(p)
    add_policy_args(p)
    add_telemetry_args(p)
    args = p.parse_args(argv)

    import jax

    if args.x64:
        jax.config.update("jax_enable_x64", True)
    setup_telemetry(args)
    setup_perf(args)
    setup_policy(args)  # after setup_perf: explicit --xla-cache-dir wins
    import jax.numpy as jnp

    from ..core.context import SketchContext
    from ..io import read_libsvm
    from ..linalg import SVDParams, approximate_svd

    if args.num_iterations is None:
        args.num_iterations = 1 if args.stream is not None else 0
    params = SVDParams(
        oversampling_ratio=args.oversampling_ratio,
        oversampling_additive=args.oversampling_additive,
        num_iterations=args.num_iterations,
        skip_qr=args.skip_qr,
    )

    if args.stream is not None:
        if not args.profile:
            p.error("--stream requires --profile (streamed file IO: use the "
                    "library API with a custom block_fn)")
        from ..linalg import streaming_approximate_svd, synthetic_lowrank_blocks

        m, n = args.profile
        ctx = SketchContext(seed=args.seed)
        block_fn = synthetic_lowrank_blocks(
            ctx, m, n, args.rank, noise=0.01,
            dtype=jnp.float64 if args.x64 else jnp.float32,
        )
        t0 = time.perf_counter()
        u_block, s, V = streaming_approximate_svd(
            block_fn, (m, n), args.rank, ctx, params, block_rows=args.stream
        )
        jax.block_until_ready((s, V))
        dt = time.perf_counter() - t0
        np.save(f"{args.prefix}.S.npy", np.asarray(s))
        np.save(f"{args.prefix}.V.npy", np.asarray(V))
        print(f"Rank-{args.rank} streaming SVD of {m}x{n} in {dt:.3f}s "
              f"({m // args.stream} panels; U factored, not saved)")
        print(f"Leading singular values: {np.asarray(s)[: min(5, len(s))]}")
        print_perf_report(args)
        print_policy_report(args)
        print_telemetry_report(args)
        return 0

    if args.profile:
        m, n = args.profile
        rng = np.random.default_rng(args.seed)
        k = args.rank
        A = (rng.standard_normal((m, k)) @ rng.standard_normal((k, n))).astype(
            np.float64 if args.x64 else np.float32
        )
        A += 0.01 * rng.standard_normal((m, n)).astype(A.dtype)
    elif args.inputfile:
        ftype = args.filetype
        if ftype == "auto":
            if args.inputfile.endswith(".npy"):
                ftype = "npy"
            elif args.inputfile.endswith((".h5", ".hdf5")):
                ftype = "hdf5"
            else:
                ftype = "libsvm"
        if ftype == "npy":
            A = np.load(args.inputfile)
        elif ftype == "hdf5":
            from ..io import read_hdf5

            A, _ = read_hdf5(args.inputfile, sparse=args.sparse)
        elif ftype == "arclist":
            # ≙ ReadArcList → adjacency SVD (spectral embedding input).
            from ..graph import read_arc_list

            G = read_arc_list(args.inputfile)
            A = G.adjacency_bcoo() if args.sparse else G.adjacency()
        else:
            A, _ = read_libsvm(args.inputfile, sparse=args.sparse)
    else:
        p.error("need an inputfile or --profile M N")

    def write(suffix, arr):
        # --ascii ≙ El::Write(X, prefix + suffix, El::ASCII): plain text,
        # one matrix row per line (skylark_svd.cpp:110-112).
        if args.ascii:
            np.savetxt(f"{args.prefix}{suffix}", np.atleast_1d(np.asarray(arr)))
        else:
            np.save(f"{args.prefix}{suffix}.npy", np.asarray(arr))

    ctx = SketchContext(seed=args.seed)
    if args.symmetric:
        # Runs on the unsharded matrix (the eigendecomposition densifies
        # and replicates anyway); --shard row-padding would break the
        # squareness check for genuinely square inputs.
        from ..linalg import approximate_symmetric_svd

        Ad = jnp.asarray(A.todense() if hasattr(A, "todense") else A)
        if Ad.shape[0] != Ad.shape[1]:
            p.error("--symmetric needs a square matrix")
        # Access one triangle only (≙ the uplo argument of
        # ApproximateSymmetricSVD; reference defaults to upper).
        tri = jnp.tril(Ad) if args.lower else jnp.triu(Ad)
        Ad = tri + tri.T - jnp.diag(jnp.diagonal(Ad))
        t0 = time.perf_counter()
        V, lam = approximate_symmetric_svd(Ad, args.rank, ctx, params)
        jax.block_until_ready((V, lam))
        dt = time.perf_counter() - t0
        write(".S", lam)
        write(".V", V)
        print(f"Rank-{args.rank} symmetric SVD of {Ad.shape[0]}"
              f"x{Ad.shape[1]} in {dt:.3f}s")
        print(f"Leading eigenvalues: {np.asarray(lam)[: min(5, len(lam))]}")
        print_perf_report(args)
        print_policy_report(args)
        print_telemetry_report(args)
        return 0

    n_orig = None
    if args.shard:
        if args.sparse:
            print("warning: --shard ignores sparse inputs (BCOO stays on "
                  "one device)")
        else:
            from ..parallel import default_mesh, shard_rows_padded

            # Zero rows don't affect singular values/V; U is trimmed below.
            A, n_orig = shard_rows_padded(jnp.asarray(A), default_mesh())
    t0 = time.perf_counter()
    U, s, V = approximate_svd(A, args.rank, ctx, params)
    jax.block_until_ready((U, s, V))
    dt = time.perf_counter() - t0
    if n_orig is not None:
        U = U[:n_orig]
    write(".U", U)
    write(".S", s)
    write(".V", V)
    print(f"Rank-{args.rank} SVD of {U.shape[0]}x{V.shape[0]} in {dt:.3f}s")
    print(f"Leading singular values: {np.asarray(s)[: min(5, len(s))]}")
    print_perf_report(args)
    print_policy_report(args)
    print_telemetry_report(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
