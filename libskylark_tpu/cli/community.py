"""skylark-community: seed-set local community detection driver.

≙ ``ml/skylark_community.cpp`` (interactive mode included).
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="skylark-community")
    p.add_argument("graphfile", help="arc-list file (u v per line)")
    p.add_argument("--seed", "-s", action="append", default=[],
                   help="seed vertex (repeatable)")
    p.add_argument("--alpha", type=float, default=0.85)
    p.add_argument("--gamma", type=float, default=5.0)
    p.add_argument("--epsilon", type=float, default=0.001)
    p.add_argument("--recursive", action="store_true")
    p.add_argument("--interactive", "-i", action="store_true",
                   help="read seed sets from stdin, one line each")
    args = p.parse_args(argv)

    from ..graph import find_local_cluster, read_arc_list

    G = read_arc_list(args.graphfile)
    print(f"Read graph: {G.n} vertices, {G.volume // 2} edges")

    def run(seed_names) -> bool:
        for name in seed_names:
            if name not in G.index:
                print(f"unknown vertex {name!r}")
                return False
        ids = [G.index[name] for name in seed_names]
        cluster, cond = find_local_cluster(
            G, ids, args.alpha, args.gamma, args.epsilon,
            recursive=args.recursive,
        )
        members = sorted(G.vertices[v] for v in cluster)
        print(f"Conductance: {cond:.6f}")
        print("Cluster:", " ".join(str(m) for m in members))
        return True

    if args.interactive:
        print("Enter seed vertices (space-separated), empty line to quit:")
        for line in sys.stdin:
            names = line.split()
            if not names:
                break
            run(names)
    else:
        if not args.seed:
            p.error("need at least one --seed (or --interactive)")
        if not run(args.seed):
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
