"""Mesh/sharding helpers — the single communication backend.

Replaces the reference's MPI substrate (Boost.MPI communicators, Elemental
grids, CombBLAS comm grids — ``utility/get_communicator.hpp:26-50``,
``utility/external/combblas_comm_grid.hpp``) with one module wrapping
``jax.sharding.Mesh`` + GSPMD shardings.  The reference's per-distribution
template specializations (``[MC,MR]``, ``[VC,*]``, ``[*,VR]``,
``[CIRC,CIRC]``, ...) collapse to `PartitionSpec`s over a named mesh;
collectives (psum / psum_scatter / all_gather / all_to_all) are emitted by
XLA from sharding constraints, or explicitly under ``shard_map`` where an
invariant must be enforced by hand.
"""

from .collectives import (
    HEARTBEAT_DIR,
    CollectiveWatchdog,
    ShardedBCOO,
    batch_sharded_program,
    columnwise_batch_sharded,
    columnwise_sharded,
    cross_host_psum,
    columnwise_sharded_sparse,
    columnwise_sharded_sparse_2d,
    columnwise_sharded_sparse_out,
    columnwise_sharded_sparse_out_2d,
    rowwise_sharded,
    rowwise_sharded_sparse,
    rowwise_sharded_sparse_out,
    suggest_sparse_out_capacity,
)
from .mesh import (
    ROWS,
    COLS,
    constrain_rows,
    default_mesh,
    fully_replicated,
    make_mesh,
    replicate,
    row_sharding,
    shard,
    shard_cols,
    shard_rows,
    shard_rows_padded,
    sharding,
)

__all__ = [
    "ROWS",
    "COLS",
    "default_mesh",
    "fully_replicated",
    "make_mesh",
    "replicate",
    "shard",
    "shard_cols",
    "shard_rows",
    "shard_rows_padded",
    "sharding",
    "row_sharding",
    "constrain_rows",
    "cross_host_psum",
    "CollectiveWatchdog",
    "HEARTBEAT_DIR",
    "rowwise_sharded",
    "batch_sharded_program",
    "columnwise_batch_sharded",
    "columnwise_sharded",
    "rowwise_sharded_sparse",
    "columnwise_sharded_sparse",
    "columnwise_sharded_sparse_2d",
    "columnwise_sharded_sparse_out",
    "columnwise_sharded_sparse_out_2d",
    "rowwise_sharded_sparse_out",
    "suggest_sparse_out_capacity",
    "ShardedBCOO",
]
