"""Device mesh and sharding helpers.

The reference's matrix distributions (Elemental's ``[MC,MR]``, ``[VC,*]``,
``[*,VC]``, ``[*,*]``, ``[CIRC,CIRC]`` — see SURVEY §2.7) map onto named
meshes + `PartitionSpec`s:

=================  ==========================================
Elemental          TPU equivalent
=================  ==========================================
``[MC,MR]``        2-D mesh, ``P(ROWS, COLS)``
``[VC,*]/[VR,*]``  1-D (or flattened 2-D) mesh, ``P(ROWS, None)``
``[*,VC]/[*,VR]``  ``P(None, COLS)``
``[*,*]``          fully replicated, ``P()``
``[CIRC,CIRC]``    host-gathered (only at API boundaries)
=================  ==========================================

Multi-host: callers run ``jax.distributed.initialize()`` before building a
mesh; everything below is host-count agnostic (``jax.devices()`` is global).
"""

from __future__ import annotations

import math
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "ROWS",
    "COLS",
    "make_mesh",
    "default_mesh",
    "sharding",
    "shard",
    "shard_rows",
    "shard_rows_padded",
    "shard_cols",
    "replicate",
    "fully_replicated",
]

# Canonical axis names: ROWS shards the long/sample dimension (≙ [VC,*]
# row distribution / MC grid rows), COLS the feature dimension (≙ MR).
ROWS = "rows"
COLS = "cols"


def make_mesh(
    shape: Sequence[int],
    axis_names: Sequence[str] = (ROWS, COLS),
    explicit: bool = False,
) -> Mesh:
    """Build a mesh of the given shape over all visible devices.

    Axes default to ``AxisType.Auto``: shardings placed on inputs propagate
    through jitted code with GSPMD choosing the communication schedule —
    the design stance of SURVEY §2.7 P4 (the reference hand-picks
    matrix-panel/panel-matrix/inner/outer GEMM schedules; XLA does this
    automatically).  Pass ``explicit=True`` for JAX's typed-sharding mode
    where every contraction must name its output sharding.
    """
    kind = (
        jax.sharding.AxisType.Explicit
        if explicit
        else jax.sharding.AxisType.Auto
    )
    return jax.make_mesh(
        tuple(shape), tuple(axis_names), axis_types=(kind,) * len(shape)
    )


def default_mesh(n_devices: int | None = None) -> Mesh:
    """Near-square 2-D (ROWS, COLS) mesh over the visible devices.

    ≙ Elemental's default approximately-square process grid
    (``El::Grid(comm)``).  A single device yields a 1x1 mesh, so all code
    paths are mesh-agnostic.
    """
    n = len(jax.devices()) if n_devices is None else int(n_devices)
    r = int(math.isqrt(n))
    while n % r:
        r -= 1
    return make_mesh((r, n // r), (ROWS, COLS))


def sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))


def shard(x, mesh: Mesh, *spec):
    """Place ``x`` with the given PartitionSpec entries."""
    return jax.device_put(x, sharding(mesh, *spec))


def row_sharding(mesh: Mesh, ndim: int = 2) -> NamedSharding:
    """The canonical ``[VC,*]`` sharding: dim 0 over the whole mesh."""
    axes = (
        mesh.axis_names[0]
        if len(mesh.axis_names) == 1
        else tuple(mesh.axis_names)
    )
    return NamedSharding(mesh, P(axes, *([None] * (ndim - 1))))


def shard_rows(x, mesh: Mesh):
    """Distribute dim 0 over the whole mesh (≙ ``[VC,*]``)."""
    return jax.device_put(x, row_sharding(mesh, np.ndim(x)))


def constrain_rows(x, mesh: Mesh):
    """Row-shard a traced value inside jit (``[VC,*]`` constraint).

    Uses ``with_sharding_constraint`` for Auto-axis meshes and
    ``jax.sharding.reshard`` for Explicit-axis ones (JAX rejects
    constraints on explicit axes)."""
    s = row_sharding(mesh, np.ndim(x))
    if any(
        t == jax.sharding.AxisType.Explicit
        for t in getattr(mesh, "axis_types", ())
    ):
        return jax.sharding.reshard(x, s)
    return jax.lax.with_sharding_constraint(x, s)


def shard_cols(x, mesh: Mesh):
    """Distribute the last dim over the whole mesh (≙ ``[*,VR]``)."""
    spec = [None] * (np.ndim(x) - 1)
    if len(mesh.axis_names) == 1:
        spec.append(mesh.axis_names[0])
    else:
        spec.append(tuple(mesh.axis_names))
    return shard(x, mesh, *spec)


def replicate(x, mesh: Mesh):
    """Fully replicate (≙ ``[*,*]``)."""
    return shard(x, mesh)


def shard_rows_padded(x, mesh: Mesh, pad_value=0.0):
    """``shard_rows`` for arbitrary row counts: zero-pads dim 0 up to a
    multiple of the mesh size.  Returns ``(sharded, n_orig)`` — callers
    whose math tolerates zero rows (least squares residuals, SVD) trim
    row-shaped outputs back to ``n_orig``."""
    n = x.shape[0]
    total = math.prod(mesh.shape.values())
    pad = (-n) % total
    if pad:
        widths = [(0, pad)] + [(0, 0)] * (x.ndim - 1)
        import jax.numpy as jnp

        x = jnp.pad(x, widths, constant_values=pad_value)
    return shard_rows(x, mesh), n


def fully_replicated(x):
    """Reshard ``x`` to fully-replicated if it carries an explicit sharding.

    Trace-time safe: under jit with JAX's explicit-sharding types, ops like
    ``qr``/``svd``/``eigh`` reject sharded non-batch dims; small matrices
    (≙ the reference's rank-replicated ``[*,*]`` factorizations) are
    resharded here.  No-op for unsharded/replicated inputs.
    """
    aval = getattr(x, "aval", x)
    sh = getattr(aval, "sharding", None)
    spec = getattr(sh, "spec", None)
    if spec is None or not any(s is not None for s in spec):
        return x
    return jax.sharding.reshard(
        x, NamedSharding(sh.mesh, P(*([None] * np.ndim(x))))
    )
