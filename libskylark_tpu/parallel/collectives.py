"""Explicit shard_map sketch-apply schedules.

Most of the framework lets GSPMD choose communication (SURVEY §2.7 P4).
This module keeps the two schedules the reference treats as *invariants*
explicit, as `shard_map` programs:

- ``rowwise_sharded``: A sharded over rows (``[VC,*]``), sketch along the
  replicated feature axis — **communication-free** by construction
  (≙ ``doc/sphinx/sketching.rst:104-118``; the sketch operand is realized
  shard-locally from the counter stream, P5, and no collective is ever
  emitted — guaranteed here rather than hoped from the partitioner).
- ``columnwise_sharded``: A sharded over rows, sketched *along* the
  sharded axis: each shard sketches its row block with its own counter
  window of Omega, then one ``psum`` (or ``psum_scatter``) combines —
  the reduce-scatter schedule of
  ``sketch/dense_transform_Elemental_mc_mr.hpp:179,302,599``.

Works for any transform whose apply is local given the right counter
window; dense transforms expose that through ``realize`` (which accepts
traced, shard-dependent offsets), hash transforms through per-coordinate
``buckets``/``values`` slices.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..sketch.base import Dimension
from ..sketch.dense import DenseSketch

__all__ = ["rowwise_sharded", "columnwise_sharded"]


def _coerce_float(A):
    A = jnp.asarray(A)
    if not jnp.issubdtype(A.dtype, jnp.floating):
        A = A.astype(jnp.float32)
    return A


def rowwise_sharded(S, A, mesh: Mesh):
    """A (m, N) sharded on rows → A·Omegaᵀ (m, S) sharded on rows.

    Zero communication: each shard applies the full sketch to its local
    rows (Omega realized in-shard).
    """
    axes = tuple(mesh.axis_names)
    A = _coerce_float(A)

    def local(a):
        return S.apply(a, Dimension.ROWWISE)

    return jax.shard_map(
        local,
        mesh=mesh,
        in_specs=P(axes, None),
        out_specs=P(axes, None),
    )(A)


def columnwise_sharded(S: DenseSketch, A, mesh: Mesh, scatter: bool = False):
    """A (N, m) sharded on rows → S·A (S, m).

    Each shard multiplies its Omega column window (counter-derived, local
    — ``realize`` with a shard-dependent traced offset) with its row
    block, then a ``psum`` sums partial products; with ``scatter=True`` a
    ``psum_scatter`` leaves the output row-sharded (the reference's
    reduce-scatter within grid columns).
    """
    axes = tuple(mesh.axis_names)
    A = _coerce_float(A)
    nshards = 1
    for a in axes:
        nshards *= mesh.shape[a]
    n = A.shape[0]
    if n % nshards:
        raise ValueError(f"rows {n} not divisible by mesh size {nshards}")
    block = n // nshards
    if S.s % nshards and scatter:
        raise ValueError(f"S={S.s} not divisible by mesh size for scatter")

    def local(a):
        idx = jax.lax.axis_index(axes)  # linearized shard index
        omega_blk = S.realize(
            a.dtype, offset=(0, idx * block), shape=(S.s, block)
        )
        partial_out = omega_blk @ a  # (S, m_local) partial product
        if scatter:
            return jax.lax.psum_scatter(
                partial_out, axes, scatter_dimension=0, tiled=True
            )
        return jax.lax.psum(partial_out, axes)

    out_spec = P(axes, None) if scatter else P(None, None)
    return jax.shard_map(
        local, mesh=mesh, in_specs=P(axes, None), out_specs=out_spec
    )(A)