"""Explicit shard_map sketch-apply schedules.

Most of the framework lets GSPMD choose communication (SURVEY §2.7 P4).
This module keeps the two schedules the reference treats as *invariants*
explicit, as `shard_map` programs:

- ``rowwise_sharded``: A sharded over rows (``[VC,*]``), sketch along the
  replicated feature axis — **communication-free** by construction
  (≙ ``doc/sphinx/sketching.rst:104-118``; the sketch operand is realized
  shard-locally from the counter stream, P5, and no collective is ever
  emitted — guaranteed here rather than hoped from the partitioner).
- ``columnwise_sharded``: A sharded over rows, sketched *along* the
  sharded axis: each shard sketches its row block with its own counter
  window of Omega, then one ``psum`` (or ``psum_scatter``) combines —
  the reduce-scatter schedule of
  ``sketch/dense_transform_Elemental_mc_mr.hpp:179,302,599``.

Works for any transform whose apply is local given the right counter
window; dense transforms expose that through ``realize`` (which accepts
traced, shard-dependent offsets), hash transforms through per-coordinate
``buckets``/``values`` slices.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse
from jax.sharding import Mesh, PartitionSpec as P

from ..sketch.base import Dimension
from ..sketch.dense import DenseSketch
from ..sketch.hash import _segment_sum as _hash_segment_sum

__all__ = [
    "cross_host_psum",
    "CollectiveWatchdog",
    "HEARTBEAT_DIR",
    "rowwise_sharded",
    "columnwise_sharded",
    "batch_sharded_program",
    "columnwise_batch_sharded",
    "rowwise_sharded_sparse",
    "columnwise_sharded_sparse",
    "columnwise_sharded_sparse_2d",
    "columnwise_sharded_sparse_out",
    "columnwise_sharded_sparse_out_2d",
    "rowwise_sharded_sparse_out",
    "suggest_sparse_out_capacity",
    "ShardedBCOO",
]


HEARTBEAT_DIR = "heartbeats"


class CollectiveWatchdog:
    """Deadline-bound a blocking collective instead of hanging forever.

    The failure mode PR 6 left open: a peer dies (or wedges) between its
    last fold and the merge, and every survivor blocks inside
    ``process_allgather`` / ``psum`` with no timeout — the MPI-era hang
    the reference accepted.  The watchdog runs the collective on a
    worker thread and polls from the caller's thread:

    - **heartbeats**: before entering a phase, each rank atomically
      writes ``<root>/heartbeats/rank-<r>.json`` (``{rank, epoch, phase,
      ts}``).  On timeout the survivor reads its peers' files and names
      the ranks whose heartbeat never reached the phase — evidence for
      the orchestrator, not just "it hung".
    - **deadline**: past ``deadline_s`` a typed
      :class:`~libskylark_tpu.utils.exceptions.CollectiveTimeoutError`
      (code 110) is raised with the straggler list.
    - **epoch fencing**: a peer heartbeat carrying a HIGHER epoch means
      the world repartitioned without us — raise
      :class:`~libskylark_tpu.utils.exceptions.StaleEpochError` (111)
      immediately rather than waiting out the deadline.

    ``deadline_s=None`` (the default, env-overridable with
    ``SKYLARK_COLLECTIVE_TIMEOUT_S``) disables the worker thread
    entirely: the collective runs inline, bit-for-bit the pre-watchdog
    behavior.  Single-process worlds never build one.
    """

    def __init__(
        self,
        root=None,
        *,
        rank: int = 0,
        world: int = 1,
        epoch: int = 0,
        deadline_s: float | None = None,
        poll_s: float = 0.25,
    ):
        import os

        if deadline_s is None:
            env = os.environ.get("SKYLARK_COLLECTIVE_TIMEOUT_S")
            if env:
                try:
                    deadline_s = float(env)
                except ValueError:
                    deadline_s = None
        self.dir = (
            os.path.join(str(root), HEARTBEAT_DIR) if root else None
        )
        self.rank = int(rank)
        self.world = int(world)
        self.epoch = int(epoch)
        self.deadline_s = deadline_s
        self.poll_s = float(poll_s)

    def _path(self, rank: int) -> str:
        import os

        return os.path.join(self.dir, f"rank-{int(rank):05d}.json")

    def beat(self, phase: str) -> None:
        """Announce arrival at ``phase`` (atomic write, best-effort: a
        full disk must not turn a healthy collective into a failure)."""
        import json
        import os
        import time

        if self.dir is None:
            return
        try:
            os.makedirs(self.dir, exist_ok=True)
            payload = json.dumps(
                {
                    "rank": self.rank,
                    "epoch": self.epoch,
                    "phase": str(phase),
                    "ts": round(time.time(), 6),
                }
            )
            tmp = self._path(self.rank) + f".tmp.{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as fh:
                fh.write(payload)
            os.replace(tmp, self._path(self.rank))
        except OSError:
            pass

    def peers(self) -> dict:
        """``{rank: heartbeat dict}`` for every readable peer file."""
        import json
        import os

        out = {}
        if self.dir is None:
            return out
        try:
            names = os.listdir(self.dir)
        except OSError:
            return out
        for name in sorted(names):
            if not (name.startswith("rank-") and name.endswith(".json")):
                continue
            try:
                with open(
                    os.path.join(self.dir, name), encoding="utf-8"
                ) as fh:
                    rec = json.load(fh)
                out[int(rec["rank"])] = rec
            except (OSError, json.JSONDecodeError, KeyError, ValueError):
                continue
        return out

    def _check_stale(self) -> None:
        from ..utils.exceptions import StaleEpochError

        for rank, rec in self.peers().items():
            if int(rec.get("epoch", 0)) > self.epoch:
                raise StaleEpochError(
                    f"rank {self.rank} runs at elastic epoch "
                    f"{self.epoch} but rank {rank}'s heartbeat announces "
                    f"epoch {rec.get('epoch')}: the world repartitioned "
                    "past this process — its partials are stale",
                    expected=self.epoch,
                    got=int(rec.get("epoch", 0)),
                )

    def stragglers(self, phase: str) -> list:
        """Ranks whose heartbeat never reached ``phase`` (best-effort:
        empty when no heartbeat root is configured)."""
        if self.dir is None:
            return []
        seen = self.peers()
        return [
            r
            for r in range(self.world)
            if r != self.rank
            and (r not in seen or seen[r].get("phase") != str(phase))
        ]

    def guard(self, phase: str, fn):
        """Run ``fn()`` (a blocking collective) bounded by the deadline.

        Inline (no thread, no overhead) when no deadline is configured.
        """
        import threading
        import time

        from .. import telemetry
        from ..utils.exceptions import CollectiveTimeoutError

        self.beat(phase)
        if not self.deadline_s or self.deadline_s <= 0:
            return fn()
        box = {}
        done = threading.Event()

        def _run():
            try:
                box["result"] = fn()
            except BaseException as e:  # noqa: BLE001 — re-raised below
                box["error"] = e
            finally:
                done.set()

        worker = threading.Thread(
            target=_run, name=f"collective-{phase}", daemon=True
        )
        worker.start()
        deadline = time.monotonic() + float(self.deadline_s)
        while not done.wait(timeout=min(self.poll_s, 0.25)):
            self._check_stale()
            if time.monotonic() >= deadline:
                stragglers = self.stragglers(phase)
                if telemetry.enabled():
                    telemetry.inc("collective.timeouts")
                    telemetry.event(
                        "collective", "timeout",
                        {
                            "phase": str(phase),
                            "rank": self.rank,
                            "world": self.world,
                            "epoch": self.epoch,
                            "deadline_s": float(self.deadline_s),
                            "stragglers": stragglers,
                        },
                    )
                who = (
                    str(stragglers)
                    if stragglers
                    else "unknown (no heartbeat root)"
                )
                raise CollectiveTimeoutError(
                    f"collective {phase!r} did not complete within "
                    f"{self.deadline_s}s on rank {self.rank} (world "
                    f"{self.world}, epoch {self.epoch}); ranks that "
                    f"never arrived: {who}",
                    phase=str(phase),
                    deadline_s=float(self.deadline_s),
                    stragglers=stragglers,
                )
        if "error" in box:
            raise box["error"]
        return box.get("result")


def _coerce_float(A):
    A = jnp.asarray(A)
    if not jnp.issubdtype(A.dtype, jnp.floating):
        A = A.astype(jnp.float32)
    return A


def _shard_map_fn():
    # jax < 0.5 keeps shard_map under jax.experimental
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm
    from jax.experimental.shard_map import shard_map

    return shard_map


def cross_host_psum(
    tree,
    mesh: Mesh | None = None,
    *,
    watchdog: CollectiveWatchdog | None = None,
    phase: str = "psum",
):
    """Elementwise sum of a host-local float pytree over every process of
    the ``jax.distributed`` world — the merge schedule of the elastic
    streaming engine (each host folds its own row range into a partial
    ``S·A``; columnwise partials merge by sum, so one psum finishes the
    global sketch).

    Layout: each process contributes its value on its FIRST addressable
    device of ``mesh`` (default: the global 1-D device mesh) and zeros on
    the rest, then one ``shard_map`` ``psum`` over the device axis sums
    exactly one copy per process.  The result comes back as host numpy
    arrays, identical on every process.

    Single-process worlds return ``tree`` unchanged — a bitwise no-op,
    so the non-distributed streaming paths keep their PR-5 bit-identity
    even when routed through this merge.

    ``watchdog`` (a :class:`CollectiveWatchdog`) deadline-bounds the
    merge: a peer that never arrives raises ``CollectiveTimeoutError``
    (code 110) with straggler evidence instead of hanging the world.
    ``None`` (the default) keeps the blocking behavior bit-for-bit.
    """
    import numpy as np

    if jax.process_count() == 1:
        return tree
    if watchdog is not None:
        wd, watchdog = watchdog, None
        return wd.guard(
            phase, lambda: cross_host_psum(tree, mesh, watchdog=None)
        )

    import time as _time

    from .. import telemetry as _telemetry

    # Straggler attribution: each rank times its own merge wall (arrive
    # + wait for peers + sum).  The rank that arrived LAST shows the
    # SHORTEST wait — the fleet's per-rank gauges name it.  Timed on
    # the innermost path only, so a watchdog-guarded call counts once.
    t_wait = _time.monotonic() if _telemetry.enabled() else None

    from jax.sharding import NamedSharding

    if mesh is None:
        mesh = Mesh(np.asarray(jax.devices()), ("hosts",))
    axes = tuple(mesh.axis_names)
    mesh_devs = list(mesh.devices.flat)
    nd = len(mesh_devs)
    me = jax.process_index()
    mine = [i for i, d in enumerate(mesh_devs) if d.process_index == me]
    if not mine:
        raise ValueError(
            "cross_host_psum: mesh has no addressable device for process "
            f"{me}"
        )
    first = mine[0]

    leaves, treedef = jax.tree.flatten(tree)
    out = []
    for leaf in leaves:
        x = np.asarray(leaf)
        if not jnp.issubdtype(x.dtype, jnp.floating):
            raise TypeError(
                "cross_host_psum sums floating-point leaves only (merge "
                f"bookkeeping ints locally), got {x.dtype}"
            )
        zeros = np.zeros_like(x)
        spec = P(axes, *([None] * x.ndim))

        def _cb(idx, x=x, zeros=zeros):
            dev = idx[0].start or 0
            return (x if dev == first else zeros)[None]

        g = jax.make_array_from_callback(
            (nd,) + x.shape, NamedSharding(mesh, spec), _cb
        )
        summed = jax.jit(
            _shard_map_fn()(
                lambda a: jax.lax.psum(a, axes),
                mesh=mesh,
                in_specs=spec,
                out_specs=P(*([None] * (x.ndim + 1))),
            )
        )(g)
        out.append(np.asarray(summed.addressable_data(0))[0])
    result = jax.tree.unflatten(treedef, out)
    if t_wait is not None:
        wait_ms = (_time.monotonic() - t_wait) * 1e3
        _telemetry.observe_phase("collective_wait", wait_ms)
        _telemetry.set_gauge("collective.last_wait_ms", round(wait_ms, 4))
        _telemetry.set_gauge("collective.rank", jax.process_index())
    return result


def rowwise_sharded(S, A, mesh: Mesh):
    """A (m, N) sharded on rows → A·Omegaᵀ (m, S) sharded on rows.

    Zero communication: each shard applies the full sketch to its local
    rows (Omega realized in-shard).
    """
    axes = tuple(mesh.axis_names)
    A = _coerce_float(A)

    def local(a):
        return S.apply(a, Dimension.ROWWISE)

    return _shard_map_fn()(
        local,
        mesh=mesh,
        in_specs=P(axes, None),
        out_specs=P(axes, None),
    )(A)


def batch_sharded_program(local, mesh: Mesh):
    """Shard the BATCH axis: run ``local`` on column blocks of a 2-D
    operand, outputs re-concatenated on columns.  Communication-free by
    construction — the serving layer's device-parallel dispatch schedule,
    where the columns are independent coalesced requests.

    Contrast :func:`columnwise_sharded`, which shards the CONTRACTION
    axis and merges with a ``psum``: the psum reorders the accumulation,
    so its result is only approximately the single-device one.  Here no
    reduction crosses shards, so the result is bitwise-identical to the
    unsharded ``local`` PROVIDED (a) ``local`` is column-pure (each
    output column depends only on its input column — the per-slot purity
    the serve batcher's coalescing contract already pins) and (b) every
    shard's column block keeps the lane-uniform width the XLA gemm
    micro-kernels key on (a multiple of the serve ladder's base rung; a
    remainder-width shard would take a different accumulation
    micro-kernel and break bit-parity).  Callers gate on (b); this
    schedule just runs.
    """
    axes = tuple(mesh.axis_names)
    # check_rep=False: the sketch applies trace counter-stream
    # primitives that carry no replication rule; nothing here relies on
    # replication inference (every spec is explicit).
    return _shard_map_fn()(
        local,
        mesh=mesh,
        in_specs=P(None, axes),
        out_specs=P(None, axes),
        check_rep=False,
    )


def columnwise_batch_sharded(S, B, mesh: Mesh):
    """B (N, k) of k independent RHS columns → S·B (S.s, k), sharded on
    the batch (column) axis: each shard applies the FULL sketch to its
    column block (no counter windowing — every shard sees the whole
    Omega, unlike :func:`columnwise_sharded`'s contraction split).  Zero
    communication, and bitwise-equal to the unsharded columnwise apply
    under :func:`batch_sharded_program`'s lane-uniformity proviso."""
    nshards = mesh.size
    B = _coerce_float(B)
    k = B.shape[1]
    if k % nshards:
        raise ValueError(
            f"batch columns {k} not divisible by mesh size {nshards}"
        )

    def local(b):
        return S.apply(b, Dimension.COLUMNWISE)

    return batch_sharded_program(local, mesh)(B)


def columnwise_sharded(S: DenseSketch, A, mesh: Mesh, scatter: bool = False):
    """A (N, m) sharded on rows → S·A (S, m).

    Each shard multiplies its Omega column window (counter-derived, local
    — ``realize`` with a shard-dependent traced offset) with its row
    block, then a ``psum`` sums partial products; with ``scatter=True`` a
    ``psum_scatter`` leaves the output row-sharded (the reference's
    reduce-scatter within grid columns).
    """
    axes = tuple(mesh.axis_names)
    A = _coerce_float(A)
    nshards = mesh.size
    n = A.shape[0]
    if n % nshards:
        raise ValueError(f"rows {n} not divisible by mesh size {nshards}")
    block = n // nshards
    if S.s % nshards and scatter:
        raise ValueError(f"S={S.s} not divisible by mesh size for scatter")

    def local(a):
        idx = jax.lax.axis_index(axes)  # linearized shard index
        omega_blk = S.realize(
            a.dtype, offset=(0, idx * block), shape=(S.s, block)
        )
        partial_out = omega_blk @ a  # (S, m_local) partial product
        if scatter:
            return jax.lax.psum_scatter(
                partial_out, axes, scatter_dimension=0, tiled=True
            )
        return jax.lax.psum(partial_out, axes)

    out_spec = P(axes, None) if scatter else P(None, None)
    return _shard_map_fn()(
        local, mesh=mesh, in_specs=P(axes, None), out_specs=out_spec
    )(A)


# ---------------------------------------------------------------------------
# P6: explicit sharded SPARSE hash-sketch schedules.
#
# The reference distributes sparse matrices on a CombBLAS √p×√p grid and
# applies hash sketches block-locally, merging with an MPI reduce
# (``sketch/hash_transform_CombBLAS.hpp:136-302``); its own docs call 2-D
# sparse layouts imbalanced for 1-D data (``base/sparse_dist_matrix.hpp:37-41``).
# The TPU re-design shards COO nonzeros by row block (balanced padding),
# computes each shard's bucket/value counter window in-shard (P5: no sketch
# data on the wire), scatter-adds into a dense (S, m) accumulator — sketch
# outputs are short-and-dense by design, the mixed sparse→dense path of
# ``hash_transform_Mixed.hpp`` — and merges with one psum (or psum_scatter,
# the ragged-all-to-all stand-in that keeps the output sharded).


def _shard_coo_rows(A, nshards: int, block: int):
    """Host-side: split BCOO nonzeros into row blocks, padding each block
    to equal nnz with zero-data entries (they scatter 0 — harmless)."""
    import numpy as np

    rows = np.asarray(A.indices[:, 0])
    cols = np.asarray(A.indices[:, 1])
    data = np.asarray(A.data)
    owner = rows // block
    counts = np.bincount(owner, minlength=nshards)
    max_nnz = max(1, int(counts.max()))
    d = np.zeros((nshards, max_nnz), data.dtype)
    lr = np.zeros((nshards, max_nnz), np.int32)
    cc = np.zeros((nshards, max_nnz), np.int32)
    for p in range(nshards):
        sel = owner == p
        k = int(counts[p])
        d[p, :k] = data[sel]
        lr[p, :k] = rows[sel] - p * block
        cc[p, :k] = cols[sel]
    return jnp.asarray(d), jnp.asarray(lr), jnp.asarray(cc)


def _coo_dtype(data):
    return (
        data.dtype
        if jnp.issubdtype(data.dtype, jnp.floating)
        else jnp.float32
    )


def columnwise_sharded_sparse(S, A, mesh: Mesh, scatter: bool = False):
    """BCOO A (N, m), nonzeros owned by row block → dense S·A (S, m).

    Each shard hashes its row block with its own bucket/value counter
    windows (contiguous in the (nnz, N) flat layout, so shard-local) and
    scatter-adds into a local (S, m) accumulator; one ``psum`` merges
    (``psum_scatter`` with ``scatter=True`` leaves rows sharded).
    """
    axes = tuple(mesh.axis_names)
    p = mesh.size
    n, m = A.shape
    if n != S.n:
        raise ValueError(f"columnwise apply needs A with {S.n} rows, got {A.shape}")
    if n % p:
        raise ValueError(f"rows {n} not divisible by mesh size {p}")
    if scatter and S.s % p:
        raise ValueError(f"S={S.s} not divisible by mesh size for scatter")
    block = n // p
    d, lr, cc = _shard_coo_rows(A, p, block)
    if n >= (1 << 32):
        # Traced shard offsets ride raw_bits' uint32 lane; the static
        # h·N part of the window start is folded into the 64-bit counter
        # base below, so only N itself must stay below 2^32.
        raise ValueError(
            f"columnwise_sharded_sparse supports N < 2^32, got N={n}"
        )
    return _columnwise_sparse_program(S, m, block, mesh, scatter)(d, lr, cc)


def _columnwise_sparse_program(S, m: int, block: int, mesh: Mesh,
                               scatter: bool):
    """The jittable device half of :func:`columnwise_sharded_sparse`
    (host-side COO row-block splitting already done).  Factored out so the
    compiled-HLO schedule tests can lower exactly the program that runs."""
    axes = tuple(mesh.axis_names)

    def local(d, lr, cc):
        dtype = _coo_dtype(d)
        d, lr, cc = d[0].astype(dtype), lr[0], cc[0]
        idx = jax.lax.axis_index(axes)
        acc = jnp.zeros((S.s * m,), dtype)
        # uint32 shard offset + static h·N base: exact for any nnz·N
        # (an int32 product here would wrap at 2^31 and silently select
        # wrong counter windows).
        off = jnp.uint32(idx) * jnp.uint32(block)
        for h in range(S.nnz):
            start = (h * S.n, off)
            b = S.buckets(start=start, num=block)  # (block,) in-shard
            v = S.values(dtype, start=start, num=block)
            acc = acc + _hash_segment_sum(
                d * v[lr], b[lr] * m + cc, S.s * m
            ).astype(dtype)
        out = acc.reshape(S.s, m)
        if scatter:
            return jax.lax.psum_scatter(
                out, axes, scatter_dimension=0, tiled=True
            )
        return jax.lax.psum(out, axes)

    out_spec = P(axes, None) if scatter else P(None, None)
    return _shard_map_fn()(
        local,
        mesh=mesh,
        in_specs=(P(axes, None), P(axes, None), P(axes, None)),
        out_specs=out_spec,
    )


def _shard_coo_grid(A, pr: int, pc: int, rblock: int, cblock: int):
    """Host-side: split BCOO nonzeros onto a (pr, pc) grid by
    (row-block, col-block) ownership, padding every cell to equal nnz
    with zero-data entries (they scatter 0 — harmless)."""
    import numpy as np

    rows = np.asarray(A.indices[:, 0])
    cols = np.asarray(A.indices[:, 1])
    data = np.asarray(A.data)
    oi = rows // rblock
    oj = cols // cblock
    owner = oi * pc + oj
    counts = np.bincount(owner, minlength=pr * pc)
    max_nnz = max(1, int(counts.max()))
    d = np.zeros((pr, pc, max_nnz), data.dtype)
    lr = np.zeros((pr, pc, max_nnz), np.int32)
    lc = np.zeros((pr, pc, max_nnz), np.int32)
    for p in range(pr * pc):
        i, j = divmod(p, pc)
        sel = owner == p
        k = int(counts[p])
        d[i, j, :k] = data[sel]
        lr[i, j, :k] = rows[sel] - i * rblock
        lc[i, j, :k] = cols[sel] - j * cblock
    return jnp.asarray(d), jnp.asarray(lr), jnp.asarray(lc)


def _validate_grid_2d(S, A, mesh: Mesh, fn_name: str):
    """Shared preamble of the 2-D grid schedules: axis/shape/2^32
    validation + host-side COO grid split.  Returns
    ``(pr, pc, rblock, cblock, d, lr, lc)``."""
    if len(mesh.axis_names) != 2:
        raise ValueError(
            f"{fn_name} needs a 2-axis mesh, got {mesh.axis_names}"
        )
    ax_r, ax_c = mesh.axis_names
    pr, pc = mesh.shape[ax_r], mesh.shape[ax_c]
    n, m = A.shape
    if n != S.n:
        raise ValueError(f"columnwise apply needs A with {S.n} rows, got {A.shape}")
    if n % pr or m % pc:
        raise ValueError(
            f"shape {A.shape} not divisible by mesh grid ({pr}, {pc})"
        )
    if n >= (1 << 32):
        raise ValueError(f"supports N < 2^32, got N={n}")
    rblock, cblock = n // pr, m // pc
    d, lr, lc = _shard_coo_grid(A, pr, pc, rblock, cblock)
    return pr, pc, rblock, cblock, d, lr, lc


def columnwise_sharded_sparse_2d(S, A, mesh: Mesh):
    """BCOO A (N, m) on a 2-D grid → dense S·A (S, m), column-sharded.

    The 2-D answer to ``sketch/hash_transform_CombBLAS.hpp:136-302``'s
    √p×√p distribution, for matrices long in BOTH dimensions (where the
    1-D row-block schedule's (S, m) accumulator or per-shard column span
    would not fit): nonzeros are owned by (row-block, column-block); each
    shard hashes its row window with in-shard counter windows (P5) and
    scatter-adds a LOCAL (S, m/pc) block; one ``psum`` over the mesh ROW
    axis merges partial products, leaving the output sharded over mesh
    columns — communication ∝ S·m/pc per shard, never the nonzeros.

    Needs a 2-axis mesh (e.g. ``make_mesh((pr, pc))``); N and m must
    divide the respective axis sizes.
    """
    _, _, rblock, cblock, d, lr, lc = _validate_grid_2d(
        S, A, mesh, "columnwise_sharded_sparse_2d"
    )
    return _columnwise_sparse_2d_program(S, rblock, cblock, mesh)(d, lr, lc)


def _columnwise_sparse_2d_program(S, rblock: int, cblock: int, mesh: Mesh):
    """Jittable device half of :func:`columnwise_sharded_sparse_2d`
    (host-side grid split done); factored out for the compiled-HLO
    schedule tests."""
    ax_r, ax_c = mesh.axis_names

    def local(d, lr, lc):
        d, lr, lc = d[0, 0], lr[0, 0], lc[0, 0]
        dtype = _coo_dtype(d)
        d = d.astype(dtype)
        i = jax.lax.axis_index(ax_r)
        acc = jnp.zeros((S.s * cblock,), dtype)
        off = jnp.uint32(i) * jnp.uint32(rblock)
        for h in range(S.nnz):
            start = (h * S.n, off)
            b = S.buckets(start=start, num=rblock)  # in-shard row window
            v = S.values(dtype, start=start, num=rblock)
            acc = acc + _hash_segment_sum(
                d * v[lr], b[lr] * cblock + lc, S.s * cblock
            ).astype(dtype)
        out = acc.reshape(S.s, cblock)
        return jax.lax.psum(out, ax_r)

    return _shard_map_fn()(
        local,
        mesh=mesh,
        in_specs=(
            P(ax_r, ax_c, None),
            P(ax_r, ax_c, None),
            P(ax_r, ax_c, None),
        ),
        out_specs=P(None, ax_c),
    )


def rowwise_sharded_sparse(S, A, mesh: Mesh):
    """BCOO A (m, N), nonzeros owned by row block → dense A·Sᵀ (m, S),
    row-sharded.  Communication-free (≙ the ``[VC,*]`` rowwise invariant,
    P2): the hashed axis is the replicated feature axis, so each shard
    sketches its own rows with the full bucket table computed in-shard.
    """
    axes = tuple(mesh.axis_names)
    p = mesh.size
    m, n = A.shape
    if n != S.n:
        raise ValueError(f"rowwise apply needs A with {S.n} columns, got {A.shape}")
    if m % p:
        raise ValueError(f"rows {m} not divisible by mesh size {p}")
    block = m // p
    d, lr, cc = _shard_coo_rows(A, p, block)
    return _rowwise_sparse_program(S, block, mesh)(d, lr, cc)


def _rowwise_sparse_program(S, block: int, mesh: Mesh):
    """Jittable device half of :func:`rowwise_sharded_sparse` (host-side
    COO splitting done); factored out for the compiled-HLO tests."""
    axes = tuple(mesh.axis_names)

    def local(d, lr, cc):
        dtype = _coo_dtype(d)
        d, lr, cc = d[0].astype(dtype), lr[0], cc[0]
        acc = jnp.zeros((block * S.s,), dtype)
        for h in range(S.nnz):
            start = h * S.n
            b = S.buckets(start=start, num=S.n)
            v = S.values(dtype, start=start, num=S.n)
            acc = acc + _hash_segment_sum(
                d * v[cc], lr * S.s + b[cc], block * S.s
            ).astype(dtype)
        return acc.reshape(block, S.s)

    return _shard_map_fn()(
        local,
        mesh=mesh,
        in_specs=(P(axes, None), P(axes, None), P(axes, None)),
        out_specs=P(axes, None),
    )

# ---------------------------------------------------------------------------
# sparse -> SPARSE sharded output (SURVEY row 65: SpParMat -> SpParMat)
# ---------------------------------------------------------------------------


class ShardedBCOO:
    """Row-block-sharded sparse sketch result with deferred duplicates.

    The TPU re-expression of the reference's distributed-sparse output
    (``sketch/hash_transform_CombBLAS.hpp:136-302``: SpParMat in,
    SpParMat out).  Each mesh shard owns the contiguous row block
    ``[k*row_block, (k+1)*row_block)`` of the logical ``shape`` and
    holds its entries as flat (data, local-row, col) arrays — padding
    entries carry ``data == 0`` at (0, 0), harmless under the
    deferred-duplicate convention (they add zero).  Nothing here is ever
    densified; ``to_bcoo``/``todense`` are explicit host-side exits.
    """

    def __init__(self, data, rows, cols, shape, row_block, mesh,
                 col_block: int | None = None):
        self.data, self.rows, self.cols = data, rows, cols
        self.shape, self.row_block, self.mesh = shape, row_block, mesh
        # 2-D grid results (√p×√p CombBLAS analogue): cols are local to
        # the shard's column block of width col_block; None = global.
        self.col_block = col_block

    @property
    def dtype(self):
        return self.data.dtype

    def to_bcoo(self) -> jsparse.BCOO:
        """Gather to one host BCOO, duplicates summed — the same
        finalize step as the local BCOO apply (``hash.py
        _apply_sparse``), for parity checks and hand-off.  Zero-data
        padding entries (the capacity slack) are dropped host-side, so
        the result's nse is entry-proportional, never buffer-sized."""
        import numpy as np

        d = np.asarray(self.data)
        r = np.asarray(self.rows)
        c = np.asarray(self.cols)
        if d.ndim == 2:  # 1-D row-block layout -> trivial 1-wide grid
            d, r, c = d[:, None], r[:, None], c[:, None]
        pr, pc = d.shape[0], d.shape[1]
        grows = r + np.arange(pr, dtype=r.dtype)[:, None, None] * self.row_block
        gcols = c + (
            np.arange(pc, dtype=c.dtype)[None, :, None] * self.col_block
            if self.col_block is not None
            else 0
        )
        keep = d.ravel() != 0
        if not keep.any():
            return jsparse.BCOO.fromdense(
                jnp.zeros(self.shape, self.data.dtype), nse=1
            )
        dk = d.ravel()[keep]
        rk, ck = grows.ravel()[keep], gcols.ravel()[keep]
        idx = jnp.stack([jnp.asarray(rk), jnp.asarray(ck)], axis=1)
        out = jsparse.BCOO((jnp.asarray(dk), idx), shape=self.shape)
        nse = min(out.nse, self.shape[0] * self.shape[1])
        return out.sum_duplicates(nse=nse)

    def todense(self):
        return self.to_bcoo().todense()

    def sketch_columnwise(self, S2, dense_output: bool = True,
                          scatter: bool = False,
                          capacity: int | None = None):
        """Apply a second sketch to this sharded sparse result WITHOUT
        leaving the device: the per-shard (data, local-row, col) arrays
        are exactly the row-block-split input of the sharded columnwise
        programs, so chaining S2·(S1·A) costs no host exit, no gather,
        and no densification in between (≙ the reference chaining
        sketches on SpParMat, e.g. sketch-and-solve pipelines over
        CombBLAS matrices).  Duplicate entries are fine — hashing is
        linear in entries.

        ``dense_output=True`` runs the dense-merge schedule (one psum;
        ``scatter`` leaves rows sharded); ``False`` runs the sparse-out
        exchange and returns another :class:`ShardedBCOO`."""
        if self.col_block is not None:
            raise ValueError(
                "chaining from a 2-D grid result is not supported — "
                "its column indices are block-local; gather via "
                "to_bcoo() first"
            )
        p = self.mesh.size
        n2, m2 = self.shape
        if S2.n != n2:
            raise ValueError(
                f"columnwise chain needs S2.n == {n2}, got {S2.n}"
            )
        if n2 >= (1 << 32):
            raise ValueError(f"sparse schedules support N < 2^32, got {n2}")
        if (scatter or not dense_output) and S2.s % p:
            # Same precondition the non-chained entry points enforce —
            # without it the failure is an opaque reduce_scatter
            # lowering error instead of this message.
            raise ValueError(
                f"chain needs S={S2.s} divisible by mesh size {p} "
                "(sharded output rows)"
            )
        if dense_output:
            return _columnwise_sparse_program(
                S2, m2, self.row_block, self.mesh, scatter
            )(self.data, self.rows, self.cols)
        cap = (
            S2.nnz * self.data.shape[1] if capacity is None else int(capacity)
        )
        dv, rv, cv = _columnwise_sparse_out_program(
            S2, self.row_block, S2.s // p, cap, self.mesh
        )(self.data, self.rows, self.cols)
        return ShardedBCOO(
            dv, rv, cv, (S2.s, m2), S2.s // p, self.mesh
        )


def columnwise_sharded_sparse_out(S, A, mesh: Mesh, capacity: int | None = None):
    """BCOO A (N, m) -> BCOO S·A (S, m), output ROW-BLOCK-SHARDED and
    never densified (closes SURVEY row 65's partial: the other P6
    schedules merge into a dense (S, m) accumulator, the wrong
    asymptotic when S is large and the output stays sparse).

    Schedule: each shard hashes its row block with shard-local counter
    windows (P5), relabels nonzeros to (bucket, col, v·val) — deferred
    duplicates, exactly the local BCOO apply — then routes every entry
    to the shard that owns its output row block through ONE tiled
    ``all_to_all`` of fixed-capacity per-destination buffers (the TPU
    answer to CombBLAS's SpParMat redistribution; ragged exchanges
    don't exist under XLA's static shapes, so capacity is the padding).

    ``capacity`` is the per-(source, destination) buffer length.  The
    default — every entry of one source landing on one destination —
    can never drop; a tighter value trades memory for silent dropping
    of overflow entries, so only pass one derived from a real count.
    Zero-value padding entries are routed to a sentinel destination and
    never occupy capacity slots, so the relevant count is the max
    per-(source, destination) number of REAL entries.
    """
    axes = tuple(mesh.axis_names)
    p = mesh.size
    n, m = A.shape
    if n != S.n:
        raise ValueError(f"columnwise apply needs A with {S.n} rows, got {A.shape}")
    if n % p:
        raise ValueError(f"rows {n} not divisible by mesh size {p}")
    if S.s % p:
        raise ValueError(
            f"sparse-out needs S={S.s} divisible by mesh size {p} "
            "(output is row-block-sharded)"
        )
    if n >= (1 << 32):
        raise ValueError(f"sparse schedules support N < 2^32, got N={n}")
    block, out_block = n // p, S.s // p
    d, lr, cc = _shard_coo_rows(A, p, block)
    entries = S.nnz * d.shape[1]
    cap = entries if capacity is None else int(capacity)
    dv, rv, cv = _columnwise_sparse_out_program(
        S, block, out_block, cap, mesh
    )(d, lr, cc)
    return ShardedBCOO(dv, rv, cv, (S.s, m), out_block, mesh)


def _exchange_entries(val, row, col, nparts: int, out_block: int, cap: int,
                      axis, my_index):
    """Route (val, row, col) entries to the mesh-axis peer owning row
    block ``row // out_block`` via ONE tiled ``all_to_all`` of
    fixed-capacity per-destination buffers (f32: values ride the packed
    int32 index exchange via bitcast; f64 takes a second exchange).
    Returns (values, LOCAL rows, cols), each (nparts, cap), for the
    receiving shard.  Shared by the 1-D and 2-D sparse-out schedules.

    Zero-value entries (COO block padding — the hash values are nonzero
    a.s., so val == 0 iff the padded data slot was 0) are routed to the
    out-of-range sentinel destination ``nparts``: they never occupy
    capacity slots, so a user capacity derived from REAL
    per-destination counts cannot drop real entries, and the
    out-of-bounds scatter row drops them before the exchange."""
    dtype = val.dtype
    dest = row // jnp.int32(out_block)
    dest = jnp.where(val == 0, jnp.int32(nparts), dest)
    # Sort by destination; position-in-segment via searchsorted.
    order = jnp.argsort(dest)
    sd = dest[order]
    starts = jnp.searchsorted(sd, jnp.arange(nparts, dtype=sd.dtype))
    pos = jnp.arange(sd.shape[0], dtype=jnp.int32) - starts[
        jnp.minimum(sd, nparts - 1)
    ].astype(jnp.int32)
    if dtype == jnp.float32:
        # Values ride the SAME packed int32 exchange (bitcast lane):
        # the buffers are the payload, but launch latency is per-op.
        buf = (
            jnp.zeros((nparts, 3, cap), jnp.int32)
            .at[sd, 0, pos].set(row[order], mode="drop")
            .at[sd, 1, pos].set(col[order], mode="drop")
            .at[sd, 2, pos].set(
                jax.lax.bitcast_convert_type(val[order], jnp.int32),
                mode="drop",
            )
        )
        rbuf = jax.lax.all_to_all(buf, axis, 0, 0, tiled=True)
        rr, rc = rbuf[:, 0], rbuf[:, 1]
        rv = jax.lax.bitcast_convert_type(rbuf[:, 2], jnp.float32)
    else:  # f64 (x64 parity runs): values need their own exchange
        buf_v = jnp.zeros((nparts, cap), dtype).at[sd, pos].set(
            val[order], mode="drop"
        )
        buf_i = (
            jnp.zeros((nparts, 2, cap), jnp.int32)
            .at[sd, 0, pos].set(row[order], mode="drop")
            .at[sd, 1, pos].set(col[order], mode="drop")
        )
        rv = jax.lax.all_to_all(buf_v, axis, 0, 0, tiled=True)
        ri = jax.lax.all_to_all(buf_i, axis, 0, 0, tiled=True)
        rr, rc = ri[:, 0], ri[:, 1]
    # Received rows are global; relabel to this shard's row block.
    # Padding entries (value 0) clip to local row 0 — harmless.
    lrows = jnp.clip(
        rr - jnp.int32(my_index) * jnp.int32(out_block), 0, out_block - 1
    )
    return rv, lrows, rc


def suggest_sparse_out_capacity(S, A, mesh: Mesh) -> int:
    """Exact per-(source, destination) REAL-entry capacity for
    :func:`columnwise_sharded_sparse_out` on this (sketch, matrix, mesh)
    — the tightest value that cannot drop (padding never counts: it
    rides the sentinel destination).  Host-side: hashes the nonzero
    global rows once with the same counter-derived buckets the schedule
    uses.  Worth calling when the default (every entry of one source on
    one destination) over-allocates badly — e.g. near-uniform hashes,
    where the true max is ≈ entries/p + O(√entries).

    1-D meshes only: the row block (n/p) and destination routing here
    assume every device sits on one axis.  On a 2-D grid rows split over
    the ROW axis only (block n/pr, exchange over pr peers), so this
    count would be wrong for :func:`columnwise_sharded_sparse_out_2d` —
    rejected rather than silently under/over-sized."""
    import numpy as np

    if len(mesh.axis_names) > 1:
        raise ValueError(
            "suggest_sparse_out_capacity is 1-D only: mesh has axes "
            f"{tuple(mesh.axis_names)}; its n/p row blocks and p-way "
            "destination counts do not match the 2-D grid's row-axis "
            "exchange (see columnwise_sharded_sparse_out_2d)"
        )
    p = mesh.size
    n = A.shape[0]
    block, out_block = n // p, S.s // p
    rows = np.asarray(A.indices[:, 0])
    data = np.asarray(A.data)
    buckets = np.asarray(S.buckets())  # (nnz*N,) flat layout
    need = 1
    for src in range(p):
        sel = (rows // block == src) & (data != 0)
        gl = rows[sel]
        if not gl.size:
            continue
        # All hash functions of one source share the destination buffer.
        dests = np.concatenate(
            [buckets[h * S.n + gl] // out_block for h in range(S.nnz)]
        )
        need = max(need, int(np.bincount(dests, minlength=p).max()))
    return need


def _columnwise_sparse_out_program(S, block: int, out_block: int, cap: int,
                                   mesh: Mesh):
    """Jittable device half of :func:`columnwise_sharded_sparse_out`;
    factored out for the compiled-HLO schedule tests (the lock: one
    all-to-all, NO psum, NO (S, m) dense accumulator)."""
    axes = tuple(mesh.axis_names)
    p = mesh.size

    def local(d, lr, cc):
        dtype = _coo_dtype(d)
        d, lr, cc = d[0].astype(dtype), lr[0], cc[0]
        idx = jax.lax.axis_index(axes)
        off = jnp.uint32(idx) * jnp.uint32(block)
        vals, rows = [], []
        for h in range(S.nnz):
            start = (h * S.n, off)
            b = S.buckets(start=start, num=block)
            v = S.values(dtype, start=start, num=block)
            vals.append(d * v[lr])
            rows.append(b[lr])
        val = jnp.concatenate(vals)              # (E,)
        row = jnp.concatenate(rows)              # global out rows [0, S)
        col = jnp.tile(cc, S.nnz)
        rv, lrows, rc = _exchange_entries(
            val, row, col, p, out_block, cap, axes, idx
        )
        flat = (1, p * cap)
        return (
            rv.reshape(flat),
            lrows.reshape(flat),
            rc.reshape(flat),
        )

    return _shard_map_fn()(
        local,
        mesh=mesh,
        in_specs=(P(axes, None), P(axes, None), P(axes, None)),
        out_specs=(P(axes, None), P(axes, None), P(axes, None)),
    )


def columnwise_sharded_sparse_out_2d(S, A, mesh: Mesh,
                                     capacity: int | None = None):
    """BCOO A (N, m) on a 2-D grid -> BCOO S·A (S, m) on the SAME grid,
    never densified — the full SpParMat→SpParMat analogue
    (``sketch/hash_transform_CombBLAS.hpp:136-302``: the reference's
    CombBLAS matrices are natively √p×√p-distributed, and its sketch
    keeps the output on the grid).

    Nonzeros are owned by (row-block, column-block).  An entry's output
    column block is its INPUT column block (columnwise sketching leaves
    columns alone), so routing is column-local: each shard relabels its
    entries to (bucket, local col, v·val) with in-shard counter windows
    (P5) and exchanges them with its mesh-COLUMN peers through one
    tiled ``all_to_all`` over the mesh ROW axis.  Output: shard (i, j)
    owns rows [i·S/pr, (i+1)·S/pr) × cols [j·m/pc, (j+1)·m/pc).
    Communication ∝ entries, rides one mesh axis; memory is
    entry-proportional — never an (S, m/pc) dense block (contrast
    :func:`columnwise_sharded_sparse_2d`, the dense-output variant).

    ``capacity`` as in :func:`columnwise_sharded_sparse_out`: per-
    (source, destination) REAL-entry buffer length; the default cannot
    drop.  NOTE: :func:`suggest_sparse_out_capacity` is the 1-D helper
    and refuses 2-D meshes — here entries route over the ROW axis only
    (pr peers, row block n/pr), so a tight 2-D capacity must count
    per-(row-block, destination) maxima on that axis instead.
    """
    pr, pc, rblock, cblock, d, lr, lc = _validate_grid_2d(
        S, A, mesh, "columnwise_sharded_sparse_out_2d"
    )
    if S.s % pr:
        raise ValueError(
            f"sparse-out needs S={S.s} divisible by mesh rows {pr} "
            "(output rows are block-sharded over the row axis)"
        )
    out_rblock = S.s // pr
    entries = S.nnz * d.shape[2]
    cap = entries if capacity is None else int(capacity)
    dv, rv, cv = _columnwise_sparse_out_2d_program(
        S, rblock, out_rblock, cap, mesh
    )(d, lr, lc)
    return ShardedBCOO(
        dv, rv, cv, (S.s, A.shape[1]), out_rblock, mesh, col_block=cblock
    )


def _columnwise_sparse_out_2d_program(S, rblock: int, out_rblock: int,
                                      cap: int, mesh: Mesh):
    """Jittable device half of :func:`columnwise_sharded_sparse_out_2d`;
    factored out for the compiled-HLO locks (one all-to-all over the
    row axis only, NO psum, NO dense accumulator)."""
    ax_r, ax_c = mesh.axis_names
    pr = mesh.shape[ax_r]

    def local(d, lr, lc):
        dtype = _coo_dtype(d)
        d, lr, lc = d[0, 0].astype(dtype), lr[0, 0], lc[0, 0]
        i = jax.lax.axis_index(ax_r)
        off = jnp.uint32(i) * jnp.uint32(rblock)
        vals, rows = [], []
        for h in range(S.nnz):
            start = (h * S.n, off)
            b = S.buckets(start=start, num=rblock)
            v = S.values(dtype, start=start, num=rblock)
            vals.append(d * v[lr])
            rows.append(b[lr])
        val = jnp.concatenate(vals)
        row = jnp.concatenate(rows)              # global out rows [0, S)
        col = jnp.tile(lc, S.nnz)                # LOCAL cols: stay put
        rv, lrows, rc = _exchange_entries(
            val, row, col, pr, out_rblock, cap, ax_r, i
        )
        flat = (1, 1, pr * cap)
        return (
            rv.reshape(flat),
            lrows.reshape(flat),
            rc.reshape(flat),
        )

    return _shard_map_fn()(
        local,
        mesh=mesh,
        in_specs=(
            P(ax_r, ax_c, None),
            P(ax_r, ax_c, None),
            P(ax_r, ax_c, None),
        ),
        out_specs=(
            P(ax_r, ax_c, None),
            P(ax_r, ax_c, None),
            P(ax_r, ax_c, None),
        ),
    )


def rowwise_sharded_sparse_out(S, A, mesh: Mesh):
    """BCOO A (m, N), row-sharded -> BCOO A·Sᵀ (m, S), row-sharded,
    never densified.  Communication-FREE (P2: the hashed axis is the
    replicated feature axis): each shard relabels its own rows' column
    indices with the full in-shard bucket table and keeps its entries
    local — the output row owner is the input row owner."""
    axes = tuple(mesh.axis_names)
    p = mesh.size
    m, n = A.shape
    if n != S.n:
        raise ValueError(f"rowwise apply needs A with {S.n} columns, got {A.shape}")
    if m % p:
        raise ValueError(f"rows {m} not divisible by mesh size {p}")
    block = m // p
    d, lr, cc = _shard_coo_rows(A, p, block)
    dv, rv, cv = _rowwise_sparse_out_program(S, mesh)(d, lr, cc)
    return ShardedBCOO(dv, rv, cv, (m, S.s), block, mesh)


def _rowwise_sparse_out_program(S, mesh: Mesh):
    """Jittable device half of :func:`rowwise_sharded_sparse_out`;
    factored out for the compiled-HLO tests (the lock: ZERO collectives)."""
    axes = tuple(mesh.axis_names)

    def local(d, lr, cc):
        dtype = _coo_dtype(d)
        d, lr, cc = d[0].astype(dtype), lr[0], cc[0]
        vals, cols = [], []
        for h in range(S.nnz):
            start = h * S.n
            b = S.buckets(start=start, num=S.n)
            v = S.values(dtype, start=start, num=S.n)
            vals.append(d * v[cc])
            cols.append(b[cc])
        flat = (1, S.nnz * d.shape[0])
        return (
            jnp.concatenate(vals).reshape(flat),
            jnp.tile(lr, S.nnz).reshape(flat),
            jnp.concatenate(cols).reshape(flat),
        )

    return _shard_map_fn()(
        local,
        mesh=mesh,
        in_specs=(P(axes, None), P(axes, None), P(axes, None)),
        out_specs=(P(axes, None), P(axes, None), P(axes, None)),
    )
