"""Bounded admission queue: depth-capped, tenant-laned, key-aware take.

Admission control happens at the door (``offer``): a full queue rejects
with :class:`~libskylark_tpu.utils.exceptions.AdmissionError` (code 112)
instead of queueing unboundedly — under overload the tail latency of
everything already admitted stays bounded, and shed requests carry a
structured error their caller can back off on.  The depth cap is GLOBAL
across lanes: per-tenant *rate* protection is the token-bucket quota
layer's job (code 117, enforced in the server before ``offer``).

Deadline shedding happens at *dispatch* (the server checks each taken
entry's absolute deadline before executing): an expired request never
burns device work, and its :class:`DeadlineExceededError` (code 113)
carries how long it actually waited.

``take_batch`` is the coalescing half, now scheduled as **deficit-
weighted round-robin over per-tenant lanes**: each tenant owns a FIFO
sub-queue; a lane earns ``quantum * weight`` credits when the scheduler
visits it at the head of the rotation, pays 1 credit per BATCH taken
(coalescing is deliberately unpunished — a fused batch is the cheap
outcome we want), and rotates to the tail when its deficit runs dry.
Within a tenant pick the coalescing identity is unchanged from the
legacy FIFO: the lane's head entry plus every same-key entry in that
lane, FIFO order preserved, up to ``max_coalesce``.  Cross-tenant
entries never coalesce into one batch — a batch is one tenant's work,
which is what makes per-tenant latency accounting honest.

When only ONE lane exists (every request on the default tenant — the
entire pre-QoS world) the scheduler short-circuits to that lane
directly, so single-tenant behaviour is the exact legacy head-of-line
FIFO: same order, same batches, same bits.

Counter reservations for fresh-sketch requests run inside ``offer``'s
lock (the ``on_admit`` callback), so the reservation order IS the
admission order — deterministic and replayable regardless of how
batches later form.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from ..utils.exceptions import AdmissionError
from .qos import DEFAULT_TENANT, LaneConfig

__all__ = ["Entry", "AdmissionQueue"]


class Entry:
    """One admitted request riding the queue."""

    __slots__ = (
        "request", "future", "key", "op", "payload", "squeeze",
        "t_admit", "t_pop", "phases", "deadline", "sketch",
        "counter_base", "entity", "trace", "tctx", "tenant",
        "tenant_label", "cache_key", "cache_entity", "idem_key",
    )

    def __init__(self, request, future, key, op, payload=None):
        self.request = request
        self.future = future
        self.key = key
        self.op = op
        self.payload = payload
        self.squeeze = False
        self.t_admit = None
        # Phase-clock stamps: monotonic pop time (take_batch, telemetry
        # on only) and the phases dict the batcher assembles for traced
        # requests; both stay None on a disabled run.
        self.t_pop = None
        self.phases = None
        self.deadline = None
        self.sketch = None
        self.counter_base = None
        # The registry version object PINNED at validation: live-registry
        # updates publish NEW version objects, so an in-flight coalesced
        # batch executes against exactly the epoch it admitted under —
        # bitwise, regardless of folds landing while it queued.
        self.entity = None
        self.trace = {"events": []}
        # TraceContext minted at admission when telemetry is on; its
        # event list ALIASES trace["events"] so everything attached
        # mid-flight lands in the response envelope too.
        self.tctx = None
        # QoS lane key (qos.tenant_of at validation), and the BOUNDED
        # telemetry label for it (the server folds tenants beyond its
        # metric cap into "other" so an untrusted client cannot mint
        # unbounded counter names; lanes/quotas always use the raw key).
        self.tenant = DEFAULT_TENANT
        self.tenant_label = DEFAULT_TENANT
        # ResultCache key (placement_key, payload crc, pinned epoch) and
        # the entity name it invalidates under — None means uncacheable.
        self.cache_key = None
        self.cache_entity = None
        # Idempotency key for op:"update" requests — the dedup window
        # identity is (tenant, idem_key); None for every other op.
        self.idem_key = None


class AdmissionQueue:
    def __init__(self, max_depth: int, lanes: LaneConfig | None = None):
        self.max_depth = int(max_depth)
        self.lanes = lanes or LaneConfig()
        self._lanes: dict[str, deque[Entry]] = {}
        self._active: deque[str] = deque()  # DRR rotation order
        self._deficit: dict[str, float] = {}
        self._charged: set[str] = set()  # credited this head-visit
        self._depth = 0
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._closed = False

    def __len__(self) -> int:
        with self._lock:
            return self._depth

    def depth_by_tenant(self) -> dict:
        with self._lock:
            return {t: len(q) for t, q in self._lanes.items() if q}

    def offer(self, entry: Entry, on_admit=None) -> None:
        """Admit or shed.  ``on_admit(entry)`` runs under the queue lock
        after the depth check passes — the admission-ordered side effect
        slot (fresh-sketch counter reservation)."""
        with self._cond:
            if self._closed:
                raise AdmissionError("serve queue is shut down")
            if self._depth >= self.max_depth:
                raise AdmissionError(
                    f"serve queue full ({self._depth}/{self.max_depth})",
                    queue_depth=self._depth,
                    max_depth=self.max_depth,
                )
            entry.t_admit = time.monotonic()
            if on_admit is not None:
                on_admit(entry)
            tenant = entry.tenant or DEFAULT_TENANT
            lane = self._lanes.get(tenant)
            if lane is None:
                lane = deque()
                self._lanes[tenant] = lane
                self._active.append(tenant)
                self._deficit[tenant] = 0.0
            lane.append(entry)
            self._depth += 1
            self._cond.notify()

    # -- DRR scheduling -----------------------------------------------------

    def _drop_lane_locked(self, tenant):
        self._lanes.pop(tenant, None)
        self._deficit.pop(tenant, None)
        self._charged.discard(tenant)
        try:
            self._active.remove(tenant)
        except ValueError:
            pass

    def _pick_lane_locked(self):
        """Return the tenant whose lane serves the next batch, or None.

        Lone-lane short circuit: with a single active lane DRR reduces
        to FIFO, so skip the credit bookkeeping entirely — the default-
        tenant world stays structurally identical to the legacy queue.
        """
        while self._active and not self._lanes.get(self._active[0]):
            self._drop_lane_locked(self._active[0])
        if not self._active:
            return None
        if len(self._active) == 1:
            return self._active[0]
        for _ in range(2 * len(self._active)):
            tenant = self._active[0]
            lane = self._lanes.get(tenant)
            if not lane:
                self._drop_lane_locked(tenant)
                continue
            if tenant not in self._charged:
                # Credit once per head-visit; cap so an idle-then-bursty
                # lane cannot bank unbounded credit.
                w = self.lanes.weight(tenant)
                quantum = self.lanes.quantum * w
                cap = max(2.0, 2.0 * quantum)
                self._deficit[tenant] = min(
                    cap, self._deficit.get(tenant, 0.0) + quantum)
                self._charged.add(tenant)
            if self._deficit[tenant] >= 1.0:
                return tenant
            # Out of credit: rotate to the tail, next lane gets credit.
            self._charged.discard(tenant)
            self._active.rotate(-1)
        return self._active[0]  # all lanes broke; serve head anyway

    def _settle_lane_locked(self, tenant):
        """Charge one batch to ``tenant`` and rotate if its credit ran
        dry (or its lane emptied)."""
        if len(self._active) <= 1:
            if not self._lanes.get(tenant):
                self._drop_lane_locked(tenant)
            return
        self._deficit[tenant] = self._deficit.get(tenant, 0.0) - 1.0
        if not self._lanes.get(tenant):
            self._drop_lane_locked(tenant)
        elif self._deficit[tenant] < 1.0:
            self._charged.discard(tenant)
            if self._active and self._active[0] == tenant:
                self._active.rotate(-1)

    def _take_same_key_locked(self, lane, batch, max_coalesce,
                              stamp: bool = False):
        key = batch[0].key
        keep = deque()
        while lane and len(batch) < max_coalesce:
            e = lane.popleft()
            if e.key == key:
                if stamp:
                    e.t_pop = time.monotonic()
                batch.append(e)
                # Freed at pop, not at take_batch return: entries in the
                # in-flight batch no longer hold queue depth, so a
                # coalesce-window linger near capacity cannot shed 112
                # for requests the drained queue has room for.
                self._depth -= 1
            else:
                keep.append(e)
        keep.extend(lane)
        lane.clear()
        lane.extend(keep)

    def take_batch(self, max_coalesce: int, window_s: float = 0.0):
        """Block for work; return one tenant's head entry + all same-key
        entries from that tenant's lane (up to ``max_coalesce``), or
        ``None`` once closed and drained.  ``window_s`` > 0 lingers
        briefly for same-key same-tenant arrivals when the batch is not
        yet full — latency traded for fuller batches.  Depth is released
        entry-by-entry as the batch forms, so lingering never holds
        admission capacity against ``offer``."""
        from .. import telemetry

        with self._cond:
            while True:
                tenant = self._pick_lane_locked()
                if tenant is not None:
                    break
                if self._closed:
                    return None
                self._cond.wait(timeout=0.1)
            # Phase-clock pop stamps (admit_wait ends / coalesce_linger
            # starts here) — gated so a disabled run allocates nothing.
            stamp = telemetry.enabled()
            lane = self._lanes[tenant]
            head = lane.popleft()
            if stamp:
                head.t_pop = time.monotonic()
            batch = [head]
            self._depth -= 1
            self._take_same_key_locked(lane, batch, max_coalesce, stamp)
            if window_s > 0:
                end = time.monotonic() + window_s
                while len(batch) < max_coalesce and not self._closed:
                    left = end - time.monotonic()
                    if left <= 0:
                        break
                    self._cond.wait(timeout=left)
                    lane = self._lanes.get(tenant)
                    if lane is None:
                        break
                    self._take_same_key_locked(lane, batch, max_coalesce,
                                               stamp)
            self._settle_lane_locked(tenant)
            return batch

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def drain(self):
        """Remove and return every queued entry (shutdown path),
        admission-ordered across lanes."""
        with self._cond:
            out = []
            for tenant in list(self._active):
                out.extend(self._lanes.get(tenant, ()))
            out.sort(key=lambda e: e.t_admit or 0.0)
            self._lanes.clear()
            self._active.clear()
            self._deficit.clear()
            self._charged.clear()
            self._depth = 0
            return out
