"""Bounded admission queue: depth-capped FIFO with key-aware batch take.

Admission control happens at the door (``offer``): a full queue rejects
with :class:`~libskylark_tpu.utils.exceptions.AdmissionError` (code 112)
instead of queueing unboundedly — under overload the tail latency of
everything already admitted stays bounded, and shed requests carry a
structured error their caller can back off on.

Deadline shedding happens at *dispatch* (the server checks each taken
entry's absolute deadline before executing): an expired request never
burns device work, and its :class:`DeadlineExceededError` (code 113)
carries how long it actually waited.

``take_batch`` is the coalescing half: it removes the head-of-line entry
plus every queued entry with the SAME coalesce key (FIFO order
preserved) up to ``max_coalesce`` — requests for different plans never
block each other's batch, and one hot key cannot starve others beyond
its single batch per take.  Counter reservations for fresh-sketch
requests run inside ``offer``'s lock (the ``on_admit`` callback), so the
reservation order IS the admission order — deterministic and
replayable regardless of how batches later form.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from ..utils.exceptions import AdmissionError

__all__ = ["Entry", "AdmissionQueue"]


class Entry:
    """One admitted request riding the queue."""

    __slots__ = (
        "request", "future", "key", "op", "payload", "squeeze",
        "t_admit", "deadline", "sketch", "counter_base", "entity",
        "trace", "tctx",
    )

    def __init__(self, request, future, key, op, payload=None):
        self.request = request
        self.future = future
        self.key = key
        self.op = op
        self.payload = payload
        self.squeeze = False
        self.t_admit = None
        self.deadline = None
        self.sketch = None
        self.counter_base = None
        # The registry version object PINNED at validation: live-registry
        # updates publish NEW version objects, so an in-flight coalesced
        # batch executes against exactly the epoch it admitted under —
        # bitwise, regardless of folds landing while it queued.
        self.entity = None
        self.trace = {"events": []}
        # TraceContext minted at admission when telemetry is on; its
        # event list ALIASES trace["events"] so everything attached
        # mid-flight lands in the response envelope too.
        self.tctx = None


class AdmissionQueue:
    def __init__(self, max_depth: int):
        self.max_depth = int(max_depth)
        self._q: deque[Entry] = deque()
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._closed = False

    def __len__(self) -> int:
        with self._lock:
            return len(self._q)

    def offer(self, entry: Entry, on_admit=None) -> None:
        """Admit or shed.  ``on_admit(entry)`` runs under the queue lock
        after the depth check passes — the admission-ordered side effect
        slot (fresh-sketch counter reservation)."""
        with self._cond:
            if self._closed:
                raise AdmissionError("serve queue is shut down")
            depth = len(self._q)
            if depth >= self.max_depth:
                raise AdmissionError(
                    f"serve queue full ({depth}/{self.max_depth})",
                    queue_depth=depth,
                    max_depth=self.max_depth,
                )
            entry.t_admit = time.monotonic()
            if on_admit is not None:
                on_admit(entry)
            self._q.append(entry)
            self._cond.notify()

    def _take_same_key(self, batch, max_coalesce):
        key = batch[0].key
        keep = deque()
        while self._q and len(batch) < max_coalesce:
            e = self._q.popleft()
            if e.key == key:
                batch.append(e)
            else:
                keep.append(e)
        keep.extend(self._q)
        self._q = keep

    def take_batch(self, max_coalesce: int, window_s: float = 0.0):
        """Block for work; return the head entry + all same-key entries
        (up to ``max_coalesce``), or ``None`` once closed and drained.
        ``window_s`` > 0 lingers briefly for same-key arrivals when the
        batch is not yet full — latency traded for fuller batches."""
        with self._cond:
            while not self._q:
                if self._closed:
                    return None
                self._cond.wait(timeout=0.1)
            batch = [self._q.popleft()]
            self._take_same_key(batch, max_coalesce)
            if window_s > 0:
                end = time.monotonic() + window_s
                while len(batch) < max_coalesce and not self._closed:
                    left = end - time.monotonic()
                    if left <= 0:
                        break
                    self._cond.wait(timeout=left)
                    self._take_same_key(batch, max_coalesce)
            return batch

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def drain(self):
        """Remove and return every queued entry (shutdown path)."""
        with self._cond:
            out = list(self._q)
            self._q.clear()
            return out
