"""Multi-tenant QoS primitives for the serve front door.

Two mechanisms, both tenant-keyed:

* **Weighted-fair lanes** (:class:`LaneConfig`) — the admission queue
  becomes deficit-weighted round-robin over per-tenant sub-queues, so a
  noisy tenant flooding the door gets *its own lane* drained at its
  weight's share instead of starving the global FIFO.  The scheduling
  itself lives in :class:`~libskylark_tpu.serve.admission.AdmissionQueue`;
  this module only parses the weights.

* **Token-bucket quotas** (:class:`TenantQuotas`) — per-tenant admission
  rate limits shedding a structured code-117
  :class:`~libskylark_tpu.utils.exceptions.QuotaExceededError` at the
  door, with a ``retry_after_ms`` backoff hint.  Global depth/deadline
  sheds keep codes 112/113; 117 is the *per-tenant* refusal.

Requests name their tenant via a ``tenant`` payload field (the HTTP
transport also maps an ``X-Skylark-Tenant`` header onto it).  Requests
that carry none ride the default lane — and when only the default lane
exists the queue short-circuits to the exact legacy FIFO, so
single-tenant deployments are preserved bitwise.

Knobs: ``SKYLARK_QOS_QUANTUM`` (batches of credit per round, default 1),
``SKYLARK_QOS_WEIGHTS`` (``"tenantA:4,tenantB:1"``),
``SKYLARK_QOS_QUOTA_RPS`` (default 0 = unlimited),
``SKYLARK_QOS_QUOTA_BURST`` (bucket capacity, default 2x rate),
``SKYLARK_QOS_QUOTAS`` (per-tenant ``"tenantA:100:200,tenantB:5"``
rate[:burst] overrides).
"""

from __future__ import annotations

import os
import threading
import time

from ..utils.exceptions import QuotaExceededError

__all__ = [
    "DEFAULT_TENANT",
    "tenant_of",
    "LaneConfig",
    "TokenBucket",
    "TenantQuotas",
]

DEFAULT_TENANT = "default"


def tenant_of(request):
    """Extract the tenant key from a request payload (dict or None)."""
    if isinstance(request, dict):
        t = request.get("tenant")
        if t is not None:
            return str(t)
    return DEFAULT_TENANT


def _parse_weights(spec):
    weights = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        name, _, w = part.partition(":")
        try:
            weights[name.strip()] = max(1e-6, float(w))
        except ValueError:
            continue
    return weights


class LaneConfig:
    """Deficit-round-robin parameters for the per-tenant lanes."""

    def __init__(self, quantum=None, weights=None):
        if quantum is None:
            quantum = float(os.environ.get("SKYLARK_QOS_QUANTUM", "1"))
        if weights is None:
            weights = _parse_weights(os.environ.get("SKYLARK_QOS_WEIGHTS"))
        elif isinstance(weights, str):
            weights = _parse_weights(weights)
        self.quantum = max(1e-6, float(quantum))
        self.weights = dict(weights or {})

    def weight(self, tenant):
        return float(self.weights.get(tenant, 1.0))


class TokenBucket:
    """Classic token bucket: ``rate`` tokens/s refill, ``burst`` cap.

    ``clock`` is injectable so quota tests are deterministic without
    sleeping.  Not thread-safe on its own — :class:`TenantQuotas` holds
    the lock.
    """

    def __init__(self, rate, burst, clock=time.monotonic):
        self.rate = float(rate)
        self.burst = max(1.0, float(burst))
        self.clock = clock
        self.tokens = self.burst
        self._t_last = clock()

    def _refill(self):
        now = self.clock()
        dt = max(0.0, now - self._t_last)
        self._t_last = now
        self.tokens = min(self.burst, self.tokens + dt * self.rate)

    def take(self):
        """Consume one token; return None on success or the ms until a
        token accrues on refusal."""
        self._refill()
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return None
        if self.rate <= 0:
            return None  # rate 0 with a take() call means unlimited lane
        deficit = 1.0 - self.tokens
        return int(deficit / self.rate * 1000.0) + 1


class TenantQuotas:
    """Per-tenant token-bucket admission quotas.

    ``default_rps`` of 0 (the knob default) means tenants without an
    explicit quota are unlimited — quotas are opt-in, so deployments
    that never configure them see zero behaviour change.
    """

    def __init__(self, default_rps=None, default_burst=None, quotas=None,
                 clock=time.monotonic):
        if default_rps is None:
            default_rps = float(os.environ.get("SKYLARK_QOS_QUOTA_RPS", "0"))
        if default_burst is None:
            burst_env = os.environ.get("SKYLARK_QOS_QUOTA_BURST")
            default_burst = float(burst_env) if burst_env else None
        if quotas is None:
            quotas = self._parse_quotas(
                os.environ.get("SKYLARK_QOS_QUOTAS"))
        elif isinstance(quotas, str):
            quotas = self._parse_quotas(quotas)
        self.default_rps = float(default_rps)
        self.default_burst = default_burst
        self.quotas = dict(quotas or {})  # tenant -> (rate, burst|None)
        self.clock = clock
        self._buckets = {}
        self._lock = threading.Lock()

    @staticmethod
    def _parse_quotas(spec):
        quotas = {}
        for part in (spec or "").split(","):
            part = part.strip()
            if not part:
                continue
            bits = part.split(":")
            if len(bits) < 2:
                continue
            try:
                rate = float(bits[1])
                burst = float(bits[2]) if len(bits) > 2 else None
            except ValueError:
                continue
            quotas[bits[0].strip()] = (rate, burst)
        return quotas

    def _limits_for(self, tenant):
        if tenant in self.quotas:
            rate, burst = self.quotas[tenant]
        else:
            rate, burst = self.default_rps, self.default_burst
        if rate <= 0:
            return None
        if burst is None:
            burst = max(1.0, 2.0 * rate)
        return rate, burst

    def admit(self, tenant):
        """Charge one request to ``tenant``'s bucket; raise
        :class:`QuotaExceededError` (code 117) when exhausted."""
        limits = self._limits_for(tenant)
        if limits is None:
            return
        rate, burst = limits
        with self._lock:
            bucket = self._buckets.get(tenant)
            if bucket is None or bucket.rate != rate or bucket.burst != burst:
                bucket = TokenBucket(rate, burst, clock=self.clock)
                self._buckets[tenant] = bucket
            retry_ms = bucket.take()
        if retry_ms is not None:
            raise QuotaExceededError(
                "tenant %r quota exceeded (%.3g req/s, burst %.3g)"
                % (tenant, rate, burst),
                tenant=tenant, rate=rate, burst=burst,
                retry_after_ms=retry_ms)

    def stats(self):
        with self._lock:
            return {
                t: {"tokens": b.tokens, "rate": b.rate, "burst": b.burst}
                for t, b in self._buckets.items()
            }
