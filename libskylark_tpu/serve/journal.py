"""Write-ahead journal for the serve registry — durable serve state.

The live registry (PR 16) made the serve plane mutable; this module makes
those mutations survive the process.  Every :meth:`Registry._mint` kind
(``register``, ``graph_fold``, ``row_append``, ``row_downdate``,
``model_update``) appends one CRC-framed, epoch-stamped JSONL record to
``<dir>/registry-journal.jsonl`` *before* the mutation publishes, riding
the same fsync-file-then-directory discipline ``utils/checkpoint.py``
uses for solver state.  A record's payload is the canonical update delta
(ndarrays inline via the dtype-faithful ``model.save`` encoding: dtype
name + shape + raw bytes), so replaying the journal re-executes the
exact deterministic code paths the live registry ran — the recovered
registry is **bitwise identical** to the never-crashed one: same entity
bits, same epoch counter, same ``epoch_log``.

Crash model and the two failure classes it separates:

- a **torn final line** is what a SIGKILL mid-append legitimately
  leaves.  Recovery truncates it, counts it (``journal.torn_tail``),
  and continues — exactly the tolerance ``read_progress`` extends to a
  torn elastic ledger.
- **mid-file damage** — a CRC-bad record with valid records after it,
  or an epoch gap between consecutive records — cannot be produced by
  the crash model and means the journal is not trustworthy: code-118
  :class:`~..utils.exceptions.JournalError`, never a silent partial
  replay.

Periodic **compaction** folds the journal into a
:class:`~..utils.checkpoint.CheckpointStore` snapshot slot
(``registry-snap-<epoch>.npz``) holding every entity's exact bits
(including the factorizations, so restore is a field copy — no re-QR,
no re-sketch) plus the epoch counter, ``epoch_log``, and the
idempotency-receipt window; the journal then truncates, so recovery
cost is one snapshot load plus the tail since the last compaction.

The **idempotency window** rides the journal: update records may carry
an ``idem`` pair ``(tenant, key)``; the registry records the minted
epoch receipt under it, and both snapshot and replay restore the
window — a failover-replayed update after a crash still returns the
original receipt instead of double-applying.
"""

from __future__ import annotations

import base64
import json
import os
import zlib

import jax.numpy as jnp
import numpy as np

from .. import telemetry
from ..ml.model import _dtype_from_name, _json_info
from ..sketch import base as sketch_base
from ..utils.checkpoint import CheckpointStore, _fsync_dir
from ..utils.exceptions import JournalError

__all__ = [
    "Journal",
    "JournalError",
    "RECORD_KINDS",
    "REPLAY_HANDLERS",
    "read_journal",
    "scan_journal",
]

JOURNAL_NAME = "registry-journal.jsonl"
SNAP_PREFIX = "registry-snap"


# -- framing ----------------------------------------------------------------


def _canon(rec) -> str:
    """Canonical JSON image of a record: sorted keys, no whitespace.
    ``json.dumps(json.loads(x))`` is a fixed point of this form, so the
    CRC computed at write time is recomputable from the parsed record."""
    return json.dumps(rec, sort_keys=True, separators=(",", ":"))


def _frame(rec) -> str:
    body = _canon(rec)
    return '{"crc": %d, "rec": %s}' % (zlib.crc32(body.encode()), body)


def _parse_frame(line: bytes):
    """Parsed record, or ``None`` when the line fails any integrity
    check (unparseable, wrong shape, CRC mismatch)."""
    try:
        obj = json.loads(line.decode("utf-8"))
    except (UnicodeDecodeError, ValueError):
        return None
    if not isinstance(obj, dict):
        return None
    rec, crc = obj.get("rec"), obj.get("crc")
    if not isinstance(rec, dict) or not isinstance(crc, int):
        return None
    if zlib.crc32(_canon(rec).encode()) != crc:
        return None
    if not isinstance(rec.get("epoch"), int) or not isinstance(
        rec.get("kind"), str
    ):
        return None
    return rec


def scan_journal(path):
    """Validate a journal file; returns ``(records, torn, valid_end)``.

    ``torn`` counts the CRC-bad/unparseable FINAL line (0 or 1) and
    ``valid_end`` is the byte offset the file should be truncated to so
    later appends extend a clean prefix.  A bad record with valid
    records after it — damage the crash model cannot explain — raises
    :class:`JournalError` (code 118)."""
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except FileNotFoundError:
        return [], 0, 0
    pos = 0
    entries = []  # (1-based line number, byte offset, line)
    for i, ln in enumerate(raw.split(b"\n")):
        entries.append((i + 1, pos, ln))
        pos += len(ln) + 1
    nonempty = [e for e in entries if e[2].strip()]
    records = []
    valid_end = 0
    for j, (no, start, ln) in enumerate(nonempty):
        rec = _parse_frame(ln)
        if rec is None:
            if j == len(nonempty) - 1:
                return records, 1, start
            raise JournalError(
                f"{path}: corrupt journal record at line {no} with valid "
                "records after it — this is damage beyond a torn tail, "
                "refusing a silent partial replay",
                path=str(path), record=no, reason="crc",
            )
        records.append(rec)
        valid_end = min(start + len(ln) + 1, len(raw))
    return records, 0, valid_end if records else len(raw)


def read_journal(path):
    """``(records, torn)`` — the torn-tail-tolerant journal reader.
    Mid-file corruption raises :class:`JournalError` (118)."""
    records, torn, _ = scan_journal(path)
    return records, torn


# -- ndarray codec (the dtype-faithful ``model.save`` encoding) -------------


def _enc_array(a) -> dict:
    a = np.asarray(a)
    return {
        "__ndarray__": True,
        "dtype": str(a.dtype),
        "shape": [int(d) for d in a.shape],
        "data": base64.b64encode(
            np.ascontiguousarray(a).tobytes()
        ).decode("ascii"),
    }


def _dec_array(d) -> np.ndarray:
    dt = _dtype_from_name(d["dtype"])
    buf = base64.b64decode(d["data"])
    return np.frombuffer(buf, dtype=dt).reshape(
        [int(x) for x in d["shape"]]
    ).copy()


# -- entity codecs (shared by journal records and snapshot slots) -----------
#
# ``enc``/``dec`` abstract the array channel: journal records inline the
# bytes (base64) so each line is self-contained; snapshots park arrays as
# npz leaves (dtype-faithful via leaf_dtypes) and reference them by index.


def encode_system(system, enc) -> dict:
    return {
        "entity": "system",
        "sketch": json.loads(system.S.to_json()),
        "capacity": int(system.capacity),
        "m": int(system.m),
        "n": int(system.n),
        "retired": sorted(int(i) for i in system.retired),
        "epoch": int(system.epoch),
        "A": enc(system.A),
        "SA": enc(system.SA),
        "Qt": enc(system.Qt),
        "R": enc(system.R),
    }


def decode_system(name, d, dec):
    from .registry import LSSystem

    s = object.__new__(LSSystem)
    s.name = name
    s.S = sketch_base.from_dict(d["sketch"])
    s.capacity = int(d["capacity"])
    s.m, s.n = int(d["m"]), int(d["n"])
    s.retired = frozenset(int(i) for i in d["retired"])
    s.epoch = int(d["epoch"])
    s.A = jnp.asarray(dec(d["A"]))
    s.dtype = s.A.dtype
    s.SA = jnp.asarray(dec(d["SA"]))
    s.Qt = jnp.asarray(dec(d["Qt"]))
    s.R = jnp.asarray(dec(d["R"]))
    return s


def _json_vertex(v):
    if isinstance(v, str):
        return v
    if isinstance(v, (bool, np.bool_)):
        return bool(v)
    if isinstance(v, (int, np.integer)):
        return int(v)
    raise JournalError(
        f"graph vertex name {v!r} ({type(v).__name__}) is not "
        "JSON-representable; durable registries need int/str vertex names",
        reason="opaque-graph",
    )


def encode_graph(g, enc) -> dict:
    d = {
        "entity": "graph",
        "k": int(g.k),
        "streamed": bool(g._streamed),
        "epoch": int(g.epoch),
        "vertices": [_json_vertex(v) for v in g.G.vertices],
        "indptr": enc(g.G.indptr),
        "indices": enc(g.G.indices),
        "X": enc(g.X),
        "lam": enc(g.lam),
    }
    if g._S is not None:
        d["sketch"] = json.loads(g._S.to_json())
        d["sa"] = enc(g._sa)
    return d


def decode_graph(name, d, dec):
    from ..graph.graph import SimpleGraph
    from .registry import GraphSystem

    G = object.__new__(SimpleGraph)
    G.vertices = list(d["vertices"])
    G.index = {w: i for i, w in enumerate(G.vertices)}
    G.n = len(G.vertices)
    G.indptr = dec(d["indptr"])
    G.indices = dec(d["indices"])
    g = object.__new__(GraphSystem)
    g.name = name
    g.G = G
    g.k = int(d["k"])
    g._streamed = bool(d["streamed"])
    g.epoch = int(d["epoch"])
    if "sketch" in d:
        g._S = sketch_base.from_dict(d["sketch"])
        g._sa = jnp.asarray(dec(d["sa"]))
    else:
        g._S = None
        g._sa = None
    g.X = dec(d["X"])
    g.lam = dec(d["lam"])
    g._ppr_reports = {}
    return g


def encode_model(model, enc) -> dict:
    from ..ml.model import FeatureMapModel, KernelModel

    if isinstance(model, FeatureMapModel):
        return {
            "entity": "model",
            "model_type": "feature_map",
            "epoch": int(getattr(model, "epoch", 0)),
            "scale_maps": bool(model.scale_maps),
            "input_dim": model.input_dim,
            "classes": model.classes,
            "maps": [S.to_dict() for S in model.maps],
            "info": _json_info(model.info),
            "W": enc(model.W),
        }
    if isinstance(model, KernelModel):
        return {
            "entity": "model",
            "model_type": "kernel",
            "epoch": int(getattr(model, "epoch", 0)),
            "classes": model.classes,
            "kernel": model.kernel.to_dict(),
            "info": _json_info(model.info),
            "X_train": enc(model.X_train),
            "A": enc(model.A),
        }
    raise JournalError(
        f"model of type {type(model).__name__} has no journal codec — "
        "only the ml.model classes (FeatureMapModel, KernelModel) are "
        "durable; register it on a journal-less registry or add a codec",
        reason="opaque-model",
    )


def decode_model(d, dec):
    from ..ml.model import FeatureMapModel, KernelModel

    mtype = d.get("model_type")
    if mtype == "feature_map":
        model = FeatureMapModel(
            [sketch_base.from_dict(md) for md in d["maps"]],
            jnp.asarray(dec(d["W"])),
            scale_maps=d["scale_maps"],
            input_dim=d["input_dim"],
            classes=d["classes"],
        )
    elif mtype == "kernel":
        from ..ml.kernels import from_dict as kernel_from_dict

        model = KernelModel(
            kernel_from_dict(d["kernel"]),
            jnp.asarray(dec(d["X_train"])),
            jnp.asarray(dec(d["A"])),
            classes=d["classes"],
        )
    else:
        raise JournalError(
            f"journal model record has unknown model_type {mtype!r}",
            reason="opaque-model",
        )
    model.info = d["info"]
    model.epoch = int(d.get("epoch", 0))
    return model


_ENTITY_DECODERS = {
    "system": decode_system,
    "graph": decode_graph,
}


# -- snapshot (compaction target) -------------------------------------------


def snapshot_registry(registry):
    """``(leaves, metadata)`` for a CheckpointStore slot holding the
    registry's full durable state at its current epoch."""
    leaves: list[np.ndarray] = []

    def enc(a):
        leaves.append(np.asarray(a))
        return len(leaves) - 1

    entities = {"models": {}, "systems": {}, "graphs": {}}
    for name, m in registry.models.items():
        entities["models"][name] = encode_model(m, enc)
    for name, s in registry.systems.items():
        entities["systems"][name] = encode_system(s, enc)
    for name, g in registry.graphs.items():
        entities["graphs"][name] = encode_graph(g, enc)
    meta = {
        "skylark_journal_snapshot": 1,
        "epoch": int(registry.epoch),
        "epoch_log": [dict(r) for r in registry.epoch_log],
        "idem": [[t, k, dict(rec)] for (t, k), rec in registry._idem.items()],
        "entities": entities,
    }
    return leaves, meta


def restore_registry(registry, leaves, meta):
    """Field-copy restore of a snapshot into a (fresh) registry."""

    def dec(i):
        return leaves[int(i)]

    ents = meta["entities"]
    for name, d in ents["systems"].items():
        registry.systems[name] = decode_system(name, d, dec)
    for name, d in ents["graphs"].items():
        registry.graphs[name] = decode_graph(name, d, dec)
    for name, d in ents["models"].items():
        registry.models[name] = decode_model(d, dec)
    registry.epoch = int(meta["epoch"])
    registry.epoch_log[:] = [dict(r) for r in meta["epoch_log"]]
    for t, k, rec in meta.get("idem", []):
        registry._idem[(str(t), str(k))] = dict(rec)


# -- replay -----------------------------------------------------------------


def _rec_idem(rec):
    idem = rec.get("idem")
    return (str(idem[0]), str(idem[1])) if idem else None


def _replay_register(registry, rec):
    name, p = rec["name"], rec["payload"]
    entity = rec["attrs"]["entity"]
    if entity == "model":
        model = decode_model(p, _dec_array)
        registry.register_model(name, model)
    else:
        obj = _ENTITY_DECODERS[entity](name, p, _dec_array)
        target = registry.systems if entity == "system" else registry.graphs
        target[name] = obj
        registry._mint("register", name, obj, entity=entity)


def _replay_row_append(registry, rec):
    registry.append_system_rows(
        rec["name"], _dec_array(rec["payload"]["rows"]), idem=_rec_idem(rec)
    )


def _replay_row_downdate(registry, rec):
    registry.downdate_system_rows(
        rec["name"], [int(i) for i in rec["payload"]["drop"]],
        idem=_rec_idem(rec),
    )


def _replay_graph_fold(registry, rec):
    registry.fold_graph_edges(
        rec["name"], [tuple(p) for p in rec["payload"]["edges"]],
        idem=_rec_idem(rec),
    )


def _replay_model_update(registry, rec):
    p = rec["payload"]
    idem = _rec_idem(rec)
    if "model" in p:
        registry.update_model(
            rec["name"], model=decode_model(p["model"], _dec_array),
            idem=idem,
        )
    elif "append_X" in p:
        registry.update_model(
            rec["name"],
            append=(_dec_array(p["append_X"]), _dec_array(p["append_A"])),
            idem=idem,
        )
    else:
        registry.update_model(
            rec["name"], drop=[int(i) for i in p["drop"]], idem=idem
        )


REPLAY_HANDLERS = {
    "register": _replay_register,
    "row_append": _replay_row_append,
    "row_downdate": _replay_row_downdate,
    "graph_fold": _replay_graph_fold,
    "model_update": _replay_model_update,
}

# The journal's durability contract: every Registry._mint kind has a
# record codec and a replay handler (pinned by a static contract test).
RECORD_KINDS = frozenset(REPLAY_HANDLERS)


# -- the journal ------------------------------------------------------------


class Journal:
    """Append-only CRC-framed JSONL WAL + CheckpointStore compaction.

    Opening validates the existing file: a torn final line (crash
    mid-append) is truncated and counted; mid-file corruption raises
    :class:`JournalError` immediately — better to refuse at open than
    to append after damage.  Callers serialize appends (the registry
    holds its RLock across journal-append + publish + mint).

    ``compact_every`` <= 0 disables compaction; default comes from
    ``SKYLARK_JOURNAL_COMPACT_EVERY`` (records between snapshots).
    ``faults`` takes a :class:`~..resilient.faults.JournalFaultPlan`
    for chaos drills (torn-write and die-after-append injection).
    """

    def __init__(self, directory, *, compact_every=None, keep_snapshots=2,
                 faults=None):
        self.directory = os.fspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.path = os.path.join(self.directory, JOURNAL_NAME)
        if compact_every is None:
            compact_every = int(
                os.environ.get("SKYLARK_JOURNAL_COMPACT_EVERY", "256")
            )
        self.compact_every = int(compact_every)
        self.faults = faults
        self.store = CheckpointStore(
            self.directory, keep_last=max(1, int(keep_snapshots)),
            prefix=SNAP_PREFIX,
        )
        records, torn, valid_end = scan_journal(self.path)
        self.torn_truncated = torn
        if torn:
            with open(self.path, "rb+") as f:
                f.truncate(valid_end)
                f.flush()
                os.fsync(f.fileno())
            telemetry.inc("journal.torn_tail", torn)
        self._pending = len(records)
        self._appends = 0
        self._f = open(self.path, "a", encoding="utf-8")
        _fsync_dir(self.directory)

    # -- write path ---------------------------------------------------------

    def append(self, rec: dict) -> None:
        """Durably append one record: write, flush, sync — the caller
        publishes the mutation only after this returns.  ``fdatasync``
        where the platform has it: appends need the data and the file
        size durable, not the mtime metadata a full ``fsync`` also
        flushes — this is the per-update hot path (the bench's
        journal-on/off QPS ratio charges exactly this call)."""
        line = _frame(rec)
        index = self._appends
        self._appends += 1
        sync = getattr(os, "fdatasync", os.fsync)
        if self.faults is not None and self.faults.torn_fires(index):
            # Simulate a SIGKILL mid-write: half a frame, no newline,
            # durably on disk — then die.
            self._f.write(line[: max(1, len(line) // 2)])
            self._f.flush()
            sync(self._f.fileno())
            self.faults.kill()
        self._f.write(line + "\n")
        self._f.flush()
        sync(self._f.fileno())
        self._pending += 1
        telemetry.inc("journal.appends")
        if self.faults is not None and self.faults.die_after_fires(index):
            self.faults.kill()

    # -- compaction ---------------------------------------------------------

    def due(self) -> bool:
        return self.compact_every > 0 and self._pending >= self.compact_every

    def compact(self, leaves, metadata, step: int) -> None:
        """Commit a snapshot slot (fsynced by ``save_solver_state``),
        then truncate the journal — crash-ordering-safe: the snapshot
        is durable before a single journal byte is dropped."""
        self.store.save(leaves, step=int(step), metadata=metadata)
        self._f.close()
        with open(self.path, "w", encoding="utf-8") as f:
            f.flush()
            os.fsync(f.fileno())
        _fsync_dir(self.directory)
        self._f = open(self.path, "a", encoding="utf-8")
        self._pending = 0
        telemetry.inc("journal.compactions")

    def load_snapshot(self):
        """``(leaves, metadata)`` of the newest valid snapshot slot, or
        ``None`` when the registry never compacted."""
        out = self.store.load_latest()
        if out is None:
            return None
        leaves, meta, _step = out
        return leaves, meta

    def close(self) -> None:
        try:
            self._f.close()
        except OSError:
            pass
