"""Chaos-tested fleet autoscaler: spawn and drain replicas against load.

The :class:`Autoscaler` is a control loop over the fleet's own
observability plane — the per-replica ``load_report()``s the router
already polls — that changes MEMBERSHIP instead of shedding: queue
depth (or p99) above target spawns a replica, a persistently idle
fleet drains one.  It builds entirely on the zero-downtime discipline
the fleet layer already enforces:

- **Scale-up rides the join fence.**  A spawned replica's
  ``Server.start`` primes the full plan ladder BEFORE its workers
  spawn, and ``Router.join`` marks it placeable only once its report
  shows a live worker — so a cold replica can never receive traffic it
  would stall on compiling, and a replica with a mismatched registry
  is refused outright (code 109).
- **Scale-down drains to zero.**  The victim is ``Router.drain``-ed
  (no NEW placements; in-flight and queued work finishes; heartbeats
  keep flowing) and only ``Router.remove``-d — a clean epoch-bumped
  ``leave``, never a code-114 eject — once its queue reads empty.  No
  caller ever sees a shed or a lost-replica error because the fleet
  got smaller on purpose.
- **Every decision is ledgered**: a bounded in-process decision log
  (:attr:`Autoscaler.ledger`), ``autoscale.*`` counters, and one
  telemetry trace event per decision, so a post-mortem can replay why
  the fleet was the size it was at any tick.

Faults are injected through the same plan vocabulary the resilient
streaming layer drills with (``resilient/faults.py``): a
:class:`~..resilient.faults.FleetFaultPlan` bound to the loop fires
die-under-load / slow-heartbeat / join-storm / flapping faults at exact
ticks, deterministically — the chaos drills in ``tests/test_autoscale.py``
assert the loop restores capacity without a single caller-visible 114
while placeable replicas remain.

The loop is deterministic and test-drivable: ``interval_s=0`` (default)
means nothing runs in the background — callers step the loop themselves
with :meth:`step`, injecting their own clock.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass

from .. import telemetry

__all__ = ["AutoscaleParams", "Autoscaler"]


@dataclass
class AutoscaleParams:
    """Control-loop targets and limits.

    - ``min_replicas`` / ``max_replicas``: hard membership bounds; the
      loop never drains below the floor nor spawns past the ceiling.
    - ``queue_high``: mean placeable queue depth above which the loop
      scales up.
    - ``queue_low``: mean depth at or below which the fleet counts as
      idle (a scale-down candidate).
    - ``p99_high_ms``: optional latency target; reported p99 above it
      scales up even when queues look shallow (``None`` disables).
    - ``cooldown_ticks``: decision ticks to hold after any scale event
      — one replica's worth of effect must land before the next
      decision, or the loop oscillates.
    - ``idle_ticks``: consecutive idle ticks required before a drain
      starts; a single quiet tick between bursts must not shrink the
      fleet.
    - ``drain_timeout_s``: a draining replica that has not reached an
      empty queue within this window is removed anyway (its queue is
      shedding-bounded, so this only fires on a wedged replica).
    - ``interval_s``: background thread period; ``0`` = caller-stepped.
    """

    min_replicas: int = 1
    max_replicas: int = 4
    queue_high: float = 8.0
    queue_low: float = 1.0
    p99_high_ms: float | None = None
    cooldown_ticks: int = 2
    idle_ticks: int = 3
    drain_timeout_s: float = 30.0
    interval_s: float = 0.0


class Autoscaler:
    """Membership control loop over a :class:`~.router.Router`.

    ``factory(name) -> Server`` builds a replica for scale-up; the
    autoscaler starts it (prime-before-placeable), joins it, and owns
    its lifecycle — a drained owned replica is ``stop()``-ed after it
    leaves the membership table.  Replicas the autoscaler did not spawn
    are drained/removed but never stopped (their owner does that).

    ``fault_plan`` (optional): an object with ``before_tick(tick)`` —
    the :class:`~..resilient.faults.FleetFaultPlan` hook — called at the
    top of every :meth:`step`, so chaos lands at deterministic ticks.
    """

    def __init__(self, router, factory, params: AutoscaleParams | None = None,
                 *, fault_plan=None, name_prefix: str = "auto"):
        self.router = router
        self.factory = factory
        self.params = params or AutoscaleParams()
        self.fault_plan = fault_plan
        self.name_prefix = name_prefix
        self.ledger: deque[dict] = deque(maxlen=256)
        self._owned: dict[str, object] = {}
        self._draining: dict[str, float] = {}  # name -> drain start (clock)
        self._tick = 0
        self._seq = 0
        self._cooldown = 0
        self._idle_streak = 0
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()

    # -- lifecycle ----------------------------------------------------------

    def adopt(self, name: str, server) -> None:
        """Register an existing in-process replica as autoscaler-owned,
        so a later drain of it also stops its worker threads."""
        self._owned[name] = server

    def start(self) -> "Autoscaler":
        if self.params.interval_s > 0 and self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="skylark-autoscale", daemon=True,
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None

    def __enter__(self) -> "Autoscaler":
        return self.start()

    def __exit__(self, *exc_info) -> None:
        self.stop()

    def _loop(self) -> None:
        while not self._stop.wait(self.params.interval_s):
            try:
                self.step()
            except Exception:  # noqa: BLE001 — the loop must survive
                telemetry.inc("autoscale.loop_errors")

    # -- the control loop ---------------------------------------------------

    def step(self, now: float | None = None) -> dict:
        """One decision tick: fire scheduled faults, sweep heartbeats,
        progress drains, then decide scale_up / scale_down / hold.
        Returns the ledgered decision record.  Deterministic under an
        injected ``now`` — the chaos drills replay exact schedules."""
        now = time.monotonic() if now is None else now
        self._tick += 1
        telemetry.inc("autoscale.ticks")
        if self.fault_plan is not None:
            self.fault_plan.before_tick(self._tick)
        self.router.poll_once(now)
        fleet = self.router.fleet_report()
        members = fleet["members"]
        self._progress_drains(members, now)
        placeable = {
            n: m for n, m in members.items() if m.get("placeable")
        }
        depths = [
            m["report"].get("queue_depth", 0) or 0
            for m in placeable.values()
        ]
        mean_depth = (sum(depths) / len(depths)) if depths else 0.0
        p99 = max(
            (
                (m["report"].get("latency") or {}).get("latency_p99_ms", 0.0)
                for m in placeable.values()
            ),
            default=0.0,
        )
        decision = {
            "tick": self._tick,
            "replicas": len(members),
            "placeable": len(placeable),
            "draining": len(self._draining),
            "mean_depth": round(mean_depth, 3),
            "p99_ms": round(p99, 3),
        }
        if self._cooldown > 0:
            self._cooldown -= 1
            decision["action"] = "cooldown"
            return self._ledger(decision)
        hot = mean_depth > self.params.queue_high or (
            self.params.p99_high_ms is not None
            and p99 > self.params.p99_high_ms
        )
        idle = mean_depth <= self.params.queue_low and not hot
        self._idle_streak = self._idle_streak + 1 if idle else 0
        # Capacity counts placeable members plus anything mid-join this
        # loop owns; draining members are already spoken for.
        live = len(placeable)
        if hot and live < self.params.max_replicas:
            decision.update(self._scale_up())
            self._cooldown = self.params.cooldown_ticks
            self._idle_streak = 0
        elif (
            idle
            and self._idle_streak >= self.params.idle_ticks
            and live > self.params.min_replicas
            and not self._draining
        ):
            decision.update(self._scale_down(placeable))
            self._cooldown = self.params.cooldown_ticks
            self._idle_streak = 0
        else:
            decision["action"] = "hold"
        return self._ledger(decision)

    def _progress_drains(self, members: dict, now: float) -> None:
        """Retire draining members whose queues reached zero (clean
        ``leave``), or whose drain window expired (wedged — removed
        anyway, ledgered as forced)."""
        for name in list(self._draining):
            member = members.get(name)
            started = self._draining[name]
            if member is None:  # ejected/removed behind our back
                self._draining.pop(name)
                self._finish_drain(name, "gone", members)
                continue
            depth = member["report"].get("queue_depth")
            drained = depth == 0
            expired = now - started > self.params.drain_timeout_s
            if drained or expired:
                self._draining.pop(name)
                self.router.remove(
                    name, reason="drained" if drained else "drain timeout"
                )
                self._finish_drain(
                    name, "drained" if drained else "forced", members
                )

    def _finish_drain(self, name: str, how: str, members: dict) -> None:
        telemetry.inc("autoscale.drains_done")
        telemetry.event(
            "autoscale", "drain_done", {"replica": name, "how": how}
        )
        srv = self._owned.pop(name, None)
        if srv is not None:
            try:
                srv.stop()
            except Exception:  # noqa: BLE001 — teardown is best-effort
                pass

    def _scale_up(self) -> dict:
        self._seq += 1
        name = f"{self.name_prefix}-{self._seq}"
        telemetry.inc("autoscale.scale_ups")
        try:
            server = self.factory(name)
            # prime-before-placeable: start() compiles the full plan
            # ladder BEFORE spawning workers; join() then fences the
            # registry signature and flips placeable only on a live
            # worker report — a cold or mismatched replica never takes
            # traffic.
            server.start()
            self.router.join(name, server=server)
        except Exception as e:  # noqa: BLE001 — a failed spawn is a decision, not a crash
            telemetry.inc("autoscale.spawn_failures")
            telemetry.error_event("autoscale.spawn", e, replica=name)
            try:
                server.stop()
            except Exception:  # noqa: BLE001
                pass
            return {"action": "scale_up_failed", "replica": name,
                    "error": f"{type(e).__name__}: {e}"[:200]}
        self._owned[name] = server
        telemetry.event("autoscale", "scale_up", {"replica": name})
        return {"action": "scale_up", "replica": name}

    def _scale_down(self, placeable: dict) -> dict:
        victim = self._pick_victim(placeable)
        if victim is None:
            return {"action": "hold"}
        self.router.drain(victim)
        self._draining[victim] = time.monotonic()
        telemetry.inc("autoscale.scale_downs")
        telemetry.event("autoscale", "scale_down", {"replica": victim})
        return {"action": "scale_down", "replica": victim}

    def _pick_victim(self, placeable: dict) -> str | None:
        """Deterministic: the newest autoscaler-spawned replica first
        (LIFO — the fleet returns to its hand-built core), else the
        lexicographically last placeable member."""
        owned = sorted(n for n in placeable if n in self._owned)
        if owned:
            return owned[-1]
        names = sorted(placeable)
        return names[-1] if names else None

    def _ledger(self, decision: dict) -> dict:
        self.ledger.append(decision)
        telemetry.event("autoscale", "decision", dict(decision))
        return decision

    # -- observability ------------------------------------------------------

    def report(self) -> dict:
        """The ``skylark-top`` panel payload: current shape, targets,
        and the ledger tail."""
        return {
            "tick": self._tick,
            "owned": sorted(self._owned),
            "draining": sorted(self._draining),
            "cooldown": self._cooldown,
            "idle_streak": self._idle_streak,
            "params": {
                "min_replicas": self.params.min_replicas,
                "max_replicas": self.params.max_replicas,
                "queue_high": self.params.queue_high,
                "queue_low": self.params.queue_low,
                "p99_high_ms": self.params.p99_high_ms,
            },
            "ledger": list(self.ledger)[-8:],
        }
